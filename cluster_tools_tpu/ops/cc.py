"""Connected components as an XLA program.

Replaces skimage.morphology.label / vigra.labelVolumeWithBackground
(reference thresholded_components/block_components.py:143-182,
watershed/watershed.py:206,331).

Algorithm (TPU-friendly, no data-dependent shapes): iterative *min-label
propagation* over the neighborhood, accelerated by *pointer jumping* — after each
local propagation every voxel re-gathers the label of the voxel its label points to,
so label information travels exponentially per iteration (O(log diameter)
iterations instead of O(diameter)).  This is the same union-find-by-minimum idea a
parallel CC on GPUs uses (coarse-to-fine CCL literature), expressed as pure
gather/min ops inside a ``lax.while_loop``.
"""

from __future__ import annotations

from functools import partial
from itertools import product
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import _backend


def neighbor_offsets(
    ndim: int, connectivity: int, per_slice: bool = False
) -> np.ndarray:
    """All neighbor offsets with 1 ≤ #nonzero-coords ≤ connectivity
    (connectivity=1 → faces, ndim → full Moore neighborhood).  ``per_slice``
    drops offsets crossing axis 0, so each z-slice is an independent domain
    (the reference's 2d watershed/labeling modes)."""
    offs = [
        o
        for o in product((-1, 0, 1), repeat=ndim)
        if 0 < sum(c != 0 for c in o) <= connectivity
    ]
    if per_slice:
        offs = [o for o in offs if o[0] == 0]
    return np.array(offs, dtype=np.int32)


def _shift(x: jnp.ndarray, offset, fill) -> jnp.ndarray:
    """x shifted so out[p] = x[p + offset], `fill` outside."""
    out = x
    for axis, o in enumerate(offset):
        if o == 0:
            continue
        out = jnp.roll(out, -o, axis=axis)
        idx = [slice(None)] * x.ndim
        # out[p] = x[p+o] is invalid where p+o leaves the axis: the first |o|
        # entries for o<0, the last o entries for o>0
        idx[axis] = slice(0, -o) if o < 0 else slice(x.shape[axis] - o, None)
        out = out.at[tuple(idx)].set(fill)
    return out


def boundary_cross_offsets(
    ndim: int, connectivity: int, per_slice: bool = False
):
    """In-plane shifts of every neighbor offset that crosses an axis-0
    boundary plane: the ONE derivation of cross-plane connectivity, shared
    by the sharded CC collective (parallel/sharded.py) and the per-slice
    merge paths, so connectivity semantics can't drift between kernels.
    Both dz signs map to the same in-plane shift, deduped."""
    offs = neighbor_offsets(ndim, connectivity, per_slice)
    return sorted({tuple(int(c) for c in o[1:]) for o in offs if o[0] != 0})


def _canonical_offsets(ndim: int, connectivity: int, per_slice: bool):
    """The lexicographically-positive half of the neighborhood: each
    unordered adjacency {p, p+o} appears under exactly one canonical o."""
    out = []
    for o in neighbor_offsets(ndim, connectivity, per_slice):
        nz = [int(c) for c in o if c != 0]
        if nz and nz[0] > 0:
            out.append(tuple(int(c) for c in o))
    return out


def _use_assoc() -> bool:
    return _backend.use_assoc()


def _min_sweep_seq(label, mask, partition, axis, reverse, sentinel):
    """Sequential-carry variant of ``_min_sweep``: the same Gauss–Seidel
    min-label conduction as one ``lax.scan`` over planes — O(n) work, n
    dependent steps, the work-bound-backend winner (the CC analog of
    watershed's ``_sweep_altitude_seq``; both compute the identical
    fixpoint).  Before ctt-cc the seq path had NO sweep at all (one-voxel
    shift propagation), which is why the flat kernel needed ~7x the rounds
    on the CPU mesh."""

    def mv(x):
        x = jnp.moveaxis(x, axis, 0)
        return jnp.flip(x, axis=0) if reverse else x

    l_v = mv(label)
    m_v = mv(mask)
    p_v = mv(partition) if partition is not None else None
    plane = l_v.shape[1:]

    def step(carry, x):
        c_lab, c_m, c_p = carry
        if p_v is not None:
            l, m, p = x
            conduct = m & c_m & (p == c_p)
        else:
            l, m = x
            p = c_p
            conduct = m & c_m
        new = jnp.where(conduct, jnp.minimum(l, c_lab), l)
        return (jnp.where(m, new, sentinel), m, p), new

    xs = (l_v, m_v) if p_v is None else (l_v, m_v, p_v)
    init_p = (
        jnp.zeros(plane, p_v.dtype) if p_v is not None
        else jnp.zeros(plane, jnp.int32)
    )
    _, out = lax.scan(
        step,
        (jnp.full(plane, sentinel), jnp.zeros(plane, bool), init_p),
        xs,
    )
    if reverse:
        out = jnp.flip(out, axis=0)
    return jnp.moveaxis(out, 0, axis)


def _min_sweep(label, mask, partition, axis, reverse, sentinel):
    """Min-label propagation along one axis in log depth: the carry chain is
    a composition of clamp transfers c → min(u, max(c, l)) (the same family
    as the watershed sweeps), so a whole straight run collapses to its
    minimum in one ``lax.associative_scan`` instead of one voxel per round."""

    def mv(x):
        x = jnp.moveaxis(x, axis, 0)
        return jnp.flip(x, axis=0) if reverse else x

    l_v = mv(label)
    m_v = mv(mask)
    # conduction across the edge (i-1, i): both in mask, same partition
    prev_m = jnp.concatenate([jnp.zeros_like(m_v[:1]), m_v[:-1]], axis=0)
    conduct = m_v & prev_m
    if partition is not None:
        p_v = mv(partition)
        prev_p = jnp.concatenate([p_v[:1], p_v[:-1]], axis=0)
        conduct &= p_v == prev_p

    u = jnp.where(m_v, l_v, sentinel)
    low = jnp.where(conduct, jnp.int32(-1), sentinel)

    def combine(f, g):  # f earlier, g later
        uf, lf = f
        ug, lg = g
        return jnp.minimum(ug, jnp.maximum(uf, lg)), jnp.maximum(lf, lg)

    u_inc, _ = lax.associative_scan(combine, (u, low), axis=0)
    carry_in = jnp.concatenate(
        [jnp.full_like(u_inc[:1], sentinel), u_inc[:-1]], axis=0
    )
    out = jnp.where(conduct, jnp.minimum(l_v, carry_in), l_v)
    if reverse:
        out = jnp.flip(out, axis=0)
    return jnp.moveaxis(out, 0, axis)


def _axis_conduct(mask, partition, axis):
    """Loop-invariant conduction masks for one axis, in scan layout (the
    axis moved to front): ``c_f[i]`` conducts the edge (i-1, i), ``c_r[i]``
    the edge (i, i+1).  Hoisting these out of the fixpoint loop is a large
    part of the ctt-cc flat-path win — the per-sweep formulation re-derived
    the mask/partition transposes and the edge predicate every round."""
    m_v = jnp.moveaxis(mask, axis, 0)
    c_f = m_v & jnp.concatenate([jnp.zeros_like(m_v[:1]), m_v[:-1]], axis=0)
    if partition is not None:
        p_v = jnp.moveaxis(partition, axis, 0)
        c_f &= p_v == jnp.concatenate([p_v[:1], p_v[:-1]], axis=0)
    c_r = jnp.concatenate([c_f[1:], jnp.zeros_like(c_f[:1])], axis=0)
    return c_f, c_r


def _assoc_sweep_dir(l_v, cond, sentinel, reverse):
    """One clamp-transfer ``associative_scan`` sweep along the leading axis
    (the ``_min_sweep`` recurrence on a precomputed conduction mask);
    labels keep the off-mask == sentinel invariant, so no masking is
    needed beyond ``cond``."""
    if reverse:
        return jnp.flip(
            _assoc_sweep_dir(
                jnp.flip(l_v, 0), jnp.flip(cond, 0), sentinel, False
            ),
            0,
        )
    low = jnp.where(cond, jnp.int32(-1), sentinel)

    def combine(f, g):  # f earlier, g later
        uf, lf = f
        ug, lg = g
        return jnp.minimum(ug, jnp.maximum(uf, lg)), jnp.maximum(lf, lg)

    u_inc, _ = lax.associative_scan(combine, (l_v, low), axis=0)
    carry = jnp.concatenate(
        [jnp.full_like(u_inc[:1], sentinel), u_inc[:-1]], axis=0
    )
    return jnp.where(cond, jnp.minimum(l_v, carry), l_v)


def _axis_sweep_pair(l_v, c_f, c_r, sentinel):
    """Forward then backward min-conduction along the leading axis (a
    Gauss–Seidel pair: the backward pass consumes the forward result, so
    one call resolves every straight run to its minimum).  The backend
    sweep mode picks the formulation: log-depth ``associative_scan`` or
    the sequential-carry ``lax.scan`` (native ``reverse=True``, no flips).
    The two-op step relies on the labels' off-mask == sentinel invariant:
    conduction is false off-mask, so no re-masking is needed per plane."""
    if _use_assoc():
        out = _assoc_sweep_dir(l_v, c_f, sentinel, False)
        return _assoc_sweep_dir(out, c_r, sentinel, True)

    plane = l_v.shape[1:]

    def step(carry, x):
        l, cond = x
        new = jnp.where(cond, jnp.minimum(l, carry), l)
        return new, new

    _, out = lax.scan(step, jnp.full(plane, sentinel), (l_v, c_f))
    _, out = lax.scan(
        step, jnp.full(plane, sentinel), (out, c_r), reverse=True
    )
    return out


# rounds run unconditionally before the stability-gated loop: volumes that
# need fewer rounds pay at most one redundant (cheap, already-converged)
# round, while every realistic volume skips the stability test for rounds
# that cannot pass it
_FLAT_PRE_ROUNDS = 2


def _flat_cc(
    mask: jnp.ndarray,
    connectivity: int,
    partition: Optional[jnp.ndarray],
    per_slice: bool,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Whole-volume min-propagation fixpoint (the flat kernel); returns
    ``(raw_labels, fixpoint_iters)`` — see ``connected_components_raw`` for
    the label contract.  ``fixpoint_iters`` counts WORK rounds: the loop
    terminates on an explicit edge-stability test instead of re-running a
    full round just to observe "nothing changed".

    Per round: one moveaxis per axis with a fused forward+backward sweep
    pair over precomputed conduction masks (``_axis_conduct``), diagonal
    shift-propagation only for connectivity > 1, then one pointer jump.
    Termination: labels are a fixpoint iff every conducting edge is
    label-equal — sweep-stable labels are constant per component, and the
    component's minimal voxel pins that constant to the minimal flat id —
    which costs three shifted compares instead of a full verification
    round.  The lane-most-first axis order and the single jump are the
    measured winners on the CPU fallback (bench.py cc config)."""
    shape = mask.shape
    size = int(np.prod(shape))
    sentinel = jnp.int32(size)
    flat_ids = jnp.arange(size, dtype=jnp.int32).reshape(shape)
    init = jnp.where(mask, flat_ids, sentinel)
    axes = tuple(range(mask.ndim))
    if per_slice:
        axes = axes[1:]
    # lane axis first: its (expensive, strided) transpose then overlaps
    # the cheap outer-axis moves instead of serializing after them
    order = tuple(reversed(axes))
    conds = {a: _axis_conduct(mask, partition, a) for a in order}
    offsets = neighbor_offsets(mask.ndim, connectivity, per_slice)
    # face-neighbor conduction is exactly axis conduction, so the sweep
    # path needs no shift-propagation for connectivity=1 at all; higher
    # connectivities keep shifts for the diagonal offsets
    prop_offsets = [o for o in offsets if sum(c != 0 for c in o) > 1]

    def propagate(label):
        best = label
        for off in prop_offsets:
            neigh = _shift(label, off, sentinel)
            ok = mask
            if partition is not None:
                same = _shift(partition, off, jnp.asarray(-1, partition.dtype)) == partition
                ok = ok & same
            best = jnp.minimum(best, jnp.where(ok, neigh, sentinel))
        return jnp.where(mask, best, sentinel)

    def jump(label):
        # label[p] <- label[label[p]]: pointer jumping through the flat
        # volume.  On-mask labels always index in bounds (every label is
        # some voxel's flat id) and off-mask voxels are re-pinned by the
        # where, so the gather needs no appended sentinel row (the old
        # formulation copied the whole volume per jump for a self-loop).
        flat = label.reshape(-1)
        jumped = flat[flat].reshape(label.shape)
        return jnp.where(mask, jumped, sentinel)

    def one_round(label):
        for a in order:
            l_v = jnp.moveaxis(label, a, 0)
            l_v = _axis_sweep_pair(l_v, conds[a][0], conds[a][1], sentinel)
            label = jnp.moveaxis(l_v, 0, a)
        if prop_offsets:
            label = propagate(label)
        return jump(label)

    # stability predicate over the canonical half-neighborhood (equality
    # is symmetric, so each unordered edge is tested once); conduction
    # masks are loop constants
    stab = []
    for off in _canonical_offsets(mask.ndim, connectivity, per_slice):
        ok = mask & _shift(mask, off, False)
        if partition is not None:
            ok &= (
                _shift(partition, off, jnp.asarray(-1, partition.dtype))
                == partition
            )
        stab.append((off, ok))

    def unstable(label):
        u = jnp.bool_(False)
        for off, ok in stab:
            u |= jnp.any(ok & (label != _shift(label, off, sentinel)))
        return u

    label = init
    for _ in range(_FLAT_PRE_ROUNDS):
        label = one_round(label)
    label, iters = lax.while_loop(
        lambda s: unstable(s[0]),
        lambda s: (one_round(s[0]), s[1] + 1),
        (label, jnp.int32(_FLAT_PRE_ROUNDS)),
    )
    return jnp.where(mask, label, jnp.int32(-1)), iters


@partial(jax.jit, static_argnames=("connectivity", "per_slice"))
def connected_components_raw(
    mask: jnp.ndarray,
    connectivity: int = 1,
    partition: Optional[jnp.ndarray] = None,
    per_slice: bool = False,
) -> jnp.ndarray:
    """Label foreground components of ``mask`` with the flat (whole-volume)
    fixpoint kernel.  See ``_flat_cc`` for the algorithm; the coarse-to-fine
    path (``connected_components_coarse_raw``) computes identical labels in
    far fewer, tile-bounded rounds and is the default behind
    ``connected_components``.

    Returns int32 labels where background = -1 and each component carries the
    *minimal flat index* of its voxels — not consecutive; compose with
    ``relabel.relabel_consecutive`` (or host np.unique) for 1..N labels.

    With ``partition`` (an int array), voxels only merge when their partition
    values are equal — i.e. CC *within* existing labels, the equivalent of
    vigra.labelMultiArrayWithBackground on a segmentation (used to re-close
    labels after halo cropping, reference watershed.py:329-333).
    """
    return _flat_cc(mask, connectivity, partition, per_slice)[0]


@partial(jax.jit, static_argnames=("connectivity", "per_slice"))
def connected_components_raw_with_iters(
    mask: jnp.ndarray,
    connectivity: int = 1,
    partition: Optional[jnp.ndarray] = None,
    per_slice: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``connected_components_raw`` plus its fixpoint round count — the
    bench/CI instrumentation hook for the flat-vs-coarse iteration contract
    (tools/ci_check.sh asserts coarse < flat on the serpentine fixture)."""
    return _flat_cc(mask, connectivity, partition, per_slice)


# ---------------------------------------------------------------------------
# coarse-to-fine CC (ctt-cc): tile-local fixpoints + compact boundary merge
# ---------------------------------------------------------------------------
#
# The flat kernel's fixpoint runs O(log volume-diameter) rounds (worst case
# O(#bends of the longest corridor)) and every round gathers over the ENTIRE
# volume, even when only labels near component boundaries still change.  The
# coarse-to-fine path (the shape of arXiv:1712.09789) instead:
#
#   1. labels fixed-size tiles independently — the fixpoint is bounded by the
#      structure INSIDE one tile, and a per-tile live mask drops converged
#      tiles (uniform background regions) out after one round;
#   2. resolves only the tile-face label equivalences with a value-space
#      union-find whose table is O(tile-boundary area), not O(volume)
#      (ops.unionfind.merge_value_table);
#   3. applies the resolved roots with one gather.
#
# Tile-local labels live in TILE-LOCAL id space during the fixpoint (pointer
# jumping becomes a per-tile take_along_axis) and translate to the caller's
# id array afterwards: within one tile, tile-row-major order and any
# lexicographic global id order are order-isomorphic, so min-label semantics
# survive the translation exactly.

_TILE_ENV = "CTT_CC_TILE"


def default_coarse_tile(ndim: int) -> Tuple[int, ...]:
    """Built-in tile shape: 64 along the two trailing (lane-friendly) axes,
    8 along every leading axis — the bench's tile sweep records whether a
    different pin wins on a given chip (deploy via CTT_CC_TILE)."""
    if ndim <= 2:
        return (64,) * ndim
    return (8,) * (ndim - 2) + (64, 64)


def parse_tile_spec(spec, ndim: int) -> Optional[Tuple[int, ...]]:
    """Parse a CTT_CC_TILE value ("8,64,64" or a single int for a cube) into
    an ndim tile tuple; a spec longer than ndim keeps its trailing entries, a
    shorter one left-pads with its first entry (one env var serves the 3d
    volumes and the 2d seed masks alike).  Invalid specs return None (the
    caller falls back to the default and warns — malformed env must not
    crash a run, the bench.py deadline-parsing idiom)."""
    try:
        parts = [int(p) for p in str(spec).split(",") if p.strip() != ""]
    except (TypeError, ValueError):
        return None
    if not parts or any(p < 1 for p in parts):
        return None
    if len(parts) == 1:
        parts = parts * ndim
    if len(parts) >= ndim:
        return tuple(parts[-ndim:])
    return tuple([parts[0]] * (ndim - len(parts)) + parts)


def resolve_coarse_tile(shape, coarse_tile=None) -> Tuple[int, ...]:
    """Tile-shape precedence: explicit ``coarse_tile`` (int = cube, sequence
    = per-axis) > CTT_CC_TILE env / chip_modes.json pin > built-in default —
    clipped per-axis to ``shape``.  Read at TRACE time like every mode
    switch (ops/_backend.py): compiled shapes keep their tile until the jit
    caches clear."""
    ndim = len(shape)
    if coarse_tile is None:
        pin = _backend.pinned_value(_TILE_ENV)
        tile = parse_tile_spec(pin, ndim) if pin is not None else None
        if pin is not None and tile is None:
            import warnings

            warnings.warn(
                f"invalid {_TILE_ENV}={pin!r}; using the default tile",
                RuntimeWarning,
                stacklevel=2,
            )
        if tile is None:
            tile = default_coarse_tile(ndim)
    elif isinstance(coarse_tile, (int, np.integer)):
        tile = (int(coarse_tile),) * ndim
    else:
        tile = tuple(int(t) for t in coarse_tile)
        if len(tile) != ndim:
            raise ValueError(
                f"coarse_tile {coarse_tile!r} does not match ndim {ndim}"
            )
    return tuple(max(1, min(int(t), int(s))) for t, s in zip(tile, shape))


def _tile_grid(shape, tile) -> Tuple[int, ...]:
    return tuple(-(-int(s) // int(t)) for s, t in zip(shape, tile))


def tile_stack(x: jnp.ndarray, tile, fill) -> jnp.ndarray:
    """Pad ``x`` to tile multiples with ``fill`` and reshape to
    ``(n_tiles, *tile)`` (tiles in row-major grid order).  Shared by the
    coarse CC and the hierarchical flood (ops/watershed.py)."""
    shape = x.shape
    grid = _tile_grid(shape, tile)
    padded = tuple(g * t for g, t in zip(grid, tile))
    if padded != tuple(shape):
        x = jnp.pad(
            x,
            [(0, p - s) for p, s in zip(padded, shape)],
            constant_values=fill,
        )
    x = x.reshape(tuple(v for gt in zip(grid, tile) for v in gt))
    ndim = len(shape)
    perm = tuple(2 * i for i in range(ndim)) + tuple(
        2 * i + 1 for i in range(ndim)
    )
    return x.transpose(perm).reshape((-1,) + tuple(tile))


def tile_unstack(xt: jnp.ndarray, shape, tile, crop: bool = True):
    """Inverse of ``tile_stack``; ``crop=False`` keeps the padded extent."""
    grid = _tile_grid(shape, tile)
    ndim = len(shape)
    x = xt.reshape(tuple(grid) + tuple(tile))
    perm = tuple(
        v for pair in zip(range(ndim), range(ndim, 2 * ndim)) for v in pair
    )
    x = x.transpose(perm).reshape(tuple(g * t for g, t in zip(grid, tile)))
    if crop:
        x = x[tuple(slice(0, int(s)) for s in shape)]
    return x


def tile_crossing_take(arrs, off, tile, grid):
    """For one canonical neighbor offset ``off``: the voxel slabs where the
    adjacency (p, p+off) crosses a tile boundary, for every array in
    ``arrs`` (pass pre-shifted companions alongside the originals).  Yields
    one tuple of flattened slabs per crossing axis; a diagonal offset
    crossing two axes yields its corner pairs twice — harmless for the
    union-find.  Slab positions are static (tile-grid planes), so every
    shape stays data-independent."""
    out = []
    for ax, o_a in enumerate(off):
        if o_a == 0 or grid[ax] == 1:
            continue
        t_a = int(tile[ax])
        s_a = int(arrs[0].shape[ax])
        idx = np.arange(t_a - 1 if o_a > 0 else 0, s_a, t_a)
        out.append(
            tuple(jnp.take(a, idx, axis=ax).reshape(-1) for a in arrs)
        )
    return out


def _tile_boundary_pairs(
    L, partition, tile, connectivity, per_slice, sentinel
):
    """Label equivalence pairs across tile faces of a label volume ``L``
    (values: component ids, ``sentinel`` on background).  Returns
    ``(a_vals, b_vals, n_valid)`` with invalid slots set to ``sentinel`` on
    both sides (self-loops), or ``None`` when the tiling has no interior
    boundaries (single tile)."""
    shape = L.shape
    grid = _tile_grid(shape, tile)
    sent = jnp.int32(sentinel)
    a_parts, b_parts, n_valid = [], [], jnp.int32(0)
    for off in _canonical_offsets(len(shape), connectivity, per_slice):
        if all(o == 0 or grid[ax] == 1 for ax, o in enumerate(off)):
            continue
        nei = _shift(L, off, sent)
        arrs = [L, nei]
        if partition is not None:
            same = (
                _shift(partition, off, jnp.asarray(-1, partition.dtype))
                == partition
            )
            arrs.append(same)
        for slabs in tile_crossing_take(arrs, off, tile, grid):
            a_v, b_v = slabs[0], slabs[1]
            ok = (a_v < sent) & (b_v < sent)
            if partition is not None:
                ok &= slabs[2]
            a_parts.append(jnp.where(ok, a_v, sent))
            b_parts.append(jnp.where(ok, b_v, sent))
            n_valid = n_valid + jnp.sum(ok.astype(jnp.int32))
    if not a_parts:
        return None
    return jnp.concatenate(a_parts), jnp.concatenate(b_parts), n_valid


def _coarse_cc_core(
    mask: jnp.ndarray,
    ids: jnp.ndarray,
    sentinel: int,
    connectivity: int,
    partition: Optional[jnp.ndarray],
    per_slice: bool,
    tile: Tuple[int, ...],
):
    """The coarse-to-fine labeling core (traced; see the section comment).

    ``ids`` assigns every voxel its component-id candidate (any array whose
    row-major order is lexicographic in the voxel coordinates — the local
    ``arange`` here, the shard-offset global ids in parallel/sharded.py);
    ``sentinel`` must exceed every id.  Returns ``(labels, stats)`` where
    ``labels[p]`` is the minimal id of p's component (``sentinel`` on
    background) and ``stats`` carries int32 scalars ``fixpoint_iters``
    (tile-fixpoint rounds), ``live_tile_rounds`` (Σ live tiles per round)
    and ``merge_pairs`` (valid tile-face equivalences)."""
    shape = mask.shape
    ndim = mask.ndim
    grid = _tile_grid(shape, tile)
    n_tiles = int(np.prod(grid))
    ts = int(np.prod(tile))
    sent_l = jnp.int32(ts)

    mask_t = tile_stack(mask, tile, False)
    part_t = (
        tile_stack(partition, tile, -1) if partition is not None else None
    )
    iota = jnp.arange(ts, dtype=jnp.int32).reshape(tile)
    init = jnp.where(mask_t, jnp.broadcast_to(iota, mask_t.shape), sent_l)

    offsets = neighbor_offsets(ndim, connectivity, per_slice)
    axes = tuple(range(1, ndim + 1))
    if per_slice:
        axes = axes[1:]
    sweep_fn = _min_sweep if _use_assoc() else _min_sweep_seq
    prop_offsets = [o for o in offsets if sum(c != 0 for c in o) > 1]

    def tjump(lab):
        # per-tile pointer jump in local id space: one take_along_axis,
        # sentinel self-loops via the appended column
        flat = jnp.concatenate(
            [
                lab.reshape(n_tiles, ts),
                jnp.full((n_tiles, 1), sent_l, jnp.int32),
            ],
            axis=1,
        )
        jumped = jnp.take_along_axis(
            flat, lab.reshape(n_tiles, ts), axis=1
        ).reshape(lab.shape)
        return jnp.where(mask_t, jumped, sent_l)

    def one_round(lab):
        new = lab
        for axis in axes:
            for reverse in (False, True):
                new = sweep_fn(new, mask_t, part_t, axis, reverse, sent_l)
        if prop_offsets:
            best = new
            for off in prop_offsets:
                soff = (0,) + tuple(off)
                neigh = _shift(new, soff, sent_l)
                ok = mask_t
                if part_t is not None:
                    same = _shift(part_t, soff, jnp.asarray(-1, part_t.dtype))
                    ok = ok & (same == part_t)
                best = jnp.minimum(best, jnp.where(ok, neigh, sent_l))
            new = jnp.where(mask_t, best, sent_l)
        return tjump(tjump(new))

    def cond(state):
        return jnp.any(state[1])

    def body(state):
        lab, live, it, live_rounds = state
        new = one_round(lab)
        # live-mask early-exit: a tile whose labels stopped changing is
        # converged forever (tiles are independent) and drops out
        new = jnp.where(live.reshape((n_tiles,) + (1,) * ndim), new, lab)
        changed = jnp.any((new != lab).reshape(n_tiles, ts), axis=1)
        return (
            new,
            changed,
            it + 1,
            live_rounds + jnp.sum(live.astype(jnp.int32)),
        )

    lab_t, _, iters, live_rounds = lax.while_loop(
        cond,
        body,
        (
            init,
            jnp.ones((n_tiles,), bool),
            jnp.int32(0),
            jnp.int32(0),
        ),
    )

    # translate tile-local labels to the caller's id space (see section
    # comment: the two orders are isomorphic within a tile, so min survives)
    sent = jnp.int32(sentinel)
    gids = tile_stack(ids, tile, 0).reshape(n_tiles, ts)
    safe = jnp.clip(lab_t.reshape(n_tiles, ts), 0, ts - 1)
    glab = jnp.take_along_axis(gids, safe, axis=1).reshape(mask_t.shape)
    glab = jnp.where(lab_t == sent_l, sent, glab)

    L = tile_unstack(glab, shape, tile)
    stats = {
        "fixpoint_iters": iters,
        "live_tile_rounds": live_rounds,
        "merge_pairs": jnp.int32(0),
    }
    pairs = _tile_boundary_pairs(
        L,
        partition,
        tile,
        connectivity,
        per_slice,
        sentinel,
    )
    if pairs is not None:
        from .unionfind import apply_value_roots, merge_value_table

        a_vals, b_vals, n_valid = pairs
        vals, root_vals = merge_value_table(a_vals, b_vals)
        L = apply_value_roots(L, vals, root_vals)
        stats["merge_pairs"] = n_valid
    return L, stats


@partial(jax.jit, static_argnames=("connectivity", "per_slice", "tile"))
def connected_components_coarse_raw(
    mask: jnp.ndarray,
    connectivity: int = 1,
    partition: Optional[jnp.ndarray] = None,
    per_slice: bool = False,
    tile: Optional[Tuple[int, ...]] = None,
):
    """Coarse-to-fine labeling with the exact ``connected_components_raw``
    contract (min flat index per component, background -1), plus the kernel
    stats dict (``fixpoint_iters``, ``live_tile_rounds``, ``merge_pairs``).
    ``tile=None`` resolves CTT_CC_TILE / the default at trace time."""
    shape = mask.shape
    tile = resolve_coarse_tile(shape, tile)
    size = int(np.prod(shape))
    ids = jnp.arange(size, dtype=jnp.int32).reshape(shape)
    lab, stats = _coarse_cc_core(
        mask, ids, size, connectivity, partition, per_slice, tile
    )
    return jnp.where(mask, lab, jnp.int32(-1)), stats


def connected_components_coarse(
    mask,
    connectivity: int = 1,
    partition=None,
    per_slice: bool = False,
    coarse_tile=None,
):
    """Host-side wrapper over the coarse kernel: consecutive ``(labels, n)``
    like ``connected_components``, and emits the ``cc.*`` obs counters
    (fixpoint_iters / live_tiles / merge_pairs — obs/registry.py).  Metric
    emission must stay outside jit (CTT001/CTT002), which is why the jitted
    dispatch path cannot do it; bench.py and the CI smoke call this."""
    from ..obs import metrics as obs_metrics

    mask = jnp.asarray(mask).astype(bool)
    tile = resolve_coarse_tile(mask.shape, coarse_tile)
    raw, stats = connected_components_coarse_raw(
        mask, connectivity, partition, per_slice, tile
    )
    size = int(np.prod(mask.shape))
    labels, n = consecutive_from_flat_roots(raw.reshape(-1), size)
    obs_metrics.inc("cc.fixpoint_iters", int(stats["fixpoint_iters"]))
    obs_metrics.inc("cc.live_tiles", int(stats["live_tile_rounds"]))
    obs_metrics.inc("cc.merge_pairs", int(stats["merge_pairs"]))
    return labels.reshape(mask.shape), n


@partial(jax.jit, static_argnames=("tile", "connectivity", "per_slice"))
def merge_tiled_labels(
    mask: jnp.ndarray,
    glabels: jnp.ndarray,
    tile: Tuple[int, ...],
    connectivity: int = 1,
    per_slice: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Consecutive volume CC from tile-local minimal-flat-index labels
    (−1 background): resolve the tile-face equivalences with the compact
    value union-find, then rank.  Generalizes ``merge_slice_labels`` (tiles
    = whole slices) to arbitrary tile grids; shared with the tiled Pallas
    kernel (ops/pallas_cc.py)."""
    shape = mask.shape
    size = int(np.prod(shape))
    sent = jnp.int32(size)
    L = jnp.where(glabels < 0, sent, glabels)
    pairs = _tile_boundary_pairs(
        L, None, tile, connectivity, per_slice, size
    )
    if pairs is not None:
        from .unionfind import apply_value_roots, merge_value_table

        a_vals, b_vals, _ = pairs
        vals, root_vals = merge_value_table(a_vals, b_vals)
        L = apply_value_roots(L, vals, root_vals)
    flat = jnp.where(mask.reshape(-1), L.reshape(-1), -1)
    labels, n = consecutive_from_flat_roots(flat, size)
    return labels.reshape(shape), n


def merge_slice_labels(
    mask: jnp.ndarray, sliced: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Volume CC from per-slice minimal-flat-index labels (−1 background):
    one device pointer-jumping union-find over the z-face equivalences, then
    consecutive ranking.  Shared by the Pallas per-slice kernel
    (ops/pallas_cc.py) and the XLA ``slices`` CC mode — valid for
    connectivity 1 only (z-diagonal adjacency would need more edges)."""
    from .unionfind import merge_labels_device

    n, h, w = mask.shape
    size = n * h * w
    # z-face equivalences (self-loops where either side is background pad
    # the static edge table)
    up = sliced[:-1].reshape(-1)
    dn = sliced[1:].reshape(-1)
    both = (up >= 0) & (dn >= 0)
    edges = jnp.stack(
        [jnp.where(both, up, 0), jnp.where(both, dn, 0)], axis=1
    )
    parent = jnp.arange(size, dtype=jnp.int32)
    roots = merge_labels_device(parent, edges)
    flat = jnp.where(
        mask.reshape(-1),
        roots[jnp.clip(sliced.reshape(-1), 0, size - 1)],
        -1,
    )
    labels, n_comp = consecutive_from_flat_roots(flat, size)
    return labels.reshape(mask.shape), n_comp


@partial(
    jax.jit, static_argnames=("connectivity", "per_slice", "coarse_tile")
)
def connected_components(
    mask: jnp.ndarray,
    connectivity: int = 1,
    partition: Optional[jnp.ndarray] = None,
    per_slice: bool = False,
    coarse_tile: Optional[Tuple[int, ...]] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Consecutive component labeling: background 0, components 1..n.

    Returns ``(labels, n_components)``.  Consecutive ids come from ranking the
    component roots (minimal flat indices) with a cumsum — no dynamic shapes.
    See ``connected_components_raw`` for ``partition`` / ``per_slice``.

    Mode switches (read at trace time, ops/_backend.py):
      * ``CTT_CC_MODE=coarse`` — the coarse-to-fine tiled kernel
        (``connected_components_coarse_raw``): tile-local fixpoints + one
        compact boundary union-find; ``coarse_tile`` overrides the tile
        shape per call (and forces this path), CTT_CC_TILE /
        chip_modes.json per deployment.  The unpinned default on non-CPU
        backends (``_backend.use_coarse_cc``);
      * ``CTT_CC_MODE=flat`` — the whole-volume fixpoint kernel (the
        unpinned default on the work-bound CPU fallback, where the ctt-cc
        seq sweeps converge in a handful of rounds and the merge-table
        relabel costs more than the saved rounds — measured in bench.py);
      * ``CTT_CC_MODE=pallas`` — VMEM-resident per-slice kernel + z-merge
        (ops/pallas_cc.py) on eligible volumes (3d, connectivity 1, no
        partition, lane-aligned slices, TPU backend); slices too large for
        whole-slice VMEM residency take the tiled Pallas variant;
      * ``CTT_CC_MODE=slices`` — the same slices+z-merge STRUCTURE in plain
        XLA: per-slice 2d sweeps converge in far fewer rounds than
        whole-volume 3d propagation (a 3d component can wind through z),
        and the z-faces merge in one log-depth union-find.
    All paths produce identical labels (bit-exact, tests/test_cc_coarse.py).
    """
    from . import _backend

    if partition is None:
        from .pallas_cc import (
            pallas_cc_available,
            pallas_cc_tile,
            pallas_cc_tiled_available,
            pallas_connected_components,
            pallas_connected_components_tiled,
        )

        if pallas_cc_available(mask.shape, connectivity, per_slice):
            return pallas_connected_components(mask)
        if pallas_cc_tiled_available(mask.shape, connectivity, per_slice):
            return pallas_connected_components_tiled(
                mask, pallas_cc_tile(mask.shape)
            )
        if (
            _backend.use_slices_cc()
            and not per_slice and mask.ndim == 3 and connectivity == 1
        ):
            sliced = connected_components_raw(
                mask, connectivity, None, per_slice=True
            )
            return merge_slice_labels(mask, sliced)
    size = int(np.prod(mask.shape))
    if _backend.use_coarse_cc() or coarse_tile is not None:
        tile = resolve_coarse_tile(mask.shape, coarse_tile)
        raw, _ = connected_components_coarse_raw(
            mask, connectivity, partition, per_slice, tile
        )
    else:
        raw = connected_components_raw(
            mask, connectivity, partition, per_slice
        )
    labels, n = consecutive_from_flat_roots(raw.reshape(-1), size)
    return labels.reshape(mask.shape), n


def rank_of_flat_roots(flat: jnp.ndarray, size: int):
    """Prefix-count rank table over flat-index roots: ``rank[i]`` is the
    1-based consecutive id of the root at flat index i (valid where a root
    exists).  Shared by every consumer that must number components in
    minimal-flat-index order."""
    is_root = flat == jnp.arange(size, dtype=jnp.int32)
    root_rank = jnp.cumsum(is_root.astype(jnp.int32))
    n = root_rank[-1] if size > 0 else jnp.int32(0)
    return root_rank, n.astype(jnp.int32)


def consecutive_from_flat_roots(
    flat: jnp.ndarray, size: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rank flat-index component roots into consecutive ids 1..n (background
    stays 0, marked by negative entries).  Shared by the XLA and Pallas CC
    paths so their numbering stays in lockstep."""
    root_rank, n = rank_of_flat_roots(flat, size)
    safe = jnp.clip(flat, 0, size - 1)
    labels = jnp.where(flat >= 0, root_rank[safe], 0)
    return labels.astype(jnp.int32), n


def connected_components_labels(
    labels: jnp.ndarray,
    connectivity: int = 1,
    per_slice: bool = False,
    coarse_tile: Optional[Tuple[int, ...]] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Split a label image into its connected pieces (CC within equal labels,
    background 0) — vigra.labelMultiArrayWithBackground equivalent."""
    return connected_components(
        labels > 0, connectivity, partition=labels, per_slice=per_slice,
        coarse_tile=coarse_tile,
    )


def connected_components_np(mask: np.ndarray, connectivity: int = 1):
    """Host oracle via scipy (used by tests and the local parity path)."""
    from scipy import ndimage

    structure = ndimage.generate_binary_structure(mask.ndim, connectivity)
    labels, n = ndimage.label(mask, structure=structure)
    return labels.astype(np.int32), int(n)


def serpentine_mask(shape) -> np.ndarray:
    """Adversarial CC fixture: ONE corridor snaking through every other row
    and turning at alternating ends, so the component's graph diameter is
    Θ(H·W) with a bend every band — the worst case for propagation-style
    labeling (each fixpoint round resolves one straight segment).  3d shapes
    replicate the serpentine in every z-slice.  Shared by the parity tests
    (tests/test_cc_coarse.py), the bench iteration contract (bench.py), and
    the CI smoke (tools/ci_check.sh asserts the coarse kernel needs strictly
    fewer rounds than the flat one here)."""
    h, w = int(shape[-2]), int(shape[-1])
    m2 = np.zeros((h, w), dtype=bool)
    m2[::2, :] = True
    for i, r in enumerate(range(1, h, 2)):
        m2[r, w - 1 if i % 2 == 0 else 0] = True
    if len(shape) == 2:
        return m2
    return np.broadcast_to(m2, tuple(shape)).copy()
