"""Connected components as an XLA program.

Replaces skimage.morphology.label / vigra.labelVolumeWithBackground
(reference thresholded_components/block_components.py:143-182,
watershed/watershed.py:206,331).

Algorithm (TPU-friendly, no data-dependent shapes): iterative *min-label
propagation* over the neighborhood, accelerated by *pointer jumping* — after each
local propagation every voxel re-gathers the label of the voxel its label points to,
so label information travels exponentially per iteration (O(log diameter)
iterations instead of O(diameter)).  This is the same union-find-by-minimum idea a
parallel CC on GPUs uses (coarse-to-fine CCL literature), expressed as pure
gather/min ops inside a ``lax.while_loop``.
"""

from __future__ import annotations

from functools import partial
from itertools import product
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import _backend


def neighbor_offsets(
    ndim: int, connectivity: int, per_slice: bool = False
) -> np.ndarray:
    """All neighbor offsets with 1 ≤ #nonzero-coords ≤ connectivity
    (connectivity=1 → faces, ndim → full Moore neighborhood).  ``per_slice``
    drops offsets crossing axis 0, so each z-slice is an independent domain
    (the reference's 2d watershed/labeling modes)."""
    offs = [
        o
        for o in product((-1, 0, 1), repeat=ndim)
        if 0 < sum(c != 0 for c in o) <= connectivity
    ]
    if per_slice:
        offs = [o for o in offs if o[0] == 0]
    return np.array(offs, dtype=np.int32)


def _shift(x: jnp.ndarray, offset, fill) -> jnp.ndarray:
    """x shifted so out[p] = x[p + offset], `fill` outside."""
    out = x
    for axis, o in enumerate(offset):
        if o == 0:
            continue
        out = jnp.roll(out, -o, axis=axis)
        idx = [slice(None)] * x.ndim
        # out[p] = x[p+o] is invalid where p+o leaves the axis: the first |o|
        # entries for o<0, the last o entries for o>0
        idx[axis] = slice(0, -o) if o < 0 else slice(x.shape[axis] - o, None)
        out = out.at[tuple(idx)].set(fill)
    return out


def _use_assoc() -> bool:
    return _backend.use_assoc()


def _min_sweep(label, mask, partition, axis, reverse, sentinel):
    """Min-label propagation along one axis in log depth: the carry chain is
    a composition of clamp transfers c → min(u, max(c, l)) (the same family
    as the watershed sweeps), so a whole straight run collapses to its
    minimum in one ``lax.associative_scan`` instead of one voxel per round."""

    def mv(x):
        x = jnp.moveaxis(x, axis, 0)
        return jnp.flip(x, axis=0) if reverse else x

    l_v = mv(label)
    m_v = mv(mask)
    # conduction across the edge (i-1, i): both in mask, same partition
    prev_m = jnp.concatenate([jnp.zeros_like(m_v[:1]), m_v[:-1]], axis=0)
    conduct = m_v & prev_m
    if partition is not None:
        p_v = mv(partition)
        prev_p = jnp.concatenate([p_v[:1], p_v[:-1]], axis=0)
        conduct &= p_v == prev_p

    u = jnp.where(m_v, l_v, sentinel)
    low = jnp.where(conduct, jnp.int32(-1), sentinel)

    def combine(f, g):  # f earlier, g later
        uf, lf = f
        ug, lg = g
        return jnp.minimum(ug, jnp.maximum(uf, lg)), jnp.maximum(lf, lg)

    u_inc, _ = lax.associative_scan(combine, (u, low), axis=0)
    carry_in = jnp.concatenate(
        [jnp.full_like(u_inc[:1], sentinel), u_inc[:-1]], axis=0
    )
    out = jnp.where(conduct, jnp.minimum(l_v, carry_in), l_v)
    if reverse:
        out = jnp.flip(out, axis=0)
    return jnp.moveaxis(out, 0, axis)


@partial(jax.jit, static_argnames=("connectivity", "per_slice"))
def connected_components_raw(
    mask: jnp.ndarray,
    connectivity: int = 1,
    partition: Optional[jnp.ndarray] = None,
    per_slice: bool = False,
) -> jnp.ndarray:
    """Label foreground components of ``mask``.

    Returns int32 labels where background = -1 and each component carries the
    *minimal flat index* of its voxels — not consecutive; compose with
    ``relabel.relabel_consecutive`` (or host np.unique) for 1..N labels.

    With ``partition`` (an int array), voxels only merge when their partition
    values are equal — i.e. CC *within* existing labels, the equivalent of
    vigra.labelMultiArrayWithBackground on a segmentation (used to re-close
    labels after halo cropping, reference watershed.py:329-333).
    """
    shape = mask.shape
    size = int(np.prod(shape))
    sentinel = jnp.int32(size)
    flat_ids = jnp.arange(size, dtype=jnp.int32).reshape(shape)
    init = jnp.where(mask, flat_ids, sentinel)
    offsets = neighbor_offsets(mask.ndim, connectivity, per_slice)
    axes = tuple(range(mask.ndim))
    if per_slice:
        axes = axes[1:]
    # face-neighbor conduction is exactly axis conduction, so on the sweep
    # path connectivity=1 needs no shift-propagation at all; higher
    # connectivities keep shifts for the diagonal offsets
    sweep = _use_assoc()
    prop_offsets = (
        [o for o in offsets if sum(c != 0 for c in o) > 1] if sweep
        else list(offsets)
    )

    def propagate(label):
        best = label
        for off in prop_offsets:
            neigh = _shift(label, off, sentinel)
            ok = mask
            if partition is not None:
                same = _shift(partition, off, jnp.asarray(-1, partition.dtype)) == partition
                ok = ok & same
            best = jnp.minimum(best, jnp.where(ok, neigh, sentinel))
        return jnp.where(mask, best, sentinel)

    def jump(label):
        # label[p] <- label[label[p]]: pointer jumping through the flat volume
        flat = jnp.append(label.reshape(-1), sentinel)  # sentinel self-loops
        jumped = flat[label.reshape(-1)].reshape(label.shape)
        return jnp.where(mask, jumped, sentinel)

    def cond(state):
        label, prev_changed = state
        return prev_changed

    def body(state):
        label, _ = state
        new = label
        if sweep:
            for axis in axes:
                for reverse in (False, True):
                    new = _min_sweep(
                        new, mask, partition, axis, reverse, sentinel
                    )
        if prop_offsets:
            new = propagate(new)
        new = jump(jump(new))
        return (new, jnp.any(new != label))

    label, _ = lax.while_loop(cond, body, (init, jnp.bool_(True)))
    return jnp.where(mask, label, jnp.int32(-1))


def merge_slice_labels(
    mask: jnp.ndarray, sliced: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Volume CC from per-slice minimal-flat-index labels (−1 background):
    one device pointer-jumping union-find over the z-face equivalences, then
    consecutive ranking.  Shared by the Pallas per-slice kernel
    (ops/pallas_cc.py) and the XLA ``slices`` CC mode — valid for
    connectivity 1 only (z-diagonal adjacency would need more edges)."""
    from .unionfind import merge_labels_device

    n, h, w = mask.shape
    size = n * h * w
    # z-face equivalences (self-loops where either side is background pad
    # the static edge table)
    up = sliced[:-1].reshape(-1)
    dn = sliced[1:].reshape(-1)
    both = (up >= 0) & (dn >= 0)
    edges = jnp.stack(
        [jnp.where(both, up, 0), jnp.where(both, dn, 0)], axis=1
    )
    parent = jnp.arange(size, dtype=jnp.int32)
    roots = merge_labels_device(parent, edges)
    flat = jnp.where(
        mask.reshape(-1),
        roots[jnp.clip(sliced.reshape(-1), 0, size - 1)],
        -1,
    )
    labels, n_comp = consecutive_from_flat_roots(flat, size)
    return labels.reshape(mask.shape), n_comp


@partial(jax.jit, static_argnames=("connectivity", "per_slice"))
def connected_components(
    mask: jnp.ndarray,
    connectivity: int = 1,
    partition: Optional[jnp.ndarray] = None,
    per_slice: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Consecutive component labeling: background 0, components 1..n.

    Returns ``(labels, n_components)``.  Consecutive ids come from ranking the
    component roots (minimal flat indices) with a cumsum — no dynamic shapes.
    See ``connected_components_raw`` for ``partition`` / ``per_slice``.

    Mode switches (read at trace time, ops/_backend.py):
      * ``CTT_CC_MODE=pallas`` — VMEM-resident per-slice kernel + z-merge
        (ops/pallas_cc.py) on eligible volumes (3d, connectivity 1, no
        partition, lane-aligned slices, TPU backend);
      * ``CTT_CC_MODE=slices`` — the same slices+z-merge STRUCTURE in plain
        XLA: per-slice 2d sweeps converge in far fewer rounds than
        whole-volume 3d propagation (a 3d component can wind through z),
        and the z-faces merge in one log-depth union-find.
    Both produce identical labels to the default path.
    """
    if partition is None:
        from . import _backend
        from .pallas_cc import pallas_cc_available, pallas_connected_components

        if pallas_cc_available(mask.shape, connectivity, per_slice):
            return pallas_connected_components(mask)
        if (
            _backend.use_slices_cc()
            and not per_slice and mask.ndim == 3 and connectivity == 1
        ):
            sliced = connected_components_raw(
                mask, connectivity, None, per_slice=True
            )
            return merge_slice_labels(mask, sliced)
    raw = connected_components_raw(mask, connectivity, partition, per_slice)
    size = int(np.prod(mask.shape))
    labels, n = consecutive_from_flat_roots(raw.reshape(-1), size)
    return labels.reshape(mask.shape), n


def rank_of_flat_roots(flat: jnp.ndarray, size: int):
    """Prefix-count rank table over flat-index roots: ``rank[i]`` is the
    1-based consecutive id of the root at flat index i (valid where a root
    exists).  Shared by every consumer that must number components in
    minimal-flat-index order."""
    is_root = flat == jnp.arange(size, dtype=jnp.int32)
    root_rank = jnp.cumsum(is_root.astype(jnp.int32))
    n = root_rank[-1] if size > 0 else jnp.int32(0)
    return root_rank, n.astype(jnp.int32)


def consecutive_from_flat_roots(
    flat: jnp.ndarray, size: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rank flat-index component roots into consecutive ids 1..n (background
    stays 0, marked by negative entries).  Shared by the XLA and Pallas CC
    paths so their numbering stays in lockstep."""
    root_rank, n = rank_of_flat_roots(flat, size)
    safe = jnp.clip(flat, 0, size - 1)
    labels = jnp.where(flat >= 0, root_rank[safe], 0)
    return labels.astype(jnp.int32), n


def connected_components_labels(
    labels: jnp.ndarray, connectivity: int = 1, per_slice: bool = False
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Split a label image into its connected pieces (CC within equal labels,
    background 0) — vigra.labelMultiArrayWithBackground equivalent."""
    return connected_components(
        labels > 0, connectivity, partition=labels, per_slice=per_slice
    )


def connected_components_np(mask: np.ndarray, connectivity: int = 1):
    """Host oracle via scipy (used by tests and the local parity path)."""
    from scipy import ndimage

    structure = ndimage.generate_binary_structure(mask.ndim, connectivity)
    labels, n = ndimage.label(mask, structure=structure)
    return labels.astype(np.int32), int(n)
