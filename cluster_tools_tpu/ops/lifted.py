"""Lifted multicut: sparse lifted neighborhoods and a lifted-GAEC solver.

Replaces nifty's lifted-multicut stack (reference
lifted_features/sparse_lifted_neighborhood.py:132-137 via
``ndist.computeLiftedNeighborhoodFromNodeLabels`` and
lifted_multicut/solve_lifted_subproblems.py:205-213 via
``elf...get_lifted_multicut_solver``).

The neighborhood search runs on host (scipy.sparse BFS — ragged graph data);
the solver is greedy additive edge contraction generalized to lifted edges:
clusters are contractible only along *local* (RAG) edges, but the contraction
priority is the combined local+lifted cost between the two clusters, and both
cost maps merge on contraction.  Contraction stops when the best combined cost
drops to 0 (the GAEC stopping rule).
"""

from __future__ import annotations

import heapq
from typing import Dict, Optional, Tuple

import numpy as np


def lifted_neighborhood(
    n_nodes: int,
    edges: np.ndarray,
    participating: np.ndarray,
    depth: int = 2,
) -> np.ndarray:
    """Sparse lifted edges: pairs of ``participating`` nodes with graph
    distance in [2, depth] over the local graph.

    ``participating`` is a boolean mask [n_nodes] (the reference restricts the
    neighborhood to nodes carrying a semantic label,
    sparse_lifted_neighborhood.py:132-137).  Distance-1 pairs are local edges,
    not lifted ones.  Returns [L, 2] with u < v, lexicographically sorted.

    Memory stays sparse: chunked multi-source frontier BFS over a CSR
    adjacency (never a dense distance matrix), so the cost is proportional to
    the edges actually reached within ``depth``.
    """
    from scipy.sparse import csr_matrix

    part_idx = np.nonzero(participating)[0]
    if part_idx.size < 2 or edges.shape[0] == 0 or depth < 2:
        return np.zeros((0, 2), dtype=np.int64)
    # int32 path counts: int8 overflows at >=128 parallel paths through
    # high-degree hubs, silently dropping reached nodes; per-entry counts are
    # bounded by node degree, so int32 is safe at a quarter of int64's memory
    data = np.ones(edges.shape[0], dtype=np.int32)
    adj = csr_matrix(
        (data, (edges[:, 0], edges[:, 1])), shape=(n_nodes, n_nodes)
    )
    adj = ((adj + adj.T) > 0).astype(np.int32)

    pair_chunks = []
    chunk = 4096
    for lo in range(0, part_idx.size, chunk):
        sources = part_idx[lo : lo + chunk]
        visited = csr_matrix(
            (
                np.ones(sources.size, dtype=np.int32),
                (np.arange(sources.size), sources),
            ),
            shape=(sources.size, n_nodes),
        )
        frontier = visited
        reached = []
        for d in range(1, depth + 1):
            frontier = ((frontier @ adj) > 0).astype(np.int32)
            frontier = frontier - frontier.multiply(visited)
            frontier.eliminate_zeros()
            if frontier.nnz == 0:
                break
            visited = ((visited + frontier) > 0).astype(np.int32)
            if d >= 2:
                reached.append(frontier.tocoo())
        for coo in reached:
            u = sources[coo.row]
            v = coo.col.astype(np.int64)
            keep = (u < v) & participating[v]
            if keep.any():
                pair_chunks.append(
                    np.stack([u[keep], v[keep]], axis=1).astype(np.int64)
                )
    if not pair_chunks:
        return np.zeros((0, 2), dtype=np.int64)
    pairs = np.unique(np.concatenate(pair_chunks, axis=0), axis=0)
    return pairs


def lifted_costs_from_node_labels(
    lifted_uv: np.ndarray,
    node_labels: np.ndarray,
    same_cost: float,
    different_cost: float,
    ignore_label: Optional[int] = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Attractive/repulsive lifted costs from per-node semantic labels
    (reference lifted_features/costs_from_node_labels.py:25).

    Pairs with equal labels get ``same_cost`` (attractive > 0), different
    labels ``different_cost`` (repulsive < 0); pairs touching ``ignore_label``
    are dropped.  Returns (filtered lifted_uv, costs).
    """
    la = node_labels[lifted_uv[:, 0]]
    lb = node_labels[lifted_uv[:, 1]]
    keep = np.ones(lifted_uv.shape[0], dtype=bool)
    if ignore_label is not None:
        keep = (la != ignore_label) & (lb != ignore_label)
    la, lb = la[keep], lb[keep]
    costs = np.where(la == lb, float(same_cost), float(different_cost))
    return lifted_uv[keep], costs


def merge_lifted_problems(problems) -> Tuple[np.ndarray, np.ndarray]:
    """Concatenate (lifted_uv, costs) problems, summing costs of duplicate
    pairs (reference lifted_features/merge_lifted_problems.py:23)."""
    uvs = [p[0] for p in problems if p[0].shape[0]]
    if not uvs:
        return np.zeros((0, 2), dtype=np.int64), np.zeros(0)
    uv = np.concatenate(uvs, axis=0)
    costs = np.concatenate([p[1] for p in problems if p[0].shape[0]])
    uniq, inv = np.unique(uv, axis=0, return_inverse=True)
    summed = np.zeros(uniq.shape[0])
    np.add.at(summed, inv, costs)
    return uniq.astype(np.int64), summed


def _lifted_gaec_python(
    n_nodes: int,
    uv: np.ndarray,
    costs: np.ndarray,
    lifted_uv: np.ndarray,
    lifted_costs: np.ndarray,
) -> np.ndarray:
    """Greedy additive edge contraction with lifted costs (host fallback)."""
    local: list = [dict() for _ in range(n_nodes)]
    lifted: list = [dict() for _ in range(n_nodes)]
    for (u, v), c in zip(uv, costs):
        u, v = int(u), int(v)
        if u == v:
            continue
        local[u][v] = local[u].get(v, 0.0) + float(c)
        local[v][u] = local[u][v]
    for (u, v), c in zip(lifted_uv, lifted_costs):
        u, v = int(u), int(v)
        if u == v:
            continue
        lifted[u][v] = lifted[u].get(v, 0.0) + float(c)
        lifted[v][u] = lifted[u][v]

    parent = np.arange(n_nodes, dtype=np.int64)

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def combined(u, v):
        return local[u][v] + lifted[u].get(v, 0.0)

    stamp: Dict[Tuple[int, int], int] = {}
    counter = 0
    heap = []
    for u in range(n_nodes):
        for v in local[u]:
            if v > u:
                stamp[(u, v)] = 0
                heapq.heappush(heap, (-combined(u, v), u, v, 0))

    while heap:
        negp, u, v, st = heapq.heappop(heap)
        ru, rv = find(u), find(v)
        if ru == rv:
            continue
        key = (min(ru, rv), max(ru, rv))
        if stamp.get(key) != st:
            continue
        if -negp <= 0.0:
            break
        # contract rv into ru (smaller adjacency into larger)
        if len(local[ru]) + len(lifted[ru]) < len(local[rv]) + len(lifted[rv]):
            ru, rv = rv, ru
        parent[rv] = ru
        for m in (local, lifted):
            m[ru].pop(rv, None)
            m[rv].pop(ru, None)
        touched = set()
        for m in (local, lifted):
            for w, c in m[rv].items():
                m[w].pop(rv, None)
                m[ru][w] = m[ru].get(w, 0.0) + c
                m[w][ru] = m[ru][w]
                touched.add(w)
            m[rv].clear()
        touched.update(local[ru].keys())
        # sorted: heap push order must not depend on set hashing, or equal
        # costs tie-break nondeterministically across runs (CTT005)
        for w in sorted(touched):
            if w not in local[ru]:
                continue  # lifted-only pairs are not contractible
            counter += 1
            k2 = (min(ru, w), max(ru, w))
            stamp[k2] = counter
            heapq.heappush(heap, (-combined(ru, w), ru, w, counter))

    return np.array([find(i) for i in range(n_nodes)], dtype=np.int64)


def solve_lifted_multicut(
    n_nodes: int,
    uv: np.ndarray,
    costs: np.ndarray,
    lifted_uv: np.ndarray,
    lifted_costs: np.ndarray,
    use_native: bool = True,
) -> np.ndarray:
    """Lifted multicut via lifted-GAEC: consecutive node labeling (0..k-1).

    Positive cost = attractive, negative = repulsive, for both edge sets.
    Lifted edges influence merge priorities but never make two clusters
    contractible on their own.
    """
    if uv.shape[0] == 0:
        return np.arange(n_nodes, dtype=np.int64)
    if lifted_uv.shape[0] == 0:
        from .multicut import solve_multicut

        return solve_multicut(n_nodes, uv, costs, use_native=use_native)
    from .. import native

    if use_native and native.available() and hasattr(native, "lifted_gaec"):
        roots = native.lifted_gaec(n_nodes, uv, costs, lifted_uv, lifted_costs)
    else:
        roots = _lifted_gaec_python(n_nodes, uv, costs, lifted_uv, lifted_costs)
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.int64)


def lifted_multicut_energy(
    uv: np.ndarray,
    costs: np.ndarray,
    lifted_uv: np.ndarray,
    lifted_costs: np.ndarray,
    labels: np.ndarray,
) -> float:
    """Sum of costs of cut edges, local + lifted (test oracle)."""
    e = 0.0
    if uv.shape[0]:
        cut = labels[uv[:, 0]] != labels[uv[:, 1]]
        e += float(costs[cut].sum())
    if lifted_uv.shape[0]:
        cut = labels[lifted_uv[:, 0]] != labels[lifted_uv[:, 1]]
        e += float(lifted_costs[cut].sum())
    return e
