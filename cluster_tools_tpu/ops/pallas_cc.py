"""Connected components with a per-slice Pallas TPU kernel.

The XLA CC (`ops.cc.connected_components_raw`) iterates (sweeps + pointer
jumping) as full-array programs under a `lax.while_loop`: every round trips
each state array through HBM, and the pointer-jump gathers are
latency-bound.  This path instead labels each z-slice entirely inside VMEM
(grid = slices, the layout of `ops.pallas_flood`): per slice, min-label
propagation runs to its fixpoint with log-depth directional sweeps — no
gathers anywhere in the kernel — so the HBM traffic is one mask read and one
label write per slice.  Slices are then fused along z by ONE device
pointer-jumping merge over the (z, z+1) face equivalences
(`ops.unionfind.merge_labels_device`), whose rounds are O(log n_slices),
not O(volume diameter).

Labels returned match `ops.cc.connected_components` exactly: components are
numbered 1..n in minimal-flat-index order (asserted in
tests/test_pallas_cc.py), so the two paths are drop-in interchangeable.

Activation mirrors the flood kernel: `CTT_CC_MODE=pallas` opts
`connectivity=1` 3d volumes with lane-aligned slices (H % 8 == 0,
W % 128 == 0) into this path on the TPU backend; everything else falls back
to the XLA program.  Off by default until hardware-validated
(tools/tpu_validate.py measures it when a chip is reachable).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from .pallas_flood import _shift  # one shift/pad primitive for both kernels

_SENT = np.int32(np.iinfo(np.int32).max - 1)
_NEG = np.int32(-1)


def _sweep_min(label, mask_i, axis, reverse):
    """One directional min-label sweep in log depth.

    Identical clamp-transfer composition to ops.cc._min_sweep (same
    (u, low) combine), expressed with reverse shifts instead of flips so no
    data reorientation is lowered.  ``low`` is −1 on conducting edges (the
    carry passes) and the sentinel on walls (the carry resets).

    ``mask_i`` is int32 0/1, not bool: Mosaic cannot concatenate/pad i1
    vregs (invalid bitcast_vreg i1->i32 on hardware), so the shifted mask
    must be full-width."""
    prev_m = _shift(mask_i, 1, axis, reverse, jnp.int32(0))
    conduct = (mask_i & prev_m) != 0
    mask = mask_i != 0

    u = jnp.where(mask, label, _SENT)
    l = jnp.where(conduct, _NEG, _SENT)

    n = label.shape[axis]
    for k in range(int(np.ceil(np.log2(max(n, 2))))):
        uf = _shift(u, 1 << k, axis, reverse, _SENT)
        lf = _shift(l, 1 << k, axis, reverse, _NEG)
        u = jnp.minimum(u, jnp.maximum(uf, l))
        l = jnp.maximum(lf, l)

    carry_in = _shift(u, 1, axis, reverse, _SENT)
    return jnp.where(conduct, jnp.minimum(label, carry_in), label)


def _cc_slice_kernel(m_ref, o_ref):
    """Label one slice's components with its minimal *volume* flat index."""
    mask_i = m_ref[0]
    mask = mask_i != 0
    h_dim, w_dim = mask.shape
    z = pl.program_id(0)
    row = lax.broadcasted_iota(jnp.int32, (h_dim, w_dim), 0)
    col = lax.broadcasted_iota(jnp.int32, (h_dim, w_dim), 1)
    flat = (z * h_dim + row) * w_dim + col
    # true fixpoint loop: a capped fori_loop is NOT safe here — banded
    # serpentine corridors need Θ(H·W) rounds, far beyond any H+W-style
    # bound (each round resolves one directional segment of the
    # min-label propagation path, and a corridor can turn at every band)
    lab = _cc_tile_fixpoint(mask_i, jnp.where(mask, flat, _SENT))
    o_ref[0] = jnp.where(mask, lab, jnp.int32(-1))


@functools.partial(jax.jit, static_argnames=("interpret",))
def cc_slices(mask, interpret: bool = False):
    """Per-slice CC of a (N, H, W) bool volume: every foreground voxel gets
    the minimal volume-flat-index of its in-slice component; background −1."""
    n, h, w = mask.shape
    spec = lambda: pl.BlockSpec((1, h, w), lambda i: (i, 0, 0))  # noqa: E731
    return pl.pallas_call(
        _cc_slice_kernel,
        grid=(n,),
        in_specs=[spec()],
        out_specs=spec(),
        out_shape=jax.ShapeDtypeStruct((n, h, w), jnp.int32),
        interpret=interpret,
    )(mask.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_connected_components(mask, interpret: bool = False):
    """3d connectivity-1 CC: Pallas per-slice labeling + one device
    pointer-jumping merge over the z-face equivalences.

    Returns ``(labels, n)`` with consecutive components 1..n in minimal-
    flat-index order — the same contract as ``ops.cc.connected_components``.
    """
    from .cc import merge_slice_labels

    mask = mask.astype(bool)
    sliced = cc_slices(mask, interpret=interpret)
    return merge_slice_labels(mask, sliced)


def _cc_tile_fixpoint(mask_i, label0):
    """Min-label fixpoint of one in-VMEM 2d block: directional log-depth
    sweeps iterated until stable (shared by the whole-slice and tiled
    kernels; see ``_cc_slice_kernel`` for why the loop must be a true
    fixpoint, not a capped fori_loop)."""

    def cond(carry):
        _, changed = carry
        return changed

    def body(carry):
        lab, _ = carry
        new = lab
        for axis in (0, 1):
            for rev in (False, True):
                new = _sweep_min(new, mask_i, axis, rev)
        # reduce over int32, not i1 (Mosaic i1 vreg bitcast limitation)
        return new, jnp.max((new != lab).astype(jnp.int32)) > 0

    lab, _ = lax.while_loop(cond, body, (label0, jnp.bool_(True)))
    return lab


@functools.partial(jax.jit, static_argnames=("tile_hw", "interpret"))
def cc_tiles(mask, tile_hw, interpret: bool = False):
    """Tile-local CC of a (N, H, W) bool volume: grid = (slices, tile rows,
    tile cols), each (th, tw) tile labeled entirely in VMEM with the minimal
    *volume* flat index of its in-tile component (background −1).  The
    coarse-to-fine analog of ``cc_slices`` for slices too large to hold
    whole in VMEM; fuse with ``ops.cc.merge_tiled_labels``."""
    n, h, w = mask.shape
    th, tw = tile_hw

    def kernel(m_ref, o_ref):
        mask_i = m_ref[0]
        msk = mask_i != 0
        z = pl.program_id(0)
        row = lax.broadcasted_iota(jnp.int32, (th, tw), 0) + pl.program_id(1) * th
        col = lax.broadcasted_iota(jnp.int32, (th, tw), 1) + pl.program_id(2) * tw
        flat = (z * h + row) * w + col
        lab = _cc_tile_fixpoint(mask_i, jnp.where(msk, flat, _SENT))
        o_ref[0] = jnp.where(msk, lab, _NEG)

    spec = lambda: pl.BlockSpec((1, th, tw), lambda i, j, k: (i, j, k))  # noqa: E731
    return pl.pallas_call(
        kernel,
        grid=(n, h // th, w // tw),
        in_specs=[spec()],
        out_specs=spec(),
        out_shape=jax.ShapeDtypeStruct((n, h, w), jnp.int32),
        interpret=interpret,
    )(mask.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("tile_hw", "interpret"))
def pallas_connected_components_tiled(mask, tile_hw, interpret: bool = False):
    """3d connectivity-1 CC via the tiled Pallas kernel + ONE compact
    value-table merge over every tile face (z faces included — tile depth is
    1, so the slice merge rides the same table).  Same ``(labels, n)``
    contract as ``pallas_connected_components``/``ops.cc.connected_components``.
    """
    from .cc import merge_tiled_labels

    mask = mask.astype(bool)
    tiled = cc_tiles(mask, tile_hw, interpret=interpret)
    return merge_tiled_labels(mask, tiled, (1,) + tuple(tile_hw))


def pallas_cc_tile(shape):
    """Tile shape for the tiled kernel: the largest lane-aligned divisors of
    (H, W) — W tile a multiple of 128 up to 512, H tile a multiple of 8 up
    to 256 — fitting the ~8-buffer VMEM budget; None when no aligned divisor
    exists."""
    _, h, w = shape
    budget = 12 * 1024 * 1024 // (4 * 8)  # i32 elements per tile
    tw = max(
        (t for t in range(128, min(w, 512) + 1, 128) if w % t == 0),
        default=None,
    )
    if tw is None:
        return None
    th = max(
        (
            t
            for t in range(8, min(h, 256) + 1, 8)
            if h % t == 0 and t * tw <= budget
        ),
        default=None,
    )
    if th is None:
        return None
    return (th, tw)


def pallas_cc_tiled_available(shape, connectivity: int, per_slice: bool) -> bool:
    """True when the TILED Pallas CC applies: the same opt-in and volume
    conditions as ``pallas_cc_available`` but without the whole-slice VMEM
    bound — slices of any size qualify as long as an aligned tile divisor
    exists.  The dispatch in ``ops.cc.connected_components`` prefers the
    whole-slice kernel when it fits."""
    from . import _backend

    if not _backend.use_pallas_cc():
        return False
    if per_slice or connectivity != 1 or len(shape) != 3:
        return False
    if shape[1] % 8 or shape[2] % 128:
        return False
    if pallas_cc_tile(shape) is None:
        return False
    return jax.default_backend() == "tpu"


def pallas_cc_available(shape, connectivity: int, per_slice: bool) -> bool:
    """True when the Pallas CC applies: opted in (CTT_CC_MODE=pallas or a
    ``force_cc_mode('pallas')`` scope), 3d connectivity-1 volume-wide
    labeling, TPU backend, lane-aligned slices.  Evaluated at TRACE time
    (compiled shapes keep their path until the jit caches clear)."""
    from . import _backend

    if not _backend.use_pallas_cc():
        return False
    if per_slice or connectivity != 1 or len(shape) != 3:
        return False
    if shape[1] % 8 or shape[2] % 128:
        return False
    # VMEM budget (ADVICE r3): the per-slice kernel holds ~8 full-slice i32
    # buffers; oversized slices must take the XLA path instead of failing
    # Mosaic lowering at runtime
    if shape[1] * shape[2] * 4 * 8 > 12 * 1024 * 1024:
        return False
    return jax.default_backend() == "tpu"
