"""Per-slice seeded flood as a Pallas TPU kernel.

The XLA flood (`ops.watershed._seeded_watershed_scan`) runs each directional
sweep as its own full-array program under a `lax.while_loop`: every sweep round
trips through HBM for each state array.  This kernel instead keeps one
z-slice's whole flood state (height map, altitude, hops, labels) resident in
VMEM (a 256x256 f32 slice is 256 KB — a dozen such fields fit in ~16 MB) and
runs BOTH phases to their fixpoint inside a single kernel instance, so the
only HBM traffic is one read of (hmap, seeds, mask) and one write of the
labels per slice.  Grid = slices: independent floods per z-slice is exactly
the reference's 2d watershed mode (reference watershed/watershed.py:120-137),
which is also its production default (`apply_ws_2d: True`).

Semantics are identical to the XLA path (same lexicographic
(pass-height, hops, label) relaxation, same tie-breaking — see
ops/watershed.py module docstring); equivalence is asserted by
tests/test_pallas_flood.py against `_seeded_watershed_scan` in interpret
mode.  Sweeps use the same log-depth transfer-function doubling as the
`assoc` XLA mode: a directional sweep composes per-element clamp transfers
c -> min(u, max(c, l)) by repeated shift-and-compose (log2(n) steps), so no
sequential per-lane carry chain exists anywhere in the kernel.  Reverse-
direction sweeps shift from the opposite side instead of flipping the data —
no data reorientation anywhere.

Activation: `CTT_FLOOD_MODE=pallas` opts the per-slice flood into this kernel
on the TPU backend for lane-aligned slice shapes (H multiple of 8, W multiple
of 128); everything else falls back to the XLA path.  Off by default until
hardware-validated (tools/tpu_validate.py measures it when a chip is
reachable — Mosaic lowering cannot be exercised on the CPU interpreter).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from .watershed import _minlex  # one source of truth for the tie-break rule

_BIG = np.float32(3.0e38)
_NEG = np.float32(-3.0e38)
_BIG_DIST = np.int32(np.iinfo(np.int32).max - 1)


def _shift(x, d, axis, reverse, fill):
    """The value of the element ``d`` steps *earlier* along the sweep:
    earlier = lower index for a forward sweep, higher index for reverse.
    Static-size slice + constant pad (no flips, no rolls)."""
    if d >= x.shape[axis]:
        return jnp.full_like(x, fill)
    if axis == 0:
        pad = jnp.full_like(x[:d, :], fill)
        if reverse:
            return jnp.concatenate([x[d:, :], pad], axis=0)
        return jnp.concatenate([pad, x[:-d, :]], axis=0)
    pad = jnp.full_like(x[:, :d], fill)
    if reverse:
        return jnp.concatenate([x[:, d:], pad], axis=1)
    return jnp.concatenate([pad, x[:, :-d]], axis=1)


def _sweep_altitude(alt, hmap, is_seed, mask, axis, reverse):
    """One Gauss-Seidel altitude sweep A'(p) = min(A(p), max(carry, h(p))) by
    doubling the clamp-transfer composition (u, l): log2(n) shift+compose
    steps — the in-VMEM mirror of ops.watershed._sweep_altitude_assoc."""
    conduct = mask & ~is_seed
    u = jnp.where(mask, alt, _BIG)
    l = jnp.where(conduct, hmap, u)

    n = alt.shape[axis]
    for k in range(int(np.ceil(np.log2(max(n, 2))))):
        # compose the earlier window's transfer (shifted) before our own;
        # identity transfer (BIG, NEG) pads past the boundary
        uf = _shift(u, 1 << k, axis, reverse, _BIG)
        lf = _shift(l, 1 << k, axis, reverse, _NEG)
        u = jnp.minimum(u, jnp.maximum(uf, l))
        l = jnp.maximum(lf, l)

    # exclusive prefix applied to the initial carry BIG is just the composed u
    carry_in = _shift(u, 1, axis, reverse, _BIG)
    return jnp.where(conduct, jnp.minimum(alt, jnp.maximum(carry_in, hmap)), alt)


def _sweep_assign(dist, label, alt, hmap, is_seed, mask, axis, reverse):
    """One (hops, label) BFS sweep over optimal-prefix edges
    (A(p) == max(A(q), h(p))) by doubling the (const_d, const_l, step, pass)
    transfer composition — mirror of ops.watershed._sweep_assign_assoc."""
    alt_masked = jnp.where(mask, alt, _BIG)
    prev_alt = _shift(alt_masked, 1, axis, reverse, _BIG)
    edge_ok = alt == jnp.maximum(prev_alt, hmap)
    can_update = mask & ~is_seed & edge_ok

    cd = jnp.where(mask, dist, _BIG_DIST)
    cl = jnp.where(mask, label, 0)
    step = jnp.ones_like(dist)
    # pass-through flag as int32 0/1, not i1: Mosaic cannot concatenate/pad
    # i1 vregs (invalid bitcast_vreg i1->i32 on real hardware), so every
    # value that flows through _shift must be a full-width dtype
    pas = can_update.astype(jnp.int32)

    n = dist.shape[axis]
    for k in range(int(np.ceil(np.log2(max(n, 2))))):
        fd = _shift(cd, 1 << k, axis, reverse, _BIG_DIST)
        fl = _shift(cl, 1 << k, axis, reverse, jnp.int32(0))
        fk = _shift(step, 1 << k, axis, reverse, jnp.int32(0))
        fp = _shift(pas, 1 << k, axis, reverse, jnp.int32(0))
        cand_d = fd + step
        cand_l = jnp.where(pas != 0, fl, 0)
        cd, cl = _minlex(cd, cl, cand_d, cand_l)
        step = fk + step
        pas = fp & pas

    carry_d = _shift(cd, 1, axis, reverse, _BIG_DIST)
    carry_l = _shift(cl, 1, axis, reverse, jnp.int32(0))

    cand_dist = carry_d + 1
    better = can_update & (carry_l > 0) & (
        (cand_dist < dist)
        | ((cand_dist == dist) & ((label == 0) | (carry_l < label)))
    )
    return (
        jnp.where(better, cand_dist, dist),
        jnp.where(better, carry_l, label),
    )


def flood_arrays(hmap, seeds, mask):
    """Both flood phases to their fixpoint over in-VMEM (H, W) arrays —
    shared by the standalone flood kernel and the fused DT-watershed kernel
    (ops/pallas_dtws.py)."""
    seeds = jnp.where(mask, seeds, 0)
    is_seed = seeds > 0

    # true fixpoint loops: a capped fori_loop is NOT safe — banded
    # serpentine corridors turn at every band, needing Θ(H·W) rounds (one
    # directional segment resolves per round), far beyond any H+W bound

    # -- phase 1: altitude --------------------------------------------------
    def alt_cond(carry):
        _, changed = carry
        return changed

    def alt_round(carry):
        alt, _ = carry
        new = alt
        for axis in (0, 1):
            for rev in (False, True):
                new = _sweep_altitude(new, hmap, is_seed, mask, axis, rev)
        # reduce over int32, not i1 (Mosaic i1 vreg bitcast limitation)
        return new, jnp.max((new != alt).astype(jnp.int32)) > 0

    alt0 = jnp.where(is_seed, hmap, _BIG)
    alt, _ = lax.while_loop(alt_cond, alt_round, (alt0, jnp.bool_(True)))

    # -- phase 2: assignment ------------------------------------------------
    def asg_cond(carry):
        _, _, changed = carry
        return changed

    def asg_round(carry):
        dist, label, _ = carry
        d, l = dist, label
        for axis in (0, 1):
            for rev in (False, True):
                d, l = _sweep_assign(d, l, alt, hmap, is_seed, mask, axis, rev)
        changed = ((d != dist) | (l != label)).astype(jnp.int32)
        return d, l, jnp.max(changed) > 0

    dist0 = jnp.where(is_seed, 0, _BIG_DIST)
    _, label, _ = lax.while_loop(
        asg_cond, asg_round, (dist0, seeds, jnp.bool_(True))
    )
    return jnp.where(mask, label, 0)


def _flood_slice_kernel(h_ref, s_ref, m_ref, o_ref):
    """Whole per-slice flood: both phases iterated to their fixpoint in VMEM."""
    o_ref[0] = flood_arrays(h_ref[0], s_ref[0], m_ref[0] != 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def flood_slices(hmap, seeds, mask, interpret: bool = False):
    """Flood every z-slice of ``hmap`` (N, H, W) independently from ``seeds``
    (int32, 0 = unlabeled), restricted to ``mask``.  One kernel instance per
    slice; returns int32 labels shaped like ``hmap``.

    Same fixpoint as ``seeded_watershed(..., per_slice=True)`` on a (N, H, W)
    volume (asserted in tests).  ``interpret=True`` runs the CPU interpreter
    (correctness testing without TPU hardware).
    """
    n, h, w = hmap.shape
    spec = lambda: pl.BlockSpec((1, h, w), lambda i: (i, 0, 0))  # noqa: E731
    return pl.pallas_call(
        _flood_slice_kernel,
        grid=(n,),
        in_specs=[spec(), spec(), spec()],
        out_specs=spec(),
        out_shape=jax.ShapeDtypeStruct((n, h, w), jnp.int32),
        interpret=interpret,
    )(
        hmap.astype(jnp.float32),
        seeds.astype(jnp.int32),
        mask.astype(jnp.int32),
    )


def _flood_tile_alt_kernel(h_ref, s_ref, m_ref, o_ref):
    """Phase-1 (altitude) fixpoint of one in-VMEM tile — the ctt-cc
    hierarchy warm start: tile-local altitudes are min-max passes of real
    in-tile paths, a valid phase-1 over-approximation for the XLA global
    loops (ops.watershed._flood_scan_impl's ``warm``).  Phase 2 is NOT
    warm-started here on purpose: tile-local (hops, label) states against
    tile-local altitudes can undercut the global fixpoint (see the
    _flood_scan_impl docstring)."""
    hmap = h_ref[0]
    mask = m_ref[0] != 0
    is_seed = (s_ref[0] > 0) & mask

    def cond(carry):
        _, changed = carry
        return changed

    def body(carry):
        alt, _ = carry
        new = alt
        for axis in (0, 1):
            for rev in (False, True):
                new = _sweep_altitude(new, hmap, is_seed, mask, axis, rev)
        # reduce over int32, not i1 (Mosaic i1 vreg bitcast limitation)
        return new, jnp.max((new != alt).astype(jnp.int32)) > 0

    alt0 = jnp.where(is_seed, hmap, _BIG)
    alt, _ = lax.while_loop(cond, body, (alt0, jnp.bool_(True)))
    o_ref[0] = alt


@functools.partial(jax.jit, static_argnames=("tile_hw", "interpret"))
def flood_tiles_warm(hmap, seeds, mask, tile_hw, interpret: bool = False):
    """Tile-local flood-altitude fixpoints of a (N, H, W) volume: grid =
    (slices, tile rows, tile cols), each (th, tw) tile relaxed entirely in
    VMEM.  Returns the f32 warm altitude field (``_BIG`` outside mask) for
    ``ops.watershed`` to finish globally — the Pallas leg of the
    hierarchical flood."""
    n, h, w = hmap.shape
    th, tw = tile_hw
    spec = lambda: pl.BlockSpec((1, th, tw), lambda i, j, k: (i, j, k))  # noqa: E731
    return pl.pallas_call(
        _flood_tile_alt_kernel,
        grid=(n, h // th, w // tw),
        in_specs=[spec(), spec(), spec()],
        out_specs=spec(),
        out_shape=jax.ShapeDtypeStruct((n, h, w), jnp.float32),
        interpret=interpret,
    )(
        hmap.astype(jnp.float32),
        seeds.astype(jnp.int32),
        mask.astype(jnp.int32),
    )


def pallas_flood_tiled_available(shape, per_slice: bool, tile) -> bool:
    """True when the tiled Pallas warm start applies: opted in
    (CTT_FLOOD_MODE=pallas), 3d volume, TPU backend, and the flood tile's
    in-plane extent exactly tiles a lane-aligned slice.  Valid for both 2d
    and 3d floods (in-tile paths are real paths either way); the whole-slice
    kernel is preferred when it applies (``pallas_flood_available``)."""
    from . import _backend

    if not _backend.use_pallas_flood():
        return False
    if len(shape) != 3 or len(tile) != 3:
        return False
    th, tw = int(tile[1]), int(tile[2])
    if th % 8 or tw % 128 or shape[1] % th or shape[2] % tw:
        return False
    return jax.default_backend() == "tpu"


def pallas_flood_available(shape, per_slice: bool) -> bool:
    """True when the Pallas flood applies: opted in (CTT_FLOOD_MODE=pallas or
    a ``force_flood_mode('pallas')`` scope), per-slice mode, 3d volume, TPU
    backend, lane-aligned slice shape.

    Evaluated at TRACE time (this runs inside jitted callers): a shape that
    was already compiled keeps its path until the jit caches are cleared —
    pin the mode before first use, or use ``_backend.force_flood_mode``,
    which owns the cache invalidation."""
    from . import _backend

    if not _backend.use_pallas_flood():
        return False
    if not per_slice or len(shape) != 3:
        return False
    if shape[1] % 8 or shape[2] % 128:
        return False
    return jax.default_backend() == "tpu"
