"""The ENTIRE per-slice DT-watershed as one Pallas TPU kernel.

The reference's production-default watershed config is the 2d mode
(``apply_dt_2d: True, apply_ws_2d: True`` — reference watershed.py:54-56):
every z-slice runs threshold → 2d EDT → smoothed-maxima seeds → height map
→ seeded flood independently.  The XLA path (`ops.watershed.dt_watershed`)
runs that as a dozen full-array programs; this kernel runs the WHOLE
per-slice pipeline inside VMEM — grid = slices, one input read and three
output writes (labels, seed roots, hmap) of HBM traffic per slice:

  1. threshold (+ mask/valid) → fg;
  2. 2d squared EDT: exact line distances along H (prefix-max doubling over
     the nearest-background index), then the dense min-plus parabola pass
     along W in j-tiles (the same tiled formulation as ops/dt._parabola_pass);
  3. seeds: gaussian(dt) by explicit symmetric-padded tap sums → 3×3 maxima
     (plateau-tolerant) → full-connectivity in-slice CC by log-depth
     min-label sweeps along rows, columns AND diagonals (pallas_cc's clamp
     composition; diagonal conduction via composed shifts);
  4. height map α·x + (1-α)·(1 − normalize(dt)), gaussian-smoothed;
  5. both flood phases to their fixpoint (`pallas_flood.flood_arrays`).

Labels come back as in-slice seed roots encoded as volume-flat indices (+1);
the host-side wrapper `pallas_dt_watershed` ranks them globally consecutive
(the same minimal-flat-index order as `ops.watershed.dt_seeds`) and applies
the size filter with the XLA epilogue — bit-for-bit the label semantics of
``dt_watershed(apply_dt_2d=True, apply_ws_2d=True)`` up to float-sum
ordering inside the gaussian taps (asserted partition-identical, and
near-exact stage-wise, in tests/test_pallas_dtws.py).

Activation: `CTT_DTWS_MODE=pallas` (TPU backend, per-slice mode, lane-aligned
slices, no NMS, no pixel pitch).  Off by default until hardware-validated;
tools/tpu_validate.py measures lowering + perf when a chip is reachable.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from .filters import _gauss_kernel
from .pallas_flood import _BIG, _shift, flood_arrays

_BIG_DT = np.float32(1e10)  # ops/dt._BIG


def _prefix_max(x, axis, reverse):
    """Inclusive prefix max along a direction by shift-compose doubling."""
    n = x.shape[axis]
    out = x
    for k in range(int(np.ceil(np.log2(max(n, 2))))):
        out = jnp.maximum(out, _shift(out, 1 << k, axis, reverse, -_BIG))
    return out


def _line_distance_sq(bg, pitch=1.0):
    """Squared exact 1d distance to the nearest True of ``bg`` along axis 0
    — the in-VMEM mirror of ops/dt._line_scan_distance (assoc mode):
    d_i = pitch · (i − nearest bg index), directional via prefix max over
    bg-carrying indices, two directions, min."""
    h, w = bg.shape
    iota = lax.broadcasted_iota(jnp.float32, (h, w), 0)

    # forward: nearest True at or before i
    last = _prefix_max(jnp.where(bg, iota, -_BIG), 0, False)
    fwd = jnp.minimum((iota - last) * pitch, _BIG_DT)
    # backward: nearest True at or after i — mirror via reversed iota
    riota = jnp.float32(h - 1) - iota
    rlast = _prefix_max(jnp.where(bg, riota, -_BIG), 0, True)
    bwd = jnp.minimum((riota - rlast) * pitch, _BIG_DT)
    d = jnp.minimum(fwd, bwd)
    return d * d


def _parabola_w(g, tile=32):
    """g'(h, i) = min_j g(h, j) + (i-j)² along axis 1 — the dense j-tiled
    min-plus product of ops/dt._parabola_pass, j-tiles statically unrolled."""
    h, w = g.shape
    n_pad = -w % tile
    gp = (
        jnp.concatenate([g, jnp.full((h, n_pad), _BIG_DT, g.dtype)], axis=1)
        if n_pad else g
    )
    i_idx = lax.broadcasted_iota(jnp.float32, (w, tile), 0)
    out = jnp.full((h, w), _BIG_DT, g.dtype)
    for t in range(gp.shape[1] // tile):
        j0 = t * tile
        j_idx = jnp.float32(j0) + lax.broadcasted_iota(
            jnp.float32, (w, tile), 1
        )
        diff = i_idx - j_idx  # (w_i, tile_j)
        # static slice via lax.slice_in_dim + expand_dims: the jnp mixed
        # None+slice indexing path lowers to lax.gather, which Mosaic
        # rejects ("Shape mismatch in input, indices and output")
        g_tile = jnp.expand_dims(
            lax.slice_in_dim(gp, j0, j0 + tile, axis=1), 1)
        cost = g_tile + jnp.expand_dims(diff * diff, 0)
        out = jnp.minimum(out, cost.min(axis=-1))
    return out


def _reflect_pad(x, r, axis):
    """Symmetric ('reflect-including-edge') padding by r on both sides,
    built from static single-row/column concatenations (no flips)."""
    parts = []
    n = x.shape[axis]
    take = lambda k: (  # noqa: E731
        x[k : k + 1] if axis == 0 else x[:, k : k + 1]
    )
    for k in range(r - 1, -1, -1):
        parts.append(take(min(k, n - 1)))
    parts.append(x)
    for k in range(r):
        parts.append(take(max(n - 1 - k, 0)))
    return jnp.concatenate(parts, axis=axis)


def _conv1d(x, taps, axis):
    """Correlation with a symmetric 1d kernel along ``axis``, symmetric
    boundary — explicit tap sum over static slices (taps are host floats)."""
    r = len(taps) // 2
    xp = _reflect_pad(x, r, axis)
    n = x.shape[axis]
    acc = None
    for k, wgt in enumerate(taps):
        sl = (
            xp[k : k + n] if axis == 0 else xp[:, k : k + n]
        )
        term = jnp.float32(wgt) * sl
        acc = term if acc is None else acc + term
    return acc


def _max3(x):
    """3×3 maximum filter with edge-replicate boundary (symmetric pad of 1)."""
    xp = _reflect_pad(_reflect_pad(x, 1, 0), 1, 1)
    h, w = x.shape
    out = None
    for dy in range(3):
        for dx in range(3):
            v = xp[dy : dy + h, dx : dx + w]
            out = v if out is None else jnp.maximum(out, v)
    return out


_SENT = np.int32(np.iinfo(np.int32).max - 1)


def _shift2(x, d, rev0, rev1, fill):
    """Diagonal shift: d steps along BOTH axes (direction per axis)."""
    return _shift(_shift(x, d, 0, rev0, fill), d, 1, rev1, fill)


def _cc_full_conn(mask, label0):
    """In-slice CC over the FULL 8-neighborhood: log-depth min-label sweeps
    along rows, columns and both diagonals, iterated to the fixpoint —
    pallas_cc's clamp composition extended with diagonal directions.  The
    fixpoint (minimal label per component) is schedule-independent, so it
    matches ops/cc's pointer-jumping result exactly."""
    # int32 mirror of the mask for everything that flows through _shift:
    # Mosaic cannot concatenate/pad i1 vregs (invalid bitcast_vreg on chip)
    mask_i = mask.astype(jnp.int32)

    def sweep(label, shift_fn, prev_mask_fn):
        conduct = mask & (prev_mask_fn(mask_i) != 0)
        u = jnp.where(mask, label, _SENT)
        l = jnp.where(conduct, jnp.int32(-1), _SENT)
        n = max(label.shape)
        for k in range(int(np.ceil(np.log2(max(n, 2))))):
            uf = shift_fn(u, 1 << k, _SENT)
            lf = shift_fn(l, 1 << k, jnp.int32(-1))
            u = jnp.minimum(u, jnp.maximum(uf, l))
            l = jnp.maximum(lf, l)
        carry_in = shift_fn(u, 1, _SENT)
        return jnp.where(conduct, jnp.minimum(label, carry_in), label)

    directions = []
    for axis in (0, 1):
        for rev in (False, True):
            directions.append((
                lambda x, d, f, a=axis, r=rev: _shift(x, d, a, r, f),
                lambda m, a=axis, r=rev: _shift(m, 1, a, r, jnp.int32(0)),
            ))
    for rev0 in (False, True):
        for rev1 in (False, True):
            directions.append((
                lambda x, d, f, r0=rev0, r1=rev1: _shift2(x, d, r0, r1, f),
                lambda m, r0=rev0, r1=rev1: _shift2(
                    m, 1, r0, r1, jnp.int32(0)),
            ))

    def cond(carry):
        _, changed = carry
        return changed

    def body(carry):
        lab, _ = carry
        new = lab
        for shift_fn, prev_fn in directions:
            new = sweep(new, shift_fn, prev_fn)
        return new, jnp.max((new != lab).astype(jnp.int32)) > 0

    lab, _ = lax.while_loop(cond, body, (label0, jnp.bool_(True)))
    return lab


def _dtws_slice_kernel(
    x_ref, m_ref, v_ref, lab_ref, root_ref, hmap_ref,
    *, threshold, seed_taps, weight_taps, alpha, invert,
):
    x = x_ref[0]
    mask = m_ref[0] != 0
    valid = v_ref[0] != 0
    h, w = x.shape
    if invert:
        x = 1.0 - x
    fg = (x < threshold) & mask

    # -- 2d squared EDT: lines along H, parabola along W --------------------
    g = _line_distance_sq(~fg)
    g = _parabola_w(g)
    dt = jnp.sqrt(jnp.minimum(g, _BIG_DT)).astype(jnp.float32)

    # -- seeds ---------------------------------------------------------------
    sm = dt
    if seed_taps is not None:
        sm = _conv1d(_conv1d(sm, seed_taps, 0), seed_taps, 1)
    local_max = (_max3(sm) == sm) & (dt > 0)

    z = pl.program_id(0)
    row = lax.broadcasted_iota(jnp.int32, (h, w), 0)
    col = lax.broadcasted_iota(jnp.int32, (h, w), 1)
    flat = (z * h + row) * w + col
    label0 = jnp.where(local_max, flat, _SENT)
    roots = _cc_full_conn(local_max, label0)
    seed_ids = jnp.where(local_max, roots + 1, 0)  # volume-flat root + 1

    # -- height map ----------------------------------------------------------
    lo = dt.min()
    hi = dt.max()
    dtn = (dt - lo) / jnp.maximum(hi - lo, jnp.float32(1e-6))
    hmap = alpha * x + (1.0 - alpha) * (1.0 - dtn)
    if weight_taps is not None:
        hmap = _conv1d(_conv1d(hmap, weight_taps, 0), weight_taps, 1)

    # -- flood ---------------------------------------------------------------
    labels = flood_arrays(hmap, seed_ids, fg & valid)

    lab_ref[0] = labels
    root_ref[0] = jnp.where(local_max, roots, jnp.int32(-1))
    hmap_ref[0] = hmap


@functools.partial(
    jax.jit,
    static_argnames=(
        "threshold", "sigma_seeds", "sigma_weights", "alpha", "invert",
        "interpret",
    ),
)
def dtws_slices(
    x, mask, valid,
    threshold: float = 0.5,
    sigma_seeds: float = 2.0,
    sigma_weights: float = 2.0,
    alpha: float = 0.8,
    invert: bool = False,
    interpret: bool = False,
):
    """Run the fused per-slice DT-watershed kernel over an (N, H, W) stack.

    Returns ``(labels, seed_roots, hmap)``: labels carry volume-flat seed
    roots + 1 (0 background), seed_roots the maxima CC roots (−1 off-seed),
    hmap the smoothed height map (for the size-filter epilogue)."""
    n, h, w = x.shape
    seed_taps = (
        tuple(float(t) for t in _gauss_kernel(sigma_seeds))
        if sigma_seeds and sigma_seeds > 0 else None
    )
    weight_taps = (
        tuple(float(t) for t in _gauss_kernel(sigma_weights))
        if sigma_weights and sigma_weights > 0 else None
    )
    kernel = functools.partial(
        _dtws_slice_kernel,
        threshold=np.float32(threshold),
        seed_taps=seed_taps,
        weight_taps=weight_taps,
        alpha=np.float32(alpha),
        invert=bool(invert),
    )
    spec = lambda: pl.BlockSpec((1, h, w), lambda i: (i, 0, 0))  # noqa: E731
    labels, roots, hmap = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[spec(), spec(), spec()],
        out_specs=(spec(), spec(), spec()),
        out_shape=(
            jax.ShapeDtypeStruct((n, h, w), jnp.int32),
            jax.ShapeDtypeStruct((n, h, w), jnp.int32),
            jax.ShapeDtypeStruct((n, h, w), jnp.float32),
        ),
        interpret=interpret,
    )(
        x.astype(jnp.float32),
        mask.astype(jnp.int32),
        valid.astype(jnp.int32),
    )
    return labels, roots, hmap


def pallas_dt_watershed(
    input_,
    mask=None,
    valid=None,
    threshold: float = 0.5,
    sigma_seeds: float = 2.0,
    sigma_weights: float = 2.0,
    alpha: float = 0.8,
    size_filter: int = 25,
    invert_input: bool = False,
    interpret: bool = False,
):
    """Drop-in for ``dt_watershed(apply_dt_2d=True, apply_ws_2d=True)`` on a
    3d block: fused kernel + the global consecutive seed ranking and the XLA
    size-filter epilogue.  Returns ``(labels int32, n_seeds)``."""
    from .cc import rank_of_flat_roots
    from .watershed import apply_size_filter

    x = jnp.asarray(input_, jnp.float32)
    n, h, w = x.shape
    if mask is None:
        mask = jnp.ones(x.shape, bool)
    if valid is None:
        valid = jnp.ones(x.shape, bool)
    labels_flat, roots, hmap = dtws_slices(
        x, mask, valid,
        threshold=threshold, sigma_seeds=sigma_seeds,
        sigma_weights=sigma_weights, alpha=alpha, invert=invert_input,
        interpret=interpret,
    )
    size = n * h * w
    # seeds globally consecutive in minimal-flat-index order — identical
    # numbering to dt_seeds(per_slice=True)
    rank, n_seeds = rank_of_flat_roots(roots.reshape(-1), size)
    lf = labels_flat.reshape(-1)
    safe = jnp.clip(lf - 1, 0, size - 1)
    labels = jnp.where(lf > 0, rank[safe], 0).reshape(x.shape).astype(
        jnp.int32
    )
    if size_filter > 0:
        num_segments = int(np.prod(x.shape)) // 2 + 2
        fg = x if not invert_input else 1.0 - x
        flood_mask = (fg < threshold) & mask.astype(bool) & valid.astype(bool)
        labels = apply_size_filter(
            labels, hmap, size_filter, num_segments, mask=flood_mask,
            per_slice=True,
        )
    return labels, n_seeds


def pallas_dtws_available(shape, apply_dt_2d, apply_ws_2d, pixel_pitch,
                          nms, sigma_seeds=0.0, sigma_weights=0.0) -> bool:
    """Gate: opted in (CTT_DTWS_MODE=pallas / force_dtws_mode), per-slice
    mode, 3d, no pitch/NMS, TPU backend, lane-aligned slices, and gaussian
    radii strictly inside the slice — the kernel's reflect padding clamps
    at the edge where np.pad(mode="symmetric") cycles, so radii reaching
    across a full axis would silently diverge from the XLA path."""
    from . import _backend

    if not _backend.use_pallas_dtws():
        return False
    if not (apply_dt_2d and apply_ws_2d) or len(shape) != 3:
        return False
    if pixel_pitch is not None or nms:
        return False
    if shape[1] % 8 or shape[2] % 128:
        return False
    # VMEM budget (ADVICE r3): _parabola_w materializes an (H, W, 32) f32
    # cost tensor plus ~a dozen full-slice f32 temporaries; slices whose
    # working set exceeds the ~16 MB VMEM would fail Mosaic lowering at
    # runtime inside the gated dt_watershed instead of falling back
    vmem = shape[1] * shape[2] * 4 * (32 + 12)
    if vmem > 12 * 1024 * 1024:
        return False
    for sigma in (sigma_seeds, sigma_weights):
        if sigma and sigma > 0:
            radius = max(int(4.0 * sigma + 0.5), 1)
            if radius >= min(shape[1], shape[2]):
                return False
    return jax.default_backend() == "tpu"
