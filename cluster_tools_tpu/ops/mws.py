"""Mutex watershed (reference mutex_watershed/mws_blocks.py via affogato C++).

The MWS is a Kruskal-with-mutex-constraints algorithm (SURVEY.md §7
hard-parts #2).  The per-block solve defaults to the host (C++ via
``native``, python fallback); a data-parallel device formulation exists in
``ops/mws_device.py`` (mutually-best-edge parallel greedy, CTT_MWS_MODE=device).
Block results are stitched with the standard offset + stitching machinery.

``compute_mws_segmentation`` builds the pixel grid graph from long-range
affinities: the first ``ndim`` offsets are attractive (nearest-neighbor), the
rest repulsive, with optional strides/randomization subsampling the repulsive
edges (reference mws_blocks.py:135-170).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from .. import native


def _grid_edges(
    shape: Sequence[int],
    offsets: np.ndarray,
    strides: Optional[Sequence[int]],
    randomize_strides: bool,
    rng: np.random.Generator,
    ndim: int,
):
    """Edges (u, v, channel) for every offset; long-range edges subsampled."""
    size = int(np.prod(shape))
    ids = np.arange(size, dtype=np.int64).reshape(shape)
    uvc = []
    for c, off in enumerate(offsets):
        src = [slice(max(-o, 0), s - max(o, 0)) for o, s in zip(off, shape)]
        dst = [slice(max(o, 0), s - max(-o, 0)) for o, s in zip(off, shape)]
        u = ids[tuple(src)]
        v = ids[tuple(dst)]
        is_attractive = c < ndim
        if not is_attractive and strides is not None:
            if randomize_strides:
                keep = rng.random(u.shape) < 1.0 / np.prod(strides)
                u, v = u[keep], v[keep]
            else:
                stride_sl = tuple(slice(None, None, s) for s in strides)
                u, v = u[stride_sl], v[stride_sl]
        uvc.append((u.reshape(-1), v.reshape(-1), c, is_attractive))
    return uvc


def compute_mws_segmentation(
    affs: np.ndarray,
    offsets: Sequence[Sequence[int]],
    strides: Optional[Sequence[int]] = None,
    randomize_strides: bool = False,
    mask: Optional[np.ndarray] = None,
    noise_level: float = 0.0,
    seed: int = 0,
    use_native: bool = True,
) -> np.ndarray:
    """Mutex watershed over an affinity map [C, *spatial].

    Attractive channels (first ndim) use weight = affinity; repulsive channels
    use weight = 1 - affinity ... both sorted together by weight descending —
    equivalently affogato sorts by max(w_attr, w_rep).  Higher attractive
    affinity ⇒ stronger merge; higher repulsive evidence (low affinity) ⇒
    stronger mutex.
    """
    offsets = np.asarray(offsets, dtype=np.int64)
    ndim = affs.ndim - 1
    shape = affs.shape[1:]
    if offsets.shape[0] != affs.shape[0]:
        raise ValueError(
            f"{affs.shape[0]} affinity channels but {offsets.shape[0]} offsets"
        )
    rng = np.random.default_rng(seed)
    us, vs, ws, attr = _affinity_edge_lists(
        affs, offsets, strides, randomize_strides, noise_level, rng, ndim
    )
    uv = np.stack([np.concatenate(us), np.concatenate(vs)], axis=1)
    weights = np.concatenate(ws)
    attractive = np.concatenate(attr)

    if mask is not None:
        m = mask.reshape(-1).astype(bool)
        keep = m[uv[:, 0]] & m[uv[:, 1]]
        uv, weights, attractive = uv[keep], weights[keep], attractive[keep]

    size = int(np.prod(shape))
    roots = mutex_watershed_graph(size, uv, weights, attractive, use_native)
    _, labels = np.unique(roots, return_inverse=True)
    labels = (labels + 1).astype(np.uint64).reshape(shape)
    if mask is not None:
        labels[~mask.astype(bool)] = 0
    return labels


def _affinity_edge_lists(affs, offsets, strides, randomize_strides,
                         noise_level, rng, ndim):
    """Shared grid-edge construction for the plain and seeded MWS variants."""
    shape = affs.shape[1:]
    affs = affs.astype(np.float64)
    if noise_level > 0:
        affs = np.clip(
            affs + noise_level * rng.standard_normal(affs.shape), 0.0, 1.0
        )
    us, vs, ws, attr = [], [], [], []
    for u, v, c, is_attractive in _grid_edges(
        shape, offsets, strides, randomize_strides, rng, ndim
    ):
        us.append(u)
        vs.append(v)
        aff_vals = affs[c].reshape(-1)
        # edge weight lives at the source voxel position of the offset slice
        ws.append(aff_vals[u] if is_attractive else 1.0 - aff_vals[u])
        attr.append(np.full(u.shape, is_attractive, dtype=np.uint8))
    return us, vs, ws, attr


def mutex_watershed_graph(
    n_nodes: int,
    uv: np.ndarray,
    weights: np.ndarray,
    attractive: np.ndarray,
    use_native: bool = True,
) -> np.ndarray:
    """Graph-domain MWS returning root per node.

    Routes to the mutually-best-edge parallel-greedy device kernel
    (ops/mws_device.py — the TPU formulation) when CTT_MWS_MODE=device /
    ``force_mws_mode("device")``; otherwise host C++ (default) or the
    python fallback."""
    from . import _backend

    if _backend.use_mws_device() and n_nodes < np.iinfo(np.int32).max:
        from .mws_device import mutex_watershed_device

        return mutex_watershed_device(n_nodes, uv, weights, attractive)
    if use_native and native.available():
        return native.mutex_watershed(n_nodes, uv, weights, attractive)
    return _mws_python(n_nodes, uv, weights, attractive)


def _mws_python(n_nodes, uv, weights, attractive) -> np.ndarray:
    order = np.argsort(-weights, kind="stable")
    parent = np.arange(n_nodes, dtype=np.int64)

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    mutexes = [set() for _ in range(n_nodes)]
    for idx in order:
        a, b = int(uv[idx, 0]), int(uv[idx, 1])
        ra, rb = find(a), find(b)
        if ra == rb:
            continue
        if attractive[idx]:
            if rb in mutexes[ra]:
                continue
            # merge smaller mutex set into larger
            if len(mutexes[ra]) < len(mutexes[rb]):
                ra, rb = rb, ra
            parent[rb] = ra
            for m in mutexes[rb]:
                mutexes[ra].add(m)
                mutexes[m].discard(rb)
                mutexes[m].add(ra)
            mutexes[rb] = set()
        else:
            mutexes[ra].add(rb)
            mutexes[rb].add(ra)
    return np.array([find(i) for i in range(n_nodes)], dtype=np.int64)


def compute_mws_segmentation_with_seeds(
    affs: np.ndarray,
    offsets: Sequence[Sequence[int]],
    seeds: np.ndarray,
    strides: Optional[Sequence[int]] = None,
    randomize_strides: bool = False,
    mask: Optional[np.ndarray] = None,
    noise_level: float = 0.0,
    seed: int = 0,
    use_native: bool = True,
    max_mutex_ids: int = 1024,
) -> np.ndarray:
    """MWS constrained by pre-labeled seed voxels.

    The two-pass seeding mechanism (reference two_pass_mws.py:137-193 via
    affogato grid-graph state): voxels sharing a seed label are chained with
    above-maximal attractive edges (processed before any affinity edge), and
    one representative per seed label is pairwise-mutexed against every other
    label's representative — so pass-2 blocks can neither split a neighbor's
    segment nor merge two distinct neighbor segments.  Output voxels in a seed
    region keep the seed label; new segments get ids past ``seeds.max()``.
    """
    offsets = np.asarray(offsets, dtype=np.int64)
    ndim = affs.ndim - 1
    shape = affs.shape[1:]
    if offsets.shape[0] != affs.shape[0]:
        raise ValueError(
            f"{affs.shape[0]} affinity channels but {offsets.shape[0]} offsets"
        )
    rng = np.random.default_rng(seed)
    us, vs, ws, attr = _affinity_edge_lists(
        affs, offsets, strides, randomize_strides, noise_level, rng, ndim
    )

    # vectorized seed constraints: group seed voxels by label with one argsort
    flat_seeds = seeds.reshape(-1).astype(np.int64)
    seeded_vox = np.nonzero(flat_seeds > 0)[0]
    order = seeded_vox[np.argsort(flat_seeds[seeded_vox], kind="stable")]
    lab_sorted = flat_seeds[order]
    new_group = np.concatenate([[True], lab_sorted[1:] != lab_sorted[:-1]])
    seed_ids = lab_sorted[new_group]
    reps = order[new_group]
    if order.size:
        # chains within each seed label (consecutive sorted voxels, skipping
        # the group boundaries) — super-attractive, processed before any
        # affinity edge
        intra = ~new_group[1:]
        if intra.any():
            us.append(order[:-1][intra])
            vs.append(order[1:][intra])
            ws.append(np.full(int(intra.sum()), 2.0))
            attr.append(np.ones(int(intra.sum()), dtype=np.uint8))
    k = reps.size
    if k > 1:
        if k <= max_mutex_ids:
            ru, rv = np.triu_indices(k, k=1)
        else:
            # all-pairs would be O(k^2); chain mutexes are a weaker guarantee
            # (mutual exclusion is not transitive) but bound the edge count
            ru = np.arange(k - 1)
            rv = ru + 1
        us.append(reps[ru])
        vs.append(reps[rv])
        ws.append(np.full(ru.size, 2.0))
        attr.append(np.zeros(ru.size, dtype=np.uint8))

    uv = np.stack([np.concatenate(us), np.concatenate(vs)], axis=1)
    weights = np.concatenate(ws)
    attractive = np.concatenate(attr)
    if mask is not None:
        m = mask.reshape(-1).astype(bool)
        keep = m[uv[:, 0]] & m[uv[:, 1]]
        uv, weights, attractive = uv[keep], weights[keep], attractive[keep]

    size = int(np.prod(shape))
    roots = mutex_watershed_graph(size, uv, weights, attractive, use_native)
    _, labels = np.unique(roots, return_inverse=True)
    labels = (labels + 1).astype(np.int64)

    # vectorized relabel: clusters holding a seed representative take the seed
    # id, the rest move past the seed id range
    seed_base = int(seed_ids.max()) if seed_ids.size else 0
    cluster_to_seed = np.zeros(int(labels.max()) + 1, dtype=np.int64)
    if reps.size:
        cluster_to_seed[labels[reps]] = seed_ids
    out = np.where(
        cluster_to_seed[labels] > 0, cluster_to_seed[labels],
        labels + seed_base,
    ).astype(np.uint64)
    out = out.reshape(shape)
    if mask is not None:
        out[~mask.astype(bool)] = 0
    return out
