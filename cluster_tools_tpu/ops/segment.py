"""Segment reductions: the TPU replacement for nifty's accumulators.

Per-segment statistics (count/sum/mean/min/max/quantiles), overlap counting and
contingency tables are all expressed over flat label arrays with
``jax.ops.segment_*`` / bincount — the data-parallel primitives XLA lowers to
efficient scatter-reductions.  These back region features, morphology, node-label
votes and Rand/VoI evaluation (reference: nifty.distributed accumulators,
SURVEY.md §2.10).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("num_segments",))
def segment_count(labels: jnp.ndarray, num_segments: int) -> jnp.ndarray:
    return jnp.bincount(labels.reshape(-1), length=num_segments)


@partial(jax.jit, static_argnames=("num_segments",))
def segment_sum(labels: jnp.ndarray, values: jnp.ndarray, num_segments: int):
    return jax.ops.segment_sum(
        values.reshape(-1), labels.reshape(-1), num_segments=num_segments
    )


@partial(jax.jit, static_argnames=("num_segments",))
def segment_mean(labels: jnp.ndarray, values: jnp.ndarray, num_segments: int):
    s = segment_sum(labels, values, num_segments)
    c = segment_count(labels, num_segments)
    return s / jnp.maximum(c, 1)


@partial(jax.jit, static_argnames=("num_segments",))
def segment_min(labels: jnp.ndarray, values: jnp.ndarray, num_segments: int):
    return jax.ops.segment_min(
        values.reshape(-1), labels.reshape(-1), num_segments=num_segments
    )


@partial(jax.jit, static_argnames=("num_segments",))
def segment_max(labels: jnp.ndarray, values: jnp.ndarray, num_segments: int):
    return jax.ops.segment_max(
        values.reshape(-1), labels.reshape(-1), num_segments=num_segments
    )


@partial(jax.jit, static_argnames=("num_segments",))
def segment_moments(labels: jnp.ndarray, values: jnp.ndarray, num_segments: int):
    """count, mean, variance per segment in one pass."""
    lab = labels.reshape(-1)
    val = values.reshape(-1).astype(jnp.float32)
    c = jnp.bincount(lab, length=num_segments)
    s1 = jax.ops.segment_sum(val, lab, num_segments=num_segments)
    s2 = jax.ops.segment_sum(val * val, lab, num_segments=num_segments)
    cs = jnp.maximum(c, 1)
    mean = s1 / cs
    var = jnp.maximum(s2 / cs - mean * mean, 0.0)
    return c, mean, var


@partial(jax.jit, static_argnames=("num_segments", "ndim"))
def segment_bounding_boxes(
    labels: jnp.ndarray, num_segments: int, ndim: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-segment bbox begin/end (morphology columns, reference
    block_morphology.py:128-134)."""
    lab = labels.reshape(-1)
    coords = jnp.stack(
        jnp.meshgrid(*[jnp.arange(s) for s in labels.shape], indexing="ij"), axis=-1
    ).reshape(-1, ndim)
    begin = jnp.stack(
        [
            jax.ops.segment_min(coords[:, d], lab, num_segments=num_segments)
            for d in range(ndim)
        ],
        axis=1,
    )
    end = jnp.stack(
        [
            jax.ops.segment_max(coords[:, d], lab, num_segments=num_segments)
            for d in range(ndim)
        ],
        axis=1,
    )
    return begin, end + 1


@partial(jax.jit, static_argnames=("num_segments", "ndim"))
def segment_center_of_mass(labels: jnp.ndarray, num_segments: int, ndim: int):
    lab = labels.reshape(-1)
    c = jnp.maximum(jnp.bincount(lab, length=num_segments), 1)
    coords = jnp.stack(
        jnp.meshgrid(*[jnp.arange(s) for s in labels.shape], indexing="ij"), axis=-1
    ).reshape(-1, ndim)
    com = jnp.stack(
        [
            jax.ops.segment_sum(
                coords[:, d].astype(jnp.float32), lab, num_segments=num_segments
            )
            for d in range(ndim)
        ],
        axis=1,
    )
    return com / c[:, None]


# -- overlaps / contingency (host-side, ragged outputs) -------------------------


def contingency_table(
    seg_a: np.ndarray, seg_b: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sparse contingency table between two labelings of the same voxels.

    Returns (ids_a, ids_b, counts) for every co-occurring label pair — the basis
    of overlap votes and Rand/VoI (reference evaluation/measures.py:90-118,
    nifty.ground_truth.overlap).  Host implementation over np.unique: inputs may
    be uint64 volumes larger than any static shape budget.
    """
    a = np.asarray(seg_a).reshape(-1)
    b = np.asarray(seg_b).reshape(-1)
    pairs = np.stack([a, b], axis=1)
    uniq, counts = np.unique(pairs, axis=0, return_counts=True)
    return uniq[:, 0], uniq[:, 1], counts


def max_overlap_assignment(
    seg: np.ndarray, reference: np.ndarray, ignore_zero: bool = True
) -> dict:
    """For each label in ``seg``, the reference label with maximal overlap
    (mutual-max stitching votes / node-label merging, reference
    merge_node_labels.py:149, stitch_faces.py:110-175).

    ``ignore_zero`` drops label 0 on *both* sides: background source segments get
    no entry, and overlaps **with** background never win the vote (the
    reference's ignore-label masking, stitch_faces.py:100-107)."""
    ids_a, ids_b, counts = contingency_table(seg, reference)
    if ignore_zero:
        keep = (ids_a != 0) & (ids_b != 0)
        ids_a, ids_b, counts = ids_a[keep], ids_b[keep], counts[keep]
    order = np.lexsort((counts,))  # ascending; later wins below → max count
    best: dict = {}
    for a, b, c in zip(ids_a[order], ids_b[order], counts[order]):
        best[int(a)] = int(b)
    return best
