"""ctt-hier: one-flood hierarchical segmentation primitives.

The reference stack re-runs the whole block-wise pipeline for every merge
threshold a proofreader tries.  GPU hierarchical watershed partitioning
(arXiv:2410.08946, PAPERS.md) shows the hierarchy can be built ONCE and
re-cut at any level: record, for every pair of adjacent regions, the
*saddle* — the minimum over their shared boundary of the voxel-pair edge
weight ``max(h(p), h(q))`` — and segmentation at merge threshold ``t`` is
exactly "union every region pair whose saddle ≤ t", a value-space
union-find over the edge table plus one gather through the resolved
roots.  No flood, no distance transform, no seed detection: the
re-segmentation cost is O(edges ≤ t) + O(voxels) gather.

This module is the device layer of that story:

  * :func:`block_merge_table` — the FULL-adjacency sibling of
    ``ops.watershed.flood_merge_table`` (which records tile-face edges
    only): every canonical-offset voxel adjacency of a labeled block, as
    static-shape ``(a, b, saddle)`` columns (``a < b``; slots that are
    not a real inter-region edge carry ``(0, 0, _BIG)``) — vmappable over
    a stacked block batch, one dispatch per batch.
  * :func:`reduce_merge_table` / :func:`merge_face_pairs` — host
    reductions to the per-pair minimum saddle (the hierarchy edge), for
    in-block tables and 1-voxel block-face slabs respectively.
  * :func:`cut_table` — threshold the saddle column of a sorted-by-saddle
    hierarchy (one ``searchsorted``), resolve the selected edges with ONE
    value-space union-find pass (``ops.unionfind.merge_value_table`` —
    O(edges) table, not O(labels)), and return the ``(vals, roots)``
    relabel table.  Padded to power-of-two sizes so a threshold sweep
    recompiles O(log edges) times, not once per threshold.
  * :func:`recut_labels` — the re-cut "kernel": one gather of a labels
    block batch through the relabel table (``apply_value_roots``); labels
    absent from the table pass through unchanged.
  * :func:`resegment_np` — the host brute-force oracle (full adjacency
    union-find with numpy), the parity reference for tests.
  * :func:`save_hierarchy` / :func:`load_hierarchy` — the persistent
    artifact (npz, sorted by saddle; schema documented beside the store
    schemas in ``obs/trace.py``).

Saddle heights are measured on whatever height field the caller passes —
tasks/hier.py uses the flood's *working input* (the normalized, possibly
inverted boundary map), which is a per-voxel transform of the stored
volume and therefore globally consistent across blocks: in-block edges
(device) and block-face edges (host) land on identical values.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .cc import _canonical_offsets, _shift
from .unionfind import UnionFindNp, apply_value_roots, merge_value_table

# same non-conducting sentinel as the flood kernels (ops/watershed.py);
# numpy scalar so importing this module never initializes a backend
_BIG = np.float32(3.0e38)

HIER_SCHEMA_VERSION = 1


# ---------------------------------------------------------------------------
# device: full-adjacency merge table of one labeled block


@partial(jax.jit, static_argnames=("connectivity", "per_slice"))
def block_merge_table(
    labels: jnp.ndarray,
    heights: jnp.ndarray,
    connectivity: int = 1,
    per_slice: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full-adjacency region merge table of a labeled block.

    For every voxel pair ``(p, p + off)`` under the canonical half of the
    neighborhood with distinct non-zero labels, one slot
    ``(min(la, lb), max(la, lb), max(h(p), h(p + off)))``.  Returns flat
    static-shape ``(a, b, saddle)`` columns of length
    ``len(offsets) * prod(shape)``; non-edge slots carry ``(0, 0, _BIG)``.
    The per-pair *minimum* saddle (the hierarchy edge weight) is a host
    reduction — see :func:`reduce_merge_table`.

    Unlike ``ops.watershed.flood_merge_table`` this records EVERY
    adjacency, not only tile-crossing ones — the complete in-block edge
    set a re-cut needs (two regions meeting inside a tile must merge at
    their saddle too).
    """
    lab = labels.astype(jnp.int32)
    h = heights.astype(jnp.float32)
    a_parts, b_parts, s_parts = [], [], []
    for off in _canonical_offsets(lab.ndim, connectivity, per_slice):
        nei_l = _shift(lab, off, jnp.int32(0))
        nei_h = _shift(h, off, _BIG)
        ok = (lab > 0) & (nei_l > 0) & (lab != nei_l)
        a_parts.append(
            jnp.where(ok, jnp.minimum(lab, nei_l), 0).reshape(-1)
        )
        b_parts.append(
            jnp.where(ok, jnp.maximum(lab, nei_l), 0).reshape(-1)
        )
        s_parts.append(
            jnp.where(ok, jnp.maximum(h, nei_h), _BIG).reshape(-1)
        )
    if not a_parts:
        z = jnp.zeros((0,), jnp.int32)
        return z, z, jnp.zeros((0,), jnp.float32)
    return (
        jnp.concatenate(a_parts),
        jnp.concatenate(b_parts),
        jnp.concatenate(s_parts),
    )


# ---------------------------------------------------------------------------
# host: reductions to per-pair minimum saddles


def reduce_merge_table(
    a: np.ndarray, b: np.ndarray, saddle: np.ndarray,
    normalize: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """Reduce raw ``(a, b, saddle)`` columns (device output, face slabs)
    to the deduplicated per-pair MINIMUM saddle.  Returns
    ``(pairs[k, 2] int64 with a < b, saddles[k] float32)`` sorted by
    ``(a, b)``; empty/padding slots (``a == 0`` or ``b == 0``) drop.

    ``normalize=False`` keeps the columns side-ordered instead of
    swapping each pair to (min, max) — required while the two columns
    live in DIFFERENT id namespaces (block-face pairs before their
    per-side offsets are applied; normalizing local ids first would
    attach the offsets to the wrong sides)."""
    a = np.asarray(a).reshape(-1).astype(np.int64)
    b = np.asarray(b).reshape(-1).astype(np.int64)
    s = np.asarray(saddle).reshape(-1).astype(np.float32)
    keep = (a > 0) & (b > 0)
    if not keep.any():
        return np.zeros((0, 2), np.int64), np.zeros((0,), np.float32)
    a, b, s = a[keep], b[keep], s[keep]
    if normalize:
        lo = np.minimum(a, b)
        hi = np.maximum(a, b)
    else:
        lo, hi = a, b
    order = np.lexsort((hi, lo))
    lo, hi, s = lo[order], hi[order], s[order]
    first = np.concatenate(
        [[True], (lo[1:] != lo[:-1]) | (hi[1:] != hi[:-1])]
    )
    starts = np.nonzero(first)[0]
    mins = np.minimum.reduceat(s, starts)
    return np.stack([lo[first], hi[first]], axis=1), mins.astype(np.float32)


def merge_face_pairs(
    lo_labels: np.ndarray,
    hi_labels: np.ndarray,
    lo_heights: np.ndarray,
    hi_heights: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Cross-block hierarchy edges of one 1-voxel block face: the label
    pair and ``max`` of the two touching height planes, reduced to the
    per-pair minimum saddle.  The host-side sibling of
    :func:`block_merge_table` for the stitching step (the
    ``parallel/sharded.py`` boundary-plane idiom at the block grain).

    The returned pairs stay SIDE-ORDERED (column 0 = lower-block ids,
    column 1 = upper-block ids, still block-local): the caller applies
    the two blocks' offsets per column before the global reduction
    normalizes — swapping to (min, max) here would mix the namespaces."""
    lo = np.asarray(lo_labels).reshape(-1).astype(np.int64)
    hi = np.asarray(hi_labels).reshape(-1).astype(np.int64)
    s = np.maximum(
        np.asarray(lo_heights, np.float32).reshape(-1),
        np.asarray(hi_heights, np.float32).reshape(-1),
    )
    both = (lo > 0) & (hi > 0)
    return reduce_merge_table(lo[both], hi[both], s[both], normalize=False)


def sort_by_saddle(
    pairs: np.ndarray, saddles: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Sort hierarchy edges ascending by saddle (ties by pair) — the
    artifact invariant that makes every threshold cut ONE searchsorted."""
    order = np.lexsort((pairs[:, 1], pairs[:, 0], saddles))
    return pairs[order], saddles[order]


# ---------------------------------------------------------------------------
# artifact (sorted-by-saddle global hierarchy)


def save_hierarchy(
    path: str,
    pairs: np.ndarray,
    saddles: np.ndarray,
    n_labels: int,
    shape,
    block_shape,
) -> None:
    """Persist the sorted global hierarchy (schema in ``obs/trace.py``
    beside the store/lease schemas).  ``pairs`` are GLOBAL label ids."""
    pairs, saddles = sort_by_saddle(
        np.asarray(pairs, np.int64).reshape(-1, 2),
        np.asarray(saddles, np.float32).reshape(-1),
    )
    from ..utils.store import atomic_write_bytes

    import io

    buf = io.BytesIO()
    np.savez(
        buf,
        schema=np.int64(HIER_SCHEMA_VERSION),
        a=pairs[:, 0],
        b=pairs[:, 1],
        saddle=saddles,
        n_labels=np.int64(n_labels),
        shape=np.asarray(shape, np.int64),
        block_shape=np.asarray(block_shape, np.int64),
    )
    atomic_write_bytes(path, buf.getvalue())


def load_hierarchy(path: str) -> dict:
    """Load a hierarchy artifact; loud on schema mismatch."""
    with np.load(path) as f:
        out = {k: f[k] for k in f.files}
    schema = int(out.get("schema", -1))
    if schema != HIER_SCHEMA_VERSION:
        raise ValueError(
            f"hierarchy artifact {path!r} has schema {schema}, expected "
            f"{HIER_SCHEMA_VERSION}"
        )
    if not (np.diff(out["saddle"]) >= 0).all():
        raise ValueError(
            f"hierarchy artifact {path!r} is not sorted by saddle"
        )
    return out


# ---------------------------------------------------------------------------
# re-cut: threshold -> one union-find pass -> relabel table


def _pad_pow2(arr: np.ndarray, fill) -> np.ndarray:
    n = arr.shape[0]
    size = 1
    while size < n:
        size *= 2
    if size == n:
        return arr
    return np.concatenate([arr, np.full(size - n, fill, arr.dtype)])


def cut_table(
    a: np.ndarray, b: np.ndarray, saddle: np.ndarray, threshold: float
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Resolve the hierarchy at ``threshold``: select every edge with
    ``saddle <= threshold`` (one searchsorted — the columns are the
    sorted artifact) and run ONE value-space union-find pass over the
    selected pairs.  Returns ``(vals, roots)`` (int32, sorted ``vals``)
    for :func:`recut_labels`, or None when no edge is selected (identity
    re-cut).  Edge columns pad to the next power of two with self-loop
    zeros so a sweep reuses O(log edges) compiled shapes."""
    k = int(np.searchsorted(saddle, np.float32(threshold), side="right"))
    if k == 0:
        return None
    a_sel = _pad_pow2(np.asarray(a[:k], np.int32), 0)
    b_sel = _pad_pow2(np.asarray(b[:k], np.int32), 0)
    vals, roots = merge_value_table(jnp.asarray(a_sel), jnp.asarray(b_sel))
    return np.asarray(vals), np.asarray(roots)


def cut_table_np(
    a: np.ndarray, b: np.ndarray, saddle: np.ndarray, threshold: float
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Host-side :func:`cut_table`: same ``(vals, roots)`` contract and
    min-member-id semantics, but int64 value-space union-find on the
    host — the re-cut path for hierarchies beyond 2^31 regions, where
    the device gather's int32 ids would overflow.  Memory scales with
    the SELECTED edges (the table is value-space: only ids touched by a
    merge appear), never with ``n_labels``.  Apply with
    :func:`apply_cut_np`."""
    k = int(np.searchsorted(saddle, np.float32(threshold), side="right"))
    if k == 0:
        return None
    a_sel = np.asarray(a[:k], np.int64)
    b_sel = np.asarray(b[:k], np.int64)
    vals = np.unique(np.concatenate([a_sel, b_sel]))
    uf = UnionFindNp(vals.size)
    # vals is sorted, so merging dense ids toward the smaller id IS
    # merging toward the smaller label value — the device semantics.
    uf.merge(np.searchsorted(vals, a_sel), np.searchsorted(vals, b_sel))
    roots = vals[uf.compress()]
    return vals, roots


@jax.jit
def recut_labels(
    labels: jnp.ndarray, vals: jnp.ndarray, roots: jnp.ndarray
) -> jnp.ndarray:
    """Re-segment a labels array at the cut encoded by ``(vals, roots)``:
    one gather through the relabel table.  Labels absent from the table
    (regions untouched by any selected edge — including background 0 when
    the padding self-loops put it in ``vals``) pass through unchanged or
    map to themselves, so the result is the merged partition with every
    class renamed to its minimum member id."""
    return apply_value_roots(labels.astype(jnp.int32), vals, roots)


CUT_SCHEMA_VERSION = 1


def save_cut_table(
    path: str, threshold: float, cut, n_labels: int
) -> None:
    """Persist one threshold's relabel table (the table-mode sweep
    product: a proofreading client applies it to whatever view it holds
    instead of waiting for a full volume rewrite).  ``cut`` is
    :func:`cut_table`'s result (None = identity)."""
    import io

    from ..utils.store import atomic_write_bytes

    vals, roots = (
        (np.zeros(0, np.int32), np.zeros(0, np.int32)) if cut is None
        else cut
    )
    buf = io.BytesIO()
    np.savez(
        buf,
        schema=np.int64(CUT_SCHEMA_VERSION),
        threshold=np.float64(threshold),
        # dtype preserved: device tables are int32, the host-relabel
        # fallback's are int64 (ids past 2^31 must not be truncated)
        vals=np.asarray(vals),
        roots=np.asarray(roots),
        n_labels=np.int64(n_labels),
    )
    atomic_write_bytes(path, buf.getvalue())


def load_cut_table(path: str) -> dict:
    with np.load(path) as f:
        out = {k: f[k] for k in f.files}
    if int(out.get("schema", -1)) != CUT_SCHEMA_VERSION:
        raise ValueError(f"cut-table artifact {path!r}: schema mismatch")
    return out


def apply_cut_np(
    labels: np.ndarray, vals: np.ndarray, roots: np.ndarray
) -> np.ndarray:
    """Host application of a persisted cut table (the client-side gather:
    ``apply_value_roots`` semantics in numpy)."""
    lab = np.asarray(labels).astype(np.int64)
    vals = np.asarray(vals, np.int64)
    roots = np.asarray(roots, np.int64)
    if vals.size == 0:
        return lab
    idx = np.clip(np.searchsorted(vals, lab), 0, vals.size - 1)
    hit = vals[idx] == lab
    return np.where(hit, roots[idx], lab)


# ---------------------------------------------------------------------------
# host oracle (tests / documentation of the semantics)


def resegment_np(
    labels: np.ndarray,
    heights: np.ndarray,
    threshold: float,
    connectivity: int = 1,
) -> np.ndarray:
    """Brute-force re-segmentation oracle: merge every pair of adjacent
    regions whose saddle (min over their shared boundary of
    ``max(h(p), h(q))``) is ≤ ``threshold``, entirely with host numpy —
    the independent parity reference for the hierarchy + re-cut path.
    Merged classes take their minimum member id (the device semantics)."""
    lab = np.asarray(labels).astype(np.int64)
    h = np.asarray(heights, np.float32)
    pairs_parts = []
    for off in _canonical_offsets(lab.ndim, connectivity, False):
        src = tuple(
            slice(None, -o) if o > 0 else slice(-o, None) for o in off
        )
        dst = tuple(
            slice(o, None) if o > 0 else slice(None, o or None) for o in off
        )
        la, lb = lab[src], lab[dst]
        ok = (la > 0) & (lb > 0) & (la != lb)
        saddle = np.maximum(h[src], h[dst])
        ok &= saddle <= np.float32(threshold)
        if ok.any():
            pairs_parts.append(
                np.stack([la[ok], lb[ok]], axis=1)
            )
    if not pairs_parts:
        return lab
    pairs = np.unique(np.concatenate(pairs_parts, axis=0), axis=0)
    uniq = np.unique(lab)
    uf = UnionFindNp(int(uniq.max()) + 1)
    uf.merge(pairs[:, 0], pairs[:, 1])
    roots = uf.compress()
    return roots[lab]
