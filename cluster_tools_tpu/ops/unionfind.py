"""Union-find for global label merging.

Replaces nifty.ufd.boost_ufd (reference thresholded_components/
merge_assignments.py:125-130, multicut/reduce_problem.py:161-163).

Two implementations:
  * ``union_find_np`` — host numpy, iterative with full path compression; used by
    single-shot merge tasks (these are 1-job reductions in the reference too).
  * ``merge_labels_device`` — pointer-jumping on device: given merge edges over a
    dense id space, converges parents in O(log n) gather sweeps under jit.  This
    is the building block for doing merges with ICI-resident data.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class UnionFindNp:
    """Array-based union-find with path compression (host)."""

    def __init__(self, n: int):
        self.parent = np.arange(n, dtype=np.int64)

    def find(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.int64)
        root = self.parent[x]
        # iterate until fixpoint (vectorized path walk)
        while True:
            nxt = self.parent[root]
            if (nxt == root).all():
                break
            root = nxt
        return root

    def merge(self, a: np.ndarray, b: np.ndarray) -> None:
        """Union pairs; roots are merged towards the smaller id."""
        a = np.asarray(a, dtype=np.int64).reshape(-1)
        b = np.asarray(b, dtype=np.int64).reshape(-1)
        # process iteratively: after each pass re-root and re-link
        while a.size:
            ra = self.find(a)
            rb = self.find(b)
            ne = ra != rb
            ra, rb = ra[ne], rb[ne]
            if ra.size == 0:
                break
            lo = np.minimum(ra, rb)
            hi = np.maximum(ra, rb)
            # link hi → lo; duplicate hi entries keep the smallest target
            order = np.lexsort((lo, hi))
            hi, lo = hi[order], lo[order]
            first = np.concatenate([[True], hi[1:] != hi[:-1]])
            self.parent[hi[first]] = lo[first]
            a, b = ra, rb  # re-check remaining conflicts next pass

    def compress(self) -> np.ndarray:
        """Full path compression; returns the root of every element."""
        while True:
            nxt = self.parent[self.parent]
            if (nxt == self.parent).all():
                break
            self.parent = nxt
        return self.parent


def _finalize_roots(
    roots: np.ndarray, consecutive: bool
) -> Tuple[np.ndarray, int]:
    roots[0] = 0
    if not consecutive:
        return roots, int(roots.max())
    uniq, inv = np.unique(roots, return_inverse=True)
    if uniq.size and uniq[0] == 0:
        assignment = inv.astype(np.int64)
        n_new = uniq.size - 1
    else:
        assignment = (inv + 1).astype(np.int64)
        n_new = uniq.size
    assignment[0] = 0
    return assignment, int(n_new)


def merge_assignments_np(
    n_labels: int, pairs: np.ndarray, consecutive: bool = True
) -> Tuple[np.ndarray, int]:
    """Merge equivalence ``pairs`` over ids [0, n_labels) and return a dense
    assignment array old_id → new_id (0 fixed to 0) plus the new max id."""
    uf = UnionFindNp(n_labels)
    if pairs.size:
        uf.merge(pairs[:, 0], pairs[:, 1])
    return _finalize_roots(uf.compress(), consecutive)


def merge_assignments_device(
    n_labels: int, pairs: np.ndarray, consecutive: bool = True
) -> Tuple[np.ndarray, int]:
    """Device analog of ``merge_assignments_np``: the id space lives on the
    mesh and equivalences resolve by pointer jumping (``merge_labels_device``)
    instead of a host union-find — the ICI replacement for the reference's
    1-job boost_ufd merge (merge_assignments.py:125-130).  Falls back to the
    host path when the id space exceeds int32."""
    if n_labels >= np.iinfo(np.int32).max:
        return merge_assignments_np(n_labels, pairs, consecutive)
    parent = jnp.arange(n_labels, dtype=jnp.int32)
    if pairs.size:
        edges = jnp.asarray(np.ascontiguousarray(pairs, dtype=np.int32))
    else:
        edges = jnp.zeros((1, 2), jnp.int32)
    roots = np.asarray(merge_labels_device(parent, edges)).astype(np.int64)
    return _finalize_roots(roots, consecutive)


def merge_value_table(
    a_vals: jnp.ndarray, b_vals: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Union-find over the *values* appearing in equivalence pairs
    ``(a_vals[i], b_vals[i])`` — the compact form of ``merge_labels_device``
    for sparse id spaces (ctt-cc tile-face merging, parallel/sharded.py
    shard-face merging): the parent table covers only the values that occur
    in the pairs (O(#pairs) entries), not the dense id range they are drawn
    from, so resolving tile-boundary equivalences of a volume costs
    O(boundary), not O(volume).

    Padding slots must carry the same value on both sides (self-loops merge
    nothing).  Returns ``(vals, root_vals)``: ``vals`` is the sorted multiset
    of all pair values; ``root_vals[i]`` is the minimum value of the
    equivalence class of ``vals[i]``.  Apply with :func:`apply_value_roots`.

    Min semantics ride the sort: compacted ids (positions in ``vals``) are
    order-isomorphic to the values, so ``merge_labels_device``'s link-to-min
    over ids resolves each class to its minimal *value*.  Duplicate values
    share their leftmost slot (``searchsorted`` side='left'); the orphaned
    right slots stay self-rooted and are never referenced.
    """
    vals = jnp.sort(jnp.concatenate([a_vals, b_vals]))
    n = vals.shape[0]
    ca = jnp.searchsorted(vals, a_vals).astype(jnp.int32)
    cb = jnp.searchsorted(vals, b_vals).astype(jnp.int32)
    edges = jnp.stack([ca, cb], axis=1)
    roots = merge_labels_device(jnp.arange(n, dtype=jnp.int32), edges)
    return vals, vals[roots]


def apply_value_roots(
    x: jnp.ndarray, vals: jnp.ndarray, root_vals: jnp.ndarray
) -> jnp.ndarray:
    """Map every element of ``x`` through a resolved value table from
    :func:`merge_value_table`; values absent from ``vals`` pass through
    unchanged (components never touching a boundary keep their label)."""
    n = vals.shape[0]
    idx = jnp.clip(jnp.searchsorted(vals, x), 0, n - 1).astype(jnp.int32)
    hit = vals[idx] == x
    return jnp.where(hit, root_vals[idx], x)


@partial(jax.jit)
def merge_labels_device(parent: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """Device merge: ``parent`` is a dense [n] parent array, ``edges`` [m,2]
    merge requests (may contain padding rows with a == b).

    Iterates (link-to-min over edges, then pointer jumping) until stable.
    Returns the fully compressed root array.
    """
    n = parent.shape[0]

    def cond(state):
        parent, changed = state
        return changed

    def body(state):
        parent, _ = state
        ra = parent[edges[:, 0]]
        rb = parent[edges[:, 1]]
        lo = jnp.minimum(ra, rb)
        hi = jnp.maximum(ra, rb)
        # link: parent[hi] <- min(parent[hi], lo); scatter-min resolves dups
        new = parent.at[hi].min(lo)
        # pointer jumping (two hops per sweep)
        new = new[new]
        new = new[new]
        return (new, jnp.any(new != parent))

    parent, _ = lax.while_loop(cond, body, (parent, jnp.bool_(True)))
    return parent
