"""Paintera label multisets: per-voxel label histograms for multiscale labels.

Replaces elf.label_multiset (reference label_multisets/create_multiset.py:25,
downscale_multiset.py:29).  A multiset assigns each voxel a list of
(label id, count) pairs; at scale 0 every voxel has one entry with count 1,
and each downscaling step pools the children's entries, so a coarse voxel
remembers every label beneath it — what paintera needs for consistent
painting across scales.

Serialization (big-endian, after the imglib2/paintera chunk layout):
  int32                 n_voxels
  int64[n_voxels]       argmax label per voxel (the majority label)
  int32[n_voxels]       byte offset of each voxel's entry list within the
                        entry-data region (shared lists deduplicated)
  entry data            per list: int32 N, then N x (int64 id, int32 count)

Everything here is vectorized numpy (byte scatters, repeat/cumsum gathers) —
the codec runs once per block per scale on the conversion hot path, so
per-voxel Python loops are not acceptable.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def _gather_indices(offsets: np.ndarray, sizes: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """For per-voxel contiguous slices [offset, offset+size): the flat entry
    indices of all voxels concatenated, plus each entry's voxel index."""
    total = int(sizes.sum())
    voxel_of_entry = np.repeat(np.arange(sizes.size), sizes)
    starts = np.repeat(offsets, sizes)
    within = np.arange(total) - np.repeat(
        np.cumsum(sizes) - sizes, sizes
    )
    return starts + within, voxel_of_entry


class LabelMultiset:
    """shape: spatial shape; per flat voxel v, entries are
    ids[entry_offsets[v] : entry_offsets[v] + entry_sizes[v]] / counts[...]."""

    def __init__(self, shape, entry_offsets, entry_sizes, ids, counts):
        self.shape = tuple(shape)
        self.n_voxels = int(np.prod(self.shape))
        self.entry_offsets = np.asarray(entry_offsets, dtype=np.int64)
        self.entry_sizes = np.asarray(entry_sizes, dtype=np.int64)
        self.ids = np.asarray(ids, dtype=np.uint64)
        self.counts = np.asarray(counts, dtype=np.int32)

    @property
    def argmax(self) -> np.ndarray:
        entry_idx, voxel_of_entry = _gather_indices(
            self.entry_offsets, self.entry_sizes
        )
        if entry_idx.size == 0:
            return np.zeros(self.n_voxels, dtype=np.uint64)
        counts = self.counts[entry_idx]
        ids = self.ids[entry_idx]
        # last entry per voxel after sorting by (voxel, count) is the argmax
        order = np.lexsort((counts, voxel_of_entry))
        voxel_s = voxel_of_entry[order]
        last = np.concatenate([voxel_s[1:] != voxel_s[:-1], [True]])
        out = np.zeros(self.n_voxels, dtype=np.uint64)
        out[voxel_s[last]] = ids[order][last]
        return out

    def voxel_entries(self, v: int):
        o, s = self.entry_offsets[v], self.entry_sizes[v]
        return self.ids[o : o + s], self.counts[o : o + s]


def create_multiset_from_labels(labels: np.ndarray) -> LabelMultiset:
    """Scale-0 multiset: one (label, 1) entry per voxel."""
    flat = labels.reshape(-1).astype(np.uint64)
    n = flat.size
    return LabelMultiset(
        labels.shape,
        entry_offsets=np.arange(n, dtype=np.int64),
        entry_sizes=np.ones(n, dtype=np.int64),
        ids=flat,
        counts=np.ones(n, dtype=np.int32),
    )


def downsample_multiset(
    multiset: LabelMultiset,
    scale_factor: Sequence[int],
    restrict_set: int = -1,
) -> LabelMultiset:
    """Pool scale_factor-sized voxel windows, summing entry counts;
    ``restrict_set`` > 0 keeps only the top-count entries per coarse voxel
    (paintera's maxNumEntries, reference downscale_multiset.py)."""
    sf = tuple(int(s) for s in scale_factor)
    shape = multiset.shape
    new_shape = tuple(-(-s // f) for s, f in zip(shape, sf))

    # coarse voxel of every fine voxel
    fine_idx = np.indices(shape).reshape(3, -1)
    coarse = [fi // f for fi, f in zip(fine_idx, sf)]
    coarse_of_voxel = np.ravel_multi_index(coarse, new_shape)

    # expand all entries, tag with coarse voxel, then aggregate (coarse, id)
    entry_idx, voxel_of_entry = _gather_indices(
        multiset.entry_offsets, multiset.entry_sizes
    )
    e_coarse = coarse_of_voxel[voxel_of_entry]
    e_ids = multiset.ids[entry_idx]
    e_counts = multiset.counts[entry_idx].astype(np.int64)

    order = np.lexsort((e_ids, e_coarse))
    e_coarse, e_ids, e_counts = (
        e_coarse[order], e_ids[order], e_counts[order]
    )
    newgroup = np.concatenate(
        [[True], (e_coarse[1:] != e_coarse[:-1]) | (e_ids[1:] != e_ids[:-1])]
    )
    group = np.cumsum(newgroup) - 1
    g_coarse = e_coarse[newgroup]
    g_ids = e_ids[newgroup]
    g_counts = np.zeros(group[-1] + 1, dtype=np.int64)
    np.add.at(g_counts, group, e_counts)

    if restrict_set > 0:
        # keep top-restrict_set counts per coarse voxel: sort by
        # (coarse, -count), rank within group, filter
        order2 = np.lexsort((-g_counts, g_coarse))
        gc, gi, gn = g_coarse[order2], g_ids[order2], g_counts[order2]
        newv = np.concatenate([[True], gc[1:] != gc[:-1]])
        group_start = np.maximum.accumulate(np.where(newv, np.arange(gc.size), 0))
        rank = np.arange(gc.size) - group_start
        keep = rank < restrict_set
        gc, gi, gn = gc[keep], gi[keep], gn[keep]
        # restore (coarse, id) order
        order3 = np.lexsort((gi, gc))
        g_coarse, g_ids, g_counts = gc[order3], gi[order3], gn[order3]

    sizes = np.bincount(g_coarse, minlength=int(np.prod(new_shape))).astype(
        np.int64
    )
    entry_offsets = np.concatenate([[0], np.cumsum(sizes)[:-1]])
    return LabelMultiset(
        new_shape,
        entry_offsets=entry_offsets,
        entry_sizes=sizes,
        ids=g_ids,
        counts=g_counts.astype(np.int32),
    )


def merge_multisets(multisets, positions, shape) -> LabelMultiset:
    """Assemble a larger multiset from sub-multisets at given corner
    ``positions`` (fills gaps with background (0, 1) entries)."""
    shape = tuple(shape)
    n = int(np.prod(shape))
    entry_offsets = np.full(n, -1, dtype=np.int64)
    entry_sizes = np.zeros(n, dtype=np.int64)
    ids_parts, counts_parts = [], []
    cursor = 0
    region_idx = np.arange(n).reshape(shape)
    for sub, pos in zip(multisets, positions):
        sl = tuple(
            slice(p, p + s) for p, s in zip(pos, sub.shape)
        )
        targets = region_idx[sl].reshape(-1)
        entry_offsets[targets] = cursor + sub.entry_offsets
        entry_sizes[targets] = sub.entry_sizes
        ids_parts.append(sub.ids)
        counts_parts.append(sub.counts)
        cursor += sub.ids.size
    missing = entry_offsets < 0
    if missing.any():
        m = int(missing.sum())
        entry_offsets[missing] = cursor + np.arange(m)
        entry_sizes[missing] = 1
        ids_parts.append(np.zeros(m, dtype=np.uint64))
        counts_parts.append(np.ones(m, dtype=np.int32))
    return LabelMultiset(
        shape,
        entry_offsets,
        entry_sizes,
        np.concatenate(ids_parts) if ids_parts else np.zeros(0, np.uint64),
        np.concatenate(counts_parts) if counts_parts else np.zeros(0, np.int32),
    )


def _scatter_bytes(buf: np.ndarray, positions: np.ndarray, payload: np.ndarray):
    """buf[positions[i] : positions[i]+w] = payload[i] for fixed width w."""
    w = payload.shape[1]
    idx = positions[:, None] + np.arange(w)[None, :]
    buf[idx.reshape(-1)] = payload.reshape(-1)


def serialize_multiset(multiset: LabelMultiset) -> np.ndarray:
    """→ uint8 payload (the varlen chunk body); fully vectorized."""
    n = multiset.n_voxels
    offsets = multiset.entry_offsets
    sizes = multiset.entry_sizes

    # deduplicate shared lists by their (offset, size) slice identity
    keys = np.stack([offsets, sizes], axis=1)
    uniq_keys, voxel_list = np.unique(keys, axis=0, return_inverse=True)
    u_off, u_size = uniq_keys[:, 0], uniq_keys[:, 1]
    list_bytes = 4 + 12 * u_size
    list_pos = np.concatenate([[0], np.cumsum(list_bytes)[:-1]])
    region_size = int(list_bytes.sum())

    region = np.zeros(region_size, dtype=np.uint8)
    # headers
    _scatter_bytes(
        region, list_pos,
        np.ascontiguousarray(u_size.astype(">i4")).view(np.uint8).reshape(-1, 4),
    )
    # entries
    entry_idx, list_of_entry = _gather_indices(u_off, u_size)
    within = np.arange(entry_idx.size) - np.repeat(
        np.cumsum(u_size) - u_size, u_size
    )
    entry_pos = np.repeat(list_pos + 4, u_size) + 12 * within
    rec = np.zeros(entry_idx.size, dtype=[("id", ">i8"), ("count", ">i4")])
    rec["id"] = multiset.ids[entry_idx].astype(np.int64)
    rec["count"] = multiset.counts[entry_idx]
    _scatter_bytes(region, entry_pos, rec.view(np.uint8).reshape(-1, 12))

    header = np.asarray([n], dtype=">i4").view(np.uint8)
    argmax = (
        np.ascontiguousarray(multiset.argmax.astype(">i8")).view(np.uint8)
    )
    voxel_offsets = (
        np.ascontiguousarray(list_pos[voxel_list].astype(">i4")).view(np.uint8)
    )
    return np.concatenate([header, argmax, voxel_offsets, region])


def deserialize_multiset(payload: np.ndarray, shape: Sequence[int]) -> LabelMultiset:
    buf = np.ascontiguousarray(np.asarray(payload, dtype=np.uint8))
    n = int(buf[:4].view(">i4")[0])
    if int(np.prod(shape)) != n:
        raise ValueError(
            f"multiset has {n} voxels, shape {shape} expects "
            f"{int(np.prod(shape))}"
        )
    pos = 4 + 8 * n  # skip argmax (recomputable)
    voxel_offsets = buf[pos : pos + 4 * n].view(">i4").astype(np.int64)
    pos += 4 * n
    region = buf[pos:]

    uniq_pos, voxel_list = np.unique(voxel_offsets, return_inverse=True)
    # list sizes from the int32 headers
    hdr_idx = uniq_pos[:, None] + np.arange(4)[None, :]
    u_size = (
        np.ascontiguousarray(region[hdr_idx.reshape(-1)])
        .view(">i4")
        .astype(np.int64)
    )
    # entry records
    entry_idx, list_of_entry = _gather_indices(
        np.zeros(u_size.size, dtype=np.int64), u_size
    )
    within = np.arange(entry_idx.size) - np.repeat(
        np.cumsum(u_size) - u_size, u_size
    )
    entry_pos = np.repeat(uniq_pos + 4, u_size) + 12 * within
    rec_idx = entry_pos[:, None] + np.arange(12)[None, :]
    rec = (
        np.ascontiguousarray(region[rec_idx.reshape(-1)])
        .view([("id", ">i8"), ("count", ">i4")])
    )
    ids = rec["id"].astype(np.int64).astype(np.uint64)
    counts = rec["count"].astype(np.int32)

    u_offsets = np.concatenate([[0], np.cumsum(u_size)[:-1]])
    return LabelMultiset(
        shape,
        entry_offsets=u_offsets[voxel_list],
        entry_sizes=u_size[voxel_list],
        ids=ids,
        counts=counts,
    )
