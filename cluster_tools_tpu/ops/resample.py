"""Resampling kernels: scale-pyramid down/up-sampling on device.

Replaces the reference's vigra.sampling.resize / skimage block_reduce samplers
(reference downscaling/downscaling.py:217-259, _ds_vol/_ds_vigra/_ds_skimage):

  * ``nearest``      — order-0 strided subsample (labels / non-interpolatable
                       dtypes, the reference's vigra order=0 path)
  * ``mean``         — box mean pooling via ``lax.reduce_window`` (skimage
                       block_reduce equivalent)
  * ``interpolate``  — ``jax.image.resize`` linear interpolation (vigra
                       spline path; order-1 on device)

All three map onto one fused XLA program per block batch; anisotropic factors
(e.g. ``[1, 2, 2]``) are per-axis window/stride settings.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

ScaleFactor = Union[int, Sequence[int]]

#: methods usable for dtypes that cannot be interpolated (integer labels)
ORDER0_METHODS = ("nearest",)
#: reference library names accepted as aliases
METHOD_ALIASES = {"vigra": "interpolate", "skimage": "mean"}


def per_axis_factor(scale_factor: ScaleFactor, ndim: int) -> Tuple[int, ...]:
    if isinstance(scale_factor, (int, np.integer)):
        return (int(scale_factor),) * ndim
    sf = tuple(int(s) for s in scale_factor)
    if len(sf) != ndim:
        raise ValueError(f"scale factor {sf} does not match rank {ndim}")
    return sf


def downscale_shape(shape: Sequence[int], scale_factor: ScaleFactor) -> Tuple[int, ...]:
    """ceil(shape / factor) per axis (elf.util.downscale_shape semantics)."""
    sf = per_axis_factor(scale_factor, len(shape))
    return tuple(-(-s // f) for s, f in zip(shape, sf))


@partial(jax.jit, static_argnames=("sf",))
def _mean_pool(x: jnp.ndarray, sf: Tuple[int, ...]) -> jnp.ndarray:
    pad = tuple((0, (-s) % f) for s, f in zip(x.shape, sf))
    if any(p[1] for p in pad):
        x = jnp.pad(x, pad, mode="edge")
    summed = lax.reduce_window(
        x.astype(jnp.float32), 0.0, lax.add,
        window_dimensions=sf, window_strides=sf, padding="VALID",
    )
    return summed / float(np.prod(sf))


@partial(jax.jit, static_argnames=("sf", "out_shape"))
def _interp_resize(x: jnp.ndarray, sf, out_shape) -> jnp.ndarray:
    return jax.image.resize(x.astype(jnp.float32), out_shape, method="linear")


def downscale(
    x: jnp.ndarray, scale_factor: ScaleFactor, method: str = "interpolate"
) -> jnp.ndarray:
    """Downsample to ``downscale_shape(x.shape, scale_factor)``."""
    method = METHOD_ALIASES.get(method, method)
    sf = per_axis_factor(scale_factor, x.ndim)
    out_shape = downscale_shape(x.shape, sf)
    if method == "nearest":
        return x[tuple(slice(None, None, f) for f in sf)]
    if method == "mean":
        return _mean_pool(x, sf)
    if method == "interpolate":
        return _interp_resize(x, sf, out_shape)
    raise ValueError(f"unknown downscaling method {method!r}")


@partial(jax.jit, static_argnames=("out_shape", "method"))
def _upscale(x: jnp.ndarray, out_shape, method: str) -> jnp.ndarray:
    return jax.image.resize(
        x.astype(jnp.float32) if method != "nearest" else x,
        out_shape,
        method="nearest" if method == "nearest" else "linear",
    )


def upscale(
    x: jnp.ndarray, out_shape: Sequence[int], method: str = "interpolate"
) -> jnp.ndarray:
    """Upsample to ``out_shape`` (reference upscaling.py sampler wrap)."""
    method = METHOD_ALIASES.get(method, method)
    if method not in ("nearest", "mean", "interpolate"):
        raise ValueError(f"unknown upscaling method {method!r}")
    if method == "mean":
        method = "interpolate"  # mean pooling has no upscale analog
    return _upscale(x, tuple(int(s) for s in out_shape), method)


def cast_resampled(out: jnp.ndarray, dtype) -> np.ndarray:
    """Round + clip float resampling results back to integer dtypes
    (reference downscaling.py:217-224)."""
    out = np.asarray(out)
    if np.dtype(dtype) in (np.dtype("uint8"), np.dtype("uint16")):
        info = np.iinfo(np.dtype(dtype))
        out = np.round(np.clip(out, 0, info.max))
    return out.astype(dtype)
