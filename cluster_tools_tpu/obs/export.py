"""ctt-obs export: merge per-process shards, summarize, Chrome trace, diff.

A run directory (``<CTT_TRACE_DIR>/<run_id>/``) holds one
``spans.p<pid>.t<tid>.jsonl`` shard per writer thread per process plus
one ``metrics.p<pid>.json`` snapshot per process.  This module is the
read side:

  * `load_run` — merge every shard into one event list.  Durations stay
    on each process's monotonic clock (exact); *placement* on a shared
    wall-clock axis uses the per-shard (wall, mono) anchor pair from the
    shard header — good to cross-process clock skew, which is fine for
    eyeballing concurrency in Perfetto and irrelevant for the summaries.
  * `summarize` — per-task breakdown into distinct buckets: ``host_io``
    (chunk reads/writes), ``device`` (batched dispatch), ``collective``
    (mesh programs), ``host`` (other host work).  Bucket sums use *self
    time* (span duration minus its children's durations), so a device
    batch that encloses a host-IO read is never double-counted, and
    ``host_io + device + host > dispatch wall`` is exactly the pipeline
    overlap (host IO hidden behind device execution).
  * `to_chrome_trace` — Chrome ``trace_event`` JSON (load it in Perfetto
    or ``chrome://tracing``).
  * `diff` — compare two runs task by task and flag wall-clock
    regressions beyond a threshold: the machine half of the BENCH
    trajectory (two bench runs with tracing on are machine-comparable).

Malformed shards raise :class:`TraceFormatError` — the CLI maps it to a
nonzero exit so CI catches truncated/corrupt traces instead of
summarizing garbage.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional

from . import hist as hist_mod
from .metrics import METRICS_FILE_PREFIX

__all__ = [
    "TraceFormatError", "resolve_run_dir", "load_run",
    "summarize", "format_summary", "to_chrome_trace",
    "diff", "format_diff",
]

SHARD_GLOB = "spans.p*.jsonl"

# span kinds → summary buckets; structural/bridge kinds are excluded from
# the bucket sums (see summarize)
_BUCKETS = {"host_io": "host_io_s", "device": "device_s",
            "collective": "collective_s"}
_EXCLUDED_KINDS = {"task", "dispatch", "run", "timing"}


class TraceFormatError(ValueError):
    """A shard or metrics file is not valid ctt-obs output."""


def resolve_run_dir(path: str) -> str:
    """Accept either a run directory or a trace dir containing runs.
    A trace dir with exactly one run resolves to it; several runs is an
    error naming them (the caller must pick)."""
    if glob.glob(os.path.join(path, SHARD_GLOB)):
        return path
    if not os.path.isdir(path):
        raise TraceFormatError(f"no such trace directory: {path}")
    runs = sorted(
        d for d in os.listdir(path)
        if glob.glob(os.path.join(path, d, SHARD_GLOB))
    )
    if len(runs) == 1:
        return os.path.join(path, runs[0])
    if not runs:
        raise TraceFormatError(f"no trace shards under {path}")
    raise TraceFormatError(
        f"{len(runs)} runs under {path} — pass one of: "
        + ", ".join(runs[:5])
    )


_SPAN_KEYS = ("id", "name", "kind", "t0", "t1", "pid", "tid")


def _load_shard(path: str, spans: List[dict], headers: List[dict]) -> None:
    anchor = None  # (wall, mono) of this shard
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise TraceFormatError(
                    f"{path}:{lineno}: not JSON ({e.msg})"
                ) from None
            if not isinstance(rec, dict):
                raise TraceFormatError(f"{path}:{lineno}: not an object")
            rtype = rec.get("type")
            if rtype == "header":
                anchor = (float(rec["wall"]), float(rec["mono"]))
                headers.append(rec)
            elif rtype == "span":
                if anchor is None:
                    raise TraceFormatError(
                        f"{path}:{lineno}: span before shard header"
                    )
                missing = [k for k in _SPAN_KEYS if k not in rec]
                if missing:
                    raise TraceFormatError(
                        f"{path}:{lineno}: span missing {missing}"
                    )
                wall0, mono0 = anchor
                rec = dict(rec)
                rec["wall_t0"] = wall0 + (float(rec["t0"]) - mono0)
                rec["wall_t1"] = wall0 + (float(rec["t1"]) - mono0)
                spans.append(rec)
            else:
                raise TraceFormatError(
                    f"{path}:{lineno}: unknown record type {rtype!r}"
                )


def _load_metrics(run_dir: str) -> Dict[str, Any]:
    counters: Dict[str, float] = {}
    gauges: Dict[str, Any] = {}
    for path in sorted(glob.glob(
        os.path.join(run_dir, f"{METRICS_FILE_PREFIX}*.json")
    )):
        try:
            with open(path) as f:
                snap = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            raise TraceFormatError(f"{path}: bad metrics file ({e})") from None
        for k, v in snap.get("counters", {}).items():
            counters[k] = counters.get(k, 0.0) + float(v)
        gauges.update(snap.get("gauges", {}))
    return {"counters": counters, "gauges": gauges}


def load_run(path: str) -> Dict[str, Any]:
    """Merge every shard of a run.  Returns ``{"run_id", "dir", "spans",
    "headers", "counters", "gauges"}`` with spans carrying both monotonic
    (``t0``/``t1``, duration-exact) and wall (``wall_t0``/``wall_t1``,
    placement) endpoints."""
    run_dir = resolve_run_dir(path)
    spans: List[dict] = []
    headers: List[dict] = []
    for shard in sorted(glob.glob(os.path.join(run_dir, SHARD_GLOB))):
        _load_shard(shard, spans, headers)
    if not headers:
        raise TraceFormatError(f"no shard headers under {run_dir}")
    run_ids = sorted({h["run"] for h in headers})
    if len(run_ids) > 1:
        raise TraceFormatError(
            f"shards from different runs in {run_dir}: {run_ids}"
        )
    metrics = _load_metrics(run_dir)
    spans.sort(key=lambda s: s["wall_t0"])
    return {
        "run_id": run_ids[0],
        "dir": run_dir,
        "spans": spans,
        "headers": headers,
        "counters": metrics["counters"],
        "gauges": metrics["gauges"],
        # ctt-slo: exact cross-process merge of hist.p*.json (the fixed
        # bucket edges make it bucket-wise addition)
        "hists": hist_mod.load_run_hists(run_dir),
    }


# ---------------------------------------------------------------------------
# summarize


def _task_of(span: dict, by_id: Dict[int, dict]) -> Optional[str]:
    """Nearest explicit ``task=`` attribute or enclosing task span."""
    seen = 0
    node: Optional[dict] = span
    while node is not None and seen < 64:  # cycle guard
        attrs = node.get("attrs") or {}
        if "task" in attrs:
            return str(attrs["task"])
        if node.get("kind") == "task":
            return str(node["name"])
        node = by_id.get(node.get("parent"))
        seen += 1
    return None


def _new_row() -> Dict[str, float]:
    return {
        "wall_s": 0.0, "host_io_s": 0.0, "device_s": 0.0,
        "collective_s": 0.0, "host_s": 0.0, "dispatch_wall_s": 0.0,
        "overlap_hidden_s": 0.0, "n_spans": 0,
    }


def summarize(run: Dict[str, Any]) -> Dict[str, Any]:
    spans = run["spans"]
    by_id = {s["id"]: s for s in spans}
    child_time: Dict[int, float] = {}
    for s in spans:
        parent = s.get("parent")
        if parent is not None and s.get("kind") != "timing":
            child_time[parent] = (
                child_time.get(parent, 0.0) + (s["t1"] - s["t0"])
            )

    tasks: Dict[str, Dict[str, float]] = {}
    n_task_spans = 0
    for s in spans:
        name = _task_of(s, by_id) or "(no task)"
        row = tasks.setdefault(name, _new_row())
        dur = s["t1"] - s["t0"]
        self_t = max(0.0, dur - child_time.get(s["id"], 0.0))
        kind = s["kind"]
        row["n_spans"] += 1
        if kind == "task":
            n_task_spans += 1
            row["wall_s"] += dur
        elif kind == "dispatch":
            row["dispatch_wall_s"] += dur
        elif kind in _EXCLUDED_KINDS:
            pass
        else:
            row[_BUCKETS.get(kind, "host_s")] += self_t
    for row in tasks.values():
        if row["dispatch_wall_s"] > 0.0:
            busy = row["host_io_s"] + row["device_s"] + row["host_s"]
            row["overlap_hidden_s"] = max(0.0, busy - row["dispatch_wall_s"])
    return {
        "run_id": run["run_id"],
        "n_task_spans": n_task_spans,
        "n_processes": len({h["pid"] for h in run["headers"]}),
        "tasks": tasks,
        "counters": run["counters"],
        "gauges": run["gauges"],
        # ctt-slo: the key appears only when the run recorded histograms,
        # so the machine-readable golden stays unchanged without them
        **({"hists": run["hists"]}
           if (run.get("hists") or {}).get("hists") else {}),
    }


def format_summary(summary: Dict[str, Any]) -> str:
    cols = ["wall_s", "host_io_s", "device_s", "collective_s", "host_s",
            "overlap_hidden_s", "n_spans"]
    names = sorted(
        summary["tasks"],
        key=lambda n: -summary["tasks"][n]["wall_s"],
    )
    width = max([len(n) for n in names] + [4])
    cw = [max(9, len(c)) for c in cols]
    lines = [
        f"run {summary['run_id']}  "
        f"({summary['n_task_spans']} task spans, "
        f"{summary['n_processes']} processes)",
        "  ".join(["task".ljust(width)]
                  + [c.rjust(w) for c, w in zip(cols, cw)]),
    ]
    for n in names:
        row = summary["tasks"][n]
        cells = [
            (f"{row[c]:.3f}" if c != "n_spans" else f"{int(row[c])}").rjust(w)
            for c, w in zip(cols, cw)
        ]
        lines.append("  ".join([n.ljust(width)] + cells))
    counters = summary["counters"]
    if counters:
        lines.append("counters:")
        for k in sorted(counters):
            v = counters[k]
            lines.append(f"  {k} = {v:.0f}" if float(v).is_integer()
                         else f"  {k} = {v:.3f}")
    # ctt-slo: only when the run actually carries histograms, so existing
    # summary output stays byte-identical for runs without them.
    hists = (summary.get("hists") or {}).get("hists") or []
    if hists:
        lines.append("latency (s):")
        for s in hists:
            buckets = list(s["buckets"])
            p50 = hist_mod.quantile(buckets, 0.50)
            p99 = hist_mod.quantile(buckets, 0.99)
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(s.get("labels", {}).items()))
            series = s["name"] + (f"{{{lbl}}}" if lbl else "")
            lines.append(
                f"  {series} p50={p50:.6f} p99={p99:.6f} n={int(s['count'])}"
            )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Chrome trace_event export (Perfetto / chrome://tracing)


def to_chrome_trace(run: Dict[str, Any]) -> Dict[str, Any]:
    events: List[dict] = []
    for h in run["headers"]:
        events.append({
            "ph": "M", "name": "process_name", "pid": h["pid"], "tid": 0,
            "args": {"name": f"pid {h['pid']} ({h.get('host', '?')})"},
        })
    for s in run["spans"]:
        args = dict(s.get("attrs") or {})
        args["span_id"] = s["id"]
        if s.get("parent") is not None:
            args["parent_id"] = s["parent"]
        events.append({
            "ph": "X",
            "name": s["name"],
            "cat": s["kind"],
            "ts": s["wall_t0"] * 1e6,
            "dur": (s["t1"] - s["t0"]) * 1e6,
            "pid": s["pid"],
            "tid": s["tid"],
            "args": args,
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"run_id": run["run_id"], "tool": "ctt-obs"},
    }


# ---------------------------------------------------------------------------
# run diff


def diff(
    run_a: Dict[str, Any],
    run_b: Dict[str, Any],
    threshold: float = 0.2,
    min_seconds: float = 0.01,
) -> Dict[str, Any]:
    """Per-task wall-clock comparison of two runs (a = baseline, b =
    candidate).  A task regresses when its wall grows by more than
    ``threshold`` (fractional) AND by more than ``min_seconds`` (absolute
    floor: microsecond jitter on trivial tasks is not a regression)."""
    sa, sb = summarize(run_a), summarize(run_b)
    rows: List[dict] = []
    names = sorted(set(sa["tasks"]) | set(sb["tasks"]))
    for name in names:
        a = sa["tasks"].get(name)
        b = sb["tasks"].get(name)
        if a is None or b is None:
            rows.append({
                "task": name,
                "a_wall_s": a["wall_s"] if a else None,
                "b_wall_s": b["wall_s"] if b else None,
                "ratio": None,
                "regressed": False,
                "note": "only in baseline" if b is None else "only in candidate",
            })
            continue
        aw, bw = a["wall_s"], b["wall_s"]
        ratio = (bw / aw) if aw > 0 else None
        regressed = (
            bw > aw * (1.0 + threshold) and (bw - aw) > min_seconds
        )
        rows.append({
            "task": name, "a_wall_s": aw, "b_wall_s": bw,
            "ratio": ratio, "regressed": regressed, "note": "",
        })
    return {
        "a": sa["run_id"], "b": sb["run_id"],
        "threshold": threshold, "rows": rows,
        "n_regressed": sum(1 for r in rows if r["regressed"]),
    }


def format_diff(result: Dict[str, Any]) -> str:
    width = max([len(r["task"]) for r in result["rows"]] + [4])
    lines = [
        f"diff {result['a']} -> {result['b']} "
        f"(threshold {result['threshold']:.0%})",
        "  ".join(["task".ljust(width), "base_s".rjust(9),
                   "cand_s".rjust(9), "ratio".rjust(7), "flag"]),
    ]
    for r in result["rows"]:
        a = "-" if r["a_wall_s"] is None else f"{r['a_wall_s']:.3f}"
        b = "-" if r["b_wall_s"] is None else f"{r['b_wall_s']:.3f}"
        ratio = "-" if r["ratio"] is None else f"{r['ratio']:.2f}x"
        flag = "REGRESSED" if r["regressed"] else r["note"]
        lines.append("  ".join([
            r["task"].ljust(width), a.rjust(9), b.rjust(9),
            ratio.rjust(7), flag,
        ]).rstrip())
    lines.append(
        f"{result['n_regressed']} task(s) regressed beyond the threshold"
    )
    return "\n".join(lines)
