"""ctt-obs span recorder: low-overhead, process-safe structured tracing.

Where a workflow's wall-clock goes was previously invisible: the only
telemetry was per-dispatch ``time.time()`` deltas buried in status JSON
(`Task.record_timing`).  This module records *spans* — named, nested
intervals on the monotonic clock — into per-(pid, thread) JSONL shards
that `obs.export` merges across processes into one run:

  run (``build``) → task → dispatch → block-batch / block → host-IO,
  plus collective spans from ``parallel/sharded*.py``.

Design constraints (the reasons it looks the way it does):

  * **No-op fast path.**  Tracing is off unless ``CTT_TRACE_DIR`` is set
    (or `enable()` is called): ``span()`` then returns a shared singleton
    context manager — no allocation, no clock read, no lock.  Hot paths
    (per-chunk store IO) use `obs.metrics` counters instead of spans.
  * **One writer per shard.**  Every (pid, thread) pair appends to its own
    ``spans.p<pid>.t<tid>.jsonl`` — the same pid+thread-uniqueness
    convention as the store's atomic tmp files (utils/store.py
    ``_atomic_write_bytes``) — so concurrent block threads never interleave
    partial lines and no cross-process lock exists.
  * **Monotonic durations, wall-clock anchors.**  Span endpoints are
    ``time.monotonic()`` (immune to clock jumps — the same fix applied to
    the task deadlines, see CTT008); each shard's header records one
    (wall, mono) anchor pair so the exporter can place spans on a shared
    wall-clock axis across processes.
  * **Cross-process-unique span ids**: ``pid << 24 | counter`` — shards
    from any number of single-host processes merge without collisions.
  * **Parents are best-effort.**  Nesting is tracked per thread; spans
    opened in worker threads (executor pipelining) carry an explicit
    ``task=...`` attribute instead, and the exporter resolves task
    attribution through either route.

Clock vocabulary for the rest of the codebase (enforced by lint rule
CTT008): ``time.time()`` is for *timestamps* only; durations and deadlines
use ``obs.trace.monotonic()`` (= ``time.monotonic()``) so a host clock
jump can never fire or stall a timeout.

The artifact formats below are REGISTRY-DERIVED: the machine-readable
source of truth is ``analysis/protocols.py`` (one ``ArtifactSchema`` per
file kind — required/optional keys, producers, consumers, torn-write
tolerance), and ``analysis.check_docstring_sync()`` asserts every
registered required key still appears in this docstring (whole-tree test
in tests/test_ctt_proto.py).  Edit the registry first; this prose
follows it.

Run-directory file formats (everything ``obs.live`` tails)::

    spans.p<pid>.t<tid>.jsonl   append-only; line 1 a header record
                                {"type": "header", "run", "pid", "tid",
                                 "host", "wall", "mono"}  (the (wall, mono)
                                anchor pair), then span records
                                {"type": "span", "id", "parent", "name",
                                 "kind", "t0", "t1", "pid", "tid",
                                 "attrs"?}  with monotonic endpoints.
    metrics.p<pid>.json         one snapshot per process, atomically
                                replaced on flush: {"counters", "gauges"}.
    hist.p<pid>.json            ctt-slo latency-histogram snapshot per
                                process, atomically replaced on flush:
                                {"schema", "edges" (the FIXED log2 bucket
                                edges every histogram shares — merging
                                two snapshots is bucket-wise addition,
                                exact), "hists": [{"name", "labels",
                                "buckets", "sum", "count"}, ...]}.
    hb.p<pid>.json              ctt-watch heartbeat, atomically replaced
                                every CTT_HEARTBEAT_S while the process
                                executes blocks: liveness + role/job id +
                                progress counters + in-flight block ids +
                                device-memory high-water + an (wall, mono)
                                anchor and the promised cadence — full
                                field list in obs/heartbeat.py.

Work-queue file formats (ctt-steal; live in ``<job_dir>/queue/`` next to
the cluster job scripts, not the trace dir — documented here beside the
heartbeat schema because leases follow the same clock contract: wall
stamps for cross-process ageing, monotonic for the writer's diagnostics)::

    manifest.json               written once by the driver (fsync'd
                                atomic): {"task", "items": [[block ids],
                                ...], "lease_s", "duplicate",
                                "created_wall"}.
    lease.<k>.g<g>.json         generation-g ownership of item k, created
                                by an exclusive os.link publish (the claim
                                race's arbiter) and atomically re-stamped
                                every lease_s by the owner: {"item",
                                "gen", "blocks", "owner_pid", "job_id",
                                "host", "claim_wall", "wall", "mono"}.
                                A stamp older than 3 x lease_s means the
                                owner is dead (the heartbeat-staleness
                                rule) — any worker may claim gen g+1.
    result.<k>.json             item k's terminal record, published
                                first-writer-wins via the same link
                                idiom: {"item", "gen", "done", "failed",
                                "errors", "pid", "job_id", "duplicate",
                                "seconds", "wall"}.

Serving-daemon file formats (ctt-serve; live in the daemon's state dir —
the same lease clock contract, lifted from block-batch grain to job
grain; the HTTP wire schema is documented in ``serve/protocol.py``)::

    serve.json                  the endpoint record, atomically replaced
                                at daemon start with mode 0600: {"host",
                                "port", "pid", "daemon_id",
                                "started_wall", "run_id",
                                "token"} — clients discover the daemon by
                                file, not by port convention, and
                                "token" (required on every request
                                except /healthz, via X-CTT-Serve-Token
                                or Authorization: Bearer) makes reading
                                this file the authorization: loopback
                                reachability alone grants nothing.
    jobs/job.<id>.json          one submission, published exactly once
                                (exclusive link): {"id", "seq", "schema",
                                "workflow", "kwargs", "configs",
                                "tenant", "priority", "submit_wall"}.
    jobs/lease.<id>.g<g>.json   generation-g execution ownership,
                                re-stamped every lease_s by the running
                                daemon: {"job", "gen", "owner_pid",
                                "daemon" (the claiming daemon's fleet id,
                                stamped at claim time so peers can judge
                                the lease even if the owner dies before
                                its first renewal), "claim_wall", "wall",
                                "mono", optional "dispatch_wall" (ctt-slo:
                                when this generation's execution began,
                                after any microbatch window — re-stamped
                                on every renewal so it survives to the
                                post-mortem)}.  Stale beyond 3 x lease_s = the
                                daemon died mid-job; the next daemon on
                                the same state dir claims gen g+1 — or
                                immediately, if the owner's fleet beat
                                (below) already proves it dead.  A lease
                                re-stamped with {"released": true, "wall":
                                0} is a voluntary give-back (drain suspend
                                of a long-lived ingest job): it classifies
                                expired at once, and released generations
                                do not count against the retry budget.
    jobs/admit.<id>.json        ctt-fleet two-phase admission marker,
                                exclusive link: {"id", "wall", "daemon"}.
                                A record published with "admitted": false
                                is claimable only once this lands; a
                                rejected submission is retracted as a
                                result with "rejected": true instead.
    jobs/result.<id>.json       terminal record, first writer wins:
                                {"id", "gen", "ok", "error", "seconds",
                                "warm", "compile_cache": {"hits",
                                "misses"}, "tenant", "pid", "daemon",
                                "finished_wall", plus the ctt-slo phase
                                walls "claimed_wall"/"dispatch_wall"/
                                "published_wall" of the winning
                                generation — ``obs journey`` rebuilds
                                the per-phase breakdown from this record
                                alone}.  A quarantined poison
                                job (retry budget exhausted) parks here
                                with {"ok": false, "quarantined": true,
                                "failure_log": [each burned generation's
                                last lease stamp], "gen" = max_job_gens};
                                an admission retraction with {"ok":
                                false, "rejected": true, "gen": -1}.
    daemon.<id>.json            ctt-fleet heartbeat, atomically replaced
                                every CTT_HEARTBEAT_S (the ctt-watch
                                cadence — NOT lease_s: failover latency
                                is bounded by this beat): {"id", "pid",
                                "host", "port", "wall", "mono",
                                "interval_s" (the promised cadence),
                                "seq", "draining", "exiting",
                                "running_jobs", "queued", "concurrency"}.
                                A beat older than 3 x its interval_s, or
                                stamped "exiting": true, marks the daemon
                                dead: peers expire its job leases on the
                                spot (serve.jobs_reclaimed) instead of
                                waiting out lease staleness.
    snap.<id>.json              ctt-slo per-daemon telemetry snapshot,
                                atomically replaced on the fleet-beat
                                cadence: {"schema", "daemon", "pid",
                                "wall", "counters", "gauges", "hists"
                                (a hist.p-format snapshot)}.  ``obs
                                fleet`` merges every daemon's snap over
                                one backend listing — counters summed,
                                gauges last-writer in sorted-daemon
                                order, histograms bucket-wise (exact) —
                                into one OpenMetrics rollup with
                                fleet-wide p50/p99 latency gauges, and
                                ``obs slo`` gates objectives against it.

Hierarchy artifact (ctt-hier; lives BESIDE the labels volume —
``<output_path>/<output_key>_hierarchy.npz`` by default — because it is
part of the segmentation product, not run scratch; documented here with
the other cross-process file contracts)::

    <key>_hierarchy.npz         np.savez, written atomically: {"schema"
                                (ops/hier.HIER_SCHEMA_VERSION), "a", "b"
                                (int64 GLOBAL region-id pairs, a < b),
                                "saddle" (float32, ascending — the sorted
                                order IS the contract: re-cutting at any
                                threshold is one searchsorted over this
                                column), "n_labels", "shape",
                                "block_shape"}.  Saddle of a pair = min
                                over the regions' shared boundary of
                                max(h(p), h(q)) on the flood's working
                                input.
    hier_offsets.npz            tmp-folder scratch (the merge_offsets
                                idiom): {"offsets" (exclusive prefix sum
                                of per-block max ids), "n_labels"}.
    data.zarr/hier/*            ragged per-block scratch: ``max_ids``,
                                ``pairs``/``saddles`` (in-block table,
                                block-LOCAL ids, (k,2) int64 flattened +
                                (k,) float32), ``face_pairs``/
                                ``face_saddles`` (cross-block table,
                                GLOBAL ids).

Streaming-ingest control dir (ctt-ingest; a POSIX dir or object-store
prefix the acquisition writer and the ingest daemon share — the watcher's
poll primitive is one listing GET over it)::

    ingest.manifest.json        stream geometry, published once
                                (publish_once) by the writer before the
                                first slab: {"schema", "domain"
                                ("volume"/"frames"), "shape" (final),
                                "slab_depth" (extent along axis 0 per
                                landing), "slabs_total", "created_wall"}.
    slab.NNNNNN.json            per-slab landing marker, create-only,
                                published AFTER the slab's data is
                                durably written: {"slab", "wall",
                                optional "digest"}.  The marker is the
                                commit point; a torn marker is skipped
                                until a later poll reads it whole, and
                                the watcher's ready-frontier (count of
                                consecutive markers from 0) never
                                regresses.
    ingest.carry.sNNNNNN.json   carry snapshot after chunk N committed,
                                create-only (a lost race = a concurrent
                                successor committed the same slab):
                                {"schema", "chain", "slab", "slabs_done",
                                "carry" (pickle+zlib+base64 of the
                                _ChainRunner carry: max-id offsets,
                                face-edge tables), "carry_bytes"
                                (raw pickle size), "cap_hint"
                                (ops.events._CAP_HINT snapshot — the
                                frame domain's zero-recompile warmup),
                                "wall"}.  A resuming process loads the
                                highest readable record and skips its
                                chunks; an unreadable record falls back
                                one slab (idempotent block writes make
                                the re-run harmless).
    ingest.frontier.json        advisory commit frontier, atomically
                                replaced after every slab: {"schema",
                                "slabs_done", "slabs_total", "resumes",
                                "wall"}.  Torn reads degrade to the
                                carry records, which are the truth.
"""

from __future__ import annotations

import atexit
import functools
import itertools
import json
import os
import socket
import threading
import time
from typing import Any, Dict, IO, Optional, Tuple

__all__ = [
    "enabled", "enable", "disable", "flush", "span", "event", "traced",
    "current_run_id", "run_dir", "monotonic", "new_run_id",
]

ENV_DIR = "CTT_TRACE_DIR"
ENV_RUN = "CTT_RUN_ID"

# duration clock for the whole codebase (CTT008: wall clock is for
# timestamps only) — a named alias so call sites read as intent
monotonic = time.monotonic

_SPAN_ID_PID_SHIFT = 24  # pid << 24 | counter: unique across processes


def new_run_id() -> str:
    """Human-sortable, collision-safe run id (wall stamp + pid + nonce)."""
    stamp = time.strftime("%Y%m%d_%H%M%S")
    nonce = os.urandom(2).hex()
    return f"run_{stamp}_p{os.getpid()}_{nonce}"


class _RunState:
    """Open shard handles + per-thread span stacks for one enabled run."""

    def __init__(self, trace_dir: str, run_id: str):
        self.trace_dir = trace_dir
        self.run_id = run_id
        self.dir = os.path.join(trace_dir, run_id)
        self.lock = threading.Lock()  # guards the shard-handle dict only
        self.shards: Dict[Tuple[int, int], IO[str]] = {}
        self.local = threading.local()
        self.counter = itertools.count(1)

    # -- per-thread span stack (parent tracking) --------------------------

    def stack(self):
        st = getattr(self.local, "stack", None)
        if st is None:
            st = []
            self.local.stack = st
        return st

    # -- shard IO ----------------------------------------------------------

    def _shard(self) -> IO[str]:
        key = (os.getpid(), threading.get_ident())
        f = self.shards.get(key)
        if f is None or f.closed:
            with self.lock:
                f = self.shards.get(key)
                if f is None or f.closed:
                    os.makedirs(self.dir, exist_ok=True)
                    path = os.path.join(
                        self.dir, f"spans.p{key[0]}.t{key[1]}.jsonl"
                    )
                    f = open(path, "a", buffering=1)
                    # anchor pair: the exporter maps mono -> wall with it.
                    # time.time() here is a timestamp, not duration math.
                    f.write(json.dumps({
                        "type": "header",
                        "run": self.run_id,
                        "pid": key[0],
                        "tid": key[1],
                        "host": socket.gethostname(),
                        "wall": time.time(),
                        "mono": monotonic(),
                    }) + "\n")
                    self.shards[key] = f
        return f

    def write(self, record: Dict[str, Any]) -> None:
        self._shard().write(json.dumps(record) + "\n")

    def next_span_id(self) -> int:
        return (os.getpid() << _SPAN_ID_PID_SHIFT) | (
            next(self.counter) & ((1 << _SPAN_ID_PID_SHIFT) - 1)
        )

    def flush(self) -> None:
        with self.lock:
            for f in list(self.shards.values()):
                try:
                    if not f.closed:
                        f.flush()
                except OSError:  # pragma: no cover - flush is best-effort
                    pass

    def close(self) -> None:
        with self.lock:
            for f in list(self.shards.values()):
                try:
                    if not f.closed:
                        f.close()
                except OSError:  # pragma: no cover
                    pass
            self.shards.clear()


_RUN: Optional[_RunState] = None
_ATEXIT_REGISTERED = False


def enabled() -> bool:
    return _RUN is not None


def current_run_id() -> Optional[str]:
    return _RUN.run_id if _RUN is not None else None


def run_dir() -> Optional[str]:
    """Directory holding this run's shards (``<trace_dir>/<run_id>``)."""
    return _RUN.dir if _RUN is not None else None


def enable(
    trace_dir: Optional[str] = None,
    run_id: Optional[str] = None,
    export_env: bool = True,
) -> str:
    """Turn tracing on (idempotent for an identical dir+run).

    ``export_env=True`` publishes CTT_TRACE_DIR / CTT_RUN_ID so child
    processes (bench subprocesses, scheduler workers, multi-host peers
    launched from here) join the SAME run — the cross-process contract.
    Returns the run id.
    """
    global _RUN, _ATEXIT_REGISTERED
    if trace_dir is None:
        trace_dir = os.environ.get(ENV_DIR)
        if not trace_dir:
            raise ValueError(
                "enable() needs a trace_dir (argument or CTT_TRACE_DIR)"
            )
    if run_id is None:
        run_id = os.environ.get(ENV_RUN) or new_run_id()
    if _RUN is not None:
        if _RUN.trace_dir == trace_dir and _RUN.run_id == run_id:
            return run_id
        disable()
    _RUN = _RunState(trace_dir, run_id)
    if export_env:
        os.environ[ENV_DIR] = trace_dir
        os.environ[ENV_RUN] = run_id
    if not _ATEXIT_REGISTERED:
        atexit.register(flush)
        _ATEXIT_REGISTERED = True
    return run_id


def disable() -> None:
    """Flush and stop recording (the env vars are left untouched so an
    explicit disable() sticks for this process only)."""
    global _RUN
    if _RUN is not None:
        try:
            from . import metrics as _metrics

            _metrics.flush()
        except Exception:  # pragma: no cover  # ctt: noqa[CTT009] teardown is best-effort: a metrics flush failure must not block disable()
            pass
        _RUN.flush()
        _RUN.close()
        _RUN = None


def flush() -> None:
    """Flush every open shard (and the metrics snapshot) to disk — called
    at the end of ``runtime.build`` and atexit, so short-lived processes
    (scheduler workers, bench subprocesses) never lose buffered spans."""
    if _RUN is not None:
        try:
            from . import metrics as _metrics

            _metrics.flush()
        except Exception:  # pragma: no cover  # ctt: noqa[CTT009] flush is best-effort by contract (atexit path)
            pass
        _RUN.flush()


def _bootstrap_from_env() -> None:
    trace_dir = os.environ.get(ENV_DIR)
    if trace_dir:
        enable(trace_dir)


# ---------------------------------------------------------------------------
# spans


class _NoopSpan:
    """Shared do-nothing span: the disabled fast path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> None:
        pass


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "kind", "attrs", "sid", "parent", "t0", "_st")

    def __init__(self, st: _RunState, name: str, kind: str, attrs):
        self._st = st
        self.name = name
        self.kind = kind
        self.attrs = attrs
        self.sid = st.next_span_id()
        self.parent = None
        self.t0 = 0.0

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "_Span":
        stack = self._st.stack()
        if stack:
            self.parent = stack[-1].sid
        stack.append(self)
        self.t0 = monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = monotonic()
        stack = self._st.stack()
        if stack and stack[-1] is self:
            stack.pop()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        record = {
            "type": "span",
            "id": self.sid,
            "parent": self.parent,
            "name": self.name,
            "kind": self.kind,
            "t0": self.t0,
            "t1": t1,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if self.attrs:
            record["attrs"] = self.attrs
        self._st.write(record)
        return False


def span(name: str, kind: str = "host", **attrs):
    """Context manager recording one interval.

    ``kind`` buckets the summarize table: ``host_io`` (chunk reads/writes),
    ``device`` (batched device dispatch), ``collective`` (mesh programs in
    parallel/), ``task``/``dispatch``/``run`` (structural), ``barrier``
    (peer waits), ``host`` (everything else), ``timing`` (retroactive
    record_timing bridge events — excluded from bucket sums).  Pass
    ``task=<identifier>`` when the span may open in a worker thread, where
    the per-thread parent stack cannot see the task span.
    """
    st = _RUN
    if st is None:
        return _NOOP
    return _Span(st, name, kind, attrs)


def traced(name: Optional[str] = None, kind: str = "host", **attrs):
    """Decorator form of :func:`span` for whole functions (e.g. the
    collective entry points in ``parallel/sharded*.py``).  When tracing is
    disabled the only overhead is one module-global None check."""

    def deco(fn):
        label = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if _RUN is None:
                return fn(*args, **kwargs)
            with span(label, kind=kind, **attrs):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def event(name: str, kind: str, seconds: float, **attrs) -> None:
    """Record a retroactive, already-measured interval ending now (the
    bridge for `Task.record_timing`'s after-the-fact durations).  The
    placement on the time axis is approximate (ends at 'now'); the
    duration is exact."""
    st = _RUN
    if st is None:
        return
    t1 = monotonic()
    record = {
        "type": "span",
        "id": st.next_span_id(),
        "parent": None,
        "name": name,
        "kind": kind,
        "t0": t1 - float(seconds),
        "t1": t1,
        "pid": os.getpid(),
        "tid": threading.get_ident(),
    }
    if attrs:
        record["attrs"] = attrs
    st.write(record)


_bootstrap_from_env()
