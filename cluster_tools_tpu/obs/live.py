"""ctt-watch live reader: tail a run's telemetry while it is in flight.

`obs.export` is the post-mortem path — it re-parses every shard from byte
0 and *rejects* malformed input, which is right for CI and wrong for a
run that is still being written.  This module is the in-flight path, one
incremental pass in the streaming-analysis sense:

  * **Per-file offset cursors.**  Every ``spans.p*.jsonl`` shard keeps a
    byte offset; each ``poll()`` reads only the appended suffix.  A torn
    trailing line (a writer mid-``write``) is simply *not consumed* — the
    cursor stays at the line start until the newline lands.  A complete
    line that still fails to parse is counted (``malformed_lines``) and
    skipped: the watcher must outlive a corrupt record, the post-mortem
    exporter is the strict one.
  * **Heartbeats** (``hb.p*.json``, obs.heartbeat) are single small JSON
    objects atomically replaced per beat — re-read whole each poll.
  * **Derived state**: per-task block progress (done/total), block
    throughput and ETA, per-block duration map (the z-slab heatmap),
    straggler flags (in-flight block older than ``k``·median completed
    duration), and suspected-dead workers (heartbeat older than
    ``stale_intervals``·its own promised cadence — catches a hung or
    killed worker *before* the deadline watchdog or scheduler limit).
  * **OpenMetrics export** (:func:`render_openmetrics`): counters/gauges
    plus heartbeat-derived worker/task gauges in Prometheus text
    exposition format, so a scrape job can watch a cluster run.

Ageing across processes uses wall-clock deltas (the same cross-process
contract as the shard-header anchors: good to host clock skew); in-flight
block age combines the writer's own monotonic delta with the wall time
since the beat, so a reader clock jump cannot un-flag a straggler.
"""

from __future__ import annotations

import glob
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from . import hist as hist_mod
from .heartbeat import FILE_PREFIX as HB_PREFIX
from .metrics import METRICS_FILE_PREFIX
from ..runtime.queue import STALE_INTERVALS, STRAGGLER_K

__all__ = [
    "LiveRun", "resolve_live_dir", "format_watch", "format_heatmap",
    "render_openmetrics",
]

SHARD_GLOB = "spans.p*.jsonl"

# span names that represent block *execution* (the things the heatmap and
# progress counters aggregate).  host_io stage spans are excluded: they
# cover the same blocks again and would double-count.
_BLOCK_SPAN_NAMES = {"block", "block_fallback", "block_batch", "stage_compute"}

_now_wall = time.time  # module-level so tests can fake the reader clock


def resolve_live_dir(path: str) -> Optional[str]:
    """Like export.resolve_run_dir but tolerant of a run that has not
    produced anything yet: accepts a dir holding shards OR heartbeats,
    descends one level when exactly one child run exists, and returns
    None (caller keeps polling) instead of raising."""
    def _is_run(d: str) -> bool:
        return bool(
            glob.glob(os.path.join(d, SHARD_GLOB))
            or glob.glob(os.path.join(d, f"{HB_PREFIX}*.json"))
            or glob.glob(os.path.join(d, f"{METRICS_FILE_PREFIX}*.json"))
        )

    if not os.path.isdir(path):
        return None
    if _is_run(path):
        return path
    runs = sorted(d for d in os.listdir(path)
                  if _is_run(os.path.join(path, d)))
    if len(runs) == 1:
        return os.path.join(path, runs[0])
    return None


class LiveRun:
    """Incremental reader over one run directory.  Construct once, call
    :meth:`poll` repeatedly; state accumulates across polls."""

    def __init__(
        self,
        run_dir: str,
        # defaults are THE shared clock-contract constants (CTT204): the
        # live view must age leases/beats exactly like the scheduler does
        straggler_k: float = STRAGGLER_K,
        stale_intervals: float = STALE_INTERVALS,
    ):
        self.run_dir = run_dir
        self.straggler_k = float(straggler_k)
        self.stale_intervals = float(stale_intervals)
        self.run_id: Optional[str] = None
        self.malformed_lines = 0
        self._offsets: Dict[str, int] = {}
        self._anchors: Dict[str, Tuple[float, float]] = {}
        self._pids: set = set()
        # task -> accumulated state
        self._durations: Dict[str, Dict[int, float]] = {}
        self._failed: Dict[str, set] = {}
        self._complete: Dict[str, bool] = {}
        self._dispatch: Dict[str, Dict[str, Any]] = {}
        self._first_wall: Dict[str, float] = {}
        self._last_wall: Dict[str, float] = {}

    # -- incremental shard tailing ----------------------------------------

    def _ingest_shards(self) -> None:
        for path in sorted(
            glob.glob(os.path.join(self.run_dir, SHARD_GLOB))
        ):
            offset = self._offsets.get(path, 0)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            if size <= offset:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    data = f.read()
            except OSError:
                continue
            consumed = len(data)
            if not data.endswith(b"\n"):
                # torn trailing line: leave the cursor at its start; the
                # writer's newline will complete it by the next poll
                nl = data.rfind(b"\n")
                if nl < 0:
                    continue  # nothing complete yet
                consumed = nl + 1
                data = data[:consumed]
            for raw in data.split(b"\n"):
                if not raw.strip():
                    continue
                self._ingest_line(path, raw)
            self._offsets[path] = offset + consumed

    def _ingest_line(self, path: str, raw: bytes) -> None:
        try:
            rec = json.loads(raw)
            if not isinstance(rec, dict):
                raise ValueError
        except (json.JSONDecodeError, ValueError, UnicodeDecodeError):
            self.malformed_lines += 1
            return
        rtype = rec.get("type")
        if rtype == "header":
            try:
                self._anchors[path] = (float(rec["wall"]), float(rec["mono"]))
            except (KeyError, TypeError, ValueError):
                self.malformed_lines += 1
                return
            if self.run_id is None:
                self.run_id = rec.get("run")
            if "pid" in rec:
                self._pids.add(rec["pid"])
            return
        if rtype != "span":
            self.malformed_lines += 1
            return
        anchor = self._anchors.get(path)
        if anchor is None:
            self.malformed_lines += 1
            return
        try:
            t0, t1 = float(rec["t0"]), float(rec["t1"])
        except (KeyError, TypeError, ValueError):
            self.malformed_lines += 1
            return
        wall0, mono0 = anchor
        self._note_span(rec, wall0 + (t0 - mono0), wall0 + (t1 - mono0))

    def _note_span(self, rec: dict, wall_t0: float, wall_t1: float) -> None:
        kind = rec.get("kind")
        attrs = rec.get("attrs") or {}
        name = rec.get("name")
        if kind == "task" and isinstance(name, str):
            self._complete[name] = True
            return
        task = attrs.get("task")
        if not isinstance(task, str):
            return
        if kind == "dispatch":
            info = self._dispatch.setdefault(task, {})
            if isinstance(attrs.get("blocks"), int):
                # retry dispatches carry only the failed share — keep the
                # largest round as the task total fallback
                info["blocks"] = max(info.get("blocks", 0), attrs["blocks"])
            if isinstance(attrs.get("grid"), list):
                info["grid"] = attrs["grid"]
            return
        if name not in _BLOCK_SPAN_NAMES:
            return
        if "block" in attrs:
            bids = [attrs["block"]]
        elif isinstance(attrs.get("block_ids"), list):
            bids = attrs["block_ids"]
        else:
            return
        try:
            bids = [int(b) for b in bids]
        except (TypeError, ValueError):
            return
        if "error" in attrs:
            failed = self._failed.setdefault(task, set())
            dmap = self._durations.get(task, {})
            failed.update(b for b in bids if b not in dmap)
            return
        dur = (rec.get("t1", 0.0) - rec.get("t0", 0.0)) / max(len(bids), 1)
        dmap = self._durations.setdefault(task, {})
        failed = self._failed.get(task)
        for b in bids:
            dmap[b] = dur
            if failed:
                failed.discard(b)  # retry healed it
        if task not in self._first_wall or wall_t0 < self._first_wall[task]:
            self._first_wall[task] = wall_t0
        if task not in self._last_wall or wall_t1 > self._last_wall[task]:
            self._last_wall[task] = wall_t1

    # -- heartbeat / metrics re-reads -------------------------------------

    def _read_heartbeats(self) -> List[dict]:
        out = []
        for path in sorted(
            glob.glob(os.path.join(self.run_dir, f"{HB_PREFIX}*.json"))
        ):
            try:
                with open(path) as f:
                    hb = json.load(f)
            except (json.JSONDecodeError, OSError):
                continue  # replaced mid-read; next poll sees it whole
            if isinstance(hb, dict) and "pid" in hb:
                out.append(hb)
                self._pids.add(hb["pid"])
        return out

    def _read_metrics(self) -> Tuple[Dict[str, float], Dict[str, Any]]:
        counters: Dict[str, float] = {}
        gauges: Dict[str, Any] = {}
        for path in sorted(glob.glob(
            os.path.join(self.run_dir, f"{METRICS_FILE_PREFIX}*.json")
        )):
            try:
                with open(path) as f:
                    snap = json.load(f)
            except (json.JSONDecodeError, OSError):
                continue
            for k, v in (snap.get("counters") or {}).items():
                try:
                    counters[k] = counters.get(k, 0.0) + float(v)
                except (TypeError, ValueError):
                    continue
            gauges.update(snap.get("gauges") or {})
        return counters, gauges

    def _read_hists(self) -> Dict[str, Any]:
        """Exact merge of every ``hist.p*.json`` snapshot (ctt-slo):
        fixed bucket edges make the cross-process merge bucket-wise
        addition, so the live view's percentiles equal a single merged
        process's.  Torn snapshots are skipped (atomic-replace writers;
        the next poll sees them whole)."""
        return hist_mod.load_run_hists(self.run_dir)

    # -- derived state ------------------------------------------------------

    @staticmethod
    def _median(values: List[float]) -> Optional[float]:
        if not values:
            return None
        vals = sorted(values)
        n = len(vals)
        mid = n // 2
        return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])

    def _worker_rows(self, hbs: List[dict], now: float) -> List[dict]:
        rows = []
        for hb in hbs:
            interval = float(hb.get("interval_s") or 5.0)
            age = max(0.0, now - float(hb.get("wall") or now))
            exiting = bool(hb.get("exiting"))
            rows.append({
                "pid": hb.get("pid"),
                "host": hb.get("host"),
                "role": hb.get("role", "worker"),
                "job_id": hb.get("job_id"),
                "process_id": hb.get("process_id"),
                "task": hb.get("task"),
                "age_s": age,
                "interval_s": interval,
                "exiting": exiting,
                "stale": (not exiting
                          and age > self.stale_intervals * interval),
                "blocks_total": int(hb.get("blocks_total") or 0),
                "blocks_done": int(hb.get("blocks_done") or 0),
                "blocks_failed": int(hb.get("blocks_failed") or 0),
                "blocks_retried": int(hb.get("blocks_retried") or 0),
                "device_mem_peak_bytes": hb.get("device_mem_peak_bytes"),
                "queue_depth": hb.get("queue_depth"),
                "draining": bool(hb.get("draining")),
                "current_blocks": hb.get("current_blocks") or [],
                "mono": float(hb.get("mono") or 0.0),
                "grid": hb.get("grid"),
            })
        return rows

    def _stragglers(self, workers: List[dict], now: float) -> List[dict]:
        out = []
        for w in workers:
            if w["exiting"] or not w["task"]:
                continue
            med = self._median(
                list(self._durations.get(w["task"], {}).values())
            )
            if not med or med <= 0:
                continue
            for cb in w["current_blocks"]:
                try:
                    start_mono = float(cb["start_mono"])
                    bid = int(cb["id"])
                except (KeyError, TypeError, ValueError):
                    continue
                # age on the writer's own clock up to the beat, plus wall
                # time elapsed since the beat landed
                in_flight = (w["mono"] - start_mono) + w["age_s"]
                if in_flight > self.straggler_k * med:
                    out.append({
                        "task": w["task"], "block": bid, "pid": w["pid"],
                        "in_flight_s": in_flight, "median_s": med,
                    })
        return out

    def _task_rows(self, workers: List[dict]) -> Dict[str, dict]:
        names = (
            set(self._durations) | set(self._complete)
            | set(self._dispatch) | set(self._failed)
            | {w["task"] for w in workers if w["task"]}
        )
        # totals: prefer driver heartbeats (each multi-host driver reports
        # its own shard; workers report sub-shares of one driver's
        # dispatch and would double-count on top of it)
        totals: Dict[str, int] = {}
        for role in ("driver", "worker"):
            for w in workers:
                if w["role"] == role and w["task"] and w["blocks_total"]:
                    totals.setdefault(w["task"], 0)
                    totals[w["task"]] += w["blocks_total"]
            if totals:
                break
        rows: Dict[str, dict] = {}
        for name in sorted(names):
            durs = self._durations.get(name, {})
            done = len(durs)
            total = totals.get(name)
            if total is None:
                total = self._dispatch.get(name, {}).get("blocks")
            if total is not None and total < done:
                total = done  # retries can shrink a dispatch's share
            first = self._first_wall.get(name)
            last = self._last_wall.get(name)
            throughput = None
            eta = None
            if done and first is not None and last is not None and last > first:
                throughput = done / (last - first)
                if total is not None and throughput > 0:
                    eta = max(0, total - done) / throughput
            rows[name] = {
                "blocks_done": done,
                "blocks_total": total,
                "blocks_failed": len(self._failed.get(name, ())),
                "complete": bool(self._complete.get(name)),
                "median_block_s": self._median(list(durs.values())),
                "throughput_bps": throughput,
                "eta_s": eta,
            }
        return rows

    def poll(self) -> Dict[str, Any]:
        """One incremental pass: ingest appended shard lines, re-read
        heartbeats + metrics, return the full derived snapshot."""
        self._ingest_shards()
        now = _now_wall()
        hbs = self._read_heartbeats()
        counters, gauges = self._read_metrics()
        hists = self._read_hists()
        workers = self._worker_rows(hbs, now)
        tasks = self._task_rows(workers)
        stragglers = self._stragglers(workers, now)
        for name, row in tasks.items():
            row["stragglers"] = [s for s in stragglers if s["task"] == name]
        stale = [w for w in workers if w["stale"]]
        progress = (
            any(r["blocks_done"] > 0 for r in tasks.values())
            or any(r["complete"] for r in tasks.values())
        )
        return {
            "run_id": self.run_id,
            "dir": self.run_dir,
            "now_wall": now,
            "progress": progress,
            "malformed_lines": self.malformed_lines,
            "n_processes": len(self._pids),
            "tasks": tasks,
            "workers": workers,
            "stragglers": stragglers,
            "n_stale": len(stale),
            "stale_workers": [
                {"pid": w["pid"], "job_id": w["job_id"], "task": w["task"],
                 "age_s": w["age_s"], "interval_s": w["interval_s"]}
                for w in stale
            ],
            "counters": counters,
            "gauges": gauges,
            # present only when a histogram snapshot exists, so poll
            # snapshots (and the --json watch stream) of runs without
            # latency series stay byte-identical to the pre-slo output
            **({"hists": hists} if hists.get("hists") else {}),
        }

    def task_median_s(self, task: str) -> Optional[float]:
        """Median completed-block duration for one task, from the spans
        ingested so far (incremental — call freely).  The lease-aware
        straggler baseline ``runtime/queue.py`` rides: the work queue's
        duplication threshold uses THIS median instead of recomputing its
        own from item result records, so duplication can fire before the
        queue's first result lands and both detectors agree on what
        'slow' means."""
        self._ingest_shards()
        return self._median(list(self._durations.get(task, {}).values()))

    # -- heatmap ------------------------------------------------------------

    def heatmap_grid(self, task: str) -> Optional[List[int]]:
        """Blocking grid shape for ``task``: dispatch-span attrs first
        (exact), else the latest heartbeat that carried one."""
        grid = self._dispatch.get(task, {}).get("grid")
        if grid:
            return [int(g) for g in grid]
        for hb in self._read_heartbeats():
            if hb.get("task") == task and hb.get("grid"):
                return [int(g) for g in hb["grid"]]
        return None

    def heatmap(self, task: Optional[str] = None) -> Optional[dict]:
        """Per-block duration map for one task (default: the task with the
        most completed blocks).  Returns ``{"task", "grid", "durations"}``
        or None when nothing has finished yet."""
        if task is None:
            if not self._durations:
                return None
            task = max(self._durations, key=lambda t: len(self._durations[t]))
        durs = self._durations.get(task)
        if not durs:
            return None
        return {
            "task": task,
            "grid": self.heatmap_grid(task),
            "durations": dict(durs),
        }


# ---------------------------------------------------------------------------
# rendering


_HEAT_LEVELS = " .:-=+*#%@"  # cold .. hot, 10 levels


def format_heatmap(hm: dict) -> str:
    """Z-slab text heatmap: one character grid per slab along axis 0,
    duration mapped onto 10 shade levels between the observed min and max
    (``_`` = block not finished).  Deterministic for fixed input."""
    task = hm["task"]
    durs: Dict[int, float] = {int(k): float(v)
                              for k, v in hm["durations"].items()}
    lo, hi = min(durs.values()), max(durs.values())

    def shade(bid: int) -> str:
        d = durs.get(bid)
        if d is None:
            return "_"
        if hi <= lo:
            return _HEAT_LEVELS[-1]
        idx = int((d - lo) / (hi - lo) * (len(_HEAT_LEVELS) - 1) + 0.5)
        return _HEAT_LEVELS[idx]

    grid = hm.get("grid")
    lines = [
        f"task {task}  block-duration heatmap  "
        f"({len(durs)} blocks, {lo:.3f}s..{hi:.3f}s, "
        f"'{_HEAT_LEVELS[0]}'=fastest '@'=slowest '_'=pending)"
    ]
    if not grid:
        # no geometry: a flat strip in block-id order, 64 per row
        ids = range(0, max(durs) + 1)
        row: List[str] = []
        for bid in ids:
            row.append(shade(bid))
            if len(row) == 64:
                lines.append("".join(row))
                row = []
        if row:
            lines.append("".join(row))
        return "\n".join(lines)
    if len(grid) == 1:
        grid = [1, 1] + grid
    elif len(grid) == 2:
        grid = [1] + grid
    gz, rest = grid[0], grid[1:]
    per_slab = 1
    for g in rest:
        per_slab *= g
    gy, gx = rest[0], per_slab // max(rest[0], 1)
    for z in range(gz):
        lines.append(f"z-slab {z}:")
        base = z * per_slab
        for y in range(gy):
            lines.append(
                "  " + "".join(shade(base + y * gx + x) for x in range(gx))
            )
    return "\n".join(lines)


def _fmt_lat_s(seconds: float) -> str:
    return (f"{seconds * 1e3:.1f}ms" if seconds < 1.0
            else f"{seconds:.2f}s")


def _format_lat_line(snap: Dict[str, Any]) -> Optional[str]:
    """The ``lat:`` watch line (ctt-slo): e2e p50/p99 per priority class
    from the merged histogram snapshot, tenants aggregated bucket-wise
    (exact).  None when no e2e series exists."""
    series = (snap.get("hists") or {}).get("hists") or []
    by_prio: Dict[str, List[int]] = {}
    for s in series:
        if s.get("name") != "serve.latency.e2e":
            continue
        prio = str((s.get("labels") or {}).get("priority", "?"))
        acc = by_prio.setdefault(prio, [0] * len(s["buckets"]))
        for i, c in enumerate(s["buckets"]):
            acc[i] += int(c)

    def _prio_key(p: str):
        try:
            return (0, -int(p))  # numeric classes, highest first
        except ValueError:
            return (1, 0)

    parts = []
    for prio in sorted(by_prio, key=_prio_key):
        p50 = hist_mod.quantile(by_prio[prio], 0.5)
        p99 = hist_mod.quantile(by_prio[prio], 0.99)
        if p50 is None or p99 is None:
            continue
        parts.append(
            f"prio {prio} p50 {_fmt_lat_s(p50)} p99 {_fmt_lat_s(p99)}"
        )
    return "  lat: e2e " + ", ".join(parts) if parts else None


def format_watch(snap: Dict[str, Any]) -> str:
    """Human watch report for one poll."""
    workers = snap["workers"]
    n_live = sum(1 for w in workers if not w["stale"] and not w["exiting"])
    n_exited = sum(1 for w in workers if w["exiting"])
    header = (
        f"run {snap['run_id'] or '?'}  "
        f"workers: {len(workers)} ({n_live} live, {n_exited} exited, "
        f"{snap['n_stale']} stale)  processes seen: {snap['n_processes']}"
    )
    lines = [header]
    tasks = snap["tasks"]
    if tasks:
        width = max(len(n) for n in tasks) if tasks else 4
        width = max(width, 4)
        lines.append(
            "  ".join([
                "task".ljust(width), "done/total".rjust(12),
                "%".rjust(6), "blk/s".rjust(8), "eta_s".rjust(8),
                "median_s".rjust(9), "flags",
            ])
        )
        for name in sorted(tasks):
            row = tasks[name]
            total = row["blocks_total"]
            done = row["blocks_done"]
            frac = f"{100.0 * done / total:.1f}" if total else "-"
            tput = (f"{row['throughput_bps']:.2f}"
                    if row["throughput_bps"] else "-")
            eta = f"{row['eta_s']:.1f}" if row["eta_s"] is not None else "-"
            med = (f"{row['median_block_s']:.3f}"
                   if row["median_block_s"] is not None else "-")
            flags = []
            if row["complete"]:
                flags.append("complete")
            if row["blocks_failed"]:
                flags.append(f"{row['blocks_failed']} failed")
            if row["stragglers"]:
                flags.append(f"{len(row['stragglers'])} straggler(s)")
            lines.append("  ".join([
                name.ljust(width),
                f"{done}/{total if total is not None else '?'}".rjust(12),
                frac.rjust(6), tput.rjust(8), eta.rjust(8), med.rjust(9),
                ",".join(flags),
            ]).rstrip())
    for s in snap["stragglers"]:
        lines.append(
            f"  straggler: task {s['task']} block {s['block']} in flight "
            f"{s['in_flight_s']:.1f}s (median {s['median_s']:.3f}s) "
            f"on pid {s['pid']}"
        )
    counters = snap.get("counters", {})
    if any(k.startswith("sched.") for k in counters):
        # ctt-steal: one line of scheduler health — how much work remains
        # unclaimed and how the leases have moved
        depth = snap.get("gauges", {}).get("sched.queue_depth")
        parts = [
            f"queue depth {int(depth)}" if isinstance(depth, (int, float))
            else None,
            f"claimed {int(counters.get('sched.leases_claimed', 0))}",
            f"expired {int(counters.get('sched.leases_expired', 0))}",
            f"requeued {int(counters.get('sched.leases_requeued', 0))}",
            f"stolen {int(counters.get('sched.leases_stolen', 0))}",
        ]
        lines.append("  sched: " + ", ".join(p for p in parts if p))
    if any(k.startswith("serve.") for k in counters):
        # ctt-serve: one line of daemon health — queue pressure, admission
        # outcomes, and how warm the compile state is running
        gauges = snap.get("gauges", {})
        parts = []
        for label, key, store in (
            ("queue depth", "serve.queue_depth", gauges),
            ("running", "serve.running_jobs", gauges),
            ("submitted", "serve.submissions", counters),
            ("done", "serve.jobs_done", counters),
            ("failed", "serve.jobs_failed", counters),
            ("rejected", "serve.quota_rejections", counters),
            ("warm", "serve.warm_compile_jobs", counters),
            ("cold", "serve.cold_compile_jobs", counters),
        ):
            val = store.get(key)
            if isinstance(val, (int, float)):
                parts.append(f"{label} {int(val)}")
        lines.append("  serve: " + ", ".join(parts))
    lat = _format_lat_line(snap)
    if lat:
        # ctt-slo: one line of request-latency health — end-to-end
        # p50/p99 per priority class from the merged histograms.  Only
        # rendered when a histogram snapshot exists, so watch output for
        # runs without latency series stays byte-identical
        lines.append(lat)
    if any(k.startswith("serve.microbatch_") for k in counters):
        # ctt-microbatch: one line of aggregation-window economics — how
        # deep the last window filled, how many jobs rode stacked
        # dispatches (jobs/dispatch is the amortization ratio), and how
        # often the window degraded (splits, deadline closes)
        gauges = snap.get("gauges", {})
        batches = counters.get("serve.microbatch_batches", 0)
        jobs = counters.get("serve.microbatch_jobs_batched", 0)
        depth = gauges.get("serve.microbatch_depth")
        parts = [
            (f"depth {int(depth)}"
             if isinstance(depth, (int, float)) else None),
            f"batches {int(batches)}",
            f"jobs batched {int(jobs)}",
            (f"jobs/dispatch {jobs / batches:.1f}" if batches else None),
            f"splits {int(counters.get('serve.microbatch_splits', 0))}",
            "window timeouts "
            f"{int(counters.get('serve.microbatch_window_timeouts', 0))}",
        ]
        lines.append("  batch: " + ", ".join(p for p in parts if p))
    gauges = snap.get("gauges", {})
    if (
        "serve.peers" in gauges
        or "fleet.queue_depth" in gauges
        or any(
            k in counters
            for k in ("serve.jobs_reclaimed", "serve.jobs_quarantined")
        )
    ):
        # ctt-fleet: one line of fleet health — live daemons over the
        # shared state dir, the fleet-wide backlog, and the failure-
        # recovery ledger (fast-path reclaims + quarantined poison jobs)
        parts = []
        for label, key, store in (
            ("peers", "serve.peers", gauges),
            ("queue depth", "fleet.queue_depth", gauges),
            ("reclaimed", "serve.jobs_reclaimed", counters),
            ("quarantined", "serve.jobs_quarantined", counters),
        ):
            val = store.get(key)
            if isinstance(val, (int, float)):
                parts.append(f"{label} {int(val)}")
        lines.append("  fleet: " + ", ".join(parts))
    if (
        "fleet.target_daemons" in gauges
        or any(k.startswith("serve.supervisor_") for k in counters)
    ):
        # ctt-diskless: one line of elastic-fleet actuation — the daemon
        # count the supervisor is converging toward, plus its action
        # ledger (spawns, drains, and beats-only re-adoptions after a
        # supervisor restart)
        parts = []
        for label, key, store in (
            ("target", "fleet.target_daemons", gauges),
            ("spawned", "serve.supervisor_spawns", counters),
            ("drained", "serve.supervisor_drains", counters),
            ("adopted", "serve.supervisor_adoptions", counters),
        ):
            val = store.get(key)
            if isinstance(val, (int, float)):
                parts.append(f"{label} {int(val)}")
        lines.append("  supervisor: " + ", ".join(parts))
    if any(k.startswith("device.") for k in counters):
        # ctt-hbm: one line of device-pipeline health — bytes that crossed
        # to HBM vs uploads the warm buffer cache absorbed, dispatch
        # aggregation, and resident cache pressure.  Sits beside the
        # per-worker device-memory high-water the heartbeats carry
        # (ctt_worker_device_mem_peak_bytes in the prom exposition).
        gauges = snap.get("gauges", {})
        cache_b = gauges.get("device.cache_bytes")
        inflight = gauges.get("device.inflight_uploads")
        parts = [
            "uploaded "
            f"{counters.get('device.upload_bytes', 0) / 1e6:.1f} MB",
            f"skipped {int(counters.get('device.uploads_skipped', 0))}",
            f"dispatches {int(counters.get('device.dispatches', 0))}",
            f"fused blocks {int(counters.get('device.fused_blocks', 0))}",
            f"evictions {int(counters.get('device.cache_evictions', 0))}",
            (f"cache {cache_b / 1e6:.1f} MB"
             if isinstance(cache_b, (int, float)) else None),
            (f"inflight {int(inflight)}"
             if isinstance(inflight, (int, float)) else None),
        ]
        lines.append("  device: " + ", ".join(p for p in parts if p))
    if any(k.startswith("store.remote_") for k in counters):
        # ctt-cloud: one line of remote-IO health — request volume, wire
        # bytes, retries absorbed, and how many requests are in flight
        inflight = snap.get("gauges", {}).get("store.remote_inflight")
        parts = [
            f"reads {int(counters.get('store.remote_reads', 0))}",
            f"writes {int(counters.get('store.remote_writes', 0))}",
            f"retries {int(counters.get('store.remote_retries', 0))}",
            "read "
            f"{counters.get('store.remote_bytes_read', 0) / 1e6:.1f} MB",
            "written "
            f"{counters.get('store.remote_bytes_written', 0) / 1e6:.1f} MB",
            (f"inflight {int(inflight)}"
             if isinstance(inflight, (int, float)) else None),
        ]
        lines.append("  remote: " + ", ".join(p for p in parts if p))
    if any(k.startswith("ingest.") for k in counters):
        # ctt-ingest: streaming-ingest health — the landed-vs-committed
        # frontier, resumes survived, poll volume, carry bytes persisted,
        # and the ingest task's ETA (the incremental driver's note_task
        # row makes the standard rate/ETA machinery apply)
        gauges = snap.get("gauges", {})
        ingested = int(counters.get("ingest.slabs_ingested", 0))
        pending = gauges.get("ingest.slabs_pending")
        pending = int(pending) if isinstance(pending, (int, float)) else 0
        eta = next(
            (row.get("eta_s") for name, row in snap.get("tasks", {}).items()
             if str(name).startswith("ingest")
             and row.get("eta_s") is not None),
            None,
        )
        parts = [
            f"frontier {ingested + pending}",
            f"ingested {ingested}",
            f"pending {pending}",
            f"resumes {int(counters.get('ingest.resumes', 0))}",
            f"polls {int(counters.get('ingest.poll_rounds', 0))}",
            "carry "
            f"{counters.get('ingest.carry_bytes_persisted', 0) / 1e6:.1f} MB",
            (f"eta {eta:.0f}s" if isinstance(eta, (int, float)) else None),
        ]
        lines.append("  ingest: " + ", ".join(p for p in parts if p))
    for w in snap["workers"]:
        if w.get("draining") and not w["exiting"]:
            lines.append(
                f"  DRAINING: pid {w['pid']} ({w['role']}) — finishing "
                "in-flight jobs, submissions refused"
            )
    for w in snap["stale_workers"]:
        where = f"job {w['job_id']}" if w["job_id"] is not None else "driver"
        lines.append(
            f"  STALE: pid {w['pid']} ({where}, task {w['task']}): last "
            f"heartbeat {w['age_s']:.1f}s ago "
            f"(> 3x the {w['interval_s']:.1f}s cadence) — suspected dead"
        )
    if snap["malformed_lines"]:
        lines.append(f"  ({snap['malformed_lines']} malformed line(s) skipped)")
    if not snap["progress"]:
        lines.append("  no progress observed yet")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# OpenMetrics / Prometheus text exposition

_METRIC_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(name: str) -> str:
    return "ctt_" + _METRIC_NAME_RE.sub("_", name)


def _escape_label(value: Any) -> str:
    return (
        str(value).replace("\\", r"\\").replace('"', r'\"')
        .replace("\n", r"\n")
    )


def _fmt_value(value: float) -> str:
    return repr(float(value))


def render_openmetrics(snap: Dict[str, Any]) -> str:
    """OpenMetrics 1.0 text exposition of a poll snapshot: every obs
    counter (as ``ctt_<name>_total``) and numeric gauge, plus
    heartbeat-derived per-worker and per-task gauges.  Ends with the
    mandatory ``# EOF``."""
    lines: List[str] = []
    families: set = set()

    def family(name: str, mtype: str, help_text: str) -> str:
        # one TYPE line per family; counters whose raw name already ends
        # in _total keep one suffix only
        if mtype == "counter" and name.endswith("_total"):
            name = name[: -len("_total")]
        while name in families:
            name += "_"
        families.add(name)
        lines.append(f"# TYPE {name} {mtype}")
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        return name

    merged_counters: Dict[str, float] = {}
    for raw, val in snap.get("counters", {}).items():
        name = _metric_name(raw)
        if name.endswith("_total"):
            name = name[: -len("_total")]
        merged_counters[name] = merged_counters.get(name, 0.0) + float(val)
    for name in sorted(merged_counters):
        fam = family(name, "counter", "")
        lines.append(f"{fam}_total {_fmt_value(merged_counters[name])}")

    for raw in sorted(snap.get("gauges", {})):
        val = snap["gauges"][raw]
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        fam = family(_metric_name(raw), "gauge", "")
        lines.append(f"{fam} {_fmt_value(val)}")

    # ctt-slo latency histograms (``_bucket``/``_sum``/``_count``);
    # empty when the run recorded none — the exposition is then
    # byte-identical to the pre-slo output
    lines.extend(hist_mod.render_openmetrics(snap.get("hists") or {}))

    workers = snap.get("workers", [])
    if workers:
        specs = [
            ("ctt_worker_up", "gauge",
             "1 while the process heartbeats, 0 when stale or exited",
             lambda w: 0.0 if (w["stale"] or w["exiting"]) else 1.0),
            ("ctt_worker_stale", "gauge",
             "1 when the last heartbeat is older than 3x its cadence",
             lambda w: 1.0 if w["stale"] else 0.0),
            ("ctt_worker_heartbeat_age_seconds", "gauge", "",
             lambda w: w["age_s"]),
            ("ctt_worker_blocks_done", "gauge", "",
             lambda w: float(w["blocks_done"])),
            ("ctt_worker_blocks_total", "gauge", "",
             lambda w: float(w["blocks_total"])),
            ("ctt_worker_blocks_failed", "gauge", "",
             lambda w: float(w["blocks_failed"])),
            ("ctt_worker_in_flight_blocks", "gauge", "",
             lambda w: float(len(w["current_blocks"]))),
            ("ctt_worker_device_mem_peak_bytes", "gauge", "",
             lambda w: (float(w["device_mem_peak_bytes"])
                        if w["device_mem_peak_bytes"] is not None else None)),
            ("ctt_worker_queue_depth", "gauge",
             "unclaimed work-queue items at the worker's last pull (ctt-steal)",
             lambda w: (float(w["queue_depth"])
                        if w.get("queue_depth") is not None else None)),
            # only emitted for processes that ever raised the flag, so
            # non-serve expositions are byte-unchanged
            ("ctt_worker_draining", "gauge",
             "1 while a serve daemon drains (alive, refusing submissions)",
             lambda w: 1.0 if w.get("draining") else None),
        ]
        for name, mtype, help_text, fn in specs:
            rows = []
            for w in workers:
                val = fn(w)
                if val is None:
                    continue
                labels = (
                    f'pid="{_escape_label(w["pid"])}",'
                    f'role="{_escape_label(w["role"])}"'
                )
                if w["job_id"] is not None:
                    labels += f',job="{_escape_label(w["job_id"])}"'
                rows.append(f"{name}{{{labels}}} {_fmt_value(val)}")
            if rows:
                family(name, mtype, help_text)
                lines.extend(rows)

    tasks = snap.get("tasks", {})
    if tasks:
        tspecs = [
            ("ctt_task_blocks_done", "", lambda r: float(r["blocks_done"])),
            ("ctt_task_blocks_total", "",
             lambda r: (float(r["blocks_total"])
                        if r["blocks_total"] is not None else None)),
            ("ctt_task_blocks_failed", "",
             lambda r: float(r["blocks_failed"])),
            ("ctt_task_throughput_blocks_per_second", "",
             lambda r: r["throughput_bps"]),
            ("ctt_task_eta_seconds", "estimated seconds to completion",
             lambda r: r["eta_s"]),
            ("ctt_task_stragglers", "in-flight blocks beyond k x median",
             lambda r: float(len(r["stragglers"]))),
            ("ctt_task_complete", "",
             lambda r: 1.0 if r["complete"] else 0.0),
        ]
        for name, help_text, fn in tspecs:
            rows = []
            for tname in sorted(tasks):
                val = fn(tasks[tname])
                if val is None:
                    continue
                rows.append(
                    f'{name}{{task="{_escape_label(tname)}"}} '
                    f"{_fmt_value(val)}"
                )
            if rows:
                family(name, "gauge", help_text)
                lines.extend(rows)

    fam = family("ctt_watch_malformed_lines", "gauge",
                 "complete-but-unparsable shard lines skipped by the tailer")
    lines.append(f"{fam} {_fmt_value(snap.get('malformed_lines', 0))}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"
