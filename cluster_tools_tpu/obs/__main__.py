"""CLI: ``python -m cluster_tools_tpu.obs`` — summarize / trace / diff.

    python -m cluster_tools_tpu.obs summarize <run_dir> [--json]
    python -m cluster_tools_tpu.obs trace <run_dir> [-o trace.json]
    python -m cluster_tools_tpu.obs diff <base_run> <cand_run> \
        [--threshold 0.2] [--min-s 0.01] [--json]

``<run_dir>`` is either ``<CTT_TRACE_DIR>/<run_id>`` or a trace dir
containing exactly one run.  Exit codes:

  0  success (summarize: at least one task span; diff: no regression)
  1  summarize found no task spans (a run that recorded nothing is a CI
     failure, not a silent pass)
  2  malformed trace (truncated/corrupt shard, mixed runs, bad metrics)
  3  diff found at least one task regressed beyond the threshold
"""

from __future__ import annotations

import argparse
import json
import sys

from .export import (
    TraceFormatError,
    diff,
    format_diff,
    format_summary,
    load_run,
    summarize,
    to_chrome_trace,
)

EXIT_OK = 0
EXIT_NO_TASKS = 1
EXIT_MALFORMED = 2
EXIT_REGRESSED = 3


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cluster_tools_tpu.obs",
        description="ctt-obs: merge, summarize, export, and diff "
        "structured run traces",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_sum = sub.add_parser(
        "summarize", help="per-task host-IO/device/collective breakdown"
    )
    p_sum.add_argument("run")
    p_sum.add_argument("--json", action="store_true",
                       help="machine-readable output")

    p_trace = sub.add_parser(
        "trace", help="export Chrome trace_event JSON (Perfetto-loadable)"
    )
    p_trace.add_argument("run")
    p_trace.add_argument("-o", "--output", default=None,
                         help="output path (default: stdout)")

    p_diff = sub.add_parser(
        "diff", help="compare two runs; nonzero exit on regression"
    )
    p_diff.add_argument("base")
    p_diff.add_argument("candidate")
    p_diff.add_argument("--threshold", type=float, default=0.2,
                        help="fractional wall-clock growth that counts as "
                        "a regression (default 0.2 = 20%%)")
    p_diff.add_argument("--min-s", type=float, default=0.01,
                        help="absolute floor in seconds below which growth "
                        "is jitter, not regression (default 0.01)")
    p_diff.add_argument("--json", action="store_true")

    args = parser.parse_args(argv)
    try:
        if args.cmd == "summarize":
            summary = summarize(load_run(args.run))
            if args.json:
                print(json.dumps(summary, indent=2, sort_keys=True))
            else:
                print(format_summary(summary))
            if summary["n_task_spans"] < 1:
                print("obs: no task spans recorded", file=sys.stderr)
                return EXIT_NO_TASKS
            return EXIT_OK
        if args.cmd == "trace":
            chrome = to_chrome_trace(load_run(args.run))
            payload = json.dumps(chrome)
            if args.output:
                with open(args.output, "w") as f:
                    f.write(payload)
                print(f"wrote {len(chrome['traceEvents'])} events to "
                      f"{args.output}", file=sys.stderr)
            else:
                print(payload)
            return EXIT_OK
        if args.cmd == "diff":
            result = diff(
                load_run(args.base), load_run(args.candidate),
                threshold=args.threshold, min_seconds=args.min_s,
            )
            if args.json:
                print(json.dumps(result, indent=2, sort_keys=True))
            else:
                print(format_diff(result))
            return EXIT_REGRESSED if result["n_regressed"] else EXIT_OK
    except TraceFormatError as e:
        print(f"obs: malformed trace: {e}", file=sys.stderr)
        return EXIT_MALFORMED
    except OSError as e:
        print(f"obs: {e}", file=sys.stderr)
        return EXIT_MALFORMED
    raise AssertionError(f"unhandled command {args.cmd}")


if __name__ == "__main__":
    sys.exit(main())
