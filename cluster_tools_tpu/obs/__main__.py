"""CLI: ``python -m cluster_tools_tpu.obs`` — post-mortem and live verbs.

Post-mortem (strict: malformed traces fail loudly):

    python -m cluster_tools_tpu.obs summarize <run_dir> [--json]
    python -m cluster_tools_tpu.obs trace <run_dir> [-o trace.json]
    python -m cluster_tools_tpu.obs diff <base_run> <cand_run> \
        [--threshold 0.2] [--min-s 0.01] [--json]

Live (ctt-watch: incremental, tolerant of in-flight writes):

    python -m cluster_tools_tpu.obs watch <run_dir> [--once]
        [--interval S] [--fail-on-stall] [--straggler-k K] [--json]
    python -m cluster_tools_tpu.obs heatmap <run_dir> [--task NAME]
    python -m cluster_tools_tpu.obs prom <run_dir>

Request-grain (ctt-slo: serve state dirs, POSIX or object-store):

    python -m cluster_tools_tpu.obs journey <state_dir> <job_id> [--json]
    python -m cluster_tools_tpu.obs fleet <state_dir>
    python -m cluster_tools_tpu.obs slo <dir> --objective SPEC [...]
        [--fail-on-violation] [--json]

``<run_dir>`` is either ``<CTT_TRACE_DIR>/<run_id>`` or a trace dir
containing exactly one run.  Exit codes:

  0  success (summarize: at least one task span; diff: no regression;
     watch: block/task progress observed and no stall flagged;
     journey: timeline rendered; fleet: rollup emitted; slo: every
     objective judged against data and none violated)
  1  nothing recorded (summarize: no task spans; watch --once: no
     progress; heatmap: no finished blocks; prom: no run directory;
     journey: no such job; fleet: no daemon snapshots; slo: an
     objective matched no data)
  2  malformed trace (truncated/corrupt shard, mixed runs, bad metrics,
     a bad --objective spec, or foreign histogram bucket edges)
  3  diff found at least one task regressed beyond the threshold
  4  watch --fail-on-stall flagged a stale worker (heartbeat older than
     3x its cadence: suspected dead before the deadline watchdog
     fires); slo --fail-on-violation found an objective violated
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .export import (
    TraceFormatError,
    diff,
    format_diff,
    format_summary,
    load_run,
    summarize,
    to_chrome_trace,
)

EXIT_OK = 0
EXIT_NO_TASKS = 1
EXIT_MALFORMED = 2
EXIT_REGRESSED = 3
EXIT_STALLED = 4


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cluster_tools_tpu.obs",
        description="ctt-obs: merge, summarize, export, and diff "
        "structured run traces; ctt-watch: live watch/heatmap/prom",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_sum = sub.add_parser(
        "summarize", help="per-task host-IO/device/collective breakdown"
    )
    p_sum.add_argument("run")
    p_sum.add_argument("--json", action="store_true",
                       help="machine-readable output")

    p_trace = sub.add_parser(
        "trace", help="export Chrome trace_event JSON (Perfetto-loadable)"
    )
    p_trace.add_argument("run")
    p_trace.add_argument("-o", "--output", default=None,
                         help="output path (default: stdout)")

    p_diff = sub.add_parser(
        "diff", help="compare two runs; nonzero exit on regression"
    )
    p_diff.add_argument("base")
    p_diff.add_argument("candidate")
    p_diff.add_argument("--threshold", type=float, default=0.2,
                        help="fractional wall-clock growth that counts as "
                        "a regression (default 0.2 = 20%%)")
    p_diff.add_argument("--min-s", type=float, default=0.01,
                        help="absolute floor in seconds below which growth "
                        "is jitter, not regression (default 0.01)")
    p_diff.add_argument("--json", action="store_true")

    p_watch = sub.add_parser(
        "watch", help="live progress/ETA/straggler report (ctt-watch)"
    )
    p_watch.add_argument("run")
    p_watch.add_argument("--once", action="store_true",
                         help="one poll + report, then exit (CI mode)")
    p_watch.add_argument("--interval", type=float, default=5.0,
                         help="poll cadence in seconds (default 5)")
    p_watch.add_argument("--fail-on-stall", action="store_true",
                         help="exit 4 as soon as a stale worker is flagged")
    p_watch.add_argument("--straggler-k", type=float, default=4.0,
                         help="flag in-flight blocks older than K x the "
                         "median completed block duration (default 4)")
    p_watch.add_argument("--json", action="store_true",
                         help="one JSON snapshot object per poll")

    p_heat = sub.add_parser(
        "heatmap", help="z-slab text heatmap of per-block durations"
    )
    p_heat.add_argument("run")
    p_heat.add_argument("--task", default=None,
                        help="task identifier (default: most blocks done)")

    p_prom = sub.add_parser(
        "prom", help="OpenMetrics/Prometheus text exposition of the run"
    )
    p_prom.add_argument("run")

    p_journey = sub.add_parser(
        "journey", help="per-job phase timeline from serve state-dir "
        "records (failover-aware, purely post-hoc)"
    )
    p_journey.add_argument("state_dir")
    p_journey.add_argument("job_id")
    p_journey.add_argument("--json", action="store_true")

    p_fleet = sub.add_parser(
        "fleet", help="fleet-wide OpenMetrics rollup of every daemon's "
        "snap.<id>.json (counters summed, histograms exactly merged)"
    )
    p_fleet.add_argument("state_dir")

    p_slo = sub.add_parser(
        "slo", help="gate latency objectives against merged histograms "
        "(exit 0 met / 1 no data / 4 violated with --fail-on-violation)"
    )
    p_slo.add_argument("dir")
    p_slo.add_argument("--objective", action="append", required=True,
                       metavar="PHASE_pNN_s=SECONDS[@label=value,...]",
                       help="e.g. e2e_p99_s=2.0@priority=5 (repeatable)")
    p_slo.add_argument("--fail-on-violation", action="store_true",
                       help="exit 4 when any objective is violated")
    p_slo.add_argument("--json", action="store_true")

    args = parser.parse_args(argv)
    if args.cmd in ("watch", "heatmap", "prom"):
        return _live_main(args)
    if args.cmd in ("journey", "fleet", "slo"):
        return _slo_main(args)
    try:
        if args.cmd == "summarize":
            summary = summarize(load_run(args.run))
            if args.json:
                print(json.dumps(summary, indent=2, sort_keys=True))
            else:
                print(format_summary(summary))
            if summary["n_task_spans"] < 1:
                print("obs: no task spans recorded", file=sys.stderr)
                return EXIT_NO_TASKS
            return EXIT_OK
        if args.cmd == "trace":
            chrome = to_chrome_trace(load_run(args.run))
            payload = json.dumps(chrome)
            if args.output:
                with open(args.output, "w") as f:
                    f.write(payload)
                print(f"wrote {len(chrome['traceEvents'])} events to "
                      f"{args.output}", file=sys.stderr)
            else:
                print(payload)
            return EXIT_OK
        if args.cmd == "diff":
            result = diff(
                load_run(args.base), load_run(args.candidate),
                threshold=args.threshold, min_seconds=args.min_s,
            )
            if args.json:
                print(json.dumps(result, indent=2, sort_keys=True))
            else:
                print(format_diff(result))
            return EXIT_REGRESSED if result["n_regressed"] else EXIT_OK
    except TraceFormatError as e:
        print(f"obs: malformed trace: {e}", file=sys.stderr)
        return EXIT_MALFORMED
    except OSError as e:
        print(f"obs: {e}", file=sys.stderr)
        return EXIT_MALFORMED
    raise AssertionError(f"unhandled command {args.cmd}")


def _slo_main(args) -> int:
    from . import journey as journey_mod
    from . import slo as slo_mod

    try:
        if args.cmd == "journey":
            j = journey_mod.load_journey(args.state_dir, args.job_id)
            if j is None:
                print(f"obs: no job {args.job_id} under {args.state_dir}",
                      file=sys.stderr)
                return EXIT_NO_TASKS
            if args.json:
                print(json.dumps(j, indent=2, sort_keys=True))
            else:
                print(journey_mod.format_journey(j))
            return EXIT_OK
        if args.cmd == "fleet":
            merged = slo_mod.load_fleet(args.state_dir)
            if not merged["daemons"]:
                print(f"obs: no daemon snapshots under {args.state_dir}",
                      file=sys.stderr)
                return EXIT_NO_TASKS
            print(slo_mod.render_fleet(merged), end="")
            return EXIT_OK
        if args.cmd == "slo":
            objectives = [slo_mod.parse_objective(s)
                          for s in args.objective]
            hists = slo_mod.load_hists_any(args.dir)
            rows = slo_mod.evaluate(hists, objectives)
            if args.json:
                print(json.dumps(rows, indent=2, sort_keys=True))
            else:
                print(slo_mod.format_report(rows))
            # contract: violated (4) outranks no-data (1) outranks met (0);
            # without --fail-on-violation a violation only reports
            if args.fail_on_violation and any(
                r["status"] == "violated" for r in rows
            ):
                return EXIT_STALLED
            if any(r["status"] == "no_data" for r in rows):
                return EXIT_NO_TASKS
            return EXIT_OK
    except ValueError as e:
        # bad --objective spec or foreign histogram edges (version skew)
        print(f"obs: {e}", file=sys.stderr)
        return EXIT_MALFORMED
    except OSError as e:
        print(f"obs: {e}", file=sys.stderr)
        return EXIT_MALFORMED
    raise AssertionError(f"unhandled command {args.cmd}")


def _watch_exit_code(snap, fail_on_stall: bool) -> int:
    if fail_on_stall and snap["n_stale"] > 0:
        return EXIT_STALLED
    return EXIT_OK if snap["progress"] else EXIT_NO_TASKS


def _live_main(args) -> int:
    from .live import (
        LiveRun,
        format_heatmap,
        format_watch,
        render_openmetrics,
        resolve_live_dir,
    )

    run_dir = resolve_live_dir(args.run)
    if args.cmd == "prom":
        if run_dir is None:
            print(f"obs: no run telemetry under {args.run}", file=sys.stderr)
            return EXIT_NO_TASKS
        print(render_openmetrics(LiveRun(run_dir).poll()), end="")
        return EXIT_OK

    if args.cmd == "heatmap":
        if run_dir is None:
            print(f"obs: no run telemetry under {args.run}", file=sys.stderr)
            return EXIT_NO_TASKS
        live = LiveRun(run_dir)
        live.poll()
        hm = live.heatmap(task=args.task)
        if hm is None:
            print("obs: no finished blocks to map yet", file=sys.stderr)
            return EXIT_NO_TASKS
        print(format_heatmap(hm))
        return EXIT_OK

    # watch: poll until progress settles (or forever without --once)
    live = None
    while True:
        if run_dir is None:
            run_dir = resolve_live_dir(args.run)
        if run_dir is not None and live is None:
            live = LiveRun(run_dir, straggler_k=args.straggler_k)
        if live is None:
            if args.once:
                print(f"obs: no run telemetry under {args.run}",
                      file=sys.stderr)
                return EXIT_NO_TASKS
            print(f"waiting for telemetry under {args.run} ...",
                  file=sys.stderr)
        else:
            snap = live.poll()
            if args.json:
                print(json.dumps(snap, sort_keys=True))
            else:
                print(format_watch(snap))
            sys.stdout.flush()
            rc = _watch_exit_code(snap, args.fail_on_stall)
            if args.once:
                return rc
            if rc == EXIT_STALLED:
                return rc
            # a finished run: every heartbeat says exiting and >= 1 task
            # completed — stop polling a corpse
            workers = snap["workers"]
            if (
                workers
                and all(w["exiting"] for w in workers)
                and any(r["complete"] for r in snap["tasks"].values())
            ):
                return EXIT_OK
        try:
            time.sleep(max(args.interval, 0.05))  # ctt: noqa[CTT009] poll cadence, not an IO retry — nothing here is retried
        except KeyboardInterrupt:  # pragma: no cover - interactive only
            return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
