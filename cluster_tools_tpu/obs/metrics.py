"""ctt-obs counters and gauges: cheap aggregates for hot paths.

Spans (obs.trace) are the right tool for intervals; per-chunk store IO is
far too hot for a JSONL line per operation.  These process-local counters
cost one enabled-check + one dict update per call and flush as ONE
``metrics.p<pid>.json`` snapshot per process into the active run's
directory (atomic tmp+replace, the store convention), where
``obs.export`` sums them across processes.

Wired in:

  * ``utils/store.py`` — ``store.bytes_read`` / ``store.bytes_written`` /
    ``store.chunks_read`` / ``store.chunks_written`` (chunk payload sizes
    at the codec boundary: what actually crossed the filesystem);
    ``store.chunk_cache_hits`` / ``store.chunk_cache_misses`` (the decoded-
    chunk LRU: hits are decodes the cache absorbed, e.g. overlapping halo
    reads) and ``store.aligned_chunk_writes`` (region writes that took the
    chunk-aligned encode fast path instead of read-modify-write);
  * ``utils/compile_cache.py`` — ``compile_cache.cache_hits`` /
    ``compile_cache.cache_misses`` via a ``jax.monitoring`` event
    listener, plus an ``entries_at_enable`` gauge;
  * ``runtime/task.py`` — ``task.blocks_failed`` / ``task.blocks_retried``;
  * ``faults/`` + the resilience paths it validates (ctt-fault) —
    ``faults.injected`` / ``faults.injected.<site>`` (every fired
    injection), ``store.io_retries`` (backoff sleeps absorbed by
    ``utils/retry.py`` on transient chunk IO), ``executor.blocks_timed_out``
    (blocks the soft-deadline watchdog converted into failures), and
    ``sharded.fallback_local`` (collective→local kernel degradations) —
    so a chaos run's injections AND recoveries are diffable with
    ``obs diff``;
  * ``runtime/executor.py`` — ``executor.batches`` /
    ``executor.batch_s`` (summed in-flight batch seconds) /
    ``executor.dispatch_wall_s`` (wall of the whole dispatch round):
    ``batch_s - dispatch_wall_s > 0`` is host IO hidden behind device
    execution by the pipeline (depth > 1).  The three-stage pipeline
    (split-protocol tasks at depth > 1) additionally reports per-stage
    occupancy — ``executor.stage_read_s`` / ``executor.stage_compute_s`` /
    ``executor.stage_write_s`` / ``executor.stage_batches`` — and
    ``executor.stage_hidden_io_s``, the read+write seconds hidden behind
    the serialized compute stage.

  * ``runtime/stream.py`` (ctt-stream) — ``stream.chains`` /
    ``stream.slabs`` / ``stream.elided_bytes`` (intermediate bytes that
    never reached the store) / ``stream.fallbacks`` plus the
    ``stream.carry_bytes`` peak gauge: how much a fused chain streamed,
    elided, and carried.

Enabled exactly when tracing is enabled (one switch: CTT_TRACE_DIR).

Naming: every counter/gauge name is listed in :mod:`obs.registry`
(dynamic families like ``faults.injected.<site>`` by prefix) and lint
rule CTT010 flags literals absent from it — a typo'd name would
otherwise silently create a series nothing reads.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict

from . import trace

__all__ = [
    "inc", "set_gauge", "snapshot", "flush",
    "install_compile_cache_listener", "reset",
]

_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {}
_GAUGES: Dict[str, Any] = {}

METRICS_FILE_PREFIX = "metrics.p"


def inc(name: str, value: float = 1.0) -> None:
    if not trace.enabled():
        return
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0.0) + value


def set_gauge(name: str, value: Any) -> None:
    if not trace.enabled():
        return
    with _LOCK:
        _GAUGES[name] = value


def snapshot() -> Dict[str, Any]:
    with _LOCK:
        return {"counters": dict(_COUNTERS), "gauges": dict(_GAUGES)}


def reset() -> None:
    """Drop all accumulated values (test isolation helper)."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()


def flush() -> None:
    """Write this process's snapshot into the active run directory.
    Atomic (tmp + os.replace); repeated flushes overwrite with the latest
    totals, so the last write per process wins."""
    rdir = trace.run_dir()
    if rdir is None:
        return
    snap = snapshot()
    if not snap["counters"] and not snap["gauges"]:
        return
    os.makedirs(rdir, exist_ok=True)
    path = os.path.join(rdir, f"{METRICS_FILE_PREFIX}{os.getpid()}.json")
    tmp = path + f".tmp{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# jax compile-cache hit/miss listener

_CACHE_LISTENER_INSTALLED = False

# jax.monitoring event names emitted by the persistent compilation cache
_CACHE_EVENTS = {
    "/jax/compilation_cache/cache_hits": "compile_cache.cache_hits",
    "/jax/compilation_cache/cache_misses": "compile_cache.cache_misses",
    "/jax/compilation_cache/tasks_using_cache": "compile_cache.tasks_using_cache",
}


def install_compile_cache_listener() -> bool:
    """Count persistent-compile-cache hits/misses via ``jax.monitoring``
    (idempotent).  Returns False when the monitoring API is unavailable —
    the cache keeps working, only the metric is absent."""
    global _CACHE_LISTENER_INSTALLED
    if _CACHE_LISTENER_INSTALLED:
        return True
    try:
        from jax import monitoring
    except ImportError:  # pragma: no cover - jax is baked into the image
        return False

    def _listener(event: str, **kwargs) -> None:
        name = _CACHE_EVENTS.get(event)
        if name is not None:
            inc(name)

    try:
        monitoring.register_event_listener(_listener)
    except Exception:  # pragma: no cover - API drift must not break callers
        return False
    _CACHE_LISTENER_INSTALLED = True
    return True
