"""ctt-slo fleet rollup and SLO gate over merged latency histograms.

Every serve daemon publishes ``snap.<daemon_id>.json`` into the shared
state dir on its fleet-beat cadence: its counters, gauges, and latency
histograms (:mod:`obs.hist`).  Because every histogram uses the SAME
fixed bucket edges, the fleet-wide rollup is *exact* — bucket-wise
integer addition — so a percentile computed here equals the percentile
a single process observing every request would have computed (to bucket
resolution).  Two verbs ride that exactness:

  * ``obs fleet <state_dir>`` — merge every daemon's snapshot into one
    OpenMetrics exposition: counters summed, gauges last-writer in
    sorted-daemon order (deterministic), histogram families in
    ``_bucket``/``_sum``/``_count`` form, plus derived
    ``ctt_fleet_latency_p50_seconds`` / ``ctt_fleet_latency_p99_seconds``
    gauges labeled ``phase``/``tenant``/``priority``.
  * ``obs slo <dir> --objective e2e_p99_s=2.0@priority=5`` — evaluate
    declared objectives against the merged histograms with a CI
    exit-code contract (0 met / 1 no data / 4 violated, the violation
    code gated behind ``--fail-on-violation``).

Objective grammar: ``<phase>_p<NN>_s=<seconds>[@label=value[,...]]``
where ``<phase>`` is one of the serve latency phases (``admission``,
``queue_wait``, ``window_wait``, ``execution``, ``publish``, ``e2e``)
and ``p<NN>`` maps digits to a quantile (``p50`` = 0.50, ``p99`` = 0.99,
``p999`` = 0.999).  Label constraints select series; series matching the
constraint are aggregated bucket-wise before the quantile is taken, so
``e2e_p99_s=2.0`` with no labels gates the whole fleet across every
tenant and priority class.

``<dir>`` resolution: a serve state dir (``snap.*.json`` fleet
snapshots) or a trace run dir (``hist.p*.json`` per-process snapshots)
— both merge exactly.  Dirs route through the store backend, so an
``http(s)://`` object-store prefix works too (listing rides the
paginated continuation GETs).
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional

from ..utils.store_backend import backend_for
from . import hist as hist_mod

__all__ = [
    "SNAP_RE", "PHASES", "load_fleet", "merge_fleet", "load_hists_any",
    "render_fleet", "parse_objective", "evaluate", "format_report",
]

# matches serve/server.py _publish_snapshot (daemon ids are _ID_SAFE_RE)
SNAP_RE = re.compile(r"^snap\.([A-Za-z0-9_.-]+)\.json$")

PHASES = (
    "admission", "queue_wait", "window_wait", "execution", "publish", "e2e",
)
_LATENCY_PREFIX = "serve.latency."

_OBJ_RE = re.compile(
    r"^([a-z0-9_]+)_p(\d+)_s=([0-9eE.+-]+)(?:@(.+))?$"
)

_METRIC_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _read_json(backend, path: str) -> Optional[dict]:
    try:
        rec = json.loads(backend.read_bytes(path).decode())
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None  # torn snapshot: the daemon's next beat replaces it


def _read_snaps(backend, state_dir: str) -> List[dict]:
    try:
        names = backend.listdir(state_dir)
    except OSError:
        names = []
    snaps = []
    for fn in sorted(names):
        if SNAP_RE.match(fn):
            rec = _read_json(backend, backend.join(state_dir, fn))
            if rec is not None:
                snaps.append(rec)
    return snaps


def merge_fleet(snaps: List[dict]) -> Dict[str, Any]:
    """Merge daemon snapshots: counters summed, gauges last-writer in
    sorted-daemon order (deterministic regardless of listing order),
    histograms bucket-wise (exact — the fixed-edges contract).  A
    snapshot with foreign bucket edges raises ValueError: version skew
    must fail the rollup loudly, not approximate it."""
    ordered = sorted(snaps, key=lambda r: str(r.get("daemon", "")))
    daemons: List[str] = []
    counters: Dict[str, float] = {}
    gauges: Dict[str, Any] = {}
    hist_snaps: List[dict] = []
    for rec in ordered:
        daemons.append(str(rec.get("daemon", "?")))
        for k, v in (rec.get("counters") or {}).items():
            try:
                counters[k] = counters.get(k, 0.0) + float(v)
            except (TypeError, ValueError):
                continue
        gauges.update(rec.get("gauges") or {})
        if isinstance(rec.get("hists"), dict):
            hist_snaps.append(rec["hists"])
    return {
        "daemons": daemons,
        "counters": counters,
        "gauges": gauges,
        "hists": hist_mod.merge_snapshots(hist_snaps),
    }


def load_fleet(state_dir: str) -> Dict[str, Any]:
    """Merge every ``snap.<daemon_id>.json`` under a serve state dir."""
    return merge_fleet(_read_snaps(backend_for(state_dir), state_dir))


def load_hists_any(path: str) -> Dict[str, Any]:
    """Merged histogram snapshot from either source: fleet snapshots
    (``snap.*.json``, a serve state dir) when present, else per-process
    histogram files (``hist.p*.json``, a trace run dir)."""
    backend = backend_for(path)
    snaps = _read_snaps(backend, path)
    if snaps:
        return merge_fleet(snaps)["hists"]
    try:
        names = backend.listdir(path)
    except OSError:
        names = []
    hist_snaps = []
    for fn in sorted(names):
        if fn.startswith(hist_mod.HIST_FILE_PREFIX) and fn.endswith(".json"):
            rec = _read_json(backend, backend.join(path, fn))
            if rec is not None:
                hist_snaps.append(rec)
    return hist_mod.merge_snapshots(hist_snaps)


# ---------------------------------------------------------------------------
# fleet exposition


def _metric_name(raw: str) -> str:
    return "ctt_" + _METRIC_NAME_RE.sub("_", raw)


def _escape_label(value: Any) -> str:
    return (
        str(value).replace("\\", r"\\").replace('"', r'\"')
        .replace("\n", r"\n")
    )


def _label_str(labels: Dict[str, str]) -> str:
    parts = []
    for k in sorted(labels):
        escaped = _escape_label(labels[k])
        parts.append('%s="%s"' % (k, escaped))
    return "{" + ",".join(parts) + "}" if parts else ""


def render_fleet(merged: Dict[str, Any]) -> str:
    """OpenMetrics 1.0 text exposition of the fleet rollup: summed
    counters (``ctt_<name>_total``), last-writer gauges, exact histogram
    families, derived per-series p50/p99 latency gauges, and a
    ``ctt_fleet_daemons`` gauge.  Ends with the mandatory ``# EOF``."""
    lines: List[str] = []

    folded: Dict[str, float] = {}
    for raw, val in merged.get("counters", {}).items():
        name = _metric_name(raw)
        if name.endswith("_total"):
            name = name[: -len("_total")]
        folded[name] = folded.get(name, 0.0) + float(val)
    for name in sorted(folded):
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name}_total {repr(folded[name])}")

    for raw in sorted(merged.get("gauges", {})):
        val = merged["gauges"][raw]
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        name = _metric_name(raw)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {repr(float(val))}")

    hists = merged.get("hists") or {}
    lines.extend(hist_mod.render_openmetrics(hists))

    # derived fleet percentiles: one gauge sample per latency series,
    # labeled by phase + the series' own labels (tenant, priority)
    for fam, q in (("ctt_fleet_latency_p50_seconds", 0.50),
                   ("ctt_fleet_latency_p99_seconds", 0.99)):
        rows = []
        for s in hists.get("hists", []):
            name = str(s.get("name", ""))
            if not name.startswith(_LATENCY_PREFIX):
                continue
            val = hist_mod.quantile(list(s["buckets"]), q)
            if val is None:
                continue
            labels = {str(k): str(v)
                      for k, v in (s.get("labels") or {}).items()}
            labels["phase"] = name[len(_LATENCY_PREFIX):]
            rows.append(f"{fam}{_label_str(labels)} {repr(float(val))}")
        if rows:
            lines.append(f"# TYPE {fam} gauge")
            lines.append(f"# HELP {fam} fleet-wide latency quantile from "
                         "exactly-merged histograms")
            lines.extend(sorted(rows))

    lines.append("# TYPE ctt_fleet_daemons gauge")
    lines.append("# HELP ctt_fleet_daemons daemon snapshots merged into "
                 "this rollup")
    lines.append(f"ctt_fleet_daemons {repr(float(len(merged.get('daemons', []))))}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# objectives


def parse_objective(spec: str) -> Dict[str, Any]:
    """Parse ``<phase>_p<NN>_s=<seconds>[@label=value,...]``; raises
    ValueError with the expected grammar on any malformed spec."""
    m = _OBJ_RE.match(spec.strip())
    if not m:
        raise ValueError(
            f"bad objective {spec!r}: expected "
            "<phase>_p<NN>_s=<seconds>[@label=value,...] "
            "(e.g. e2e_p99_s=2.0@priority=5)"
        )
    phase, digits, threshold, labelpart = m.groups()
    if phase not in PHASES:
        raise ValueError(
            f"bad objective {spec!r}: unknown phase {phase!r} "
            f"(one of {', '.join(PHASES)})"
        )
    q = int(digits) / (10 ** len(digits))
    if not 0.0 < q < 1.0:
        raise ValueError(f"bad objective {spec!r}: p{digits} is not a "
                         "quantile in (0, 1)")
    try:
        threshold_s = float(threshold)
    except ValueError:
        raise ValueError(
            f"bad objective {spec!r}: threshold {threshold!r} is not a "
            "number"
        ) from None
    labels: Dict[str, str] = {}
    if labelpart:
        for pair in labelpart.split(","):
            if "=" not in pair:
                raise ValueError(
                    f"bad objective {spec!r}: label constraint {pair!r} "
                    "is not label=value"
                )
            k, v = pair.split("=", 1)
            labels[k.strip()] = v.strip()
    return {
        "spec": spec,
        "phase": phase,
        "pname": f"p{digits}",
        "quantile": q,
        "threshold_s": threshold_s,
        "labels": labels,
    }


def evaluate(hists: Dict[str, Any],
             objectives: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Judge each objective against the merged histogram snapshot.
    Series matching the objective's label constraints aggregate
    bucket-wise (exact) before the quantile; a row's ``status`` is
    ``met`` / ``violated`` / ``no_data``."""
    series = hists.get("hists") or []
    rows = []
    for obj in objectives:
        name = _LATENCY_PREFIX + obj["phase"]
        acc: Optional[List[int]] = None
        count = 0
        for s in series:
            if s.get("name") != name:
                continue
            labels = {str(k): str(v)
                      for k, v in (s.get("labels") or {}).items()}
            if any(labels.get(k) != str(v)
                   for k, v in obj["labels"].items()):
                continue
            buckets = list(s["buckets"])
            if acc is None:
                acc = [0] * len(buckets)
            for i, c in enumerate(buckets[: len(acc)]):
                acc[i] += int(c)
            count += int(s.get("count", 0))
        value = hist_mod.quantile(acc, obj["quantile"]) if acc else None
        if value is None:
            status = "no_data"
        elif value <= obj["threshold_s"]:
            status = "met"
        else:
            status = "violated"
        rows.append({**obj, "value_s": value, "count": count,
                     "status": status})
    return rows


def format_report(rows: List[Dict[str, Any]]) -> str:
    lines = []
    for r in rows:
        if r["status"] == "no_data":
            lines.append(f"slo {r['spec']}: NO DATA (no matching series)")
            continue
        verdict = "MET" if r["status"] == "met" else "VIOLATED"
        lines.append(
            f"slo {r['spec']}: {r['pname']}="
            f"{r['value_s']:.6f}s over {r['count']} request(s) "
            f"(threshold {r['threshold_s']:.6f}s) {verdict}"
        )
    n = len(rows)
    met = sum(1 for r in rows if r["status"] == "met")
    violated = sum(1 for r in rows if r["status"] == "violated")
    nodata = n - met - violated
    lines.append(
        f"{n} objective(s): {met} met, {violated} violated, "
        f"{nodata} without data"
    )
    return "\n".join(lines)
