"""ctt-obs metric-name registry: the canonical list of series names.

Counters and gauges are stringly-typed at the call site
(``metrics.inc("store.bytes_read")``) — a typo there does not fail, it
silently creates a fresh series that no dashboard, bench contract, or
``obs diff`` ever looks at.  This module is the single source of truth:

  * every known counter/gauge name, grouped by owning subsystem;
  * the allowed *dynamic* prefixes (``faults.injected.<site>`` is one
    series per injection site by design);
  * lint rule CTT010 (analysis/ast_rules.py) flags any
    ``metrics.inc``/``set_gauge`` call whose literal name is not listed
    here, so adding a metric means adding it to this registry — which is
    exactly where README/COMPONENTS readers go looking for it.

The live exporter (obs.live ``prom``) exposes whatever a run actually
recorded; this registry is a *lint* namespace, not a runtime filter —
dynamic names and future names degrade to "unknown series", never to
dropped data.
"""

from __future__ import annotations

__all__ = ["COUNTERS", "GAUGES", "HISTOGRAMS", "DYNAMIC_PREFIXES",
           "is_known_counter", "is_known_gauge", "is_known_histogram"]

# -- counters (metrics.inc) -------------------------------------------------

COUNTERS = frozenset({
    # utils/store.py — chunk IO at the codec boundary
    "store.bytes_read",
    "store.bytes_written",
    "store.chunks_read",
    "store.chunks_written",
    "store.chunk_cache_hits",
    "store.chunk_cache_misses",
    "store.aligned_chunk_writes",
    # utils/retry.py — backoff sleeps absorbed on transient chunk IO
    "store.io_retries",
    # utils/store_backend.py — ctt-cloud object-store backend: HTTP
    # requests (GET/HEAD = reads, PUT/DELETE = writes), wire bytes, and
    # backoff sleeps absorbed on transient remote requests
    "store.remote_reads",
    "store.remote_writes",
    "store.remote_retries",
    "store.remote_bytes_read",
    "store.remote_bytes_written",
    # ctt-diskless: S3 multipart uploads taken for oversized payloads
    # (one count per whole upload, not per part), and requests the store
    # rejected 401/403 — each such rejection surfaces as a retryable
    # auth error riding the same request-level retry
    "store.remote_multipart_uploads",
    "store.remote_auth_retries",
    # utils/compile_cache.py — jax.monitoring persistent-cache events
    "compile_cache.cache_hits",
    "compile_cache.cache_misses",
    "compile_cache.tasks_using_cache",
    # runtime/task.py — retry machinery
    "task.blocks_failed",
    "task.blocks_retried",
    # runtime/executor.py — dispatch + pipeline occupancy
    "executor.batches",
    "executor.batch_s",
    "executor.dispatch_wall_s",
    "executor.blocks_timed_out",
    "executor.stage_batches",
    "executor.stage_read_s",
    "executor.stage_compute_s",
    "executor.stage_write_s",
    "executor.stage_hidden_io_s",
    # ctt-cloud async-prefetch lookahead stage (advisory LRU warming
    # ahead of the in-order compute stage)
    "executor.prefetch_batches",
    "executor.stage_prefetch_s",
    # ctt-hbm double-buffered transfer stage: seconds the upload thread
    # spent moving batches to HBM (overlap vs compute derives from this)
    "executor.stage_upload_s",
    # runtime/hbm.py — ctt-hbm device-resident pipelines
    "device.upload_bytes",      # host bytes that actually crossed to HBM
    "device.uploads_skipped",   # batches served from the warm buffer cache
    "device.cache_evictions",   # LRU evictions (explicit .delete() frees)
    "device.dispatches",        # device program launches (batch grain)
    "device.fused_blocks",      # blocks that rode an aggregated (stacked)
                                # dispatch — hbm_stack > 1 economics
    "device.deferred_deletes",  # evicted batches whose .delete() waited
                                # for the active dispatch guards to exit
                                # (the eviction/in-flight race fix)

    # ops/hier.py + tasks/hier.py — ctt-hier one-flood hierarchical
    # segmentation (host-side emission only, never inside jit)
    "hier.tables_built",        # blocks whose in-block merge table landed
    "hier.edges",               # saddle edges persisted into an artifact
    "hier.cut_edges",           # edges selected (saddle <= t) across cuts
    "hier.resegment_jobs",      # serve `resegment` jobs run to success

    # ops/events.py + tasks/events.py — ctt-events high-rate event
    # building (host-side emission from the build_events wrapper)
    "events.frames",            # detector frames labeled + summarized
    "events.clusters",          # clusters (events) extracted across frames
    "events.batches",           # batched (n_frames, h, w) device dispatches

    # ops/cc.py — ctt-cc coarse-to-fine kernel stats (host-side emission
    # from the connected_components_coarse wrapper, never inside jit)
    "cc.fixpoint_iters",
    "cc.live_tiles",
    "cc.merge_pairs",
    # faults/ — every fired injection (per-site series via prefix below)
    "faults.injected",
    # parallel/sharded.py — collective→local degradations
    "sharded.fallback_local",
    # runtime/queue.py — ctt-steal work-stealing scheduler
    "sched.leases_claimed",      # lease links won (gen 0 + requeues)
    "sched.leases_expired",      # leases found stale (3x cadence) on claim
    "sched.leases_requeued",     # expired leases taken over at gen+1
    "sched.leases_stolen",       # straggler items duplicated (no lease;
                                 # first-writer-wins result)
    "sched.driver_drain_blocks",  # blocks the driver backstop pulled after
                                  # every scheduler job had exited
    # runtime/stream.py — ctt-stream fused-chain execution
    "stream.chains",        # fused chains executed to completion
    "stream.slabs",         # block batches (z-slabs) streamed through a chain
    "stream.elided_bytes",  # intermediate bytes neither written nor re-read
    "stream.fallbacks",     # declared chains that declined/failed to fuse
    # serve/ — ctt-serve persistent serving daemon
    "serve.submissions",        # admitted job submissions
    "serve.quota_rejections",   # 429s: queue depth or tenant quota said no
    "serve.jobs_done",          # jobs executed to a successful result
    "serve.jobs_failed",        # jobs whose build raised/failed
    "serve.warm_compile_jobs",  # jobs whose (workflow, block-shape)
                                # signature already ran on this daemon —
                                # served from warm in-process compile
                                # caches (per-job persistent-cache deltas
                                # ride the job result)
    "serve.cold_compile_jobs",  # first job of a signature: pays compiles
    "serve.leases_requeued",    # stale job leases taken over at gen+1
                                # (a predecessor daemon died mid-job)
    "serve.jobs_reclaimed",     # ctt-fleet fast-path takeovers: the
                                # owner's fleet heartbeat proved it dead,
                                # so the lease expired at heartbeat (not
                                # lease) staleness — a subset of
                                # serve.leases_requeued
    "serve.jobs_quarantined",   # jobs parked as failed results after
                                # exhausting max_job_gens generations
                                # (the poison-job retry budget)
    # ctt-proto: the publish_once lost-race branches made observable —
    # each counts a benign first-writer-wins collision with a peer
    "serve.jobs_admitted",      # two-phase admissions this daemon won
    "serve.retract_races",      # retractions a peer's limbo reaper beat
    "serve.result_races",       # job results where a gen+1 re-run won
    # ctt-microbatch: cross-tenant job aggregation in the executor loop —
    # queued jobs sharing a microbatch signature coalesce into ONE
    # stacked dispatch (serve/microbatch.py); accounting stays per member
    "serve.microbatch_batches",     # stacked dispatches with >= 2 members
    "serve.microbatch_jobs_batched",  # member jobs that rode a stacked
                                      # dispatch (jobs/batches = the
                                      # aggregation ratio)
    "serve.microbatch_splits",  # members re-dispatched individually after
                                # a batch-path failure (poison isolation:
                                # only the culprit burns retry budget)
    "serve.microbatch_window_timeouts",  # aggregation windows that closed
                                         # on the deadline, not early-fill
    # ingest/ — ctt-ingest streaming ingest of a growing source
    "ingest.slabs_ingested",    # chunks committed through the chain
    "ingest.resumes",           # streams resumed from a persisted carry
    "ingest.poll_rounds",       # source listing scans (one per poll)
    "ingest.carry_bytes_persisted",  # carry-record bytes published
    # serve/supervisor.py — ctt-diskless elastic-fleet actor
    "serve.supervisor_spawns",  # daemon processes forked on scale-up
    "serve.supervisor_drains",  # surplus daemons SIGTERMed into a drain
    "serve.supervisor_adoptions",  # running daemons a (re)started
                                   # supervisor found via beats without
                                   # having spawned them — the
                                   # SIGKILL-the-supervisor recovery path
})

# -- gauges (metrics.set_gauge) ---------------------------------------------

GAUGES = frozenset({
    "compile_cache.entries_at_enable",
    # utils/store_backend.py — remote HTTP requests currently in flight
    "store.remote_inflight",
    # runtime/hbm.py — ctt-hbm: resident HBM buffer-cache bytes and
    # host→device transfers currently in flight (the two-slot gate)
    "device.cache_bytes",
    "device.inflight_uploads",
    # runtime/stream.py — peak carried merge-state bytes of a fused chain
    "stream.carry_bytes",
    # runtime/queue.py — unclaimed work-queue items at the last pull scan
    "sched.queue_depth",
    # serve/ — the daemon's job queue: queued (unleased) jobs + builds
    # currently executing
    "serve.queue_depth",
    "serve.running_jobs",
    # ctt-microbatch: member count of the most recent aggregation window
    # (1 = the window closed with a solo claim)
    "serve.microbatch_depth",
    # ctt-fleet: live (beating, non-exiting) daemons sharing the state
    # dir, and the fleet-wide queued-job backlog (the shared-dir count —
    # identical on every daemon, unlike per-daemon serve.queue_depth
    # history before the fleet)
    "serve.peers",
    "fleet.queue_depth",
    # serve/supervisor.py — ctt-diskless: the clamped daemon count the
    # supervisor is converging the fleet toward
    "fleet.target_daemons",
    # ingest/ — slabs landed (incl. out-of-order parked) but not yet
    # committed through the chain: the watcher/ingester gap
    "ingest.slabs_pending",
})

# -- histograms (hist.observe) ----------------------------------------------
#
# ctt-slo request-grain latency distributions.  Every name is a seconds
# histogram on the FIXED log2 bucket edges of obs/hist.py (exact
# cross-daemon merge), labeled by tenant + priority at the observe site.

HISTOGRAMS = frozenset({
    # serve/server.py — per-phase request latencies.  Phase walls are
    # also stamped durably (job/lease/result records), so `obs journey`
    # can reconstruct the same breakdown per job from disk.
    "serve.latency.admission",    # submit() entry -> admit/reject decision
    "serve.latency.queue_wait",   # admit wall -> lease claim_wall
    "serve.latency.window_wait",  # claim_wall -> dispatch_wall (microbatch
                                  # aggregation-window residency; ~0 when
                                  # the window is off)
    "serve.latency.execution",    # dispatch_wall -> build returned
    "serve.latency.publish",      # build returned -> result record durable
    "serve.latency.e2e",          # job submit_wall -> result published
})

# dynamic name families: one series per <suffix>, allowed by prefix
DYNAMIC_PREFIXES = (
    "faults.injected.",  # per injection site (faults/__init__.py)
)


def _matches_prefix(name: str) -> bool:
    return any(name.startswith(p) for p in DYNAMIC_PREFIXES)


def is_known_counter(name: str) -> bool:
    return name in COUNTERS or _matches_prefix(name)


def is_known_gauge(name: str) -> bool:
    return name in GAUGES or _matches_prefix(name)


def is_known_histogram(name: str) -> bool:
    return name in HISTOGRAMS or _matches_prefix(name)
