"""ctt-slo latency histograms: process-safe, exactly-mergeable buckets.

Counters (obs.metrics) answer "how many"; request-grain SLOs need "how
slow, at which percentile, for which tenant".  This module is the
histogram twin of :mod:`obs.metrics`: one enabled-check + one bisect +
one list increment per ``observe()``, flushed as ONE
``hist.p<pid>.json`` snapshot per process into the active run's
directory (atomic tmp+replace, the store convention).

The design constraint is *exact mergeability*: every histogram in every
process of every daemon uses the SAME fixed log2 bucket edges
(:data:`EDGES` — ``2**e`` for e in [-20, 6], ~1 µs to 64 s, plus a
+Inf overflow bucket).  Merging two snapshots is therefore pure
bucket-wise integer addition — no re-bucketing, no approximation — so a
fleet-wide rollup over N daemons is bit-identical to the histogram a
single process observing the same values would have produced.  That
exactness is what lets ``obs slo`` gate CI on a p99 computed from
merged per-daemon snapshots.

Quantiles are Prometheus-style: linear interpolation inside the bucket
that crosses the target rank, which bounds the error by the bucket
width (a factor-of-2 resolution; adjacent-edge ratio == 2).

Series are keyed by (name, sorted label items).  Names are registered
in :mod:`obs.registry` (``HISTOGRAMS``) and lint rule CTT010 flags
``hist.observe`` literals absent from it, exactly like counters.

Exported in OpenMetrics histogram form (``_bucket{le=...}`` / ``_sum``
/ ``_count``) by :func:`render_openmetrics` — the same exposition
``obs fleet`` emits for the whole fleet.

Enabled exactly when tracing is enabled (one switch: CTT_TRACE_DIR).
"""

from __future__ import annotations

import json
import os
import threading
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import trace

__all__ = [
    "EDGES", "observe", "snapshot", "flush", "reset",
    "merge_into", "merge_snapshots", "quantile", "series_quantile",
    "render_openmetrics", "load_run_hists", "HIST_FILE_PREFIX",
]

# Fixed for every histogram in the tree — exact cross-process merge
# depends on it.  2**-20 s ~ 0.95 us .. 2**6 s = 64 s, then +Inf.
EDGES: Tuple[float, ...] = tuple(2.0 ** e for e in range(-20, 7))
_N_BUCKETS = len(EDGES) + 1  # trailing +Inf overflow bucket

HIST_FILE_PREFIX = "hist.p"
SCHEMA = 1

_LOCK = threading.Lock()
# (name, ((label, value), ...)) -> [buckets list, sum, count]
_HISTS: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], List[Any]] = {}


def _key(name: str, labels: Dict[str, str]) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    return name, tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def observe(name: str, value: float, **labels: Any) -> None:
    """Record one observation (seconds) into the named series."""
    if not trace.enabled():
        return
    idx = bisect_left(EDGES, value)  # EDGES[idx] is the first edge >= value
    with _LOCK:
        h = _HISTS.get(_key(name, labels))
        if h is None:
            h = [[0] * _N_BUCKETS, 0.0, 0]
            _HISTS[_key(name, labels)] = h
        h[0][idx] += 1
        h[1] += float(value)
        h[2] += 1


def snapshot() -> Dict[str, Any]:
    """JSON-ready snapshot: {"schema", "edges", "hists": [series...]}."""
    with _LOCK:
        series = [
            {
                "name": name,
                "labels": dict(labels),
                "buckets": list(h[0]),
                "sum": h[1],
                "count": h[2],
            }
            for (name, labels), h in sorted(_HISTS.items())
        ]
    return {"schema": SCHEMA, "edges": list(EDGES), "hists": series}


def reset() -> None:
    """Drop all accumulated series (test isolation helper)."""
    with _LOCK:
        _HISTS.clear()


def flush() -> None:
    """Write this process's snapshot into the active run directory.
    Atomic (tmp + os.replace); the last write per process wins — same
    contract as ``metrics.flush``.  A separate file from the metrics
    snapshot because the ``metrics_snapshot`` artifact schema is closed."""
    rdir = trace.run_dir()
    if rdir is None:
        return
    snap = snapshot()
    if not snap["hists"]:
        return
    os.makedirs(rdir, exist_ok=True)
    path = os.path.join(rdir, f"{HIST_FILE_PREFIX}{os.getpid()}.json")
    tmp = path + f".tmp{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# merge — the whole point of fixed edges

def _check_edges(snap: Dict[str, Any]) -> None:
    edges = snap.get("edges")
    if edges is not None and tuple(edges) != EDGES:
        raise ValueError(
            "histogram snapshot has foreign bucket edges; exact merge "
            "requires the fixed registry edges"
        )


def merge_into(acc: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], List[Any]],
               snap: Dict[str, Any]) -> None:
    """Bucket-wise add one snapshot into an accumulator keyed like _HISTS."""
    _check_edges(snap)
    for s in snap.get("hists", []):
        k = _key(s["name"], s.get("labels", {}))
        h = acc.get(k)
        if h is None:
            h = [[0] * _N_BUCKETS, 0.0, 0]
            acc[k] = h
        buckets = s["buckets"]
        for i, c in enumerate(buckets[:_N_BUCKETS]):
            h[0][i] += int(c)
        h[1] += float(s.get("sum", 0.0))
        h[2] += int(s.get("count", 0))


def merge_snapshots(snaps: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge many snapshots into one (exact: bucket-wise addition)."""
    acc: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], List[Any]] = {}
    for snap in snaps:
        merge_into(acc, snap)
    series = [
        {
            "name": name,
            "labels": dict(labels),
            "buckets": list(h[0]),
            "sum": h[1],
            "count": h[2],
        }
        for (name, labels), h in sorted(acc.items())
    ]
    return {"schema": SCHEMA, "edges": list(EDGES), "hists": series}


# ---------------------------------------------------------------------------
# quantiles

def quantile(buckets: List[int], q: float) -> Optional[float]:
    """Prometheus-style quantile from bucket counts (q in [0, 1]).

    Linear interpolation inside the crossing bucket; the overflow
    bucket clamps to the largest finite edge.  None when empty."""
    total = sum(buckets)
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    for i, c in enumerate(buckets):
        cum += c
        if cum >= rank and c > 0:
            if i >= len(EDGES):  # +Inf bucket: clamp to last finite edge
                return EDGES[-1]
            lo = 0.0 if i == 0 else EDGES[i - 1]
            hi = EDGES[i]
            frac = (rank - (cum - c)) / c
            return lo + (hi - lo) * frac
    return EDGES[-1]


def series_quantile(snap: Dict[str, Any], name: str, q: float,
                    **labels: Any) -> Optional[float]:
    """Quantile of one (name, labels) series in a snapshot, or None."""
    want = _key(name, labels)
    for s in snap.get("hists", []):
        if _key(s["name"], s.get("labels", {})) == want:
            return quantile(list(s["buckets"]), q)
    return None


# ---------------------------------------------------------------------------
# exposition + run-dir loading

def _metric_name(name: str) -> str:
    out = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return f"ctt_{out}_seconds"


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_openmetrics(snap: Dict[str, Any]) -> List[str]:
    """OpenMetrics histogram families (no ``# EOF``; the caller owns the
    exposition envelope).  One family per name; cumulative ``le`` counts."""
    lines: List[str] = []
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for s in snap.get("hists", []):
        by_name.setdefault(s["name"], []).append(s)
    for name in sorted(by_name):
        mname = _metric_name(name)
        lines.append(f"# TYPE {mname} histogram")
        lines.append(f"# HELP {mname} {name} latency (fixed log2 buckets)")
        for s in sorted(by_name[name],
                        key=lambda s: sorted(s.get("labels", {}).items())):
            labels = {str(k): str(v) for k, v in s.get("labels", {}).items()}
            cum = 0
            for i, c in enumerate(s["buckets"]):
                cum += int(c)
                le = repr(EDGES[i]) if i < len(EDGES) else "+Inf"
                lstr = _label_str(labels, 'le="%s"' % le)
                lines.append(f"{mname}_bucket{lstr} {cum}")
            lines.append(f"{mname}_sum{_label_str(labels)} {float(s['sum'])}")
            lines.append(f"{mname}_count{_label_str(labels)} {int(s['count'])}")
    return lines


def load_run_hists(run_dir: str) -> Dict[str, Any]:
    """Merge every ``hist.p*.json`` under a run directory (exact)."""
    snaps = []
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        names = []
    for fn in names:
        if fn.startswith(HIST_FILE_PREFIX) and fn.endswith(".json"):
            try:
                with open(os.path.join(run_dir, fn)) as f:
                    snaps.append(json.load(f))
            except (OSError, ValueError):
                continue  # torn snapshot: skip, a later flush replaces it
    return merge_snapshots(snaps)
