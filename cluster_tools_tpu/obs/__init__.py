"""ctt-obs: structured tracing, metrics, and run-diff observability.

Three pieces (see each module's docstring):

  * :mod:`.trace`   — process-safe span recorder (JSONL shards per
    pid+thread, monotonic clocks, no-op fast path when disabled);
  * :mod:`.metrics` — counters/gauges for hot paths (store IO bytes,
    compile-cache hits, retry/failure counts, pipeline overlap);
  * :mod:`.export`  — cross-process shard merge, per-task summaries,
    Chrome ``trace_event`` export, and run-vs-run regression diff
    (CLI: ``python -m cluster_tools_tpu.obs``).

Enable by exporting ``CTT_TRACE_DIR=/some/dir`` before the run (child
processes — scheduler workers, bench subprocesses, multi-host peers —
inherit it and join the same run via ``CTT_RUN_ID``), or call
``obs.trace.enable(trace_dir)`` programmatically.
"""

from . import metrics, trace
from .trace import enable, enabled, event, flush, monotonic, span

__all__ = [
    "metrics", "trace",
    "enable", "enabled", "event", "flush", "monotonic", "span",
]
