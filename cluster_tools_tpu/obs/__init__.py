"""ctt-obs: structured tracing, metrics, live telemetry, run-diff.

Pieces (see each module's docstring):

  * :mod:`.trace`     — process-safe span recorder (JSONL shards per
    pid+thread, monotonic clocks, no-op fast path when disabled);
  * :mod:`.metrics`   — counters/gauges for hot paths (store IO bytes,
    compile-cache hits, retry/failure counts, pipeline overlap);
  * :mod:`.registry`  — the canonical list of counter/gauge names
    (lint rule CTT010 keeps call sites honest);
  * :mod:`.heartbeat` — ctt-watch liveness beats per executing process
    (``hb.p<pid>.json`` every ``CTT_HEARTBEAT_S``) + the SIGTERM
    preemption flush;
  * :mod:`.live`      — incremental tailer over shards + heartbeats:
    progress/ETA, stragglers, suspected-dead workers, block-duration
    heatmap, OpenMetrics exposition (``watch``/``heatmap``/``prom``);
  * :mod:`.export`    — post-mortem cross-process shard merge, per-task
    summaries, Chrome ``trace_event`` export, and run-vs-run regression
    diff (CLI: ``python -m cluster_tools_tpu.obs``).

Enable by exporting ``CTT_TRACE_DIR=/some/dir`` before the run (child
processes — scheduler workers, bench subprocesses, multi-host peers —
inherit it and join the same run via ``CTT_RUN_ID``), or call
``obs.trace.enable(trace_dir)`` programmatically.
"""

from . import metrics, trace
from .trace import enable, enabled, event, flush, monotonic, span

__all__ = [
    "metrics", "trace",
    "enable", "enabled", "event", "flush", "monotonic", "span",
]
