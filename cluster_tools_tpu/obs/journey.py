"""ctt-slo job journey: one job's whole life, reconstructed from disk.

``obs journey <state_dir> <job_id>`` renders a per-job timeline — when
the job was submitted, admitted, claimed (by which daemon, at which
generation), dispatched, and published — **purely from the durable
records** the serve substrate already writes:

  * ``job.<id>.json``       — submission record (``submit_wall``)
  * ``admit.<id>.json``     — fleet admission marker (``wall``)
  * ``lease.<id>.g<g>.json``— one per generation: the claiming daemon,
    ``claim_wall``, and the ctt-slo ``dispatch_wall`` execution stamp
  * ``result.<id>.json``    — terminal record; carries the winning
    generation's ``claimed_wall``/``dispatch_wall``/``published_wall``
    phase walls, ``seconds``, the microbatch membership note, and (for a
    quarantined job) the ``failure_log`` of every burned generation

No live daemon is consulted and no clocks are read: the journey of a job
that survived a SIGKILL failover (gen 0 owner died, gen 1 finished)
renders the same whether the fleet is still up or long gone.  Lease
generations are dense from 0, so discovery is forward existence probes —
and the quarantine ``failure_log`` backfills generations whose lease
file was torn by the death that burned it.

The phase breakdown mirrors the server-side histogram phases
(:mod:`obs.registry` ``HISTOGRAMS``):

    admission   = admit.wall − submit_wall        (two-phase admission)
    queue_wait  = claimed_wall − admit.wall       (claim-order waiting)
    window_wait = dispatch_wall − claimed_wall    (microbatch window)
    execution   = result.seconds                  (monotonic, exact)
    publish     = published_wall − dispatch_wall − seconds
    e2e         = published_wall − submit_wall

Walls come from different hosts' clocks, so cross-host phases are good
to fleet clock skew (the shard-anchor contract); ``execution`` is the
owner's monotonic delta and exact.  Negative skew artifacts clamp to 0.

State dirs route through the store backend, so ``<state_dir>`` may be a
POSIX path or an ``http(s)://`` object-store prefix (ctt-diskless).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..utils.store_backend import backend_for

__all__ = ["load_journey", "format_journey", "PHASE_ORDER"]

# render order == causal order; e2e last (it spans all the others)
PHASE_ORDER = (
    "admission", "queue_wait", "window_wait", "execution", "publish", "e2e",
)


def _read_json(backend, path: str) -> Optional[dict]:
    try:
        rec = json.loads(backend.read_bytes(path).decode())
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None  # absent or torn: the caller treats both as "no record"


def _as_wall(value: Any) -> Optional[float]:
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


def _lease_row(lease: Optional[dict], gen: int) -> Dict[str, Any]:
    """One generation's row from its lease record (None = torn/absent)."""
    if lease is None:
        return {
            "gen": gen, "torn": True, "daemon": None, "claim_wall": None,
            "dispatch_wall": None, "released": False,
        }
    return {
        "gen": gen,
        "torn": False,
        "daemon": lease.get("daemon"),
        "claim_wall": _as_wall(lease.get("claim_wall")),
        "dispatch_wall": _as_wall(lease.get("dispatch_wall")),
        "released": bool(lease.get("released")),
    }


def _generations(backend, join, root: str, job_id: str,
                 result: Optional[dict]) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    gen = 0
    while True:
        path = join(root, f"lease.{job_id}.g{gen}.json")
        if not backend.exists(path):
            break
        rows.append(_lease_row(_read_json(backend, path), gen))
        gen += 1
    if result is not None and result.get("quarantined"):
        # the death that burned a generation can also tear its lease —
        # the quarantine verdict carries every generation's last stamp,
        # so backfill torn rows (and any generation past the probe) from
        # the durable failure_log
        for i, entry in enumerate(result.get("failure_log") or []):
            if not isinstance(entry, dict) or entry.get("torn"):
                entry = None
            row = _lease_row(entry, i)
            if i < len(rows):
                if rows[i]["torn"] and not row["torn"]:
                    rows[i] = row
            else:
                rows.append(row)

    win_gen = None
    if result is not None and not result.get("rejected") \
            and not result.get("quarantined"):
        try:
            win_gen = int(result["gen"])
        except (KeyError, TypeError, ValueError):
            win_gen = None
    last = len(rows) - 1
    for row in rows:
        if win_gen is not None and row["gen"] == win_gen:
            row["outcome"] = ("won" if result.get("ok")
                              else "won (published failure)")
            # the winner's result walls are authoritative (the lease may
            # have been overwritten by a later renewal or lost entirely)
            cw = _as_wall(result.get("claimed_wall"))
            dw = _as_wall(result.get("dispatch_wall"))
            if cw is not None:
                row["claim_wall"] = cw
            if dw is not None:
                row["dispatch_wall"] = dw
        elif row["released"]:
            row["outcome"] = "released (clean hand-back)"
        elif result is not None and result.get("quarantined"):
            row["outcome"] = "died (burned a generation)"
        elif win_gen is not None or row["gen"] < last:
            # a later generation exists (or the result belongs to one):
            # this owner's lease expired — stale stamp or fleet-dead
            row["outcome"] = "expired (owner presumed dead)"
        elif result is None:
            row["outcome"] = "in flight (no result yet)"
        else:
            row["outcome"] = "superseded"
    return rows


def _phases(rec: dict, admit: Optional[dict],
            result: Optional[dict]) -> Dict[str, float]:
    """The winning generation's phase breakdown; {} when the job has no
    executed result (queued, in flight, rejected, or quarantined)."""
    if result is None or result.get("rejected") or result.get("quarantined"):
        return {}
    submit_wall = _as_wall(rec.get("submit_wall"))
    if submit_wall is None:
        return {}
    admit_wall = _as_wall((admit or {}).get("wall"))
    claimed = _as_wall(result.get("claimed_wall"))
    dispatch = _as_wall(result.get("dispatch_wall"))
    published = _as_wall(result.get("published_wall"))
    if published is None:
        published = _as_wall(result.get("finished_wall"))
    seconds = _as_wall(result.get("seconds"))

    phases: Dict[str, float] = {}
    if admit_wall is not None:
        phases["admission"] = max(0.0, admit_wall - submit_wall)
    start = admit_wall if admit_wall is not None else submit_wall
    if claimed is not None:
        phases["queue_wait"] = max(0.0, claimed - start)
        if dispatch is not None:
            phases["window_wait"] = max(0.0, dispatch - claimed)
    if seconds is not None:
        phases["execution"] = max(0.0, seconds)
    if published is not None and dispatch is not None and seconds is not None:
        phases["publish"] = max(0.0, published - dispatch - seconds)
    if published is not None:
        phases["e2e"] = max(0.0, published - submit_wall)
    return phases


def load_journey(state_dir: str, job_id: str) -> Optional[Dict[str, Any]]:
    """Reconstruct one job's journey from state-dir records alone.
    ``state_dir`` is the serve state dir (jobs under ``jobs/``) or the
    jobs dir itself; returns None when no such job record exists."""
    backend = backend_for(state_dir)
    join = backend.join
    root = state_dir
    if not backend.exists(join(root, f"job.{job_id}.json")):
        sub = join(root, "jobs")
        if not backend.exists(join(sub, f"job.{job_id}.json")):
            return None
        root = sub
    rec = _read_json(backend, join(root, f"job.{job_id}.json"))
    if rec is None:
        return None
    admit = _read_json(backend, join(root, f"admit.{job_id}.json"))
    result = _read_json(backend, join(root, f"result.{job_id}.json"))
    gens = _generations(backend, join, root, job_id, result)

    if result is None:
        state = "running" if gens else "queued"
    elif result.get("quarantined"):
        state = "quarantined"
    elif result.get("rejected"):
        state = "rejected"
    else:
        state = "done" if result.get("ok") else "failed"
    return {
        "id": job_id,
        "state": state,
        "record": rec,
        "admit_wall": _as_wall((admit or {}).get("wall")),
        "generations": gens,
        "result": result,
        "phases": _phases(rec, admit, result),
    }


def format_journey(j: Dict[str, Any]) -> str:
    """Human timeline: absolute order as ``t+<s>`` offsets from the
    submission wall, one line per generation, then the phase breakdown."""
    rec = j["record"]
    t0 = _as_wall(rec.get("submit_wall"))

    def rel(wall: Optional[float]) -> str:
        if wall is None or t0 is None:
            return "t+?"
        return f"t+{max(0.0, wall - t0):.3f}s"

    lines = [
        f"job {j['id']}  tenant={rec.get('tenant', 'default')} "
        f"priority={rec.get('priority', 0)} "
        f"workflow={rec.get('workflow', '?')}  state={j['state']}"
    ]
    lines.append(f"  submitted    {rel(t0)}")
    if j.get("admit_wall") is not None:
        lines.append(f"  admitted     {rel(j['admit_wall'])}")
    for g in j["generations"]:
        parts = [f"  gen {g['gen']}", f"daemon={g['daemon'] or '?'}"]
        if g.get("claim_wall") is not None:
            parts.append(f"claimed {rel(g['claim_wall'])}")
        if g.get("dispatch_wall") is not None:
            parts.append(f"dispatched {rel(g['dispatch_wall'])}")
        if g.get("torn"):
            parts.append("(lease torn)")
        parts.append(f"-> {g['outcome']}")
        lines.append("  ".join(parts))
    result = j.get("result")
    if result is not None:
        mb = result.get("microbatch")
        if isinstance(mb, dict):
            note = (f"  microbatch: rode a {mb.get('jobs', '?')}-job "
                    f"stacked dispatch (member {mb.get('index', '?')})")
            if mb.get("split"):
                note += " — re-dispatched solo after a batch failure"
            lines.append(note)
        published = _as_wall(result.get("published_wall"))
        if published is None:
            published = _as_wall(result.get("finished_wall"))
        lines.append(f"  published    {rel(published)}  "
                     f"(gen {result.get('gen', '?')}, "
                     f"daemon={result.get('daemon') or '?'})")
        if result.get("error"):
            lines.append(f"  error: {str(result['error']).splitlines()[0]}")
    phases = j.get("phases") or {}
    if phases:
        lines.append("  phases:")
        for name in PHASE_ORDER:
            if name in phases:
                lines.append(f"    {name:<12} {phases[name]:.3f}s")
    return "\n".join(lines)
