"""ctt-watch heartbeats: each process's "I am alive and here is where I am".

Span shards (obs.trace) only show work that *finished* — a hung worker is
exactly the process that stops producing them.  This module gives every
participating process (the driver executor and each scheduler worker) a
tiny periodic liveness record: a daemon thread writes one atomic
``hb.p<pid>.json`` file into the active run directory every
``CTT_HEARTBEAT_S`` seconds (default 5).  The live reader (obs.live)
re-reads these files each poll — they are single small JSON objects, not
append logs — and derives worker liveness, in-flight block age, and
per-process progress gauges from them.

Heartbeat file schema (one JSON object, atomically replaced per beat)::

    {
      "pid": 1234, "host": "...", "role": "driver" | "worker",
      "job_id": 3 | null,            # scheduler job id for workers
      "process_id": 0 | null,        # multi-host rank (CTT_PROCESS_ID)
      "run": "<run id>",
      "wall": 1722772000.1,          # time of this beat (timestamp)
      "mono": 5531.2,                # same instant, writer's monotonic clock
      "interval_s": 5.0,             # the cadence THIS writer promised
      "seq": 17,                     # beats written so far
      "exiting": false,              # true on the final beat (clean exit)
      "task": "watershed" | null,    # current task identifier
      "blocks_total": 64,            # this process's share of the dispatch
      "blocks_done": 24, "blocks_failed": 1, "blocks_retried": 1,
      "grid": [2, 4, 4] | null,      # blocking grid (heatmap geometry)
      "current_blocks": [{"id": 17, "start_mono": 5529.9}, ...],
      "queue_depth": 3 | null,       # unclaimed work-queue items as last
                                     # seen by this worker's pull loop
                                     # (ctt-steal; null outside steal runs)
      "draining": false,             # true once a serve daemon started its
                                     # SIGTERM drain (ctt-serve): still
                                     # alive, finishing in-flight jobs,
                                     # refusing new submissions
      "device_mem_peak_bytes": 1048576 | null
    }

Design constraints, mirroring the rest of ctt-obs:

  * **Same single switch.**  Nothing starts unless tracing is enabled
    (``CTT_TRACE_DIR``): ``ensure_started()`` is then one global check.
    The disabled-overhead smoke asserts no thread and no files.
  * **Atomic writes.**  tmp + ``os.replace`` (the store convention, minus
    fsync — heartbeats are advisory, durability would cost cadence).
  * **Monotonic durations, wall anchors.**  ``start_mono``/``mono`` are
    writer-clock; readers age a heartbeat via wall deltas (good to
    cross-process clock skew, exactly like the shard-header anchors).
  * **Never in the way.**  The beat thread swallows its own IO errors;
    ``note_*`` hooks are a lock + dict update when enabled, one global
    load when not.

``install_sigterm_flush()`` is the preemption hook (ctt-watch satellite):
scheduler SIGTERM → flush metrics + trace + one final ``exiting`` beat,
then chain to the previous handler / default die.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import socket
import sys
import threading
import time
from typing import Any, Dict, Optional

from . import trace

__all__ = [
    "ensure_started", "stop", "beat", "running", "interval_s",
    "note_task", "note_blocks_done", "note_blocks_failed",
    "note_blocks_retried", "note_block_start", "note_block_end",
    "note_queue_depth", "note_draining", "set_role",
    "install_sigterm_flush", "FILE_PREFIX", "ENV_INTERVAL",
]

ENV_INTERVAL = "CTT_HEARTBEAT_S"
DEFAULT_INTERVAL_S = 5.0
FILE_PREFIX = "hb.p"

# cap the in-flight list in the file: a wide thread pool should not make
# the heartbeat grow unboundedly — the oldest entries are the interesting
# ones (straggler detection keys on age)
_MAX_CURRENT_BLOCKS = 16


def interval_s() -> float:
    """Beat cadence: ``CTT_HEARTBEAT_S``, malformed/nonpositive values
    degrade to the default like every other CTT_* switch."""
    raw = os.environ.get(ENV_INTERVAL)
    try:
        val = float(raw) if raw is not None else DEFAULT_INTERVAL_S
    except (TypeError, ValueError):
        val = DEFAULT_INTERVAL_S
    return val if val > 0 else DEFAULT_INTERVAL_S


class _BeatState:
    """Mutable progress fields shared between the note_* hooks (hot path)
    and the beat thread (cold path)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.role = "driver"
        self.job_id: Optional[int] = None
        self.task: Optional[str] = None
        self.blocks_total = 0
        self.blocks_done = 0
        self.blocks_failed = 0
        self.blocks_retried = 0
        self.grid: Optional[list] = None
        self.queue_depth: Optional[int] = None  # ctt-steal pull loops only
        self.draining = False  # ctt-serve SIGTERM drain in progress
        self.current: Dict[int, float] = {}  # block id -> start mono
        self.seq = 0
        self.thread: Optional[threading.Thread] = None
        self.wake = threading.Event()
        self.stopping = False


_STATE: Optional[_BeatState] = None
_STATE_LOCK = threading.Lock()
_ATEXIT_REGISTERED = False


def _topology_rank() -> Optional[int]:
    """Multi-host rank (``CTT_PROCESS_ID``, the runtime/config.py process
    topology) — None for single-host runs and scheduler workers."""
    raw = os.environ.get("CTT_PROCESS_ID")
    try:
        return int(raw) if raw is not None else None
    except (TypeError, ValueError):
        return None


def _device_mem_peak_bytes() -> Optional[int]:
    """High-water device memory across local devices, when jax is already
    up.  Never *triggers* backend init: a heartbeat must not be the thing
    that opens a device tunnel."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        peak = None
        for dev in jax.local_devices():
            stats_fn = getattr(dev, "memory_stats", None)
            stats = stats_fn() if stats_fn is not None else None
            if not stats:
                continue
            val = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
            if val is not None:
                peak = max(peak or 0, int(val))
        return peak
    except Exception:  # pragma: no cover - backend quirks must not kill beats
        return None


def _write_beat(st: _BeatState, exiting: bool) -> None:
    rdir = trace.run_dir()
    if rdir is None:
        return
    with st.lock:
        st.seq += 1
        current = sorted(st.current.items(), key=lambda kv: kv[1])
        record = {
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "role": st.role,
            "job_id": st.job_id,
            "process_id": _topology_rank(),
            "run": trace.current_run_id(),
            # wall is a timestamp (reader-side ageing), mono the same
            # instant on this process's duration clock
            "wall": time.time(),
            "mono": trace.monotonic(),
            "interval_s": interval_s(),
            "seq": st.seq,
            "exiting": bool(exiting),
            "task": st.task,
            "blocks_total": st.blocks_total,
            "blocks_done": st.blocks_done,
            "blocks_failed": st.blocks_failed,
            "blocks_retried": st.blocks_retried,
            "grid": st.grid,
            "current_blocks": [
                {"id": int(b), "start_mono": float(t0)}
                for b, t0 in current[:_MAX_CURRENT_BLOCKS]
            ],
            "queue_depth": st.queue_depth,
            "draining": st.draining,
            "device_mem_peak_bytes": _device_mem_peak_bytes(),
        }
    path = os.path.join(rdir, f"{FILE_PREFIX}{os.getpid()}.json")
    tmp = path + f".tmp{os.getpid()}.{threading.get_ident()}"
    try:
        os.makedirs(rdir, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, path)
    except OSError:
        # liveness reporting is best-effort: a full disk must not take the
        # worker down with it
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _beat_loop(st: _BeatState) -> None:
    while not st.stopping:
        _write_beat(st, exiting=False)
        st.wake.wait(interval_s())
        st.wake.clear()


def ensure_started(role: Optional[str] = None,
                   job_id: Optional[int] = None) -> bool:
    """Start the beat thread (idempotent).  No-op — no thread, no file —
    unless tracing is enabled; returns True when beating."""
    global _STATE, _ATEXIT_REGISTERED
    if not trace.enabled():
        return False
    st = _STATE
    if st is None or st.thread is None or not st.thread.is_alive():
        with _STATE_LOCK:
            st = _STATE
            if st is None or st.thread is None or not st.thread.is_alive():
                st = _STATE if st is not None else _BeatState()
                st.stopping = False
                st.thread = threading.Thread(
                    target=_beat_loop, args=(st,),
                    name="ctt-heartbeat", daemon=True,
                )
                _STATE = st
                st.thread.start()
                if not _ATEXIT_REGISTERED:
                    atexit.register(stop)
                    _ATEXIT_REGISTERED = True
    if role is not None or job_id is not None:
        with st.lock:
            if role is not None:
                st.role = role
            if job_id is not None:
                st.job_id = int(job_id)
    return True


def running() -> bool:
    st = _STATE
    return st is not None and st.thread is not None and st.thread.is_alive()


def beat(exiting: bool = False) -> None:
    """Write one heartbeat now (final beats, tests).  No-op when disabled
    or never started."""
    st = _STATE
    if st is None or not trace.enabled():
        return
    _write_beat(st, exiting=exiting)


def stop(final: bool = True) -> None:
    """Stop the beat thread; with ``final``, stamp one last ``exiting``
    beat so readers can tell clean exit from death."""
    global _STATE
    st = _STATE
    if st is None:
        return
    st.stopping = True
    st.wake.set()
    thread = st.thread
    if thread is not None and thread.is_alive():
        if thread is not threading.current_thread():
            thread.join(timeout=2.0)
    st.thread = None
    if final and trace.enabled():
        _write_beat(st, exiting=True)


# ---------------------------------------------------------------------------
# progress hooks (called from runtime/{task,executor}.py hot-ish paths)


def _state_if_enabled() -> Optional[_BeatState]:
    if not trace.enabled():
        return None
    return _STATE


def set_role(role: str, job_id: Optional[int] = None) -> None:
    st = _state_if_enabled()
    if st is None:
        return
    with st.lock:
        st.role = role
        if job_id is not None:
            st.job_id = int(job_id)


def note_task(identifier: str, total: int,
              grid: Optional[Any] = None) -> None:
    """A new dispatch round: reset the per-task share counters.  ``total``
    is THIS process's block share (multi-host peers each report theirs)."""
    st = _state_if_enabled()
    if st is None:
        return
    with st.lock:
        if st.task != identifier:
            st.blocks_done = 0
            st.blocks_failed = 0
            st.blocks_retried = 0
        st.task = identifier
        st.blocks_total = int(total)
        if grid is not None:
            st.grid = [int(g) for g in grid]


def note_blocks_done(n: int = 1) -> None:
    st = _state_if_enabled()
    if st is None:
        return
    with st.lock:
        st.blocks_done += int(n)


def note_blocks_failed(n: int = 1) -> None:
    st = _state_if_enabled()
    if st is None:
        return
    with st.lock:
        st.blocks_failed += int(n)


def note_blocks_retried(n: int = 1) -> None:
    st = _state_if_enabled()
    if st is None:
        return
    with st.lock:
        st.blocks_retried += int(n)


def note_queue_depth(n: int) -> None:
    """ctt-steal: unclaimed work-queue items at this worker's last pull
    scan — `obs watch` shows how much stealable work remains."""
    st = _state_if_enabled()
    if st is None:
        return
    with st.lock:
        st.queue_depth = int(n)


def note_draining() -> None:
    """ctt-serve: the daemon entered its SIGTERM drain — readers (`obs
    watch`, /metrics scrapes) distinguish 'alive, finishing, refusing
    submissions' from both healthy and dead."""
    st = _state_if_enabled()
    if st is None:
        return
    with st.lock:
        st.draining = True


def note_block_start(block_id: int) -> None:
    st = _state_if_enabled()
    if st is None:
        return
    with st.lock:
        st.current[int(block_id)] = trace.monotonic()


def note_block_end(block_id: int) -> None:
    st = _state_if_enabled()
    if st is None:
        return
    with st.lock:
        st.current.pop(int(block_id), None)


# ---------------------------------------------------------------------------
# preemption: flush telemetry before the scheduler's SIGTERM kills us


def install_sigterm_flush() -> bool:
    """Install a SIGTERM handler that flushes metrics + trace shards and
    writes a final ``exiting`` heartbeat before re-raising (chaining any
    previously installed handler).  The common scheduler preemption path
    sends SIGTERM with a grace window — without this, the process's
    metrics snapshot and buffered shard tail die with it.

    Returns False (and installs nothing) off the main thread, where the
    signal module refuses handlers."""
    if threading.current_thread() is not threading.main_thread():
        return False
    prev = signal.getsignal(signal.SIGTERM)

    def _handler(signum, frame):
        try:
            beat(exiting=True)
            stop(final=False)
            trace.flush()  # flushes the metrics snapshot too
        finally:
            if callable(prev):
                prev(signum, frame)
            else:
                # restore default disposition and re-raise so the exit
                # status still says "killed by SIGTERM"
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

    signal.signal(signal.SIGTERM, _handler)
    return True
