"""Device mesh utilities.

Blocks are the unit of data parallelism (the analog of the reference's
round-robin job assignment, cluster_tasks.py:331): a batch of blocks is stacked
on the leading axis and sharded over a 1d ``data`` mesh; per-block kernels are
vmapped so XLA compiles one program for the whole batch and partitions it over
ICI.  Cross-block reductions (label merges, feature merges) then ride XLA
collectives instead of the reference's filesystem round-trips (SURVEY.md §2.9).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def local_device_count() -> int:
    return jax.local_device_count()


def get_mesh(devices: Optional[Sequence] = None, axis_name: str = "data") -> Mesh:
    """1d mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def shard_batch(batch, mesh: Optional[Mesh] = None, axis_name: str = "data"):
    """Place a [B, ...] stacked block batch with the leading axis sharded over
    the mesh.  B must be divisible by the mesh size (callers pad)."""
    if mesh is None:
        mesh = get_mesh(axis_name=axis_name)
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.device_put(batch, sharding)


# sharding of the most recent ``put_sharded`` placement — introspection hook so
# tests (and the driver dryrun) can assert that the production task path really
# partitioned its batch over the mesh rather than landing everything on device 0
_LAST_BATCH_SHARDING = None


def last_batch_sharding():
    return _LAST_BATCH_SHARDING


def resolve_devices(config: Optional[dict] = None):
    """Devices used for block data parallelism: the ``devices`` config entry
    (indices into ``jax.devices()``, device objects, or the string
    ``"global"`` for every device of every process after
    ``init_distributed``) or all local devices."""
    devices = (config or {}).get("devices")
    if devices == "global":
        return jax.devices()
    if devices:
        all_devices = jax.devices()
        return [all_devices[d] if isinstance(d, int) else d for d in devices]
    return jax.local_devices()


_DISTRIBUTED_INITIALIZED = False


def init_distributed(config: Optional[dict] = None) -> bool:
    """Join the multi-host jax runtime (idempotent).

    Reads ``coordinator_address`` / ``num_processes`` / ``process_id`` from
    the config or the ``CTT_COORDINATOR`` / ``CTT_NUM_PROCESSES`` /
    ``CTT_PROCESS_ID`` environment, and calls ``jax.distributed.initialize``
    — after which ``jax.devices()`` spans all processes and the collective
    kernels run their ppermute/psum over ICI within a host and DCN
    (gRPC/Gloo on CPU) across hosts.  Returns True when a multi-process
    runtime is active.

    MUST run at process startup, before any jax backend initializes
    (``jax.distributed.initialize`` refuses afterwards) — call it from the
    launcher.  Then either drive the ``parallel.sharded*`` kernels directly
    over a ``resolve_devices({"devices": "global"})`` mesh, or run the
    collective tasks through ``build()``: tasks marked
    ``collective = True`` (sharded components / watershed / problem)
    execute their program on EVERY process under the runtime's multi-host
    topology, with process 0 owning the store writes and the status file
    (``runtime.task.SimpleTask``).  The block-task layer stays
    per-process; multi-host here is the comm backend — the role NCCL/MPI
    bootstrap plays in GPU stacks (SURVEY.md §2.9).
    """
    global _DISTRIBUTED_INITIALIZED
    import os

    conf = config or {}

    def _setting(key, env_key, default=None):
        # explicit key-presence checks: 0 is a legitimate process_id and
        # must not fall through to a stale environment value
        if key in conf and conf[key] is not None:
            return conf[key]
        return os.environ.get(env_key, default)

    coord = _setting("coordinator_address", "CTT_COORDINATOR")
    if not coord:
        return False
    if _DISTRIBUTED_INITIALIZED:
        return True
    # None passes through so jax's own auto-detection (TPU pod metadata)
    # still works when only the coordinator is configured
    n_proc = _setting("num_processes", "CTT_NUM_PROCESSES")
    pid = _setting("process_id", "CTT_PROCESS_ID")
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=None if n_proc is None else int(n_proc),
        process_id=None if pid is None else int(pid),
    )
    _DISTRIBUTED_INITIALIZED = True
    return True


def put_global(arr, mesh: Mesh, axis_name: str = "data", dtype=None):
    """Place a host array onto a mesh sharding, multi-process safe.

    Every process passes the SAME full (global-shape) host array;
    ``jax.make_array_from_callback`` materializes only the shards addressable
    by this process, so the call works identically on a single-process mesh
    (where it is just a sharded device_put) and on a multi-host mesh (where
    ``jax.device_put`` would fail on non-addressable devices).

    Device arrays already carrying the target sharding pass through
    untouched (a host round-trip would crash on a global mesh and waste two
    transfers on a single-host one)."""
    sharding = NamedSharding(mesh, P(axis_name))
    if isinstance(arr, jax.Array):
        ok_dtype = dtype is None or arr.dtype == np.dtype(dtype)
        if ok_dtype and arr.sharding.is_equivalent_to(sharding, arr.ndim):
            return arr
    arr = np.asarray(arr)
    if dtype is not None:
        arr = arr.astype(dtype, copy=False)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def put_from_store(ds, mesh: Mesh, axis_name: str = "data", dtype=None,
                   pad_to: Optional[int] = None, transform=None,
                   pad_value=0):
    """Stream a chunked-store dataset onto the mesh sharding shard-by-shard:
    the placement callback reads each shard's region directly from the
    store, so no full-volume host copy ever exists (the practical bound
    becomes one shard, not the volume — and on a multi-host mesh each
    process reads only its own slab from shared storage).

    ``pad_to``: pad the leading axis up to a multiple of this, for meshes
    that do not divide the raw extent — the pad is ``pad_value`` in the
    OUTPUT dtype and never passes through ``transform``.

    ``transform``: host function applied to each shard's real region before
    it crosses to the device.  Narrowing transforms (e.g. thresholding a
    float volume to its bool mask) keep HBM at the narrow dtype — only the
    transformed shard ever leaves the host."""
    shape = list(ds.shape)
    z = shape[0]
    if pad_to:
        shape[0] = z + ((-z) % pad_to)
    shape = tuple(shape)
    sharding = NamedSharding(mesh, P(axis_name))
    out_dtype = np.dtype(dtype) if dtype is not None else ds.dtype

    def read(idx):
        sl0 = idx[0]
        start, stop = sl0.start or 0, sl0.stop or shape[0]
        stop_real = min(stop, z)
        block = np.full((stop - start,) + shape[1:], pad_value, dtype=out_dtype)
        if start < z:
            part = np.asarray(ds[(slice(start, stop_real),) + idx[1:]])
            if transform is not None:
                part = transform(part)
            block[: stop_real - start] = part.astype(out_dtype, copy=False)
        return block

    return jax.make_array_from_callback(shape, sharding, read)


def fetch_local(arr, axis: int = 0):
    """Host view of this process's shards of a (possibly multi-host) global
    array: ``(offset, local_block)`` concatenated along ``axis`` in index
    order.  Replicated (or otherwise non-``axis``-sharded) arrays return
    ``(0, full array)`` — duplicate per-device copies are collapsed, not
    concatenated."""
    by_index = {}
    for s in arr.addressable_shards:
        key = tuple((sl.start, sl.stop) for sl in s.index)
        by_index.setdefault(key, s)
        for d, sl in enumerate(s.index):
            if d != axis and (sl.start or 0) != 0:
                raise ValueError(
                    f"fetch_local(axis={axis}) expects sharding along that "
                    f"axis only, found a shard split on axis {d}"
                )
    shards = sorted(
        by_index.values(), key=lambda s: s.index[axis].start or 0
    )
    # contiguity: interleaved device orders would give this process
    # non-adjacent slabs, and a single (offset, block) pair cannot
    # represent them — fail loudly instead of mislabeling coordinates
    for prev, cur in zip(shards, shards[1:]):
        if prev.index[axis].stop != (cur.index[axis].start or 0):
            raise ValueError(
                "fetch_local: this process's shards are not contiguous "
                f"along axis {axis} ({prev.index} then {cur.index}); use a "
                "process-contiguous device order"
            )
    parts = [np.asarray(s.data) for s in shards]
    start = shards[0].index[axis].start or 0
    return start, np.concatenate(parts, axis=axis)


def fetch_global(arr, axis: int = 0):
    """Full host copy of a (possibly multi-host) global array in EVERY
    process: each process contributes its local slab via an allgather.
    Single-process arrays are just np.asarray."""
    if jax.process_count() == 1:
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    _, local = fetch_local(arr, axis)
    return np.asarray(multihost_utils.process_allgather(local, tiled=True))


def put_sharded(arr, config: Optional[dict] = None, axis_name: str = "data"):
    """Place a stacked [B, ...] block batch for compute: with >1 device the
    leading axis is padded (repeating the last block) to divide the 1d mesh and
    sharded over it; single-device falls back to a plain transfer.

    Returns ``(device_array, B)`` where ``B`` is the *unpadded* batch size —
    callers slice results back to ``[:B]``.  This is the production analog of
    the reference's round-robin block→job placement (cluster_tasks.py:331):
    blocks are the unit of data parallelism, and every kernel vmapped over the
    leading axis is partitioned over ICI by XLA.
    """
    global _LAST_BATCH_SHARDING
    b = arr.shape[0]
    # only the tpu target shards; 'local' is the single-device parity oracle
    # (sharding it would make local-vs-tpu comparisons vacuous and compute
    # every block n_dev times through the per-block path)
    if config is not None and config.get("target", "tpu") != "tpu":
        devices = []
    else:
        devices = resolve_devices(config)
    # a batch smaller than the mesh gains nothing from padding to it — run on
    # the first b devices instead of computing (n - b) wasted replicas
    if b < len(devices):
        devices = devices[:b]
    if len(devices) <= 1:
        out = jax.numpy.asarray(arr)
        _LAST_BATCH_SHARDING = out.sharding
        return out, b
    n = len(devices)
    pad = (-b) % n
    if pad:
        arr = np.concatenate(
            [arr, np.broadcast_to(arr[-1:], (pad,) + arr.shape[1:])], axis=0
        )
    out = shard_batch(arr, get_mesh(devices, axis_name), axis_name)
    _LAST_BATCH_SHARDING = out.sharding
    return out, b
