"""Device mesh utilities.

Blocks are the unit of data parallelism (the analog of the reference's
round-robin job assignment, cluster_tasks.py:331): a batch of blocks is stacked
on the leading axis and sharded over a 1d ``data`` mesh; per-block kernels are
vmapped so XLA compiles one program for the whole batch and partitions it over
ICI.  Cross-block reductions (label merges, feature merges) then ride XLA
collectives instead of the reference's filesystem round-trips (SURVEY.md §2.9).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def local_device_count() -> int:
    return jax.local_device_count()


def get_mesh(devices: Optional[Sequence] = None, axis_name: str = "data") -> Mesh:
    """1d mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def shard_batch(batch, mesh: Optional[Mesh] = None, axis_name: str = "data"):
    """Place a [B, ...] stacked block batch with the leading axis sharded over
    the mesh.  B must be divisible by the mesh size (callers pad)."""
    if mesh is None:
        mesh = get_mesh(axis_name=axis_name)
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.device_put(batch, sharding)
