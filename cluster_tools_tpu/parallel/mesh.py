"""Device mesh utilities.

Blocks are the unit of data parallelism (the analog of the reference's
round-robin job assignment, cluster_tasks.py:331): a batch of blocks is stacked
on the leading axis and sharded over a 1d ``data`` mesh; per-block kernels are
vmapped so XLA compiles one program for the whole batch and partitions it over
ICI.  Cross-block reductions (label merges, feature merges) then ride XLA
collectives instead of the reference's filesystem round-trips (SURVEY.md §2.9).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def local_device_count() -> int:
    return jax.local_device_count()


def get_mesh(devices: Optional[Sequence] = None, axis_name: str = "data") -> Mesh:
    """1d mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def shard_batch(batch, mesh: Optional[Mesh] = None, axis_name: str = "data"):
    """Place a [B, ...] stacked block batch with the leading axis sharded over
    the mesh.  B must be divisible by the mesh size (callers pad)."""
    if mesh is None:
        mesh = get_mesh(axis_name=axis_name)
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.device_put(batch, sharding)


# sharding of the most recent ``put_sharded`` placement — introspection hook so
# tests (and the driver dryrun) can assert that the production task path really
# partitioned its batch over the mesh rather than landing everything on device 0
_LAST_BATCH_SHARDING = None


def last_batch_sharding():
    return _LAST_BATCH_SHARDING


def resolve_devices(config: Optional[dict] = None):
    """Devices used for block data parallelism: the ``devices`` config entry
    (indices into ``jax.devices()`` or device objects — the TPU analog of the
    reference's per-job resource knobs) or all local devices."""
    devices = (config or {}).get("devices")
    if devices:
        all_devices = jax.devices()
        return [all_devices[d] if isinstance(d, int) else d for d in devices]
    return jax.local_devices()


def put_sharded(arr, config: Optional[dict] = None, axis_name: str = "data"):
    """Place a stacked [B, ...] block batch for compute: with >1 device the
    leading axis is padded (repeating the last block) to divide the 1d mesh and
    sharded over it; single-device falls back to a plain transfer.

    Returns ``(device_array, B)`` where ``B`` is the *unpadded* batch size —
    callers slice results back to ``[:B]``.  This is the production analog of
    the reference's round-robin block→job placement (cluster_tasks.py:331):
    blocks are the unit of data parallelism, and every kernel vmapped over the
    leading axis is partitioned over ICI by XLA.
    """
    global _LAST_BATCH_SHARDING
    b = arr.shape[0]
    # only the tpu target shards; 'local' is the single-device parity oracle
    # (sharding it would make local-vs-tpu comparisons vacuous and compute
    # every block n_dev times through the per-block path)
    if config is not None and config.get("target", "tpu") != "tpu":
        devices = []
    else:
        devices = resolve_devices(config)
    # a batch smaller than the mesh gains nothing from padding to it — run on
    # the first b devices instead of computing (n - b) wasted replicas
    if b < len(devices):
        devices = devices[:b]
    if len(devices) <= 1:
        out = jax.numpy.asarray(arr)
        _LAST_BATCH_SHARDING = out.sharding
        return out, b
    n = len(devices)
    pad = (-b) % n
    if pad:
        arr = np.concatenate(
            [arr, np.broadcast_to(arr[-1:], (pad,) + arr.shape[1:])], axis=0
        )
    out = shard_batch(arr, get_mesh(devices, axis_name), axis_name)
    _LAST_BATCH_SHARDING = out.sharding
    return out, b
