"""The flagship DT-watershed as ONE collective program over the device mesh.

``ops.watershed.dt_watershed`` fuses the whole per-block pipeline for one
chip; this module is its sharded form for volumes that exceed a chip's HBM:
the volume z-shards over the mesh and every cross-shard dependency rides an
XLA collective inside the jit program (SURVEY.md §2.8/§2.9 — the "volume
larger than HBM = long context" mapping):

  * z line-scan of the EDT — directional distance relaxation across shard
    boundaries (``lax.ppermute`` plane exchange, ``psum`` convergence); the
    y/x min-plus parabola passes are plane-local, so with z as the sharded
    axis they need no communication at all;
  * seed smoothing and the 3x3x3 maxima window — ``halo_exchange`` with the
    gaussian's true radius, symmetric padding at the volume's outer faces
    (bit-matching the single-device ``filters.gaussian``);
  * seed-plateau CC — the sharded min-label machinery (full connectivity);
  * height-map normalization — global ``lax.pmin/pmax``;
  * the flood — the sharded two-phase relaxation of ``parallel.sharded``.

The size filter needs per-segment voxel counts over data-dependent ids; the
host computes counts from the flood output (one transfer that the writing
task pays anyway) and a second collective flood re-floods the survivors —
the same split the reference's ``size_filter`` re-flood implies.

Exactness: every stage reproduces the single-device numerics (same kernels,
same accumulation windows), and seed ids (plateau-root flat indices + 1) are
order-isomorphic to ``dt_seeds``' consecutive ids, so flood tie-breaking
agrees — ``sharded_dt_watershed`` yields the SAME PARTITION as
``dt_watershed(apply_dt_2d=False, apply_ws_2d=False)`` (tested on the
8-virtual-device mesh).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..obs import trace as obs_trace
from ..ops.dt import _BIG as _DT_BIG
from ..ops.dt import _parabola_pass
from ..ops.filters import _gauss_kernel
from .mesh import get_mesh
from .sharded import _neighbor_planes, axis_size, halo_exchange, shard_map


def _directional_z_distance(bg, axis_name, reverse):
    """Distance (in planes) to the nearest background plane at-or-before each
    voxel along z, across shard boundaries.

    Local part: cummax index arithmetic (exact within the shard).  Cross-
    shard: the incoming boundary distance grows linearly inside the shard
    (cand(z) = carry + z + 1), so one plane exchange updates every local
    plane at once; rounds iterate until the global fixpoint (information
    crosses one boundary per round, like the flood)."""
    z_local = bg.shape[0]
    b = jnp.flip(bg, 0) if reverse else bg
    iota = jnp.arange(z_local, dtype=jnp.float32)[:, None, None]
    last_bg = lax.cummax(jnp.where(b, iota, -_DT_BIG), axis=0)
    local = jnp.minimum(iota - last_bg, _DT_BIG)

    direction = -1 if reverse else +1

    def body(state):
        d, _ = state
        # the neighbor's far-plane distance, +1 for the boundary hop
        carry = _neighbor_planes(d[-1], axis_name, +1 * direction)
        n = axis_size(axis_name)
        idx = lax.axis_index(axis_name)
        edge = idx == (0 if direction > 0 else n - 1)
        carry = jnp.where(edge, jnp.full_like(carry, _DT_BIG), carry)
        cand = jnp.minimum(carry[None] + iota + 1.0, _DT_BIG)
        new = jnp.minimum(d, cand)
        changed = lax.psum(jnp.any(new != d).astype(jnp.int32), axis_name) > 0
        return new, changed

    local, _ = lax.while_loop(
        lambda st: st[1], body, (local, jnp.bool_(True))
    )
    return jnp.flip(local, 0) if reverse else local


def _sharded_edt(fg, pitch, axis_name):
    """Squared→exact Euclidean DT of a z-sharded foreground mask: cross-shard
    z line scan + plane-local min-plus parabola passes (ops.dt numerics)."""
    bg = ~fg
    fwd = _directional_z_distance(bg, axis_name, False)
    bwd = _directional_z_distance(bg, axis_name, True)
    g = (jnp.minimum(fwd, bwd) * pitch[0]) ** 2
    for axis in (1, 2):
        g = jnp.moveaxis(g, axis, -1)
        g = _parabola_pass(g, pitch[axis], 32)
        g = jnp.moveaxis(g, -1, axis)
    return jnp.sqrt(jnp.minimum(g, _DT_BIG)).astype(jnp.float32)


def _reflect_z(ext, radius, z_local, axis_name, total):
    """Replace out-of-volume halo planes with the volume's symmetric
    reflection (jnp.pad mode="symmetric": global position g < 0 mirrors
    plane -g-1, g >= total mirrors 2*total-g-1).  ``total`` is the REAL
    volume depth — when the z-extent was padded up to mesh divisibility this
    is smaller than n*z_local, and the pad slab itself mirrors real planes.
    With multi-hop halos a SHALLOW shard near an edge also has out-of-volume
    planes (not just shard 0 / n-1); one gather fixes all cases.

    Scope: mirror sources are provably in range for every tap feeding a REAL
    (g < total) output plane; taps feeding pad-slab outputs (internal
    z-padding, ``total < n*z_local``) may clip to a wrong plane — callers
    MUST mask pad-slab outputs out (the watershed stages do, via ``valid``).
    """
    idx = lax.axis_index(axis_name)
    z0 = idx * z_local
    g = z0 - radius + jnp.arange(ext.shape[0])
    src = jnp.where(g < 0, -g - 1, jnp.where(g >= total, 2 * total - g - 1, g))
    loc = jnp.clip(src - (z0 - radius), 0, ext.shape[0] - 1)
    return jnp.take(ext, loc, axis=0)


def _sharded_gaussian_z(x, sigma, axis_name, total):
    """Gaussian smoothing matching ``filters.gaussian`` on the unsharded
    volume of depth ``total``: y/x passes are plane-local; the z pass
    convolves a halo-extended shard (neighbor planes via ppermute, symmetric
    padding at the volume's outer faces — the same boundary rule
    ``_conv_along_axis`` applies)."""
    from ..ops.filters import _conv_along_axis

    x = x.astype(jnp.float32)
    kernel = jnp.asarray(_gauss_kernel(float(sigma), 0))
    radius = kernel.shape[0] // 2
    ext = halo_exchange(x, radius, axis_name)
    ext = _reflect_z(ext, radius, x.shape[0], axis_name, total)
    # z pass on the extended shard (halo consumed by the VALID conv)
    moved = jnp.moveaxis(ext, 0, -1)
    smoothed = _conv_along_axis_valid(moved, kernel)
    out = jnp.moveaxis(smoothed, -1, 0)
    # y/x passes, plane-local
    for axis in (1, 2):
        out = _conv_along_axis(out, kernel, axis)
    return out


def _conv_along_axis_valid(x, kernel):
    """1d conv along the last axis with NO padding (the caller supplied the
    halo), matching ``filters._conv_along_axis``'s accumulation."""
    batch_shape = x.shape[:-1]
    n = x.shape[-1]
    flat = x.reshape(-1, 1, n)
    out = lax.conv_general_dilated(
        flat, kernel[::-1].reshape(1, 1, -1),
        window_strides=(1,), padding="VALID",
    )
    return out.reshape(batch_shape + (out.shape[-1],))


def _local_maxima(smoothed, axis_name, total):
    """3x3x3 window maxima across shard boundaries: 1-plane halo exchange,
    then the same symmetric-edge reduce_window the single-device
    ``maximum_filter`` applies (1-deep symmetric pad == edge value at the
    real volume boundary ``total``)."""
    ext = halo_exchange(smoothed, 1, axis_name, fill=-np.inf)
    ext = _reflect_z(ext, 1, smoothed.shape[0], axis_name, total)
    pad_yx = [(0, 0), (1, 1), (1, 1)]
    padded = jnp.pad(ext, pad_yx, mode="symmetric")
    win = lax.reduce_window(
        padded, -jnp.inf, lax.max, (3, 3, 3), (1, 1, 1), "VALID"
    )
    return win == smoothed


@partial(
    jax.jit,
    static_argnames=(
        "threshold", "pitch", "sigma_seeds", "sigma_weights", "alpha",
        "invert_input", "axis_name", "mesh", "z_valid",
    ),
)
def _stage_a(
    x, threshold, pitch, sigma_seeds, sigma_weights, alpha, invert_input,
    axis_name, mesh, z_valid,
):
    """threshold → EDT → smoothed maxima → height map, one collective jit
    (module-level so one compilation serves every same-shape volume).

    ``z_valid`` (static) is the REAL volume depth: when z was padded up to
    mesh divisibility (with a foreground-side value, so the pad contributes
    no DT background), smoothing mirrors at the true boundary, maxima and
    the flood mask exclude the pad slab, and the normalization ignores it —
    the result matches the unpadded single-device kernel exactly."""

    def local_fn(x):
        z_local = x.shape[0]
        idx = lax.axis_index(axis_name)
        valid = (idx * z_local + jnp.arange(z_local) < z_valid)[:, None, None]
        if invert_input:
            x = 1.0 - x
        fg = x < threshold
        dt = _sharded_edt(fg, pitch, axis_name)
        smoothed = (
            _sharded_gaussian_z(dt, sigma_seeds, axis_name, z_valid)
            if sigma_seeds and sigma_seeds > 0 else dt
        )
        maxima = _local_maxima(smoothed, axis_name, z_valid) & (dt > 0) & valid
        # global normalize for the height map, over real voxels only
        gmin = lax.pmin(jnp.min(jnp.where(valid, dt, _DT_BIG)), axis_name)
        gmax = lax.pmax(jnp.max(jnp.where(valid, dt, -_DT_BIG)), axis_name)
        dtn = (dt - gmin) / jnp.maximum(gmax - gmin, 1e-6)
        hmap = alpha * x + (1.0 - alpha) * (1.0 - dtn)
        if sigma_weights and sigma_weights > 0:
            hmap = _sharded_gaussian_z(hmap, sigma_weights, axis_name, z_valid)
        return fg & valid, maxima, hmap

    return shard_map(
        local_fn, mesh=mesh, in_specs=P(axis_name),
        out_specs=(P(axis_name),) * 3, check_vma=False,
    )(x)


def _stage_input(input_, mesh, axis_name, invert_input, z_valid, who):
    """Shared placement contract of both collective watershed kernels:
    accept a pre-placed (padded) device array carrying the mesh sharding —
    validated float32 with a mesh-divisible z extent, ``z_valid``
    required — or a host array, padded on the foreground side of the
    threshold and placed via ``put_global``.  Returns ``(x_d, z_valid)``."""
    from .mesh import put_global

    n = mesh.shape[axis_name]
    pre_placed = isinstance(input_, jax.Array) and input_.sharding.is_equivalent_to(
        NamedSharding(mesh, P(axis_name)), input_.ndim
    )
    if pre_placed:
        if z_valid is None:
            raise ValueError(
                f"pass z_valid when handing {who} a pre-placed (possibly "
                "padded) device array"
            )
        if input_.dtype != jnp.float32 or input_.shape[0] % n:
            raise ValueError(
                "pre-placed input must be float32 with a mesh-divisible z "
                f"extent, got {input_.dtype} {input_.shape}"
            )
        return input_, int(z_valid)
    if z_valid is None:
        z_valid = int(input_.shape[0])
    pad = (-z_valid) % n
    arr = np.asarray(input_, dtype=np.float32)
    if pad:
        # foreground side of the threshold AFTER the kernel's inversion
        # (assumes 0 < threshold < 1, the reference's probability range)
        pad_val = 1.0 if invert_input else 0.0
        arr = np.pad(
            arr, ((0, pad), (0, 0), (0, 0)), constant_values=pad_val
        )
    return put_global(arr, mesh, axis_name, dtype=np.float32), int(z_valid)


@obs_trace.traced(kind="collective")
def sharded_dt_watershed_2d(
    input_,
    mesh=None,
    axis_name: str = "data",
    threshold: float = 0.25,
    sigma_seeds: float = 2.0,
    sigma_weights: float = 2.0,
    alpha: float = 0.8,
    size_filter: int = 25,
    invert_input: bool = False,
    z_valid: Optional[int] = None,
) -> Tuple[np.ndarray, int]:
    """Per-slice (2d DT + 2d flood) whole-volume watershed over the mesh —
    the collective form of the reference's CREMI default
    (``apply_dt_2d=True, apply_ws_2d=True``, watershed.py:286-344's 2d
    branch).

    z-slices are INDEPENDENT in this mode, so z-sharding makes the whole
    computation embarrassingly parallel: every shard runs the fused
    single-device kernel on its slab and NO collective is needed at all —
    the cheapest possible mapping onto the mesh (no cross-shard rounds, no
    boundary exchanges; contrast ``sharded_dt_watershed``'s 3d fixpoints).
    Slices are processed by the identical single-device kernel, so the
    PARTITION equals ``dt_watershed(x, apply_dt_2d=True,
    apply_ws_2d=True)`` exactly (tested).  Label values are slab-local
    (the kernel numbers seeds consecutively within its input) made
    globally unique by the shard's plane offset ``z0*Y*X`` — callers
    relabel consecutively anyway (both tasks do).

    Pad slabs (z not divisible by the mesh) are excluded via the kernel's
    ``valid`` mask, so they produce no labels.  Returns
    ``(labels int32 [host, z_valid], n_bound)`` where ``n_bound`` is the
    summed per-slab max id — the exact distinct count when
    ``size_filter=0`` and an upper bound otherwise (the filter removes ids
    without renumbering); production callers relabel consecutively anyway.
    """
    from ..ops.watershed import dt_watershed
    from .mesh import fetch_global

    mesh = mesh if mesh is not None else get_mesh(axis_name=axis_name)
    n = mesh.shape[axis_name]
    x_d, z_valid = _stage_input(
        input_, mesh, axis_name, invert_input, z_valid,
        "sharded_dt_watershed_2d",
    )
    zp, Y, X = x_d.shape
    if zp * Y * X >= np.iinfo(np.int32).max:
        raise ValueError(
            "volume exceeds the int32 flat-index label space "
            f"({zp}x{Y}x{X}); split it into ROIs"
        )
    h = zp // n

    def local_fn(x):
        idx = lax.axis_index(axis_name)
        z0 = idx * h
        plane = z0 + jnp.arange(h, dtype=jnp.int32)
        valid = jnp.broadcast_to(
            (plane < z_valid)[:, None, None], x.shape
        )
        lab, _ = dt_watershed(
            x, threshold=threshold, apply_dt_2d=True, apply_ws_2d=True,
            sigma_seeds=sigma_seeds, sigma_weights=sigma_weights,
            alpha=alpha, size_filter=size_filter,
            invert_input=invert_input, valid=valid,
        )
        off = z0 * jnp.int32(Y * X)
        # the kernel numbers its slab's seeds 1..k consecutively, so the
        # slab max bounds the slab's distinct count (exact when no size
        # filter removes ids) — summed on host below, no full-volume
        # unique pass for a value production callers discard
        return jnp.where(lab > 0, lab + off, 0), jnp.max(lab)[None]

    labels_d, n_per_shard = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=P(axis_name),
        out_specs=(P(axis_name), P(axis_name)),
        check_vma=False,
    )(x_d)
    labels = fetch_global(labels_d)[:z_valid]
    n_labels = int(np.asarray(n_per_shard).sum())
    return labels, n_labels


@obs_trace.traced(kind="collective")
def sharded_dt_watershed(
    input_,
    mesh=None,
    axis_name: str = "data",
    threshold: float = 0.25,
    pixel_pitch: Optional[Tuple[float, ...]] = None,
    sigma_seeds: float = 2.0,
    sigma_weights: float = 2.0,
    alpha: float = 0.8,
    size_filter: int = 25,
    invert_input: bool = False,
    z_valid: Optional[int] = None,
) -> Tuple[np.ndarray, int]:
    """DT-watershed of a whole z-sharded volume — the collective form of
    ``dt_watershed(apply_dt_2d=False, apply_ws_2d=False)`` (3d DT + 3d flood).

    Returns ``(labels int32 [host], n_seeds)``: labels carry seed-plateau
    root ids (+1); the partition equals the single-device kernel's (ids are
    order-isomorphic, so the min-label tie-break agrees — tested, including
    non-divisible z).  The size filter counts on host between two collective
    programs (see module docstring).  A z-extent not divisible by the mesh
    size is padded internally on the foreground side of the threshold — the
    pad contributes no DT background, mirrors at the TRUE boundary for
    smoothing, and is excluded from seeds/flood/counts, so the result still
    matches the unpadded single-device kernel.  Shards shallower than a
    gaussian radius are fine (multi-hop halos).

    ``input_`` may also be an already-placed (padded) device array carrying
    the mesh sharding — e.g. streamed by ``mesh.put_from_store(pad_to=n,
    pad_value=<foreground side>)`` — in which case ``z_valid`` must give
    the real (unpadded) z extent.
    """
    from .sharded import sharded_seeded_watershed

    mesh = mesh if mesh is not None else get_mesh(axis_name=axis_name)
    x_d, z_valid = _stage_input(
        input_, mesh, axis_name, invert_input, z_valid,
        "sharded_dt_watershed",
    )
    pitch = (1.0,) * 3 if pixel_pitch is None else tuple(
        float(p) for p in pixel_pitch
    )
    from .mesh import fetch_global

    fg_d, maxima_d, hmap_d = _stage_a(
        x_d, threshold, pitch, sigma_seeds, sigma_weights, alpha,
        invert_input, axis_name, mesh, z_valid,
    )

    # seed-plateau CC over the mesh (full connectivity, like dt_seeds)
    from .sharded import _sharded_cc

    roots = _sharded_cc(maxima_d, 3, axis_name, mesh)
    seeds_d = jnp.where(roots >= 0, roots + 1, 0).astype(jnp.int32)

    labels = sharded_seeded_watershed(
        hmap_d, seeds_d, mask=fg_d, mesh=mesh, axis_name=axis_name
    )
    labels = fetch_global(labels)
    uniq, counts = np.unique(labels, return_counts=True)
    n_seeds = int((uniq > 0).sum())
    if size_filter > 0:
        # the pad slab holds no labels (flood mask excludes it), so these
        # counts are real-voxel counts
        too_small = uniq[(counts < size_filter) & (uniq > 0)]
        if too_small.size:
            kept = np.where(np.isin(labels, too_small), 0, labels)
            labels = fetch_global(
                sharded_seeded_watershed(
                    hmap_d, kept.astype(np.int32), mask=fg_d, mesh=mesh,
                    axis_name=axis_name,
                )
            )
    return labels[:z_valid], n_seeds
