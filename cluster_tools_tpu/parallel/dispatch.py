"""Host↔device block batching: read N blocks, run one jit program, write back.

The static-shape contract: every block in a batch is padded to the full
(halo-extended) block shape so XLA compiles exactly one program per block
geometry; validity masks carry the true extent.  Edge blocks therefore cost the
same as interior blocks — the TPU trade the reference never has to make, but the
win is that a whole batch is one dispatch instead of N python loop iterations.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..obs import trace as obs_trace
from ..utils.blocking import Blocking, BlockWithHalo


@dataclass
class BlockBatch:
    """A stacked batch of (possibly halo'd) blocks plus their geometry."""

    data: np.ndarray  # [B, *padded_shape] (+ leading channel dim inside shape)
    valid: np.ndarray  # [B, ndim, 2] valid [begin, end) inside the padded block
    blocks: List[BlockWithHalo]
    block_ids: List[int]

    @property
    def batch_size(self) -> int:
        return len(self.block_ids)


def read_block_batch(
    ds,
    blocking: Blocking,
    block_ids: Sequence[int],
    halo: Optional[Sequence[int]] = None,
    pad_to: Optional[int] = None,
    dtype=None,
    n_threads: int = 4,
) -> BlockBatch:
    """Read blocks (outer boxes when ``halo``), pad each to the static shape,
    stack.  ``pad_to`` pads the batch axis (repeating the last block) so the
    batch divides the device count.

    Reads fan out over ``n_threads`` (chunk decode is gzip-bound, so threads
    overlap IO + decompression — the intra-batch analog of the executor's
    batch pipelining).  HDF5 datasets are forced to a single thread: h5py
    serializes every call behind a global lock, so the fan-out is pure
    overhead there (and unsafe on non-threadsafe libhdf5 builds)."""
    if (
        getattr(ds, "_is_hdf5", False)
        or type(ds).__module__.split(".")[0] == "h5py"
    ):
        n_threads = 1
    ndim = blocking.ndim
    halo = tuple(halo) if halo is not None else (0,) * ndim
    full_shape = tuple(bs + 2 * h for bs, h in zip(blocking.block_shape, halo))

    blocks = [blocking.block_with_halo(bid, halo) for bid in block_ids]

    def _read(bh: BlockWithHalo) -> np.ndarray:
        arr = ds[bh.outer.slicing]
        if dtype is not None:
            arr = arr.astype(dtype, copy=False)
        pad_width = [(0, fs - s) for fs, s in zip(full_shape, arr.shape)]
        if any(p[1] for p in pad_width):
            arr = np.pad(arr, pad_width)
        return arr

    # block_ids tag (ctt-watch): lets the live reader / Perfetto tie this
    # host-IO interval to the specific volume regions it touched
    with obs_trace.span(
        "read_block_batch", kind="host_io", blocks=len(blocks),
        block_ids=[int(b) for b in block_ids],
    ):
        if n_threads > 1 and len(blocks) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(min(n_threads, len(blocks))) as pool:
                datas = list(pool.map(_read, blocks))
        else:
            datas = [_read(bh) for bh in blocks]
    valids = [
        [[0, e - b] for b, e in zip(bh.outer.begin, bh.outer.end)]
        for bh in blocks
    ]
    ids = list(block_ids)

    if pad_to is not None and len(datas) % pad_to:
        n_extra = pad_to - len(datas) % pad_to
        for _ in range(n_extra):
            datas.append(datas[-1])
            valids.append(valids[-1])

    return BlockBatch(
        data=np.stack(datas),
        valid=np.asarray(valids, dtype=np.int32),
        blocks=blocks,
        block_ids=list(ids),
    )


def _chunk_aligned_region(ds, bh: BlockWithHalo) -> bool:
    """True when the block's inner write region covers whole chunks of
    ``ds`` — begin on a chunk boundary, end on one or at the volume edge.
    Aligned regions of distinct blocks can never share a chunk, so their
    writes are free of read-modify-write races."""
    chunks = getattr(ds, "chunks", None)
    shape = getattr(ds, "shape", None)
    begin, end = bh.inner.begin, bh.inner.end
    if chunks is None or shape is None or len(chunks) != len(begin):
        return False
    for b, e, c, s in zip(begin, end, chunks, shape):
        if b % c or (e % c and e != s):
            return False
    return True


def write_block_batch(
    ds,
    batch: BlockBatch,
    results: np.ndarray,
    cast=None,
    n_threads: int = 4,
) -> None:
    """Write each block's *inner* region back (halo cropped, padding dropped).

    Only the inner box is written — overlap is re-read, never written, the
    reference's no-write-race construction (SURVEY.md §2.8.2).

    Writes fan out over ``n_threads`` (mirroring ``read_block_batch``: chunk
    encode is codec-bound and releases the GIL) — but ONLY when every
    block's inner region is chunk-aligned in ``ds``, so no two blocks
    read-modify-write the same chunk concurrently; misaligned layouts and
    hdf5 (global lock) keep the serial loop."""
    if (
        getattr(ds, "_is_hdf5", False)
        or type(ds).__module__.split(".")[0] == "h5py"
        or not all(_chunk_aligned_region(ds, bh) for bh in batch.blocks)
    ):
        n_threads = 1

    def _write(i_bh) -> None:
        i, bh = i_bh
        arr = results[i]
        local = bh.inner_local
        arr = np.asarray(arr[local.slicing])
        if cast is not None:
            arr = arr.astype(cast)
        ds[bh.inner.slicing] = arr

    with obs_trace.span(
        "write_block_batch", kind="host_io", blocks=len(batch.blocks),
        block_ids=[int(b) for b in batch.block_ids],
    ):
        if n_threads > 1 and len(batch.blocks) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                min(n_threads, len(batch.blocks))
            ) as pool:
                list(pool.map(_write, enumerate(batch.blocks)))
        else:
            for i_bh in enumerate(batch.blocks):
                _write(i_bh)
