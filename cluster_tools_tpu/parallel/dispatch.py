"""Host↔device block batching: read N blocks, run one jit program, write back.

The static-shape contract: every block in a batch is padded to the full
(halo-extended) block shape so XLA compiles exactly one program per block
geometry; validity masks carry the true extent.  Edge blocks therefore cost the
same as interior blocks — the TPU trade the reference never has to make, but the
win is that a whole batch is one dispatch instead of N python loop iterations.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import trace as obs_trace
from ..utils.blocking import Blocking, BlockWithHalo


def form_batches(block_ids: Sequence[int], batch_size: int) -> List[List[int]]:
    """Chunk a block-id sequence into dispatch batches — the ONE batch
    formation rule, shared by the device executor (blocks per jit
    dispatch), the fused-chain runner, and the ctt-steal work queue
    (blocks per lease), so a pulled item and a static dispatch chunk the
    same id run identically."""
    ids = [int(b) for b in block_ids]
    bs = max(int(batch_size), 1)
    return [ids[i: i + bs] for i in range(0, len(ids), bs)]


def batch_outer_boxes(blocking: Blocking, block_ids: Sequence[int],
                      halo: Sequence[int]):
    """Halo'd outer boxes of a batch plus its bounding box and the
    bounding-box profitability verdict — the ONE rule shared by the
    fused-chain read cache and the async-prefetch hook (ctt-cloud):
    consecutive C-order ids form a (near-)contiguous region, so one
    bounding-box read decodes every covered chunk exactly once; sparse id
    runs (retry rounds) fall back to per-block boxes.

    Returns ``(blocks, lo, hi, bbox_profitable)`` where ``bbox_profitable``
    is True when the bounding box holds no more voxels than the per-block
    outer boxes combined."""
    bhs = [blocking.block_with_halo(bid, tuple(halo)) for bid in block_ids]
    lo = tuple(
        min(bh.outer.begin[d] for bh in bhs) for d in range(blocking.ndim)
    )
    hi = tuple(
        max(bh.outer.end[d] for bh in bhs) for d in range(blocking.ndim)
    )
    bbox_voxels = int(np.prod([e - b for b, e in zip(lo, hi)]))
    block_voxels = sum(int(np.prod(bh.outer.shape)) for bh in bhs)
    return bhs, lo, hi, bbox_voxels <= block_voxels


@dataclass
class BlockBatch:
    """A stacked batch of (possibly halo'd) blocks plus their geometry.

    ctt-hbm: ``source`` carries the batch's store identity + freshness
    (``runtime.hbm.BatchSource``) when the device-buffer cache is armed;
    ``device`` the resident ``DeviceBatch`` — either a read-time probe
    hit (then ``data`` may be None: the host read was skipped entirely)
    or the upload stage's transfer result."""

    data: Optional[np.ndarray]  # [B, *padded_shape] (+ leading channel dims)
    valid: np.ndarray  # [B, ndim, 2] valid [begin, end) inside the padded block
    blocks: List[BlockWithHalo]
    block_ids: List[int]
    source: Any = None   # runtime.hbm.BatchSource when cacheable
    device: Any = None   # runtime.hbm.DeviceBatch when resident

    @property
    def batch_size(self) -> int:
        return len(self.block_ids)


# ---------------------------------------------------------------------------
# ctt-stream: per-batch shared block-read cache (cross-task halo
# reconciliation).  A fused chain reads each block's region from the store
# ONCE at the chain's maximum halo; every member's own read path then runs
# against crops of that host buffer — the member's unchanged pad/normalize
# code produces byte-identical payloads because a crop of a larger store
# read equals the direct smaller read.


class BlockReadCache:
    """Host cache of block-region reads for one fused-chain batch.

    ``prefetch`` reads each block's halo'd outer box (leading non-spatial
    axes in full) through the real dataset — the only store traffic.
    ``get`` serves any slice-expressible request fully contained in a
    cached box as a view; anything else misses (the caller falls through to
    the store, which stays correct, just unshared)."""

    def __init__(self) -> None:
        # (path, key) -> list of (begin, end, array) over ALL ds dims
        self._boxes: Dict[Tuple[str, str], List[Tuple[tuple, tuple, np.ndarray]]] = {}

    def prefetch(self, ds, path: str, key: str, blocking: Blocking,
                 block_ids: Sequence[int], halo: Sequence[int]) -> None:
        """One store read per batch when profitable: consecutive C-order
        block ids form a (near-)contiguous region, so reading the batch's
        halo'd *bounding box* decodes every covered chunk exactly once —
        per-block halo'd reads would re-decode each shared chunk up to
        2^ndim times (the amplification the decoded-chunk LRU papers over
        in-process; a fused chain removes it structurally: the z-slab is
        read once).  Falls back to per-block boxes when the bounding box
        would read more voxels than the per-block reads combined (sparse
        id runs)."""
        extra = len(ds.shape) - blocking.ndim
        lead = tuple(slice(0, s) for s in ds.shape[:extra])
        boxes = self._boxes.setdefault((path, key), [])
        bhs, lo, hi, bbox_ok = batch_outer_boxes(blocking, block_ids, halo)
        if bbox_ok:
            index = lead + tuple(slice(b, e) for b, e in zip(lo, hi))
            arr = np.asarray(ds[index])
            boxes.append((
                tuple(sl.start for sl in index),
                tuple(sl.stop for sl in index),
                arr,
            ))
            return
        for bh in bhs:
            index = lead + bh.outer.slicing
            arr = np.asarray(ds[index])
            begin = tuple(sl.start for sl in index)
            end = tuple(sl.stop for sl in index)
            boxes.append((begin, end, arr))

    def get(self, path: str, key: str, index, shape) -> Optional[np.ndarray]:
        boxes = self._boxes.get((path, key))
        if not boxes:
            return None
        norm = _normalize_index(index, shape)
        if norm is None:
            return None
        begin, end = norm
        for cb, ce, arr in boxes:
            if all(b >= b0 and e <= e0 for b, e, b0, e0 in zip(begin, end, cb, ce)):
                return arr[tuple(
                    slice(b - b0, e - b0) for b, e, b0 in zip(begin, end, cb)
                )]
        return None


def _normalize_index(index, shape) -> Optional[Tuple[tuple, tuple]]:
    """Resolve a __getitem__ key into (begin, end) per axis; None when the
    key is not a plain box (fancy indexing, ints, steps)."""
    if not isinstance(index, tuple):
        index = (index,)
    if len(index) > len(shape):
        return None
    index = index + (slice(None),) * (len(shape) - len(index))
    begin, end = [], []
    for sl, s in zip(index, shape):
        if not isinstance(sl, slice) or (sl.step not in (None, 1)):
            return None
        b = 0 if sl.start is None else int(sl.start)
        e = s if sl.stop is None else int(sl.stop)
        if b < 0 or e < 0:
            return None
        begin.append(b)
        end.append(min(e, s))
    return tuple(begin), tuple(end)


class CachedDataset:
    """A dataset proxy serving reads from a :class:`BlockReadCache` when
    possible; attribute access and cache misses delegate to the wrapped
    dataset.  Read-only by design — fused chains never write through it."""

    def __init__(self, ds, cache: BlockReadCache, path: str, key: str):
        self._ds = ds
        self._cache = cache
        self._path = path
        self._key = key
        # read_block_batch's h5py thread-gate checks this attribute and the
        # wrapped type's module; forward the verdict explicitly
        self._is_hdf5 = bool(
            getattr(ds, "_is_hdf5", False)
            or type(ds).__module__.split(".")[0] == "h5py"
        )

    def __getattr__(self, name):
        return getattr(self._ds, name)

    def __getitem__(self, index):
        hit = self._cache.get(self._path, self._key, index, self._ds.shape)
        if hit is not None:
            return hit
        return self._ds[index]


_READ_CACHE_TLS = threading.local()


def active_read_cache() -> Optional[BlockReadCache]:
    return getattr(_READ_CACHE_TLS, "cache", None)


@contextlib.contextmanager
def use_read_cache(cache: BlockReadCache):
    """Install ``cache`` for the current thread: dataset opens inside the
    context (``VolumeTask.input_ds`` and friends) come back wrapped so the
    task's own read code transparently hits the prefetched boxes."""
    prev = getattr(_READ_CACHE_TLS, "cache", None)
    _READ_CACHE_TLS.cache = cache
    try:
        yield cache
    finally:
        _READ_CACHE_TLS.cache = prev


def wrap_with_read_cache(ds, path: str, key: str):
    """Wrap ``ds`` in the thread's active read cache (no-op outside a fused
    chain's read stage)."""
    cache = active_read_cache()
    if cache is None:
        return ds
    return CachedDataset(ds, cache, path, key)


def read_block_batch(
    ds,
    blocking: Blocking,
    block_ids: Sequence[int],
    halo: Optional[Sequence[int]] = None,
    pad_to: Optional[int] = None,
    dtype=None,
    n_threads: int = 4,
    device_source: Optional[tuple] = None,
) -> BlockBatch:
    """Read blocks (outer boxes when ``halo``), pad each to the static shape,
    stack.  ``pad_to`` pads the batch axis (repeating the last block) so the
    batch divides the device count.

    Reads fan out over ``n_threads`` (chunk decode is gzip-bound, so threads
    overlap IO + decompression — the intra-batch analog of the executor's
    batch pipelining).  HDF5 datasets are forced to a single thread: h5py
    serializes every call behind a global lock, so the fan-out is pure
    overhead there (and unsafe on non-threadsafe libhdf5 builds).

    ctt-hbm: ``device_source = (path, key, tag, config)`` arms the warm
    device-buffer cache — the batch's store region is signature-probed
    (the chunk LRU's own freshness keys) and, when the identical upload
    is already HBM-resident, the host read is SKIPPED entirely: the
    returned batch carries geometry + the resident device arrays and
    ``data=None``.  A miss reads normally and stamps ``batch.source`` so
    the upload stage can insert the transfer for the next job."""
    if (
        getattr(ds, "_is_hdf5", False)
        or type(ds).__module__.split(".")[0] == "h5py"
    ):
        n_threads = 1
    ndim = blocking.ndim
    halo = tuple(halo) if halo is not None else (0,) * ndim
    full_shape = tuple(bs + 2 * h for bs, h in zip(blocking.block_shape, halo))

    blocks = [blocking.block_with_halo(bid, halo) for bid in block_ids]

    hbm_source = None
    if device_source is not None and pad_to is None:
        from ..runtime import hbm

        s_path, s_key, s_tag, s_config = device_source
        hbm_source = hbm.dataset_source(
            ds, s_path, s_key, blocking, list(block_ids), halo,
            (tuple(s_tag) + (str(dtype),)), s_config,
        )
        if hbm_source is not None:
            dc = hbm.cache()
            hit = dc.get(hbm_source) if dc is not None else None
            if hit is not None:
                from ..obs import metrics as obs_metrics

                obs_metrics.inc("device.uploads_skipped")
                valids = [
                    [[0, e - b] for b, e in zip(bh.outer.begin, bh.outer.end)]
                    for bh in blocks
                ]
                return BlockBatch(
                    data=None, valid=np.asarray(valids, dtype=np.int32),
                    blocks=blocks, block_ids=list(block_ids),
                    source=hbm_source, device=hit,
                )

    def _read(bh: BlockWithHalo) -> np.ndarray:
        arr = ds[bh.outer.slicing]
        if dtype is not None:
            arr = arr.astype(dtype, copy=False)
        pad_width = [(0, fs - s) for fs, s in zip(full_shape, arr.shape)]
        if any(p[1] for p in pad_width):
            arr = np.pad(arr, pad_width)
        return arr

    # block_ids tag (ctt-watch): lets the live reader / Perfetto tie this
    # host-IO interval to the specific volume regions it touched
    with obs_trace.span(
        "read_block_batch", kind="host_io", blocks=len(blocks),
        block_ids=[int(b) for b in block_ids],
    ):
        if n_threads > 1 and len(blocks) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(min(n_threads, len(blocks))) as pool:
                datas = list(pool.map(_read, blocks))
        else:
            datas = [_read(bh) for bh in blocks]
    valids = [
        [[0, e - b] for b, e in zip(bh.outer.begin, bh.outer.end)]
        for bh in blocks
    ]
    ids = list(block_ids)

    if pad_to is not None and len(datas) % pad_to:
        n_extra = pad_to - len(datas) % pad_to
        for _ in range(n_extra):
            datas.append(datas[-1])
            valids.append(valids[-1])

    return BlockBatch(
        data=np.stack(datas),
        valid=np.asarray(valids, dtype=np.int32),
        blocks=blocks,
        block_ids=list(ids),
        source=hbm_source,
    )


def _chunk_aligned_region(ds, bh: BlockWithHalo) -> bool:
    """True when the block's inner write region covers whole chunks of
    ``ds`` — begin on a chunk boundary, end on one or at the volume edge.
    Aligned regions of distinct blocks can never share a chunk, so their
    writes are free of read-modify-write races."""
    chunks = getattr(ds, "chunks", None)
    shape = getattr(ds, "shape", None)
    begin, end = bh.inner.begin, bh.inner.end
    if chunks is None or shape is None or len(chunks) != len(begin):
        return False
    for b, e, c, s in zip(begin, end, chunks, shape):
        if b % c or (e % c and e != s):
            return False
    return True


def write_block_batch(
    ds,
    batch: BlockBatch,
    results: np.ndarray,
    cast=None,
    n_threads: int = 4,
) -> None:
    """Write each block's *inner* region back (halo cropped, padding dropped).

    Only the inner box is written — overlap is re-read, never written, the
    reference's no-write-race construction (SURVEY.md §2.8.2).

    Writes fan out over ``n_threads`` (mirroring ``read_block_batch``: chunk
    encode is codec-bound and releases the GIL) — but ONLY when every
    block's inner region is chunk-aligned in ``ds``, so no two blocks
    read-modify-write the same chunk concurrently; misaligned layouts and
    hdf5 (global lock) keep the serial loop."""
    if (
        getattr(ds, "_is_hdf5", False)
        or type(ds).__module__.split(".")[0] == "h5py"
        or not all(_chunk_aligned_region(ds, bh) for bh in batch.blocks)
    ):
        n_threads = 1

    def _write(i_bh) -> None:
        i, bh = i_bh
        arr = results[i]
        local = bh.inner_local
        arr = np.asarray(arr[local.slicing])
        if cast is not None:
            arr = arr.astype(cast)
        ds[bh.inner.slicing] = arr

    with obs_trace.span(
        "write_block_batch", kind="host_io", blocks=len(batch.blocks),
        block_ids=[int(b) for b in batch.block_ids],
    ):
        if n_threads > 1 and len(batch.blocks) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                min(n_threads, len(batch.blocks))
            ) as pool:
                list(pool.map(_write, enumerate(batch.blocks)))
        else:
            for i_bh in enumerate(batch.blocks):
                _write(i_bh)
