"""RAG edge-feature accumulation over the device mesh.

The block pipeline accumulates 10 features per RAG edge block-by-block and
merges the partials through the scratch store (tasks/features.py); this is
the collective form for a z-sharded whole volume (SURVEY.md §2.9: "feature
merges ride all_gather/psum instead of files"):

  1. per shard: face-pair samples (one +z neighbor plane via ``ppermute``
     owns the cross-shard pairs; each pair is owned by exactly one shard) →
     3-key sort → segment reduction into a fixed-size SUFFICIENT-STATISTICS
     table: (u, v, count, sum, sum², min, max, histogram-sketch row) — the
     mergeable form of the 10 features;
  2. ``lax.all_gather`` of the per-shard tables (kilobytes — tables, not
     samples) → lexicographic argsort by (u, v) → one more segment reduction
     merges the partial statistics of edges spanning shards;
  3. finalize: mean/variance from the moments, quantiles from the merged
     histogram sketch (the same convention as the host merge,
     ops/rag._histogram_quantiles — exact to one bin width).

Count/mean/min/max columns match the host oracle exactly; the five quantile
columns are sketch-accurate (≤ 1/HIST_BINS drift), the identical contract the
block pipeline's cross-block merge provides (tests/test_sharded_rag.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..obs import trace as obs_trace
from ..ops.rag import HIST_BINS, QUANTILES
from .mesh import get_mesh, put_global
from .sharded import _neighbor_planes, shard_map

_BIG_ID = np.int32(np.iinfo(np.int32).max)


def _edge_segments(u, v, max_edges):
    """Shared segment machinery over (u, v)-sorted keys: validity mask,
    per-edge segment ids (invalid rows → the overflow bucket), the distinct
    count, and a reducer bound to those segments."""
    valid = u != _BIG_ID
    first = jnp.concatenate(
        [valid[:1], (u[1:] != u[:-1]) | (v[1:] != v[:-1])]
    ) & valid
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    seg = jnp.where(valid, seg, max_edges)
    n_distinct = first.sum()

    def red(x, op=jax.ops.segment_sum):
        return op(x, seg, num_segments=max_edges + 1)[:max_edges]

    return valid, seg, n_distinct, red


def _local_stats_table(lab, val, lab_hi, val_hi, max_edges, hist_bins,
                       packed=False, max_samples=None):
    """Per-shard samples → sorted sufficient-statistics table (fixed size).

    ``packed`` (static): single-int32-key sort ``u*65536 + v`` when every
    global label id ≤ 32766 (caller-gated) — same order-preserving packing
    as ops/rag._boundary_edge_features_device_impl, same bit-identical
    results, one sort stream fewer.

    ``max_samples`` (static): pre-sort compaction of the shard's valid face
    rows to a fixed cap, exactly like the single-device kernel — at
    CREMI-like boundary densities ~3/4 of the rows are sentinels that cost
    the same to sort as real samples.  The cap must bound EVERY shard's
    valid count (callers size it host-side); the true per-shard count is
    returned so the caller can fail loudly on overflow."""
    lab_e = jnp.concatenate([lab, lab_hi[None]], 0)
    val_e = jnp.concatenate([val, val_hi[None]], 0)

    us, vs, ss = [], [], []
    # axis 0 pairs over the +z-extended arrays (owns the cross-shard pairs;
    # the mesh-edge shard's received plane is ppermute zero-fill → label 0 →
    # those pairs are invalid automatically)
    for arrs, axis in (((lab_e, val_e), 0), ((lab, val), 1), ((lab, val), 2)):
        l0 = jnp.moveaxis(arrs[0], axis, 0)
        v0 = jnp.moveaxis(arrs[1], axis, 0)
        lo, hi = l0[:-1].reshape(-1), l0[1:].reshape(-1)
        vlo, vhi = v0[:-1].reshape(-1), v0[1:].reshape(-1)
        sel = (lo != hi) & (lo != 0) & (hi != 0)
        a = jnp.where(sel, jnp.minimum(lo, hi), _BIG_ID)
        b = jnp.where(sel, jnp.maximum(lo, hi), _BIG_ID)
        us += [a, a]
        vs += [b, b]
        ss += [vlo, vhi]
    u = jnp.concatenate(us)
    v = jnp.concatenate(vs)
    s = jnp.concatenate(ss).astype(jnp.float32)

    n_true = (u != _BIG_ID).sum()
    if max_samples is not None:
        from ..ops.rag import compact_valid_rows

        u, v, s = compact_valid_rows(u, v, s, max_samples, _BIG_ID)

    if packed:
        from ..ops.rag import pack_uv, unpack_uv

        p = pack_uv(u, v, _BIG_ID)
        p, s = lax.sort((p, s), num_keys=2)
        # segment machinery straight off the packed key: one diff per
        # boundary, and endpoints recovered by ONE edge-level reduction +
        # unpack — no per-sample div/mod (mirrors ops/rag's packed path)
        valid = p != _BIG_ID
        first = jnp.concatenate([valid[:1], p[1:] != p[:-1]]) & valid
        seg = jnp.cumsum(first.astype(jnp.int32)) - 1
        seg = jnp.where(valid, seg, max_edges)
        n_local = first.sum()

        def red(x, op=jax.ops.segment_sum):
            return op(x, seg, num_segments=max_edges + 1)[:max_edges]

        e_p = red(jnp.where(valid, p, _BIG_ID), op=jax.ops.segment_min)
        e_u, e_v = unpack_uv(e_p, _BIG_ID)
    else:
        u, v, s = lax.sort((u, v, s), num_keys=3)
        valid, seg, n_local, red = _edge_segments(u, v, max_edges)
        e_u = red(jnp.where(valid, u, _BIG_ID), op=jax.ops.segment_min)
        e_v = red(jnp.where(valid, v, _BIG_ID), op=jax.ops.segment_min)
    ones = valid.astype(jnp.float32)

    count = red(ones)
    ssum = red(s * ones)
    ssum2 = red(s * s * ones)
    smin = red(jnp.where(valid, s, jnp.inf), op=jax.ops.segment_min)
    smax = red(jnp.where(valid, s, -jnp.inf), op=jax.ops.segment_max)
    bins = jnp.clip((s * hist_bins).astype(jnp.int32), 0, hist_bins - 1)
    flat = jnp.where(valid, seg * hist_bins + bins, max_edges * hist_bins)
    hist = jax.ops.segment_sum(
        ones, flat, num_segments=max_edges * hist_bins + 1
    )[: max_edges * hist_bins].reshape(max_edges, hist_bins)
    return e_u, e_v, count, ssum, ssum2, smin, smax, hist, n_local, n_true


def _hist_quantile(hist, cum, counts, q):
    """jnp port of ops/rag._histogram_quantiles (same convention — the
    sharded result must match what the block pipeline's merge would say)."""
    n_bins = hist.shape[1]
    target = q * (counts - 1.0)
    idx = (cum <= target[:, None]).sum(axis=1)
    idx = jnp.minimum(idx, n_bins - 1)
    rows = jnp.arange(hist.shape[0])
    below = jnp.where(idx > 0, cum[rows, jnp.maximum(idx - 1, 0)], 0.0)
    in_bin = jnp.maximum(hist[rows, idx], 1.0)
    frac = jnp.clip((target - below + 0.5) / in_bin, 0.0, 1.0)
    return (idx + frac) / n_bins


@partial(
    jax.jit,
    static_argnames=(
        "max_edges", "hist_bins", "axis_name", "mesh", "packed",
        "max_samples",
    ),
)
def _sharded_rag(labels, values, max_edges, hist_bins, axis_name, mesh,
                 packed=False, max_samples=None):
    def local_fn(lab, val):
        lab_hi = _neighbor_planes(lab[0], axis_name, -1)  # +z neighbor plane
        val_hi = _neighbor_planes(val[0], axis_name, -1)
        (e_u, e_v, count, ssum, ssum2, smin, smax, hist,
         n_local, n_true) = _local_stats_table(
            lab, val, lab_hi, val_hi, max_edges, hist_bins, packed,
            max_samples,
        )
        # a local table that truncated (> max_edges distinct edges in one
        # shard) silently drops the lexicographic tail IDENTICALLY on every
        # shard, so the merged count cannot detect it — report the max local
        # count so the host can fail loudly; same for the sample cap
        n_local_max = lax.pmax(n_local, axis_name)
        n_true_max = lax.pmax(n_true, axis_name)

        def gather(x):
            g = lax.all_gather(x, axis_name)
            return g.reshape((-1,) + g.shape[2:])

        u = gather(e_u)
        v = gather(e_v)
        count = gather(count)
        ssum = gather(ssum)
        ssum2 = gather(ssum2)
        smin = gather(smin)
        smax = gather(smax)
        hist = gather(hist)

        # lexicographic (u, v) order: one argsort of the packed key when
        # the id space fits, else two stable argsorts
        if packed:
            from ..ops.rag import pack_uv

            perm = jnp.argsort(pack_uv(u, v, _BIG_ID), stable=True)
        else:
            perm = jnp.argsort(v, stable=True)
            perm = perm[jnp.argsort(u[perm], stable=True)]
        u, v = u[perm], v[perm]
        count, ssum, ssum2 = count[perm], ssum[perm], ssum2[perm]
        smin, smax, hist = smin[perm], smax[perm], hist[perm]

        valid, seg, n_edges, red = _edge_segments(u, v, max_edges)

        m_count = red(count)
        m_sum = red(ssum)
        m_sum2 = red(ssum2)
        m_min = red(jnp.where(valid, smin, jnp.inf), op=jax.ops.segment_min)
        m_max = red(jnp.where(valid, smax, -jnp.inf), op=jax.ops.segment_max)
        m_hist = red(hist)
        m_u = red(jnp.where(valid, u, _BIG_ID), op=jax.ops.segment_min)
        m_v = red(jnp.where(valid, v, _BIG_ID), op=jax.ops.segment_min)

        present = m_count > 0
        safe = jnp.maximum(m_count, 1.0)
        mean = m_sum / safe
        var = jnp.maximum(m_sum2 / safe - mean**2, 0.0)
        cum = jnp.cumsum(m_hist, axis=1)
        qcols = [
            jnp.where(present, _hist_quantile(m_hist, cum, m_count, q), 0.0)
            for q in QUANTILES
        ]
        feats = jnp.stack(
            [
                jnp.where(present, mean, 0.0),
                jnp.where(present, var, 0.0),
                jnp.where(present, m_min, 0.0),
                *qcols,
                jnp.where(present, m_max, 0.0),
                m_count,
            ],
            axis=1,
        )
        return m_u, m_v, feats, m_hist, n_edges, n_local_max, n_true_max

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name)),
        out_specs=(P(), P(), P(), P(), P(), P(), P()),
        check_vma=False,
    )(labels, values)


def shard_sample_cap(labels_host: np.ndarray, n_shards: int) -> int:
    """Static per-shard compaction capacity from a HOST label volume
    (padded z divisible by ``n_shards``): the max over shards of the
    shard's valid face rows — in-slab pairs plus the +z cross-shard plane
    the shard owns — with ``sample_capacity``'s headroom/bucketing.  The
    extended-slab count includes the borrowed plane's in-plane pairs too
    (not owned), so it upper-bounds the kernel's count — safe for a cap."""
    from ..ops.rag import count_boundary_samples, sample_capacity

    z = labels_host.shape[0]
    h = z // n_shards
    worst = 1
    for i in range(n_shards):
        z0, z1 = i * h, (i + 1) * h
        ext = labels_host[z0 : min(z1 + 1, z)]  # +z neighbor plane if any
        worst = max(worst, count_boundary_samples(ext))
    return sample_capacity(worst)


@obs_trace.traced(kind="collective")
def sharded_boundary_edge_features(
    labels,
    values,
    mesh=None,
    axis_name: str = "data",
    max_edges: int = 16384,
    hist_bins: int = HIST_BINS,
    max_id=None,
    max_samples=None,
):
    """10 RAG edge features of a z-sharded volume in one collective program.

    ``max_id``: the largest label id, when the caller knows it (e.g. the
    compact node count) — gates the packed single-key sort without touching
    the (possibly multi-host global) labels array.

    ``labels``: int32 compact ids (0 = background), z-extent divisible by the
    mesh size.  Returns host arrays ``(edges [n,2] int64, feats [n,10])`` in
    lexicographic edge order — the same contract as
    ``ops.rag.boundary_edge_features``; count/mean/min/max exact, quantiles
    within one histogram bin (the block pipeline's own merge tolerance).
    """
    mesh = mesh if mesh is not None else get_mesh(axis_name=axis_name)
    n = mesh.shape[axis_name]
    if labels.shape[0] % n:
        raise ValueError(
            f"z extent {labels.shape[0]} not divisible by mesh size {n}"
        )
    lab = put_global(labels, mesh, axis_name, dtype=np.int32)
    val = put_global(values, mesh, axis_name, dtype=np.float32)
    # single-key packed sorts whenever the global id space fits 15 bits.
    # The bound must come from the caller (max_id) or a HOST array: an
    # eager labels.max() on a multi-host global jax.Array would crash
    # (non-addressable shards) and adds a blocking reduction otherwise.
    from ..ops.rag import PACK_MAX_ID

    if max_id is None and isinstance(labels, np.ndarray) and labels.size:
        max_id = int(labels.max())
    packed = max_id is not None and 0 <= int(max_id) <= PACK_MAX_ID
    # pre-sort compaction: size the per-shard cap from the host labels when
    # available; device-resident callers pass max_samples themselves
    if max_samples is None and isinstance(labels, np.ndarray) and labels.size:
        max_samples = shard_sample_cap(labels, n)
    if max_samples is not None:
        # skip compaction that cannot shrink the sort (small or
        # boundary-dense shards) — same guard as the single-device wrapper
        h, y, x_ = lab.shape[0] // n, lab.shape[1], lab.shape[2]
        raw_rows = 2 * (h * y * x_ + h * (y - 1) * x_ + h * y * (x_ - 1))
        if int(max_samples) >= raw_rows:
            max_samples = None
    e_u, e_v, feats, _, n_edges, n_local_max, n_true_max = _sharded_rag(
        lab, val, int(max_edges), int(hist_bins), axis_name, mesh,
        packed=bool(packed),
        max_samples=None if max_samples is None else int(max_samples),
    )
    n_edges = int(n_edges)
    if int(n_local_max) > max_edges or n_edges > max_edges:
        raise RuntimeError(
            f"edge table overflow (local max {int(n_local_max)}, merged "
            f"{n_edges} vs max_edges={max_edges}); raise the bound"
        )
    if max_samples is not None and int(n_true_max) > int(max_samples):
        raise RuntimeError(
            f"sample compaction overflow ({int(n_true_max)} valid rows in "
            f"one shard vs max_samples={int(max_samples)}) — a dropped row "
            "would corrupt features; raise the cap"
        )
    edges = np.stack(
        [np.asarray(e_u)[:n_edges], np.asarray(e_v)[:n_edges]], axis=1
    ).astype(np.int64)
    return edges, np.asarray(feats)[:n_edges]
