from .mesh import get_mesh, shard_batch, local_device_count
from .dispatch import BlockBatch, read_block_batch, write_block_batch

__all__ = [
    "get_mesh",
    "shard_batch",
    "local_device_count",
    "BlockBatch",
    "read_block_batch",
    "write_block_batch",
]
