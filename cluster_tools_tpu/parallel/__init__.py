from .mesh import (
    get_mesh,
    last_batch_sharding,
    local_device_count,
    put_sharded,
    resolve_devices,
    shard_batch,
)
from .dispatch import BlockBatch, read_block_batch, write_block_batch
from .sharded import (
    halo_exchange,
    sharded_connected_components,
    sharded_seeded_watershed,
)
from .sharded_watershed import sharded_dt_watershed
from .sharded_rag import sharded_boundary_edge_features

__all__ = [
    "get_mesh",
    "last_batch_sharding",
    "local_device_count",
    "put_sharded",
    "resolve_devices",
    "shard_batch",
    "BlockBatch",
    "read_block_batch",
    "write_block_batch",
    "halo_exchange",
    "sharded_connected_components",
    "sharded_seeded_watershed",
    "sharded_dt_watershed",
    "sharded_boundary_edge_features",
]
