"""Sharded whole-volume kernels: XLA collectives over the device mesh.

The block runtime scales by *data parallelism* — independent halo'd blocks
ride a `NamedSharding` and never talk to each other; every cross-block merge
goes through the chunked store.  This module is the other half of the
SURVEY.md §2.8/§2.9 mapping: when one volume is larger than a chip's HBM, the
volume itself is sharded over the mesh (blocks = "sequence shards") and
neighbor communication rides **ICI collectives inside one jit program** —
`lax.ppermute` halo exchange along the sharded axis, `lax.psum` convergence
votes — instead of filesystem round-trips.  This is the spatial analog of
ring attention's neighbor exchange (SURVEY.md §5 "long-context").

Kernels:

  * ``halo_exchange`` — pad a z-sharded array with its neighbors' boundary
    planes (the reference's overlapping block reads, volume_utils
    getBlockWithHalo, as an ICI ring exchange).
  * ``sharded_connected_components`` — global CC of a z-sharded volume:
    per-shard log-depth min-label sweeps (ops.cc) + boundary-plane exchange,
    iterated inside one ``lax.while_loop`` until the *global* fixpoint
    (``psum`` of per-shard change flags).  The cross-shard merge that the
    block pipeline does via face files + union-find (ThresholdedComponents
    steps 3-4) happens entirely on the mesh.

Tested on the 8-virtual-device CPU mesh against the scipy oracle
(tests/test_sharded.py); the same program runs unchanged on a real ICI mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map as _shard_map_impl
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl

import inspect as _inspect

# the replication-check kwarg was renamed check_rep -> check_vma across jax
# versions; resolve the installed spelling once so every collective in
# parallel/ runs on either API
_SHARD_MAP_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map_impl).parameters
    else "check_rep"
)


def shard_map(f, *args, check_vma=None, **kwargs):
    """``jax.shard_map`` with the check kwarg translated to the installed
    jax's spelling (``check_vma`` >= 0.7, ``check_rep`` before).  On the
    ``check_rep`` API the check defaults OFF: that generation of the
    replication checker has no rules for ``while_loop``/``scan`` and
    rejects every fixpoint kernel in this module."""
    if check_vma is None and _SHARD_MAP_CHECK_KW == "check_rep":
        check_vma = False
    if check_vma is not None:
        kwargs[_SHARD_MAP_CHECK_KW] = check_vma
    return _shard_map_impl(f, *args, **kwargs)


def axis_size(axis_name):
    """``lax.axis_size`` where it exists; the classic constant-folded
    ``psum(1, axis)`` idiom on older jax."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


import warnings

from .. import faults
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..ops.cc import (
    _coarse_cc_core,
    _min_sweep,
    _min_sweep_seq,
    _shift,
    boundary_cross_offsets,
    neighbor_offsets,
    resolve_coarse_tile,
)
from .mesh import get_mesh, put_global


class CollectiveInitError(RuntimeError):
    """Collective setup (mesh/device resolution) failed — the entry kernels
    degrade to the single-device local kernel instead of failing the run
    (``sharded.fallback_local`` obs counter + warning, never silent)."""


def _collective_mesh(mesh, axis_name: str):
    """Resolve the mesh for a collective entry kernel; every failure —
    injected (``collective.init`` fault site) or real (driver/device init)
    — surfaces as :class:`CollectiveInitError` so callers can fall back."""
    try:
        faults.check("collective.init")
        return mesh if mesh is not None else get_mesh(axis_name=axis_name)
    except Exception as e:
        raise CollectiveInitError(f"collective init failed: {e}") from e


def _note_local_fallback(what: str, err: Exception) -> None:
    """Record a sharded→local degradation — loud (warning + obs counter),
    and refused outright on a multi-process runtime, where one host
    computing locally while peers enter the collective would deadlock the
    program or silently split the answer."""
    if jax.process_count() > 1:
        raise err
    obs_metrics.inc("sharded.fallback_local")
    warnings.warn(
        f"{what}: {err} — falling back to the single-device local kernel "
        "(same result, no ICI parallelism)",
        RuntimeWarning,
        stacklevel=3,
    )


def _neighbor_planes(plane, axis_name, direction):
    """Every shard receives ``plane`` from its -z neighbor (direction=+1) or
    +z neighbor (direction=-1) along the mesh ring; shards with no such
    neighbor receive zeros (lax.ppermute semantics), which callers mask out
    via the exchanged mask plane."""
    n = axis_size(axis_name)
    if direction > 0:
        perm = [(i, i + 1) for i in range(n - 1)]
    else:
        perm = [(i + 1, i) for i in range(n - 1)]
    return lax.ppermute(plane, axis_name, perm=perm)


def halo_exchange(x, halo: int, axis_name: str, fill=0):
    """Extend a z-sharded array with ``halo`` boundary planes from its mesh
    neighbors (call inside ``shard_map``).  Beyond-the-volume planes (outer
    shards) pad with ``fill``.

    A halo deeper than one shard chains ppermutes — hop k forwards the block
    received at hop k-1, so shard i accumulates shards i∓1..i∓hops — and
    slices the nearest ``halo`` planes.  Returns shape (Zl + 2*halo, ...):
    the ICI equivalent of the reference's overlapping chunk reads
    (SURVEY.md §2.8.2).
    """
    z_local = x.shape[0]
    hops = -(-halo // z_local)  # ceil
    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)

    def gather(direction):
        if hops == 1:
            # common case: one hop moves only the needed boundary planes
            plane = x[-halo:] if direction > 0 else x[:halo]
            got = _neighbor_planes(plane, axis_name, direction)
            missing = (idx < 1) if direction > 0 else (idx >= n - 1)
            return jnp.where(missing, jnp.full_like(got, fill), got)
        # shallow shards: chain full blocks (hop h forwards hop h-1's block,
        # so shard i accumulates shards i∓1..i∓hops), then slice
        parts = []
        block = x
        for h in range(1, hops + 1):
            block = _neighbor_planes(block, axis_name, direction)
            missing = (idx < h) if direction > 0 else (idx >= n - h)
            block = jnp.where(missing, jnp.full_like(block, fill), block)
            # keep global z order: lo side grows downward (farthest first),
            # hi side grows upward (nearest first)
            if direction > 0:
                parts.insert(0, block)
            else:
                parts.append(block)
        stacked = jnp.concatenate(parts, axis=0)
        # nearest `halo` planes: the trailing ones on the lo side, the
        # leading ones on the hi side
        return stacked[-halo:] if direction > 0 else stacked[:halo]

    lo = gather(+1)  # from the -z side
    hi = gather(-1)  # from the +z side
    return jnp.concatenate([lo, x, hi], axis=0)


def _exchange_planes(arrs, axis_name):
    """The shard-boundary exchange both sharded kernels share: every array's
    last plane goes to the +z neighbor, first plane to the -z neighbor.
    Returns ``(from_below, from_above)`` plane tuples (zero-filled at the
    mesh edges — combiners guard via exchanged mask/label planes)."""
    lo = tuple(_neighbor_planes(a[-1], axis_name, +1) for a in arrs)
    hi = tuple(_neighbor_planes(a[0], axis_name, -1) for a in arrs)
    return lo, hi


def _update_boundary(state, combine, lo, hi, z_local):
    """Apply a cross-boundary ``combine`` to every volume in ``state``:
    first planes against the -z neighbor's contribution ``lo``, last planes
    against the +z neighbor's ``hi``.  A one-plane shard is both boundary
    planes, so both contributions combine into the same plane.

    ``combine(own_planes, got_planes, plane_idx) -> new_planes`` where
    ``plane_idx`` is 0 or -1 (for indexing side data in the closure).
    """
    first = combine(tuple(v[0] for v in state), lo, 0)
    if z_local == 1:
        first = combine(first, hi, 0)
        return tuple(f[None] for f in first)
    last = combine(tuple(v[-1] for v in state), hi, -1)
    return tuple(
        jnp.concatenate([f[None], v[1:-1], l[None]], 0)
        for f, v, l in zip(first, state, last)
    )


def _local_relax(label, mask, offsets, axes, size, shard_offset, local_size):
    """One round of per-shard relaxation: min-label propagation (directional
    axis sweeps — log-depth ``_min_sweep`` on the assoc path, the ctt-cc
    sequential-carry ``_min_sweep_seq`` otherwise, the same CTT_SWEEP_MODE
    switch every sweep kernel honors; diagonal offsets keep one-voxel
    shifts), then two pointer jumps (only labels rooted inside this shard
    can be jumped locally)."""
    from ..ops import _backend

    sentinel = jnp.int32(size)
    new = label
    sweep_fn = _min_sweep if _backend.use_assoc() else _min_sweep_seq
    prop = [o for o in offsets if sum(c != 0 for c in o) > 1]
    for axis in axes:
        for reverse in (False, True):
            new = sweep_fn(new, mask, None, axis, reverse, sentinel)
    if prop:
        best = new
        for off in prop:
            neigh = _shift(new, off, sentinel)
            best = jnp.minimum(best, jnp.where(mask, neigh, sentinel))
        new = jnp.where(mask, best, sentinel)

    def jump(lab):
        flat = lab.reshape(-1)
        idx = flat - shard_offset
        local = (idx >= 0) & (idx < local_size)
        safe = jnp.clip(idx, 0, local_size - 1)
        jumped = jnp.where(local, flat[safe], flat)
        return jnp.where(mask, jumped.reshape(lab.shape), sentinel)

    return jump(jump(new))


@partial(jax.jit, static_argnames=("connectivity", "axis_name", "mesh"))
def _sharded_cc(mask, connectivity, axis_name, mesh):
    """Coarse-to-fine CC at shard granularity (ctt-cc, the shard-level
    instance of ops/cc.py's tile scheme): each shard labels its slab to its
    LOCAL fixpoint in global-id space (no collectives — the rounds are
    bounded by in-shard structure), then ONE plane exchange + all-gather
    builds the complete cross-shard equivalence table, resolved by the
    compact value union-find replicated on every shard and applied with one
    gather.  Replaces the pre-ctt-cc global fixpoint loop, whose label
    information crawled one shard per round (local relax + plane merge +
    psum vote, O(n_shards · local rounds) collective rounds)."""
    shape = mask.shape
    size = int(np.prod(shape))
    if size >= np.iinfo(np.int32).max:
        raise ValueError("volume too large for int32 flat label ids")
    n_shards = mesh.shape[axis_name]
    z_local = shape[0] // n_shards
    local_size = z_local * int(np.prod(shape[1:]))
    offsets = neighbor_offsets(3, connectivity)
    # cross-boundary offsets, expressed as in-plane shifts of the received
    # neighbor plane (dz = ±1 face/diagonal connections) — the ONE shared
    # derivation in ops/cc.py, so connectivity semantics cannot drift
    cross = boundary_cross_offsets(3, connectivity)
    from ..ops import _backend
    from ..ops.unionfind import apply_value_roots, merge_value_table

    local_shape = (z_local,) + shape[1:]
    coarse = _backend.use_coarse_cc()
    tile = resolve_coarse_tile(local_shape, None) if coarse else None

    def local_fn(m):
        shard = lax.axis_index(axis_name)
        offset = shard * local_size
        gids = (
            jnp.arange(local_size, dtype=jnp.int32).reshape(local_shape)
            + offset
        )
        sentinel = jnp.int32(size)

        # -- stage 1: shard-local fixpoint, global-id labels ---------------
        if coarse:
            label, _ = _coarse_cc_core(
                m, gids, size, connectivity, None, False, tile
            )
        else:
            init = jnp.where(m, gids, sentinel)

            def body(state):
                lab, _ = state
                new = _local_relax(
                    lab, m, offsets, (0, 1, 2), size, offset, local_size
                )
                return new, jnp.any(new != lab)

            label, _ = lax.while_loop(
                lambda s: s[1], body, (init, jnp.bool_(True))
            )

        if n_shards == 1:
            return jnp.where(m, label, jnp.int32(-1))

        # -- stage 2: one all-gathered boundary table ----------------------
        # each shard contributes its +z face: own last plane against the +z
        # neighbor's first plane (zero-filled mask past the mesh edge, so
        # the last shard contributes only self-loop padding)
        _, hi = _exchange_planes((label, m), axis_name)
        hi_lab, hi_msk = hi
        own_lab, own_msk = label[-1], m[-1]
        a_parts, b_parts = [], []
        for off in cross:
            g_lab = _shift(hi_lab, off, sentinel)
            g_msk = _shift(hi_msk, off, False)
            ok = own_msk & g_msk & (g_lab < sentinel)
            a_parts.append(jnp.where(ok, own_lab, sentinel).reshape(-1))
            b_parts.append(jnp.where(ok, g_lab, sentinel).reshape(-1))
        a = lax.all_gather(jnp.concatenate(a_parts), axis_name).reshape(-1)
        b = lax.all_gather(jnp.concatenate(b_parts), axis_name).reshape(-1)
        vals, root_vals = merge_value_table(a, b)
        label = apply_value_roots(label, vals, root_vals)
        return jnp.where(m, label, jnp.int32(-1))

    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=P(axis_name),
        out_specs=P(axis_name),
    )
    return fn(mask)


@partial(jax.jit, static_argnames=("axis_name", "mesh"))
def _sharded_flood(hmap, seeds, mask, axis_name, mesh):
    from ..ops import _backend
    from ..ops.watershed import (
        _BIG,
        _sweep_altitude_assoc,
        _sweep_altitude_seq,
        _sweep_assign_assoc,
        _sweep_assign_seq,
    )

    if _backend.use_assoc():
        sweep_alt, sweep_asg = _sweep_altitude_assoc, _sweep_assign_assoc
    else:
        sweep_alt, sweep_asg = _sweep_altitude_seq, _sweep_assign_seq
    big_dist = jnp.int32(np.iinfo(np.int32).max - 1)
    n_shards = mesh.shape[axis_name]
    z_local = hmap.shape[0] // n_shards

    def local_fn(h, s, m):
        s = jnp.where(m, s, 0)
        is_seed = s > 0

        # -- phase 1: altitude ---------------------------------------------
        def alt_boundary(alt):
            lo, hi = _exchange_planes((alt, m), axis_name)

            def comb(own, got, plane_idx):
                (own_alt,) = own
                got_a, got_m = got
                cand = jnp.maximum(got_a, h[plane_idx])
                ok = m[plane_idx] & ~is_seed[plane_idx] & got_m
                return (jnp.where(ok, jnp.minimum(own_alt, cand), own_alt),)

            (out,) = _update_boundary((alt,), comb, lo, hi, z_local)
            return out

        def alt_body(state):
            alt, _ = state
            new = alt
            for axis in (0, 1, 2):
                for rev in (False, True):
                    new = sweep_alt(new, h, is_seed, m, axis, rev)
            new = alt_boundary(new)
            changed = lax.psum(
                jnp.any(new != alt).astype(jnp.int32), axis_name
            ) > 0
            return new, changed

        alt0 = jnp.where(is_seed, h, _BIG)
        alt, _ = lax.while_loop(
            lambda st: st[1], alt_body, (alt0, jnp.bool_(True))
        )

        # -- phase 2: assignment -------------------------------------------
        alt_masked = jnp.where(m, alt, _BIG)
        (alt_lo,), (alt_hi,) = _exchange_planes((alt_masked,), axis_name)
        # mesh-edge shards received zeros: overwrite with BIG (no edge)
        idx = lax.axis_index(axis_name)
        alt_lo = jnp.where(idx == 0, jnp.full_like(alt_lo, _BIG), alt_lo)
        alt_hi = jnp.where(
            idx == n_shards - 1, jnp.full_like(alt_hi, _BIG), alt_hi
        )
        def asg_boundary(dist, label):
            lo, hi = _exchange_planes((dist, label), axis_name)

            def comb(own, got, plane_idx):
                d, l = own
                got_d, got_l = got
                # the neighbor altitude belongs to the SIDE the contribution
                # came from (a one-plane shard combines both sides into the
                # same plane, so the side can't be derived from plane_idx)
                got_a = alt_lo if got is lo else alt_hi
                edge_ok = alt[plane_idx] == jnp.maximum(got_a, h[plane_idx])
                cand = got_d + 1
                valid = (
                    m[plane_idx] & ~is_seed[plane_idx] & edge_ok & (got_l > 0)
                )
                better = valid & (
                    (cand < d) | ((cand == d) & ((l == 0) | (got_l < l)))
                )
                return (
                    jnp.where(better, cand, d),
                    jnp.where(better, got_l, l),
                )

            return _update_boundary((dist, label), comb, lo, hi, z_local)

        def asg_body(state):
            dist, label, _ = state
            d, l = dist, label
            for axis in (0, 1, 2):
                for rev in (False, True):
                    d, l = sweep_asg(d, l, alt, h, is_seed, m, axis, rev)
            d, l = asg_boundary(d, l)
            changed = lax.psum(
                jnp.any((d != dist) | (l != label)).astype(jnp.int32),
                axis_name,
            ) > 0
            return d, l, changed

        dist0 = jnp.where(is_seed, 0, big_dist)
        _, label, _ = lax.while_loop(
            lambda st: st[2], asg_body, (dist0, s, jnp.bool_(True))
        )
        return jnp.where(m, label, 0)

    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name)),
        out_specs=P(axis_name),
        # the reused sweep kernels build scan carries from shape constants,
        # which the varying-manual-axes tracker sees as replicated values
        # meeting varying ones — semantically fine here (every value is
        # per-shard), so disable the strict check
        check_vma=False,
    )
    return fn(hmap, seeds, mask)


@obs_trace.traced(kind="collective")
def sharded_seeded_watershed(
    hmap,
    seeds,
    mask=None,
    mesh=None,
    axis_name: str = "data",
) -> jnp.ndarray:
    """Seeded 3d flood of a z-sharded volume over the device mesh — the
    flagship kernel's collective form: per-shard directional sweeps
    (ops.watershed, honoring CTT_SWEEP_MODE) + ppermute'd boundary-plane
    relaxation + psum convergence votes, both flood phases inside one jit.

    Computes the SAME lexicographic (pass-height, hops, label) fixpoint as
    ``ops.watershed.seeded_watershed(..., per_slice=False)`` — exact label
    equality (tested) — for volumes whose z-extent is divisible by the mesh
    size.  Seeds are global int32 ids (0 = unlabeled); voxels outside
    ``mask`` stay 0.

    When collective setup fails (``CollectiveInitError`` — a wedged device
    runtime, or the ``collective.init`` fault site), the kernel degrades to
    the single-device ``ops.watershed.seeded_watershed`` fixpoint, which
    computes the SAME labels (the equality claimed above); the degradation
    is recorded (``sharded.fallback_local`` counter + warning), and refused
    under a multi-process runtime.
    """
    try:
        mesh = _collective_mesh(mesh, axis_name)
    except CollectiveInitError as e:
        _note_local_fallback("sharded_seeded_watershed", e)
        from ..ops.watershed import seeded_watershed

        return seeded_watershed(
            jnp.asarray(np.asarray(hmap, dtype=np.float32)),
            jnp.asarray(np.asarray(seeds, dtype=np.int32)),
            mask=None if mask is None else jnp.asarray(
                np.asarray(mask, dtype=bool)
            ),
            per_slice=False,
        )
    n = mesh.shape[axis_name]
    if hmap.shape[0] % n:
        raise ValueError(
            f"z extent {hmap.shape[0]} not divisible by mesh size {n}"
        )
    if mask is None:
        mask = np.ones(hmap.shape, dtype=bool)  # host: no device round-trip
    # put_global: multi-process-safe placement (each process materializes
    # only its addressable shards)
    hmap = put_global(hmap, mesh, axis_name, dtype=np.float32)
    seeds = put_global(seeds, mesh, axis_name, dtype=np.int32)
    mask = put_global(mask, mesh, axis_name, dtype=bool)
    faults.check("collective.execute")
    return _sharded_flood(hmap, seeds, mask, axis_name, mesh)


@obs_trace.traced(kind="collective")
def sharded_connected_components(
    mask,
    mesh=None,
    axis_name: str = "data",
    connectivity: int = 1,
) -> jnp.ndarray:
    """Global connected components of a volume z-sharded over the device mesh.

    Returns int32 labels where background = -1 and each component carries the
    minimal *global* flat index of its voxels (compose with
    ``ops.relabel.relabel_consecutive`` or host ``np.unique`` for 1..N ids —
    root order matches the single-device ``connected_components_raw``, so the
    consecutive renumbering is identical).  The volume's z-extent must divide
    by the mesh size.

    One jit program: per-shard sweeps + pointer jumping, ppermute'd boundary
    planes, psum'd convergence — no host round-trips between rounds.

    When collective setup fails (``CollectiveInitError`` — a wedged device
    runtime, or the ``collective.init`` fault site), the kernel degrades to
    the single-device ``ops.cc.connected_components_raw``, which carries the
    IDENTICAL label contract (min global flat index per component,
    background -1) — same values, no ICI parallelism; the degradation is
    recorded (``sharded.fallback_local`` counter + warning), and refused
    under a multi-process runtime.
    """
    try:
        mesh = _collective_mesh(mesh, axis_name)
    except CollectiveInitError as e:
        _note_local_fallback("sharded_connected_components", e)
        from ..ops.cc import connected_components_raw

        return connected_components_raw(
            jnp.asarray(np.asarray(mask, dtype=bool)),
            connectivity=connectivity,
        )
    n = mesh.shape[axis_name]
    if mask.shape[0] % n:
        raise ValueError(
            f"z extent {mask.shape[0]} not divisible by mesh size {n}"
        )
    mask = put_global(mask, mesh, axis_name, dtype=bool)
    faults.check("collective.execute")
    return _sharded_cc(mask, connectivity, axis_name, mesh)


def fused_threshold_components(
    x,
    threshold: float,
    mesh=None,
    axis_name: str = "data",
    connectivity: int = 1,
) -> jnp.ndarray:
    """ctt-stream under the sharded collective: threshold + global CC as
    one device-resident sequence — the boolean mask is born on device and
    flows straight into the collective label program, never crossing to
    host (the collective analog of the fused block chain's elided
    threshold intermediate).

    ``x`` is the z-sharded raw volume (``mesh.put_from_store`` placement;
    pad slabs must be 0.0).  Only ``greater``-mode with ``threshold >= 0``
    is supported: zero pad slabs then threshold to background, preserving
    the host-threshold path's pad contract — callers with other modes keep
    the host-side transform.  Labels match ``sharded_connected_components``
    on the host-thresholded mask exactly.
    """
    if threshold < 0:
        raise ValueError(
            "fused_threshold_components requires threshold >= 0 (pad "
            "slabs are 0.0 and must stay background)"
        )
    mask = jax.jit(lambda v: v > threshold)(x)
    return sharded_connected_components(
        mask, mesh=mesh, axis_name=axis_name, connectivity=connectivity
    )
