"""Per-segment skeletonization + skeleton-based evaluation
(reference skeletons/{skeletonize,upsample_skeletons,skeleton_evaluation}.py).

The id space is blocked (a "block" = a range of segment ids, reference
skeletonize.py blocking over [n_labels]); each id is cropped out by its
morphology bounding box, skeletonized (ops/skeleton.py) and serialized as a
flat varlen record [n_nodes, nodes..., edges...] — the varlen-chunk format in
the spirit of the reference's skeleton n5 serialization."""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..ops.skeleton import skeletonize
from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask
from .morphology import IdBlockTask, load_morphology

SKELETONS_KEY = "skeletons/objects"
SKELETON_EVAL_NAME = "skeleton_eval.npz"


def serialize_skeleton(nodes: np.ndarray, edges: np.ndarray) -> np.ndarray:
    return np.concatenate(
        [
            [float(nodes.shape[0]), float(edges.shape[0])],
            nodes.reshape(-1),
            edges.reshape(-1).astype(float),
        ]
    )


def deserialize_skeleton(data: np.ndarray):
    n_nodes, n_edges = int(data[0]), int(data[1])
    nodes = data[2 : 2 + 3 * n_nodes].reshape(n_nodes, 3)
    edges = (
        data[2 + 3 * n_nodes : 2 + 3 * n_nodes + 2 * n_edges]
        .reshape(n_edges, 2)
        .astype(np.int64)
    )
    return nodes, edges


class SkeletonizeTask(IdBlockTask):
    task_name = "skeletonize"
    output_dtype = None

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {"size_threshold": None, "resolution": [1.0, 1.0, 1.0],
             "method": "teasar", "halo": [2, 2, 2]}
        )
        return conf

    def process_block(self, block_id: int, blocking: Blocking, config):
        by_id = self.morphology_by_id()
        seg_ds = self.input_ds()
        shape = seg_ds.shape
        resolution = config.get("resolution", [1.0, 1.0, 1.0])
        size_threshold = config.get("size_threshold")
        halo = config.get("halo", [2, 2, 2])

        block = blocking.block(block_id)
        id_begin = max(1, block.begin[0])  # 0 is the ignore label
        id_end = block.end[0]
        out = self.tmp_ragged(SKELETONS_KEY, blocking.shape[0], np.float64)
        for seg_id in range(id_begin, id_end):
            row = by_id.get(seg_id)
            if row is None:
                continue
            if size_threshold is not None and row[1] < size_threshold:
                continue
            bb = tuple(
                slice(max(int(mi) - h, 0), min(int(ma) + h, sh))
                for mi, ma, sh, h in zip(row[5:8], row[8:11], shape, halo)
            )
            obj = np.asarray(seg_ds[bb]) == seg_id
            try:
                nodes, edges = skeletonize(obj, resolution=None)
            except Exception as err:  # skip pathological objects (reference)
                self.log(f"skeletonize failed for id {seg_id}: {err}")
                continue
            # global coordinates, physical units
            nodes = (nodes + [b.start for b in bb]) * np.asarray(
                resolution, dtype=float
            )
            out.write_chunk((seg_id,), serialize_skeleton(nodes, edges))


def load_skeletons(tmp_folder: str):
    """{seg_id: (nodes [n,3] physical coords, edges [m,2])}."""
    from .base import scratch_store_path

    ds = store.file_reader(scratch_store_path(tmp_folder), "r")[SKELETONS_KEY]
    out = {}
    for (sid,) in np.ndindex(ds.grid_shape):
        chunk = ds.read_chunk((sid,))
        if chunk is not None and chunk.size:
            out[sid] = deserialize_skeleton(chunk)
    return out


class UpsampleSkeletonsTask(VolumeTask):
    """Paint skeletons into a (finer) label volume
    (reference upsample_skeletons.py:29).

    Blocks over the OUTPUT volume (not the id space) so every voxel belongs to
    exactly one block — concurrent blocks never write overlapping regions."""

    task_name = "upsample_skeletons"
    output_dtype = "uint64"

    def __init__(self, *args, output_shape: Sequence[int] = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.output_shape = list(output_shape) if output_shape else None
        self._skel_voxels = None

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"resolution": [1.0, 1.0, 1.0]})
        return conf

    def get_shape(self) -> Sequence[int]:
        return self.output_shape or self.input_ds().shape

    def _voxels(self, config, shape):
        """All skeleton voxels (with edge midpoints) → (coords [n,3], ids [n]),
        loaded once per process."""
        if self._skel_voxels is None:
            resolution = np.asarray(config.get("resolution", [1.0, 1.0, 1.0]))
            skels = store.file_reader(self.tmp_store_path, "r")[SKELETONS_KEY]
            coords, ids = [], []
            for (sid,) in np.ndindex(skels.grid_shape):
                chunk = skels.read_chunk((sid,))
                if chunk is None or not chunk.size:
                    continue
                nodes, edges = deserialize_skeleton(chunk)
                vox = np.round(nodes / resolution[None]).astype(np.int64)
                if edges.size:
                    mids = np.round(
                        (vox[edges[:, 0]] + vox[edges[:, 1]]) / 2
                    ).astype(np.int64)
                    vox = np.concatenate([vox, mids])
                vox = np.clip(vox, 0, np.asarray(shape) - 1)
                coords.append(vox)
                ids.append(np.full(vox.shape[0], sid, dtype=np.uint64))
            if coords:
                self._skel_voxels = (
                    np.concatenate(coords), np.concatenate(ids)
                )
            else:
                self._skel_voxels = (
                    np.zeros((0, 3), np.int64), np.zeros(0, np.uint64)
                )
        return self._skel_voxels

    def process_block(self, block_id: int, blocking: Blocking, config):
        block = blocking.block(block_id)
        coords, ids = self._voxels(config, blocking.shape)
        lo = np.asarray(block.begin)
        hi = np.asarray(block.end)
        sel = ((coords >= lo) & (coords < hi)).all(axis=1)
        if not sel.any():
            return
        out_ds = self.output_ds()
        region = np.asarray(out_ds[block.slicing])
        local = coords[sel] - lo
        region[tuple(local.T)] = ids[sel]
        out_ds[block.slicing] = region


class SkeletonEvaluationTask(VolumeSimpleTask):
    """Skeleton-vs-segmentation metrics (reference skeleton_evaluation.py:26
    via nifty.ground_truth): per GT skeleton, the distribution of segmentation
    labels its nodes land on gives correctness / split / merge scores."""

    task_name = "skeleton_evaluation"

    def __init__(self, *args, skeleton_folder: str = None, seg_path: str = None,
                 seg_key: str = None, **kwargs):
        super().__init__(*args, skeleton_folder=skeleton_folder,
                         seg_path=seg_path, seg_key=seg_key, **kwargs)

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"resolution": [1.0, 1.0, 1.0]})
        return conf

    def run_impl(self) -> None:
        conf = self.get_task_config()
        resolution = np.asarray(conf.get("resolution", [1.0, 1.0, 1.0]))
        seg = store.file_reader(self.seg_path, "r")[self.seg_key]
        shape = np.asarray(seg.shape)
        skels = load_skeletons(self.skeleton_folder or self.tmp_folder)

        labels_per_skel = {}
        for sid, (nodes, _) in skels.items():
            vox = np.round(nodes / resolution[None]).astype(np.int64)
            vox = np.clip(vox, 0, shape - 1)
            # one bbox read per skeleton instead of one chunk-decompressing
            # voxel read per node
            lo = vox.min(axis=0)
            hi = vox.max(axis=0) + 1
            region = np.asarray(
                seg[tuple(slice(int(a), int(b)) for a, b in zip(lo, hi))]
            )
            labels = region[tuple((vox - lo).T)].astype(np.uint64)
            labels_per_skel[sid] = labels[labels > 0]

        sids = sorted(labels_per_skel)
        correct = []
        n_splits = []
        seen_by_label: Dict[int, set] = {}
        for sid in sids:
            labels = labels_per_skel[sid]
            if labels.size == 0:
                correct.append(0.0)
                n_splits.append(0)
                continue
            vals, counts = np.unique(labels, return_counts=True)
            correct.append(float(counts.max() / labels.size))
            n_splits.append(int(vals.size))
            for v in vals:
                seen_by_label.setdefault(int(v), set()).add(sid)
        merges = sum(1 for v, s in seen_by_label.items() if len(s) > 1)
        np.savez(
            os.path.join(self.tmp_folder, SKELETON_EVAL_NAME),
            skeleton_ids=np.asarray(sids),
            correctness=np.asarray(correct),
            n_splits=np.asarray(n_splits),
            n_merges=np.int64(merges),
        )
        self.log(
            f"skeleton eval: {len(sids)} skeletons, mean correctness "
            f"{np.mean(correct) if correct else 0:.3f}, {merges} merged labels"
        )


def load_skeleton_evaluation(tmp_folder: str) -> Dict[str, Any]:
    with np.load(os.path.join(tmp_folder, SKELETON_EVAL_NAME)) as f:
        return {k: f[k] for k in f.files}
