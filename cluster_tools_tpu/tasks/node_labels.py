"""Node ↔ label overlap votes.

Reference node_labels/{block_node_labels,merge_node_labels}.py via
nifty.distributed overlaps (SURVEY.md §2.4): per-block sparse contingency
between a segmentation ("nodes") and a label volume, merged globally; the
merged table yields the max-overlap label per node (used to transfer ground
truth / semantic labels onto segments).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np

from ..ops.evaluation import merge_contingency_tables
from ..ops.segment import contingency_table
from ..utils import store as store_mod
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, merge_threads, read_ragged_chunks, resolve_n_blocks

OVERLAPS_KEY = "node_labels/overlaps"
NODE_LABELS_NAME = "node_labels.npy"
OVERLAPS_MERGED_NAME = "node_overlaps.npz"


class BlockNodeLabelsTask(VolumeTask):
    """Per-block overlap serialization (reference block_node_labels.py:27).

    ``input_path/key`` = segmentation (nodes); ``labels_path/key`` = the label
    volume to vote over.
    """

    task_name = "block_node_labels"
    output_dtype = None

    def __init__(self, *args, labels_path: str = None, labels_key: str = None,
                 ignore_label=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.ignore_label = ignore_label

    def process_block(self, block_id: int, blocking: Blocking, config):
        bb = blocking.block(block_id).slicing
        seg = self.input_ds()[bb]
        labels = store_mod.file_reader(self.labels_path, "r")[self.labels_key][bb]
        ia, ib, counts = contingency_table(seg, labels)
        if self.ignore_label is not None:
            keep = ib != self.ignore_label
            ia, ib, counts = ia[keep], ib[keep], counts[keep]
        out = self.tmp_ragged(OVERLAPS_KEY, blocking.n_blocks, np.int64)
        packed = np.stack(
            [ia.astype(np.int64), ib.astype(np.int64), counts.astype(np.int64)],
            axis=1,
        )
        out.write_chunk((block_id,), packed.reshape(-1))


class MergeNodeLabelsTask(VolumeSimpleTask):
    """Merge overlaps by summation, emit max-overlap assignment
    (reference merge_node_labels.py:24)."""

    task_name = "merge_node_labels"

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 max_overlap: bool = True, **kwargs):
        super().__init__(*args, input_path=input_path, input_key=input_key,
                         max_overlap=max_overlap, **kwargs)

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(self.config_dir, self.input_path, self.input_key)
        ds = self.tmp_store()[OVERLAPS_KEY]
        tables = []
        for chunk in read_ragged_chunks(ds, n_blocks, merge_threads(self)):
            if chunk is None or chunk.size == 0:
                continue
            t = chunk.reshape(-1, 3)
            tables.append((t[:, 0], t[:, 1], t[:, 2]))
        if not tables:
            # downstream (measures) loads the merged table unconditionally —
            # write empty arrays rather than leaving the file missing
            empty = np.zeros(0, dtype=np.int64)
            np.savez(
                os.path.join(self.tmp_folder, OVERLAPS_MERGED_NAME),
                ids_a=empty, ids_b=empty, counts=empty,
            )
            np.save(os.path.join(self.tmp_folder, NODE_LABELS_NAME),
                    np.zeros((0, 2), dtype=np.uint64))
            return
        ia, ib, counts = merge_contingency_tables(tables)
        np.savez(
            os.path.join(self.tmp_folder, OVERLAPS_MERGED_NAME),
            ids_a=ia, ids_b=ib, counts=counts,
        )
        if self.max_overlap:
            order = np.lexsort((counts, ia))
            ia_s, ib_s, c_s = ia[order], ib[order], counts[order]
            last = np.concatenate([ia_s[1:] != ia_s[:-1], [True]])
            table = np.stack(
                [ia_s[last].astype(np.uint64), ib_s[last].astype(np.uint64)],
                axis=1,
            )
            np.save(os.path.join(self.tmp_folder, NODE_LABELS_NAME), table)
        self.log(f"merged node overlaps: {ia.size} pairs")
