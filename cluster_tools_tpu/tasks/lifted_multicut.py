"""Hierarchical lifted multicut solve.

Reference lifted_multicut/{solve_lifted_subproblems,reduce_lifted_problem,
solve_lifted_global}.py (SURVEY.md §2.3): the same domain-decomposition scheme
as the multicut family, with the lifted edges/costs carried through every
contraction.  Per-block subproblems include the lifted edges internal to the
block's node set (solve_lifted_subproblems.py:205-213); the reduction contracts
local edges, remaps lifted pairs and sum-merges duplicates; the global step
solves the final reduced lifted problem.

Scratch layout (extends tasks/multicut.py):
  lifted_multicut/s{s}/cut_edges      ragged per block: cut LOCAL edge ids
  lifted_multicut_s{s}.npz            reduced problem: edges, costs,
                                      lifted_uv, lifted_costs, node_labeling
  lifted_multicut_assignments.npy     final (label, segment) table
"""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np

from ..ops.lifted import solve_lifted_multicut
from ..ops.multicut import contract_edges
from ..ops.unionfind import UnionFindNp
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, merge_threads, read_ragged_chunks, resolve_n_blocks
from .costs import COSTS_NAME
from .graph import load_graph
from .lifted_features import load_lifted_problem
from .multicut import (
    block_dense_nodes,
    extract_cluster_subgraph,
    load_scale_problem,
    write_assignment_table,
)

LIFTED_ASSIGNMENTS_NAME = "lifted_multicut_assignments.npy"


def _lifted_scale_path(tmp_folder: str, scale: int) -> str:
    return os.path.join(tmp_folder, f"lifted_multicut_s{scale}.npz")


def load_lifted_scale_problem(task, scale: int, prefix: str = "lifted"):
    """(edges, costs, lifted_uv, lifted_costs, node_labeling) at a scale."""
    if scale == 0:
        edges, costs, node_labeling = load_scale_problem(task, 0)
        lifted_uv, lifted_costs = load_lifted_problem(task.tmp_folder, prefix)
        return edges, costs, lifted_uv, lifted_costs, node_labeling
    with np.load(_lifted_scale_path(task.tmp_folder, scale)) as f:
        return (
            f["edges"], f["costs"], f["lifted_uv"], f["lifted_costs"],
            f["node_labeling"],
        )


class SolveLiftedSubproblemsTask(VolumeTask):
    """Per-block lifted subproblem solve
    (reference solve_lifted_subproblems.py:32)."""

    task_name = "solve_lifted_subproblems"
    output_dtype = None

    def __init__(self, *args, scale: int = 0, prefix: str = "lifted", **kwargs):
        super().__init__(*args, **kwargs)
        self.scale = scale
        self.prefix = prefix

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_s{self.scale}"

    def get_block_shape(self, gconf):
        return [bs * (2**self.scale) for bs in gconf["block_shape"]]

    def process_block(self, block_id: int, blocking: Blocking, config):
        store = self.tmp_store()
        nodes, _ = load_graph(store)
        edges, costs, lifted_uv, lifted_costs, node_labeling = (
            load_lifted_scale_problem(self, self.scale, self.prefix)
        )

        seg = self.input_ds()[blocking.block(block_id).slicing]
        out = self.tmp_ragged(
            f"lifted_multicut/s{self.scale}/cut_edges", blocking.n_blocks,
            np.int64,
        )

        def emit(cut_ids):
            out.write_chunk((block_id,), np.asarray(cut_ids, dtype=np.int64))

        dense = block_dense_nodes(nodes, seg)
        if dense.size == 0 or edges.shape[0] == 0:
            emit([])
            return
        sub_edge_ids, uniq, local_uv, member = extract_cluster_subgraph(
            edges, node_labeling, dense
        )
        if sub_edge_ids.size == 0:
            emit([])
            return

        # lifted edges inner to the block's node set, in local coordinates
        # (lifted_uv is in current-scale cluster coordinates, like edges)
        if lifted_uv.shape[0]:
            lu, lv = lifted_uv[:, 0], lifted_uv[:, 1]
            in_lift = member[lu] & member[lv] & (lu != lv)
            llu = np.searchsorted(uniq, lu[in_lift])
            llv = np.searchsorted(uniq, lv[in_lift])
            # keep only pairs whose endpoints appear in the local subgraph
            ok = (
                (llu < uniq.size) & (llv < uniq.size)
            )
            ok &= uniq[np.clip(llu, 0, uniq.size - 1)] == lu[in_lift]
            ok &= uniq[np.clip(llv, 0, uniq.size - 1)] == lv[in_lift]
            local_lifted = np.stack([llu[ok], llv[ok]], axis=1)
            local_lifted_costs = lifted_costs[in_lift][ok]
        else:
            local_lifted = np.zeros((0, 2), dtype=np.int64)
            local_lifted_costs = np.zeros(0)

        result = solve_lifted_multicut(
            uniq.size, local_uv, costs[sub_edge_ids],
            local_lifted, local_lifted_costs,
        )
        cut = result[local_uv[:, 0]] != result[local_uv[:, 1]]
        emit(sub_edge_ids[cut])


class ReduceLiftedProblemTask(VolumeSimpleTask):
    """Contract non-cut local edges, carry lifted edges to the next scale
    (reference reduce_lifted_problem.py:30)."""

    task_name = "reduce_lifted_problem"

    def __init__(self, *args, scale: int = 0, prefix: str = "lifted",
                 input_path: str = None, input_key: str = None, **kwargs):
        super().__init__(*args, scale=scale, prefix=prefix,
                         input_path=input_path, input_key=input_key, **kwargs)

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_s{self.scale}"

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(
            self.config_dir, self.input_path, self.input_key, scale=self.scale
        )
        edges, costs, lifted_uv, lifted_costs, node_labeling = (
            load_lifted_scale_problem(self, self.scale, self.prefix)
        )
        store = self.tmp_store()
        cut_ds = store[f"lifted_multicut/s{self.scale}/cut_edges"]
        cut = np.zeros(edges.shape[0], dtype=bool)
        for chunk in read_ragged_chunks(cut_ds, n_blocks, merge_threads(self)):
            if chunk is not None and chunk.size:
                cut[chunk] = True

        n_current = int(node_labeling.max()) + 1
        uf = UnionFindNp(n_current)
        # edges/lifted_uv are already in current-scale cluster coordinates
        cur_u, cur_v = edges[:, 0], edges[:, 1]
        keep = ~cut & (cur_u != cur_v)
        uf.merge(cur_u[keep], cur_v[keep])
        roots = uf.compress()
        _, new_ids = np.unique(roots, return_inverse=True)
        merged_labeling = new_ids[node_labeling].astype(np.int64)

        new_edges, new_costs = contract_edges(
            new_ids[cur_u], new_ids[cur_v], costs
        )
        if lifted_uv.shape[0]:
            cl_u = new_ids[lifted_uv[:, 0]]
            cl_v = new_ids[lifted_uv[:, 1]]
            new_lifted, new_lifted_costs = contract_edges(cl_u, cl_v, lifted_costs)
        else:
            new_lifted = np.zeros((0, 2), dtype=np.int64)
            new_lifted_costs = np.zeros(0)

        np.savez(
            _lifted_scale_path(self.tmp_folder, self.scale + 1),
            edges=new_edges,
            costs=new_costs,
            lifted_uv=new_lifted,
            lifted_costs=new_lifted_costs,
            node_labeling=merged_labeling,
        )
        self.log(
            f"scale {self.scale}: {edges.shape[0]} local / "
            f"{lifted_uv.shape[0]} lifted edges, {n_current} nodes → "
            f"{new_edges.shape[0]} / {new_lifted.shape[0]} edges, "
            f"{int(new_ids.max()) + 1} nodes"
        )


class SolveLiftedGlobalTask(VolumeSimpleTask):
    """Solve the final reduced lifted problem
    (reference solve_lifted_global.py:25)."""

    task_name = "solve_lifted_global"

    def __init__(self, *args, scale: int = 0, prefix: str = "lifted", **kwargs):
        super().__init__(*args, scale=scale, prefix=prefix, **kwargs)

    def run_impl(self) -> None:
        edges, costs, lifted_uv, lifted_costs, node_labeling = (
            load_lifted_scale_problem(self, self.scale, self.prefix)
        )
        n_current = int(node_labeling.max()) + 1
        result = solve_lifted_multicut(
            n_current, edges, costs, lifted_uv, lifted_costs
        )
        final = result[node_labeling]
        write_assignment_table(self, final, LIFTED_ASSIGNMENTS_NAME)
        self.log(
            f"lifted global solve: {n_current} nodes → "
            f"{int(result.max()) + 1} segments"
        )
