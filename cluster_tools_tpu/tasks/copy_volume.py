"""Block-wise volume copy with dtype cast, channel reduction and insert mode.

Re-expression of the reference's copy_volume component
(reference copy_volume/copy_volume.py:27 ``CopyVolumeBase``): per block it can
  * cast dtype (uint8 gets normalize→*255 treatment),
  * keep only values in a ``value_list`` (everything else → 0),
  * skip empty / uniform blocks,
  * reduce a leading channel axis (``reduce_channels`` = numpy reduction name),
  * add a constant label ``offset`` to non-zero values,
  * ``insert_mode``: write only where the copied data is non-zero,
  * fit the output to the global ROI (``fit_to_roi``) so the output shape is
    the ROI extent and block boxes are shifted by roi_begin.

This is an IO-bound task — the per-block arithmetic stays on host where the
bytes already are (shipping a memcpy through HBM would only add PCIe traffic);
the task still runs under the same executor/retry machinery as device tasks.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeTask


def cast_type(data: np.ndarray, dtype) -> np.ndarray:
    """dtype cast with the reference's special uint8 path (normalize → *255,
    reference copy_volume.py cast_type)."""
    if np.dtype(data.dtype) == np.dtype(dtype):
        return data
    if np.dtype(dtype) == np.dtype("uint8"):
        data = data.astype("float32")
        dmin, dmax = data.min(), data.max()
        data = (data - dmin) / max(dmax - dmin, 1e-6)
        return (data * 255).astype("uint8")
    return data.astype(dtype)


class CopyVolumeTask(VolumeTask):
    task_name = "copy_volume"
    output_dtype = None  # dataset creation handled in prepare() below

    def __init__(
        self,
        *args,
        prefix: str = "",
        dtype: Optional[str] = None,
        fit_to_roi: bool = False,
        effective_scale_factor: Sequence[float] = (),
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.prefix = prefix
        self.dtype = dtype
        self.fit_to_roi = fit_to_roi
        self.effective_scale_factor = list(effective_scale_factor)

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_{self.prefix}" if self.prefix else self.task_name

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {
                "chunks": None,
                "compression": "gzip",
                "reduce_channels": None,
                "map_uniform_blocks_to_background": False,
                "value_list": None,
                "offset": None,
                "insert_mode": False,
            }
        )
        return conf

    # -- geometry ------------------------------------------------------------

    def _roi(self, config):
        roi_begin = config.get("roi_begin")
        roi_end = config.get("roi_end")
        if roi_begin is not None and self.effective_scale_factor:
            roi_begin = [int(rb // sf) for rb, sf in
                         zip(roi_begin, self.effective_scale_factor)]
            roi_end = [int(re // sf) for re, sf in
                       zip(roi_end, self.effective_scale_factor)]
        return roi_begin, roi_end

    def get_shape(self) -> Sequence[int]:
        shape = self.input_ds().shape
        return shape[-3:] if len(shape) > 3 else shape

    def _out_space_shape(self, config) -> Sequence[int]:
        shape = self.get_shape()
        roi_begin, roi_end = self._roi(config)
        if self.fit_to_roi and roi_begin is not None:
            return tuple(re - rb for rb, re in zip(roi_begin, roi_end))
        return tuple(shape)

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        in_ds = self.input_ds()
        in_shape = in_ds.shape
        ndim = len(in_shape)
        if ndim not in (3, 4):
            raise ValueError("copy_volume supports 3d and 4d inputs")

        out_shape = self._out_space_shape(config)
        reduce_channels = config.get("reduce_channels")
        if ndim == 4 and reduce_channels is None:
            out_shape = (in_shape[0],) + tuple(out_shape)

        dtype = self.dtype if self.dtype is not None else str(in_ds.dtype)
        chunks = config.get("chunks")
        chunks = tuple(blocking.block_shape) if chunks is None else tuple(chunks)
        if len(out_shape) == 4 and len(chunks) == 3:
            chunks = (1,) + chunks
        chunks = tuple(min(ch, sh) for ch, sh in zip(chunks, out_shape))

        f = store.file_reader(self.output_path, "a")
        f.require_dataset(
            self.output_key,
            shape=tuple(out_shape),
            dtype=dtype,
            chunks=chunks,
            compression=config.get("compression", "gzip"),
        )

    # -- per-block copy ------------------------------------------------------

    def process_block(self, block_id: int, blocking: Blocking, config: Dict[str, Any]):
        in_ds = self.input_ds()
        out_ds = self.output_ds()
        ndim_in = len(in_ds.shape)

        block = blocking.block(block_id)
        bb = block.slicing
        if ndim_in == 4:
            read_bb = (slice(None),) + bb
        else:
            read_bb = bb
        data = np.asarray(in_ds[read_bb])

        value_list = config.get("value_list")
        if value_list is not None:
            data = np.where(np.isin(data, value_list), data, 0)

        # skip empty / uniform blocks (reference copy_volume.py _copy_block)
        if data.size == 0 or not np.any(data):
            return
        if config.get("map_uniform_blocks_to_background", False) and (
            np.unique(data).size == 1
        ):
            return

        out_bb = bb
        roi_begin, _ = self._roi(config)
        if self.fit_to_roi and roi_begin is not None:
            out_bb = tuple(
                slice(b.start - off, b.stop - off)
                for b, off in zip(bb, roi_begin)
            )

        reduce_channels = config.get("reduce_channels")
        if reduce_channels is not None and data.ndim == 4:
            data = getattr(np, reduce_channels)(data[0:3], axis=0)
        elif data.ndim == 4:
            out_bb = (slice(None),) + out_bb

        offset = config.get("offset")
        if offset is not None:
            data = np.where(data != 0, data + offset, data)

        if config.get("insert_mode", False):
            prev = np.asarray(out_ds[out_bb])
            data = np.where(data == 0, prev.astype(data.dtype, copy=False), data)

        out_ds[out_bb] = cast_type(data, out_ds.dtype)

    def finalize(self, blocking, config, block_ids: List[int]) -> None:
        # mirror input attributes onto the output (reference copy_volume job 0)
        in_ds = self.input_ds()
        out_ds = self.output_ds()
        for k in in_ds.attrs.keys():
            out_ds.attrs[k] = in_ds.attrs[k]
