"""Edge-feature accumulation over boundary or affinity maps.

Reference features/{block_edge_features,merge_edge_features}.py via
nifty.distributed accumulators (SURVEY.md §2.3).  10 features per edge
(mean, var, min, q10..q90, max, count); the cross-block merge is exact for
the moment statistics, and quantiles merge through a per-edge HIST_BINS-bin
histogram sketch carried in the block partials (exact up to one bin width;
out-of-range or legacy 10-column partials degrade to count-weighted
averaging — ops/rag.py doc).

Scratch layout:
  features/ids     ragged per block: global edge ids
  features/vals    ragged per block: flattened [k,10] partial features
  features/hists   ragged per block: flattened [k, HIST_BINS] uint32 sketches
  features/edges   [m,10] merged feature matrix
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..ops.rag import (
    N_FEATURES,
    affinity_edge_features,
    boundary_edge_features,
    filter_edge_features,
    merge_edge_features,
    merge_edge_features_multi,
    HIST_BINS,
)
from ..runtime import config as cfg
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, merge_threads, read_ragged_chunks, read_threads, resolve_n_blocks
from .graph import read_block_with_upper_halo, load_graph

def quantile_plan(config):
    """(exact, sketch) from quantile_mode × path — shared by the block task
    (what partials to write) and the merge task (what the partials must
    support), so the two sides cannot silently disagree.  "sketch" and
    "approx" on the filter path both mean approx (filter responses escape
    the sketch's [0,1] bin domain)."""
    mode = config.get("quantile_mode", "auto")
    if mode not in ("auto", "exact", "sketch", "approx"):
        raise ValueError(f"unknown quantile_mode {mode!r}")
    filters = config.get("filters") is not None
    exact = mode == "exact" or (mode == "auto" and filters)
    sketch = not exact and not filters and mode != "approx"
    return exact, sketch


FEATURE_IDS_KEY = "features/ids"
FEATURE_VALS_KEY = "features/vals"
FEATURE_HISTS_KEY = "features/hists"
FEATURE_SAMPLES_KEY = "features/samples"
FEATURES_KEY = "features/edges"


class BlockEdgeFeaturesTask(VolumeTask):
    """Per-block edge features (reference block_edge_features.py:21).

    ``input_path/key`` is the boundary/affinity map; ``labels_path/key`` the
    segmentation whose RAG was extracted.
    """

    task_name = "block_edge_features"
    output_dtype = None

    def __init__(self, *args, labels_path: str = None, labels_key: str = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.labels_path = labels_path
        self.labels_key = labels_key

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {
                "offsets": None,  # affinity offsets, None → boundary map
                # filter-bank accumulation (reference
                # block_edge_features.py:40-41,151-238): a bank of device
                # filters (ops/filters) × sigmas, 9 stats per response
                # channel + one trailing count column
                "filters": None,
                "sigmas": None,
                "halo": [0, 0, 0],
                "apply_in_2d": False,
                "channel_agglomeration": "mean",
                # quantile merge strategy: "auto" (sketch for the 10-column
                # default path, exact raw-sample partials for the filter
                # bank), "exact" (raw samples everywhere — zero drift vs a
                # single-shot recompute), "sketch" (histogram sketch; filter
                # responses leave the sketch's [0,1] domain so the filter
                # path degrades to "approx"), or "approx" (count-weighted
                # quantile averaging — smallest partials, largest drift)
                "quantile_mode": "auto",
                # fused device accumulator (ops/rag.boundary_edge_features_tpu)
                # for boundary-map blocks without halos; numpy path otherwise.
                # Off by default: wins on TPU (hardware sort), loses on XLA-CPU
                "device_accumulation": False,
                "max_edges_per_block": 16384,
            }
        )
        return conf

    def labels_ds(self):
        from ..utils import store

        return store.file_reader(self.labels_path, "r")[self.labels_key]

    def _quantile_plan(self, config):
        return quantile_plan(config)

    def _filter_responses(self, blocking: Blocking, block_id: int, config):
        """Halo'd read → device filter bank → per-channel responses cropped
        to the inner(+1-upper-halo) region (reference
        block_edge_features.py:172-238 via vu.apply_filter).

        Unlike the reference's per-block min-max ``vu.normalize`` this uses
        the task's deterministic normalization (uint8 → /255, floats raw), so
        blocked responses equal a single-shot whole-volume recompute wherever
        the halo covers the filter support."""
        import jax.numpy as jnp

        from ..ops import filters as F

        block = blocking.block(block_id)
        shape = blocking.shape
        halo = [int(h) for h in (config.get("halo") or [0, 0, 0])]
        # the accumulated region carries a +1 upper halo (cross-block faces
        # are owned by the lower block), so the upper read extends halo + 1:
        # even the +1-slab voxels then see the full filter support
        ob = [max(b - h, 0) for b, h in zip(block.begin, halo)]
        oe = [min(e + h + 1, s) for e, h, s in zip(block.end, halo, shape)]
        bb = tuple(slice(b, e) for b, e in zip(ob, oe))
        data_ds = self.input_ds()
        if len(data_ds.shape) == 4:
            # agglomerate over ALL channels (the reference hardcodes the
            # first three, block_edge_features.py:214-215 — a marked TODO
            # there; silent truncation is worse than the divergence)
            data = self._normalize(data_ds[(slice(None),) + bb])
            agglo = config.get("channel_agglomeration") or "mean"
            data = getattr(np, agglo)(data, axis=0)
        else:
            data = self._normalize(data_ds[bb])
        ie = [min(e + 1, s) for e, s in zip(block.end, shape)]
        local = tuple(
            slice(b - o, e - o) for b, o, e in zip(block.begin, ob, ie)
        )
        if not config.get("sigmas"):
            raise ValueError(
                "filter-bank accumulation needs 'sigmas' (a list of filter "
                "scales) alongside 'filters' in the block_edge_features "
                "config (reference block_edge_features.py:312)"
            )
        responses = []
        x = jnp.asarray(data.astype(np.float32))
        in_2d = bool(config.get("apply_in_2d", False))
        for name in config["filters"]:
            for sigma in config["sigmas"]:
                resp = np.asarray(
                    F.apply_filter(x, name, sigma, apply_in_2d=in_2d),
                    dtype=np.float64,
                )
                if resp.ndim == 4:  # multichannel filters: channels last
                    responses.extend(
                        resp[..., c][local] for c in range(resp.shape[-1])
                    )
                else:
                    responses.append(resp[local])
        return responses

    def process_block(self, block_id: int, blocking: Blocking, config):
        seg = read_block_with_upper_halo(
            self.labels_ds(), blocking, block_id
        ).astype(np.uint64)
        data_ds = self.input_ds()
        offsets = config.get("offsets")
        block = blocking.block(block_id)
        end = tuple(min(e + 1, s) for e, s in zip(block.end, blocking.shape))
        bb = tuple(slice(b, e) for b, e in zip(block.begin, end))
        exact, sketch = self._quantile_plan(config)
        hist_bins = HIST_BINS if sketch else 0
        hists = samples = None
        if config.get("filters") is not None:
            if offsets is not None:
                raise ValueError(
                    "filters and offsets are mutually exclusive "
                    "(reference block_edge_features.py:311)"
                )
            responses = self._filter_responses(blocking, block_id, config)
            out = filter_edge_features(
                seg, responses, owner_shape=block.shape, return_samples=exact
            )
            edges, feats = out[0], out[1]
            if exact:
                samples = out[2]
        elif offsets is not None:
            data = self._normalize(data_ds[(slice(0, len(offsets)),) + bb])
            out = affinity_edge_features(
                seg, data, offsets, hist_bins=hist_bins,
                owner_shape=block.shape, return_samples=exact,
            )
            edges, feats = out[0], out[1]
            if exact:
                samples = out[2]
            elif sketch:
                hists = out[2]
        elif config.get("device_accumulation") and not exact:
            from ..ops.rag import boundary_edge_features_tpu

            data = self._normalize(data_ds[bb])
            edges, feats, hists = boundary_edge_features_tpu(
                seg, data, hist_bins=HIST_BINS, owner_shape=block.shape,
                max_edges=int(config.get("max_edges_per_block", 16384)),
            )
            if not sketch:
                hists = None
        else:
            data = self._normalize(data_ds[bb])
            out = boundary_edge_features(
                seg, data, hist_bins=hist_bins, owner_shape=block.shape,
                return_samples=exact,
            )
            edges, feats = out[0], out[1]
            if exact:
                samples = out[2]
            elif sketch:
                hists = out[2]

        store = self.tmp_store()
        nodes, gedges = load_graph(store)
        ids_out = self.tmp_ragged(FEATURE_IDS_KEY, blocking.n_blocks, np.int64)
        vals_out = self.tmp_ragged(FEATURE_VALS_KEY, blocking.n_blocks, np.float64)
        hists_out = self.tmp_ragged(FEATURE_HISTS_KEY, blocking.n_blocks, np.uint32)
        # keep the samples dataset in lockstep even when this run does not
        # produce samples: a previous exact-mode run's stale chunks must not
        # poison this run's merge (empty chunk ⇒ merge rejects exact path)
        samples_out = (
            self.tmp_ragged(FEATURE_SAMPLES_KEY, blocking.n_blocks, np.float64)
            if (samples is not None or FEATURE_SAMPLES_KEY in store)
            else None
        )
        if edges.shape[0] == 0:
            ids_out.write_chunk((block_id,), np.array([], dtype=np.int64))
            vals_out.write_chunk((block_id,), np.array([], dtype=np.float64))
            hists_out.write_chunk((block_id,), np.array([], dtype=np.uint32))
            if samples_out is not None:
                samples_out.write_chunk(
                    (block_id,), np.array([], dtype=np.float64)
                )
            return
        pairs = np.searchsorted(nodes, edges).astype(np.int64)
        keys = gedges[:, 0] * (nodes.size + 1) + gedges[:, 1]
        want = pairs[:, 0] * (nodes.size + 1) + pairs[:, 1]
        ids = np.searchsorted(keys, want)
        valid = keys[np.clip(ids, 0, keys.size - 1)] == want
        ids_out.write_chunk((block_id,), ids[valid].astype(np.int64))
        vals_out.write_chunk((block_id,), feats[valid].reshape(-1))
        hists_out.write_chunk(
            (block_id,),
            hists[valid].reshape(-1) if hists is not None
            else np.array([], dtype=np.uint32),
        )
        if samples_out is not None:
            if samples is None:
                samples_out.write_chunk(
                    (block_id,), np.array([], dtype=np.float64)
                )
            else:
                counts = feats[:, -1].astype(np.int64)
                total = int(counts.sum())
                n_groups = (feats.shape[1] - 1) // 9
                keep = np.repeat(valid, counts)
                kept = (
                    samples.reshape(n_groups, total)[:, keep].reshape(-1)
                    if total
                    else samples
                )
                samples_out.write_chunk((block_id,), kept)

    @staticmethod
    def _normalize(data: np.ndarray) -> np.ndarray:
        if data.dtype == np.uint8:
            return data.astype(np.float64) / 255.0
        return data.astype(np.float64)


class MergeEdgeFeaturesTask(VolumeSimpleTask):
    """Merge per-block partial features (reference merge_edge_features.py:17)."""

    task_name = "merge_edge_features"

    def __init__(self, *args, labels_path: str = None, labels_key: str = None,
                 **kwargs):
        super().__init__(*args, labels_path=labels_path, labels_key=labels_key,
                         **kwargs)

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(self.config_dir, self.labels_path, self.labels_key)
        store = self.tmp_store()
        n_edges = store["graph/edges"].attrs["n_edges"]
        ids_ds = store[FEATURE_IDS_KEY]
        vals_ds = store[FEATURE_VALS_KEY]
        ids_list, feats_list, hists_list, samples_list = [], [], [], []
        n_thr = merge_threads(self)
        all_ids = read_ragged_chunks(ids_ds, n_blocks, n_thr)
        all_vals = read_ragged_chunks(vals_ds, n_blocks, n_thr)
        # sketches live in their own uint32 ragged dataset; absent for scratch
        # written before the histogram merge existed (legacy fallback)
        if FEATURE_HISTS_KEY in store:
            all_hists = read_ragged_chunks(store[FEATURE_HISTS_KEY], n_blocks, n_thr)
        else:
            all_hists = [None] * n_blocks
        # raw sorted samples: only written in exact quantile mode
        if FEATURE_SAMPLES_KEY in store:
            all_samples = read_ragged_chunks(
                store[FEATURE_SAMPLES_KEY], n_blocks, n_thr
            )
        else:
            all_samples = [None] * n_blocks
        for ids, vals, hists, samples in zip(
            all_ids, all_vals, all_hists, all_samples
        ):
            if ids is None or ids.size == 0:
                continue
            ids_list.append(ids)
            feats_list.append(vals.reshape(ids.size, -1))
            hists_list.append(
                hists.reshape(ids.size, -1)
                if hists is not None and hists.size
                else None
            )
            samples_list.append(samples)
        n_cols = next(
            (f.shape[1] for f in feats_list if f.shape[0]), N_FEATURES
        )
        widths = {f.shape[1] for f in feats_list if f.shape[0]}
        if len(widths) > 1:
            raise ValueError(
                f"mixed per-block feature widths {sorted(widths)} — stale "
                "partials from a config switch; rerun block_edge_features "
                "over all blocks"
            )
        # exact merge only when EVERY nonempty block shipped a size-consistent
        # sample partial (stale/empty chunks from a mode switch disqualify)
        n_groups = (n_cols - 1) // 9
        exact = bool(samples_list) and all(
            s is not None and s.size == n_groups * int(f[:, -1].sum())
            for s, f in zip(samples_list, feats_list)
        )
        # never silently downgrade a configured exact merge: partials from a
        # sketch-mode run (e.g. mode switched without rerunning the blocks)
        # lack usable samples
        bconf = cfg.read_config(self.config_dir, "block_edge_features")
        wants_exact, _ = quantile_plan(bconf)
        if wants_exact and not exact and ids_list:
            raise ValueError(
                "quantile_mode requests the exact merge but the block "
                "partials carry no usable sample arrays — rerun "
                "block_edge_features (clear its status) so the blocks "
                "write exact-mode partials"
            )
        if n_cols == N_FEATURES and not exact:
            merged = merge_edge_features(
                ids_list, feats_list, n_edges, hists_list
            )
        else:
            merged = merge_edge_features_multi(
                ids_list, feats_list, n_edges,
                samples_list if exact else None,
            )
        ds = store.create_dataset(
            FEATURES_KEY,
            data=merged,
            chunks=(max(merged.shape[0], 1), merged.shape[1]),
            exist_ok=True,
        )
        ds.attrs["n_features"] = int(merged.shape[1])
        self.log(
            f"merged {merged.shape[1]}-column features for {n_edges} edges"
        )


class ShardedProblemTask(VolumeSimpleTask):
    """Whole-problem RAG extraction + 10-feature accumulation in ONE
    collective program over the device mesh
    (``parallel.sharded_rag.sharded_boundary_edge_features``) — the
    collective replacement for the InitialSubGraphs→MergeSubGraphs→MapEdgeIds
    + BlockEdgeFeatures→MergeEdgeFeatures chain when the volume fits the
    mesh's aggregate HBM.  Both volumes stream shard-by-shard from the
    store (``mesh.put_from_store``) with per-slab label compaction and
    normalization in the read callbacks, so peak host RAM on ingest is one
    slab plus the global node table; HBM holds the int32 compact labels and
    float32 data.  Writes the standard problem scratch layout
    (graph/nodes, graph/edges + attrs, features/edges) so every downstream
    consumer (costs, global multicut solve, postprocess graph tasks) runs
    unchanged.

    ``input_path/key`` = boundary map, ``labels_path/key`` = segmentation.
    """

    task_name = "sharded_problem"
    collective = True

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 labels_path: str = None, labels_key: str = None, **kwargs):
        super().__init__(
            *args, input_path=input_path, input_key=input_key,
            labels_path=labels_path, labels_key=labels_key, **kwargs,
        )

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"max_edges": 16384})
        return conf

    def run_impl(self) -> None:
        from ..parallel.mesh import get_mesh, put_from_store, resolve_devices
        from ..parallel.sharded_rag import sharded_boundary_edge_features
        from ..utils import store

        conf = {**self.global_config(), **self.get_task_config()}
        seg_ds = store.file_reader(self.labels_path, "r")[self.labels_key]
        data_ds = store.file_reader(self.input_path, "r")[self.input_key]
        store.set_read_threads(seg_ds, read_threads(conf))
        store.set_read_threads(data_ds, read_threads(conf))
        if len(data_ds.shape) != len(seg_ds.shape):
            raise ValueError(
                "sharded_problem supports 3d boundary maps only — affinity "
                "(4d) inputs go through the block pipeline "
                "(sharded_problem=False with block_edge_features offsets)"
            )

        devices = resolve_devices(conf)
        mesh = get_mesh(devices)
        n_dev = len(devices)
        z = int(seg_ds.shape[0])

        # pass 1 (host, slab-wise): the global node table — peak host RAM
        # is one slab plus the accumulating uniques.  Slab height follows
        # the store's z-chunking so no chunk is decompressed twice.  The
        # same pass counts boundary face rows per z-plane, from which the
        # per-shard sample-compaction cap is sized (shard_sample_cap needs
        # the whole volume; here only per-plane counts accumulate).
        zc = int((seg_ds.chunks or (8,))[0]) or 8
        zp = z + (-z) % n_dev  # padded extent (pad planes count 0)
        c_in = np.zeros(zp, np.int64)   # in-plane pairs of plane zi
        c_z = np.zeros(zp, np.int64)    # pairs between planes zi and zi+1
        prev_last = None
        slabs = []
        from ..ops.rag import plane_face_counts

        for z0 in range(0, z, zc):
            # cast BEFORE unique: signed ignore labels (e.g. -1) must wrap
            # to their uint64 identity exactly as the full-volume cast did,
            # or the node table silently drops/disorders them
            slab = np.asarray(seg_ds[z0 : z0 + zc]).astype(np.uint64)
            slabs.append(np.unique(slab))
            s_in, s_z, boundary, prev_last = plane_face_counts(
                slab, prev_last
            )
            c_in[z0 : z0 + slab.shape[0]] += s_in
            c_z[z0 : z0 + slab.shape[0]] += s_z
            if z0:
                c_z[z0 - 1] += boundary
        nodes = np.unique(np.concatenate(slabs)) if slabs else np.zeros(
            0, np.uint64
        )
        nodes = nodes[nodes > 0]
        # shard i owns planes [i*h, (i+1)*h) plus the z-pair into the next
        # shard's first plane (mesh-edge shard: ppermute zero-fill)
        h = zp // n_dev
        worst = 1
        for i in range(n_dev):
            zo, z1 = i * h, (i + 1) * h
            cnt = int(c_in[zo:z1].sum() + c_z[zo:z1].sum())
            worst = max(worst, cnt)
        from ..ops.rag import sample_capacity

        sample_cap = sample_capacity(worst)

        # pass 2: stream both volumes shard-by-shard; compaction to
        # 1..n node ids and the block path's normalization convention
        # (uint8 → /255, other dtypes raw) run per shard in the callbacks
        def compact_slab(s):
            s = s.astype(np.uint64)
            c = np.searchsorted(nodes, s) + 1
            return np.where(s > 0, c, 0)  # label 0: no pairs in the pad

        def normalize_slab(d):
            if d.dtype == np.uint8:
                return d.astype(np.float32) / 255.0
            return np.asarray(d, dtype=np.float32)

        # compact labels depend on the run-local node table, so they stay
        # uncached; the boundary-map upload routes through the warm
        # device-buffer cache (ctt-hbm) — a back-to-back serve job on the
        # same volume reuses the HBM-resident float32 array
        from ..runtime import hbm

        compact_d = put_from_store(
            seg_ds, mesh, dtype=np.int32, pad_to=n_dev, transform=compact_slab
        )
        data_d = hbm.cached_put_from_store(
            data_ds, mesh, source_path=self.input_path,
            source_key=self.input_key, tag=("problem-data",),
            dtype=np.float32, pad_to=n_dev, transform=normalize_slab,
        )

        edges_c, feats = sharded_boundary_edge_features(
            compact_d, data_d, mesh=mesh,
            max_edges=int(conf.get("max_edges", 16384)),
            # compact ids are 1..nodes.size (searchsorted+1): the exact
            # bound gates the packed single-key sort without touching the
            # (possibly multi-host global) device array
            max_id=int(nodes.size),
            max_samples=sample_cap,
        )
        import jax as _jax

        if _jax.process_index() != 0:
            return  # process 0 owns the scratch-store writes
        self._write_problem_scratch(nodes, edges_c, feats)
        self.log(
            f"sharded problem over {len(devices)} devices: "
            f"{nodes.size} nodes, {edges_c.shape[0]} edges"
        )

    def _write_problem_scratch(self, nodes, edges_c, feats):
        """Write the standard problem scratch layout (graph/nodes,
        graph/edges + attrs, features/edges) from compact-id edges —
        shared by the collective problem tasks."""
        from .graph import EDGES_KEY, NODES_KEY

        dense = (edges_c - 1).astype(np.int64)  # compact id → node index
        out = self.tmp_store()
        out.create_dataset(
            NODES_KEY, data=nodes, chunks=(max(nodes.size, 1),), exist_ok=True
        )
        out.create_dataset(
            EDGES_KEY, data=dense,
            chunks=(max(dense.shape[0], 1), 2), exist_ok=True,
        )
        g = out[EDGES_KEY]
        g.attrs["n_nodes"] = int(nodes.size)
        g.attrs["n_edges"] = int(dense.shape[0])
        out.create_dataset(
            FEATURES_KEY, data=feats.astype(np.float64),
            chunks=(max(feats.shape[0], 1), N_FEATURES), exist_ok=True,
        )


class ShardedWsProblemTask(ShardedProblemTask):
    """Device-resident watershed → RAG+features: ONE collective session for
    the whole front of the multicut pipeline (VERDICT r4 item 3 — "keep the
    volume device-resident across watershed→graph→features").

    The split pipeline moves the volume across the host↔device boundary
    five times: the block watershed uploads halo'd blocks and fetches
    labels per batch, writes them, then the problem task re-reads BOTH
    volumes from the store and re-uploads them.  Here the boundary map is
    uploaded ONCE and stays device-resident: the sharded DT-watershed
    consumes it, its labels come down once (the size filter and the ws
    store write need them on host anyway), and the compact relabeling goes
    back up for the collective RAG, which reuses the SAME device-resident
    boundary array.  Per run that removes one full boundary re-read +
    re-upload, one label store re-read + re-upload, the per-block halo'd
    reads, and the slab-wise node-table pass (the host relabel already
    yields it) — on a tunneled chip each saved transfer is wall-clock.

    Writes the ws dataset (``output_path/output_key``, compact consecutive
    ids — same contract as ``ShardedWatershedTask``) AND the standard
    problem scratch, so every downstream consumer (costs, global solve,
    write) runs unchanged, and resume/checkpoint semantics stay store-based.

    The watershed mode follows ``apply_dt_2d``/``apply_ws_2d`` in the task
    config exactly like ``ShardedWatershedTask`` (both default False → the
    3d collective; both True → the zero-collective per-slice kernel, the
    block pipeline's CREMI default — ``run_sharded_ws_kernel`` dispatches).
    Masked volumes go through the block pipeline.
    """

    task_name = "sharded_ws_problem"
    collective = True

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        from .watershed import ShardedWatershedTask

        ws_conf = ShardedWatershedTask.default_task_config()
        conf.update({
            k: v for k, v in ws_conf.items() if k not in conf
        })
        return conf

    def run_impl(self) -> None:
        import jax as _jax

        from ..ops.relabel import relabel_consecutive_np
        from ..parallel.mesh import get_mesh, put_global, resolve_devices
        from ..parallel.sharded_rag import sharded_boundary_edge_features
        from ..utils import store
        from .watershed import _normalize_host, run_sharded_ws_kernel

        conf = {**self.global_config(), **self.get_task_config()}
        in_ds = store.file_reader(self.input_path, "r")[self.input_key]
        if in_ds.ndim != 3:
            raise ValueError(
                "sharded_ws_problem supports 3d boundary maps only"
            )
        if np.dtype(in_ds.dtype) == np.uint16:
            # the device-resident array serves BOTH stages, but the split
            # pipeline normalizes them differently for uint16 (watershed
            # /65535, features raw) — reusing one array would silently
            # change the features; keep exact parity by refusing
            raise ValueError(
                "sharded_ws does not support uint16 boundary maps (the "
                "watershed and feature stages disagree on uint16 "
                "normalization) — use sharded_ws=False"
            )
        store.set_read_threads(in_ds, read_threads(conf))
        devices = resolve_devices(conf)
        mesh = get_mesh(devices)
        n_dev = len(devices)
        z = int(in_ds.shape[0])
        invert = bool(conf.get("invert_inputs", False))

        import time as _time

        def timed(phase, fn):
            # sequential phases under the breakdown's "batch_*" convention
            # so bench_e2e_lib.task_breakdown attributes the fused wall
            t0 = _time.perf_counter()
            r = fn()
            self.record_timing(f"batch_{phase}", 1, _time.perf_counter() - t0)
            return r

        # ONE upload; the array stays resident through watershed AND RAG —
        # and, through the shared device-buffer cache (ctt-hbm), across
        # back-to-back jobs on the same volume: this task's "uploaded
        # ONCE, stays resident" pattern is exactly what the cache
        # generalizes, so the upload is no longer an ad-hoc one-off (the
        # timing record keeps the batch_* breakdown contract)
        from ..runtime import hbm

        x_d = timed("upload", lambda: hbm.cached_put_from_store(
            in_ds, mesh, source_path=self.input_path,
            source_key=self.input_key,
            tag=("ws-problem-input", bool(invert)),
            dtype=np.float32, pad_to=n_dev,
            pad_value=1.0 if invert else 0.0,
            transform=_normalize_host,
        ))

        labels, _ = timed("watershed", lambda: run_sharded_ws_kernel(
            x_d, conf, mesh, z_valid=z
        ))
        compact, n_labels = relabel_consecutive_np(labels.astype(np.uint64))
        compact32 = compact.astype(np.int32)
        pad = (-z) % n_dev
        if pad:  # pad slab: label 0 → contributes no RAG pairs
            compact32 = np.pad(compact32, ((0, pad), (0, 0), (0, 0)))
        compact_d = put_global(compact32, mesh, dtype=np.int32)

        from ..parallel.sharded_rag import shard_sample_cap

        edges_c, feats = timed("rag", lambda: sharded_boundary_edge_features(
            compact_d, x_d, mesh=mesh,
            max_edges=int(conf.get("max_edges", 16384)),
            max_id=int(n_labels),
            # the padded compact labels are on host anyway — size the
            # per-shard compaction cap from them
            max_samples=shard_sample_cap(compact32, n_dev),
        ))

        if _jax.process_index() != 0:
            return  # process 0 owns the store writes
        ds = self.require_output(in_ds.shape, conf)
        # threaded chunk-aligned whole-volume write (store fast path)
        store.set_read_threads(ds, read_threads(conf))
        timed("write", lambda: ds.__setitem__(slice(None), compact))
        # ws ids ARE 1..n_labels consecutive — the node table is implied
        nodes = np.arange(1, n_labels + 1, dtype=np.uint64)
        self._write_problem_scratch(nodes, edges_c, feats)
        self.log(
            f"sharded ws+problem over {n_dev} devices: {n_labels} fragments, "
            f"{edges_c.shape[0]} edges, boundary volume device-resident"
        )
