"""Postprocessing: size filters, id filters, orphan handling, graph components.

Reference postprocess/*.py (SURVEY.md §2.4, 1798 LoC family):
  * size_filter           — discard segments below/above size bounds
    (size_filter_blocks.py:23 + background_size_filter/filling_size_filter)
  * id_filter             — remove an explicit id list (id_filter.py:22)
  * graph_watershed_assignments — reassign discarded segments to their
    strongest-connected kept neighbor by edge-weighted graph watershed
    (graph_watershed_assignments.py:172)
  * graph_connected_components  — CC over the node graph
    (graph_connected_components.py:25)
  * orphan_assignments    — merge orphans (segments without kept neighbors)
    into their largest neighbor (orphan_assignments.py:26)

All emit (old_id → new_id) assignment tables consumed by the write task.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..ops.unionfind import UnionFindNp
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask
from .morphology import MORPHOLOGY_NAME

SIZE_FILTER_NAME = "size_filter_assignments.npy"
SIZE_FILTER_DISCARD_NAME = "size_filter_discard.npy"
ID_FILTER_NAME = "id_filter_assignments.npy"
GRAPH_CC_NAME = "graph_cc_assignments.npy"
GRAPH_WS_NAME = "graph_watershed_assignments.npy"


class SizeFilterTask(VolumeSimpleTask):
    """Assignment table zeroing segments outside [min_size, max_size]
    (consumes the morphology table)."""

    task_name = "size_filter"

    def __init__(self, *args, min_size: int = 0, max_size: Optional[int] = None,
                 relabel: bool = True, **kwargs):
        super().__init__(*args, min_size=min_size, max_size=max_size,
                         relabel=relabel, **kwargs)

    def run_impl(self) -> None:
        table = np.load(os.path.join(self.tmp_folder, MORPHOLOGY_NAME))
        ids = table[:, 0].astype(np.uint64)
        sizes = table[:, 1]
        keep = sizes >= self.min_size
        if self.max_size is not None:
            keep &= sizes <= self.max_size
        keep &= ids != 0
        kept_ids = ids[keep]
        new_ids = (
            np.arange(1, kept_ids.size + 1, dtype=np.uint64)
            if self.relabel
            else kept_ids
        )
        assignment = np.stack([kept_ids, new_ids], axis=1)
        np.save(os.path.join(self.tmp_folder, SIZE_FILTER_NAME), assignment)
        # the complementary discard list drives the apply steps
        # (background_size_filter / filling_size_filter / graph watershed)
        discard = ids[~keep & (ids != 0)]
        np.save(
            os.path.join(self.tmp_folder, SIZE_FILTER_DISCARD_NAME), discard
        )
        self.log(
            f"size filter: kept {kept_ids.size}/{ids.size} segments "
            f"(min_size={self.min_size})"
        )


class IdFilterTask(VolumeSimpleTask):
    """Remove an explicit list of ids (reference id_filter.py:22)."""

    task_name = "id_filter"

    def __init__(self, *args, filter_ids=(), all_ids_path: str = None, **kwargs):
        super().__init__(*args, filter_ids=tuple(filter_ids),
                         all_ids_path=all_ids_path, **kwargs)

    def run_impl(self) -> None:
        table = np.load(os.path.join(self.tmp_folder, MORPHOLOGY_NAME))
        ids = table[:, 0].astype(np.uint64)
        drop = np.isin(ids, np.asarray(self.filter_ids, dtype=np.uint64))
        kept = ids[~drop & (ids != 0)]
        assignment = np.stack([kept, kept], axis=1)
        np.save(os.path.join(self.tmp_folder, ID_FILTER_NAME), assignment)


def graph_watershed_assignments(
    edges: np.ndarray,
    weights: np.ndarray,
    seeds: np.ndarray,
    n_nodes: int,
) -> np.ndarray:
    """Edge-weighted graph watershed: unlabeled nodes adopt the label of the
    neighbor reachable over the strongest path (max-min edge weight) —
    nifty.graph.edgeWeightedWatershedsSegmentation equivalent.

    ``seeds`` [n_nodes] with 0 = unlabeled.  Host Prim-style flood.
    """
    import heapq

    labels = seeds.copy()
    adj: list = [[] for _ in range(n_nodes)]
    for (u, v), w in zip(edges, weights):
        adj[int(u)].append((int(v), float(w)))
        adj[int(v)].append((int(u), float(w)))
    heap = []
    for u in np.nonzero(seeds > 0)[0]:
        for v, w in adj[u]:
            if labels[v] == 0:
                heapq.heappush(heap, (-w, int(u), v))
    while heap:
        negw, u, v = heapq.heappop(heap)
        if labels[v] != 0:
            continue
        labels[v] = labels[u]
        for x, w in adj[v]:
            if labels[x] == 0:
                heapq.heappush(heap, (-w, v, x))
    return labels


class GraphWatershedAssignmentsTask(VolumeSimpleTask):
    """Reassign filtered-out segments to kept neighbors via graph watershed
    (reference graph_watershed_assignments.py:25).  Needs the problem graph
    (graph/edges) and edge costs/weights in the scratch store."""

    task_name = "graph_watershed_assignments"

    def __init__(self, *args, filter_path: str = None, **kwargs):
        super().__init__(*args, filter_path=filter_path, **kwargs)

    def run_impl(self) -> None:
        from .costs import COSTS_NAME
        from .graph import load_graph

        nodes, edges = load_graph(self.tmp_store())
        weights = np.load(os.path.join(self.tmp_folder, COSTS_NAME))
        filtered = np.load(self.filter_path)  # ids to discard
        drop = np.isin(nodes, filtered.astype(nodes.dtype))
        seeds = np.arange(1, nodes.size + 1, dtype=np.int64)
        seeds[drop] = 0
        # signed costs: larger = more attractive; the flood must follow merge
        # evidence, NOT |cost| (a strongly repulsive edge is a definite boundary)
        assigned = graph_watershed_assignments(
            edges, weights, seeds, nodes.size
        )
        # assigned holds (index+1) of the adopting node
        target = nodes[np.maximum(assigned - 1, 0)]
        target = np.where(assigned > 0, target, 0)
        assignment = np.stack([nodes, target.astype(np.uint64)], axis=1)
        np.save(os.path.join(self.tmp_folder, GRAPH_WS_NAME), assignment)
        self.log(f"graph-watershed reassigned {int(drop.sum())} segments")


class GraphConnectedComponentsTask(VolumeSimpleTask):
    """Connected components over the node graph, optionally restricted to edges
    above a merge threshold (reference graph_connected_components.py:25)."""

    task_name = "graph_connected_components"

    def __init__(self, *args, threshold: Optional[float] = None, **kwargs):
        super().__init__(*args, threshold=threshold, **kwargs)

    def run_impl(self) -> None:
        from .costs import COSTS_NAME
        from .graph import load_graph

        nodes, edges = load_graph(self.tmp_store())
        use = np.ones(edges.shape[0], dtype=bool)
        if self.threshold is not None:
            weights = np.load(os.path.join(self.tmp_folder, COSTS_NAME))
            use = weights > self.threshold
        uf = UnionFindNp(nodes.size)
        if use.any():
            uf.merge(edges[use, 0], edges[use, 1])
        roots = uf.compress()
        _, comp = np.unique(roots, return_inverse=True)
        assignment = np.stack(
            [nodes, (comp + 1).astype(np.uint64)], axis=1
        )
        np.save(os.path.join(self.tmp_folder, GRAPH_CC_NAME), assignment)
        n_comp = int(comp.max()) + 1 if comp.size else 0
        self.log(f"graph CC: {nodes.size} nodes → {n_comp} components")


ORPHANS_NAME = "orphan_assignments.npy"


class OrphanAssignmentsTask(VolumeSimpleTask):
    """Merge orphan segments (graph degree one after applying an assignment)
    into their single neighbor (reference orphan_assignments.py:26-146)."""

    task_name = "orphan_assignments"

    def __init__(self, *args, assignment_path: str = None,
                 relabel: bool = False, **kwargs):
        super().__init__(*args, assignment_path=assignment_path,
                         relabel=relabel, **kwargs)

    def run_impl(self) -> None:
        from ..ops.multicut import contract_edges
        from .graph import load_graph

        nodes, edges = load_graph(self.tmp_store())
        # assignments: dense per-node-index cluster vector or (node, cluster)
        # table; nodes absent from a sparse table keep their own label
        # (mapping them to 0 would wipe every unlisted segment to background).
        # No path = identity: orphans judged on the raw fragment graph.
        table = (
            nodes.astype(np.uint64)
            if self.assignment_path is None
            else np.load(self.assignment_path)
        )
        if table.ndim == 2:
            assignments = nodes.astype(np.uint64).copy()
            idx = np.searchsorted(nodes, table[:, 0].astype(nodes.dtype))
            ok = idx < nodes.size
            ok &= nodes[np.clip(idx, 0, nodes.size - 1)] == table[:, 0].astype(
                nodes.dtype
            )
            assignments[idx[ok]] = table[ok, 1].astype(np.uint64)
        else:
            assignments = table.astype(np.uint64)

        cl_u = assignments[edges[:, 0]].astype(np.int64)
        cl_v = assignments[edges[:, 1]].astype(np.int64)
        new_uv, _ = contract_edges(cl_u, cl_v, np.ones(edges.shape[0]))
        ids, degrees = np.unique(new_uv, return_counts=True)
        orphans = ids[degrees == 1]
        orphans = orphans[orphans != 0]
        adopt = assignments.copy()
        if orphans.size:
            # each orphan has exactly one incident contracted edge — adopt
            # the other endpoint (reference orphan_assignments.py:129-141)
            flat = new_uv.reshape(-1)
            other = new_uv[:, ::-1].reshape(-1)
            order = np.argsort(flat, kind="stable")
            pos = np.searchsorted(flat[order], orphans)
            neighbor = other[order][pos]
            remap = {int(o): int(nb) for o, nb in zip(orphans, neighbor)}
            adopt = np.asarray(
                [remap.get(int(a), int(a)) for a in assignments],
                dtype=np.uint64,
            )
        if self.relabel:
            uniq, inv = np.unique(adopt, return_inverse=True)
            # keep 0 fixed, compact the rest to 1..k
            remap_v = np.zeros(uniq.size, dtype=np.uint64)
            nonzero = uniq != 0
            remap_v[nonzero] = np.arange(1, int(nonzero.sum()) + 1)
            adopt = remap_v[inv]
        assignment = np.stack([nodes, adopt], axis=1)
        np.save(os.path.join(self.tmp_folder, ORPHANS_NAME), assignment)
        self.log(f"merged {orphans.size} orphans")


class FilterBlocksTask(VolumeTask):
    """Zero out an id list block-wise (reference filter_blocks.py:25;
    background_size_filter.py:20 is the same apply step driven by the size
    filter's discard list)."""

    task_name = "filter_blocks"
    output_dtype = "uint64"

    def __init__(self, *args, filter_path: str = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.filter_path = filter_path
        self._discard = None

    def discard_ids(self) -> np.ndarray:
        if self._discard is None:  # loaded once per task, not once per block
            self._discard = np.load(self.filter_path).astype(np.uint64)
        return self._discard

    def process_block(self, block_id: int, blocking: Blocking, config):
        block = blocking.block(block_id)
        labels = np.asarray(self.input_ds()[block.slicing]).astype(np.uint64)
        if not labels.any():
            return
        labels = np.where(np.isin(labels, self.discard_ids()), 0, labels)
        self.output_ds()[block.slicing] = labels


class BackgroundSizeFilterTask(FilterBlocksTask):
    """Alias task matching the reference's name for the map-to-background
    apply step (background_size_filter.py:20)."""

    task_name = "background_size_filter"


class FillingSizeFilterTask(VolumeTask):
    """Discarded ids are re-flooded from the surviving segments over a height
    map instead of mapped to background (reference filling_size_filter.py:21);
    the seeded flood is the device watershed kernel."""

    task_name = "filling_size_filter"
    output_dtype = "uint64"

    def __init__(self, *args, hmap_path: str = None, hmap_key: str = None,
                 res_path: str = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.hmap_path = hmap_path
        self.hmap_key = hmap_key
        self.res_path = res_path
        self._discard = None

    def discard_ids(self) -> np.ndarray:
        if self._discard is None:
            self._discard = np.load(self.res_path).astype(np.uint64)
        return self._discard

    def process_block(self, block_id: int, blocking: Blocking, config):
        import jax.numpy as jnp

        from ..ops.watershed import seeded_watershed
        from ..utils import store as store_mod

        block = blocking.block(block_id)
        bb = block.slicing
        labels = np.asarray(self.input_ds()[bb]).astype(np.uint64)
        if not labels.any():
            return
        discard_mask = np.isin(labels, self.discard_ids())
        out_ds = self.output_ds()
        if not discard_mask.any():
            out_ds[bb] = labels
            return
        hmap_ds = store_mod.file_reader(self.hmap_path, "r")[self.hmap_key]
        hmap_bb = ((slice(0, 1),) + bb) if len(hmap_ds.shape) == 4 else bb
        hmap = np.asarray(hmap_ds[hmap_bb])
        if hmap.ndim == 4:
            hmap = hmap[0]
        labels[discard_mask] = 0
        # compact to int32 seeds for the device flood, map back after
        uniq = np.unique(labels)
        seeds = np.searchsorted(uniq, labels).astype(np.int32)
        flooded = np.array(
            seeded_watershed(jnp.asarray(hmap, jnp.float32), jnp.asarray(seeds))
        )
        out_ds[bb] = uniq[flooded]
