"""Postprocessing: size filters, id filters, orphan handling, graph components.

Reference postprocess/*.py (SURVEY.md §2.4, 1798 LoC family):
  * size_filter           — discard segments below/above size bounds
    (size_filter_blocks.py:23 + background_size_filter/filling_size_filter)
  * id_filter             — remove an explicit id list (id_filter.py:22)
  * graph_watershed_assignments — reassign discarded segments to their
    strongest-connected kept neighbor by edge-weighted graph watershed
    (graph_watershed_assignments.py:172)
  * graph_connected_components  — CC over the node graph
    (graph_connected_components.py:25)
  * orphan_assignments    — merge orphans (segments without kept neighbors)
    into their largest neighbor (orphan_assignments.py:26)

All emit (old_id → new_id) assignment tables consumed by the write task.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..ops.unionfind import UnionFindNp
from .base import VolumeSimpleTask
from .morphology import MORPHOLOGY_NAME

SIZE_FILTER_NAME = "size_filter_assignments.npy"
ID_FILTER_NAME = "id_filter_assignments.npy"
GRAPH_CC_NAME = "graph_cc_assignments.npy"
GRAPH_WS_NAME = "graph_watershed_assignments.npy"


class SizeFilterTask(VolumeSimpleTask):
    """Assignment table zeroing segments outside [min_size, max_size]
    (consumes the morphology table)."""

    task_name = "size_filter"

    def __init__(self, *args, min_size: int = 0, max_size: Optional[int] = None,
                 relabel: bool = True, **kwargs):
        super().__init__(*args, min_size=min_size, max_size=max_size,
                         relabel=relabel, **kwargs)

    def run_impl(self) -> None:
        table = np.load(os.path.join(self.tmp_folder, MORPHOLOGY_NAME))
        ids = table[:, 0].astype(np.uint64)
        sizes = table[:, 1]
        keep = sizes >= self.min_size
        if self.max_size is not None:
            keep &= sizes <= self.max_size
        keep &= ids != 0
        kept_ids = ids[keep]
        new_ids = (
            np.arange(1, kept_ids.size + 1, dtype=np.uint64)
            if self.relabel
            else kept_ids
        )
        assignment = np.stack([kept_ids, new_ids], axis=1)
        np.save(os.path.join(self.tmp_folder, SIZE_FILTER_NAME), assignment)
        self.log(
            f"size filter: kept {kept_ids.size}/{ids.size} segments "
            f"(min_size={self.min_size})"
        )


class IdFilterTask(VolumeSimpleTask):
    """Remove an explicit list of ids (reference id_filter.py:22)."""

    task_name = "id_filter"

    def __init__(self, *args, filter_ids=(), all_ids_path: str = None, **kwargs):
        super().__init__(*args, filter_ids=tuple(filter_ids),
                         all_ids_path=all_ids_path, **kwargs)

    def run_impl(self) -> None:
        table = np.load(os.path.join(self.tmp_folder, MORPHOLOGY_NAME))
        ids = table[:, 0].astype(np.uint64)
        drop = np.isin(ids, np.asarray(self.filter_ids, dtype=np.uint64))
        kept = ids[~drop & (ids != 0)]
        assignment = np.stack([kept, kept], axis=1)
        np.save(os.path.join(self.tmp_folder, ID_FILTER_NAME), assignment)


def graph_watershed_assignments(
    edges: np.ndarray,
    weights: np.ndarray,
    seeds: np.ndarray,
    n_nodes: int,
) -> np.ndarray:
    """Edge-weighted graph watershed: unlabeled nodes adopt the label of the
    neighbor reachable over the strongest path (max-min edge weight) —
    nifty.graph.edgeWeightedWatershedsSegmentation equivalent.

    ``seeds`` [n_nodes] with 0 = unlabeled.  Host Prim-style flood.
    """
    import heapq

    labels = seeds.copy()
    adj: list = [[] for _ in range(n_nodes)]
    for (u, v), w in zip(edges, weights):
        adj[int(u)].append((int(v), float(w)))
        adj[int(v)].append((int(u), float(w)))
    heap = []
    for u in np.nonzero(seeds > 0)[0]:
        for v, w in adj[u]:
            if labels[v] == 0:
                heapq.heappush(heap, (-w, int(u), v))
    while heap:
        negw, u, v = heapq.heappop(heap)
        if labels[v] != 0:
            continue
        labels[v] = labels[u]
        for x, w in adj[v]:
            if labels[x] == 0:
                heapq.heappush(heap, (-w, v, x))
    return labels


class GraphWatershedAssignmentsTask(VolumeSimpleTask):
    """Reassign filtered-out segments to kept neighbors via graph watershed
    (reference graph_watershed_assignments.py:25).  Needs the problem graph
    (graph/edges) and edge costs/weights in the scratch store."""

    task_name = "graph_watershed_assignments"

    def __init__(self, *args, filter_path: str = None, **kwargs):
        super().__init__(*args, filter_path=filter_path, **kwargs)

    def run_impl(self) -> None:
        from .costs import COSTS_NAME
        from .graph import load_graph

        nodes, edges = load_graph(self.tmp_store())
        weights = np.load(os.path.join(self.tmp_folder, COSTS_NAME))
        filtered = np.load(self.filter_path)  # ids to discard
        drop = np.isin(nodes, filtered.astype(nodes.dtype))
        seeds = np.arange(1, nodes.size + 1, dtype=np.int64)
        seeds[drop] = 0
        # signed costs: larger = more attractive; the flood must follow merge
        # evidence, NOT |cost| (a strongly repulsive edge is a definite boundary)
        assigned = graph_watershed_assignments(
            edges, weights, seeds, nodes.size
        )
        # assigned holds (index+1) of the adopting node
        target = nodes[np.maximum(assigned - 1, 0)]
        target = np.where(assigned > 0, target, 0)
        assignment = np.stack([nodes, target.astype(np.uint64)], axis=1)
        np.save(os.path.join(self.tmp_folder, GRAPH_WS_NAME), assignment)
        self.log(f"graph-watershed reassigned {int(drop.sum())} segments")


class GraphConnectedComponentsTask(VolumeSimpleTask):
    """Connected components over the node graph, optionally restricted to edges
    above a merge threshold (reference graph_connected_components.py:25)."""

    task_name = "graph_connected_components"

    def __init__(self, *args, threshold: Optional[float] = None, **kwargs):
        super().__init__(*args, threshold=threshold, **kwargs)

    def run_impl(self) -> None:
        from .costs import COSTS_NAME
        from .graph import load_graph

        nodes, edges = load_graph(self.tmp_store())
        use = np.ones(edges.shape[0], dtype=bool)
        if self.threshold is not None:
            weights = np.load(os.path.join(self.tmp_folder, COSTS_NAME))
            use = weights > self.threshold
        uf = UnionFindNp(nodes.size)
        if use.any():
            uf.merge(edges[use, 0], edges[use, 1])
        roots = uf.compress()
        _, comp = np.unique(roots, return_inverse=True)
        assignment = np.stack(
            [nodes, (comp + 1).astype(np.uint64)], axis=1
        )
        np.save(os.path.join(self.tmp_folder, GRAPH_CC_NAME), assignment)
        n_comp = int(comp.max()) + 1 if comp.size else 0
        self.log(f"graph CC: {nodes.size} nodes → {n_comp} components")
