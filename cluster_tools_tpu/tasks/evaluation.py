"""Distributed evaluation: Rand / VoI vs ground truth.

Reference evaluation/{measures,object_vi}.py (SURVEY.md §2.7) — the parity
metric of BASELINE.md.  Pipeline: per-block contingency (block_node_labels
machinery) → merged table → metric computation.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

import numpy as np

from ..ops.evaluation import object_vi, rand_scores, vi_scores
from .base import VolumeSimpleTask
from .node_labels import OVERLAPS_MERGED_NAME

MEASURES_NAME = "evaluation_measures.json"
OBJECT_VI_NAME = "object_vi.json"


class MeasuresTask(VolumeSimpleTask):
    """RI / adapted-Rand / VoI from the merged overlap table
    (reference measures.py:27)."""

    task_name = "measures"

    def run_impl(self) -> None:
        with np.load(os.path.join(self.tmp_folder, OVERLAPS_MERGED_NAME)) as f:
            ia, ib, counts = f["ids_a"], f["ids_b"], f["counts"]
        # ignore gt label 0 (unlabeled), the reference convention
        keep = ib != 0
        ia, ib, counts = ia[keep], ib[keep], counts[keep]
        out = rand_scores(ia, ib, counts)
        out.update(vi_scores(ia, ib, counts))
        path = os.path.join(self.tmp_folder, MEASURES_NAME)
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        self.log(f"measures: {out}")


def load_measures(tmp_folder: str) -> Dict[str, float]:
    with open(os.path.join(tmp_folder, MEASURES_NAME)) as f:
        return json.load(f)


class ObjectViTask(VolumeSimpleTask):
    """Per-ground-truth-object VI scores from the merged overlap table
    (reference object_vi.py:26)."""

    task_name = "object_vi"

    def run_impl(self) -> None:
        from ..ops.evaluation import object_vi_from_contingency

        with np.load(os.path.join(self.tmp_folder, OVERLAPS_MERGED_NAME)) as f:
            ia, ib, counts = f["ids_a"], f["ids_b"], f["counts"]
        keep = ib != 0
        scores = object_vi_from_contingency(ia[keep], ib[keep], counts[keep])
        path = os.path.join(self.tmp_folder, OBJECT_VI_NAME)
        with open(path, "w") as f:
            json.dump(
                {int(k): [float(v[0]), float(v[1])] for k, v in scores.items()},
                f, indent=2,
            )
        self.log(f"object VI scores for {len(scores)} gt objects")


def load_object_vi(tmp_folder: str) -> Dict[int, Any]:
    with open(os.path.join(tmp_folder, OBJECT_VI_NAME)) as f:
        return {int(k): v for k, v in json.load(f).items()}
