"""Write task: apply a node-label assignment to a label volume block-wise.

Reference write.py:29-206 (`_apply_node_labels`, `_write_block_with_offsets`).
Assignment modes (sniffed from the array on disk):
  * dense 1d array   — ``out = assignment[labels]`` (labels must be dense ids)
  * 2-column table   — (old_id, new_id) rows, looked up via searchsorted; ids
                       absent from the table map to 0 (``table_default="zero"``,
                       relabel/filter semantics) or pass through unchanged
                       (``table_default="identity"``, stitching semantics)

Optional per-block offsets (from merge_offsets) are added to non-zero labels
before the lookup.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ..ops.relabel import apply_assignment_table_np
from ..utils.blocking import Blocking
from .base import VolumeTask


class WriteTask(VolumeTask):
    task_name = "write"
    output_dtype = "uint64"

    def __init__(
        self,
        *args,
        assignment_path: str = None,
        offsets_path: Optional[str] = None,
        identifier: Optional[str] = None,
        table_default: str = "zero",
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.assignment_path = assignment_path
        self.offsets_path = offsets_path
        self._identifier = identifier
        if table_default not in ("zero", "identity"):
            raise ValueError(
                f"table_default must be 'zero' or 'identity', got {table_default!r}"
            )
        self.table_default = table_default

    @property
    def identifier(self) -> str:
        # distinguish multiple Write instances in one workflow
        # (reference write.py:128-130 per-identifier log names)
        return f"{self.task_name}_{self._identifier}" if self._identifier else self.task_name

    def _load_assignment(self) -> np.ndarray:
        if self.assignment_path.endswith(".npz"):
            with np.load(self.assignment_path) as f:
                return f[f.files[0]]
        return np.load(self.assignment_path)

    def process_block(self, block_id: int, blocking: Blocking, config: Dict[str, Any]):
        in_ds = self.input_ds()
        out_ds = self.output_ds()
        assignment = self._load_assignment()
        bb = blocking.block(block_id).slicing
        labels = in_ds[bb].astype(np.int64)
        if self.offsets_path is not None:
            with np.load(self.offsets_path) as f:
                offsets = f["offsets"]
            labels = np.where(labels > 0, labels + offsets[block_id], 0)
        if assignment.ndim == 1:
            out = assignment[labels]
        else:
            out = apply_assignment_table_np(
                labels.astype(np.uint64), assignment,
                default_zero=(self.table_default == "zero"),
            )
        out_ds[bb] = out.astype(np.uint64)
