"""Task library: block-parallel tasks over chunked volumes.

One module per component family, mirroring the reference's component inventory
(SURVEY.md §2) re-expressed on the TPU runtime: per-block compute is a batched
jit program, merges are host reductions (or device collectives), and every task
records per-block completion for retry/resume.
"""

from .base import VolumeTask
from .threshold import ThresholdTask
from .thresholded_components import (
    BlockComponentsTask,
    ShardedComponentsTask,
    MergeOffsetsTask,
    BlockFacesTask,
    MergeAssignmentsTask,
)
from .write import WriteTask
from .relabel import FindUniquesTask, FindLabelingTask, MergeUniquesTask
from .copy_volume import CopyVolumeTask
from .transformations import LinearTransformationTask
from .masking import BlocksFromMaskTask, MinfilterTask
from .downscaling import DownscalingTask, UpscalingTask, ScaleToBoundariesTask
from .affinities import (
    InsertAffinitiesTask,
    EmbeddingDistancesTask,
    GradientsTask,
)
from .ilastik import (
    IlastikPredictionTask,
    MergePredictionsTask,
    StackPredictionsTask,
    WriteCarvingTask,
)
from .inference import InferenceTask
from .multiscale_inference import MultiscaleInferenceTask
from .learning import (
    EdgeLabelsTask,
    LearnRFTask,
    PredictEdgeProbabilitiesTask,
)
from .region_features import (
    RegionFeaturesTask,
    MergeRegionFeaturesTask,
    ImageFilterTask,
)
from .skeletons import (
    SkeletonizeTask,
    UpsampleSkeletonsTask,
    SkeletonEvaluationTask,
)
from .distances import ObjectDistancesTask, MergeObjectDistancesTask
from .meshes import ComputeMeshesTask
from .morphology import (
    BlockMorphologyTask,
    MergeMorphologyTask,
    RegionCentersTask,
)
from .label_multisets import CreateMultisetTask, DownscaleMultisetTask
from .paintera import UniqueBlockLabelsTask, LabelBlockMappingTask
from .postprocess import (
    SizeFilterTask,
    IdFilterTask,
    GraphWatershedAssignmentsTask,
    GraphConnectedComponentsTask,
    OrphanAssignmentsTask,
    FilterBlocksTask,
    BackgroundSizeFilterTask,
    FillingSizeFilterTask,
)
from .stitching import (
    StitchFacesTask,
    StitchAssignmentsTask,
    SimpleStitchEdgesTask,
    SimpleStitchAssignmentsTask,
    StitchingMulticutTask,
)
from .mws import MwsBlocksTask, TwoPassMwsTask
from .debugging import CheckComponentsTask, CheckSubGraphsTask
from .evaluation import MeasuresTask, ObjectViTask
from .multicut import (
    SolveSubproblemsTask,
    ReduceProblemTask,
    ReducedAssignmentsTask,
    SolveGlobalTask,
    SubSolutionsTask,
)

__all__ = [
    "VolumeTask",
    "ThresholdTask",
    "BlockComponentsTask",
    "ShardedComponentsTask",
    "MergeOffsetsTask",
    "BlockFacesTask",
    "MergeAssignmentsTask",
    "WriteTask",
    "FindUniquesTask",
    "FindLabelingTask",
    "MergeUniquesTask",
    "CopyVolumeTask",
    "LinearTransformationTask",
    "BlocksFromMaskTask",
    "MinfilterTask",
    "DownscalingTask",
    "UpscalingTask",
    "ScaleToBoundariesTask",
    "InsertAffinitiesTask",
    "EmbeddingDistancesTask",
    "GradientsTask",
    "InferenceTask",
    "MultiscaleInferenceTask",
    "EdgeLabelsTask",
    "LearnRFTask",
    "PredictEdgeProbabilitiesTask",
    "RegionFeaturesTask",
    "MergeRegionFeaturesTask",
    "ImageFilterTask",
    "SkeletonizeTask",
    "UpsampleSkeletonsTask",
    "SkeletonEvaluationTask",
    "ObjectDistancesTask",
    "MergeObjectDistancesTask",
    "ComputeMeshesTask",
    "BlockMorphologyTask",
    "MergeMorphologyTask",
    "RegionCentersTask",
    "IlastikPredictionTask",
    "MergePredictionsTask",
    "StackPredictionsTask",
    "WriteCarvingTask",
    "CreateMultisetTask",
    "DownscaleMultisetTask",
    "UniqueBlockLabelsTask",
    "LabelBlockMappingTask",
    "SizeFilterTask",
    "IdFilterTask",
    "GraphWatershedAssignmentsTask",
    "GraphConnectedComponentsTask",
    "OrphanAssignmentsTask",
    "FilterBlocksTask",
    "BackgroundSizeFilterTask",
    "FillingSizeFilterTask",
    "StitchFacesTask",
    "StitchAssignmentsTask",
    "SimpleStitchEdgesTask",
    "SimpleStitchAssignmentsTask",
    "StitchingMulticutTask",
    "MwsBlocksTask",
    "TwoPassMwsTask",
    "CheckComponentsTask",
    "CheckSubGraphsTask",
    "MeasuresTask",
    "ObjectViTask",
    "SolveSubproblemsTask",
    "ReduceProblemTask",
    "ReducedAssignmentsTask",
    "SolveGlobalTask",
    "SubSolutionsTask",
]
