"""Predictor/preprocessor registry for block-wise NN inference.

Reference inference/frameworks.py:38-166: thread-locked pytorch predictors with
optional TTA and mixed precision, a preprocessor doing zero-mean/unit-variance
or [0,1] casting, looked up by framework name.

Here the first-class framework is ``jax``: the checkpoint is a flax model
(models/unet.py) and predict is one jit program per block geometry — the
batch rides the MXU, no thread lock needed (dispatch is async).  ``pytorch``
wraps a TorchScript/torch.nn checkpoint on host as the compatibility path for
foreign models (torch-cpu is in the image); ``tensorflow`` raises, as in the
reference (frameworks.py:150-151 is a stub).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Any, Callable, Dict, Optional

import numpy as np


# -- preprocessing ------------------------------------------------------------


def preprocess_zero_mean_unit_variance(data: np.ndarray, eps: float = 1e-6):
    data = data.astype("float32")
    return (data - data.mean()) / (data.std() + eps)


def preprocess_to_01(data: np.ndarray, eps: float = 1e-6):
    data = data.astype("float32")
    lo, hi = data.min(), data.max()
    return (data - lo) / max(hi - lo, eps)


PREPROCESSORS = {
    "zero_mean_unit_variance": preprocess_zero_mean_unit_variance,
    "to_01": preprocess_to_01,
    "none": lambda data: data.astype("float32"),
}


def get_preprocessor(name: str = "zero_mean_unit_variance") -> Callable:
    return PREPROCESSORS[name]


# -- model surgery hooks (reference inference/prep_model.py:9-23) -------------


def prep_add_sigmoid(apply_fn):
    import jax

    def wrapped(params, x):
        return jax.nn.sigmoid(apply_fn(params, x))

    return wrapped


PREP_MODELS = {"add_sigmoid": prep_add_sigmoid, None: lambda f: f}


# torch-side surgery: operates on nn.Module objects (the reference's hooks
# mutate the module graph, prep_model.py:9-23); the jax hooks above wrap the
# apply function instead — same contract, idiomatic to each framework
def _torch_extract_unet(model):
    return model.unet


def _torch_add_sigmoid(model):
    import torch.nn as nn

    wrapped = nn.Sequential(model, nn.Sigmoid())
    # keep channel introspection working through the wrapper (only when the
    # wrapped model exposes it — don't materialize a None attribute)
    if hasattr(model, "out_channels"):
        wrapped.out_channels = model.out_channels
    return wrapped


TORCH_PREP_MODELS = {
    "extract_unet": _torch_extract_unet,
    "add_sigmoid": _torch_add_sigmoid,
    None: lambda m: m,
}


# -- test-time augmentation ---------------------------------------------------


def mirror_flip_sets(dim: int = 3):
    """All axis-flip subsets over the trailing ``dim`` spatial axes:
    8 variants for 3d, 4 for 2d (per-slice)."""
    if dim not in (2, 3):
        raise ValueError(f"augmentation_dim must be 2 or 3, got {dim}")
    axes = (-2, -1) if dim == 2 else (-3, -2, -1)
    sets = [()]
    for ax in axes:
        sets += [s + (ax,) for s in sets]
    return sets


AUGMENTATION_MODES = (None, "all")


def mirror_tta(forward: Callable, dim: int = 3) -> Callable:
    """Mirror test-time augmentation (the role of neurofire's
    TestTimeAugmenter in the reference, frameworks.py:103-131): run the
    forward under every spatial mirror, invert the mirror on the output,
    average.  Assumes flip-equivariant output channels (boundary/membrane
    maps); offset-channel outputs (affinities) would need channel remapping
    and are not supported here.

    All mirror variants are stacked along the batch axis so the (batched)
    forward runs as ONE dispatch — on the jax path that is one
    host→device transfer and one jit call instead of eight."""

    def augmented(data: np.ndarray) -> np.ndarray:
        sets = mirror_flip_sets(dim)
        b = data.shape[0]
        stack = np.concatenate(
            [
                np.ascontiguousarray(np.flip(data, axes)) if axes else data
                for axes in sets
            ],
            axis=0,
        )
        out = forward(stack)
        acc = np.zeros_like(out[:b], dtype="float32")
        for i, axes in enumerate(sets):
            part = out[i * b:(i + 1) * b]
            acc += np.flip(part, axes) if axes else part
        return acc / len(sets)

    return augmented


def build_augmented_forward(
    forward: Callable,
    augmentation_mode: Optional[str],
    augmentation_dim,
) -> Callable:
    """TTA seam shared by the predictors: validates the mode instead of
    truthiness-enabling on arbitrary strings."""
    if augmentation_mode not in AUGMENTATION_MODES:
        raise ValueError(
            f"augmentation_mode must be one of {AUGMENTATION_MODES}, "
            f"got {augmentation_mode!r}"
        )
    if augmentation_mode is None:
        return forward
    return mirror_tta(forward, dim=int(augmentation_dim or 3))


# -- predictors ---------------------------------------------------------------


class BasePredictor:
    """Shared predictor shell: batch-shape normalization, the validated TTA
    seam around ``_forward_raw``, and the final halo crop (the reference
    predictors crop the halo too, frameworks.py:87-101 via their ``crop``
    wrapper).  Subclasses implement ``_forward_raw([B,C,z,y,x]) →
    [B,C_out,z,y,x]``."""

    def _init_base(self, halo, augmentation_mode, augmentation_dim):
        self.halo = list(halo)
        self._forward = build_augmented_forward(
            self._forward_raw, augmentation_mode, augmentation_dim
        )

    def _forward_raw(self, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, data: np.ndarray) -> np.ndarray:
        squeeze_batch = data.ndim in (3, 4)
        if data.ndim == 3:
            data = data[None, None]
        elif data.ndim == 4:
            data = data[None]
        out = self._forward(np.asarray(data))
        ha = self.halo
        if any(ha):
            crop = tuple(
                slice(h, s - h if h else None)
                for h, s in zip(ha, out.shape[-3:])
            )
            out = out[(Ellipsis,) + crop]
        return out[0] if squeeze_batch else out


class JaxPredictor(BasePredictor):
    """Batched jit forward of a flax checkpoint.

    Input: [B, C?, z, y, x] host array → output [B, C_out, z, y, x] with the
    halo already cropped.
    """

    def __init__(self, checkpoint_path: str, halo, prep_model: Optional[str] = None,
                 config: Optional[dict] = None,
                 augmentation_mode: Optional[str] = None,
                 augmentation_dim: int = 3, **_unused):
        import jax

        from ..models.unet import load_checkpoint

        self.model, self.params = load_checkpoint(checkpoint_path)
        self.config = config  # carries target/devices for batch sharding
        apply_fn = PREP_MODELS[prep_model](
            lambda params, x: self.model.apply(params, x)
        )
        self._apply = jax.jit(apply_fn)
        self._init_base(halo, augmentation_mode, augmentation_dim)

    def _forward_raw(self, data: np.ndarray) -> np.ndarray:
        from ..parallel.mesh import put_sharded

        # batch data-parallel over the device mesh (padded to divide)
        xb, n = put_sharded(np.asarray(data), self.config)
        return np.asarray(self._apply(self.params, xb))[:n]


def _import_dotted(path: str):
    """Resolve ``package.module.Attr`` to the attribute object."""
    import importlib

    mod_name, _, attr = path.rpartition(".")
    if not mod_name:
        raise ValueError(f"model_class must be a dotted path, got {path!r}")
    return getattr(importlib.import_module(mod_name), attr)


def _load_torch_model(checkpoint_path, use_best, model_class, model_kwargs):
    """Every checkpoint flavor the reference stack produces, one loader:

      * TorchScript archive → ``torch.jit.load`` (no class import needed);
      * pickled eager ``nn.Module`` → ``torch.load`` (reference
        PytorchPredicter, frameworks.py:76: ``torch.load(model_path)``);
      * state-dict checkpoint (bare state dict or a dict nesting it under
        ``state_dict``/``model_state_dict``/``model``/``_model``) →
        construct ``model_class(**model_kwargs)`` and load the weights —
        the loader the reference left as a TODO (frameworks.py:37);
      * inferno ``Trainer`` checkpoint DIRECTORY → pick
        ``Weights/best_checkpoint.pytorch`` (``use_best``) or
        ``Weights/checkpoint.pytorch`` and recurse (reference
        InfernoPredicter, frameworks.py:145 ``Trainer().load(best=...)``).
    """
    import os

    import torch

    if os.path.isdir(checkpoint_path):
        name = "best_checkpoint.pytorch" if use_best else "checkpoint.pytorch"
        for sub in (os.path.join("Weights", name), name):
            p = os.path.join(checkpoint_path, sub)
            if os.path.exists(p):
                return _load_torch_model(p, use_best, model_class, model_kwargs)
        raise FileNotFoundError(
            f"no {name} under inferno checkpoint directory {checkpoint_path}"
        )
    try:
        return torch.jit.load(checkpoint_path, map_location="cpu")
    except RuntimeError:
        pass
    obj = torch.load(checkpoint_path, map_location="cpu", weights_only=False)
    if isinstance(obj, torch.nn.Module):
        return obj
    if isinstance(obj, dict):
        state = obj
        for key in ("state_dict", "model_state_dict", "model", "_model"):
            if key in obj:
                state = obj[key]
                break
        if isinstance(state, torch.nn.Module):  # e.g. {'model': module}
            return state
        if model_class is None:
            raise ValueError(
                f"{checkpoint_path} holds a state dict; pass model_class="
                "'pkg.module.Class' (+ model_kwargs) so the module can be "
                "constructed to receive the weights"
            )
        cls = (
            _import_dotted(model_class)
            if isinstance(model_class, str) else model_class
        )
        model = cls(**(model_kwargs or {}))
        model.load_state_dict(state)
        return model
    raise TypeError(
        f"unsupported torch checkpoint content {type(obj).__name__} "
        f"in {checkpoint_path}"
    )


class PytorchPredictor(BasePredictor):
    """Host torch forward for foreign checkpoints (compat path; the model is
    shared across prefetch threads behind a lock like the reference's,
    frameworks.py:63,88).

    Accepts every reference checkpoint flavor (see ``_load_torch_model``)
    plus ``prep_model`` surgery on the loaded module ('extract_unet',
    'add_sigmoid' — reference prep_model.py:9-23).  ``mixed_precision`` runs
    the forward under bf16 autocast — the host analog of the reference's
    apex O1 mode (frameworks.py:55-57); there is no CUDA in this deployment,
    the MXU path for mixed precision is the jax predictor."""

    def __init__(self, checkpoint_path: str, halo, use_best: bool = True,
                 prep_model: Optional[str] = None,
                 model_class: Optional[str] = None,
                 model_kwargs: Optional[dict] = None,
                 mixed_precision: bool = False,
                 augmentation_mode: Optional[str] = None,
                 augmentation_dim: int = 3, **_unused):
        import torch

        self.torch = torch
        self.model = _load_torch_model(
            checkpoint_path, use_best, model_class, model_kwargs
        )
        if prep_model is not None:
            if prep_model not in TORCH_PREP_MODELS:
                raise ValueError(
                    f"prep_model must be one of "
                    f"{sorted(k for k in TORCH_PREP_MODELS if k)}, "
                    f"got {prep_model!r}"
                )
            if isinstance(self.model, torch.jit.ScriptModule):
                if prep_model == "add_sigmoid":
                    # scripted graphs cannot be rewritten; compose outside
                    self._post = torch.nn.Sigmoid()
                else:
                    raise ValueError(
                        f"prep_model={prep_model!r} cannot rewrite a "
                        "TorchScript archive; apply it before scripting"
                    )
            else:
                self.model = TORCH_PREP_MODELS[prep_model](self.model)
        self.model.eval()
        self.mixed_precision = bool(mixed_precision)
        self.lock = threading.Lock()
        self._init_base(halo, augmentation_mode, augmentation_dim)

    def _forward_raw(self, data: np.ndarray) -> np.ndarray:
        torch = self.torch
        with self.lock, torch.no_grad():
            x = torch.from_numpy(np.ascontiguousarray(data))
            if self.mixed_precision:
                with torch.autocast("cpu", dtype=torch.bfloat16):
                    out = self.model(x)
                out = out.float()
            else:
                out = self.model(x)
            post = getattr(self, "_post", None)
            if post is not None:
                out = post(out)
        return out.cpu().numpy()


def _tensorflow_stub(*args, **kwargs):
    raise NotImplementedError(
        "tensorflow inference is not implemented (stub in the reference too, "
        "frameworks.py:150-151)"
    )


PREDICTORS: Dict[str, Any] = {
    "jax": JaxPredictor,
    "pytorch": PytorchPredictor,
    "inferno": PytorchPredictor,  # inferno trainers export torch models
    "tensorflow": _tensorflow_stub,
}


def get_predictor(framework: str) -> Callable:
    return PREDICTORS[framework]
