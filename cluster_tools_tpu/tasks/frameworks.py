"""Predictor/preprocessor registry for block-wise NN inference.

Reference inference/frameworks.py:38-166: thread-locked pytorch predictors with
optional TTA and mixed precision, a preprocessor doing zero-mean/unit-variance
or [0,1] casting, looked up by framework name.

Here the first-class framework is ``jax``: the checkpoint is a flax model
(models/unet.py) and predict is one jit program per block geometry — the
batch rides the MXU, no thread lock needed (dispatch is async).  ``pytorch``
wraps a TorchScript/torch.nn checkpoint on host as the compatibility path for
foreign models (torch-cpu is in the image); ``tensorflow`` raises, as in the
reference (frameworks.py:150-151 is a stub).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Any, Callable, Dict, Optional

import numpy as np


# -- preprocessing ------------------------------------------------------------


def preprocess_zero_mean_unit_variance(data: np.ndarray, eps: float = 1e-6):
    data = data.astype("float32")
    return (data - data.mean()) / (data.std() + eps)


def preprocess_to_01(data: np.ndarray, eps: float = 1e-6):
    data = data.astype("float32")
    lo, hi = data.min(), data.max()
    return (data - lo) / max(hi - lo, eps)


PREPROCESSORS = {
    "zero_mean_unit_variance": preprocess_zero_mean_unit_variance,
    "to_01": preprocess_to_01,
    "none": lambda data: data.astype("float32"),
}


def get_preprocessor(name: str = "zero_mean_unit_variance") -> Callable:
    return PREPROCESSORS[name]


# -- model surgery hooks (reference inference/prep_model.py:9-23) -------------


def prep_add_sigmoid(apply_fn):
    import jax

    def wrapped(params, x):
        return jax.nn.sigmoid(apply_fn(params, x))

    return wrapped


PREP_MODELS = {"add_sigmoid": prep_add_sigmoid, None: lambda f: f}


# -- predictors ---------------------------------------------------------------


class JaxPredictor:
    """Batched jit forward of a flax checkpoint.

    Input: [B, C?, z, y, x] host array → output [B, C_out, z, y, x] with the
    halo already cropped (the reference predictors crop the halo too,
    frameworks.py:87-101 via their `crop` wrapper).
    """

    def __init__(self, checkpoint_path: str, halo, prep_model: Optional[str] = None,
                 config: Optional[dict] = None, **_unused):
        import jax

        from ..models.unet import load_checkpoint

        self.model, self.params = load_checkpoint(checkpoint_path)
        self.halo = list(halo)
        self.config = config  # carries target/devices for batch sharding
        apply_fn = PREP_MODELS[prep_model](
            lambda params, x: self.model.apply(params, x)
        )
        self._apply = jax.jit(apply_fn)

    def __call__(self, data: np.ndarray) -> np.ndarray:
        from ..parallel.mesh import put_sharded

        squeeze_batch = data.ndim in (3, 4)
        if data.ndim == 3:
            data = data[None, None]
        elif data.ndim == 4:
            data = data[None]
        # batch data-parallel over the device mesh (padded to divide)
        xb, n = put_sharded(np.asarray(data), self.config)
        out = np.asarray(self._apply(self.params, xb))[:n]
        ha = self.halo
        if any(ha):
            crop = tuple(
                slice(h, s - h if h else None)
                for h, s in zip(ha, out.shape[-3:])
            )
            out = out[(Ellipsis,) + crop]
        return out[0] if squeeze_batch else out


class PytorchPredictor:
    """Host torch forward for foreign checkpoints (compat path; the model is
    shared across prefetch threads behind a lock like the reference's,
    frameworks.py:63,88)."""

    def __init__(self, checkpoint_path: str, halo, use_best: bool = True,
                 **_unused):
        import torch

        self.torch = torch
        try:
            self.model = torch.jit.load(checkpoint_path, map_location="cpu")
        except RuntimeError:
            self.model = torch.load(
                checkpoint_path, map_location="cpu", weights_only=False
            )
        self.model.eval()
        self.halo = list(halo)
        self.lock = threading.Lock()

    def __call__(self, data: np.ndarray) -> np.ndarray:
        torch = self.torch
        squeeze_batch = data.ndim in (3, 4)
        if data.ndim == 3:
            data = data[None, None]
        elif data.ndim == 4:
            data = data[None]
        with self.lock, torch.no_grad():
            out = self.model(torch.from_numpy(np.ascontiguousarray(data)))
        out = out.cpu().numpy()
        ha = self.halo
        if any(ha):
            crop = tuple(
                slice(h, s - h if h else None)
                for h, s in zip(ha, out.shape[-3:])
            )
            out = out[(Ellipsis,) + crop]
        return out[0] if squeeze_batch else out


def _tensorflow_stub(*args, **kwargs):
    raise NotImplementedError(
        "tensorflow inference is not implemented (stub in the reference too, "
        "frameworks.py:150-151)"
    )


PREDICTORS: Dict[str, Any] = {
    "jax": JaxPredictor,
    "pytorch": PytorchPredictor,
    "inferno": PytorchPredictor,  # inferno trainers export torch models
    "tensorflow": _tensorflow_stub,
}


def get_predictor(framework: str) -> Callable:
    return PREDICTORS[framework]
