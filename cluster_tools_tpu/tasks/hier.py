"""ctt-hier tasks: build the merge hierarchy once, re-cut it in milliseconds.

Pipeline shape mirrors the thresholded-components stack (SURVEY.md §3.4),
with the merge TABLE carried beside the labels:

  1. hierarchy_blocks  — per block, ONE fused device program: the
                         threshold → DT-seed → CC → watershed-flood chain
                         (``ops.watershed.dt_watershed``) plus the block's
                         full-adjacency ``(a, b, saddle)`` merge table
                         (``ops.hier.block_merge_table``) over the flood's
                         working input.  Writes block-LOCAL labels, per-
                         block max ids, and the reduced in-block table.
  2. hierarchy_offsets — exclusive prefix sum of max ids → global id
                         offsets (the merge_offsets idiom).
  3. hierarchy_faces   — per inter-block face: label pairs + saddles over
                         the 1-voxel boundary planes (the block-grain
                         analog of the sharded boundary-plane stitching,
                         parallel/sharded.py), in GLOBAL ids.
  4. hierarchy_build   — concat in-block (+offsets) and face tables,
                         reduce to per-pair min saddle, sort by saddle,
                         persist the hierarchy artifact npz beside the
                         labels volume + the identity assignment for step 5.
  5. write             — the existing WriteTask applies offsets (+identity
                         assignment): the labels volume becomes GLOBAL ids,
                         which is exactly what a re-cut gathers through.

Steps 2 and 3 are *covered* when the workflow's fused chain runs
(ctt-stream): ``hierarchy_blocks`` carries max ids and boundary
label/height planes slab-by-slab and finalizes the offsets npz + face
tables from carry — the labels volume is never re-read for stitching.

:class:`ResegmentTask` is the serve-side consumer: load the artifact,
threshold the saddle column, one value-space union-find pass
(``ops.hier.cut_table``), then gather every labels block batch through
the relabel table — block reads ride the warm ctt-hbm DeviceBufferCache,
so a threshold sweep on a serve daemon re-reads and re-uploads nothing.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import metrics as obs_metrics
from ..ops import hier as hier_ops
from ..ops import watershed as ws_ops
from ..parallel.dispatch import BlockBatch, read_block_batch, write_block_batch
from ..runtime import hbm
from ..utils import store
from ..utils.blocking import Blocking
from .base import (
    VolumeSimpleTask,
    VolumeTask,
    merge_threads,
    read_ragged_chunks,
    read_threads,
    resolve_n_blocks,
)
from .watershed import _normalize_host

HIER_MAX_IDS_KEY = "hier/max_ids"
HIER_PAIRS_KEY = "hier/pairs"            # per block: (k, 2) int64, flattened
HIER_SADDLES_KEY = "hier/saddles"        # per block: (k,) float32
HIER_FACE_PAIRS_KEY = "hier/face_pairs"  # per block: GLOBAL-id pairs
HIER_FACE_SADDLES_KEY = "hier/face_saddles"
HIER_OFFSETS_NAME = "hier_offsets.npz"
HIER_ASSIGNMENTS_NAME = "hier_assignments.npy"


def default_hierarchy_path(output_path: str, output_key: str) -> str:
    """The artifact's default home: beside the labels volume inside its
    container directory (``<output_path>/<output_key>_hierarchy.npz``)."""
    return os.path.join(output_path, f"{output_key}_hierarchy.npz")


def load_hier_offsets(tmp_folder: str):
    with np.load(os.path.join(tmp_folder, HIER_OFFSETS_NAME)) as f:
        return f["offsets"], int(f["n_labels"])


def _working_heights(raw: np.ndarray, config) -> np.ndarray:
    """The flood's working input as the saddle height field: normalize by
    dtype range, optionally invert — a PER-VOXEL transform of the stored
    volume, so host (face stitching) and device (in-block table) land on
    bit-identical values and the field is globally consistent across
    blocks (a per-block normalization would make face saddles depend on
    which side measured them)."""
    x = _normalize_host(np.asarray(raw))
    if config.get("invert_inputs", False):
        x = 1.0 - x
    return x


@lru_cache(maxsize=16)
def _hier_block_kernel(params_key):
    """One jitted program per config: the fused DT-watershed
    (threshold → DT → seeds → hmap → flood → size filter, exactly the
    WatershedTask kernel) PLUS the block's full-adjacency merge table
    over the working input, vmapped over the stacked block batch.  The
    flood rides ``seeded_watershed``'s own dispatch (tile warm start,
    sweep/Pallas mode pins), so hierarchy labels are bit-identical to a
    plain watershed run of the same config."""
    params = dict(params_key)
    invert = bool(params.get("invert_input", False))
    kernel = partial(ws_ops.dt_watershed, **params)

    def one(x, v):
        lab, _ = kernel(x, valid=v)
        h = 1.0 - x if invert else x  # the flood's working height field
        a, b, s = hier_ops.block_merge_table(lab, h)
        # boundary height planes per axis (first, last): the fused-chain
        # carry stitches block faces from these without re-reading raw
        hplanes = []
        for axis in range(x.ndim):
            hplanes.append(jnp.stack(
                [jnp.take(h, 0, axis=axis),
                 jnp.take(h, x.shape[axis] - 1, axis=axis)]
            ))
        return lab, a, b, s, tuple(hplanes)

    return jax.jit(jax.vmap(one))


class HierarchyBlocksTask(VolumeTask):
    """Step 1: per-block flood + full-adjacency merge table (one fused
    dispatch per block batch).  Labels are block-local consecutive ids
    (offsets applied by the write step); the in-block table is reduced to
    per-pair min saddles host-side and stored as ragged chunks."""

    task_name = "hierarchy_blocks"
    output_dtype = "uint64"
    # ctt-stream: single-member fused chain head — carries max ids +
    # boundary planes so offsets/faces are produced from carry, never by
    # re-reading the labels volume
    fusable = True

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {
                "threshold": 0.5,
                "apply_dt_2d": True,
                "apply_ws_2d": True,
                "sigma_seeds": 2.0,
                "sigma_weights": 2.0,
                "alpha": 0.8,
                "size_filter": 25,
                "invert_inputs": False,
                "non_maximum_suppression": False,
            }
        )
        return conf

    @staticmethod
    def _kernel_params(config) -> Dict[str, Any]:
        return dict(
            threshold=float(config["threshold"]),
            apply_dt_2d=bool(config.get("apply_dt_2d", True)),
            apply_ws_2d=bool(config.get("apply_ws_2d", True)),
            sigma_seeds=float(config.get("sigma_seeds", 2.0)),
            sigma_weights=float(config.get("sigma_weights", 2.0)),
            alpha=float(config.get("alpha", 0.8)),
            size_filter=int(config.get("size_filter", 25)),
            invert_input=bool(config.get("invert_inputs", False)),
            non_maximum_suppression=bool(
                config.get("non_maximum_suppression", False)
            ),
        )

    # -- split batch protocol ------------------------------------------------

    def read_batch(self, block_ids: List[int], blocking: Blocking, config):
        return read_block_batch(
            self.input_ds(), blocking, block_ids, dtype="float32",
            n_threads=read_threads(config),
            device_source=(self.input_path, self.input_key,
                           ("hier-read",), config),
        )

    def upload_batch(self, batch, blocking: Blocking, config):
        hbm.batch_device(batch, config)
        return batch

    def stack_payloads(self, payloads, blocking: Blocking, config):
        return hbm.stack_block_batches(payloads, config)

    def unstack_results(self, result, counts, blocking: Blocking, config):
        batch, labels, tables, hplanes = result
        hps, off = [], 0
        for c in counts:
            # per-axis plane shapes differ, so hplanes is a tuple of
            # per-axis [B, 2, *plane] arrays sliced along the batch axis
            hps.append(tuple(arr[off: off + c] for arr in hplanes))
            off += c
        return [
            (b, lab, tab, hp)
            for b, lab, tab, hp in zip(
                hbm.split_block_batch(batch, counts),
                hbm.split_stacked(labels, counts),
                hbm.split_stacked(tables, counts),
                hps,
            )
        ]

    def compute_batch(self, batch, blocking: Blocking, config):
        db = hbm.batch_device(batch, config)
        n = db.n
        kernel = _hier_block_kernel(
            tuple(sorted(self._kernel_params(config).items()))
        )
        valid = _valid_masks(batch, blocking)
        vb, _ = _put(valid, config)
        lab, a, b, s, hplanes = kernel(db.arrays[0], vb)
        labels = np.asarray(lab)[:n].astype(np.int64)
        tables = np.stack(
            [np.asarray(a)[:n], np.asarray(b)[:n], np.asarray(s)[:n]],
            axis=1,
        )  # [B, 3, E] raw columns (float64 holds the ids exactly);
        #    reduced to per-pair min saddles per block in write_batch
        hp = tuple(
            np.asarray(p)[:n] for p in hplanes
        )  # per axis: [B, 2, *plane] (first, last) working-height planes
        return batch, labels, tables, hp

    def write_batch(self, result, blocking: Blocking, config):
        batch, labels, tables, _hplanes = result
        write_block_batch(
            self.output_ds(), batch, labels, cast="uint64",
            n_threads=read_threads(config),
        )
        max_ids = self.tmp_ragged(HIER_MAX_IDS_KEY, blocking.n_blocks, np.int64)
        pairs_ds = self.tmp_ragged(HIER_PAIRS_KEY, blocking.n_blocks, np.int64)
        sad_ds = self.tmp_ragged(
            HIER_SADDLES_KEY, blocking.n_blocks, np.float32
        )
        for i, bid in enumerate(batch.block_ids):
            bh = batch.blocks[i]
            inner = labels[i][bh.inner_local.slicing]
            max_ids.write_chunk((bid,), np.array([inner.max()], np.int64))
            pairs, saddles = hier_ops.reduce_merge_table(
                tables[i][0], tables[i][1], tables[i][2]
            )
            pairs_ds.write_chunk((bid,), pairs.reshape(-1))
            sad_ds.write_chunk((bid,), saddles)
            obs_metrics.inc("hier.tables_built")

    def _run_batch(self, block_ids: List[int], blocking: Blocking, config):
        self.write_batch(
            self.compute_batch(
                self.read_batch(block_ids, blocking, config), blocking, config
            ),
            blocking, config,
        )

    def process_block(self, block_id, blocking, config):
        self._run_batch([block_id], blocking, config)

    def process_block_batch(self, block_ids, blocking, config):
        self._run_batch(block_ids, blocking, config)

    # -- ctt-stream fusion carry (covers offsets + faces) --------------------
    #
    # The carry is the hier analog of BlockComponentsTask's: per-block max
    # ids plus the block's boundary label AND height planes, resolved
    # against the lower neighbor's carried planes as blocks stream through
    # in ascending C-order (one slab of planes in memory).  Heights ride
    # the kernel's own working-input planes, so a warm serve job whose
    # read stage skipped the host read entirely still stitches correctly.

    def fusion_carry_init(self, blocking: Blocking, config):
        return {
            "max_ids": np.zeros(blocking.n_blocks, dtype=np.int64),
            "planes": {},  # (block_id, axis) -> (label_plane, height_plane)
            "faces": {},   # block_id -> axis -> (pairs, saddles) LOCAL ids
        }

    def fusion_carry_update(self, carry, result, block_ids,
                            blocking: Blocking, config):
        if result is None:
            return carry
        batch, labels, _tables, hplanes = result
        for i, bid in enumerate(batch.block_ids):
            bh = batch.blocks[i]
            lab = labels[i][bh.inner_local.slicing]
            carry["max_ids"][bid] = int(lab.max())
            for axis in range(blocking.ndim):
                first, last = hplanes[axis][i]
                size = tuple(e - b for b, e in zip(bh.inner.begin, bh.inner.end))
                crop = tuple(
                    slice(0, s) for d, s in enumerate(size) if d != axis
                )
                if blocking.neighbor_id(bid, axis, lower=False) is not None:
                    carry["planes"][(bid, axis)] = (
                        np.take(lab, lab.shape[axis] - 1, axis=axis),
                        last[crop],
                    )
                nb = blocking.neighbor_id(bid, axis, lower=True)
                if nb is not None:
                    lo_lab, lo_h = carry["planes"].pop((nb, axis))
                    hi_lab = np.take(lab, 0, axis=axis)
                    hi_h = first[crop]
                    pairs, saddles = hier_ops.merge_face_pairs(
                        lo_lab, hi_lab, lo_h, hi_h
                    )
                    if pairs.size:
                        carry["faces"].setdefault(nb, {})[axis] = (
                            pairs, saddles
                        )
        return carry

    def fusion_carry_nbytes(self, carry) -> int:
        n = carry["max_ids"].nbytes
        n += sum(
            la.nbytes + h.nbytes for la, h in carry["planes"].values()
        )
        n += sum(
            p.nbytes + s.nbytes
            for per_axis in carry["faces"].values()
            for p, s in per_axis.values()
        )
        return n

    def fusion_finalize(self, carry, blocking: Blocking, config) -> None:
        """Write the offsets npz (HierarchyOffsetsTask's output) and the
        GLOBAL-id face tables (HierarchyFacesTask's chunks) from carry —
        the covered tasks are stamped complete without re-reading one
        voxel of the labels volume."""
        if carry is None:
            return
        max_ids = carry["max_ids"]
        offsets = np.roll(np.cumsum(max_ids), 1)
        offsets[0] = 0
        np.savez(
            os.path.join(self.tmp_folder, HIER_OFFSETS_NAME),
            offsets=offsets,
            n_labels=np.int64(max_ids.sum()),
        )
        fp = self.tmp_ragged(
            HIER_FACE_PAIRS_KEY, blocking.n_blocks, np.int64
        )
        fs = self.tmp_ragged(
            HIER_FACE_SADDLES_KEY, blocking.n_blocks, np.float32
        )
        for bid in range(blocking.n_blocks):
            parts_p, parts_s = [], []
            for axis, ngb_id, _face in blocking.iterate_faces(bid, halo=1):
                got = carry["faces"].get(bid, {}).get(axis)
                if got is None:
                    continue
                pairs, saddles = got
                glob = pairs + np.array(
                    [[offsets[bid], offsets[ngb_id]]], np.int64
                )
                parts_p.append(glob)
                parts_s.append(saddles)
            if parts_p:
                pairs = np.concatenate(parts_p, axis=0)
                saddles = np.concatenate(parts_s)
            else:
                pairs = np.zeros((0, 2), np.int64)
                saddles = np.zeros((0,), np.float32)
            fp.write_chunk((bid,), pairs.reshape(-1))
            fs.write_chunk((bid,), saddles)


def _put(arr: np.ndarray, config):
    from ..parallel.mesh import put_sharded

    return put_sharded(arr, config)


def _valid_masks(batch: BlockBatch, blocking: Blocking) -> np.ndarray:
    """Boolean valid masks of a (possibly edge-clipped) halo-less block
    batch, built from geometry alone — a warm device-cache probe hit
    (``batch.data is None``) must not force a host read just for masks."""
    full = tuple(blocking.block_shape)
    out = np.zeros((len(batch.blocks),) + full, dtype=bool)
    for i, bh in enumerate(batch.blocks):
        size = tuple(e - b for b, e in zip(bh.outer.begin, bh.outer.end))
        out[i][tuple(slice(0, s) for s in size)] = True
    return out


class HierarchyOffsetsTask(VolumeSimpleTask):
    """Step 2: exclusive prefix sum of per-block max ids (the
    merge_offsets idiom over the hier scratch keys)."""

    task_name = "hierarchy_offsets"

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 **kwargs):
        super().__init__(*args, input_path=input_path, input_key=input_key,
                         **kwargs)

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(
            self.config_dir, self.input_path, self.input_key
        )
        max_ids_ds = self.tmp_store()[HIER_MAX_IDS_KEY]
        max_ids = np.zeros(n_blocks, dtype=np.int64)
        for bid, chunk in enumerate(
            read_ragged_chunks(max_ids_ds, n_blocks, merge_threads(self))
        ):
            if chunk is not None:
                max_ids[bid] = chunk[0]
        offsets = np.roll(np.cumsum(max_ids), 1)
        offsets[0] = 0
        np.savez(
            os.path.join(self.tmp_folder, HIER_OFFSETS_NAME),
            offsets=offsets,
            n_labels=np.int64(max_ids.sum()),
        )


class HierarchyFacesTask(VolumeTask):
    """Step 3: cross-block hierarchy edges over 1-voxel faces, in GLOBAL
    ids — the labels slab comes from the blocks volume, the saddle
    heights from the raw volume under the same per-voxel transform the
    kernel used (``heights_path/key``)."""

    task_name = "hierarchy_faces"
    output_dtype = None  # writes only scratch ragged chunks

    def __init__(self, *args, heights_path: str = None,
                 heights_key: str = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.heights_path = heights_path
        self.heights_key = heights_key

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"invert_inputs": False})
        return conf

    def process_block(self, block_id: int, blocking: Blocking, config):
        labels_ds = self.input_ds()
        heights_ds = store.file_reader(self.heights_path, "r")[
            self.heights_key
        ]
        offsets, _ = load_hier_offsets(self.tmp_folder)
        parts_p, parts_s = [], []
        for axis, ngb_id, face in blocking.iterate_faces(block_id, halo=1):
            slab = labels_ds[face.slicing].astype(np.int64)
            h_slab = _working_heights(heights_ds[face.slicing], config)
            lo, hi = np.split(slab, 2, axis=axis)
            h_lo, h_hi = np.split(h_slab, 2, axis=axis)
            pairs, saddles = hier_ops.merge_face_pairs(lo, hi, h_lo, h_hi)
            if pairs.size:
                parts_p.append(pairs + np.array(
                    [[offsets[block_id], offsets[ngb_id]]], np.int64
                ))
                parts_s.append(saddles)
        fp = self.tmp_ragged(HIER_FACE_PAIRS_KEY, blocking.n_blocks, np.int64)
        fs = self.tmp_ragged(
            HIER_FACE_SADDLES_KEY, blocking.n_blocks, np.float32
        )
        if parts_p:
            pairs = np.concatenate(parts_p, axis=0)
            saddles = np.concatenate(parts_s)
        else:
            pairs = np.zeros((0, 2), np.int64)
            saddles = np.zeros((0,), np.float32)
        fp.write_chunk((block_id,), pairs.reshape(-1))
        fs.write_chunk((block_id,), saddles)


class BuildHierarchyTask(VolumeSimpleTask):
    """Step 4: globalize + persist.  In-block tables get their block's
    offset, concat with the (already global) face tables, reduce to the
    per-pair minimum saddle, sort by saddle, save the artifact npz beside
    the labels volume plus the identity assignment the write step
    applies."""

    task_name = "hierarchy_build"

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 hierarchy_path: str = None, **kwargs):
        super().__init__(*args, input_path=input_path, input_key=input_key,
                         **kwargs)
        self.hierarchy_path = hierarchy_path

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(
            self.config_dir, self.input_path, self.input_key
        )
        gconf = self.global_config()
        offsets, n_labels = load_hier_offsets(self.tmp_folder)
        tmp = self.tmp_store()
        threads = merge_threads(self)
        pairs_chunks = read_ragged_chunks(
            tmp[HIER_PAIRS_KEY], n_blocks, threads
        )
        sad_chunks = read_ragged_chunks(
            tmp[HIER_SADDLES_KEY], n_blocks, threads
        )
        fp_chunks = read_ragged_chunks(
            tmp[HIER_FACE_PAIRS_KEY], n_blocks, threads
        )
        fs_chunks = read_ragged_chunks(
            tmp[HIER_FACE_SADDLES_KEY], n_blocks, threads
        )
        all_pairs, all_saddles = [], []
        for bid in range(n_blocks):
            p = pairs_chunks[bid]
            if p is not None and p.size:
                all_pairs.append(p.reshape(-1, 2) + offsets[bid])
                all_saddles.append(sad_chunks[bid])
            fpc = fp_chunks[bid]
            if fpc is not None and fpc.size:
                all_pairs.append(fpc.reshape(-1, 2))
                all_saddles.append(fs_chunks[bid])
        if all_pairs:
            pairs = np.concatenate(all_pairs, axis=0)
            saddles = np.concatenate(all_saddles)
            pairs, saddles = hier_ops.reduce_merge_table(
                pairs[:, 0], pairs[:, 1], saddles
            )
        else:
            pairs = np.zeros((0, 2), np.int64)
            saddles = np.zeros((0,), np.float32)
        shape = store.file_reader(self.input_path, "r")[
            self.input_key
        ].shape
        hier_ops.save_hierarchy(
            self.hierarchy_path, pairs, saddles, n_labels,
            shape, gconf["block_shape"],
        )
        # identity assignment: the write step's dense lookup (global id ->
        # global id) — the hierarchy renames nothing at build time
        np.save(
            os.path.join(self.tmp_folder, HIER_ASSIGNMENTS_NAME),
            np.arange(n_labels + 1, dtype=np.uint64),
        )
        obs_metrics.inc("hier.edges", int(pairs.shape[0]))
        self.log(
            f"hierarchy: {n_labels} regions, {pairs.shape[0]} saddle edges "
            f"-> {self.hierarchy_path}"
        )


@jax.jit
def _recut_batch(labels, vals, roots):
    """One gather per block batch: the whole re-segmentation dispatch."""
    return hier_ops.recut_labels(labels, vals, roots)


class ResegmentTask(VolumeTask):
    """Re-segment a hierarchy-built labels volume at one merge threshold:
    load the sorted artifact, select + union-find the edges ≤ threshold
    ONCE (``prepare``), then every block batch is one relabel gather.

    The input labels read carries a ctt-hbm ``device_source``: on a warm
    serve daemon a threshold sweep probes the SAME (volume, blocks,
    dtype) cache lines every job, so after the first job neither host
    reads nor HBM uploads happen — only the gather and the output write.

    ``write_volume: false`` (the interactive-sweep mode) skips the volume
    pass entirely and persists the resolved RELABEL TABLE instead
    (``<output_key>_cut.npz`` beside the hierarchy artifact,
    ``ops.hier.save_cut_table``): a proofreading client applies the table
    to whatever view it holds (``ops.hier.apply_cut_np`` / one device
    gather), so a sweep step costs one searchsorted + one union-find pass
    over the selected edges — milliseconds — while the full-volume gather
    stays one volume-mode job away for the threshold the user commits to.
    """

    task_name = "resegment"
    output_dtype = "uint64"

    # ids at/above this overflow the device gather's int32 — class-level
    # so tests can fake a tiny limit to exercise the host fallback
    INT32_LIMIT = int(np.iinfo(np.int32).max)

    def __init__(self, *args, hierarchy_path: str = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.hierarchy_path = hierarchy_path
        self._cut = None
        self._cut_ready = False
        self._n_labels = 0
        self._host_relabel = False

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"threshold": 0.5, "write_volume": True})
        return conf

    def cut_table_path(self) -> str:
        return os.path.join(
            self.output_path, f"{self.output_key}_cut.npz"
        )

    def get_block_list(self, blocking, gconf):
        tconf = self.get_task_config()
        if not tconf.get("write_volume", True):
            return []  # table mode: no volume pass at all
        return super().get_block_list(blocking, gconf)

    def _resolve_cut(self, art, threshold: float):
        """Pick the cut path from the hierarchy size: device value-space
        union-find (int32 gather) below :attr:`INT32_LIMIT`, host int64
        union-find + numpy gather at/above it — a LOUD downgrade, never a
        silent wrong answer (int32 ids past 2^31 wrap negative)."""
        self._n_labels = int(art["n_labels"])
        self._host_relabel = self._n_labels >= self.INT32_LIMIT
        if self._host_relabel:
            import warnings

            msg = (
                f"hierarchy holds {self._n_labels} regions (>= "
                f"{self.INT32_LIMIT}): int32 device gather would "
                "overflow — downgrading to the HOST relabel path "
                "(int64 numpy gather, no HBM cache)"
            )
            warnings.warn(msg, RuntimeWarning, stacklevel=3)
            self.log(f"resegment: {msg}")
            return hier_ops.cut_table_np(
                art["a"], art["b"], art["saddle"], threshold
            )
        return hier_ops.cut_table(
            art["a"], art["b"], art["saddle"], threshold
        )

    def prepare(self, blocking: Blocking, config) -> None:
        if config.get("write_volume", True):
            super().prepare(blocking, config)  # the output dataset
        art = hier_ops.load_hierarchy(self.hierarchy_path)
        threshold = float(config["threshold"])
        self._cut = self._resolve_cut(art, threshold)
        self._cut_ready = True
        k = int(np.searchsorted(
            art["saddle"], np.float32(threshold), side="right"
        ))
        obs_metrics.inc("hier.cut_edges", k)
        self.log(
            f"resegment @ t={threshold}: {k}/{art['saddle'].size} edges "
            "selected"
        )

    def finalize(self, blocking: Blocking, config, block_ids) -> None:
        if config.get("write_volume", True):
            return
        hier_ops.save_cut_table(
            self.cut_table_path(), float(config["threshold"]),
            self._cut, self._n_labels,
        )

    def _require_cut(self, config):
        # per-block fallback / local target reach compute without the
        # blockwise run() having called prepare on THIS instance state
        if not self._cut_ready:
            art = hier_ops.load_hierarchy(self.hierarchy_path)
            self._cut = self._resolve_cut(art, float(config["threshold"]))
            self._cut_ready = True
        return self._cut

    # -- split batch protocol ------------------------------------------------

    def read_batch(self, block_ids: List[int], blocking: Blocking, config):
        self._require_cut(config)  # mode decided before the read dtype
        if self._host_relabel:
            # int64 ids, no device_source: the host path never uploads
            return read_block_batch(
                self.input_ds(), blocking, block_ids, dtype="int64",
                n_threads=read_threads(config),
            )
        return read_block_batch(
            self.input_ds(), blocking, block_ids, dtype="int32",
            n_threads=read_threads(config),
            device_source=(self.input_path, self.input_key,
                           ("hier-labels",), config),
        )

    def upload_batch(self, batch, blocking: Blocking, config):
        if not self._host_relabel:
            hbm.batch_device(batch, config)
        return batch

    def stack_payloads(self, payloads, blocking: Blocking, config):
        if self._host_relabel:
            if len(payloads) == 1:
                return payloads[0]
            return BlockBatch(
                data=np.concatenate([p.data for p in payloads], axis=0),
                valid=np.concatenate([p.valid for p in payloads], axis=0),
                blocks=[bh for p in payloads for bh in p.blocks],
                block_ids=[i for p in payloads for i in p.block_ids],
            )
        return hbm.stack_block_batches(payloads, config)

    def unstack_results(self, result, counts, blocking: Blocking, config):
        batch, labels = result
        return list(zip(
            hbm.split_block_batch(batch, counts),
            hbm.split_stacked(labels, counts),
        ))

    def compute_batch(self, batch, blocking: Blocking, config):
        import jax.numpy as jnp

        cut = self._require_cut(config)
        if self._host_relabel:
            labels = np.asarray(batch.data, np.int64)
            if cut is None:
                return batch, labels
            vals, roots = cut
            return batch, hier_ops.apply_cut_np(labels, vals, roots)
        db = hbm.batch_device(batch, config)
        labels = db.arrays[0]
        if cut is None:  # identity cut: nothing below the threshold
            return batch, np.asarray(labels)[:db.n]
        vals, roots = cut
        out = _recut_batch(
            labels, jnp.asarray(vals), jnp.asarray(roots)
        )
        return batch, np.asarray(out)[:db.n]

    def write_batch(self, result, blocking: Blocking, config):
        batch, labels = result
        write_block_batch(
            self.output_ds(), batch, labels, cast="uint64",
            n_threads=read_threads(config),
        )

    def _run_batch(self, block_ids: List[int], blocking: Blocking, config):
        self.write_batch(
            self.compute_batch(
                self.read_batch(block_ids, blocking, config), blocking, config
            ),
            blocking, config,
        )

    def process_block(self, block_id, blocking, config):
        self._run_batch([block_id], blocking, config)

    def process_block_batch(self, block_ids, blocking, config):
        self._run_batch(block_ids, blocking, config)
