"""Edge probabilities → multicut costs (reference costs/probs_to_costs.py:22).

Open seam (ctt-hier, ROADMAP item 2 follow-up): the hierarchy artifact
(``ops/hier.py`` — per-region-pair minimum saddles over the flood's
working input) is a natural merge PRIOR for this cost stack: a pair's
saddle is exactly the boundary evidence the RAG feature path recomputes
per edge, already globalized and sorted, so costs could blend
``transform_probabilities_to_costs(saddle)`` for edges present in the
artifact instead of re-reading boundary features for them.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict

import numpy as np

from ..ops.multicut import (
    NODE_LABEL_MODES,
    apply_node_label_costs,
    transform_probabilities_to_costs,
)
from ..utils import store
from .base import VolumeSimpleTask
from .features import FEATURES_KEY

COSTS_NAME = "costs.npy"


def _load_node_label_array(path: str, key=None) -> np.ndarray:
    """Per-node label table from a .npy file or a chunked-store dataset."""
    if path.endswith(".npy"):
        return np.load(path)
    if key is None:
        raise ValueError(
            f"node-label source {path!r} is not a .npy file — chunked-store "
            "sources must be given as a (path, key) pair"
        )
    with store.file_reader(path, "r") as f:
        return f[key][:]


class ProbsToCostsTask(VolumeSimpleTask):
    """Log-odds cost transform with optional node-label overrides.

    ``node_label_dict`` maps an override mode (``ignore`` / ``isolate`` /
    ``ignore_transition``, reference probs_to_costs.py:25-31) to the location
    of a per-node label table: either a ``.npy`` path or ``(path, key)`` into
    a chunked store. Overrides are applied after the cost transform with
    maximally repulsive = 5×min(cost), maximally attractive = 5×max(cost)
    (reference probs_to_costs.py:216-235).
    """

    task_name = "probs_to_costs"

    def __init__(self, *args, **params):
        super().__init__(*args, **params)
        bad = [
            m for m in (getattr(self, "node_label_dict", None) or {})
            if m not in NODE_LABEL_MODES
        ]
        if bad:
            raise ValueError(
                f"invalid node-label modes {bad}, pick from {NODE_LABEL_MODES}"
            )

    @property
    def identifier(self) -> str:
        # RF-probability / node-label-override runs must not be satisfied by
        # a completed plain run in the same tmp_folder — and two override
        # runs with different dicts must not satisfy each other, so the
        # suffix hashes the dict contents
        name = self.task_name
        if getattr(self, "probs_path", None):
            name += "_rf"
        nld = getattr(self, "node_label_dict", None)
        if nld:
            digest = hashlib.sha1(
                json.dumps(
                    {k: list(v) if not isinstance(v, str) else v
                     for k, v in sorted(nld.items())}
                ).encode()
            ).hexdigest()[:10]
            name += f"_nl{digest}"
        return name

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {
                "beta": 0.5,
                "weight_edges": False,
                "weighting_exponent": 1.0,
                "invert_inputs": False,
            }
        )
        return conf

    def run_impl(self) -> None:
        conf = self.get_task_config()
        feats = self.tmp_store()[FEATURES_KEY][:]
        # probabilities: RF predictions when present (costs/predict.py path in
        # the reference EdgeCostsWorkflow), else the mean boundary response
        probs_path = getattr(self, "probs_path", None)
        if probs_path:
            probs = np.load(probs_path)
            if probs.size != feats.shape[0]:
                raise ValueError(
                    f"{probs.size} probabilities vs {feats.shape[0]} edges"
                )
        else:
            probs = feats[:, 0]
        if conf.get("invert_inputs", False):
            probs = 1.0 - probs
        # count is always the LAST column (10-col default layout or the
        # filter bank's 9*G+1 layout — tasks/features.py)
        sizes = feats[:, -1] if conf["weight_edges"] else None
        costs = transform_probabilities_to_costs(
            probs,
            beta=float(conf.get("beta", 0.5)),
            edge_sizes=sizes,
            weighting_exponent=float(conf.get("weighting_exponent", 1.0)),
        )
        node_label_dict = getattr(self, "node_label_dict", None) or {}
        if node_label_dict:
            from .graph import load_graph

            nodes, edges = load_graph(self.tmp_store())
            # bounds fixed once, before any override moves them
            # (reference probs_to_costs.py:219-220).  The reference's bare
            # 5*min / 5*max silently inverts when all costs share a sign
            # (e.g. min > 0 makes "maximally repulsive" attractive) — guard
            # with a magnitude-based bound in the degenerate case.
            scale = 5.0 * max(float(np.abs(costs).max()), 1e-6)
            cmin, cmax = float(costs.min()), float(costs.max())
            max_repulsive = 5.0 * cmin if cmin < 0 else -scale
            max_attractive = 5.0 * cmax if cmax > 0 else scale
            # edges are dense node indices; label tables are indexed by
            # original fragment id
            frag_uv = nodes[edges]
            max_frag_id = int(nodes.max())
            # sorted: application order must match the sorted-items
            # identifier hash, or dicts differing only in insertion order
            # would share a done-marker while behaving differently
            for mode, where in sorted(node_label_dict.items()):
                if isinstance(where, str):
                    labels = _load_node_label_array(where)
                else:
                    labels = _load_node_label_array(*where)
                if labels.size <= max_frag_id:
                    raise ValueError(
                        f"node-label table from {where} has {labels.size} "
                        f"entries but must be indexable by the max fragment "
                        f"id {max_frag_id} (mode={mode})"
                    )
                costs = apply_node_label_costs(
                    costs, labels[frag_uv], mode, max_repulsive, max_attractive
                )
                self.log(f"applied node-label override mode={mode}")
        np.save(os.path.join(self.tmp_folder, COSTS_NAME), costs)
        self.log(f"computed {costs.size} edge costs (beta={conf.get('beta')})")
