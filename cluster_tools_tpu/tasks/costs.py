"""Edge probabilities → multicut costs (reference costs/probs_to_costs.py:22)."""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np

from ..ops.multicut import transform_probabilities_to_costs
from .base import VolumeSimpleTask
from .features import FEATURES_KEY

COSTS_NAME = "costs.npy"


class ProbsToCostsTask(VolumeSimpleTask):
    task_name = "probs_to_costs"

    @property
    def identifier(self) -> str:
        # RF-probability runs must not be satisfied by a completed
        # boundary-mean run in the same tmp_folder
        if getattr(self, "probs_path", None):
            return f"{self.task_name}_rf"
        return self.task_name

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {
                "beta": 0.5,
                "weight_edges": True,
                "weighting_exponent": 1.0,
                "invert_inputs": False,
            }
        )
        return conf

    def run_impl(self) -> None:
        conf = self.get_task_config()
        feats = self.tmp_store()[FEATURES_KEY][:]
        # probabilities: RF predictions when present (costs/predict.py path in
        # the reference EdgeCostsWorkflow), else the mean boundary response
        probs_path = getattr(self, "probs_path", None)
        if probs_path:
            probs = np.load(probs_path)
            if probs.size != feats.shape[0]:
                raise ValueError(
                    f"{probs.size} probabilities vs {feats.shape[0]} edges"
                )
        else:
            probs = feats[:, 0]
        if conf.get("invert_inputs", False):
            probs = 1.0 - probs
        sizes = feats[:, 9] if conf.get("weight_edges", True) else None
        costs = transform_probabilities_to_costs(
            probs,
            beta=float(conf.get("beta", 0.5)),
            edge_sizes=sizes,
            weighting_exponent=float(conf.get("weighting_exponent", 1.0)),
        )
        np.save(os.path.join(self.tmp_folder, COSTS_NAME), costs)
        self.log(f"computed {costs.size} edge costs (beta={conf.get('beta')})")
