"""Hierarchical multicut solve (ICCV'17 domain decomposition).

Reference multicut/{solve_subproblems,reduce_problem,solve_global}.py
(SURVEY.md §3.5): per scale, blocks extract and solve their node-induced
subproblems; cut edges are collected; non-cut edges are union-find-merged and
the graph contracted with accumulated costs; block shape doubles per scale;
the final reduced graph is solved globally and composed back to scale 0.

Scratch layout:
  multicut/s{s}/cut_edges   ragged per (scale-s) block: cut edge ids
  multicut/s{s}.npz         reduced problem: edges, costs, node_labeling
                            (scale-0 dense node → scale-s cluster)
  multicut_assignments.npy  final (label, segment) table for the write task
"""

from __future__ import annotations

import os
from typing import Any, Dict, List

import numpy as np

from ..ops.multicut import contract_edges, solve_multicut
from ..ops.unionfind import UnionFindNp
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, merge_threads, read_ragged_chunks, resolve_n_blocks
from .costs import COSTS_NAME
from .graph import read_block_with_upper_halo, load_graph

ASSIGNMENTS_NAME = "multicut_assignments.npy"


def _scale_problem_path(tmp_folder: str, scale: int) -> str:
    return os.path.join(tmp_folder, f"multicut_s{scale}.npz")


def load_scale_problem(task, scale: int):
    """Graph at a scale: (edges, costs, node_labeling).

    Invariant: ``edges`` at scale s are in *scale-s cluster* coordinates and
    ``node_labeling`` maps scale-0 dense node ids → scale-s cluster ids (at
    scale 0 the clusters ARE the dense node ids, so the labeling is identity).
    Consumers must therefore index per-edge data with the edge endpoints
    directly — mapping them through ``node_labeling`` again would double-apply
    the contraction.
    """
    if scale == 0:
        _, edges = load_graph(task.tmp_store())
        costs = np.load(os.path.join(task.tmp_folder, COSTS_NAME))
        n_nodes = int(task.tmp_store()["graph/edges"].attrs["n_nodes"])
        return edges, costs, np.arange(n_nodes, dtype=np.int64)
    with np.load(_scale_problem_path(task.tmp_folder, scale)) as f:
        return f["edges"], f["costs"], f["node_labeling"]


def block_dense_nodes(nodes: np.ndarray, seg: np.ndarray) -> np.ndarray:
    """Dense graph ids of the (non-zero) labels present in a block, guarding
    labels missing from the graph (e.g. isolated segments)."""
    block_labels = np.unique(seg)
    block_labels = block_labels[block_labels > 0]
    if block_labels.size == 0:
        return np.zeros(0, dtype=np.int64)
    dense = np.searchsorted(nodes, block_labels)
    in_range = dense < nodes.size
    dense, block_labels = dense[in_range], block_labels[in_range]
    found = nodes[dense] == block_labels
    return dense[found].astype(np.int64)


def extract_cluster_subgraph(edges, node_labeling, dense):
    """Edges of the node-induced subproblem over current-scale clusters.

    ``dense`` are scale-0 dense node ids present in the block; the member set
    is their cluster image.  Returns (sub_edge_ids, uniq_cluster_ids,
    local_uv, member) with ``local_uv`` relabeled to 0..len(uniq)-1 and
    ``member`` the cluster membership mask, or ``(empty, None, None, member)``
    when no edge is internal.
    """
    current = np.unique(node_labeling[dense])
    member = np.zeros(int(node_labeling.max()) + 2, dtype=bool)
    member[current] = True
    cur_u, cur_v = edges[:, 0], edges[:, 1]
    in_sub = member[cur_u] & member[cur_v] & (cur_u != cur_v)
    sub_edge_ids = np.nonzero(in_sub)[0]
    if sub_edge_ids.size == 0:
        return sub_edge_ids, None, None, member
    uniq, inv = np.unique(
        np.stack([cur_u[in_sub], cur_v[in_sub]]), return_inverse=True
    )
    local_uv = inv.reshape(2, -1).T
    return sub_edge_ids, uniq, local_uv, member


def write_assignment_table(task, final: np.ndarray, out_name: str) -> None:
    """(watershed label → 1-based segment) table for the write task; label 0
    (if present in the graph) keeps segment 0."""
    nodes, _ = load_graph(task.tmp_store())
    table = np.stack(
        [nodes, (final + 1).astype(np.uint64)], axis=1
    ).astype(np.uint64)
    if nodes.size and nodes[0] == 0:
        table[0, 1] = 0
    np.save(os.path.join(task.tmp_folder, out_name), table)


class SolveSubproblemsTask(VolumeTask):
    """Per-block subgraph extraction + solve (reference solve_subproblems.py:31).

    ``input_path/key`` is the watershed label volume — a block's node set is the
    set of (current-scale clusters of) labels present in its bounding box.
    """

    task_name = "solve_subproblems"
    output_dtype = None

    def __init__(self, *args, scale: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self.scale = scale

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_s{self.scale}"

    def get_block_shape(self, gconf):
        # block shape doubles per scale (reference reduce_problem.py:246-258)
        return [bs * (2**self.scale) for bs in gconf["block_shape"]]

    def process_block(self, block_id: int, blocking: Blocking, config):
        store = self.tmp_store()
        nodes, _ = load_graph(store)
        edges, costs, node_labeling = load_scale_problem(self, self.scale)

        # +1 upper halo: the node set must cover both endpoints of every
        # cross-face edge the graph extraction saw (graph.py reads the same
        # halo), or those edges land in no subproblem, are never cut, and
        # ReduceProblem would union-merge them regardless of cost
        seg = read_block_with_upper_halo(
            self.input_ds(), blocking, block_id
        )
        out = self.tmp_ragged(
            f"multicut/s{self.scale}/cut_edges", blocking.n_blocks, np.int64
        )
        dense = block_dense_nodes(nodes, seg)
        if dense.size == 0 or edges.shape[0] == 0:
            out.write_chunk((block_id,), np.array([], dtype=np.int64))
            return
        sub_edge_ids, uniq, local_uv, _ = extract_cluster_subgraph(
            edges, node_labeling, dense
        )
        if sub_edge_ids.size == 0:
            out.write_chunk((block_id,), np.array([], dtype=np.int64))
            return
        result = solve_multicut(uniq.size, local_uv, costs[sub_edge_ids])
        cut = result[local_uv[:, 0]] != result[local_uv[:, 1]]
        out.write_chunk((block_id,), sub_edge_ids[cut].astype(np.int64))


class ReduceProblemTask(VolumeSimpleTask):
    """Merge non-cut edges, contract the graph, emit the next-scale problem
    (reference reduce_problem.py:30)."""

    task_name = "reduce_problem"

    def __init__(self, *args, scale: int = 0, input_path: str = None,
                 input_key: str = None, **kwargs):
        super().__init__(*args, scale=scale, input_path=input_path,
                         input_key=input_key, **kwargs)

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_s{self.scale}"

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(
            self.config_dir, self.input_path, self.input_key, scale=self.scale
        )
        edges, costs, node_labeling = load_scale_problem(self, self.scale)
        store = self.tmp_store()
        cut_ds = store[f"multicut/s{self.scale}/cut_edges"]
        cut = np.zeros(edges.shape[0], dtype=bool)
        for chunk in read_ragged_chunks(cut_ds, n_blocks, merge_threads(self)):
            if chunk is not None and chunk.size:
                cut[chunk] = True

        n_current = int(node_labeling.max()) + 1
        uf = UnionFindNp(n_current)
        # edges are already in current-scale cluster coordinates
        cur_u, cur_v = edges[:, 0], edges[:, 1]
        keep = ~cut & (cur_u != cur_v)
        uf.merge(cur_u[keep], cur_v[keep])
        roots = uf.compress()
        _, new_ids = np.unique(roots, return_inverse=True)
        merged_labeling = new_ids[node_labeling].astype(np.int64)

        new_edges, new_costs = contract_edges(
            new_ids[cur_u], new_ids[cur_v], costs
        )

        np.savez(
            _scale_problem_path(self.tmp_folder, self.scale + 1),
            edges=new_edges,
            costs=new_costs,
            node_labeling=merged_labeling,
        )
        self.log(
            f"scale {self.scale}: {edges.shape[0]} edges / "
            f"{n_current} nodes → {new_edges.shape[0]} edges / "
            f"{int(new_ids.max()) + 1} nodes"
        )


class SolveGlobalTask(VolumeSimpleTask):
    """Solve the final reduced problem and emit the (label → segment) table
    (reference solve_global.py:25)."""

    task_name = "solve_global"

    def __init__(self, *args, scale: int = 0, **kwargs):
        super().__init__(*args, scale=scale, **kwargs)

    def run_impl(self) -> None:
        edges, costs, node_labeling = load_scale_problem(self, self.scale)
        n_current = int(node_labeling.max()) + 1
        result = solve_multicut(n_current, edges, costs)
        final = result[node_labeling]  # scale-0 dense node → segment
        write_assignment_table(self, final, ASSIGNMENTS_NAME)
        self.log(
            f"global solve: {n_current} nodes → {int(result.max()) + 1} segments"
        )

def reduced_assignments_name(scale: int) -> str:
    return f"reduced_assignments_s{scale}.npy"


class ReducedAssignmentsTask(VolumeSimpleTask):
    """Emit the scale-``n`` *reduced* labeling (merged through the
    hierarchical reduces, but not globally solved) as a (label → segment)
    table, the role of ``s{n}/node_labeling`` in the reference's
    ReducedSolutionWorkflow (multicut_workflow.py:103-125)."""

    task_name = "reduced_assignments"

    def __init__(self, *args, scale: int = 0, **kwargs):
        super().__init__(*args, scale=scale, **kwargs)

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_s{self.scale}"

    def run_impl(self) -> None:
        if self.scale == 0:
            # identity labeling straight from the graph: scale 0 needs
            # neither edges nor costs (which may not have been computed)
            n_nodes = int(self.tmp_store()["graph/edges"].attrs["n_nodes"])
            node_labeling = np.arange(n_nodes, dtype=np.int64)
        else:
            _, _, node_labeling = load_scale_problem(self, self.scale)
        write_assignment_table(
            self, node_labeling.astype(np.int64),
            reduced_assignments_name(self.scale),
        )
        self.log(
            f"scale-{self.scale} reduced labeling: "
            f"{int(node_labeling.max()) + 1} clusters"
        )


class SubSolutionsTask(VolumeTask):
    """Write each block's standalone sub-solution as a label volume for
    inspection (reference sub_solutions.py:28): the block's subproblem is
    solved in isolation and the watershed labels (``input_path/key``) are
    mapped through the local result, offset into the block's id namespace."""

    task_name = "sub_solutions"
    output_dtype = "uint64"

    def __init__(self, *args, scale: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self.scale = scale

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_s{self.scale}"

    def get_block_shape(self, gconf):
        return [bs * (2**self.scale) for bs in gconf["block_shape"]]

    def process_block(self, block_id: int, blocking: Blocking, config):
        nodes, _ = load_graph(self.tmp_store())
        edges, costs, node_labeling = load_scale_problem(self, self.scale)
        bb = blocking.block(block_id).slicing
        ws = np.asarray(self.input_ds()[bb]).astype(np.uint64)
        out_ds = self.output_ds()
        dense = block_dense_nodes(nodes, ws)
        if dense.size == 0:
            out_ds[bb] = np.zeros(ws.shape, dtype=np.uint64)
            return
        sub_edge_ids, uniq, local_uv, _ = extract_cluster_subgraph(
            edges, node_labeling, dense
        )

        # per-voxel cluster via searchsorted over the block's (sorted) labels
        # — no dense nodes.max()-sized arrays; labels missing from the graph
        # go to 0 deliberately (a graph/volume mismatch should be visible)
        block_labels = nodes[dense]  # ascending
        pos = np.searchsorted(block_labels, ws)
        safe = np.clip(pos, 0, block_labels.size - 1)
        known = (ws > 0) & (block_labels[safe] == ws)
        cluster = np.where(known, node_labeling[dense][safe], -1)

        # every cluster present in the block gets a segment id: solved
        # clusters take their multicut component, edge-less clusters get
        # fresh ids after them — coverage never depends on edge locality
        clusters_here = np.unique(node_labeling[dense])
        if sub_edge_ids.size:
            result = solve_multicut(uniq.size, local_uv, costs[sub_edge_ids])
            n_res = int(result.max()) + 1
        else:
            uniq = np.zeros(0, dtype=np.int64)
            result = np.zeros(0, dtype=np.int64)
            n_res = 0
        seg_of_cluster = {}
        extra = n_res
        for cl in clusters_here:
            p = np.searchsorted(uniq, cl)
            if p < uniq.size and uniq[p] == cl:
                seg_of_cluster[int(cl)] = int(result[p])
            else:
                seg_of_cluster[int(cl)] = extra
                extra += 1

        # segment ids are bounded by the cluster count <= node_labeling.max()+1,
        # so this offset spacing keeps block namespaces disjoint
        offset = np.uint64(block_id) * np.uint64(int(node_labeling.max()) + 2)
        lut = np.asarray(
            [seg_of_cluster[int(c)] for c in clusters_here], dtype=np.uint64
        )
        cl_pos = np.searchsorted(clusters_here, np.maximum(cluster, 0))
        seg = np.where(
            cluster >= 0,
            lut[np.clip(cl_pos, 0, lut.size - 1)] + np.uint64(1) + offset,
            0,
        )
        out_ds[bb] = seg.astype(np.uint64)
