"""Relabeling tasks: make block-offset labels consecutive.

Reference relabel/{find_uniques,find_labeling}.py (SURVEY.md §2.4): per-block
uniques → merged sparse id set → (old → consecutive new) assignment table →
applied by the write task.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np

from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, merge_threads, read_ragged_chunks, resolve_n_blocks

UNIQUES_KEY = "relabel/uniques"
LABELING_NAME = "relabel_assignments.npy"


class FindUniquesTask(VolumeTask):
    """Per-block unique labels → ragged scratch (reference find_uniques.py:26)."""

    task_name = "find_uniques"
    output_dtype = None

    def process_block(self, block_id: int, blocking: Blocking, config):
        ds = self.input_ds()
        bb = blocking.block(block_id).slicing
        uniques = np.unique(ds[bb])
        store = self.tmp_ragged(UNIQUES_KEY, blocking.n_blocks, np.uint64)
        store.write_chunk((block_id,), uniques.astype(np.uint64))


class MergeUniquesTask(VolumeSimpleTask):
    """Merge the per-block uniques into a sorted unique-id dataset at
    ``output_path/output_key`` (reference relabel/merge_uniques.py:24,84-120).

    Unlike ``FindLabelingTask`` (which turns the merged set into a
    consecutive assignment table for relabeling), this materializes the raw
    sparse id set — the reference's standalone ``UniqueWorkflow`` output.
    Ragged chunk reads fan out over ``threads_per_job``.
    """

    task_name = "merge_uniques"

    def run_impl(self) -> None:
        from ..utils import store

        n_blocks = resolve_n_blocks(self.config_dir, self.input_path, self.input_key)
        uniques_ds = self.tmp_store()[UNIQUES_KEY]
        chunks = read_ragged_chunks(uniques_ds, n_blocks, merge_threads(self))
        collected = [c for c in chunks if c is not None and c.size]
        uniques = (
            np.unique(np.concatenate(collected))
            if collected
            else np.array([], dtype=np.uint64)
        )
        f = store.file_reader(self.output_path, "a")
        f.create_dataset(
            self.output_key,
            data=uniques.astype(np.uint64),
            chunks=(max(min(int(1e6), uniques.size), 1),),
            compression="gzip",
        )
        self.log(f"{uniques.size} unique ids -> {self.output_path}/{self.output_key}")


class FindLabelingTask(VolumeSimpleTask):
    """Merge uniques → dense consecutive assignment table
    (reference find_labeling.py:100-125)."""

    task_name = "find_labeling"

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 **kwargs):
        super().__init__(*args, input_path=input_path, input_key=input_key,
                         **kwargs)

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(self.config_dir, self.input_path, self.input_key)
        uniques_ds = self.tmp_store()[UNIQUES_KEY]
        chunks = read_ragged_chunks(uniques_ds, n_blocks, merge_threads(self))
        collected = [c for c in chunks if c is not None and c.size]
        uniques = (
            np.unique(np.concatenate(collected))
            if collected
            else np.array([], dtype=np.uint64)
        )
        nonzero = uniques[uniques > 0]
        new_ids = np.arange(1, nonzero.size + 1, dtype=np.uint64)
        table = np.stack([nonzero, new_ids], axis=1) if nonzero.size else np.zeros(
            (0, 2), dtype=np.uint64
        )
        np.save(os.path.join(self.tmp_folder, LABELING_NAME), table)
        self.log(f"relabeling {nonzero.size} ids to consecutive")
