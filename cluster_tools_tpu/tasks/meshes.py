"""Per-segment surface meshes (reference meshes/compute_meshes.py:29).

Each segment id is cropped by its morphology bounding box, meshed with the
surface-nets kernel (ops/mesh.py) and written as obj / ply / npz into the
output directory, vertex coordinates offset to global physical units."""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np

from ..ops import mesh as mesh_ops
from ..utils.blocking import Blocking
from .morphology import load_morphology
from .skeletons import IdBlockTask


class ComputeMeshesTask(IdBlockTask):
    task_name = "compute_meshes"
    output_dtype = None

    def __init__(self, *args, output_dir: str = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.output_dir = output_dir

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {"size_threshold": None, "resolution": [1.0, 1.0, 1.0],
             "smoothing_iterations": 0, "output_format": "obj"}
        )
        return conf

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        os.makedirs(self.output_dir, exist_ok=True)

    def process_block(self, block_id: int, blocking: Blocking, config):
        by_id = self.morphology_by_id()
        seg_ds = self.input_ds()
        shape = seg_ds.shape
        resolution = np.asarray(
            config.get("resolution", [1.0, 1.0, 1.0]), dtype=float
        )
        size_threshold = config.get("size_threshold")
        smoothing = int(config.get("smoothing_iterations", 0))
        fmt = config.get("output_format", "obj")
        if fmt == "npy":  # reference name for the numpy format; files are .npz
            fmt = "npz"
        if fmt not in ("obj", "ply", "npz"):
            raise ValueError(f"unknown mesh format {fmt!r}")

        block = blocking.block(block_id)
        for seg_id in range(max(1, block.begin[0]), block.end[0]):
            row = by_id.get(seg_id)
            if row is None:
                continue
            if size_threshold is not None and row[1] < size_threshold:
                continue
            bb = tuple(
                slice(max(int(mi), 0), min(int(ma), sh))
                for mi, ma, sh in zip(row[5:8], row[8:11], shape)
            )
            obj = np.asarray(seg_ds[bb]) == seg_id
            verts, faces, normals = mesh_ops.marching_cubes(
                obj, smoothing_iterations=smoothing
            )
            if verts.shape[0] == 0:
                continue
            offset = np.asarray([b.start for b in bb], dtype=float)
            verts = (verts + offset[None]) * resolution[None]
            if fmt == "obj":
                mesh_ops.write_obj(
                    os.path.join(self.output_dir, f"{seg_id}.obj"),
                    verts, faces, normals,
                )
            elif fmt == "ply":
                mesh_ops.write_ply(
                    os.path.join(self.output_dir, f"{seg_id}.ply"),
                    verts, faces, normals,
                )
            else:  # npz
                mesh_ops.write_numpy(
                    os.path.join(self.output_dir, f"{seg_id}.npz"),
                    verts, faces, normals,
                )
