"""Global agglomerative clustering of the extracted graph problem.

Reference agglomerative_clustering/agglomerative_clustering.py:25: a single-job
task that loads the scale-0 graph edges + merged edge features and runs
mala-style threshold clustering (elf/nifty ``mala_clustering``), emitting the
node → segment assignment table consumed by the write task.

The clustering itself is a sequential host solve (C++ via
``cluster_tools_tpu.native`` with a python fallback); the graph and feature
reductions feeding it were produced on device by the graph/features tasks.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np

from ..ops.multicut import agglomerative_clustering
from .base import VolumeSimpleTask
from .features import FEATURES_KEY
from .graph import load_graph

AGGLO_ASSIGNMENTS_NAME = "agglomerative_clustering_assignments.npy"


class AgglomerativeClusteringTask(VolumeSimpleTask):
    task_name = "agglomerative_clustering"

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"threshold": 0.9})
        return conf

    def run_impl(self) -> None:
        config = self.get_task_config()
        store = self.tmp_store()
        nodes, edges = load_graph(store)
        feats = store[FEATURES_KEY][:]
        clusters = agglomerative_clustering(
            int(nodes.size),
            edges,
            feats[:, 0],            # mean boundary evidence per edge
            float(config.get("threshold", 0.9)),
            edge_sizes=feats[:, -1],  # edge face size (last col in all layouts)
        )
        # segments 1-based; a background node label 0 stays 0
        table = np.stack(
            [nodes, (clusters + 1).astype(np.uint64)], axis=1
        ).astype(np.uint64)
        if nodes.size and nodes[0] == 0:
            table[0, 1] = 0
        np.save(os.path.join(self.tmp_folder, AGGLO_ASSIGNMENTS_NAME), table)
        self.log(
            f"clustered {nodes.size} nodes / {edges.shape[0]} edges → "
            f"{int(clusters.max()) + 1 if clusters.size else 0} segments"
        )
