"""Pairwise object distances (reference distances/object_distances.py:31).

Per segment id: crop the morphology bounding box, run the Euclidean DT of the
object (device kernel, anisotropic resolution), enlarge the box adaptively
when a face is closer than ``max_distance`` (reference ``_enlarge_bb``:132-153),
then the min DT value per other object inside the box is the pairwise
distance.  Pairs above ``max_distance`` are dropped; a merge task combines the
per-id-chunk dictionaries taking elementwise minima."""

from __future__ import annotations

import os
from typing import Any, Dict, Sequence

import jax.numpy as jnp
import numpy as np

from ..ops.dt import distance_transform
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask
from .morphology import load_morphology
from .skeletons import IdBlockTask

DISTANCES_KEY = "distances/pairs"
DISTANCES_NAME = "object_distances.npz"


def _face_distances(dist: np.ndarray):
    """Min DT on each bounding-box face, ordered (z0, z1, y0, y1, x0, x1)."""
    return [
        float(dist[0].min()), float(dist[-1].min()),
        float(dist[:, 0].min()), float(dist[:, -1].min()),
        float(dist[:, :, 0].min()), float(dist[:, :, -1].min()),
    ]


def _enlarge_bb(bb, face_distances, resolution, shape, max_distance):
    enlarged = []
    face_id = 0
    for dim, b in enumerate(bb):
        start, stop = b.start, b.stop
        res = resolution[dim]
        fdist = face_distances[face_id]
        if fdist < max_distance:
            start = max(int(start - (max_distance - fdist) / res), 0)
        face_id += 1
        fdist = face_distances[face_id]
        if fdist < max_distance:
            stop = min(int(stop + (max_distance - fdist) / res), shape[dim])
        face_id += 1
        enlarged.append(slice(start, stop))
    return tuple(enlarged)


def object_distances_for_id(seg_ds, label_id, bb, resolution, max_distance):
    """{(label_id, other_id): min distance} for other ids within reach."""
    shape = seg_ds.shape

    def compute(bb):
        labels = np.asarray(seg_ds[bb])
        dist = np.asarray(
            distance_transform(
                jnp.asarray(labels != label_id), pixel_pitch=resolution
            )
        )
        return labels, dist

    # the object touches every face of its own bounding box, so the reach
    # test always triggers — enlarge by the full reach up front and run the
    # DT once (the reference computes a throwaway first DT here,
    # object_distances.py:155-167)
    bb = _enlarge_bb(bb, [0.0] * 6, resolution, shape, max_distance)
    labels, dist = compute(bb)

    others = np.unique(labels)
    others = others[(others != 0) & (others != label_id)]
    out = {}
    for other in others:
        if label_id >= other:
            continue
        d = float(dist[labels == other].min())
        if d < max_distance:
            out[(int(label_id), int(other))] = d
    return out


class ObjectDistancesTask(IdBlockTask):
    task_name = "object_distances"
    output_dtype = None

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"max_distance": 100.0, "resolution": [1.0, 1.0, 1.0]})
        return conf

    def process_block(self, block_id: int, blocking: Blocking, config):
        by_id = self.morphology_by_id()
        seg_ds = self.input_ds()
        shape = seg_ds.shape
        resolution = [float(r) for r in config.get("resolution", [1, 1, 1])]
        max_distance = float(config.get("max_distance", 100.0))

        block = blocking.block(block_id)
        rows = []
        for seg_id in range(max(1, block.begin[0]), block.end[0]):
            row = by_id.get(seg_id)
            if row is None:
                continue
            bb = tuple(
                slice(max(int(mi), 0), min(int(ma), sh))
                for mi, ma, sh in zip(row[5:8], row[8:11], shape)
            )
            pairs = object_distances_for_id(
                seg_ds, seg_id, bb, resolution, max_distance
            )
            rows.extend([a, b, d] for (a, b), d in pairs.items())
        out = self.tmp_ragged(DISTANCES_KEY, blocking.n_blocks, np.float64)
        out.write_chunk(
            (block_id,),
            np.asarray(rows, dtype=np.float64).reshape(-1),
        )


class MergeObjectDistancesTask(VolumeSimpleTask):
    task_name = "merge_object_distances"

    def __init__(self, *args, n_blocks: int = None, **kwargs):
        super().__init__(*args, n_blocks=n_blocks, **kwargs)

    def run_impl(self) -> None:
        ds = self.tmp_store()[DISTANCES_KEY]
        rows = []
        for bid in range(int(np.prod(ds.grid_shape))):
            chunk = ds.read_chunk((bid,))
            if chunk is not None and chunk.size:
                rows.append(chunk.reshape(-1, 3))
        if rows:
            all_rows = np.concatenate(rows, axis=0)
            # min per pair (a pair can be seen from both endpoint ids)
            pairs = all_rows[:, :2].astype(np.int64)
            order = np.lexsort((all_rows[:, 2], pairs[:, 1], pairs[:, 0]))
            pairs, dists = pairs[order], all_rows[order, 2]
            first = np.concatenate(
                [[True], (np.diff(pairs, axis=0) != 0).any(axis=1)]
            )
            pairs, dists = pairs[first], dists[first]
        else:
            pairs = np.zeros((0, 2), dtype=np.int64)
            dists = np.zeros(0)
        np.savez(
            os.path.join(self.tmp_folder, DISTANCES_NAME),
            pairs=pairs, distances=dists,
        )
        self.log(f"merged {pairs.shape[0]} object distance pairs")


def load_object_distances(tmp_folder: str) -> Dict:
    with np.load(os.path.join(tmp_folder, DISTANCES_NAME)) as f:
        return {
            (int(a), int(b)): float(d)
            for (a, b), d in zip(f["pairs"], f["distances"])
        }
