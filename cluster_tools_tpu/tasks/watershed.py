"""Watershed tasks — the north-star hot path.

Reference watershed/watershed.py:39-394 and two_pass_watershed.py:32-99:
per halo'd block, run the DT-watershed, crop the inner box, re-close labels by
CC, add the block's id offset (``block_id * prod(block_shape)``), write.  The
two-pass variant runs checkerboard halves so pass-2 blocks can seed from their
already-written pass-1 neighbors, giving boundary-consistent labels without a
stitching step.

TPU design: the whole per-block pipeline is one fused jit program
(``ops.watershed.dt_watershed``), vmapped over a stacked block batch; IO,
offsets and uint64 conversion stay on the host.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import watershed as ws_ops
from ..ops.cc import connected_components_labels
from ..parallel.mesh import put_sharded
from ..utils import store
from ..utils.blocking import Blocking, make_checkerboard_block_lists
from .base import VolumeSimpleTask, VolumeTask, read_threads

MAX_IDS_KEY = "watershed/max_ids"


@lru_cache(maxsize=32)
def _fused_ws_kernel(params_key, block_shape, with_mask: bool, crop_cc: bool,
                     coarse_tile=None):
    """One jitted program per config: flood → per-block dynamic-slice crop to
    the inner box → CC re-close (reference watershed.py:329-333), vmapped
    over the stacked block batch.

    Fusing the crop+CC into the flood dispatch removes two host↔device
    round-trips of the full batch per stage (the dominant non-kernel cost on
    a tunneled chip) and runs the CC on the cropped extent only — 2×halo
    fewer voxels per axis than the padded outer shape.  The crop window is
    the static ``block_shape`` anchored at each block's inner-local origin;
    for edge blocks the window tail covers zero padding (masked out of the
    flood by ``valid``), which the partition-CC ignores as background."""
    from jax import lax

    kernel = partial(ws_ops.dt_watershed, **dict(params_key))
    bs = tuple(block_shape)

    def one(x, v, start, m):
        if with_mask:
            lab, _ = kernel(x, mask=m, valid=v)
        else:
            lab, _ = kernel(x, valid=v)
        if crop_cc:
            lab = lax.dynamic_slice(lab, (start[0], start[1], start[2]), bs)
            # re-close through the ctt-cc kernel: the same
            # connected_components() dispatch as every other CC call site
            # (coarse_tile config knob > CTT_CC_TILE pin > backend default)
            lab, _ = connected_components_labels(lab, coarse_tile=coarse_tile)
        return lab

    if with_mask:
        return jax.jit(jax.vmap(one))
    return jax.jit(jax.vmap(lambda x, v, s: one(x, v, s, None)))


def _read_input_block(ds, bb, config):
    """Read a (possibly multi-channel) block, normalize integer dtypes to [0,1]
    and agglomerate channels (reference ``_read_data``, watershed.py:268-283
    incl. vu.normalize)."""
    if ds.ndim == 4:
        c0 = config.get("channel_begin", 0)
        c1 = config.get("channel_end", None)
        data = ds[(slice(c0, c1),) + bb]
        data = _normalize_host(data)
        agglo = config.get("agglomerate_channels", "mean")
        if agglo == "max":
            data = data.max(axis=0)
        else:
            data = data.mean(axis=0)
        return data
    return _normalize_host(ds[bb])


def _pad_block(arr: np.ndarray, full_shape, mode: str = "edge") -> np.ndarray:
    """Pad a clipped edge-block read up to the static batch shape.

    ``mode='edge'`` (data, masks) replicates border values — constant
    background padding would inject fake boundaries into the distance
    transform at volume borders (the reference reads clipped arrays and lets
    vigra reflect at edges).  Label/seed arrays pad with zeros instead
    (``mode='zero'``): replicated labels would invent seeds."""
    pad = [(0, fs - s) for fs, s in zip(full_shape, arr.shape)]
    if not any(p for _, p in pad):
        return arr
    if mode == "zero":
        return np.pad(arr, pad)
    return np.pad(arr, pad, mode=mode)


def _normalize_host(data: np.ndarray) -> np.ndarray:
    """uint8/uint16 → [0,1] by dtype range; other dtypes cast to float32
    (integer boundary maps would otherwise be thresholded meaninglessly)."""
    if data.dtype == np.uint8:
        return data.astype(np.float32) / 255.0
    if data.dtype == np.uint16:
        return data.astype(np.float32) / 65535.0
    return data.astype(np.float32)


class WatershedTask(VolumeTask):
    task_name = "watershed"
    output_dtype = "uint64"
    # ctt-stream: fusable chain member reading the raw boundary map — in a
    # fused chain it shares the head's store read (its halo'd outer boxes
    # ARE the chain's shared read; smaller-halo members get crops)
    fusable = True

    def __init__(self, *args, mask_path: str = None, mask_key: str = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.mask_path = mask_path
        self.mask_key = mask_key

    def fusion_halo(self, config):
        return tuple(config.get("halo") or [0, 0, 0])

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        # mirrors the reference's knobs (watershed.py:50-61)
        conf.update(
            {
                "threshold": 0.5,
                "apply_dt_2d": True,
                "apply_ws_2d": True,
                "pixel_pitch": None,
                "sigma_seeds": 2.0,
                "sigma_weights": 2.0,
                "size_filter": 25,
                "alpha": 0.8,
                "halo": [0, 0, 0],
                "invert_inputs": False,
                "channel_begin": 0,
                "channel_end": None,
                "agglomerate_channels": "mean",
                "non_maximum_suppression": False,
                # ctt-cc tile for the halo-crop CC re-close (None =
                # CTT_CC_TILE env pin / backend default)
                "coarse_tile": None,
            }
        )
        return conf

    # -- kernel dispatch -----------------------------------------------------

    @staticmethod
    def _kernel_params(config) -> Dict[str, Any]:
        pitch = config.get("pixel_pitch")
        return dict(
            threshold=float(config["threshold"]),
            apply_dt_2d=bool(config.get("apply_dt_2d", True)),
            apply_ws_2d=bool(config.get("apply_ws_2d", True)),
            pixel_pitch=tuple(pitch) if pitch else None,
            sigma_seeds=float(config.get("sigma_seeds", 2.0)),
            sigma_weights=float(config.get("sigma_weights", 2.0)),
            alpha=float(config.get("alpha", 0.8)),
            size_filter=int(config.get("size_filter", 25)),
            invert_input=bool(config.get("invert_inputs", False)),
            non_maximum_suppression=bool(config["non_maximum_suppression"]),
        )

    def _load_mask_batch(self, batch, full_shape) -> Optional[np.ndarray]:
        if not self.mask_path:
            return None
        from .base import fusion_wrap

        mask_ds = fusion_wrap(
            store.file_reader(self.mask_path, "r")[self.mask_key],
            self.mask_path, self.mask_key,
        )
        return np.stack([
            _pad_block(mask_ds[bh.outer.slicing].astype(bool), full_shape)
            for bh in batch.blocks
        ])

    # -- split batch protocol (three-stage executor pipeline) ---------------

    def _read_tag(self, config):
        """Device-cache transform tag: everything that changes the bytes
        ``read_batch`` uploads (channel window + agglomeration; the
        normalization is dtype-determined)."""
        return (
            "ws-read",
            str(config.get("channel_begin", 0)),
            str(config.get("channel_end")),
            str(config.get("agglomerate_channels", "mean")),
        )

    def read_batch(self, block_ids: List[int], blocking: Blocking, config):
        """Stage 1: read (channel-agglomerated) halo'd blocks + masks.
        With the warm device-buffer cache armed (ctt-hbm) and the batch's
        upload still HBM-resident from a previous job, the host read is
        skipped entirely — the payload carries only geometry + masks."""
        from ..parallel.dispatch import BlockBatch
        from ..runtime import hbm

        in_ds = self.input_ds()
        halo = config.get("halo") or [0, 0, 0]
        full_shape = tuple(
            bs + 2 * h for bs, h in zip(blocking.block_shape, halo)
        )
        blocks = [blocking.block_with_halo(bid, halo) for bid in block_ids]
        source = hbm.dataset_source(
            in_ds, self.input_path, self.input_key, blocking,
            list(block_ids), halo, self._read_tag(config), config,
        )
        if source is not None:
            dc = hbm.cache()
            hit = dc.get(source) if dc is not None else None
            if hit is not None:
                from ..obs import metrics as obs_metrics

                obs_metrics.inc("device.uploads_skipped")
                batch = BlockBatch(
                    data=None, valid=None, blocks=blocks,
                    block_ids=list(block_ids), source=source, device=hit,
                )
                return batch, None, self._load_mask_batch(batch, full_shape)
        datas, valids = [], []
        for bh in blocks:
            arr = _read_input_block(in_ds, bh.outer.slicing, config)
            datas.append(_pad_block(arr, full_shape))
            v = np.ones(arr.shape, dtype=bool)
            valids.append(_pad_block(v, full_shape, mode="zero"))
        batch_arr = np.stack(datas)
        valid_arr = np.stack(valids)

        batch = BlockBatch(
            data=batch_arr, valid=None, blocks=blocks,
            block_ids=list(block_ids), source=source,
        )
        return batch, valid_arr, self._load_mask_batch(batch, full_shape)

    def _device_payload(self, batch, valid_arr, config):
        """(data, valid, starts) on device through the warm buffer cache —
        all three are deterministic functions of the signed store region
        plus geometry, so they ride one cache entry; the mask (its own
        dataset, its own freshness) is uploaded uncached per compute."""
        from ..runtime import hbm

        def build():
            data = hbm.require_data(batch)
            starts = np.array(
                [bh.inner_local.begin for bh in batch.blocks], dtype=np.int32
            )
            xb, n = put_sharded(data, config)
            vb, _ = put_sharded(valid_arr, config)
            sb, _ = put_sharded(starts, config)
            return hbm.DeviceBatch(
                arrays=(xb, vb, sb), n=n,
                nbytes=int(data.nbytes + valid_arr.nbytes + starts.nbytes),
            )

        return hbm.batch_device(batch, config, build=build)

    def upload_batch(self, payload, blocking: Blocking, config):
        """ctt-hbm transfer stage: batch k+1 crosses to HBM while batch
        k's flood runs."""
        batch, valid_arr, _mask = payload
        self._device_payload(batch, valid_arr, config)
        return payload

    def stack_payloads(self, payloads, blocking: Blocking, config):
        from ..runtime import hbm

        batch = hbm.stack_block_batches([p[0] for p in payloads], config)
        valids = [p[1] for p in payloads]
        valid = (
            np.concatenate(valids, axis=0)
            if all(v is not None for v in valids) else None
        )
        masks = [p[2] for p in payloads]
        mask = (
            np.concatenate(masks, axis=0)
            if all(m is not None for m in masks) else None
        )
        return batch, valid, mask

    def unstack_results(self, result, counts, blocking: Blocking, config):
        from ..runtime import hbm

        batch, labels = result
        return list(zip(
            hbm.split_block_batch(batch, counts),
            hbm.split_stacked(labels, counts),
        ))

    def compute_batch(self, payload, blocking: Blocking, config):
        """Stage 2: ONE fused dispatch — flood → inner-box crop → CC
        re-close (the former three-dispatch sequence with host round-trips
        in between) — materialized back to host."""
        batch, valid_arr, mask = payload
        halo = config.get("halo") or [0, 0, 0]
        params = self._kernel_params(config)
        has_halo = any(h > 0 for h in halo)
        coarse_tile = config.get("coarse_tile", None)
        if coarse_tile is not None and not isinstance(coarse_tile, int):
            coarse_tile = tuple(coarse_tile)
        fused = _fused_ws_kernel(
            tuple(sorted(params.items())),
            tuple(blocking.block_shape),
            mask is not None,
            has_halo,
            coarse_tile,
        )
        db = self._device_payload(batch, valid_arr, config)
        xb, vb, sb = db.arrays
        n_real = db.n
        if mask is None:
            labels = fused(xb, vb, sb)
        else:
            mb, _ = put_sharded(mask, config)
            labels = fused(xb, vb, sb, mb)
        return batch, np.asarray(labels)[:n_real].astype(np.uint64)

    def write_batch(self, result, blocking: Blocking, config):
        """Stage 3: apply block-id offsets, record per-block max ids, write
        the inner boxes."""
        batch, labels = result
        out_ds = self.output_ds()
        halo = config.get("halo") or [0, 0, 0]
        has_halo = any(h > 0 for h in halo)
        offset_unit = int(np.prod(blocking.block_shape))
        max_ids = self.tmp_ragged(MAX_IDS_KEY, blocking.n_blocks, np.int64)
        for i, (bid, bh) in enumerate(zip(batch.block_ids, batch.blocks)):
            lab = labels[i]
            if has_halo:
                # fused output is inner-origin at the static block shape;
                # trim the zero tail of edge blocks
                size = tuple(e - b for b, e in zip(bh.inner.begin, bh.inner.end))
                lab = lab[tuple(slice(0, s) for s in size)]
            else:
                lab = lab[bh.inner_local.slicing]
            off = np.uint64(bid * offset_unit)
            lab = np.where(lab > 0, lab + off, 0).astype(np.uint64)
            max_ids.write_chunk((bid,), np.array([lab.max()], dtype=np.int64))
            out_ds[bh.inner.slicing] = lab

    def _run_batch(self, block_ids: List[int], blocking: Blocking, config):
        self.write_batch(
            self.compute_batch(
                self.read_batch(block_ids, blocking, config), blocking, config
            ),
            blocking, config,
        )

    def process_block(self, block_id, blocking, config):
        self._run_batch([block_id], blocking, config)

    def process_block_batch(self, block_ids, blocking, config):
        self._run_batch(block_ids, blocking, config)


class WatershedFromSeedsTask(VolumeTask):
    """Seeded watershed from a given (global-id) seed volume
    (reference watershed/watershed_from_seeds.py:25).

    ``input_path/key`` is the boundary/height map, ``seeds_path/key`` a label
    volume whose non-zero ids become the seeds.  Because the seed ids are
    global, the output is boundary-consistent across blocks without a stitching
    step (halo'd floods agree where they overlap up to flood-order ties).
    """

    task_name = "watershed_from_seeds"
    output_dtype = "uint64"

    def __init__(self, *args, seeds_path: str = None, seeds_key: str = None,
                 mask_path: str = None, mask_key: str = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.seeds_path = seeds_path
        self.seeds_key = seeds_key
        self.mask_path = mask_path
        self.mask_key = mask_key

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {
                "sigma_weights": 2.0,
                "halo": [2, 8, 8],
                "invert_inputs": False,
                "apply_ws_2d": False,
                "size_filter": 0,
                "channel_begin": 0,
                "channel_end": None,
                "agglomerate_channels": "mean",
            }
        )
        return conf

    def process_block(self, block_id: int, blocking: Blocking, config):
        in_ds = self.input_ds()
        out_ds = self.output_ds()
        seeds_ds = store.file_reader(self.seeds_path, "r")[self.seeds_key]
        halo = config.get("halo") or [0, 0, 0]
        bh = blocking.block_with_halo(block_id, halo)

        x = _read_input_block(in_ds, bh.outer.slicing, config)
        if config.get("invert_inputs", False):
            x = 1.0 - x
        seeds = seeds_ds[bh.outer.slicing].astype(np.uint64)

        mask = None
        if self.mask_path:
            mask_ds = store.file_reader(self.mask_path, "r")[self.mask_key]
            mask = mask_ds[bh.outer.slicing].astype(bool)

        sigma = float(config.get("sigma_weights", 2.0))
        per_slice = bool(config.get("apply_ws_2d", False)) and x.ndim == 3
        hmap = jnp.asarray(x)
        if sigma > 0:
            from ..ops.filters import gaussian

            sig = (0.0,) + (sigma,) * (x.ndim - 1) if per_slice else sigma
            hmap = gaussian(hmap, sig)

        # flood over compact ids (int32-safe on device), map back after
        uniq = np.unique(seeds)
        uniq = uniq[uniq > 0]
        compact = np.searchsorted(uniq, seeds) + 1
        compact = np.where(seeds > 0, compact, 0).astype(np.int32)
        labels = ws_ops.seeded_watershed(
            hmap,
            jnp.asarray(compact),
            mask=None if mask is None else jnp.asarray(mask),
            per_slice=per_slice,
        )
        size_filter = int(config.get("size_filter", 0))
        if size_filter > 0:
            labels = ws_ops.apply_size_filter(
                labels, hmap, size_filter, int(uniq.size + 2),
                mask=None if mask is None else jnp.asarray(mask),
                per_slice=per_slice,
            )
        labels = np.asarray(labels).astype(np.int64)
        lookup = np.concatenate([[np.uint64(0)], uniq]).astype(np.uint64)
        out = lookup[labels[bh.inner_local.slicing]]
        out_ds[bh.inner.slicing] = out


class AgglomerateTask(VolumeTask):
    """Per-block agglomeration of watershed fragments
    (reference watershed/agglomerate.py:33): build the block's RAG with mean
    boundary-evidence edge weights and merge fragments below the threshold
    (mala clustering semantics).  Fragment ids stay in the block's offset
    namespace, so downstream stitching/relabel tasks apply unchanged.
    """

    task_name = "agglomerate"
    output_dtype = "uint64"

    def __init__(self, *args, labels_path: str = None, labels_key: str = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        # ``input_path/key`` = boundary map; ``labels_path/key`` = watershed
        self.labels_path = labels_path
        self.labels_key = labels_key

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {
                "threshold": 0.9,
                "use_mala_agglomeration": True,
                "channel_begin": 0,
                "channel_end": None,
                "agglomerate_channels": "mean",
                "invert_inputs": False,
            }
        )
        return conf

    def process_block(self, block_id: int, blocking: Blocking, config):
        from ..ops.multicut import agglomerative_clustering
        from ..ops.rag import boundary_edge_features

        bb = blocking.block(block_id).slicing
        seg = store.file_reader(self.labels_path, "r")[self.labels_key][bb]
        seg = seg.astype(np.uint64)
        out_ds = self.output_ds()
        uniq = np.unique(seg)
        uniq = uniq[uniq > 0]
        if uniq.size == 0:
            out_ds[bb] = seg
            return
        x = _read_input_block(self.input_ds(), bb, config)
        if config.get("invert_inputs", False):
            x = 1.0 - x
        edges, feats = boundary_edge_features(seg, x.astype(np.float64))
        if edges.shape[0] == 0:
            out_ds[bb] = seg
            return
        # compact node ids for the local clustering problem
        uv = np.searchsorted(uniq, edges).astype(np.int64)
        clusters = agglomerative_clustering(
            uniq.size,
            uv,
            feats[:, 0],                      # mean boundary evidence
            float(config.get("threshold", 0.9)),
            edge_sizes=feats[:, 9],           # face size
        )
        # merged fragments take the smallest member id — stays in the block's
        # offset namespace (reference agglomerate.py relabels w/ block offset)
        rep = np.full(int(clusters.max()) + 1, np.iinfo(np.int64).max, np.int64)
        np.minimum.at(rep, clusters, np.arange(uniq.size, dtype=np.int64))
        mapped = uniq[rep[clusters]]
        lookup = np.concatenate([[np.uint64(0)], mapped]).astype(np.uint64)
        dense = np.searchsorted(uniq, seg) + 1
        dense = np.where(seg > 0, dense, 0)
        out_ds[bb] = lookup[dense]


class TwoPassWatershedTask(WatershedTask):
    """One pass of the checkerboard two-pass watershed
    (reference two_pass_watershed.py:32-99).

    ``pass_id`` 0 processes the white half normally; ``pass_id`` 1 processes the
    black half seeding from the already-written neighbors inside the halo.
    """

    task_name = "two_pass_watershed"
    # pass 2 reads labels its own dispatch writes — never stream-fusable
    fusable = False

    def __init__(self, *args, pass_id: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self.pass_id = pass_id

    @classmethod
    def default_task_config(cls):
        conf = super().default_task_config()
        # the two-pass variant defaults NMS on (reference
        # two_pass_watershed.py:54) where plain watershed defaults it off
        conf["non_maximum_suppression"] = True
        return conf

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_pass{self.pass_id}"

    @property
    def pipeline_safe(self) -> bool:
        # pass 2 reads halo'd out_ds regions that same-color *diagonal*
        # neighbors write: concurrent batches would make the visible neighbor
        # labels timing-dependent.  One batch reads everything before writing
        # anything, so serial batches are fully deterministic.
        return self.pass_id == 0

    def get_block_list(self, blocking, gconf):
        base = super().get_block_list(blocking, gconf)
        white, black = make_checkerboard_block_lists(blocking, base)
        return white if self.pass_id == 0 else black

    def _run_batch(self, block_ids, blocking, config):
        if self.pass_id == 0:
            return super()._run_batch(block_ids, blocking, config)
        # pass 2: flood from written pass-1 labels in the halo + own seeds.
        # Blocks of one checkerboard color are independent, so the whole device
        # part (threshold → DT → seeds → flood → size filter) is ONE fused
        # kernel (ops.watershed.two_pass_flood) vmapped over the stacked batch;
        # only the global↔compact id mapping stays on the host.  Written ids
        # are compacted to 1..k per block so the device arrays stay int32-safe
        # and no per-block count leaks into the trace as a static value.
        in_ds = self.input_ds()
        out_ds = self.output_ds()
        halo = config.get("halo") or [0, 0, 0]
        if not any(h > 0 for h in halo):
            raise ValueError(
                "two-pass watershed requires a non-zero halo — pass 2 seeds from "
                "pass-1 neighbors inside the halo (set 'halo' in the task config)"
            )
        params = self._kernel_params(config)
        offset_unit = int(np.prod(blocking.block_shape))
        max_ids = self.tmp_ragged(MAX_IDS_KEY, blocking.n_blocks, np.int64)

        full_shape = tuple(
            bs + 2 * h for bs, h in zip(blocking.block_shape, halo)
        )
        xs, compacts, valids, uniqs, blocks = [], [], [], [], []
        for bid in block_ids:
            bh = blocking.block_with_halo(bid, halo)
            x = _read_input_block(in_ds, bh.outer.slicing, config)
            written = out_ds[bh.outer.slicing].astype(np.int64)
            uniq_written = np.unique(written)
            uniq_written = uniq_written[uniq_written > 0]
            compact = np.searchsorted(uniq_written, written) + 1
            compact = np.where(written > 0, compact, 0).astype(np.int32)
            xs.append(_pad_block(x, full_shape))
            compacts.append(_pad_block(compact, full_shape, mode="zero"))
            valids.append(
                _pad_block(np.ones(x.shape, dtype=bool), full_shape, mode="zero")
            )
            uniqs.append(uniq_written)
            blocks.append(bh)

        from ..parallel.dispatch import BlockBatch

        batch_arr = np.stack(xs)
        batch = BlockBatch(
            data=batch_arr, valid=None, blocks=blocks, block_ids=list(block_ids)
        )
        mask = self._load_mask_batch(batch, full_shape)

        # tight size-filter bincount bound: own-seed CC ids are consecutive
        # (≤ N/2) and written ids only occupy the halo shell (pass-1 neighbors
        # write disjoint inner boxes)
        n_outer = int(np.prod(full_shape))
        shell = n_outer - int(np.prod(blocking.block_shape))
        kernel = partial(
            ws_ops.two_pass_flood,
            num_segments=n_outer // 2 + shell + 2,
            **params,
        )
        xb, n_real = put_sharded(batch_arr, config)
        wb, _ = put_sharded(np.stack(compacts), config)
        vb, _ = put_sharded(np.stack(valids), config)
        if mask is None:
            labels, _ = jax.vmap(
                lambda x, w, v: kernel(x, w, valid=v)
            )(xb, wb, vb)
        else:
            mb, _ = put_sharded(mask, config)
            labels, _ = jax.vmap(
                lambda x, w, m, v: kernel(x, w, mask=m, valid=v)
            )(xb, wb, mb, vb)
        labels = np.asarray(labels).astype(np.int64)[:n_real]

        for i, bid in enumerate(block_ids):
            bh = blocks[i]
            k = uniqs[i].size
            lab = labels[i][bh.inner_local.slicing]
            # map back: 1..k → written global ids, k+1.. → block's namespace
            lookup = np.concatenate([[0], uniqs[i]])
            is_written = lab <= k
            written_part = lookup[np.where(is_written, lab, 0)]
            new_part = lab - k + bid * offset_unit
            lab = np.where(lab == 0, 0, np.where(is_written, written_part, new_part))
            lab = lab.astype(np.uint64)
            out_ds[bh.inner.slicing] = lab
            max_ids.write_chunk((bid,), np.array([lab.max()], dtype=np.int64))


def run_sharded_ws_kernel(x_d, config, mesh, z_valid: int):
    """Collective-watershed kernel dispatch shared by ShardedWatershedTask
    and ShardedWsProblemTask: the per-slice (2d) embarrassingly-parallel
    kernel when ``apply_dt_2d`` AND ``apply_ws_2d`` (the block pipeline's
    CREMI default), the 3d cross-shard collective when both are False;
    mixed settings are refused."""
    from ..parallel.sharded_watershed import (
        sharded_dt_watershed,
        sharded_dt_watershed_2d,
    )

    dt_2d = bool(config.get("apply_dt_2d", False))
    ws_2d = bool(config.get("apply_ws_2d", False))
    if dt_2d != ws_2d:
        raise ValueError(
            "the collective watershed supports apply_dt_2d == apply_ws_2d "
            "only (use the block pipeline for mixed 2d/3d modes)"
        )
    pitch = config.get("pixel_pitch")
    common = dict(
        mesh=mesh,
        threshold=float(config["threshold"]),
        sigma_seeds=float(config.get("sigma_seeds", 2.0)),
        sigma_weights=float(config.get("sigma_weights", 2.0)),
        alpha=float(config.get("alpha", 0.8)),
        size_filter=int(config.get("size_filter", 25)),
        invert_input=bool(config.get("invert_inputs", False)),
        z_valid=z_valid,
    )
    if dt_2d:
        if pitch:
            raise ValueError("pixel_pitch requires the 3d collective mode")
        return sharded_dt_watershed_2d(x_d, **common)
    return sharded_dt_watershed(
        x_d, pixel_pitch=tuple(pitch) if pitch else None, **common
    )


class ShardedWatershedTask(VolumeSimpleTask):
    """Whole-volume DT-watershed over the device mesh in collective form
    (``parallel.sharded_watershed.sharded_dt_watershed``) — the alternative
    to per-block watershed + stitching when the volume fits the mesh's
    aggregate HBM: no block offsets, no halos, no boundary inconsistencies,
    one globally-consistent fragmentation.

    Two collective modes, selected by the block pipeline's own knobs:
    ``apply_dt_2d=True, apply_ws_2d=True`` (the CREMI default) runs the
    per-slice kernel embarrassingly parallel over the z-shards — NO
    collectives at all, bit-exact with the single-device 2d kernel; both
    False runs the 3d collective (cross-shard EDT/flood fixpoints).  Mixed
    2d/3d settings are refused (the block path supports them; the
    collective formulations do not).  Masks are not supported yet — use
    the block pipeline for masked volumes.  ``collective``: under a
    multi-process runtime every process enters the program together
    (``devices: "global"``); process 0 owns the store writes.
    """

    task_name = "sharded_watershed"
    collective = True

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {
                "threshold": 0.5,
                "pixel_pitch": None,
                "sigma_seeds": 2.0,
                "sigma_weights": 2.0,
                "size_filter": 25,
                "alpha": 0.8,
                "invert_inputs": False,
                # collective kernel selection (defaults keep the round-4
                # behavior: the 3d collective)
                "apply_dt_2d": False,
                "apply_ws_2d": False,
            }
        )
        return conf

    def run_impl(self) -> None:
        import jax as _jax

        from ..ops.relabel import relabel_consecutive_np
        from ..parallel.mesh import get_mesh, resolve_devices

        config = {**self.global_config(), **self.get_task_config()}
        in_ds = store.file_reader(self.input_path, "r")[self.input_key]
        if in_ds.ndim != 3:
            raise ValueError(
                "sharded_watershed supports 3d volumes (channel inputs go "
                "through the block pipeline)"
            )
        store.set_read_threads(in_ds, read_threads(config))
        devices = resolve_devices(config)
        mesh = get_mesh(devices)
        n_dev = len(devices)
        invert = bool(config.get("invert_inputs", False))

        # stream shard-by-shard: peak host RAM on ingest is one shard.
        # Pad slabs sit on the foreground side of the threshold AFTER the
        # kernel's inversion, exactly like the host-pad path.  The upload
        # rides the warm device-buffer cache (ctt-hbm): a back-to-back
        # serve job on the same volume skips the transfer entirely
        from ..runtime import hbm

        x_d = hbm.cached_put_from_store(
            in_ds, mesh, source_path=self.input_path,
            source_key=self.input_key,
            tag=("sharded-ws-input", bool(invert)),
            dtype=np.float32, pad_to=n_dev,
            pad_value=1.0 if invert else 0.0,
            transform=_normalize_host,
        )

        labels, n_seeds = run_sharded_ws_kernel(
            x_d, config, mesh, z_valid=int(in_ds.shape[0])
        )
        if _jax.process_index() != 0:
            return  # process 0 owns the writes
        out, n_labels = relabel_consecutive_np(labels.astype(np.uint64))
        ds = self.require_output(in_ds.shape, config)
        # threaded chunk-aligned whole-volume write (store fast path):
        # every chunk encodes straight from the label array, in parallel
        store.set_read_threads(ds, read_threads(config))
        ds[:] = out
        self.log(
            f"sharded DT-watershed over {n_dev} devices: {n_labels} fragments"
        )
