"""ctt-events task: batched event building over a stack of detector frames.

The input volume is ``(n_frames, h, w)`` — axis 0 is the frame stream, a
block is a contiguous run of WHOLE frames (``block_shape[0]`` frames; the
frame axes must be covered by ``block_shape[1:]``, frames are never split).
One block batch becomes one ``(frames, h, w)`` device dispatch through
``ops.events.build_events``.

Outputs: a uint32 per-frame labels volume at ``output_key`` (the same
consecutive-per-frame contract as the kernel) plus ragged per-block event
tables at ``<output_key>_events`` via the varlen chunk path
(``create_ragged_dataset`` — one ``.npy`` per block holding
``(n_clusters, 1 + N_PROPS)`` float64 rows: global frame index +
:data:`~..ops.events.PROP_FIELDS`).

Speaks the full split protocol + ctt-hbm contract (``read_batch`` /
``upload_batch`` / ``stack_payloads`` / ``unstack_results``), so frame
batches ride the three-stage pipeline, the warm device-buffer cache, and
aggregated ``hbm_stack`` dispatch unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..ops import events as events_ops
from ..parallel.dispatch import read_block_batch, write_block_batch
from ..runtime import hbm
from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeTask, read_threads

EVENTS_SUFFIX = "_events"


class EventBuildingTask(VolumeTask):
    task_name = "events"
    output_dtype = "uint32"

    # ctt-stream/ctt-ingest: frames are independent (no cross-block state,
    # no halo), so the task is fusable as-is — the fusion contract
    # defaults (no carry, compute_batch doubling as fused compute) are
    # exact.  ctt-ingest wraps it in a single-member chain to fold frame
    # batches into event tables as they land.
    fusable = True

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({
            "threshold": 0.0,
            "connectivity": 2,
            "max_clusters": events_ops.DEFAULT_MAX_CLUSTERS,
        })
        return conf

    @property
    def events_key(self) -> str:
        return self.output_key + EVENTS_SUFFIX

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        shape = tuple(self.get_shape())
        if len(shape) != 3:
            raise ValueError(
                f"event building expects an (n_frames, h, w) stack, "
                f"got shape {shape}"
            )
        bs = tuple(blocking.block_shape)
        if bs[1] < shape[1] or bs[2] < shape[2]:
            raise ValueError(
                f"block_shape {bs} splits frames of shape {shape[1:]} — "
                f"frames are independent and must stay whole per block "
                f"(use block_shape [frames_per_block, {shape[1]}, "
                f"{shape[2]}])"
            )
        super().prepare(blocking, config)
        store.file_reader(self.output_path, "a").create_ragged_dataset(
            self.events_key, (blocking.n_blocks,), np.float64
        )

    # -- split batch protocol + ctt-hbm contract -----------------------------

    def read_batch(self, block_ids: List[int], blocking: Blocking, config):
        # raw float32 frame read, no halo: threshold/connectivity run on
        # device (or in the property pass), so the upload is shareable
        # across configs and jobs of the same stream
        return read_block_batch(
            self.input_ds(), blocking, block_ids, dtype="float32",
            n_threads=read_threads(config),
            device_source=(self.input_path, self.input_key,
                           ("events-read",), config),
        )

    def upload_batch(self, batch, blocking: Blocking, config):
        hbm.batch_device(batch, config)
        return batch

    def stack_payloads(self, payloads, blocking: Blocking, config):
        return hbm.stack_block_batches(payloads, config)

    def unstack_results(self, result, counts, blocking: Blocking, config):
        batch, labels, evc, evp = result
        return list(zip(
            hbm.split_block_batch(batch, counts),
            hbm.split_stacked(labels, counts),
            hbm.split_stacked(evc, counts),
            hbm.split_stacked(evp, counts),
        ))

    def compute_batch(self, batch, blocking: Blocking, config):
        db = hbm.batch_device(batch, config)
        frames = np.asarray(db.arrays[0])[: db.n]
        B, bf, h, w = frames.shape
        labels, counts, props = events_ops.build_events(
            frames.reshape(B * bf, h, w),
            threshold=float(config.get("threshold", 0.0)),
            connectivity=int(config.get("connectivity", 2)),
            max_clusters=config.get("max_clusters"),
        )
        maxc = props.shape[1]
        return (
            batch,
            labels.reshape(B, bf, h, w),
            counts.reshape(B, bf),
            props.reshape(B, bf, maxc, events_ops.N_PROPS),
        )

    def write_batch(self, result, blocking: Blocking, config):
        batch, labels, counts, props = result
        write_block_batch(
            self.output_ds(), batch, labels, cast="uint32",
            n_threads=read_threads(config),
        )
        ev_ds = store.file_reader(self.output_path, "a")[self.events_key]
        for i, bh in enumerate(batch.blocks):
            # only the block's real frames (the batch pads the frame axis
            # to the static block shape; padded frames carry no clusters
            # by construction but are dropped regardless)
            nf = bh.inner.end[0] - bh.inner.begin[0]
            table = events_ops.event_table(counts[i][:nf], props[i][:nf])
            table[:, 0] += bh.inner.begin[0]  # local -> global frame index
            ev_ds.write_chunk((batch.block_ids[i],), table)

    def _run_batch(self, block_ids: List[int], blocking: Blocking, config):
        self.write_batch(
            self.compute_batch(
                self.read_batch(block_ids, blocking, config), blocking, config
            ),
            blocking, config,
        )

    def process_block(self, block_id, blocking, config):
        self._run_batch([block_id], blocking, config)

    def process_block_batch(self, block_ids, blocking, config):
        self._run_batch(block_ids, blocking, config)


def read_event_tables(output_path: str, output_key: str,
                      n_blocks: int) -> np.ndarray:
    """Concatenate every block's ragged event table (rows sorted by global
    frame index) — the client-side helper tests and the CI smoke use to
    check parity against the scipy oracle."""
    ds = store.file_reader(output_path, "r")[output_key + EVENTS_SUFFIX]
    tables = [ds.read_chunk((bid,)) for bid in range(n_blocks)]
    tables = [t for t in tables if t is not None and len(t)]
    if not tables:
        return np.zeros((0, 1 + events_ops.N_PROPS), np.float64)
    out = np.concatenate(tables, axis=0)
    return out[np.argsort(out[:, 0], kind="stable")]
