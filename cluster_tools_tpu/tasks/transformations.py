"""Intensity transformations (reference transformations/linear.py:24).

``a*x + b`` applied block-wise, with either one global ``(a, b)`` pair or a
per-z-slice table ``{z: {"a": .., "b": ..}}``; an optional mask restricts the
transform to mask voxels.

TPU mapping: the transform is a pure elementwise program — a batch of blocks is
one jit dispatch; per-slice coefficients become a gathered ``[Z]`` coefficient
vector broadcast over the block (no per-slice python loop, unlike the
reference's ``_transform_block``).  (The reference's affine task is an empty
stub — transformations/affine.py, 0 LoC — and is intentionally not built.)
"""

from __future__ import annotations

import json
from functools import partial
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.dispatch import read_block_batch, write_block_batch
from ..runtime import hbm
from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeTask, read_threads


def load_transformation(trafo_file: str, n_slices: int) -> Dict[str, Any]:
    """Global {'a','b'} or per-slice {'0': {'a','b'}, ...} spec
    (reference linear.py:125-139)."""
    with open(trafo_file) as f:
        trafo = json.load(f)
    if set(trafo.keys()) == {"a", "b"}:
        return {"a": float(trafo["a"]), "b": float(trafo["b"])}
    if len(trafo) != n_slices:
        raise ValueError(
            f"per-slice transformation has {len(trafo)} entries, volume has "
            f"{n_slices} slices"
        )
    return {int(k): {"a": float(v["a"]), "b": float(v["b"])}
            for k, v in trafo.items()}


@jax.jit
def _linear_batch(batch, a_z, b_z, mask):
    """batch: [B, Z, Y, X]; a_z/b_z: [B, Z] per-slice coefficients;
    mask: [B, Z, Y, X] bool (all-true when no mask)."""
    out = a_z[:, :, None, None] * batch + b_z[:, :, None, None]
    return jnp.where(mask, out, batch)


class LinearTransformationTask(VolumeTask):
    task_name = "linear"

    def __init__(
        self,
        *args,
        transformation: str = None,
        mask_path: Optional[str] = None,
        mask_key: Optional[str] = None,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.transformation = transformation
        self.mask_path = mask_path
        self.mask_key = mask_key

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        in_ds = self.input_ds()
        f = store.file_reader(self.output_path, "a")
        f.require_dataset(
            self.output_key,
            shape=tuple(blocking.shape),
            dtype=str(in_ds.dtype),
            chunks=tuple(blocking.block_shape),
            compression="gzip",
        )

    def _coefficients(self, blocking: Blocking, block_ids) -> np.ndarray:
        """Per-block per-slice [B, Z] coefficient arrays."""
        n_slices = blocking.shape[0]
        trafo = load_transformation(self.transformation, n_slices)
        bz = blocking.block_shape[0]
        a = np.empty((len(block_ids), bz), dtype=np.float32)
        b = np.empty((len(block_ids), bz), dtype=np.float32)
        if "a" in trafo and isinstance(trafo["a"], float):
            a[:] = trafo["a"]
            b[:] = trafo["b"]
        else:
            for i, bid in enumerate(block_ids):
                z0 = blocking.block(bid).begin[0]
                for dz in range(bz):
                    entry = trafo.get(min(z0 + dz, n_slices - 1))
                    a[i, dz] = entry["a"]
                    b[i, dz] = entry["b"]
        return a, b

    # -- split batch protocol (three-stage executor pipeline) ---------------

    def read_batch(self, block_ids, blocking: Blocking, config):
        # only the input volume routes through the device-buffer cache —
        # coefficients come from the trafo file and the mask from its own
        # dataset, neither covered by the input's store signature
        batch = read_block_batch(
            self.input_ds(), blocking, block_ids, dtype="float32",
            n_threads=read_threads(config),
            device_source=(self.input_path, self.input_key,
                           ("linear-read",), config),
        )
        a, b = self._coefficients(blocking, block_ids)

        full_shape = (len(block_ids),) + tuple(blocking.block_shape)
        if self.mask_path:
            mask_ds = store.file_reader(self.mask_path, "r")[self.mask_key]
            mask = np.zeros(full_shape, dtype=bool)
            for i, bh in enumerate(batch.blocks):
                m = mask_ds[bh.outer.slicing].astype(bool)
                mask[i][tuple(slice(0, s) for s in m.shape)] = m
        else:
            mask = np.ones(full_shape, dtype=bool)
        return batch, a, b, mask

    def upload_batch(self, payload, blocking: Blocking, config):
        batch, a, b, mask = payload
        hbm.batch_device(batch, config)
        return payload

    def stack_payloads(self, payloads, blocking: Blocking, config):
        return (
            hbm.stack_block_batches([p[0] for p in payloads], config),
            np.concatenate([p[1] for p in payloads], axis=0),
            np.concatenate([p[2] for p in payloads], axis=0),
            np.concatenate([p[3] for p in payloads], axis=0),
        )

    def unstack_results(self, result, counts, blocking: Blocking, config):
        batch, out = result
        return list(zip(
            hbm.split_block_batch(batch, counts),
            hbm.split_stacked(out, counts),
        ))

    def compute_batch(self, payload, blocking: Blocking, config):
        batch, a, b, mask = payload
        from ..parallel.mesh import put_sharded

        db = hbm.batch_device(batch, config)
        ab, _ = put_sharded(np.asarray(a), config)
        bb, _ = put_sharded(np.asarray(b), config)
        mb, _ = put_sharded(mask, config)
        out = _linear_batch(db.arrays[0], ab, bb, mb)
        return batch, np.asarray(out)[:db.n]

    def write_batch(self, result, blocking: Blocking, config):
        batch, out = result
        out_ds = self.output_ds()
        write_block_batch(
            out_ds, batch, out, cast=out_ds.dtype,
            n_threads=read_threads(config),
        )

    def _run_batch(self, block_ids, blocking: Blocking, config):
        self.write_batch(
            self.compute_batch(
                self.read_batch(block_ids, blocking, config), blocking, config
            ),
            blocking, config,
        )

    def process_block(self, block_id, blocking, config):
        self._run_batch([block_id], blocking, config)

    def process_block_batch(self, block_ids, blocking, config):
        self._run_batch(block_ids, blocking, config)
