"""Sanity-check tasks (reference debugging/ package), wired into
ProblemWorkflow behind the ``sanity_checks`` flag in the reference
(workflows.py:61-72).

* ``CheckSubGraphsTask`` — per block, the serialized subgraph node list must
  equal a fresh recompute from the watershed volume
  (reference check_sub_graphs.py:21,80-105).
* ``CheckComponentsTask`` — find labels spanning more blocks than physically
  plausible (fragmentation / id-collision smell,
  reference check_components.py:24,95-145).
"""

from __future__ import annotations

import os
from typing import List

import numpy as np

from ..utils.blocking import Blocking
from .base import VolumeTask
from .graph import SUB_NODES_KEY, read_block_with_upper_halo

VIOLATING_IDS_NAME = "check_components_violating_ids.npy"
FAILED_SUBGRAPH_BLOCKS_NAME = "check_sub_graphs_failed_blocks.npy"


class CheckSubGraphsTask(VolumeTask):
    """input = the watershed volume the graph was extracted from."""

    task_name = "check_sub_graphs"
    output_dtype = None

    def run(self) -> None:
        # a check must recompute every block on re-run: a cached failing
        # verdict (per-block done list persisted before finalize raised)
        # would survive a data fix and keep failing forever
        target = self.output()
        status = target.read()
        if status and not status.get("complete", False):
            status["done"] = []
            target.write(status)
        super().run()

    def process_block(self, block_id: int, blocking: Blocking, config):
        seg = read_block_with_upper_halo(
            self.input_ds(), blocking, block_id
        ).astype(np.uint64)
        want = np.unique(seg)
        want = want[want > 0]
        stored = self.tmp_store()[SUB_NODES_KEY].read_chunk((block_id,))
        stored = (
            np.zeros(0, dtype=np.uint64) if stored is None else stored
        )
        ok = stored.size == want.size and np.array_equal(stored, want)
        marks = self.tmp_ragged(
            "debugging/subgraph_ok", blocking.n_blocks, np.int64
        )
        marks.write_chunk((block_id,), np.asarray([int(ok)], dtype=np.int64))

    def finalize(self, blocking, config, block_ids: List[int]) -> None:
        marks = self.tmp_store()["debugging/subgraph_ok"]
        failed = [
            bid
            for bid in block_ids
            if (m := marks.read_chunk((bid,))) is not None and m[0] == 0
        ]
        np.save(
            os.path.join(self.tmp_folder, FAILED_SUBGRAPH_BLOCKS_NAME),
            np.asarray(failed, dtype=np.int64),
        )
        if failed:
            raise RuntimeError(
                f"sub-graph serialization mismatch in blocks {failed[:10]}"
                f"{'...' if len(failed) > 10 else ''}"
            )
        self.log(f"all {len(block_ids)} block sub-graphs verified")


class CheckComponentsTask(VolumeTask):
    """Labels spanning more than ``max_blocks_per_label`` blocks are
    fragmentation suspects (the reference flags labels in more chunks than a
    block contains, check_components.py:95-145).  Block-parallel: per-block
    uniques go to a ragged scratch dataset, the count reduction runs in
    ``finalize``."""

    task_name = "check_components"
    output_dtype = None

    def __init__(self, *args, max_blocks_per_label: int = 8, **kwargs):
        super().__init__(*args, **kwargs)
        self.max_blocks_per_label = max_blocks_per_label

    def process_block(self, block_id: int, blocking: Blocking, config):
        labels = np.unique(
            np.asarray(self.input_ds()[blocking.block(block_id).slicing])
        )
        out = self.tmp_ragged(
            "debugging/block_uniques", blocking.n_blocks, np.uint64
        )
        out.write_chunk((block_id,), labels[labels > 0].astype(np.uint64))

    def finalize(self, blocking, config, block_ids: List[int]) -> None:
        ds = self.tmp_store()["debugging/block_uniques"]
        chunks = []
        for bid in block_ids:
            labels = ds.read_chunk((bid,))
            if labels is not None and labels.size:
                chunks.append(labels)
        if chunks:
            all_labels = np.concatenate(chunks)
            ids, counts = np.unique(all_labels, return_counts=True)
            mask = counts > self.max_blocks_per_label
            violating = np.stack(
                [ids[mask].astype(np.int64), counts[mask].astype(np.int64)],
                axis=1,
            )
        else:
            violating = np.zeros((0, 2), dtype=np.int64)
        np.save(os.path.join(self.tmp_folder, VIOLATING_IDS_NAME), violating)
        self.log(
            f"{violating.shape[0]} labels span more than "
            f"{self.max_blocks_per_label} blocks"
        )
