"""Multiscale NN inference: one prediction fed by center-aligned blocks from
several resolution levels (reference inference/multiscale_inference.py:31).

Geometry: the blocking lives at scale 0; for each coarser level the block's
offset is mapped through the center-alignment rule (multiscale_inference.py
``_center_align_offset``:195-203) so that all levels look at the same physical
center, then read with their own halo and reflect padding.  The predictor
receives the list of per-scale arrays (finest first)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeTask
from .frameworks import get_predictor, get_preprocessor
from .inference import InferenceTask, to_uint8


def center_align_offset(offset, shape, reference_shape, scale_factor):
    """Offset of the same physical center in a downsampled coordinate system
    (reference multiscale_inference.py:195-203)."""
    center_distance = [
        ref_sh // 2 - off for ref_sh, off in zip(reference_shape, offset)
    ]
    center_distance = [d // sf for d, sf in zip(center_distance, scale_factor)]
    return [sh // 2 - d for sh, d in zip(shape, center_distance)]


def load_multiscale_input(ds, offset, block_shape, halo, scale_factor,
                          reference_shape, padding_mode="reflect"):
    shape = ds.shape
    this_offset = center_align_offset(offset, shape, reference_shape, scale_factor)
    this_block_shape = [bs // sf for bs, sf in zip(block_shape, scale_factor)]
    starts = [off - h for off, h in zip(this_offset, halo)]
    stops = [
        off + bs + h for off, bs, h in zip(this_offset, this_block_shape, halo)
    ]
    pad_left = tuple(max(0, -s) for s in starts)
    pad_right = tuple(max(0, st - sh) for st, sh in zip(stops, shape))
    bb = tuple(
        slice(max(0, s), min(sh, st)) for s, st, sh in zip(starts, stops, shape)
    )
    data = np.asarray(ds[bb])
    if any(pad_left) or any(pad_right):
        data = np.pad(
            data,
            [(pl, pr) for pl, pr in zip(pad_left, pad_right)],
            mode=padding_mode,
        )
    return data


class MultiscaleInferenceTask(InferenceTask):
    """Prediction over center-aligned multi-resolution inputs.

    ``input_path``/``input_key`` are lists (finest scale first);
    ``scale_factors`` gives each level's sampling relative to scale 0 and
    ``halos`` each level's halo in its own coordinates."""

    task_name = "multiscale_inference"

    def __init__(self, *args, input_paths: Sequence[str] = (),
                 input_keys: Sequence[str] = (),
                 scale_factors: Sequence[Sequence[int]] = ((1, 1, 1),),
                 halos: Optional[Sequence[Sequence[int]]] = None,
                 **kwargs):
        kwargs.setdefault("input_path", input_paths[0] if input_paths else None)
        kwargs.setdefault("input_key", input_keys[0] if input_keys else None)
        super().__init__(*args, **kwargs)
        self.input_paths = list(input_paths)
        self.input_keys = list(input_keys)
        self.scale_factors = [
            [sf] * 3 if isinstance(sf, int) else list(sf)
            for sf in scale_factors
        ]
        self.halos = (
            [list(h) for h in halos]
            if halos is not None
            else [list(self.halo)] * len(self.scale_factors)
        )
        if not (
            len(self.input_paths)
            == len(self.input_keys)
            == len(self.scale_factors)
            == len(self.halos)
        ):
            raise ValueError("need one path/key/scale_factor/halo per level")

    def get_shape(self) -> Sequence[int]:
        shape = store.file_reader(self.input_paths[0], "r")[
            self.input_keys[0]
        ].shape
        return shape[-3:] if len(shape) > 3 else shape

    def _load_block(self, block_id, blocking, in_ds, mask_ds):
        block = blocking.block(block_id)
        if mask_ds is not None:
            m = np.asarray(mask_ds[block.slicing]).astype(bool)
            if not m.any():
                return None
        datasets = [
            store.file_reader(p, "r")[k]
            for p, k in zip(self.input_paths, self.input_keys)
        ]
        ref_shape = datasets[0].shape
        return [
            load_multiscale_input(
                ds, block.begin, blocking.block_shape, halo, sf, ref_shape
            )
            for ds, sf, halo in zip(datasets, self.scale_factors, self.halos)
        ]

    def process_block_batch(self, block_ids: List[int], blocking: Blocking, config):
        # multiscale inputs are ragged across levels — predict per block
        in_ds = None
        mask_ds = (
            store.file_reader(self.mask_path, "r")[self.mask_key]
            if self.mask_path
            else None
        )
        out_datasets = {
            key: store.file_reader(self.output_path, "a")[key]
            for key in self.output_key_map
        }
        predictor = self.predictor(config)
        preprocess = get_preprocessor(
            config.get("preprocess", "zero_mean_unit_variance")
        )
        for bid in block_ids:
            data = self._load_block(bid, blocking, in_ds, mask_ds)
            if data is None:
                continue
            out = predictor([preprocess(d) for d in data])
            self._write_block(bid, blocking, out_datasets, out, config)
