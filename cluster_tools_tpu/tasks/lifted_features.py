"""Sparse lifted-edge construction from biological priors.

Reference lifted_features/*.py (SURVEY.md §2.3): BFS lifted neighborhood to a
graph depth restricted to semantically labeled nodes
(``ndist.computeLiftedNeighborhoodFromNodeLabels``,
sparse_lifted_neighborhood.py:132-137), attractive/repulsive lifted costs from
same/different node labels (costs_from_node_labels.py:25), clearing lifted
edges touching given labels (clear_lifted_edges_from_labels.py:23), and merging
several lifted problems (merge_lifted_problems.py:23).

File layout in ``tmp_folder`` (one lifted problem per ``prefix``):
  lifted_problem_{prefix}.npz   uv [L,2] dense node indices, costs [L]
"""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np

from ..ops.lifted import (
    lifted_costs_from_node_labels,
    lifted_neighborhood,
    merge_lifted_problems,
)
from .base import VolumeSimpleTask
from .graph import load_graph
from .node_labels import NODE_LABELS_NAME


def lifted_problem_path(tmp_folder: str, prefix: str) -> str:
    return os.path.join(tmp_folder, f"lifted_problem_{prefix}.npz")


def load_lifted_problem(tmp_folder: str, prefix: str):
    """Returns (lifted_uv [L,2] dense indices, costs [L])."""
    with np.load(lifted_problem_path(tmp_folder, prefix)) as f:
        return f["uv"], f["costs"]


def save_lifted_problem(tmp_folder: str, prefix: str, uv, costs) -> None:
    np.savez(
        lifted_problem_path(tmp_folder, prefix),
        uv=np.asarray(uv, dtype=np.int64).reshape(-1, 2),
        costs=np.asarray(costs, dtype=np.float64),
    )


def dense_node_labels(task, nodes: np.ndarray, labels_path: str = None) -> np.ndarray:
    """Per-graph-node semantic labels.  Reads the merged node-label table
    (tasks/node_labels.py) by default, or an explicit .npy (dense [n] array or
    [k,2] (node, label) table)."""
    path = labels_path or os.path.join(task.tmp_folder, NODE_LABELS_NAME)
    table = np.load(path)
    if table.ndim == 1:
        # the dense array is indexed by node *label value*, which has gaps —
        # it must cover max(nodes), not just count nodes.size entries
        max_node = int(nodes.max()) if nodes.size else -1
        if table.size <= max_node:
            raise ValueError(
                f"dense node-label array has {table.size} entries but the "
                f"largest graph node id is {max_node}"
            )
        return table[nodes.astype(np.int64)]
    out = np.zeros(nodes.size, dtype=np.int64)
    idx = np.searchsorted(nodes, table[:, 0].astype(nodes.dtype))
    valid = (idx < nodes.size)
    valid &= nodes[np.clip(idx, 0, nodes.size - 1)] == table[:, 0].astype(nodes.dtype)
    out[idx[valid]] = table[valid, 1].astype(np.int64)
    return out


class SparseLiftedNeighborhoodTask(VolumeSimpleTask):
    """Lifted edges between labeled nodes within a graph depth
    (reference sparse_lifted_neighborhood.py:24)."""

    task_name = "sparse_lifted_neighborhood"

    def __init__(self, *args, prefix: str = "lifted",
                 node_labels_path: str = None, **kwargs):
        super().__init__(*args, prefix=prefix,
                         node_labels_path=node_labels_path, **kwargs)

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_{self.prefix}"

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"nh_graph_depth": 2, "ignore_label": 0})
        return conf

    def run_impl(self) -> None:
        conf = self.get_task_config()
        nodes, edges = load_graph(self.tmp_store())
        node_labels = dense_node_labels(self, nodes, self.node_labels_path)
        ignore = conf.get("ignore_label", 0)
        participating = (
            np.ones(nodes.size, dtype=bool)
            if ignore is None
            else node_labels != ignore
        )
        uv = lifted_neighborhood(
            nodes.size, edges, participating,
            depth=int(conf.get("nh_graph_depth", 2)),
        )
        save_lifted_problem(self.tmp_folder, self.prefix, uv, np.zeros(uv.shape[0]))
        self.log(
            f"lifted neighborhood '{self.prefix}': {uv.shape[0]} lifted edges "
            f"over {int(participating.sum())} labeled nodes "
            f"(depth {conf.get('nh_graph_depth', 2)})"
        )


class LiftedCostsFromNodeLabelsTask(VolumeSimpleTask):
    """± lifted costs from node-label agreement
    (reference costs_from_node_labels.py:25)."""

    task_name = "costs_from_node_labels"

    def __init__(self, *args, prefix: str = "lifted",
                 node_labels_path: str = None, **kwargs):
        super().__init__(*args, prefix=prefix,
                         node_labels_path=node_labels_path, **kwargs)

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_{self.prefix}"

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {"same_cost": 2.0, "different_cost": -2.0, "ignore_label": 0}
        )
        return conf

    def run_impl(self) -> None:
        conf = self.get_task_config()
        nodes, _ = load_graph(self.tmp_store())
        node_labels = dense_node_labels(self, nodes, self.node_labels_path)
        uv, _ = load_lifted_problem(self.tmp_folder, self.prefix)
        uv, costs = lifted_costs_from_node_labels(
            uv, node_labels,
            same_cost=float(conf.get("same_cost", 2.0)),
            different_cost=float(conf.get("different_cost", -2.0)),
            ignore_label=conf.get("ignore_label", 0),
        )
        save_lifted_problem(self.tmp_folder, self.prefix, uv, costs)
        self.log(
            f"lifted costs '{self.prefix}': {uv.shape[0]} edges, "
            f"{int((costs > 0).sum())} attractive / {int((costs < 0).sum())} repulsive"
        )


class ClearLiftedEdgesFromLabelsTask(VolumeSimpleTask):
    """Drop lifted edges whose endpoints carry one of the given labels
    (reference clear_lifted_edges_from_labels.py:23)."""

    task_name = "clear_lifted_edges_from_labels"

    def __init__(self, *args, prefix: str = "lifted",
                 node_labels_path: str = None, clear_labels=(), **kwargs):
        super().__init__(*args, prefix=prefix,
                         node_labels_path=node_labels_path,
                         clear_labels=tuple(clear_labels), **kwargs)

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_{self.prefix}"

    def run_impl(self) -> None:
        nodes, _ = load_graph(self.tmp_store())
        node_labels = dense_node_labels(self, nodes, self.node_labels_path)
        uv, costs = load_lifted_problem(self.tmp_folder, self.prefix)
        clear = np.asarray(self.clear_labels, dtype=node_labels.dtype)
        bad = np.isin(node_labels[uv[:, 0]], clear) | np.isin(
            node_labels[uv[:, 1]], clear
        )
        save_lifted_problem(self.tmp_folder, self.prefix, uv[~bad], costs[~bad])
        self.log(f"cleared {int(bad.sum())}/{uv.shape[0]} lifted edges")


class MergeLiftedProblemsTask(VolumeSimpleTask):
    """Sum-merge several lifted problems (reference merge_lifted_problems.py:23)."""

    task_name = "merge_lifted_problems"

    def __init__(self, *args, prefixes=(), out_prefix: str = "lifted", **kwargs):
        super().__init__(*args, prefixes=tuple(prefixes), out_prefix=out_prefix,
                         **kwargs)

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_{self.out_prefix}"

    def run_impl(self) -> None:
        problems = [
            load_lifted_problem(self.tmp_folder, p) for p in self.prefixes
        ]
        uv, costs = merge_lifted_problems(problems)
        save_lifted_problem(self.tmp_folder, self.out_prefix, uv, costs)
        self.log(
            f"merged {len(problems)} lifted problems → {uv.shape[0]} edges"
        )
