"""Per-segment intensity statistics + block-wise image filter bank.

* ``RegionFeaturesTask`` / ``MergeRegionFeaturesTask`` — per-block segment
  statistics over an intensity volume (reference features/region_features.py
  via ``vigra.analysis.extractRegionFeatures`` and merge_region_features.py),
  computed as device segment reductions (ops/segment.py) and merged exactly:
  counts add, means count-weight, min/max reduce.
* ``ImageFilterTask`` — halo'd filter-bank response volume (reference
  features/image_filter.py via fastfilters), one batched jit dispatch per
  block batch through ops/filters.apply_filter.

Scratch layout:
  region_features/partial   ragged per block: (id, count, mean, min, max) rows
  region_features.npy       merged dense [max_id+1, 4] (count, mean, min, max)
"""

from __future__ import annotations

import os
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import filters as filter_ops
from ..ops.segment import segment_count, segment_max, segment_mean, segment_min
from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, merge_threads, read_ragged_chunks, resolve_n_blocks

PARTIAL_KEY = "region_features/partial"
REGION_FEATURES_NAME = "region_features.npy"
FEATURE_COLUMNS = ("count", "mean", "minimum", "maximum")


class RegionFeaturesTask(VolumeTask):
    """Per-block segment statistics (reference region_features.py:25)."""

    task_name = "region_features"
    output_dtype = None

    def __init__(self, *args, labels_path: str = None, labels_key: str = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.labels_path = labels_path
        self.labels_key = labels_key

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"channel": None, "ignore_label": 0})
        return conf

    def process_block(self, block_id: int, blocking: Blocking, config):
        bb = blocking.block(block_id).slicing
        in_ds = self.input_ds()
        labels = np.asarray(
            store.file_reader(self.labels_path, "r")[self.labels_key][bb]
        )
        channel = config.get("channel")
        read_bb = bb if channel is None else (channel,) + bb
        values = np.asarray(in_ds[read_bb], dtype=np.float32)
        # global normalization by the dtype range so statistics are comparable
        # across storage dtypes (reference region_features.py:151-157 handles
        # only uint8; integer inputs here all map to [0, 1])
        if np.issubdtype(np.dtype(in_ds.dtype), np.integer):
            values /= float(np.iinfo(np.dtype(in_ds.dtype)).max)

        out = self.tmp_ragged(PARTIAL_KEY, blocking.n_blocks, np.float64)
        ignore_label = config.get("ignore_label")
        mask = np.ones(labels.shape, dtype=bool)
        if ignore_label is not None:
            mask = labels != ignore_label
        ids = np.unique(labels[mask]) if mask.any() else np.array([], "uint64")
        if ids.size == 0:
            out.write_chunk((block_id,), np.zeros(0, dtype=np.float64))
            return

        # compact per-block ids for the device reductions
        local = np.searchsorted(ids, labels).clip(0, ids.size - 1)
        local = np.where(mask & (labels == ids[local]), local + 1, 0)
        k = ids.size + 1
        lab_j = jnp.asarray(local.astype(np.int32)).reshape(-1)
        val_j = jnp.asarray(values).reshape(-1)
        count = np.asarray(segment_count(lab_j, k))[1:]
        mean = np.asarray(segment_mean(lab_j, val_j, k))[1:]
        mn = np.asarray(segment_min(lab_j, val_j, k))[1:]
        mx = np.asarray(segment_max(lab_j, val_j, k))[1:]

        rows = np.stack(
            [ids.astype(np.float64), count, mean, mn, mx], axis=1
        )
        out.write_chunk((block_id,), rows.reshape(-1))


class MergeRegionFeaturesTask(VolumeSimpleTask):
    """Exact cross-block merge (reference merge_region_features.py:20)."""

    task_name = "merge_region_features"

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 **kwargs):
        super().__init__(*args, input_path=input_path, input_key=input_key,
                         **kwargs)

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(self.config_dir, self.input_path, self.input_key)
        ds = self.tmp_store()[PARTIAL_KEY]
        n_cols = len(FEATURE_COLUMNS) + 1
        partials = []
        for chunk in read_ragged_chunks(ds, n_blocks, merge_threads(self)):
            if chunk is not None and chunk.size:
                partials.append(chunk.reshape(-1, n_cols))
        if not partials:
            np.save(os.path.join(self.tmp_folder, REGION_FEATURES_NAME),
                    np.zeros((0, len(FEATURE_COLUMNS))))
            return
        rows = np.concatenate(partials, axis=0)
        ids = rows[:, 0].astype(np.int64)
        max_id = int(ids.max())
        out = np.zeros((max_id + 1, len(FEATURE_COLUMNS)), dtype=np.float64)
        count = np.zeros(max_id + 1)
        wsum = np.zeros(max_id + 1)
        mn = np.full(max_id + 1, np.inf)
        mx = np.full(max_id + 1, -np.inf)
        np.add.at(count, ids, rows[:, 1])
        np.add.at(wsum, ids, rows[:, 1] * rows[:, 2])
        np.minimum.at(mn, ids, rows[:, 3])
        np.maximum.at(mx, ids, rows[:, 4])
        seen = count > 0
        out[:, 0] = count
        out[seen, 1] = wsum[seen] / count[seen]
        out[seen, 2] = mn[seen]
        out[seen, 3] = mx[seen]
        np.save(os.path.join(self.tmp_folder, REGION_FEATURES_NAME), out)
        self.log(f"merged region features for {int(seen.sum())} segments")


def load_region_features(tmp_folder: str) -> np.ndarray:
    return np.load(os.path.join(tmp_folder, REGION_FEATURES_NAME))


class ImageFilterTask(VolumeTask):
    """Filter-response volume (reference features/image_filter.py:24)."""

    task_name = "image_filter"

    def __init__(self, *args, filter_name: str = "gaussianSmoothing",
                 sigma=2.0, halo: Sequence[int] = None,
                 apply_in_2d: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self.filter_name = filter_name
        self.sigma = sigma
        self.apply_in_2d = apply_in_2d
        self.halo = (
            list(halo)
            if halo is not None
            else [int(np.ceil(4 * (self.sigma if np.isscalar(self.sigma)
                                   else max(self.sigma))))] * 3
        )

    @property
    def identifier(self) -> str:
        # every parameter that changes the output must land in the identifier,
        # or a second filter in the same tmp_folder is skipped as complete
        sig = (
            str(self.sigma)
            if np.isscalar(self.sigma)
            else "x".join(str(s) for s in self.sigma)
        )
        suffix = "_2d" if self.apply_in_2d else ""
        out = str(self.output_key or "").replace("/", "-")
        return f"{self.task_name}_{self.filter_name}_{sig}{suffix}_{out}"

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        n_chan = filter_ops.filter_channels(
            self.filter_name, apply_in_2d=self.apply_in_2d
        )
        shape = tuple(blocking.shape)
        chunks = tuple(blocking.block_shape)
        if n_chan > 1:
            shape = (n_chan,) + shape
            chunks = (1,) + chunks
        store.file_reader(self.output_path, "a").require_dataset(
            self.output_key, shape=shape, dtype="float32",
            chunks=tuple(min(c, s) for c, s in zip(chunks, shape)),
            compression="gzip",
        )

    def process_block(self, block_id: int, blocking: Blocking, config):
        bh = blocking.block_with_halo(block_id, self.halo)
        x = np.asarray(self.input_ds()[bh.outer.slicing], dtype=np.float32)
        resp = np.asarray(
            filter_ops.apply_filter(
                jnp.asarray(x), self.filter_name, self.sigma,
                apply_in_2d=self.apply_in_2d,
            )
        )
        out_ds = self.output_ds()
        local = bh.inner_local.slicing
        if resp.ndim == x.ndim + 1:  # multi-channel response (channels last)
            resp = np.moveaxis(resp, -1, 0)
            out_ds[(slice(None),) + bh.inner.slicing] = resp[
                (slice(None),) + local
            ]
        else:
            out_ds[bh.inner.slicing] = resp[local]
