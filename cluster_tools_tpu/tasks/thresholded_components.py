"""Distributed connected components over thresholded volumes.

The reference pipeline (SURVEY.md §3.4, thresholded_components/*.py):

  1. block_components  — per block: threshold (+smooth) → CC label → write local
                         labels, record per-block max id
  2. merge_offsets     — exclusive prefix sum of max ids → per-block offsets
  3. block_faces       — per inter-block face: touching (a+off_a, b+off_b) label
                         pairs
  4. merge_assignments — union-find over all pairs → dense assignment table
  5. write             — apply offsets + assignment (tasks/write.py)

Here step 1 is a device-batched jit program (CC is the pointer-jumping kernel,
one dispatch per block batch); steps 2/4 are host reductions (1-job merge tasks
in the reference too); step 3 reads thin face slabs host-side.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List

import jax
import numpy as np

from ..ops import cc as cc_ops
from ..ops import filters
from ..ops.unionfind import merge_assignments_device, merge_assignments_np
from ..parallel.dispatch import read_block_batch, write_block_batch
from ..runtime import hbm
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, merge_threads, read_ragged_chunks, read_threads

MAX_IDS_KEY = "thresholded_components/max_ids"
FACES_KEY = "thresholded_components/faces"
OFFSETS_NAME = "thresholded_components_offsets.npz"
ASSIGNMENTS_NAME = "thresholded_components_assignments.npy"


@partial(
    jax.jit, static_argnames=("mode", "sigma", "connectivity", "coarse_tile")
)
def _components_batch(batch, threshold, mode, sigma, connectivity,
                      coarse_tile=None):
    x = batch
    if sigma:
        x = jax.vmap(lambda b: filters.gaussian(b, sigma))(x)
    if mode == "greater":
        mask = x > threshold
    elif mode == "less":
        mask = x < threshold
    else:
        mask = x == threshold
    labels, n = jax.vmap(
        lambda m: cc_ops.connected_components(
            m, connectivity, coarse_tile=coarse_tile
        )
    )(mask)
    return labels, n


class BlockComponentsTask(VolumeTask):
    """Step 1: per-block CC with local consecutive labels
    (reference block_components.py:25)."""

    task_name = "block_components"
    output_dtype = "uint64"

    def __init__(self, *args, mask_path: str = None, mask_key: str = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.mask_path = mask_path
        self.mask_key = mask_key

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {
                "threshold": 0.5,
                "threshold_mode": "greater",
                "sigma": 0.0,
                "connectivity": 1,
                # ctt-cc coarse-to-fine tile (None = CTT_CC_TILE env pin /
                # backend default — see ops/cc.resolve_coarse_tile)
                "coarse_tile": None,
            }
        )
        return conf

    # -- ctt-stream fusion contract ------------------------------------------
    #
    # As a fused-chain member this task (a) consumes the upstream threshold
    # mask as a device handoff — the mask never round-trips through the
    # store — and (b) carries the downstream merge state forward while its
    # labels are still in memory: per-block max ids (the merge-offsets
    # input) and face-edge equivalence tables (the block-faces output, the
    # same (a, b) value-pair format ops/unionfind.merge_value_table
    # resolves device-side for ctt-cc tile faces).  The chain's ``covers``
    # list then stamps MergeOffsetsTask/BlockFacesTask complete without
    # either re-reading one voxel of the labels volume.

    fusable = True

    def fused_read_batch(self, handoffs, block_ids, blocking: Blocking,
                         config):
        """Payload from the upstream threshold handoff: the device mask
        replaces the store read of the mask dataset (which may be elided
        and never exist).  uint8 0/1 values compare against the 0.5
        default threshold exactly like the float32 store read would."""
        h = handoffs[(self.input_path, self.input_key)]
        from ..parallel.dispatch import BlockBatch

        batch = BlockBatch(
            data=h["labels"], valid=None,
            blocks=list(h["batch"].blocks),
            block_ids=list(h["batch"].block_ids),
        )
        if self.mask_path:
            from ..utils import store as _store

            mask_ds = _store.file_reader(self.mask_path, "r")[self.mask_key]
            masks = [
                mask_ds[bh.outer.slicing].astype(bool) for bh in batch.blocks
            ]
        else:
            masks = None
        return batch, masks

    def fusion_carry_init(self, blocking: Blocking, config):
        return {
            "max_ids": np.zeros(blocking.n_blocks, dtype=np.int64),
            "planes": {},  # (block_id, axis) -> the block's last label plane
            "pairs": {},   # block_id -> axis -> (lo_vals, hi_vals) int64
        }

    def fusion_carry_update(self, carry, result, block_ids,
                            blocking: Blocking, config):
        """Per-slab carry: record each block's max id and its upper
        boundary planes; resolve faces against the carried plane of the
        lower neighbor (already processed — block ids stream in ascending
        C-order, so the carry window is one slab of planes).  Pair values
        stay block-local; offsets are applied at finalize, after the last
        slab fixes the global offset table."""
        if result is None:
            return carry
        batch, labels = result
        for i, bid in enumerate(batch.block_ids):
            bh = batch.blocks[i]
            lab = labels[i][bh.inner_local.slicing]
            carry["max_ids"][bid] = int(lab.max())
            for axis in range(blocking.ndim):
                if blocking.neighbor_id(bid, axis, lower=False) is not None:
                    carry["planes"][(bid, axis)] = np.take(
                        lab, lab.shape[axis] - 1, axis=axis
                    ).astype(np.int64)
                nb = blocking.neighbor_id(bid, axis, lower=True)
                if nb is not None:
                    lo = carry["planes"].pop((nb, axis))
                    hi = np.take(lab, 0, axis=axis).astype(np.int64)
                    both = (lo > 0) & (hi > 0)
                    if both.any():
                        carry["pairs"].setdefault(nb, {})[axis] = (
                            lo[both], hi[both]
                        )
        return carry

    def fusion_carry_nbytes(self, carry) -> int:
        n = carry["max_ids"].nbytes
        n += sum(a.nbytes for a in carry["planes"].values())
        n += sum(
            lo.nbytes + hi.nbytes
            for per_axis in carry["pairs"].values()
            for lo, hi in per_axis.values()
        )
        return n

    def fusion_finalize(self, carry, blocking: Blocking, config) -> None:
        """Write the carried merge state in the exact shape the downstream
        tasks would have produced: the offsets npz (MergeOffsetsTask) and
        one FACES_KEY chunk per block (BlockFacesTask) — byte-identical
        pair tables, so MergeAssignmentsTask and WriteTask run unchanged."""
        import os

        if carry is None:
            return
        max_ids = carry["max_ids"]
        offsets = np.roll(np.cumsum(max_ids), 1)
        offsets[0] = 0
        empty_blocks = np.nonzero(max_ids == 0)[0]
        np.savez(
            os.path.join(self.tmp_folder, OFFSETS_NAME),
            offsets=offsets,
            empty_blocks=empty_blocks,
            n_labels=np.int64(max_ids.sum()),
        )
        faces = self.tmp_ragged(FACES_KEY, blocking.n_blocks, np.int64)
        for bid in range(blocking.n_blocks):
            parts = []
            for axis, ngb_id, _face in blocking.iterate_faces(bid, halo=1):
                got = carry["pairs"].get(bid, {}).get(axis)
                if got is None:
                    continue
                lo, hi = got
                a = lo + offsets[bid]
                b = hi + offsets[ngb_id]
                parts.append(np.unique(np.stack([a, b], axis=1), axis=0))
            out = (
                np.concatenate(parts, axis=0).reshape(-1)
                if parts
                else np.array([], dtype=np.int64)
            )
            faces.write_chunk((bid,), out)

    # -- split batch protocol (three-stage executor pipeline) ---------------

    def read_batch(self, block_ids: List[int], blocking: Blocking, config):
        # the device cache covers ONLY the input upload (masks are applied
        # host-side after compute, so mask freshness never rides the key)
        batch = read_block_batch(
            self.input_ds(), blocking, block_ids, dtype="float32",
            n_threads=read_threads(config),
            device_source=(self.input_path, self.input_key,
                           ("components-read",), config),
        )
        if self.mask_path:
            from ..utils import store as _store

            mask_ds = _store.file_reader(self.mask_path, "r")[self.mask_key]
            masks = [
                mask_ds[bh.outer.slicing].astype(bool) for bh in batch.blocks
            ]
        else:
            masks = None
        return batch, masks

    def upload_batch(self, payload, blocking: Blocking, config):
        batch, masks = payload
        hbm.batch_device(batch, config)
        return payload

    def stack_payloads(self, payloads, blocking: Blocking, config):
        masks = None
        if any(p[1] is not None for p in payloads):
            masks = [m for p in payloads for m in (p[1] or [])]
        return hbm.stack_block_batches(
            [p[0] for p in payloads], config
        ), masks

    def unstack_results(self, result, counts, blocking: Blocking, config):
        batch, labels = result
        return list(zip(
            hbm.split_block_batch(batch, counts),
            hbm.split_stacked(labels, counts),
        ))

    def compute_batch(self, payload, blocking: Blocking, config):
        batch, masks = payload
        sigma = config.get("sigma", 0.0) or 0.0
        if isinstance(sigma, list):
            sigma = tuple(sigma)
        db = hbm.batch_device(batch, config)
        n = db.n
        coarse_tile = config.get("coarse_tile", None)
        if coarse_tile is not None and not isinstance(coarse_tile, int):
            coarse_tile = tuple(coarse_tile)
        labels, _ = _components_batch(
            db.arrays[0],
            float(config.get("threshold", 0.5)),
            config.get("threshold_mode", "greater"),
            sigma,
            int(config.get("connectivity", 1)),
            coarse_tile,
        )
        labels = np.array(labels[:n])  # writable host copy (mask edit below)
        if masks is not None:
            for i, m in enumerate(masks):
                sl = tuple(slice(0, s) for s in m.shape)
                labels[i][sl] = np.where(m, labels[i][sl], 0)
        return batch, labels

    def write_batch(self, result, blocking: Blocking, config):
        batch, labels = result
        write_block_batch(
            self.output_ds(), batch, labels, cast="uint64",
            n_threads=read_threads(config),
        )
        max_ids = self.tmp_ragged(MAX_IDS_KEY, blocking.n_blocks, np.int64)
        for i, bid in enumerate(batch.block_ids):
            bh = batch.blocks[i]
            inner = labels[i][bh.inner_local.slicing]
            max_ids.write_chunk((bid,), np.array([inner.max()], dtype=np.int64))

    def _run_batch(self, block_ids: List[int], blocking: Blocking, config):
        self.write_batch(
            self.compute_batch(
                self.read_batch(block_ids, blocking, config), blocking, config
            ),
            blocking, config,
        )

    def process_block(self, block_id, blocking, config):
        self._run_batch([block_id], blocking, config)

    def process_block_batch(self, block_ids, blocking, config):
        self._run_batch(block_ids, blocking, config)


class MergeOffsetsTask(VolumeSimpleTask):
    """Step 2: exclusive prefix sum of per-block max ids
    (reference merge_offsets.py:96-125)."""

    task_name = "merge_offsets"

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 **kwargs):
        super().__init__(*args, input_path=input_path, input_key=input_key,
                         **kwargs)

    def run_impl(self) -> None:
        import os

        from .base import resolve_n_blocks

        n_blocks = resolve_n_blocks(self.config_dir, self.input_path, self.input_key)
        max_ids_ds = self.tmp_store()[MAX_IDS_KEY]
        max_ids = np.zeros(n_blocks, dtype=np.int64)
        for bid, chunk in enumerate(
            read_ragged_chunks(max_ids_ds, n_blocks, merge_threads(self))
        ):
            if chunk is not None:
                max_ids[bid] = chunk[0]
        offsets = np.roll(np.cumsum(max_ids), 1)
        offsets[0] = 0
        empty_blocks = np.nonzero(max_ids == 0)[0]
        out = os.path.join(self.tmp_folder, OFFSETS_NAME)
        np.savez(
            out,
            offsets=offsets,
            empty_blocks=empty_blocks,
            n_labels=np.int64(max_ids.sum()),
        )


def load_offsets(tmp_folder: str):
    import os

    with np.load(os.path.join(tmp_folder, OFFSETS_NAME)) as f:
        return f["offsets"], f["empty_blocks"], int(f["n_labels"])


class BlockFacesTask(VolumeTask):
    """Step 3: cross-block label equivalences over 1-voxel-halo faces
    (reference block_faces.py:87-137)."""

    task_name = "block_faces"
    output_dtype = None  # writes only scratch data

    def process_block(self, block_id: int, blocking: Blocking, config):
        labels_ds = self.input_ds()
        offsets, _, _ = load_offsets(self.tmp_folder)
        pairs = []
        for axis, ngb_id, face in blocking.iterate_faces(block_id, halo=1):
            slab = labels_ds[face.slicing]
            lo, hi = np.split(slab, 2, axis=axis)
            both = (lo > 0) & (hi > 0)
            if not both.any():
                continue
            a = lo[both].astype(np.int64) + offsets[block_id]
            b = hi[both].astype(np.int64) + offsets[ngb_id]
            pairs.append(np.unique(np.stack([a, b], axis=1), axis=0))
        faces = self.tmp_ragged(FACES_KEY, blocking.n_blocks, np.int64)
        out = (
            np.concatenate(pairs, axis=0).reshape(-1)
            if pairs
            else np.array([], dtype=np.int64)
        )
        faces.write_chunk((block_id,), out)


class MergeAssignmentsTask(VolumeSimpleTask):
    """Step 4: global union-find over face pairs → dense assignment table
    (reference merge_assignments.py:88-146)."""

    task_name = "merge_assignments"

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 **kwargs):
        super().__init__(*args, input_path=input_path, input_key=input_key,
                         **kwargs)

    def run_impl(self) -> None:
        import os

        from .base import resolve_n_blocks

        n_blocks = resolve_n_blocks(self.config_dir, self.input_path, self.input_key)
        _, _, n_labels = load_offsets(self.tmp_folder)
        faces = self.tmp_store()[FACES_KEY]
        all_pairs = []
        for chunk in read_ragged_chunks(faces, n_blocks, merge_threads(self)):
            if chunk is not None and chunk.size:
                all_pairs.append(chunk.reshape(-1, 2))
        pairs = (
            np.concatenate(all_pairs, axis=0)
            if all_pairs
            else np.zeros((0, 2), dtype=np.int64)
        )
        conf = {**self.global_config(), **self.get_task_config()}
        merge = (
            merge_assignments_device
            if conf.get("target") == "tpu"
            else merge_assignments_np
        )
        assignment, n_new = merge(n_labels + 1, pairs)
        np.save(os.path.join(self.tmp_folder, ASSIGNMENTS_NAME), assignment)
        self.log(f"merged {n_labels} block-local labels into {n_new} components")


def _np_smooth(raw: np.ndarray, sigma) -> np.ndarray:
    from scipy import ndimage as _ndi

    return _ndi.gaussian_filter(raw.astype("float32"), sigma)


def _threshold_host(raw: np.ndarray, threshold: float, mode: str) -> np.ndarray:
    if mode == "greater":
        return raw > threshold
    if mode == "less":
        return raw < threshold
    return raw == threshold


class ShardedComponentsTask(VolumeSimpleTask):
    """Whole-volume connected components over the device mesh in ONE jit
    program — the collective alternative to the 5-step block pipeline above.

    At ``sigma == 0`` (the default) the input streams from the store shard-
    by-shard and each shard thresholds on host inside the placement
    callback (``mesh.put_from_store(transform=...)``) — peak host RAM on
    the ingest side is one shard and only the 1-byte/voxel bool mask ever
    reaches HBM; with smoothing the full volume is gaussian-filtered on
    host (scipy) first and the boolean mask crosses whole.  Labeling is
    ``parallel.sharded.sharded_connected_components`` (per-shard sweeps +
    ppermute'd boundary planes + psum convergence): the cross-block merge
    that steps 2-4 route through the filesystem happens entirely over ICI.
    Bounds: the labels round-trip through host for the consecutive relabel
    (int32/voxel), and the mask must fit the mesh's aggregate HBM; the
    block pipeline remains the truly out-of-core path.  Output is consecutive
    uint64 labels (background 0) matching the block pipeline's partition at
    ``sigma == 0``; with smoothing the two differ at block borders by design
    — the block path smooths each halo-less block (truncating the filter at
    every block boundary, as the reference's block_components does), while
    this path smooths the whole volume seamlessly.
    """

    task_name = "sharded_components"
    collective = True

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 output_path: str = None, output_key: str = None,
                 mask_path: str = None, mask_key: str = None, **kwargs):
        super().__init__(
            *args, input_path=input_path, input_key=input_key,
            output_path=output_path, output_key=output_key,
            mask_path=mask_path, mask_key=mask_key, **kwargs,
        )

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {"threshold": 0.5, "threshold_mode": "greater", "sigma": 0.0,
             "connectivity": 1,
             # ctt-stream: threshold on DEVICE, fused into the collective
             # CC program (parallel.sharded.fused_threshold_components) —
             # HBM holds the float volume instead of the bool mask, but
             # the mask never crosses the host boundary.  Only greater-
             # mode, sigma 0, unmasked; other settings keep the
             # host-threshold ingest transform.
             "device_threshold": False}
        )
        return conf

    def run_impl(self) -> None:
        import jax

        from ..parallel.mesh import (
            get_mesh,
            put_from_store,
            put_global,
            resolve_devices,
        )
        from ..parallel.sharded import sharded_connected_components
        from ..utils import store as store_mod

        conf = {**self.global_config(), **self.get_task_config()}
        mode = conf.get("threshold_mode", "greater")
        if mode not in ("greater", "less", "equal"):
            raise ValueError(f"unsupported threshold_mode {mode!r}")
        in_ds = store_mod.file_reader(self.input_path, "r")[self.input_key]
        store_mod.set_read_threads(in_ds, read_threads(conf))
        z = int(in_ds.shape[0])
        devices = resolve_devices(conf)
        mesh = get_mesh(devices)
        n_dev = len(devices)
        threshold = float(conf.get("threshold", 0.5))
        sigma = conf.get("sigma", 0.0) or 0.0  # scalar or per-axis sequence

        device_threshold = (
            bool(conf.get("device_threshold", False))
            and mode == "greater"
            and threshold >= 0
            and not self.mask_path
            and not np.any(np.asarray(sigma) > 0)
        )
        if device_threshold:
            # ctt-stream collective fusion: the raw volume streams to HBM
            # and thresholds there, feeding the CC program directly — the
            # mask intermediate never exists host-side
            from ..parallel.mesh import fetch_global
            from ..parallel.sharded import fused_threshold_components

            x_d = put_from_store(
                in_ds, mesh, dtype=np.float32, pad_to=n_dev,
            )
            raw_labels = fetch_global(
                fused_threshold_components(
                    x_d, threshold, mesh=mesh,
                    connectivity=int(conf.get("connectivity", 1)),
                )
            )[:z]
            self._write_labels(raw_labels, conf, n_dev)
            return

        if np.any(np.asarray(sigma) > 0):
            # smoothing runs on host over the full volume (scipy) — the
            # full-copy path; sigma == 0 streams instead (below)
            raw = _np_smooth(in_ds[:], sigma)
            mask = _threshold_host(raw, threshold, mode)
            del raw
            if self.mask_path:
                m = store_mod.file_reader(self.mask_path, "r")[self.mask_key]
                mask &= m[:].astype(bool)
            pad = (-z) % n_dev
            if pad:
                mask = np.pad(mask, ((0, pad),) + ((0, 0),) * (mask.ndim - 1))
            mask_d = put_global(mask, mesh, dtype=bool)
            del mask
        else:
            # stream shard-by-shard from the store, thresholding each shard
            # on host inside the read callback: peak host RAM is one shard
            # and only the 1-byte/voxel bool mask ever crosses to HBM
            # (ADVICE r2; the zero pad slab is bool False by construction,
            # so no pad-foreground guard is needed for any mode)
            mask_d = put_from_store(
                in_ds, mesh, dtype=bool, pad_to=n_dev,
                transform=lambda part: _threshold_host(
                    part.astype("float32"), threshold, mode
                ),
            )
            if self.mask_path:
                m_ds = store_mod.file_reader(self.mask_path, "r")[self.mask_key]
                m_d = put_from_store(m_ds, mesh, dtype=bool, pad_to=n_dev)
                mask_d = jax.jit(jax.numpy.logical_and)(mask_d, m_d)

        from ..parallel.mesh import fetch_global

        raw_labels = fetch_global(
            sharded_connected_components(
                mask_d, mesh=mesh,
                connectivity=int(conf.get("connectivity", 1)),
            )
        )[:z]
        self._write_labels(raw_labels, conf, n_dev)

    def _write_labels(self, raw_labels, conf, n_dev: int) -> None:
        """Relabel + write the collective CC result (shared by the
        host-threshold and device-threshold/fused ingest paths)."""
        import jax

        from ..utils import store as store_mod

        if jax.process_index() != 0:
            return  # process 0 owns the writes

        # consecutive uint64 ids in root order (matches the block pipeline's
        # relabeling up to partition equality); background -1 → 0 first so the
        # shared helper keeps zero
        from ..ops.relabel import relabel_consecutive_np

        shifted = np.where(raw_labels < 0, 0, raw_labels.astype(np.int64) + 1)
        out, n_labels = relabel_consecutive_np(shifted.astype(np.uint64))

        ds = self.require_output(out.shape, conf)
        # threaded chunk-aligned whole-volume write (store fast path)
        store_mod.set_read_threads(ds, read_threads(conf))
        ds[:] = out
        ds.attrs["n_labels"] = int(n_labels)
        self.log(
            f"sharded CC over {n_dev} devices: {n_labels} components"
        )
