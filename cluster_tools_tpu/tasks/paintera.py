"""Paintera container conversion (reference paintera/ package).

* ``UniqueBlockLabelsTask`` — per block, the sorted unique label ids as a
  varlen chunk (reference unique_block_labels.py:26; paintera's
  ``unique-labels`` aux dataset).
* ``LabelBlockMappingTask`` — the inverse lookup: for each label id, the list
  of block ids containing it, serialized over id-range chunks
  (reference label_block_mapping.py:19 via ``ndist.serializeBlockMapping``;
  record layout per id: [id, n_blocks, block ids...]).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask


class UniqueBlockLabelsTask(VolumeTask):
    """Sorted unique ids per block; reads either a plain label volume or a
    label-multiset dataset (any pyramid level), like the reference's
    LabelMultisetWrapper path (unique_block_labels.py:26)."""

    task_name = "unique_block_labels"

    def __init__(self, *args, prefix: str = "", **kwargs):
        super().__init__(*args, **kwargs)
        self.prefix = prefix

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_{self.prefix}" if self.prefix else self.task_name

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        f = store.file_reader(self.output_path, "a")
        f.require_dataset(
            self.output_key,
            shape=tuple(blocking.shape),
            dtype="uint64",
            chunks=tuple(blocking.block_shape),
            compression="gzip",
        )

    def process_block(self, block_id: int, blocking: Blocking, config):
        block = blocking.block(block_id)
        in_ds = self.input_ds()
        if in_ds.attrs.get("isLabelMultiset", False):
            from ..ops.label_multiset import deserialize_multiset

            grid_pos = tuple(
                b // c for b, c in zip(block.begin, in_ds.chunks)
            )
            payload = in_ds.read_chunk_varlen(grid_pos)
            if payload is None:
                uniques = np.zeros(1, dtype=np.uint64)  # background only
            else:
                c_shape = tuple(
                    min((g + 1) * c, s) - g * c
                    for g, c, s in zip(grid_pos, in_ds.chunks, in_ds.shape)
                )
                uniques = np.unique(
                    deserialize_multiset(payload, c_shape).ids
                )
        else:
            uniques = np.unique(np.asarray(in_ds[block.slicing]))
        out_ds = self.output_ds()
        grid_pos = tuple(b // c for b, c in zip(block.begin, out_ds.chunks))
        out_ds.write_chunk_varlen(grid_pos, uniques.astype(np.uint64))


class LabelBlockMappingTask(VolumeSimpleTask):
    """Invert the per-block uniques into per-label block lists."""

    task_name = "label_block_mapping"
    # constructed with input_path/input_key (the uniques dataset),
    # output_path/output_key, and optional number_of_labels/prefix — all
    # stored by VolumeSimpleTask's **params

    number_of_labels = None
    prefix = ""

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_{self.prefix}" if self.prefix else self.task_name

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"id_chunk_size": 2000})
        return conf

    def run_impl(self) -> None:
        conf = self.get_task_config()
        uniques_ds = store.file_reader(self.input_path, "r")[self.input_key]
        grid = uniques_ds.chunk_grid
        n_blocks = int(np.prod(grid))

        by_label: Dict[int, List[int]] = {}
        for block_id in range(n_blocks):
            gp = np.unravel_index(block_id, grid)
            uniques = uniques_ds.read_chunk_varlen(tuple(gp))
            if uniques is None:
                continue
            for label in uniques:
                by_label.setdefault(int(label), []).append(block_id)

        n_labels = self.number_of_labels or (
            (max(by_label) + 1) if by_label else 1
        )
        chunk_size = int(conf.get("id_chunk_size", 2000))
        f = store.file_reader(self.output_path, "a")
        out = f.require_dataset(
            self.output_key,
            shape=(n_labels,),
            dtype="uint64",
            chunks=(chunk_size,),
            compression="gzip",
        )
        for chunk_start in range(0, n_labels, chunk_size):
            record = []
            found = False
            for label in range(chunk_start, min(chunk_start + chunk_size, n_labels)):
                blocks = by_label.get(label)
                if blocks:
                    found = True
                    record.extend([label, len(blocks), *blocks])
            if found:
                out.write_chunk_varlen(
                    (chunk_start // chunk_size,),
                    np.asarray(record, dtype=np.uint64),
                )
        self.log(
            f"serialized block mapping for {len(by_label)} labels over "
            f"{n_blocks} blocks"
        )


def read_label_block_mapping(path: str, key: str) -> Dict[int, List[int]]:
    """{label id: [block ids]} from the serialized mapping."""
    ds = store.file_reader(path, "r")[key]
    out: Dict[int, List[int]] = {}
    for cid in range(ds.chunk_grid[0]):
        record = ds.read_chunk_varlen((cid,))
        if record is None:
            continue
        pos = 0
        while pos < record.size:
            label = int(record[pos])
            n = int(record[pos + 1])
            out[label] = [int(b) for b in record[pos + 2 : pos + 2 + n]]
            pos += 2 + n
    return out
