"""Paintera label-multiset datasets (reference label_multisets/ package).

``CreateMultisetTask`` turns a uint64 label dataset into a scale-0 multiset
dataset (one varlen n5 chunk per block, reference create_multiset.py:25);
``DownscaleMultisetTask`` builds coarser levels by pooling child entries with
an entry-count cap per scale (reference downscale_multiset.py:29)."""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..ops import label_multiset as lms
from ..ops.resample import downscale_shape
from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeTask

PAINTERA_IGNORE_LABEL = 18446744073709551615


def read_multiset_region(ds, bb) -> lms.LabelMultiset:
    """Assemble a LabelMultiset for an arbitrary region from varlen chunks
    (vectorized gathers — no per-voxel Python loop)."""
    begin = [b.start for b in bb]
    end = [b.stop for b in bb]
    shape = tuple(e - b for b, e in zip(begin, end))
    n = int(np.prod(shape))
    entry_offsets = np.full(n, -1, dtype=np.int64)
    entry_sizes = np.zeros(n, dtype=np.int64)
    ids_out: List[np.ndarray] = []
    counts_out: List[np.ndarray] = []
    cursor = 0

    grid_lo = [b // c for b, c in zip(begin, ds.chunks)]
    grid_hi = [(e - 1) // c for e, c in zip(end, ds.chunks)]
    region_idx = np.arange(n).reshape(shape)
    for gz in range(grid_lo[0], grid_hi[0] + 1):
        for gy in range(grid_lo[1], grid_hi[1] + 1):
            for gx in range(grid_lo[2], grid_hi[2] + 1):
                gp = (gz, gy, gx)
                payload = ds.read_chunk_varlen(gp)
                c_begin = [g * c for g, c in zip(gp, ds.chunks)]
                c_end = [
                    min((g + 1) * c, s)
                    for g, c, s in zip(gp, ds.chunks, ds.shape)
                ]
                c_shape = tuple(e - b for b, e in zip(c_begin, c_end))
                # region ∩ chunk, in each coordinate system
                lo = [max(b, cb) for b, cb in zip(begin, c_begin)]
                hi = [min(e, ce) for e, ce in zip(end, c_end)]
                if any(l >= h for l, h in zip(lo, hi)):
                    continue
                reg_sl = tuple(
                    slice(l - b, h - b) for l, h, b in zip(lo, hi, begin)
                )
                targets = region_idx[reg_sl].reshape(-1)
                if payload is None:
                    continue  # missing chunk → background fill below
                sub = lms.deserialize_multiset(payload, c_shape)
                chunk_idx = np.arange(int(np.prod(c_shape))).reshape(c_shape)
                chunk_sl = tuple(
                    slice(l - cb, h - cb) for l, h, cb in zip(lo, hi, c_begin)
                )
                sources = chunk_idx[chunk_sl].reshape(-1)
                # gather the selected voxels' entry slices in one shot
                s_off = sub.entry_offsets[sources]
                s_size = sub.entry_sizes[sources]
                entry_idx, _ = lms._gather_indices(s_off, s_size)
                ids_out.append(sub.ids[entry_idx])
                counts_out.append(sub.counts[entry_idx])
                entry_sizes[targets] = s_size
                entry_offsets[targets] = cursor + np.concatenate(
                    [[0], np.cumsum(s_size)[:-1]]
                )
                cursor += int(s_size.sum())
    missing = entry_offsets < 0
    if missing.any():
        m = int(missing.sum())
        entry_offsets[missing] = cursor + np.arange(m)
        entry_sizes[missing] = 1
        ids_out.append(np.zeros(m, dtype=np.uint64))
        counts_out.append(np.ones(m, dtype=np.int32))
    return lms.LabelMultiset(
        shape,
        entry_offsets,
        entry_sizes,
        np.concatenate(ids_out) if ids_out else np.zeros(0, np.uint64),
        np.concatenate(counts_out) if counts_out else np.zeros(0, np.int32),
    )


class CreateMultisetTask(VolumeTask):
    task_name = "create_multiset"

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        f = store.file_reader(self.output_path, "a")
        ds = f.require_dataset(
            self.output_key,
            shape=tuple(blocking.shape),
            dtype="uint8",
            chunks=tuple(blocking.block_shape),
            compression="gzip",
        )
        in_ds = self.input_ds()
        ds.attrs["isLabelMultiset"] = True
        if "maxId" in in_ds.attrs:
            ds.attrs["maxId"] = in_ds.attrs["maxId"]

    def process_block(self, block_id: int, blocking: Blocking, config):
        block = blocking.block(block_id)
        labels = np.asarray(self.input_ds()[block.slicing]).astype(np.uint64)
        # paintera's ignore label cannot be encoded (reference
        # create_multiset.py:115-118)
        labels[labels == PAINTERA_IGNORE_LABEL] = 0
        if not labels.any():
            return
        multiset = lms.create_multiset_from_labels(labels)
        ser = lms.serialize_multiset(multiset)
        out_ds = self.output_ds()
        grid_pos = tuple(b // c for b, c in zip(block.begin, out_ds.chunks))
        out_ds.write_chunk_varlen(grid_pos, ser)


class DownscaleMultisetTask(VolumeTask):
    """One multiset pyramid step; blocking over the OUTPUT (coarser) shape."""

    task_name = "downscale_multiset"

    def __init__(self, *args, scale_factor=2, restrict_set: int = -1,
                 effective_scale_factor: Sequence[int] = (),
                 scale_prefix: str = "", **kwargs):
        super().__init__(*args, **kwargs)
        self.scale_factor = (
            [scale_factor] * 3 if isinstance(scale_factor, int)
            else list(scale_factor)
        )
        self.restrict_set = restrict_set
        self.effective_scale_factor = list(effective_scale_factor)
        self.scale_prefix = scale_prefix

    @property
    def identifier(self) -> str:
        return (
            f"{self.task_name}_{self.scale_prefix}"
            if self.scale_prefix
            else self.task_name
        )

    def get_shape(self) -> Sequence[int]:
        return downscale_shape(self.input_ds().shape, self.scale_factor)

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        f = store.file_reader(self.output_path, "a")
        ds = f.require_dataset(
            self.output_key,
            shape=tuple(blocking.shape),
            dtype="uint8",
            chunks=tuple(blocking.block_shape),
            compression="gzip",
        )
        ds.attrs["isLabelMultiset"] = True
        eff = self.effective_scale_factor or self.scale_factor
        ds.attrs["downsamplingFactors"] = [float(e) for e in eff[::-1]]
        in_ds = self.input_ds()
        if "maxId" in in_ds.attrs:
            ds.attrs["maxId"] = in_ds.attrs["maxId"]

    def process_block(self, block_id: int, blocking: Blocking, config):
        block = blocking.block(block_id)
        in_ds = self.input_ds()
        sf = self.scale_factor
        in_bb = tuple(
            slice(b.start * f, min(b.stop * f, s))
            for b, f, s in zip(block.slicing, sf, in_ds.shape)
        )
        sub = read_multiset_region(in_ds, in_bb)
        pooled = lms.downsample_multiset(sub, sf, self.restrict_set)
        out_ds = self.output_ds()
        grid_pos = tuple(b // c for b, c in zip(block.begin, out_ds.chunks))
        out_ds.write_chunk_varlen(grid_pos, lms.serialize_multiset(pooled))
