"""Scale pyramids: block-wise down/up-scaling and boundary-fitted rescaling.

Reference downscaling/{downscaling,upscaling,scale_to_boundaries}.py: the
blocking is over the *output* volume; each output block reads its scaled
input footprint, resamples on device (ops/resample.py), and writes its inner
region.  Non-interpolatable dtypes (integer labels) force order-0 sampling
(reference downscaling.py:54,99-106).
"""

from __future__ import annotations

import os
from math import ceil
from typing import Any, Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..ops import resample
from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeTask

INTERPOLATABLE = ("float32", "float64", "uint8", "uint16")


class DownscalingTask(VolumeTask):
    """One pyramid level: input at scale s-1 → output at scale s
    (reference downscaling.py:36)."""

    task_name = "downscaling"

    def __init__(
        self,
        *args,
        scale_factor=2,
        scale_prefix: str = "",
        halo: Sequence[int] = (),
        effective_scale_factor: Sequence[int] = (),
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.scale_factor = scale_factor
        self.scale_prefix = scale_prefix
        self.halo = list(halo)
        self.effective_scale_factor = list(effective_scale_factor)

    @property
    def identifier(self) -> str:
        return (
            f"{self.task_name}_{self.scale_prefix}"
            if self.scale_prefix
            else self.task_name
        )

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"library": "interpolate", "chunks": None,
                     "compression": "gzip", "library_kwargs": None})
        return conf

    def _method(self, config) -> str:
        method = resample.METHOD_ALIASES.get(
            config.get("library", "interpolate"), config.get("library", "interpolate")
        )
        kwargs = config.get("library_kwargs") or {}
        if kwargs.get("order") == 0:
            method = "nearest"
        dtype = str(self.input_ds().dtype)
        if dtype not in INTERPOLATABLE and method not in resample.ORDER0_METHODS:
            # labels cannot be interpolated — the reference asserts here
            # (downscaling.py:99-106); we fall back with a log line instead
            self.log(f"dtype {dtype} is not interpolatable; forcing nearest")
            method = "nearest"
        return method

    # -- geometry: blocking is over the DOWNSAMPLED shape --------------------

    def _sf(self):
        return resample.per_axis_factor(self.scale_factor, 3)

    def get_shape(self) -> Sequence[int]:
        in_shape = self.input_ds().shape
        space = in_shape[-3:] if len(in_shape) > 3 else in_shape
        return resample.downscale_shape(space, self._sf())

    def _roi_divisor(self):
        """The global ROI is in full-resolution voxels; this task's blocking is
        at the (cumulative) downscaled resolution."""
        eff = self.effective_scale_factor or list(self._sf())
        return [int(e) for e in eff]

    def get_block_list(self, blocking: Blocking, gconf: Dict[str, Any]):
        gconf = dict(gconf)
        div = self._roi_divisor()
        if gconf.get("roi_begin") is not None:
            gconf["roi_begin"] = [
                rb // d for rb, d in zip(gconf["roi_begin"], div)
            ]
        if gconf.get("roi_end") is not None:
            gconf["roi_end"] = [
                -(-re // d) for re, d in zip(gconf["roi_end"], div)
            ]
        return super().get_block_list(blocking, gconf)

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        in_ds = self.input_ds()
        out_shape = tuple(blocking.shape)
        if len(in_ds.shape) == 4:
            out_shape = (in_ds.shape[0],) + out_shape
        chunks = config.get("chunks")
        chunks = tuple(blocking.block_shape) if chunks is None else tuple(chunks)
        if len(out_shape) == 4 and len(chunks) == 3:
            chunks = (1,) + chunks
        chunks = tuple(min(c, s) for c, s in zip(chunks, out_shape))
        store.file_reader(self.output_path, "a").require_dataset(
            self.output_key,
            shape=out_shape,
            dtype=str(in_ds.dtype),
            chunks=chunks,
            compression=config.get("compression", "gzip"),
        )

    def process_block(self, block_id: int, blocking: Blocking, config):
        sf = self._sf()
        method = self._method(config)
        in_ds = self.input_ds()
        out_ds = self.output_ds()
        in_shape = in_ds.shape
        in_space = in_shape[-3:] if len(in_shape) > 3 else in_shape

        halo = [h // f for h, f in zip(self.halo, sf)] if self.halo else None
        if halo:
            bh = blocking.block_with_halo(block_id, halo)
            out_box, read_box, local = bh.inner, bh.outer, bh.inner_local
        else:
            blk = blocking.block(block_id)
            out_box = read_box = blk
            local = None

        in_bb = tuple(
            slice(b.start * f, min(b.stop * f, s))
            for b, f, s in zip(read_box.slicing, sf, in_space)
        )
        is_4d = len(in_shape) == 4
        x = np.asarray(in_ds[((slice(None),) + in_bb) if is_4d else in_bb])
        if not np.any(x):
            return  # empty block (reference _ds_block)

        def _one(vol):
            if method == "nearest":
                # pure strided subsample — stays on host: jax has no x64 here,
                # a device round-trip would truncate uint64 label ids
                return vol[tuple(slice(None, None, f) for f in sf)]
            out = resample.downscale(jnp.asarray(vol), sf, method)
            return resample.cast_resampled(out, in_ds.dtype)

        out = np.stack([_one(c) for c in x]) if is_4d else _one(x)
        if local is not None:
            sl = local.slicing
            out = out[((slice(None),) + sl) if is_4d else sl]
        out_bb = out_box.slicing
        # clip to the true downscaled extent (resample may ceil-round)
        want = tuple(b.stop - b.start for b in out_bb)
        crop = tuple(slice(0, w) for w in want)
        out = out[((slice(None),) + crop) if is_4d else crop]
        out_ds[((slice(None),) + out_bb) if is_4d else out_bb] = out


class UpscalingTask(DownscalingTask):
    """Inverse pyramid step (reference upscaling.py:35): blocking over the
    UPSAMPLED shape; each output block reads its floor/ceil-scaled input
    footprint and resizes up."""

    task_name = "upscaling"

    def get_shape(self) -> Sequence[int]:
        in_shape = self.input_ds().shape
        space = in_shape[-3:] if len(in_shape) > 3 else in_shape
        sf = self._sf()
        return tuple(s * f for s, f in zip(space, sf))

    def get_block_list(self, blocking: Blocking, gconf: Dict[str, Any]):
        # the ROI is given in the coarse source coordinates here — scale it UP
        # to the output resolution (reference upscaling.py:146-157)
        gconf = dict(gconf)
        eff = self.effective_scale_factor
        if eff:
            if gconf.get("roi_begin") is not None:
                gconf["roi_begin"] = [
                    int(rb * e) for rb, e in zip(gconf["roi_begin"], eff)
                ]
            if gconf.get("roi_end") is not None:
                gconf["roi_end"] = [
                    int(re * e) for re, e in zip(gconf["roi_end"], eff)
                ]
        return super(DownscalingTask, self).get_block_list(blocking, gconf)

    def process_block(self, block_id: int, blocking: Blocking, config):
        sf = self._sf()
        method = self._method(config)
        in_ds = self.input_ds()
        out_ds = self.output_ds()
        in_shape = in_ds.shape
        in_space = in_shape[-3:] if len(in_shape) > 3 else in_shape

        blk = blocking.block(block_id)
        out_bb = blk.slicing
        in_bb = tuple(
            slice(b.start // f, min(ceil(b.stop / f), s))
            for b, f, s in zip(out_bb, sf, in_space)
        )
        is_4d = len(in_shape) == 4
        x = np.asarray(in_ds[((slice(None),) + in_bb) if is_4d else in_bb])
        if not np.any(x):
            return
        out_shape = tuple(b.stop - b.start for b in out_bb)

        def _one(vol):
            # resize the input footprint so that voxel centers align: the
            # footprint covers [start*f, stop*f); crop the output window
            full = tuple(s * f for s, f in zip(vol.shape, sf))
            off = tuple(b.start - ib.start * f
                        for b, ib, f in zip(out_bb, in_bb, sf))
            sl = tuple(slice(o, o + w) for o, w in zip(off, out_shape))
            if method == "nearest":
                # host-side repeat: keeps uint64 label ids exact (no x64 on
                # device) and is a pure memory op anyway
                up = vol
                for ax, f in enumerate(sf):
                    up = np.repeat(up, f, axis=ax)
                return up[sl].astype(in_ds.dtype, copy=False)
            up = resample.upscale(jnp.asarray(vol), full, method)
            return resample.cast_resampled(up[sl], in_ds.dtype)

        out = np.stack([_one(c) for c in x]) if is_4d else _one(x)
        out_ds[((slice(None),) + out_bb) if is_4d else out_bb] = out


class ScaleToBoundariesTask(VolumeTask):
    """Rescale coarse objects to a full-resolution boundary map: upscale
    nearest, erode, re-grow with a seeded watershed on the boundary height map
    (reference scale_to_boundaries.py:32 + volume_utils.fit_to_hmap:336)."""

    task_name = "scale_to_boundaries"
    output_dtype = "uint64"

    def __init__(self, *args, boundaries_path: str = None,
                 boundaries_key: str = None, offset: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self.boundaries_path = boundaries_path
        self.boundaries_key = boundaries_key
        self.offset = offset

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"erode_by": 12, "erode_3d": True, "channel": 0})
        return conf

    def get_shape(self) -> Sequence[int]:
        shape = store.file_reader(self.boundaries_path, "r")[
            self.boundaries_key
        ].shape
        return shape[-3:] if len(shape) > 3 else shape

    def _halo(self, config):
        erode_by = config["erode_by"]
        h = int(erode_by) if not isinstance(erode_by, dict) else max(
            erode_by.values()
        )
        return [h, h, h] if config.get("erode_3d", True) else [0, h, h]

    def process_block(self, block_id: int, blocking: Blocking, config):
        from ..ops.watershed import fit_to_hmap

        erode_by = config["erode_by"]
        if isinstance(erode_by, dict):
            erode_by = max(erode_by.values())  # per-object radii: use the max
        erode_by = int(erode_by)
        channel = int(config.get("channel", 0))

        bh = blocking.block_with_halo(block_id, self._halo(config))
        in_bb = bh.outer.slicing

        bd_ds = store.file_reader(self.boundaries_path, "r")[self.boundaries_key]
        in_ds = self.input_ds()
        shape = tuple(blocking.shape)

        # objects may live at a coarser resolution — map the bb through
        # nearest-neighbor index scaling (reference wraps ds_in in ResizedVolume)
        obj_shape = in_ds.shape
        idx = tuple(
            np.minimum(
                (np.arange(b.start, b.stop) * os_ // s).astype(np.int64), os_ - 1
            )
            for b, os_, s in zip(in_bb, obj_shape, shape)
        )
        slab = np.asarray(in_ds[
            tuple(slice(int(i[0]), int(i[-1]) + 1) for i in idx)
        ])
        objs = slab[np.ix_(*(i - i[0] for i in idx))].astype(np.uint64)
        if not np.any(objs):
            return

        if len(bd_ds.shape) == 4:
            hmap = np.asarray(bd_ds[(slice(channel, channel + 1),) + in_bb])[0]
        else:
            hmap = np.asarray(bd_ds[in_bb])

        fitted = fit_to_hmap(
            objs, hmap, erode_by, config.get("erode_3d", True)
        )[bh.inner_local.slicing]

        fg = fitted != 0
        out_ds = self.output_ds()
        out = np.asarray(out_ds[bh.inner.slicing])
        out[fg] = fitted[fg] + self.offset
        out_ds[bh.inner.slicing] = out
