"""Shared plumbing for volume-to-volume block tasks."""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..runtime.task import BlockTask, SimpleTask
from ..utils import store
from ..utils.blocking import Blocking


SCRATCH_STORE_NAME = "data.zarr"


def fusion_wrap(ds, path: str, key: str):
    """Route a dataset through the fused chain's active per-batch read
    cache (ctt-stream) — a no-op outside a chain's read stage."""
    from ..parallel.dispatch import wrap_with_read_cache

    return wrap_with_read_cache(ds, path, key)


def scratch_store_path(tmp_folder: str) -> str:
    """The shared per-tmp-folder scratch store (single source of truth)."""
    return os.path.join(tmp_folder, SCRATCH_STORE_NAME)


class VolumeTask(BlockTask):
    """A block task reading ``input_path/input_key`` and writing
    ``output_path/output_key``.

    The blocking is derived from the input dataset shape (the last ``ndim``
    axes when the input carries leading channel axes).
    """

    output_dtype = None  # subclasses set to create the output dataset
    output_chunks_from_blocks = True
    space_ndim = 3  # spatial rank; inputs may have extra leading channel axes

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        dependencies: Sequence = (),
        input_path: str = None,
        input_key: str = None,
        output_path: Optional[str] = None,
        output_key: Optional[str] = None,
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key

    # -- datasets ------------------------------------------------------------

    def input_ds(self, mode: str = "r"):
        # ctt-stream seam: inside a fused chain's read stage the thread
        # carries a per-batch BlockReadCache — reads come back as crops of
        # the one shared store read instead of hitting the codec again
        return fusion_wrap(
            store.file_reader(self.input_path, mode)[self.input_key],
            self.input_path, self.input_key,
        )

    def output_ds(self, mode: str = "a"):
        return store.file_reader(self.output_path, mode)[self.output_key]

    def get_shape(self) -> Sequence[int]:
        shape = self.input_ds().shape
        return shape[-self.space_ndim :] if len(shape) > self.space_ndim else shape

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        if self.output_path is None or self.output_dtype is None:
            return
        f = store.file_reader(self.output_path, "a")
        chunks = (
            tuple(blocking.block_shape)
            if self.output_chunks_from_blocks
            else None
        )
        # user-facing outputs keep the reference's gzip default (vanilla
        # n5-java readers lack the blosc plugin); SCRATCH datasets get the
        # fast house codec via create_dataset's "default"
        f.require_dataset(
            self.output_key,
            shape=tuple(blocking.shape),
            dtype=self.output_dtype,
            chunks=chunks,
            compression="gzip",
        )

    # -- ctt-cloud async prefetch ---------------------------------------------

    def prefetch_halo(self, config) -> Sequence[int]:
        """Halo of the regions ``read_batch`` will request — the task
        config's ``halo`` key when it matches the spatial rank, else no
        halo.  An approximate halo is fine: prefetch works at chunk
        granularity and is advisory, so over/under-shoot degrades to a few
        extra (or missed) chunk warms, never to wrong data."""
        halo = config.get("halo")
        if not halo:
            return (0,) * self.space_ndim
        halo = tuple(int(h) for h in halo)
        if len(halo) != self.space_ndim:
            return (0,) * self.space_ndim
        return halo

    def prefetch_batch(self, block_ids, blocking: Blocking, config) -> int:
        """Warm the decoded-chunk LRU with every input chunk the batch's
        read stage will need (the executor's async-prefetch stage issues
        this up to ``pipeline_depth`` batches ahead of the in-order
        compute stage — ctt-cloud).  Consecutive ids prefetch as one
        bounding superslab (each chunk probed once); sparse id runs fall
        back to per-block outer boxes.  Returns the chunk count submitted
        (0 when the dataset has no prefetch support, e.g. hdf5)."""
        from ..parallel.dispatch import batch_outer_boxes

        ds = self.input_ds()
        prefetch = getattr(ds, "prefetch", None)
        if prefetch is None or not block_ids:
            return 0
        halo = self.prefetch_halo(config)
        extra = len(ds.shape) - blocking.ndim
        lead = tuple(slice(0, s) for s in ds.shape[:extra])
        bhs, lo, hi, bbox_ok = batch_outer_boxes(blocking, block_ids, halo)
        if bbox_ok:
            return prefetch(
                lead + tuple(slice(b, e) for b, e in zip(lo, hi))
            )
        return sum(prefetch(lead + bh.outer.slicing) for bh in bhs)

    # -- ctt-stream fusion contract ------------------------------------------

    def fusion_inputs(self, config):
        """Per-block dataset reads of a volume-to-volume task: the input
        (plus the optional mask) — the fused chain's shared-read set."""
        pairs = [(self.input_path, self.input_key)]
        mask_path = getattr(self, "mask_path", None)
        if mask_path:
            pairs.append((mask_path, getattr(self, "mask_key", None)))
        return pairs

    # -- scratch data --------------------------------------------------------

    @property
    def tmp_store_path(self) -> str:
        return scratch_store_path(self.tmp_folder)

    def tmp_store(self):
        return store.file_reader(self.tmp_store_path, "a")

    def tmp_ragged(self, key: str, grid_size: int, dtype):
        return self.tmp_store().create_ragged_dataset(key, (grid_size,), dtype)


def read_ragged_chunks(ds, n_blocks: int, n_threads: int = 1) -> list:
    """Read all per-block ragged chunks, fanned out over a thread pool when
    ``n_threads > 1`` (the reference's ``threads_per_job`` merge pattern,
    write.py:236-243, measures.py:121-127 — chunk decode is gzip-bound, so
    threads overlap IO + decompression).  Returns a list indexed by block id,
    ``None`` where a chunk is absent."""
    from concurrent.futures import ThreadPoolExecutor

    if n_threads <= 1:
        return [ds.read_chunk((bid,)) for bid in range(n_blocks)]
    with ThreadPoolExecutor(n_threads) as pool:
        return list(pool.map(lambda bid: ds.read_chunk((bid,)), range(n_blocks)))


def merge_threads(task) -> int:
    """The ``threads_per_job`` knob of a merge task's config."""
    return max(int(task.get_task_config().get("threads_per_job", 1)), 1)


def read_threads(config) -> int:
    """The ``read_threads`` knob (chunk-read fan-out of a block batch) —
    DEFAULT_TASK_CONFIG owns the default, this helper just clamps."""
    from ..runtime.config import DEFAULT_TASK_CONFIG

    return max(
        int(config.get("read_threads", DEFAULT_TASK_CONFIG["read_threads"])), 1
    )


def resolve_n_blocks(
    config_dir, path: str, key: str, scale: int = 0, space_ndim: int = 3
) -> int:
    """Block count of a dataset under the global block shape.  Called at task
    run time (the dataset may not exist when the DAG is built); leading channel
    axes beyond ``space_ndim`` are dropped, matching ``VolumeTask.get_shape``."""
    from ..runtime import config as cfg

    shape = store.file_reader(path, "r")[key].shape
    if len(shape) > space_ndim:
        shape = shape[-space_ndim:]
    gconf = cfg.global_config(config_dir)
    block_shape = [bs * (2**scale) for bs in gconf["block_shape"]]
    return Blocking(shape, block_shape).n_blocks


class VolumeSimpleTask(SimpleTask):
    """Single-shot reduction task with access to the shared scratch store."""

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        dependencies: Sequence = (),
        **params,
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        for k, v in params.items():
            setattr(self, k, v)

    @property
    def tmp_store_path(self) -> str:
        return scratch_store_path(self.tmp_folder)

    def tmp_store(self):
        return store.file_reader(self.tmp_store_path, "a")

    def require_output(self, shape, conf, dtype="uint64"):
        """Create/open ``output_path/output_key`` with the house convention
        (block-shape chunks, gzip — user-facing outputs stay on the
        reference's default codec for vanilla n5-java readability; scratch
        data rides the fast blosc default) — one recipe for every
        single-shot task that writes a volume."""
        f = store.file_reader(self.output_path, "a")
        block_shape = conf.get("block_shape")
        return f.require_dataset(
            self.output_key, shape=tuple(shape), dtype=dtype,
            chunks=tuple(block_shape) if block_shape else None,
            compression="gzip",
        )

