"""Distributed region-adjacency-graph extraction.

Reference graph/{initial_sub_graphs,merge_sub_graphs,map_edge_ids}.py via
nifty.distributed (SURVEY.md §2.3): per-block subgraphs → merged global graph →
block-local → global edge-id maps.

Storage layout in the scratch store (``tmp_folder/data.zarr``):
  graph/sub_edges        ragged per block: flattened (u,v) label pairs (uint64)
  graph/sub_nodes        ragged per block: unique non-zero labels (uint64)
  graph/nodes            [n] sorted unique node labels (uint64)
  graph/edges            [m,2] dense node-index pairs, lexicographically sorted
  graph/block_edge_ids   ragged per block: global edge id per block edge

Nodes are collected per block (not derived from edges) so isolated fragments —
labels with no adjacent fragment — stay in the graph and keep their identity
through solve/write (the reference's graph carries all nodes the same way).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..ops.rag import block_edges
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, merge_threads, read_ragged_chunks, resolve_n_blocks

SUB_EDGES_KEY = "graph/sub_edges"
SUB_NODES_KEY = "graph/sub_nodes"
NODES_KEY = "graph/nodes"
EDGES_KEY = "graph/edges"
BLOCK_EDGE_IDS_KEY = "graph/block_edge_ids"


def read_block_with_upper_halo(ds, blocking: Blocking, block_id: int):
    """Block plus one voxel towards the upper neighbors, so cross-block label
    faces are captured (clipped at the volume border)."""
    block = blocking.block(block_id)
    end = tuple(min(e + 1, s) for e, s in zip(block.end, blocking.shape))
    return ds[tuple(slice(b, e) for b, e in zip(block.begin, end))]


def load_graph(tmp_store):
    """Returns (nodes [n] uint64, edges [m,2] int64 dense indices)."""
    nodes = tmp_store[NODES_KEY][:]
    edges = tmp_store[EDGES_KEY][:]
    return nodes, edges


class InitialSubGraphsTask(VolumeTask):
    """Per-block RAG edges (reference initial_sub_graphs.py:25)."""

    task_name = "initial_sub_graphs"
    output_dtype = None

    def process_block(self, block_id: int, blocking: Blocking, config):
        seg = read_block_with_upper_halo(self.input_ds(), blocking, block_id)
        seg = seg.astype(np.uint64)
        edges = block_edges(seg)
        sub = self.tmp_ragged(SUB_EDGES_KEY, blocking.n_blocks, np.uint64)
        sub.write_chunk((block_id,), edges.reshape(-1))
        labels = np.unique(seg)
        labels = labels[labels > 0]
        sub_nodes = self.tmp_ragged(SUB_NODES_KEY, blocking.n_blocks, np.uint64)
        sub_nodes.write_chunk((block_id,), labels)


def scale_keys(scale: int):
    """Ragged sub-graph dataset keys at pyramid ``scale`` (scale 0 = the
    per-block outputs of ``InitialSubGraphsTask``)."""
    if scale == 0:
        return SUB_EDGES_KEY, SUB_NODES_KEY
    return f"{SUB_EDGES_KEY}_s{scale}", f"{SUB_NODES_KEY}_s{scale}"


class MergeScaleSubGraphsTask(VolumeTask):
    """One level of the sub-graph scale pyramid
    (reference merge_sub_graphs.py:24, graph_workflow.py:36-54): each block at
    scale ``s`` (block shape × 2^s) merges and dedups the sub-graphs of its
    2³ child blocks at scale s-1, so the final global merge reads few large
    chunks instead of every scale-0 chunk — not a single-node memory/IO choke
    at production block counts."""

    task_name = "merge_scale_sub_graphs"
    output_dtype = None

    def __init__(self, *args, scale: int = 1, **kwargs):
        super().__init__(*args, **kwargs)
        self.scale = int(scale)

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_s{self.scale}"

    def get_block_shape(self, gconf):
        return [bs * (2 ** self.scale) for bs in gconf["block_shape"]]

    def process_block(self, block_id: int, blocking: Blocking, config):
        store = self.tmp_store()
        in_edges_key, in_nodes_key = scale_keys(self.scale - 1)
        out_edges_key, out_nodes_key = scale_keys(self.scale)
        child_bs = [bs // 2 for bs in blocking.block_shape]
        child_blocking = Blocking(blocking.shape, child_bs)
        block = blocking.block(block_id)
        child_ids = child_blocking.blocks_overlapping_roi(
            block.begin, block.end
        )
        in_edges = store[in_edges_key]
        in_nodes = store[in_nodes_key]
        edge_chunks, node_chunks = [], []
        for cid in child_ids:
            c = in_edges.read_chunk((cid,))
            if c is not None and c.size:
                edge_chunks.append(c.reshape(-1, 2))
            n = in_nodes.read_chunk((cid,))
            if n is not None and n.size:
                node_chunks.append(n)
        edges = (
            np.unique(np.concatenate(edge_chunks, axis=0), axis=0)
            if edge_chunks
            else np.zeros((0, 2), dtype=np.uint64)
        )
        nodes = (
            np.unique(np.concatenate(node_chunks))
            if node_chunks
            else np.zeros(0, dtype=np.uint64)
        )
        out_edges = self.tmp_ragged(out_edges_key, blocking.n_blocks, np.uint64)
        out_edges.write_chunk((block_id,), edges.reshape(-1))
        out_nodes = self.tmp_ragged(out_nodes_key, blocking.n_blocks, np.uint64)
        out_nodes.write_chunk((block_id,), nodes)


class MergeSubGraphsTask(VolumeSimpleTask):
    """Merge block subgraphs into the global graph
    (reference merge_sub_graphs.py:24,147 with ``scale='complete'``): one
    sort-based merge — np.unique over the chunks of the top pyramid scale."""

    task_name = "merge_sub_graphs"

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 scale: int = 0, **kwargs):
        super().__init__(*args, input_path=input_path, input_key=input_key,
                         scale=scale, **kwargs)

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(
            self.config_dir, self.input_path, self.input_key, scale=self.scale
        )
        store = self.tmp_store()
        edges_key, nodes_key = scale_keys(self.scale)
        sub = store[edges_key]
        sub_nodes = store[nodes_key]
        n_thr = merge_threads(self)
        collected = [
            c.reshape(-1, 2)
            for c in read_ragged_chunks(sub, n_blocks, n_thr)
            if c is not None and c.size
        ]
        node_chunks = [
            c
            for c in read_ragged_chunks(sub_nodes, n_blocks, n_thr)
            if c is not None and c.size
        ]
        if collected:
            label_edges = np.unique(np.concatenate(collected, axis=0), axis=0)
        else:
            label_edges = np.zeros((0, 2), dtype=np.uint64)
        nodes = (
            np.unique(np.concatenate(node_chunks))
            if node_chunks
            else np.zeros(0, dtype=np.uint64)
        )
        dense = np.searchsorted(nodes, label_edges).astype(np.int64)
        # lexicographic edge order (u, then v) — defines global edge ids
        order = np.lexsort((dense[:, 1], dense[:, 0]))
        dense = dense[order]
        store.create_dataset(
            NODES_KEY, data=nodes, chunks=(max(nodes.size, 1),), exist_ok=True
        )
        store.create_dataset(
            EDGES_KEY,
            data=dense,
            chunks=(max(dense.shape[0], 1), 2),
            exist_ok=True,
        )
        g = store[EDGES_KEY]
        g.attrs["n_nodes"] = int(nodes.size)
        g.attrs["n_edges"] = int(dense.shape[0])
        self.log(f"graph: {nodes.size} nodes, {dense.shape[0]} edges")


class MapEdgeIdsTask(VolumeTask):
    """Per-block map of block edges → global edge ids
    (reference map_edge_ids.py:23)."""

    task_name = "map_edge_ids"
    output_dtype = None

    def process_block(self, block_id: int, blocking: Blocking, config):
        store = self.tmp_store()
        nodes, edges = load_graph(store)
        sub = store[SUB_EDGES_KEY].read_chunk((block_id,))
        out = self.tmp_ragged(BLOCK_EDGE_IDS_KEY, blocking.n_blocks, np.int64)
        if sub is None or sub.size == 0:
            out.write_chunk((block_id,), np.array([], dtype=np.int64))
            return
        pairs = np.searchsorted(nodes, sub.reshape(-1, 2)).astype(np.int64)
        # edge id = position in the lexicographically sorted global edge list
        keys = edges[:, 0] * (nodes.size + 1) + edges[:, 1]
        want = pairs[:, 0] * (nodes.size + 1) + pairs[:, 1]
        ids = np.searchsorted(keys, want)
        if not (keys[np.clip(ids, 0, keys.size - 1)] == want).all():
            raise RuntimeError(
                f"block {block_id}: edges missing from the global graph"
            )
        out.write_chunk((block_id,), ids.astype(np.int64))
