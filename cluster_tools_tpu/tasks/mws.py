"""Blockwise mutex watershed tasks (reference mutex_watershed/mws_blocks.py:26).

Per halo'd block: MWS on long-range affinities (native Kruskal-with-mutex, the
sequential kernel — SURVEY.md §7 hard-parts #2), crop inner, block-id offsets;
boundary consistency comes from the stitching workflow downstream (reference
mws_workflow.py:53-68).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..ops.mws import compute_mws_segmentation
from ..utils.blocking import Blocking
from .base import VolumeTask
from .watershed import MAX_IDS_KEY


class MwsBlocksTask(VolumeTask):
    task_name = "mws_blocks"
    output_dtype = "uint64"

    def __init__(self, *args, mask_path: str = None, mask_key: str = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.mask_path = mask_path
        self.mask_key = mask_key

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {
                # default CREMI-style long-range offsets (z, y, x)
                "offsets": [
                    [-1, 0, 0], [0, -1, 0], [0, 0, -1],
                    [-2, 0, 0], [0, -3, 0], [0, 0, -3],
                    [-3, -3, -3], [-3, 3, 3],
                ],
                "strides": [1, 1, 1],
                "randomize_strides": False,
                "noise_level": 0.0,
                "halo": [2, 4, 4],
            }
        )
        return conf

    def _load_affs_and_mask(self, bh, config):
        """Halo'd affinity read (+[0,1] cast) and optional mask; returns
        (affs, mask, empty) where empty means the whole block is masked out."""
        in_ds = self.input_ds()
        offsets = config.get("offsets")
        affs = in_ds[(slice(0, len(offsets)),) + bh.outer.slicing]
        if affs.dtype == np.uint8:
            affs = affs.astype(np.float32) / 255.0
        mask = None
        if self.mask_path:
            from ..utils import store as _store

            mask = _store.file_reader(self.mask_path, "r")[self.mask_key][
                bh.outer.slicing
            ].astype(bool)
            if not mask.any():
                return affs, mask, True
        return affs, mask, False

    def process_block(self, block_id: int, blocking: Blocking, config):
        out_ds = self.output_ds()
        offsets = config.get("offsets")
        halo = config.get("halo") or [0, 0, 0]
        bh = blocking.block_with_halo(block_id, halo)
        affs, mask, empty = self._load_affs_and_mask(bh, config)
        if empty:
            out_ds[bh.inner.slicing] = np.zeros(bh.inner.shape, dtype=np.uint64)
            return
        seg = compute_mws_segmentation(
            affs,
            offsets,
            strides=config.get("strides"),
            randomize_strides=bool(config.get("randomize_strides", False)),
            mask=mask,
            noise_level=float(config.get("noise_level", 0.0)),
            seed=block_id,
        )
        # relabel the full outer region consecutively, then offset into the
        # block's id namespace (reference mws_blocks.py:164-166); the outer
        # labeling is ALSO saved so stitch_faces can compare both blocks'
        # labelings of the shared halo region
        from .stitching import save_block_overlap

        uniq, inv = np.unique(seg, return_inverse=True)
        inv = inv.reshape(seg.shape).astype(np.uint64)
        lab_outer = inv if uniq[0] == 0 else inv + 1
        # namespace sized by the FULL outer region: labels are consecutive over
        # the halo'd box, so an inner-sized namespace (the reference crops to the
        # inner block first, mws_blocks.py:161-166) could spill into the next
        # block's range here
        outer_full = [bs + 2 * h for bs, h in zip(blocking.block_shape, halo)]
        offset_unit = np.uint64(block_id * int(np.prod(outer_full)))
        lab_outer = np.where(lab_outer > 0, lab_outer + offset_unit, 0).astype(
            np.uint64
        )
        lab = lab_outer[bh.inner_local.slicing]
        out_ds[bh.inner.slicing] = lab
        save_block_overlap(
            self.tmp_folder, block_id, bh.outer.begin, bh.outer.end, lab_outer
        )
        max_ids = self.tmp_ragged(MAX_IDS_KEY, blocking.n_blocks, np.int64)
        max_ids.write_chunk((block_id,), np.array([lab.max()], dtype=np.int64))


class TwoPassMwsTask(MwsBlocksTask):
    """One checkerboard pass of the two-pass mutex watershed
    (reference two_pass_mws.py:28).

    Pass 0 runs plain block MWS on one checkerboard color; pass 1 runs on the
    other color with the already-written neighbor labels inside the halo as
    seed constraints (compute_mws_segmentation_with_seeds), which both pins
    the shared voxels to the neighbor ids and mutexes distinct neighbor
    segments — the role the reference's serialized grid-graph state plays
    (two_pass_mws.py:179-187), without the h5 state files or the separate
    two_pass_assignments merge."""

    task_name = "two_pass_mws"

    def __init__(self, *args, pass_id: int = 0, **kwargs):
        super().__init__(*args, **kwargs)
        self.pass_id = int(pass_id)

    @property
    def identifier(self) -> str:
        return f"{self.task_name}_pass{self.pass_id}"

    @property
    def pipeline_safe(self) -> bool:
        # pass 1 reads halo'd out_ds regions that same-color diagonal
        # neighbors write (see TwoPassWatershedTask.pipeline_safe)
        return self.pass_id == 0

    def get_block_list(self, blocking: Blocking, gconf):
        from ..utils.blocking import make_checkerboard_block_lists

        blocks = super().get_block_list(blocking, gconf)
        colors = make_checkerboard_block_lists(blocking)
        return sorted(set(blocks) & set(colors[self.pass_id]))

    def process_block(self, block_id: int, blocking: Blocking, config):
        if self.pass_id == 0:
            super().process_block(block_id, blocking, config)
            return

        from ..ops.mws import compute_mws_segmentation_with_seeds

        out_ds = self.output_ds()
        offsets = config.get("offsets")
        halo = config.get("halo") or [0, 0, 0]
        bh = blocking.block_with_halo(block_id, halo)
        affs, mask, empty = self._load_affs_and_mask(bh, config)
        if empty:
            out_ds[bh.inner.slicing] = np.zeros(bh.inner.shape, dtype=np.uint64)
            return

        # seeds: what pass-0 neighbors already wrote in our outer region.
        # only FACE slabs are used — corner/edge wedges of the halo overlap
        # diagonal neighbors, which share this pass's color and may still be
        # writing (the 2-coloring only serializes face adjacency)
        written = np.asarray(out_ds[bh.outer.slicing]).astype(np.uint64)
        inner_local = bh.inner_local.slicing
        face_seeds = np.zeros_like(written)
        for axis in range(3):
            for side in (0, 1):
                slab = list(inner_local)
                if side == 0:
                    slab[axis] = slice(0, inner_local[axis].start)
                else:
                    stop = inner_local[axis].stop
                    slab[axis] = slice(stop, written.shape[axis])
                slab = tuple(slab)
                face_seeds[slab] = written[slab]
        written = face_seeds

        seg = compute_mws_segmentation_with_seeds(
            affs,
            offsets,
            written,
            strides=config.get("strides"),
            randomize_strides=bool(config.get("randomize_strides", False)),
            mask=mask,
            noise_level=float(config.get("noise_level", 0.0)),
            seed=block_id,
        )
        # new (non-seed) segments move into this block's id namespace;
        # seeded segments keep the neighbor ids → global consistency
        seed_max = int(written.max())
        outer_full = [bs + 2 * h for bs, h in zip(blocking.block_shape, halo)]
        offset_unit = np.uint64(block_id * int(np.prod(outer_full)))
        is_new = seg > seed_max
        seg = np.where(is_new, seg - np.uint64(seed_max) + offset_unit, seg)
        out_ds[bh.inner.slicing] = seg[inner_local].astype(np.uint64)
