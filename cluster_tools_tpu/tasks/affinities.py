"""Affinity-map postprocessing and synthesis (reference affinities/ package).

* ``InsertAffinitiesTask`` — paste affinities derived from labeled objects into
  a predicted affinity volume: refit objects to the affinity height map,
  compute their label affinities, dilate the boundary channels, blend + clip
  (reference insert_affinities.py:33, ``_insert_affinities``:138-157).
* ``EmbeddingDistancesTask`` — per-offset distances between embedding vectors
  (reference embedding_distances.py:32).
* ``GradientsTask`` — channel-averaged central-difference gradients
  (reference gradients.py:26).

All three per-block programs are shift-and-compare / elementwise XLA code
(ops/affinities.py) over halo'd blocks.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..ops import affinities as aff_ops
from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeTask


def _offsets_halo(offsets) -> List[int]:
    return np.max(np.abs(np.asarray(offsets)), axis=0).astype(int).tolist()


class InsertAffinitiesTask(VolumeTask):
    task_name = "insert_affinities"

    def __init__(self, *args, objects_path: str = None, objects_key: str = None,
                 offsets: Sequence[Sequence[int]] = ((-1, 0, 0), (0, -1, 0), (0, 0, -1)),
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.objects_path = objects_path
        self.objects_key = objects_key
        self.offsets = [list(o) for o in offsets]

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"erode_by": 6, "erode_3d": True, "zero_objects_list": None,
                     "dilate_by": 2, "chunks": None})
        return conf

    def get_shape(self) -> Sequence[int]:
        return self.input_ds().shape[1:]

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        in_ds = self.input_ds()
        chunks = config.get("chunks") or (1,) + tuple(blocking.block_shape)
        store.file_reader(self.output_path, "a").require_dataset(
            self.output_key,
            shape=in_ds.shape,
            dtype=str(in_ds.dtype),
            chunks=tuple(min(c, s) for c, s in zip(chunks, in_ds.shape)),
            compression="gzip",
        )

    def _halo(self, config) -> List[int]:
        # offsets + erosion + in-plane dilation all widen the region whose
        # boundary responses can reach the inner block
        halo = _offsets_halo(self.offsets)
        erode_by = int(config["erode_by"])
        dilate_by = int(config.get("dilate_by", 2))
        if config["erode_3d"]:
            halo = [max(h, erode_by) for h in halo]
        else:
            halo = [halo[0]] + [max(h, erode_by) for h in halo[1:]]
        return [halo[0]] + [h + dilate_by for h in halo[1:]]

    def process_block(self, block_id: int, blocking: Blocking, config):
        in_ds = self.input_ds()
        out_ds = self.output_ds()
        objects = store.file_reader(self.objects_path, "r")[self.objects_key]

        bh = blocking.block_with_halo(block_id, self._halo(config))
        outer = bh.outer.slicing
        inner = (slice(None),) + bh.inner.slicing
        local = (slice(None),) + bh.inner_local.slicing

        objs = np.asarray(objects[outer]).astype(np.uint64)
        if not np.any(objs):
            out_ds[inner] = np.asarray(in_ds[inner])
            return

        affs = np.asarray(in_ds[(slice(None),) + outer]).astype(np.float32)
        if np.dtype(in_ds.dtype) == np.dtype("uint8"):
            affs /= 255.0

        erode_by = int(config["erode_by"])
        if erode_by > 0:
            from ..ops.watershed import fit_to_hmap

            objs = fit_to_hmap(
                objs, affs[0].copy(), erode_by, config["erode_3d"]
            )
        obj_ids = np.unique(objs)
        obj_ids = obj_ids[obj_ids > 0]

        # object affinities in boundary convention, dilated in-plane, the z
        # channel topped up with the mean in-plane response (reference
        # _insert_affinities:138-152)
        affs_insert, mask = aff_ops.compute_affinities(objs, self.offsets)
        affs_insert = np.where(mask > 0, 1.0 - affs_insert, 0.0)
        dilate_by = int(config.get("dilate_by", 2))
        if dilate_by > 0:
            affs_insert = np.stack([
                np.asarray(
                    aff_ops.binary_dilation(
                        jnp.asarray(c), dilate_by, in_2d=True
                    )
                ).astype(np.float32)
                for c in affs_insert
            ])
        if affs_insert.shape[0] >= 3:
            affs_insert[0] += np.mean(affs_insert[1:3], axis=0)

        # the reference min-max-normalizes the block here (vu.normalize) — that
        # collapses uniform blocks and makes output partition-dependent; the
        # predictions are already probabilities, so clip instead
        affs = np.clip(affs + affs_insert, 0.0, 1.0)

        zero_list = config.get("zero_objects_list")
        if zero_list:
            for zero_id in obj_ids[np.isin(obj_ids, zero_list)]:
                zmask = np.asarray(
                    aff_ops.binary_erosion(jnp.asarray(objs == zero_id), 4)
                )
                affs[:, zmask] = 0.0

        if np.dtype(in_ds.dtype) == np.dtype("uint8"):
            affs = (affs * 255.0).astype("uint8")
        out_ds[inner] = affs[local].astype(in_ds.dtype, copy=False)


class EmbeddingDistancesTask(VolumeTask):
    task_name = "embedding_distances"

    def __init__(self, *args, input_paths: Sequence[str] = (),
                 input_keys: Sequence[str] = (),
                 offsets: Sequence[Sequence[int]] = ((-1, 0, 0), (0, -1, 0), (0, 0, -1)),
                 **kwargs):
        super().__init__(*args, **kwargs)
        # single-channel datasets stacked into the embedding dimension
        self.input_paths = list(input_paths) or [kwargs.get("input_path")]
        self.input_keys = list(input_keys) or [kwargs.get("input_key")]
        self.offsets = [list(o) for o in offsets]

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"norm": "l2"})
        return conf

    def get_shape(self) -> Sequence[int]:
        shape = store.file_reader(self.input_paths[0], "r")[
            self.input_keys[0]
        ].shape
        if len(shape) != 3:
            # multi-channel embedding datasets are a reference TODO too
            # (embedding_distances.py "TODO support multi-channel input data")
            raise ValueError("embedding channels must be separate 3d datasets")
        return shape

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        store.file_reader(self.output_path, "a").require_dataset(
            self.output_key,
            shape=(len(self.offsets),) + tuple(blocking.shape),
            dtype="float32",
            chunks=(1,) + tuple(blocking.block_shape),
            compression="gzip",
        )

    def process_block(self, block_id: int, blocking: Blocking, config):
        bh = blocking.block_with_halo(block_id, _offsets_halo(self.offsets))
        outer = bh.outer.slicing
        emb = np.stack([
            np.asarray(store.file_reader(p, "r")[k][outer], dtype=np.float32)
            for p, k in zip(self.input_paths, self.input_keys)
        ])
        dist = aff_ops.embedding_distances(
            emb, self.offsets, config.get("norm", "l2")
        )
        out_ds = self.output_ds()
        out_ds[(slice(None),) + bh.inner.slicing] = dist[
            (slice(None),) + bh.inner_local.slicing
        ]


class GradientsTask(VolumeTask):
    task_name = "gradients"

    def __init__(self, *args, input_paths: Sequence[str] = (),
                 input_keys: Sequence[str] = (), **kwargs):
        super().__init__(*args, **kwargs)
        self.input_paths = list(input_paths) or [kwargs.get("input_path")]
        self.input_keys = list(input_keys) or [kwargs.get("input_key")]

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"halo": [2, 2, 2], "average_gradient": True})
        return conf

    def get_shape(self) -> Sequence[int]:
        shape = store.file_reader(self.input_paths[0], "r")[
            self.input_keys[0]
        ].shape
        if len(shape) != 3:
            raise ValueError("gradient channels must be separate 3d datasets")
        return shape

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        # averaged: one 3d volume; per-channel: leading channel axis
        # (reference gradients.py _compute_average/_compute_all)
        shape = tuple(blocking.shape)
        if not config.get("average_gradient", True):
            shape = (len(self.input_paths),) + shape
            chunks = (1,) + tuple(blocking.block_shape)
        else:
            chunks = tuple(blocking.block_shape)
        store.file_reader(self.output_path, "a").require_dataset(
            self.output_key, shape=shape, dtype="float32",
            chunks=tuple(min(c, s) for c, s in zip(chunks, shape)),
            compression="gzip",
        )

    def process_block(self, block_id: int, blocking: Blocking, config):
        halo = config.get("halo", [2, 2, 2])
        average = config.get("average_gradient", True)
        bh = blocking.block_with_halo(block_id, halo)
        outer = bh.outer.slicing
        out_ds = self.output_ds()
        grads = []
        for p, k in zip(self.input_paths, self.input_keys):
            x = np.asarray(store.file_reader(p, "r")[k][outer], dtype=np.float32)
            grads.append(np.asarray(aff_ops.gradient_mean(jnp.asarray(x))))
        local = bh.inner_local.slicing
        if average:
            out = np.mean(grads, axis=0)
            out_ds[bh.inner.slicing] = out[local]
        else:
            out = np.stack(grads)
            out_ds[(slice(None),) + bh.inner.slicing] = out[
                (slice(None),) + local
            ]
