"""Mask-driven ROI restriction (reference masking/ package).

Two tasks:

* ``BlocksFromMaskTask`` — compute the list of blocks intersecting a (possibly
  lower-resolution) mask and write it as a JSON block list, consumed by every
  other task through the global ``block_list_path`` config
  (reference blocks_from_mask.py:22; nearest-neighbor mask upscaling mirrors
  elf's ResizedVolume).
* ``MinfilterTask`` — halo'd minimum filter over a mask so that every block
  whose *receptive field* touches masked-out voxels is excluded (used to guard
  NN inference borders; reference minfilter.py:25).  The filter itself is
  ``lax.reduce_window`` min on device — one batched dispatch per block batch.
"""

from __future__ import annotations

import json
import os
from functools import partial
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np

from ..ops.filters import minimum_filter
from ..parallel.dispatch import read_block_batch, write_block_batch
from ..runtime import hbm
from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, read_threads


def resize_nearest(data: np.ndarray, shape: Sequence[int]) -> np.ndarray:
    """Nearest-neighbor resize via index mapping (the moral equivalent of
    elf's ResizedVolume used by the reference, blocks_from_mask.py:115)."""
    if tuple(data.shape) == tuple(shape):
        return data
    idx = tuple(
        np.minimum(
            (np.arange(ns) * ds / ns).astype(np.int64), ds - 1
        )
        for ns, ds in zip(shape, data.shape)
    )
    return data[np.ix_(*idx)]


class BlocksFromMaskTask(VolumeSimpleTask):
    """Write the JSON list of blocks overlapping the mask
    (reference blocks_from_mask.py:22-133)."""

    task_name = "blocks_from_mask"

    def __init__(
        self,
        *args,
        mask_path: str = None,
        mask_key: str = None,
        shape: Sequence[int] = None,
        output_path: str = None,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.shape = list(shape) if shape is not None else None
        self.output_path = output_path

    def run_impl(self) -> None:
        from ..runtime import config as cfg

        gconf = cfg.global_config(self.config_dir)
        mask = np.asarray(
            store.file_reader(self.mask_path, "r")[self.mask_key][:]
        ).astype(bool)
        shape = self.shape if self.shape is not None else list(mask.shape)
        mask = resize_nearest(mask, shape)

        blocking = Blocking(shape, gconf["block_shape"])
        # one pass over the grid: a block is kept iff any mask voxel inside
        blocks_in_mask = [
            bid
            for bid in range(blocking.n_blocks)
            if bool(np.any(mask[blocking.block(bid).slicing]))
        ]
        os.makedirs(os.path.dirname(os.path.abspath(self.output_path)),
                    exist_ok=True)
        with open(self.output_path, "w") as f:
            json.dump(blocks_in_mask, f)
        self.log(
            f"{len(blocks_in_mask)}/{blocking.n_blocks} blocks intersect the mask"
        )


@partial(jax.jit, static_argnames=("size",))
def _minfilter_batch(batch, size):
    return jax.vmap(lambda m: minimum_filter(m, size))(batch)


class MinfilterTask(VolumeTask):
    """Halo'd minimum filter over a binary mask (reference minfilter.py:25-119)."""

    task_name = "minfilter"
    output_dtype = "uint8"

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"filter_shape": [10, 100, 100]})
        return conf

    def _halo(self, config) -> Sequence[int]:
        # halo = half the filter extent, rounded up (reference minfilter.py:83)
        return [fs // 2 + 1 for fs in config["filter_shape"]]

    # -- split batch protocol (three-stage executor pipeline) ---------------

    def read_batch(self, block_ids, blocking: Blocking, config):
        halo = self._halo(config)
        # the device-source tag marks the replicate-pad edit below: the
        # cached upload holds the EDITED batch, so the key must never
        # collide with a plain zero-padded read of the same region
        batch = read_block_batch(self.input_ds(), blocking, block_ids,
                                 halo=halo, n_threads=read_threads(config),
                                 dtype="float32",
                                 device_source=(self.input_path,
                                                self.input_key,
                                                ("minfilter-read",), config))
        if batch.data is None:
            return batch  # device probe hit: the edited batch is resident
        # replicate-pad the static-shape padding: zero fill would leak
        # "masked out" into border blocks through the min window
        full_shape = batch.data.shape[1:]
        for i, bh in enumerate(batch.blocks):
            true_shape = tuple(e - b for b, e in zip(bh.outer.begin, bh.outer.end))
            if true_shape != full_shape:
                arr = batch.data[i][tuple(slice(0, s) for s in true_shape)]
                batch.data[i] = np.pad(
                    arr,
                    [(0, f - s) for f, s in zip(full_shape, true_shape)],
                    mode="edge",
                )
        return batch

    def upload_batch(self, batch, blocking: Blocking, config):
        hbm.batch_device(batch, config)
        return batch

    def stack_payloads(self, payloads, blocking: Blocking, config):
        return hbm.stack_block_batches(payloads, config)

    def unstack_results(self, result, counts, blocking: Blocking, config):
        batch, out = result
        return list(zip(
            hbm.split_block_batch(batch, counts),
            hbm.split_stacked(out, counts),
        ))

    def compute_batch(self, batch, blocking: Blocking, config):
        db = hbm.batch_device(batch, config)
        out = _minfilter_batch(
            db.arrays[0], tuple(int(f) for f in config["filter_shape"])
        )
        return batch, np.asarray(out)[:db.n]

    def write_batch(self, result, blocking: Blocking, config):
        batch, out = result
        write_block_batch(
            self.output_ds(), batch, out, cast="uint8",
            n_threads=read_threads(config),
        )

    def _run_batch(self, block_ids, blocking: Blocking, config):
        self.write_batch(
            self.compute_batch(
                self.read_batch(block_ids, blocking, config), blocking, config
            ),
            blocking, config,
        )

    def process_block(self, block_id, blocking, config):
        self._run_batch([block_id], blocking, config)

    def process_block_batch(self, block_ids, blocking, config):
        self._run_batch(block_ids, blocking, config)
