"""Per-segment morphology: sizes, centers of mass, bounding boxes.

Reference morphology/{block_morphology,merge_morphology}.py via
nifty.distributed (SURVEY.md §2.4).  Output table columns follow the reference
layout (block_morphology.py:128-134):

  [id, size, com_z, com_y, com_x, bb_begin_z, .., bb_end_z, .., bb_end_x]
"""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np

from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, resolve_n_blocks

MORPHOLOGY_KEY = "morphology/blocks"
MORPHOLOGY_NAME = "morphology.npy"
N_COLS = 11  # id, size, com*3, bb_begin*3, bb_end*3


def block_morphology(seg: np.ndarray, offset) -> np.ndarray:
    """Per-id partial morphology of one block (global coordinates)."""
    ids, inv = np.unique(seg, return_inverse=True)
    inv = inv.reshape(seg.shape)
    n = ids.size
    counts = np.bincount(inv.reshape(-1), minlength=n).astype(np.float64)
    out = np.zeros((n, N_COLS))
    out[:, 0] = ids
    out[:, 1] = counts
    coords = np.indices(seg.shape).reshape(3, -1)
    flat = inv.reshape(-1)
    for d in range(3):
        sums = np.bincount(flat, weights=coords[d], minlength=n)
        out[:, 2 + d] = sums / counts + offset[d]
        mins = np.full(n, np.inf)
        maxs = np.full(n, -np.inf)
        np.minimum.at(mins, flat, coords[d])
        np.maximum.at(maxs, flat, coords[d])
        out[:, 5 + d] = mins + offset[d]
        out[:, 8 + d] = maxs + offset[d] + 1
    return out


def merge_morphology(partials) -> np.ndarray:
    """Combine per-block partial tables: sizes sum, COM weighted, bbox min/max."""
    all_rows = np.concatenate(partials, axis=0)
    ids = np.unique(all_rows[:, 0])
    out = np.zeros((ids.size, N_COLS))
    out[:, 0] = ids
    idx = np.searchsorted(ids, all_rows[:, 0])
    np.add.at(out[:, 1], idx, all_rows[:, 1])
    for d in range(3):
        com_w = np.zeros(ids.size)
        np.add.at(com_w, idx, all_rows[:, 2 + d] * all_rows[:, 1])
        out[:, 2 + d] = com_w / out[:, 1]
        mins = np.full(ids.size, np.inf)
        maxs = np.full(ids.size, -np.inf)
        np.minimum.at(mins, idx, all_rows[:, 5 + d])
        np.maximum.at(maxs, idx, all_rows[:, 8 + d])
        out[:, 5 + d] = mins
        out[:, 8 + d] = maxs
    return out


class BlockMorphologyTask(VolumeTask):
    task_name = "block_morphology"
    output_dtype = None

    def process_block(self, block_id: int, blocking: Blocking, config):
        block = blocking.block(block_id)
        seg = self.input_ds()[block.slicing]
        table = block_morphology(seg, block.begin)
        out = self.tmp_ragged(MORPHOLOGY_KEY, blocking.n_blocks, np.float64)
        out.write_chunk((block_id,), table.reshape(-1))


class MergeMorphologyTask(VolumeSimpleTask):
    task_name = "merge_morphology"

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(self.config_dir, self.input_path, self.input_key)
        ds = self.tmp_store()[MORPHOLOGY_KEY]
        partials = []
        for bid in range(n_blocks):
            chunk = ds.read_chunk((bid,))
            if chunk is not None and chunk.size:
                partials.append(chunk.reshape(-1, N_COLS))
        table = (
            merge_morphology(partials)
            if partials
            else np.zeros((0, N_COLS))
        )
        np.save(os.path.join(self.tmp_folder, MORPHOLOGY_NAME), table)
        self.log(f"morphology for {table.shape[0]} segments")


def load_morphology(tmp_folder: str) -> np.ndarray:
    return np.load(os.path.join(tmp_folder, MORPHOLOGY_NAME))
