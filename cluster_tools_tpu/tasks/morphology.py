"""Per-segment morphology: sizes, centers of mass, bounding boxes.

Reference morphology/{block_morphology,merge_morphology}.py via
nifty.distributed (SURVEY.md §2.4).  Output table columns follow the reference
layout (block_morphology.py:128-134):

  [id, size, com_z, com_y, com_x, bb_begin_z, .., bb_end_z, .., bb_end_x]
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Sequence

import numpy as np

from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, merge_threads, read_ragged_chunks, resolve_n_blocks

MORPHOLOGY_KEY = "morphology/blocks"
MORPHOLOGY_NAME = "morphology.npy"
N_COLS = 11  # id, size, com*3, bb_begin*3, bb_end*3


def block_morphology(seg: np.ndarray, offset) -> np.ndarray:
    """Per-id partial morphology of one block (global coordinates)."""
    ids, inv = np.unique(seg, return_inverse=True)
    inv = inv.reshape(seg.shape)
    n = ids.size
    counts = np.bincount(inv.reshape(-1), minlength=n).astype(np.float64)
    out = np.zeros((n, N_COLS))
    out[:, 0] = ids
    out[:, 1] = counts
    coords = np.indices(seg.shape).reshape(3, -1)
    flat = inv.reshape(-1)
    for d in range(3):
        sums = np.bincount(flat, weights=coords[d], minlength=n)
        out[:, 2 + d] = sums / counts + offset[d]
        mins = np.full(n, np.inf)
        maxs = np.full(n, -np.inf)
        np.minimum.at(mins, flat, coords[d])
        np.maximum.at(maxs, flat, coords[d])
        out[:, 5 + d] = mins + offset[d]
        out[:, 8 + d] = maxs + offset[d] + 1
    return out


def merge_morphology(partials) -> np.ndarray:
    """Combine per-block partial tables: sizes sum, COM weighted, bbox min/max."""
    all_rows = np.concatenate(partials, axis=0)
    ids = np.unique(all_rows[:, 0])
    out = np.zeros((ids.size, N_COLS))
    out[:, 0] = ids
    idx = np.searchsorted(ids, all_rows[:, 0])
    np.add.at(out[:, 1], idx, all_rows[:, 1])
    for d in range(3):
        com_w = np.zeros(ids.size)
        np.add.at(com_w, idx, all_rows[:, 2 + d] * all_rows[:, 1])
        out[:, 2 + d] = com_w / out[:, 1]
        mins = np.full(ids.size, np.inf)
        maxs = np.full(ids.size, -np.inf)
        np.minimum.at(mins, idx, all_rows[:, 5 + d])
        np.maximum.at(maxs, idx, all_rows[:, 8 + d])
        out[:, 5 + d] = mins
        out[:, 8 + d] = maxs
    return out


def load_morphology(tmp_folder: str) -> np.ndarray:
    return np.load(os.path.join(tmp_folder, MORPHOLOGY_NAME))


class IdBlockTask(VolumeTask):
    """A block task over segment-id ranges instead of voxels."""

    id_chunk = 64
    _morpho_cache = None

    def get_shape(self) -> Sequence[int]:
        morpho = load_morphology(self.tmp_folder)
        max_id = int(morpho[:, 0].max()) if len(morpho) else 0
        return (max_id + 1, 1, 1)

    def get_block_shape(self, gconf) -> List[int]:
        return [self.id_chunk, 1, 1]

    def morphology_by_id(self) -> Dict[int, np.ndarray]:
        """Morphology rows keyed by id, loaded once per task instance (not
        once per block — that would be O(n_ids^2) over the id blocking)."""
        if self._morpho_cache is None:
            morpho = load_morphology(self.tmp_folder)
            self._morpho_cache = {int(r[0]): r for r in morpho}
        return self._morpho_cache


class RegionCentersTask(IdBlockTask):
    """Representative interior point per segment: the EDT-argmax of the
    object mask inside its morphology bounding box
    (reference morphology/region_centers.py:29,106-133).

    The id space is blocked (reference id_chunks=2000); each object is cropped
    by its bbox and its most interior voxel written to a (n_labels, 3) float32
    table.  The EDT runs on host (scipy, C): per-object crops are ragged, and
    ragged shapes would force one XLA recompile per distinct crop shape.
    """

    task_name = "region_centers"
    id_chunk = 2000

    def __init__(self, *args, ignore_label=None, resolution=(1, 1, 1),
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.ignore_label = ignore_label
        self.resolution = list(resolution)

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        from ..utils import store

        n_labels = blocking.shape[0]
        store.file_reader(self.output_path, "a").require_dataset(
            self.output_key,
            shape=(n_labels, 3),
            dtype="float32",
            chunks=(min(self.id_chunk, n_labels), 3),
            compression="gzip",
        )

    def process_block(self, block_id: int, blocking: Blocking, config):
        from scipy.ndimage import distance_transform_edt

        block = blocking.block(block_id)
        label_begin, label_end = block.begin[0], block.end[0]
        by_id = self.morphology_by_id()
        seg_ds = self.input_ds()
        centers = np.zeros((label_end - label_begin, 3), dtype=np.float32)
        for label_id in range(label_begin, label_end):
            row = by_id.get(label_id)
            if row is None or label_id == self.ignore_label:
                continue
            bb = tuple(
                slice(int(b), int(e))
                for b, e in zip(row[5:8], row[8:11])
            )
            obj = seg_ds[bb] == label_id
            if not obj.any():
                continue
            dist = distance_transform_edt(obj, sampling=self.resolution)
            center = np.unravel_index(np.argmax(dist), obj.shape)
            centers[label_id - label_begin] = [
                c + b.start for c, b in zip(center, bb)
            ]
        self.output_ds()[label_begin:label_end] = centers


class BlockMorphologyTask(VolumeTask):
    task_name = "block_morphology"
    output_dtype = None

    def process_block(self, block_id: int, blocking: Blocking, config):
        block = blocking.block(block_id)
        seg = self.input_ds()[block.slicing]
        table = block_morphology(seg, block.begin)
        out = self.tmp_ragged(MORPHOLOGY_KEY, blocking.n_blocks, np.float64)
        out.write_chunk((block_id,), table.reshape(-1))


class MergeMorphologyTask(VolumeSimpleTask):
    task_name = "merge_morphology"

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(self.config_dir, self.input_path, self.input_key)
        ds = self.tmp_store()[MORPHOLOGY_KEY]
        chunks = read_ragged_chunks(ds, n_blocks, merge_threads(self))
        partials = [
            c.reshape(-1, N_COLS) for c in chunks if c is not None and c.size
        ]
        table = (
            merge_morphology(partials)
            if partials
            else np.zeros((0, N_COLS))
        )
        np.save(os.path.join(self.tmp_folder, MORPHOLOGY_NAME), table)
        self.log(f"morphology for {table.shape[0]} segments")
