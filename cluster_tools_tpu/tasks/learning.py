"""Edge-classifier learning: edge ground truth + random-forest training and
prediction (reference learning/{edge_labels,learn_rf}.py + costs/predict.py).

The RF itself stays on host (sklearn, like the reference) — it is a tiny
sequential model over per-edge feature rows; the expensive parts (feature
accumulation, node-overlap voting) already run on device in their own tasks.

Scratch layout (per dataset tmp_folder):
  edge_labels.npy   int8 per edge: 1 = GT boundary, 0 = merged, -1 = ignore
  edge_probs.npy    float32 per edge: RF boundary probability
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .base import VolumeSimpleTask
from .features import FEATURES_KEY
from .graph import load_graph
from .lifted_features import dense_node_labels
from ..runtime.task import SimpleTask
from ..utils import store

EDGE_LABELS_NAME = "edge_labels.npy"
EDGE_PROBS_NAME = "edge_probs.npy"


class EdgeLabelsTask(VolumeSimpleTask):
    """GT edge labels from node-overlap ground truth: an edge is a true
    boundary iff its endpoint nodes carry different GT labels
    (reference edge_labels.py:19,100-125)."""

    task_name = "edge_labels"

    def __init__(self, *args, node_labels_path: Optional[str] = None,
                 ignore_label_gt: bool = False, **kwargs):
        super().__init__(*args, node_labels_path=node_labels_path,
                         ignore_label_gt=ignore_label_gt, **kwargs)

    def run_impl(self) -> None:
        nodes, edges = load_graph(self.tmp_store())
        gt = dense_node_labels(self, nodes, self.node_labels_path)
        lu = gt[edges[:, 0]]
        lv = gt[edges[:, 1]]
        edge_labels = (lu != lv).astype(np.int8)
        if self.ignore_label_gt:
            edge_labels[(lu == 0) | (lv == 0)] = -1
        np.save(os.path.join(self.tmp_folder, EDGE_LABELS_NAME), edge_labels)
        n_pos = int((edge_labels == 1).sum())
        self.log(
            f"edge labels: {edge_labels.size} edges, {n_pos} boundary, "
            f"{int((edge_labels == -1).sum())} ignored"
        )


class LearnRFTask(SimpleTask):
    """Random-forest training over one or more datasets' edge features
    (reference learn_rf.py:25,100-147)."""

    task_name = "learn_rf"

    def __init__(self, tmp_folder, config_dir=None, max_jobs=None,
                 dependencies=(), tmp_folders: Sequence[str] = (),
                 output_path: str = None):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        # one scratch folder per training dataset (each holds its own graph,
        # features and edge labels — the analog of features_dict/labels_dict)
        self.tmp_folders = list(tmp_folders) or [tmp_folder]
        self.output_path = output_path

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"n_trees": 100})
        return conf

    def run_impl(self) -> None:
        from sklearn.ensemble import RandomForestClassifier

        conf = self.get_task_config()
        features, labels = [], []
        from .base import scratch_store_path

        for folder in self.tmp_folders:
            feats = store.file_reader(
                scratch_store_path(folder), "r"
            )[FEATURES_KEY][:]
            labs = np.load(os.path.join(folder, EDGE_LABELS_NAME))
            if len(labs) != len(feats):
                raise ValueError(
                    f"{folder}: {len(labs)} labels vs {len(feats)} feature rows"
                )
            keep = labs != -1
            features.append(feats[keep])
            labels.append(labs[keep])
        X = np.concatenate(features, axis=0)
        y = np.concatenate(labels, axis=0)
        self.log(f"learning RF on {X.shape[0]} edges x {X.shape[1]} features")
        rf = RandomForestClassifier(
            n_estimators=int(conf.get("n_trees", 100)),
            n_jobs=int(conf.get("threads_per_job", 1)),
        )
        rf.fit(X, y)
        os.makedirs(os.path.dirname(os.path.abspath(self.output_path)),
                    exist_ok=True)
        with open(self.output_path, "wb") as f:
            pickle.dump(rf, f)


class PredictEdgeProbabilitiesTask(VolumeSimpleTask):
    """RF boundary probability per edge (reference costs/predict.py:23)."""

    task_name = "predict_edge_probabilities"

    def __init__(self, *args, rf_path: str = None, **kwargs):
        super().__init__(*args, rf_path=rf_path, **kwargs)

    def run_impl(self) -> None:
        conf = self.get_task_config()
        with open(self.rf_path, "rb") as f:
            rf = pickle.load(f)
        rf.n_jobs = int(conf.get("threads_per_job", 1))
        feats = self.tmp_store()[FEATURES_KEY][:]
        proba = rf.predict_proba(feats)
        if proba.shape[1] == 1:
            # degenerate RF trained on a single class — constant probability
            p = float(rf.classes_[0])
            self.log(f"WARNING: RF saw a single class ({p}); constant output")
            probs = np.full(feats.shape[0], p, dtype="float32")
        else:
            probs = proba[:, 1].astype("float32")
        np.save(os.path.join(self.tmp_folder, EDGE_PROBS_NAME), probs)
        self.log(f"predicted boundary probabilities for {probs.size} edges")
