"""Distributed NN inference over halo'd blocks.

Reference inference/inference.py:30 ``InferenceBase`` + the per-block
dask.delayed 5-stage pipeline (:217-327).  The TPU re-expression:

  * blocks are read with reflect-padded halos (``_load_input`` semantics,
    inference.py:175-205) by host prefetch threads;
  * predict is a batched jit flax forward (frameworks.JaxPredictor) — the
    device works on batch N while the host reads batch N+1 and writes batch
    N-1 (the dask-pipeline IO/compute overlap, without dask);
  * outputs map to one or more datasets through ``output_key`` channel ranges,
    optionally channel-accumulated, optionally quantized to uint8 with the
    mirrored scaling of the reference (``_to_uint8``, inference.py:208-214).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeTask
from .frameworks import get_predictor, get_preprocessor


def load_input_with_halo(ds, begin, block_shape, halo, padding_mode="reflect"):
    """Reflect-padded halo'd read (reference _load_input, inference.py:175-205)."""
    shape = ds.shape[-3:]
    starts = [b - h for b, h in zip(begin, halo)]
    stops = [b + bs + h for b, bs, h in zip(begin, block_shape, halo)]
    pad_left = tuple(max(0, -s) for s in starts)
    pad_right = tuple(max(0, st - sh) for st, sh in zip(stops, shape))
    bb = tuple(
        slice(max(0, s), min(sh, st)) for s, st, sh in zip(starts, stops, shape)
    )
    if len(ds.shape) == 4:
        bb = (slice(None),) + bb
    data = np.asarray(ds[bb])
    if any(pad_left) or any(pad_right):
        pad = [(pl, pr) for pl, pr in zip(pad_left, pad_right)]
        if data.ndim == 4:
            pad = [(0, 0)] + pad
        data = np.pad(data, pad, mode=padding_mode)
    return data


def to_uint8(data, float_range=(0.0, 1.0), safe_scale=True):
    """Mirrored quantization (reference _to_uint8, inference.py:208-214)."""
    if safe_scale:
        mult = np.floor(255.0 / (float_range[1] - float_range[0]))
    else:
        mult = np.ceil(255.0 / (float_range[1] - float_range[0]))
    add = 255 - mult * float_range[1]
    return np.clip((data * mult + add).round(), 0, 255).astype("uint8")


class InferenceTask(VolumeTask):
    """Block-wise prediction.

    ``output_key`` is a dict {dataset_key: [channel_start, channel_stop]}
    (reference output_key DictParameter); a 3d dataset gets one channel (or an
    accumulated reduction), a 4d dataset the full range.
    """

    task_name = "inference"

    def __init__(
        self,
        *args,
        checkpoint_path: str = None,
        halo: Sequence[int] = (0, 0, 0),
        output_key: Optional[Dict[str, Sequence[int]]] = None,
        mask_path: str = None,
        mask_key: str = None,
        framework: str = "jax",
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.checkpoint_path = checkpoint_path
        self.halo = list(halo)
        self.output_key_map = dict(output_key or {})
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.framework = framework
        self._predictor = None

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update(
            {
                "dtype": "uint8",
                "compression": "gzip",
                "chunks": None,
                "channel_accumulation": None,
                "prep_model": None,
                # eager-torch checkpoint knobs (frameworks._load_torch_model):
                # state-dict checkpoints need the module class to construct;
                # use_best picks best_checkpoint.pytorch in inferno dirs
                "model_class": None,
                "model_kwargs": None,
                "mixed_precision": False,
                "use_best": True,
                "preprocess": "zero_mean_unit_variance",
                "batch_size": 1,
                "prefetch_threads": 2,
                # mirror test-time augmentation: None (off) or "all"
                # (reference frameworks.py:103-131 via neurofire)
                "augmentation_mode": None,
                "augmentation_dim": 3,
            }
        )
        return conf

    # -- outputs -------------------------------------------------------------

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        dtype = config.get("dtype", "uint8")
        chunks = config.get("chunks")
        chunks = (
            tuple(chunks)
            if chunks is not None
            else tuple(max(1, bs // 2) for bs in blocking.block_shape)
        )
        accumulation = config.get("channel_accumulation")
        f = store.file_reader(self.output_path, "a")
        for key, (c0, c1) in self.output_key_map.items():
            n_channels = c1 - c0
            if n_channels > 1 and accumulation is None:
                shape = (n_channels,) + tuple(blocking.shape)
                ds_chunks = (1,) + chunks
            else:
                shape = tuple(blocking.shape)
                ds_chunks = chunks
            f.require_dataset(
                key,
                shape=shape,
                dtype=dtype,
                chunks=tuple(min(c, s) for c, s in zip(ds_chunks, shape)),
                compression=config.get("compression", "gzip"),
            )

    def predictor(self, config):
        if self._predictor is None:
            self._predictor = get_predictor(self.framework)(
                self.checkpoint_path,
                self.halo,
                prep_model=config.get("prep_model"),
                use_best=config.get("use_best", True),
                model_class=config.get("model_class"),
                model_kwargs=config.get("model_kwargs"),
                mixed_precision=config.get("mixed_precision", False),
                augmentation_mode=config.get("augmentation_mode"),
                augmentation_dim=config.get("augmentation_dim", 3),
                config=config,
            )
        return self._predictor

    # -- per-block -----------------------------------------------------------

    def _load_block(self, block_id, blocking, in_ds, mask_ds):
        block = blocking.block(block_id)
        if mask_ds is not None:
            m = np.asarray(mask_ds[block.slicing]).astype(bool)
            if not m.any():
                return None
        return load_input_with_halo(
            in_ds, block.begin, blocking.block_shape, self.halo
        )

    def _write_block(self, block_id, blocking, out_datasets, output, config):
        block = blocking.block(block_id)
        bb = block.slicing
        actual = tuple(b.stop - b.start for b in bb)
        if output.ndim == 3:
            output = output[None]
        # crop overhanging padding at the volume end (halo itself was cropped
        # by the predictor)
        output = output[(slice(None),) + tuple(slice(0, a) for a in actual)]

        accumulation = config.get("channel_accumulation")
        dtype = config.get("dtype", "uint8")
        for key, (c0, c1) in self.output_key_map.items():
            ds = out_datasets[key]
            chan_out = output[c0:c1]
            if len(ds.shape) == 3:
                if accumulation is not None and chan_out.shape[0] > 1:
                    chan_out = getattr(np, accumulation)(chan_out, axis=0)
                else:
                    chan_out = chan_out[0]
                out_bb = bb
            else:
                out_bb = (slice(None),) + bb
            if dtype == "uint8" and chan_out.dtype != np.uint8:
                chan_out = to_uint8(chan_out)
            ds[out_bb] = chan_out.astype(ds.dtype, copy=False)

    def process_block(self, block_id, blocking, config):
        self.process_block_batch([block_id], blocking, config)

    def process_block_batch(self, block_ids: List[int], blocking: Blocking, config):
        in_ds = self.input_ds()
        mask_ds = (
            store.file_reader(self.mask_path, "r")[self.mask_key]
            if self.mask_path
            else None
        )
        out_datasets = {
            key: store.file_reader(self.output_path, "a")[key]
            for key in self.output_key_map
        }
        predictor = self.predictor(config)
        preprocess = get_preprocessor(
            config.get("preprocess", "zero_mean_unit_variance")
        )
        batch_size = int(config.get("batch_size", 1))
        n_threads = int(config.get("prefetch_threads", 2))

        # pipelined host IO ↔ device compute: prefetch reads ahead, the
        # writer drains behind (reference dask pipeline, inference.py:319-327)
        with ThreadPoolExecutor(max(1, n_threads)) as pool:
            loads = {
                bid: pool.submit(self._load_block, bid, blocking, in_ds, mask_ds)
                for bid in block_ids
            }
            pending = []
            for lo in range(0, len(block_ids), batch_size):
                chunk = block_ids[lo : lo + batch_size]
                datas = {bid: loads[bid].result() for bid in chunk}
                live = [bid for bid in chunk if datas[bid] is not None]
                if not live:
                    continue
                batch = np.stack([preprocess(datas[bid]) for bid in live])
                if batch.ndim == 4:  # [B, z, y, x] → add channel
                    batch = batch[:, None]
                out = predictor(batch)
                for i, bid in enumerate(live):
                    pending.append(
                        pool.submit(
                            self._write_block, bid, blocking, out_datasets,
                            out[i], config,
                        )
                    )
            for fut in pending:
                fut.result()
