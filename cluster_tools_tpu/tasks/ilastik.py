"""ilastik integration: block-wise headless pixel classification and the
carving-project export (reference ilastik/ package, SURVEY.md §2.6).

* ``IlastikPredictionTask`` — the subprocess-per-block seam
  (reference prediction.py:104-160): assembles the headless command
  (``run_ilastik.sh``/``ilastik.py --headless --project=… --cutout_subregion=…``)
  for each halo'd block and runs it; each block lands in its own
  ``<prefix>_block<i>.h5`` under ``exported_data``.  ilastik itself is an
  external install (never shipped with either framework) — the task validates
  the executable up front and fails with a clear error when absent, so the
  seam is testable with any stand-in executable honoring the CLI contract.
* ``MergePredictionsTask`` — reads each block's h5, crops the halo back to the
  inner block and writes the channel-first result into the output dataset
  (reference merge_predictions.py:91-114, zyxc→czyx transpose).
* ``StackPredictionsTask`` — stacks the raw volume on top of the prediction
  channels into a (1+C, z, y, x) dataset (reference stack_predictions.py).
* ``WriteCarvingTask`` — serializes the RAG + edge features of a watershed
  oversegmentation into an ilastik carving project (.ilp h5): the
  vigra-adjacency-list-graph layout [counts, uv ids, neighborhoods] plus the
  metadata groups ilastik expects (reference carving.py:26-131).
"""

from __future__ import annotations

import os
import subprocess
import time
import uuid
from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..runtime.task import SimpleTask
from ..utils import store
from ..utils.blocking import Blocking
from .base import VolumeTask
from .features import FEATURES_KEY
from .graph import EDGES_KEY, NODES_KEY


def ilastik_executable(ilastik_folder: str) -> str:
    """``run_ilastik.sh`` if present, else ``ilastik.py``
    (reference prediction.py:131-135)."""
    exe = os.path.join(ilastik_folder, "run_ilastik.sh")
    if not os.path.exists(exe):
        exe = os.path.join(ilastik_folder, "ilastik.py")
    if not os.path.exists(exe):
        raise RuntimeError(
            f"no ilastik executable (run_ilastik.sh / ilastik.py) under "
            f"{ilastik_folder!r}"
        )
    return exe


def prediction_block_path(prefix: str, block_id: int) -> str:
    return f"{prefix}_block{block_id}.h5"


class IlastikPredictionTask(VolumeTask):
    """Headless ilastik pixel classification, one subprocess per halo'd block
    (reference prediction.py:21,104-160)."""

    task_name = "ilastik_prediction"
    output_dtype = None  # block h5 files; merged by MergePredictionsTask

    def __init__(
        self,
        *args,
        ilastik_folder: str = None,
        ilastik_project: str = None,
        halo: Sequence[int] = (0, 0, 0),
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.ilastik_folder = ilastik_folder
        self.ilastik_project = ilastik_project
        self.halo = list(halo)

    @property
    def output_prefix(self) -> str:
        return os.path.join(self.tmp_folder, "ilastik_prediction")

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        ilastik_executable(self.ilastik_folder)  # fail fast when absent
        if not os.path.exists(self.ilastik_project):
            raise RuntimeError(
                f"ilastik project {self.ilastik_project!r} does not exist"
            )

    def process_block(self, block_id: int, blocking: Blocking, config):
        block = blocking.block_with_halo(block_id, self.halo)
        exe = ilastik_executable(self.ilastik_folder)
        out_path = prediction_block_path(self.output_prefix, block_id)
        # ilastik's cutout axis order: spatial + trailing channel slot
        # (reference prediction.py:113-127)
        start = ",".join(str(b) for b in block.outer.begin) + ",None"
        stop = ",".join(str(e) for e in block.outer.end) + ",None"
        cmd = [
            exe,
            "--headless",
            f"--project={self.ilastik_project}",
            "--output_format=compressed hdf5",
            f"--raw_data={self.input_path}/{self.input_key}",
            f"--cutout_subregion=[({start}), ({stop})]",
            f"--output_filename_format={out_path}",
            "--readonly=1",
        ]
        self.log(f"block {block_id}: {' '.join(cmd)}")
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"ilastik failed on block {block_id} "
                f"(exit {proc.returncode}):\n{proc.stderr[-4000:]}"
            )
        if not os.path.exists(out_path):
            raise RuntimeError(
                f"ilastik produced no output for block {block_id} ({out_path})"
            )


class MergePredictionsTask(VolumeTask):
    """Write each block h5's inner region into the merged output dataset
    (reference merge_predictions.py:91-114).  ilastik emits trailing-channel
    (z, y, x, c); the output dataset is channel-first (c, z, y, x)."""

    task_name = "merge_predictions"

    def __init__(
        self,
        *args,
        tmp_prefix: str = None,
        halo: Sequence[int] = (0, 0, 0),
        n_channels: int = 1,
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.tmp_prefix = tmp_prefix
        self.halo = list(halo)
        self.n_channels = int(n_channels)

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        shape = tuple(blocking.shape)
        if self.n_channels > 1:
            shape = (self.n_channels,) + shape
        store.file_reader(self.output_path, "a").require_dataset(
            self.output_key,
            shape=shape,
            dtype="float32",
            chunks=((1,) if self.n_channels > 1 else ())
            + tuple(blocking.block_shape),
            compression="gzip",
        )

    def process_block(self, block_id: int, blocking: Blocking, config):
        block = blocking.block_with_halo(block_id, self.halo)
        tmp_path = prediction_block_path(self.tmp_prefix, block_id)
        with store.file_reader(tmp_path, "r") as f:
            data = f["exported_data"][block.inner_local.slicing]
        inner_bb = block.inner.slicing
        if self.n_channels > 1:
            data = np.moveaxis(data, -1, 0)  # zyxc -> czyx
            inner_bb = (slice(None),) + inner_bb
        elif data.ndim == 4:
            data = data[..., 0]
        ds = self.output_ds()
        ds[inner_bb] = data.astype(np.float32)
        os.remove(tmp_path)


class StackPredictionsTask(VolumeTask):
    """Stack raw + prediction channels into (1+C, z, y, x)
    (reference stack_predictions.py:23-160)."""

    task_name = "stack_predictions"

    def __init__(
        self,
        *args,
        pred_path: str = None,
        pred_key: str = None,
        dtype: str = "float32",
        **kwargs,
    ):
        super().__init__(*args, **kwargs)
        self.pred_path = pred_path
        self.pred_key = pred_key
        self.dtype = dtype

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        pred_shape = store.file_reader(self.pred_path, "r")[self.pred_key].shape
        if len(pred_shape) != 4 or tuple(pred_shape[1:]) != tuple(blocking.shape):
            raise ValueError(
                f"prediction shape {pred_shape} does not stack onto raw shape "
                f"{tuple(blocking.shape)}"
            )
        store.file_reader(self.output_path, "a").require_dataset(
            self.output_key,
            shape=(1 + pred_shape[0],) + tuple(blocking.shape),
            dtype=self.dtype,
            chunks=(1,) + tuple(blocking.block_shape),
            compression="gzip",
        )

    def process_block(self, block_id: int, blocking: Blocking, config):
        bb = blocking.block(block_id).slicing
        raw = self.input_ds()[bb]
        pred = store.file_reader(self.pred_path, "r")[self.pred_key][
            (slice(None),) + bb
        ]
        out = self.output_ds()
        dtype = np.dtype(self.dtype)

        def to_dtype(arr):
            # float data quantized into the integer range, not truncated
            if np.issubdtype(dtype, np.integer) and np.issubdtype(
                np.asarray(arr).dtype, np.floating
            ):
                return (np.clip(arr, 0, 1) * np.iinfo(dtype).max).astype(dtype)
            return arr.astype(dtype)

        out[(slice(0, 1),) + bb] = to_dtype(raw)[None]
        out[(slice(1, 1 + pred.shape[0]),) + bb] = to_dtype(pred)


class WriteCarvingTask(SimpleTask):
    """Export the scratch-store RAG + edge features as an ilastik carving
    project (reference carving.py:10-131).

    Graph serialization follows the vigra adjacency-list-graph layout the
    reference cites: header [n_nodes, n_edges, max_node_id, max_edge_id]
    (uint32), flattened uv ids, then per-node neighborhoods
    [degree, (neighbor, edge_id)...] for every node id 0..max_node_id.
    """

    task_name = "write_carving"

    def __init__(
        self,
        tmp_folder,
        config_dir=None,
        max_jobs=None,
        dependencies=(),
        output_path: str = None,
        raw_path: str = None,
        raw_key: str = None,
        copy_inputs: bool = False,
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.output_path = output_path
        self.raw_path = raw_path
        self.raw_key = raw_key
        self.copy_inputs = copy_inputs

    def run_impl(self) -> None:
        import h5py

        from .base import scratch_store_path

        scratch = store.file_reader(scratch_store_path(self.tmp_folder), "r")
        nodes = scratch[NODES_KEY][:]
        edge_idx = scratch[EDGES_KEY][:]
        feats = scratch[FEATURES_KEY][:]
        uv = nodes[edge_idx].astype(np.uint32)

        # size by the full node set, not edge endpoints: isolated fragments
        # are graph nodes too and need seed/result-table slots
        max_node = int(nodes.max()) if nodes.size else 0
        n_nodes = max_node + 1
        n_edges = uv.shape[0]

        # per-node neighborhoods [degree, (neighbor, edge)...] — vectorized:
        # one scatter of the interleaved (dst, eid) stream into a layout with
        # degree-prefix offsets (production RAGs have 1e6+ nodes)
        order = np.argsort(
            np.concatenate([uv[:, 0], uv[:, 1]]), kind="stable"
        )
        src = np.concatenate([uv[:, 0], uv[:, 1]])[order]
        dst = np.concatenate([uv[:, 1], uv[:, 0]])[order]
        eid = np.tile(np.arange(n_edges, dtype=np.uint32), 2)[order]
        degrees = np.bincount(src, minlength=n_nodes).astype(np.uint32)
        total = n_nodes + 2 * 2 * n_edges
        nbh = np.zeros(total, dtype=np.uint32)
        # record start = prefix over (1 + 2*deg); degree goes at the start
        rec_starts = np.concatenate(
            [[0], np.cumsum(1 + 2 * degrees)[:-1]]
        ).astype(np.int64)
        nbh[rec_starts] = degrees
        # position of each (dst, eid) pair within its node's record
        within = np.arange(src.size, dtype=np.int64) - np.repeat(
            np.concatenate([[0], np.cumsum(degrees)[:-1]]).astype(np.int64),
            degrees,
        )
        base = np.repeat(rec_starts, degrees) + 1 + 2 * within
        nbh[base] = dst
        nbh[base + 1] = eid

        header = np.array(
            [n_nodes, n_edges, max_node, max(n_edges - 1, 0)], dtype=np.uint32
        )
        serialization = np.concatenate([header, uv.reshape(-1), nbh])

        uid = str(uuid.uuid4())
        with h5py.File(self.output_path, "a") as f:
            g = f.create_group("preprocessing/graph")
            g.create_dataset("graph", data=serialization, compression="gzip")
            g.create_dataset("nodeSeeds", shape=(n_nodes,), dtype="uint8")
            g.create_dataset("resultSegmentation", shape=(n_nodes,), dtype="uint8")
            g.attrs["numNodes"] = n_nodes
            # carving edge weights: mean boundary probability in 0-255
            g.create_dataset(
                "edgeWeights",
                data=(feats[:, 0] * 255).astype("float32"),
                compression="gzip",
            )
            f.create_dataset("workflowName", data=np.bytes_("Carving"))
            f.create_dataset("time", data=np.bytes_(time.ctime()))
            f.create_dataset("currentApplet", data=2)
            f.create_dataset("preprocessing/StorageVersion", data="0.1")
            f.create_dataset("preprocessing/filter", data=3)
            f.create_dataset("preprocessing/sigma", data=1.0)
            f.create_dataset("preprocessing/invert_watershed_source", data=False)
            f.create_dataset(
                "preprocessing/watershed_source", data=np.bytes_("filtered")
            )
            f.create_dataset("carving/StorageVersion", data="0.1")
            f.create_group("carving/objects")
            gi = f.create_group("Input Data")
            gi.create_dataset(
                "Role Names", data=[np.bytes_("Raw Data"), np.bytes_("Overlay")]
            )
            gi.create_dataset("StorageVersion", data="0.2")
            gi.create_group("local_data")
            gr = f.create_group("Input Data/infos/lane0000/Raw Data")
            gr.create_dataset("allowLabels", data=True)
            gr.create_dataset("axisorder", data=np.bytes_("zyx"))
            gr.create_dataset("fromstack", data=False)
            gr.create_dataset("datasetId", data=uid.encode("utf-8"))
            gr.create_dataset("display_mode", data=np.bytes_("default"))
            raw = os.path.join(self.raw_path or "", self.raw_key or "")
            gr.create_dataset("filePath", data=raw.encode("utf-8"))
            gr.create_dataset(
                "location",
                data=np.bytes_(
                    "ProjectInternal" if self.copy_inputs else "FileSystem"
                ),
            )
            gr.create_dataset("nickname", data=np.bytes_("Input"))
        self.log(
            f"carving project with {n_nodes} nodes / {n_edges} edges "
            f"-> {self.output_path}"
        )
