"""Threshold task (reference thresholded_components/threshold.py:21).

Per-block: optional gaussian pre-smoothing, then compare against the threshold.
The batch path stacks blocks and runs one jit program for the whole batch.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import filters
from ..parallel.dispatch import read_block_batch, write_block_batch
from ..runtime import hbm
from ..utils.blocking import Blocking
from .base import VolumeTask, read_threads

_MODES = {
    "greater": jnp.greater,
    "less": jnp.less,
    "equal": jnp.equal,
}


@partial(jax.jit, static_argnames=("mode", "sigma"))
def _threshold_batch(batch: jnp.ndarray, threshold: float, mode: str, sigma):
    x = filters.normalize_input(batch) if batch.dtype != jnp.float32 else batch
    if sigma:
        x = jax.vmap(lambda b: filters.gaussian(b, sigma))(x)
    return _MODES[mode](x, threshold).astype(jnp.uint8)


class ThresholdTask(VolumeTask):
    task_name = "threshold"
    output_dtype = "uint8"
    # ctt-stream: fusable chain member; typically the elided head of a
    # threshold → components chain (the mask never leaves HBM)
    fusable = True

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"threshold": 0.5, "threshold_mode": "greater", "sigma": 0.0})
        return conf

    # -- ctt-stream fusion contract ------------------------------------------

    def fused_compute_batch(self, payload, blocking: Blocking, config,
                            elided=False):
        """Device handoff for in-chain consumers: the uint8 mask stays a
        sharded device array ([B_padded, *block], plus the real batch
        size); when the mask volume is elided the host materialization is
        skipped entirely — the intermediate never leaves HBM.  The input
        upload routes through the warm device-buffer cache (ctt-hbm), so
        a back-to-back fused serve job on the same volume skips it."""
        batch = payload
        sigma = config.get("sigma", 0.0) or 0.0
        if isinstance(sigma, list):
            sigma = tuple(sigma)
        db = hbm.batch_device(batch, config)
        dev = _threshold_batch(
            db.arrays[0], float(config.get("threshold", 0.5)),
            config.get("threshold_mode", "greater"), sigma,
        )
        handoff = {"batch": batch, "labels": dev, "n": db.n}
        result = None if elided else (batch, np.asarray(dev)[:db.n])
        return result, handoff

    def fused_elided_nbytes(self, handoff, blocking: Blocking, config) -> int:
        # the uint8 mask bytes that were neither written nor re-read
        return sum(
            int(np.prod(bh.inner.shape)) for bh in handoff["batch"].blocks
        )

    # -- split batch protocol (three-stage executor pipeline) ---------------

    def read_batch(self, block_ids: List[int], blocking: Blocking, config):
        mode = config.get("threshold_mode", "greater")
        if mode not in _MODES:
            raise ValueError(f"unsupported threshold_mode {mode!r}")
        # device_source: raw float32 read, no halo — the kernel params
        # (threshold/sigma) run on device, so the upload is shareable
        # across configs and jobs of the same volume
        return read_block_batch(
            self.input_ds(), blocking, block_ids, dtype="float32",
            n_threads=read_threads(config),
            device_source=(self.input_path, self.input_key,
                           ("threshold-read",), config),
        )

    def upload_batch(self, batch, blocking: Blocking, config):
        """ctt-hbm transfer stage: the batch crosses to HBM (through the
        warm device-buffer cache) while the previous batch computes."""
        hbm.batch_device(batch, config)
        return batch

    def stack_payloads(self, payloads, blocking: Blocking, config):
        return hbm.stack_block_batches(payloads, config)

    def unstack_results(self, result, counts, blocking: Blocking, config):
        batch, labels = result
        return list(zip(
            hbm.split_block_batch(batch, counts),
            hbm.split_stacked(labels, counts),
        ))

    def compute_batch(self, batch, blocking: Blocking, config):
        sigma = config.get("sigma", 0.0) or 0.0
        if isinstance(sigma, list):
            sigma = tuple(sigma)
        db = hbm.batch_device(batch, config)
        result = _threshold_batch(
            db.arrays[0], float(config.get("threshold", 0.5)),
            config.get("threshold_mode", "greater"), sigma,
        )
        return batch, np.asarray(result)[:db.n]

    def write_batch(self, result, blocking: Blocking, config):
        batch, labels = result
        write_block_batch(
            self.output_ds(), batch, labels, cast="uint8",
            n_threads=read_threads(config),
        )

    def _run_batch(self, block_ids: List[int], blocking: Blocking, config):
        self.write_batch(
            self.compute_batch(
                self.read_batch(block_ids, blocking, config), blocking, config
            ),
            blocking, config,
        )

    def process_block(self, block_id, blocking, config):
        self._run_batch([block_id], blocking, config)

    def process_block_batch(self, block_ids, blocking, config):
        self._run_batch(block_ids, blocking, config)
