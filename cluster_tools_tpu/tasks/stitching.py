"""Stitching of block-wise segmentations.

Reference stitching/*.py (SURVEY.md §2.4): merge block-offset labels across
block boundaries by mutual-max overlap votes (stitch_faces.py:110-175), or by a
multicut restricted to block-boundary edges (stitching_multicut.py:135-139).

The overlap criterion compares **two labelings of the same voxels**: each block
saves its segmentation of its halo'd outer region; for a face between blocks A
and B, A's and B's labelings of the shared overlap region are contingency-
matched.  A pair merges iff each segment is the other's maximal normalized
overlap partner, both lie on the actual boundary plane, and the mean normalized
overlap exceeds ``overlap_threshold`` (reference _stitch_face semantics).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

import numpy as np

from ..ops.segment import contingency_table
from ..ops.unionfind import merge_assignments_np
from ..utils.blocking import Blocking
from .base import VolumeSimpleTask, VolumeTask, merge_threads, read_ragged_chunks, resolve_n_blocks

STITCH_PAIRS_KEY = "stitching/face_pairs"
STITCH_ASSIGNMENTS_NAME = "stitch_assignments.npy"


def overlap_dir(tmp_folder: str) -> str:
    return os.path.join(tmp_folder, "stitch_overlaps")


def save_block_overlap(tmp_folder: str, block_id: int, outer_begin, outer_end,
                       seg: np.ndarray) -> None:
    """Save a block's labeling of its outer (halo'd) region for stitching."""
    d = overlap_dir(tmp_folder)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"block_{block_id}.npz")
    tmp = path + f".tmp{os.getpid()}.{threading.get_ident()}.npz"
    np.savez_compressed(
        tmp, begin=np.asarray(outer_begin), end=np.asarray(outer_end), seg=seg
    )
    os.replace(tmp, path)


def load_block_overlap(tmp_folder: str, block_id: int):
    path = os.path.join(overlap_dir(tmp_folder), f"block_{block_id}.npz")
    if not os.path.exists(path):
        return None
    with np.load(path) as f:
        return f["begin"], f["end"], f["seg"]


def _mutual_max_pairs(seg_a, seg_b, boundary_a, boundary_b, threshold):
    """Mutual-max votes between two labelings of the same region."""
    both = (seg_a > 0) & (seg_b > 0)
    if not both.any():
        return []
    ua, ub, counts = contingency_table(
        seg_a[both].astype(np.int64), seg_b[both].astype(np.int64)
    )
    c = counts.astype(np.float64)
    uniq_a, inv_a = np.unique(ua, return_inverse=True)
    uniq_b, inv_b = np.unique(ub, return_inverse=True)
    size_a = dict(zip(uniq_a.tolist(), np.bincount(inv_a, weights=c)))
    size_b = dict(zip(uniq_b.tolist(), np.bincount(inv_b, weights=c)))
    # best partner per side by count
    order = np.argsort(c, kind="stable")
    best_ab, best_ba = {}, {}
    for x, y, n in zip(ua[order], ub[order], c[order]):
        best_ab[int(x)] = (int(y), n)
        best_ba[int(y)] = (int(x), n)
    on_a = set(int(s) for s in np.unique(boundary_a) if s != 0)
    on_b = set(int(s) for s in np.unique(boundary_b) if s != 0)
    votes = []
    for x, (y, n_xy) in best_ab.items():
        if x not in on_a or y not in on_b:
            continue
        back, n_yx = best_ba.get(y, (None, 0.0))
        if back != x:
            continue
        measure = 0.5 * (n_xy / size_a[x] + n_yx / size_b[y])
        if measure > threshold:
            votes.append((x, y))
    return votes


class StitchFacesTask(VolumeTask):
    """Per-face mutual-max-overlap merge votes (reference stitch_faces.py:25)."""

    task_name = "stitch_faces"
    output_dtype = None

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"overlap_threshold": 0.5})
        return conf

    def process_block(self, block_id: int, blocking: Blocking, config):
        threshold = float(config.get("overlap_threshold", 0.5))
        mine = load_block_overlap(self.tmp_folder, block_id)
        pairs = []
        if mine is not None:
            my_begin, my_end, my_seg = mine
            for axis in range(blocking.ndim):
                ngb_id = blocking.neighbor_id(block_id, axis, lower=False)
                if ngb_id is None:
                    continue
                theirs = load_block_overlap(self.tmp_folder, ngb_id)
                if theirs is None:
                    continue
                nb_begin, nb_end, nb_seg = theirs
                # intersection of the two outer regions
                lo = np.maximum(my_begin, nb_begin)
                hi = np.minimum(my_end, nb_end)
                if (lo >= hi).any():
                    continue
                sl_a = tuple(
                    slice(l - b, h - b) for l, h, b in zip(lo, hi, my_begin)
                )
                sl_b = tuple(
                    slice(l - b, h - b) for l, h, b in zip(lo, hi, nb_begin)
                )
                ov_a = my_seg[sl_a]
                ov_b = nb_seg[sl_b]
                # boundary plane between the two inner regions, in overlap coords
                boundary = blocking.block(block_id).end[axis]
                plane = boundary - int(lo[axis])
                plane_sl = [slice(None)] * blocking.ndim
                plane_sl[axis] = slice(max(plane - 1, 0), plane + 1)
                plane_sl = tuple(plane_sl)
                votes = _mutual_max_pairs(
                    ov_a, ov_b, ov_a[plane_sl], ov_b[plane_sl], threshold
                )
                pairs.extend(votes)
        out = self.tmp_ragged(STITCH_PAIRS_KEY, blocking.n_blocks, np.int64)
        arr = (
            np.asarray(pairs, dtype=np.int64).reshape(-1)
            if pairs
            else np.array([], dtype=np.int64)
        )
        out.write_chunk((block_id,), arr)


class StitchAssignmentsTask(VolumeSimpleTask):
    """Union-find over stitch votes → assignment table
    (reference simple_stitch_assignments.py:24)."""

    task_name = "stitch_assignments"

    def run_impl(self) -> None:
        n_blocks = resolve_n_blocks(self.config_dir, self.input_path, self.input_key)
        ds = self.tmp_store()[STITCH_PAIRS_KEY]
        chunks = read_ragged_chunks(ds, n_blocks, merge_threads(self))
        pairs = [c.reshape(-1, 2) for c in chunks if c is not None and c.size]
        all_pairs = (
            np.concatenate(pairs, axis=0) if pairs else np.zeros((0, 2), np.int64)
        )
        # ids are sparse (block-offset); compact to dense for the union-find.
        # nodes not in any vote keep their identity via the write task's
        # identity-passthrough, so the table only needs voted ids
        ids = np.unique(all_pairs.reshape(-1)) if all_pairs.size else np.array([], np.int64)
        if ids.size == 0:
            np.save(os.path.join(self.tmp_folder, STITCH_ASSIGNMENTS_NAME),
                    np.zeros((0, 2), dtype=np.uint64))
            return
        dense = np.searchsorted(ids, all_pairs)
        assignment, _ = merge_assignments_np(ids.size + 1, dense + 1)
        # map back: voted id → smallest id in its merged group
        group_min = np.full(int(assignment.max()) + 1, np.iinfo(np.int64).max)
        np.minimum.at(group_min, assignment[1:], ids)
        table = np.stack(
            [ids.astype(np.uint64), group_min[assignment[1:]].astype(np.uint64)],
            axis=1,
        )
        np.save(os.path.join(self.tmp_folder, STITCH_ASSIGNMENTS_NAME), table)
        self.log(f"stitching merged {ids.size} voted ids")


BOUNDARY_EDGES_KEY = "stitching/boundary_edges"
SIMPLE_STITCH_NAME = "simple_stitch_assignments.npy"
STITCH_MC_NAME = "stitching_multicut_assignments.npy"


class SimpleStitchEdgesTask(VolumeTask):
    """Mark graph edges whose endpoints touch across a block boundary
    (reference simple_stitch_edges.py:23 via ndist.findBlockBoundaryEdges).

    ``input_path/key`` is the (block-offset) label volume the graph was
    extracted from; per block, every touching label pair on a lower face is
    looked up in the global edge list and its dense edge id recorded."""

    task_name = "simple_stitch_edges"
    output_dtype = None
    _graph_cache = None

    def _graph(self):
        if self._graph_cache is None:  # once per task, not once per block
            from .graph import load_graph

            self._graph_cache = load_graph(self.tmp_store())
        return self._graph_cache

    def process_block(self, block_id: int, blocking: Blocking, config):
        nodes, edges = self._graph()
        labels_ds = self.input_ds()
        pairs = []
        for axis, ngb_id, face in blocking.iterate_faces(block_id, halo=1):
            slab = np.asarray(labels_ds[face.slicing])
            lo, hi = np.split(slab, 2, axis=axis)
            both = (lo > 0) & (hi > 0) & (lo != hi)
            if not both.any():
                continue
            a = lo[both]
            b = hi[both]
            pairs.append(np.unique(np.stack([a, b], axis=1), axis=0))
        out = self.tmp_ragged(BOUNDARY_EDGES_KEY, blocking.n_blocks, np.int64)
        if not pairs:
            out.write_chunk((block_id,), np.zeros(0, dtype=np.int64))
            return
        uv = np.unique(np.concatenate(pairs, axis=0), axis=0)
        # labels → dense node ids → edge ids (edges are sorted lex)
        du = np.searchsorted(nodes, uv[:, 0])
        dv = np.searchsorted(nodes, uv[:, 1])
        ok = (du < nodes.size) & (dv < nodes.size)
        ok &= nodes[np.clip(du, 0, nodes.size - 1)] == uv[:, 0]
        ok &= nodes[np.clip(dv, 0, nodes.size - 1)] == uv[:, 1]
        duv = np.stack([du[ok], dv[ok]], axis=1)
        duv.sort(axis=1)
        # lookup in the sorted edge table
        edge_keys = edges[:, 0] * (edges.max() + 1) + edges[:, 1]
        q = duv[:, 0] * (edges.max() + 1) + duv[:, 1]
        pos = np.searchsorted(edge_keys, q)
        found = pos < edge_keys.size
        found &= edge_keys[np.clip(pos, 0, edge_keys.size - 1)] == q
        out.write_chunk((block_id,), pos[found].astype(np.int64))


class SimpleStitchAssignmentsTask(VolumeSimpleTask):
    """Merge every block-boundary edge above the edge-size threshold
    (reference simple_stitch_assignments.py:24)."""

    task_name = "simple_stitch_assignments"

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 edge_size_threshold: int = 0, **kwargs):
        super().__init__(*args, input_path=input_path, input_key=input_key,
                         edge_size_threshold=edge_size_threshold, **kwargs)

    def run_impl(self) -> None:
        from ..ops.unionfind import UnionFindNp
        from .features import FEATURES_KEY
        from .graph import load_graph

        nodes, edges = load_graph(self.tmp_store())
        n_blocks = resolve_n_blocks(
            self.config_dir, self.input_path, self.input_key
        )
        ds = self.tmp_store()[BOUNDARY_EDGES_KEY]
        merge = np.zeros(edges.shape[0], dtype=bool)
        for chunk in read_ragged_chunks(ds, n_blocks, merge_threads(self)):
            if chunk is not None and chunk.size:
                merge[chunk] = True
        if self.edge_size_threshold > 0:
            if FEATURES_KEY not in self.tmp_store():
                raise ValueError(
                    "edge_size_threshold needs edge features — run "
                    "EdgeFeaturesWorkflow (or MulticutStitchingWorkflow) first"
                )
            sizes = self.tmp_store()[FEATURES_KEY][:, -1]
            if sizes.size != edges.shape[0]:
                raise ValueError(
                    f"stale edge features: {sizes.size} rows for "
                    f"{edges.shape[0]} edges"
                )
            merge &= sizes > self.edge_size_threshold
        uf = UnionFindNp(nodes.size)
        if merge.any():
            uf.merge(edges[merge, 0], edges[merge, 1])
        roots = uf.compress()
        _, comp = np.unique(roots, return_inverse=True)
        table = np.stack(
            [nodes, (comp + 1).astype(np.uint64)], axis=1
        ).astype(np.uint64)
        if nodes.size and nodes[0] == 0:
            table[0, 1] = 0
        np.save(os.path.join(self.tmp_folder, SIMPLE_STITCH_NAME), table)
        self.log(
            f"simple stitching merged {int(merge.sum())} boundary edges"
        )


class StitchingMulticutTask(VolumeSimpleTask):
    """Multicut with two betas: boundary (stitch) edges get ``beta1``, inner
    edges ``beta2`` (reference stitching_multicut.py:18,135-139)."""

    task_name = "stitching_multicut"

    def __init__(self, *args, input_path: str = None, input_key: str = None,
                 **kwargs):
        super().__init__(*args, input_path=input_path, input_key=input_key,
                         **kwargs)

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        conf = super().default_task_config()
        conf.update({"beta1": 0.5, "beta2": 0.75})
        return conf

    def run_impl(self) -> None:
        from ..ops.multicut import solve_multicut, transform_probabilities_to_costs
        from .features import FEATURES_KEY
        from .graph import load_graph
        from .multicut import write_assignment_table

        conf = self.get_task_config()
        nodes, edges = load_graph(self.tmp_store())
        feats = self.tmp_store()[FEATURES_KEY][:]
        n_blocks = resolve_n_blocks(
            self.config_dir, self.input_path, self.input_key
        )
        ds = self.tmp_store()[BOUNDARY_EDGES_KEY]
        stitch = np.zeros(edges.shape[0], dtype=bool)
        for chunk in read_ragged_chunks(ds, n_blocks, merge_threads(self)):
            if chunk is not None and chunk.size:
                stitch[chunk] = True

        probs, sizes = feats[:, 0], feats[:, -1]
        costs = np.zeros(edges.shape[0], dtype=np.float64)
        if stitch.any():
            costs[stitch] = transform_probabilities_to_costs(
                probs[stitch], beta=float(conf.get("beta1", 0.5)),
                edge_sizes=sizes[stitch],
            )
        if (~stitch).any():
            costs[~stitch] = transform_probabilities_to_costs(
                probs[~stitch], beta=float(conf.get("beta2", 0.75)),
                edge_sizes=sizes[~stitch],
            )
        result = solve_multicut(nodes.size, edges, costs)
        write_assignment_table(self, result, STITCH_MC_NAME)
        self.log(
            f"stitching multicut: {nodes.size} nodes → "
            f"{int(result.max()) + 1} segments "
            f"({int(stitch.sum())} stitch edges)"
        )
