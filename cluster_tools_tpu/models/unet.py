"""3D U-Net in flax, bfloat16-friendly, with anisotropic pooling.

Design notes (TPU-first):
  * convs are 3x3x3 (or 1x3x3 on anisotropic levels) NCDHW→NDHWC transposed
    internally — XLA tiles channels-last convs onto the MXU;
  * default compute dtype bfloat16 with float32 params — the MXU-native mix;
  * group norm (batch-size independent, works at batch 1 per block);
  * the whole forward is shape-static per block geometry, so one compiled
    program serves every block.

The architecture mirrors what the reference's external pytorch checkpoints
implement (neurofire-style UNet3D; reference inference/frameworks.py wraps
them but the repo defines none itself).
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

try:
    import flax.linen as nn
    from flax import serialization
except ImportError:  # pragma: no cover - flax is baked into the image
    nn = None


def _scale3(sf) -> Tuple[int, int, int]:
    return (sf,) * 3 if isinstance(sf, int) else tuple(sf)


class ConvBlock(nn.Module):
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        for _ in range(2):
            x = nn.Conv(self.features, (3, 3, 3), padding="SAME",
                        dtype=self.dtype)(x)
            x = nn.GroupNorm(
                num_groups=min(8, self.features), dtype=jnp.float32
            )(x.astype(jnp.float32))
            x = nn.relu(x).astype(self.dtype)
        return x


class UNet3D(nn.Module):
    """Encoder/decoder with skip connections.

    in/out layout: [batch, channel, z, y, x] (the block convention used by the
    tasks); internally channels-last for MXU-friendly convs.
    """

    out_channels: int = 3
    initial_features: int = 16
    depth: int = 3
    scale_factors: Optional[Sequence] = None  # per-level, e.g. [[1,2,2],2]
    final_activation: Optional[str] = "sigmoid"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # NCDHW → NDHWC
        x = jnp.transpose(x, (0, 2, 3, 4, 1)).astype(self.dtype)
        scales = list(self.scale_factors or [2] * (self.depth - 1))
        if len(scales) != self.depth - 1:
            raise ValueError("need depth-1 scale factors")
        feats = [self.initial_features * (2**i) for i in range(self.depth)]

        skips = []
        for level in range(self.depth - 1):
            x = ConvBlock(feats[level], self.dtype)(x)
            skips.append(x)
            sf = _scale3(scales[level])
            x = nn.max_pool(x, window_shape=sf, strides=sf)
        x = ConvBlock(feats[-1], self.dtype)(x)
        for level in reversed(range(self.depth - 1)):
            sf = _scale3(scales[level])
            target = skips[level]
            x = jax.image.resize(
                x,
                x.shape[:1] + target.shape[1:4] + x.shape[-1:],
                method="nearest",
            )
            x = nn.Conv(feats[level], (1, 1, 1), dtype=self.dtype)(x)
            x = jnp.concatenate([target, x], axis=-1)
            x = ConvBlock(feats[level], self.dtype)(x)
        x = nn.Conv(self.out_channels, (1, 1, 1), dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
        if self.final_activation == "sigmoid":
            x = jax.nn.sigmoid(x)
        elif self.final_activation == "softmax":
            x = jax.nn.softmax(x, axis=-1)
        # NDHWC → NCDHW
        return jnp.transpose(x, (0, 4, 1, 2, 3))


MODEL_REGISTRY = {"UNet3D": UNet3D}


def save_checkpoint(path: str, params, model_config: Dict[str, Any]) -> None:
    """Checkpoint = flax msgpack params + JSON model config sidecar."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "params.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(params))
    with open(os.path.join(path, "model.json"), "w") as f:
        json.dump(model_config, f, indent=2)


def load_checkpoint(path: str):
    """Returns (model, params). ``model.json`` carries the constructor args
    plus ``"model": "UNet3D"``."""
    with open(os.path.join(path, "model.json")) as f:
        conf = json.load(f)
    name = conf.pop("model", "UNet3D")
    in_channels = conf.pop("in_channels", 1)
    if "dtype" in conf:
        # mixed-precision knob: "bfloat16" (default — MXU-native compute
        # with float32 params/norms) or "float32" for full precision
        conf["dtype"] = jnp.dtype(conf["dtype"])
    model = MODEL_REGISTRY[name](**conf)
    # template params to restore structure
    dummy = jnp.zeros((1, in_channels, 8, 16, 16), jnp.float32)
    template = model.init(jax.random.PRNGKey(0), dummy)
    with open(os.path.join(path, "params.msgpack"), "rb") as f:
        params = serialization.from_bytes(template, f.read())
    return model, params
