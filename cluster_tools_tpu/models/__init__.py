"""Neural-network models for block-wise inference.

The reference ships no models of its own — it wraps external pytorch/inferno
checkpoints (reference inference/frameworks.py).  The TPU-native build instead
carries a first-class flax U-Net (the standard architecture those checkpoints
have in EM segmentation) so the whole predict path is one jit-compiled XLA
program on the MXU, plus loaders for foreign checkpoints.
"""

from .unet import UNet3D, load_checkpoint, save_checkpoint

__all__ = ["UNet3D", "load_checkpoint", "save_checkpoint"]
