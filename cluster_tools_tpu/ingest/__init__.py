"""ctt-ingest: streaming ingest — segment data while it is being acquired.

Every other pipeline in the repo assumes a *finished* dataset; acquisition
reality for both workload domains is data landing incrementally (3D EM
volumes slab-by-slab, detector frame stacks growing mid-run).  This
package connects a growing source to the fused chain runner:

  * :mod:`.source` — :class:`~.source.GrowingSource`, a watcher over a
    POSIX directory or object-store prefix that detects newly landed
    slabs, tolerates torn/partial landings and out-of-order arrival, and
    emits a monotone ready-frontier;
  * :mod:`.runner` — :class:`~.runner.IngestRunner`, the incremental
    driver that feeds each ready slab through an existing fused chain,
    persisting the carry window via ``publish_once`` after every slab so
    the stream is resumable (and byte-identical to the batch run), plus
    :class:`~.runner.IngestTask`, the serve-hosted long-lived job.
"""

from .source import GrowingSource, publish_manifest, publish_slab
from .runner import IngestRunner, IngestSuspended, IngestTask, install_suspend_check

__all__ = [
    "GrowingSource",
    "IngestRunner",
    "IngestSuspended",
    "IngestTask",
    "install_suspend_check",
    "publish_manifest",
    "publish_slab",
]
