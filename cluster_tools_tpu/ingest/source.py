"""Growing-source watcher: the acquisition side of ctt-ingest.

Control-directory protocol (a POSIX directory or an object-store prefix —
every access goes through ``utils.store_backend.backend_for``, so the
ctt-cloud listing GET is the poll primitive on remote stores):

  ``ingest.manifest.json``   published exactly once (``publish_once``) by
      the acquisition writer before the first slab: the final geometry of
      the stream (``shape``), the landing granularity (``slab_depth``
      voxels/frames along axis 0) and the derived ``slabs_total``.

  ``slab.NNNNNN.json``       per-slab landing marker, create-only,
      published AFTER the slab's data is durably written to the input
      dataset.  The marker — not the data — is the commit point: a torn
      or in-progress data write is invisible to the watcher because its
      marker does not exist yet, and a torn *marker* (half-uploaded JSON)
      is skipped until a later poll sees it whole.

The watcher's contract is a **monotone ready-frontier**: ``poll()`` returns
the number of leading slabs (0..frontier-1) that have all landed.  Slabs
arriving out of order park in the seen-set until the gap fills; duplicate
re-landings are idempotent (create-only markers + set semantics); the
frontier never regresses by construction — it only advances when the next
consecutive marker becomes readable.
"""

from __future__ import annotations

import json
import re
import time
from typing import Any, Dict, List, Optional, Sequence

from ..obs import metrics as obs_metrics
from ..utils import store_backend

MANIFEST_NAME = "ingest.manifest.json"
SLAB_RE = re.compile(r"^slab\.(\d{6})\.json$")


def slab_marker_name(slab: int) -> str:
    return f"slab.{int(slab):06d}.json"


# ---------------------------------------------------------------------------
# writer side: the two artifacts an acquisition process publishes


def publish_manifest(
    control_dir: str,
    shape: Sequence[int],
    slab_depth: int,
    domain: str = "volume",
) -> bool:
    """Publish the stream manifest (create-only; False = already there)."""
    backend = store_backend.backend_for(control_dir)
    shape = [int(s) for s in shape]
    slab_depth = int(slab_depth)
    if slab_depth <= 0:
        raise ValueError(f"slab_depth must be positive, got {slab_depth}")
    record = {
        "schema": 1,
        "domain": str(domain),
        "shape": shape,
        "slab_depth": slab_depth,
        "slabs_total": -(-shape[0] // slab_depth),
        "created_wall": time.time(),
    }
    backend.makedirs(control_dir)
    return backend.publish_once(
        backend.join(control_dir, MANIFEST_NAME),
        json.dumps(record, sort_keys=True).encode("utf-8"),
    )


def publish_slab(control_dir: str, slab: int) -> bool:
    """Publish slab ``slab``'s landing marker — call AFTER the slab's data
    is durably written.  Create-only: a duplicate re-landing returns False
    and changes nothing the watcher can observe."""
    backend = store_backend.backend_for(control_dir)
    record = {"slab": int(slab), "wall": time.time()}
    return backend.publish_once(
        backend.join(control_dir, slab_marker_name(slab)),
        json.dumps(record, sort_keys=True).encode("utf-8"),
    )


# ---------------------------------------------------------------------------
# watcher side


class GrowingSource:
    """Watch a control directory for landed slabs (see module docstring).

    One ``poll()`` is one listing scan plus one marker read per *newly*
    listed slab — already-seen markers are never re-read, so steady-state
    polling of a quiet source costs exactly one listing GET."""

    def __init__(self, control_dir: str):
        self.control_dir = str(control_dir).rstrip("/")
        self.backend = store_backend.backend_for(self.control_dir)
        self._manifest: Optional[Dict[str, Any]] = None
        self._seen: set = set()
        self._frontier = 0

    # -- manifest ------------------------------------------------------------

    def manifest(self) -> Optional[Dict[str, Any]]:
        """The stream manifest, or None while it is absent/torn (the next
        call retries the read)."""
        if self._manifest is None:
            path = self.backend.join(self.control_dir, MANIFEST_NAME)
            try:
                rec = self.backend.read_json(path)
            except (OSError, ValueError):
                return None
            if not isinstance(rec, dict) or "slabs_total" not in rec:
                return None
            self._manifest = rec
        return self._manifest

    # -- polling -------------------------------------------------------------

    def poll(self) -> int:
        """One listing scan; returns the ready frontier — slabs
        ``0..frontier-1`` have all landed.  Monotone across polls."""
        obs_metrics.inc("ingest.poll_rounds")
        try:
            names: List[str] = self.backend.listdir(self.control_dir)
        except (OSError, ValueError):
            names = []
        for name in names:
            m = SLAB_RE.match(name)
            if m is None:
                continue
            slab = int(m.group(1))
            if slab in self._seen:
                continue
            try:
                rec = self.backend.read_json(
                    self.backend.join(self.control_dir, name)
                )
            except (OSError, ValueError):
                continue  # torn/partial marker: retry on a later poll
            if not isinstance(rec, dict) or rec.get("slab") != slab:
                continue
            self._seen.add(slab)
        while self._frontier in self._seen:
            self._frontier += 1
        return self._frontier

    @property
    def frontier(self) -> int:
        return self._frontier

    def landed(self) -> int:
        """Slabs observed landed so far (including out-of-order ones parked
        beyond a gap) — the ``ingest.slabs_pending`` gauge rides this."""
        return len(self._seen)

    def complete(self) -> bool:
        man = self.manifest()
        return man is not None and self._frontier >= int(man["slabs_total"])
