"""Incremental ingest driver: feed ready slabs through a fused chain.

:class:`IngestRunner` is the streaming twin of one ``try_run_chain`` pass
(``runtime/stream.py``): it plans the SAME chain against the stream's
*final* geometry (from the manifest), then walks the plan's chunks in
order, gating each chunk on the :class:`~.source.GrowingSource` frontier —
a chunk runs only once every voxel/frame it reads (block extent plus the
chain-max halo along axis 0) has landed.  Because the chunk sequence, the
serialized compute order and the carry updates are identical to the batch
pass, the finished ingest run is **byte-identical** to the batch run over
the finished volume.

Resumability: after every chunk commit the carried merge state
(``_ChainRunner.export_carry()`` — max-id offsets, face-edge tables — plus
the ``ops.events._CAP_HINT`` warm-capacity hint for the frame domain) is
persisted create-only (``publish_once``) as ``ingest.carry.sNNNNNN.json``
in the control directory, and ``ingest.frontier.json`` is atomically
replaced with the commit frontier.  A successor process (serve gen+1
takeover after a SIGKILL, or a drain-suspended job re-claimed later) loads
the highest readable carry record, restores it, skips the committed
chunks and continues the stream — still byte-identical, because committed
chunks' writes are already on the store and the carry replays nothing.

Serve integration: :class:`IngestTask` is the long-lived ``ingest`` job
the daemon hosts.  The daemon installs a suspend probe
(:func:`install_suspend_check`) at startup; a drain request surfaces here
as :class:`IngestSuspended` between slabs, the daemon releases the lease
(``JobQueue.release``) and a peer picks the stream up where the carry
says it stopped.
"""

from __future__ import annotations

import base64
import json
import pickle
import re
import time
import zlib
from typing import Any, Callable, Dict, Optional, Tuple

from ..obs import heartbeat as obs_heartbeat
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..ops import events as events_ops
from ..runtime import stream
from ..runtime.task import SimpleTask
from ..utils import store_backend
from .source import GrowingSource

FRONTIER_NAME = "ingest.frontier.json"
CARRY_RE = re.compile(r"^ingest\.carry\.s(\d{6})\.json$")


def carry_record_name(chunk_index: int) -> str:
    return f"ingest.carry.s{int(chunk_index):06d}.json"


class IngestSuspended(RuntimeError):
    """Raised between slabs when the host asks the stream to yield (serve
    drain).  The carry for every committed slab is already persisted, so
    suspension loses no work — a successor resumes from the last commit."""


# The host-installed suspend probe (the serve daemon wires its draining
# flag here at startup).  Module-level on purpose: the probe must reach an
# IngestRunner constructed deep inside a task's run_impl.
_suspend_check: Optional[Callable[[], bool]] = None


def install_suspend_check(fn: Optional[Callable[[], bool]]) -> None:
    global _suspend_check
    _suspend_check = fn


def _suspend_requested() -> bool:
    return bool(_suspend_check is not None and _suspend_check())


# ---------------------------------------------------------------------------
# carry codec: the carried state is numpy-heavy with tuple dict keys
# ((block_id, axis) face planes), so the JSON record holds a
# pickle→zlib→base64 blob.  Output byte-identity never depends on these
# bytes — the carry is replayed state, not output.


def encode_carry(state: Dict[str, Any]) -> Tuple[str, int]:
    raw = pickle.dumps(state, protocol=4)
    return base64.b64encode(zlib.compress(raw)).decode("ascii"), len(raw)


def decode_carry(blob: str) -> Dict[str, Any]:
    return pickle.loads(zlib.decompress(base64.b64decode(blob.encode("ascii"))))


# ---------------------------------------------------------------------------


class IngestRunner:
    """Drive one fused chain incrementally over a growing source.

    ``chain`` must be fusion-eligible (``plan_chain`` raising
    ``ChainFallback`` is an error here, not a fallback — there is no
    task-at-a-time path over half-landed data)."""

    def __init__(
        self,
        chain: "stream.FusedChain",
        source: GrowingSource,
        poll_s: float = 0.2,
        timeout_s: float = 600.0,
    ):
        self.chain = chain
        self.source = source
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self.control_dir = source.control_dir
        self.backend = source.backend
        self._resumes = 0
        self._ingested = 0

    # -- control-dir records -------------------------------------------------

    def _publish_frontier(self, done: int, total: int) -> None:
        record = {
            "schema": 1,
            "slabs_done": int(done),
            "slabs_total": int(total),
            "resumes": int(self._resumes),
            "wall": time.time(),
        }
        self.backend.write_json(
            self.backend.join(self.control_dir, FRONTIER_NAME), record
        )

    def _persist_carry(self, runner: "stream._ChainRunner",
                       chunk_index: int, total: int) -> None:
        blob, nraw = encode_carry(runner.export_carry())
        record = {
            "schema": 1,
            "chain": self.chain.name,
            "slab": int(chunk_index),
            "slabs_done": int(chunk_index) + 1,
            "carry": blob,
            "carry_bytes": int(nraw),
            "cap_hint": {
                str(k): int(v) for k, v in events_ops._CAP_HINT.items()
            },
            "wall": time.time(),
        }
        payload = json.dumps(record, sort_keys=True).encode("utf-8")
        published = self.backend.publish_once(
            self.backend.join(self.control_dir,
                              carry_record_name(chunk_index)),
            payload,
        )
        if published:
            obs_metrics.inc("ingest.carry_bytes_persisted", len(payload))
        # a lost publish race means a concurrent successor committed the
        # same slab from the same carry — identical record, nothing to do

    def _load_carry(self) -> Optional[Dict[str, Any]]:
        """Highest readable carry record for this chain, or None.  An
        unreadable/torn record falls back to the previous one — resuming a
        few slabs early only re-runs idempotent block writes."""
        try:
            names = self.backend.listdir(self.control_dir)
        except (OSError, ValueError):
            names = []
        indexed = sorted(
            (int(m.group(1)), n)
            for n in names
            if (m := CARRY_RE.match(n)) is not None
        )
        for _, name in reversed(indexed):
            try:
                rec = self.backend.read_json(
                    self.backend.join(self.control_dir, name)
                )
            except (OSError, ValueError):
                continue
            if (isinstance(rec, dict) and rec.get("chain") == self.chain.name
                    and isinstance(rec.get("carry"), str)):
                return rec
        return None

    # -- gating --------------------------------------------------------------

    def _check_suspend(self) -> None:
        if _suspend_requested():
            raise IngestSuspended(
                f"ingest of {self.chain.name!r} suspended at slab "
                f"{self._ingested} (carry persisted; resume re-claims here)"
            )

    def _wait_manifest(self) -> Dict[str, Any]:
        deadline = obs_trace.monotonic() + self.timeout_s
        while True:
            self._check_suspend()
            man = self.source.manifest()
            if man is not None:
                return man
            if obs_trace.monotonic() > deadline:
                raise RuntimeError(
                    f"ingest: no readable manifest in {self.control_dir} "
                    f"after {self.timeout_s:.0f}s"
                )
            time.sleep(self.poll_s)

    def _wait_ready(self, need_z: int, slab_depth: int, total_z: int) -> None:
        """Block until the landed frontier covers ``need_z`` voxels/frames
        along axis 0 (a quiet source parks the stream here; a drain
        request surfaces between polls)."""
        need_z = min(int(need_z), int(total_z))
        deadline = obs_trace.monotonic() + self.timeout_s
        while True:
            self._check_suspend()
            frontier = self.source.poll()
            obs_metrics.set_gauge(
                "ingest.slabs_pending",
                max(self.source.landed() - self._ingested, 0),
            )
            if frontier * slab_depth >= need_z:
                return
            if obs_trace.monotonic() > deadline:
                raise RuntimeError(
                    f"ingest: source quiet — frontier {frontier} "
                    f"(need z>={need_z}, slab_depth {slab_depth}) after "
                    f"{self.timeout_s:.0f}s"
                )
            time.sleep(self.poll_s)

    # -- main ----------------------------------------------------------------

    def run(self) -> None:
        man = self._wait_manifest()
        plan = stream.plan_chain(self.chain)  # ChainFallback = hard error
        shape = tuple(plan.blocking.shape)
        if tuple(int(s) for s in man["shape"]) != shape:
            raise RuntimeError(
                f"ingest: manifest shape {man['shape']} != chain shape "
                f"{list(shape)}"
            )
        slab_depth = int(man["slab_depth"])
        chunks = plan.chunks
        # chain-max read halo along axis 0: a chunk is ready only when the
        # halo rows of its last block have landed too
        halo_z = max((h[0] for h in plan.prefetch.values()), default=0)

        obs_metrics.inc("stream.chains")
        obs_heartbeat.note_task(
            f"ingest:{self.chain.name}", len(plan.block_ids),
            grid=plan.blocking.grid_shape,
        )
        runner = stream._ChainRunner(plan)
        runner.prepare()

        # resume: restore the newest committed carry and skip its chunks
        start = 0
        prior = self._read_frontier()
        if prior is not None:
            self._resumes = int(prior.get("resumes", 0))
        rec = self._load_carry()
        if rec is not None:
            runner.import_carry(decode_carry(rec["carry"]))
            for k, v in (rec.get("cap_hint") or {}).items():
                events_ops._CAP_HINT[int(k)] = max(
                    events_ops._CAP_HINT.get(int(k), 1), int(v)
                )
            start = int(rec["slabs_done"])
            self._resumes += 1
            self._ingested = start
            obs_metrics.inc("ingest.resumes")
            for chunk in chunks[:start]:
                obs_heartbeat.note_blocks_done(len(chunk))

        t0 = obs_trace.monotonic()
        with obs_trace.span(
            "ingest", kind="dispatch", task=f"ingest:{self.chain.name}",
            chain=self.chain.name, blocks=len(plan.block_ids),
            resumed=start,
        ):
            for ci in range(start, len(chunks)):
                chunk = chunks[ci]
                self._check_suspend()
                need_z = max(
                    plan.blocking.block(b).end[0] for b in chunk
                ) + halo_z
                self._wait_ready(need_z, slab_depth, shape[0])
                runner.run_chunk(chunk)
                self._persist_carry(runner, ci, len(chunks))
                self._ingested = ci + 1
                obs_metrics.inc("ingest.slabs_ingested")
                obs_metrics.set_gauge(
                    "ingest.slabs_pending",
                    max(self.source.landed() - self._ingested, 0),
                )
                self._publish_frontier(ci + 1, len(chunks))
        runner.finalize(obs_trace.monotonic() - t0)
        self._publish_frontier(len(chunks), len(chunks))

    def _read_frontier(self) -> Optional[Dict[str, Any]]:
        try:
            rec = self.backend.read_json(
                self.backend.join(self.control_dir, FRONTIER_NAME)
            )
        except (OSError, ValueError):
            return None
        return rec if isinstance(rec, dict) else None


# ---------------------------------------------------------------------------
# the serve-hosted job


class IngestTask(SimpleTask):
    """Long-lived ``ingest`` job: watch ``control_dir``, stream every slab
    through the domain's chain, finish the non-fused tail (volume domain:
    assignments + label write), stamp complete.

    ``domain="volume"`` ingests through the streaming segmentation chain
    (threshold → CC[→ watershed], offsets/faces covered by the carry);
    ``domain="frames"`` ingests through a single-member events chain —
    each landed frame batch folds into the labels volume and ragged event
    tables exactly as the batch ``EventBuildingTask`` run would."""

    task_name = "ingest"

    def __init__(
        self,
        tmp_folder: str,
        control_dir: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        domain: str = "volume",
        input_path: Optional[str] = None,
        input_key: Optional[str] = None,
        output_path: Optional[str] = None,
        output_key: Optional[str] = None,
        watershed: bool = False,
        poll_s: float = 0.2,
        timeout_s: float = 600.0,
        dependencies=(),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        if domain not in ("volume", "frames"):
            raise ValueError(f"unknown ingest domain {domain!r}")
        self.control_dir = control_dir
        self.domain = domain
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.watershed = bool(watershed)
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)

    def _volume_workflow(self):
        from ..workflows.streaming import StreamingSegmentationWorkflow

        return StreamingSegmentationWorkflow(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
            input_path=self.input_path,
            input_key=self.input_key,
            output_path=self.output_path,
            output_key=self.output_key,
            watershed=self.watershed,
        )

    def _frames_chain(self):
        from ..tasks.events import EventBuildingTask

        task = EventBuildingTask(
            tmp_folder=self.tmp_folder,
            config_dir=self.config_dir,
            max_jobs=self.max_jobs,
            input_path=self.input_path,
            input_key=self.input_key,
            output_path=self.output_path,
            output_key=self.output_key,
        )
        return stream.FusedChain(name="ingest_events", members=[task])

    def run_impl(self) -> None:
        source = GrowingSource(self.control_dir)
        if self.domain == "volume":
            workflow = self._volume_workflow()
            chain = list(workflow.fused_chains())[0]
        else:
            workflow, chain = None, self._frames_chain()
        IngestRunner(
            chain, source, poll_s=self.poll_s, timeout_s=self.timeout_s
        ).run()
        if workflow is not None:
            # the non-fused tail (assignments + final label write): the
            # chain members and covered tasks are already stamped
            # complete, so this is exactly the batch run's tail
            from ..runtime.workflow import build

            if not build([workflow]):
                raise RuntimeError("ingest: downstream workflow tail failed")
