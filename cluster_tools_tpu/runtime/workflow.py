"""Workflow DAG runner: topological execution with resume-from-checkpoint.

The analog of running luigi with the local scheduler in the reference
(reference workflows.py + cluster_tasks.py:644-675): a workflow's ``requires()``
builds a dependency chain; ``build([task])`` executes incomplete tasks in
topological order, skipping tasks whose completion target already exists —
re-running a workflow resumes from the first incomplete task.

Submission vs execution (ctt-serve): ``build()`` historically fused the
two — every call also (re)armed the per-process amortizable state (the
persistent XLA compile cache, heartbeats, devices).  That state now lives
in :class:`ExecutionContext`: a cold process still gets one implicitly
(``ExecutionContext.process_context()``, identical behavior), while a
long-lived host — the ``cluster_tools_tpu.serve`` daemon — creates ONE
context at startup and passes it to every submitted build, so mesh
resolution, compiled executables, and the decoded-chunk LRU stay warm
across jobs instead of dying with each driver process.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

from . import config as cfg
from .task import Target, Task


class ExecutionContext:
    """The amortizable per-process execution state, made explicit.

    Owns exactly what a fresh workflow process pays to set up and then
    throws away: the persistent XLA compile-cache wiring
    (``utils/compile_cache.py``), the decoded-chunk LRU budget
    (``utils/store.py`` — the cache itself is process-global; the context
    pins its budget), the resolved local device set (``resolve_batch_size``
    asks the context instead of re-querying jax per dispatch), and the
    trace/heartbeat wiring (``obs/heartbeat.py``).  ``activate()`` is
    idempotent; ``build()`` activates the process-wide singleton on every
    call — byte-for-byte the old cold-process behavior — while the serve
    daemon activates one context once and reuses it for every job,
    which is where the amortization lives: the SECOND job submitted to a
    warm context pays neither interpreter+jax import nor jit compiles.
    """

    _PROCESS: Optional["ExecutionContext"] = None

    def __init__(
        self,
        compile_cache_path: Optional[str] = None,
        chunk_cache_mb: Optional[float] = None,
        role: Optional[str] = None,
        hbm_cache_mb: Optional[float] = None,
    ):
        self._compile_cache_path = compile_cache_path
        self._chunk_cache_mb = chunk_cache_mb
        self._hbm_cache_mb = hbm_cache_mb
        self._role = role
        self._activated = False
        self._n_devices: Optional[int] = None
        self._device_cache = None
        self.compile_cache_dir: Optional[str] = None
        self.builds_executed = 0

    def activate(self) -> "ExecutionContext":
        """Arm the warm state (idempotent).  Never raises for cache
        trouble — the context is an optimization layer, not a gate."""
        if self._activated:
            return self
        from ..obs import heartbeat as obs_heartbeat
        from ..utils.compile_cache import enable_compile_cache

        self.compile_cache_dir = enable_compile_cache(
            self._compile_cache_path
        )
        if self._chunk_cache_mb is not None:
            from ..utils import store

            store.set_chunk_cache_budget(
                int(float(self._chunk_cache_mb) * (1 << 20))
            )
        # liveness from the moment the context exists (no-op, no thread,
        # when tracing is off — the one ctt-obs switch)
        obs_heartbeat.ensure_started(role=self._role)
        self._activated = True
        return self

    def device_cache(self):
        """The context's warm device-buffer cache (ctt-hbm), created
        lazily: budget from the ``hbm_cache_mb`` constructor argument
        (the serve daemon's config — cross-job HBM reuse lives there),
        else ``CTT_HBM_CACHE_MB`` (default 0 = disabled).  Owned here so
        the cache's lifetime IS the warm process state's lifetime."""
        if self._device_cache is None:
            from . import hbm

            budget = (
                int(float(self._hbm_cache_mb) * (1 << 20))
                if self._hbm_cache_mb is not None
                else hbm.cache_budget_bytes()
            )
            self._device_cache = hbm.DeviceBufferCache(max(budget, 0))
        return self._device_cache

    def local_device_count(self) -> int:
        """Visible local devices, resolved once per context — the
        executor's batch sizing rides this instead of asking jax on every
        dispatch (on a serving host that is thousands of dispatches)."""
        if self._n_devices is None:
            try:
                import jax

                self._n_devices = max(int(jax.local_device_count()), 1)
            except Exception:  # pragma: no cover - no backend at all
                self._n_devices = 1
        return self._n_devices

    def describe(self) -> Dict[str, Any]:
        """Introspection snapshot (the serve daemon's /healthz payload)."""
        from ..utils import store

        return {
            "activated": self._activated,
            "role": self._role,
            "compile_cache_dir": self.compile_cache_dir,
            "chunk_cache_budget_bytes": store.chunk_cache_budget(),
            "device_cache": self.device_cache().describe(),  # ctt-hbm
            "devices": self._n_devices,  # None until first dispatch asks
            "builds_executed": self.builds_executed,
            "pid": os.getpid(),
        }

    @classmethod
    def process_context(cls) -> "ExecutionContext":
        """The implicit per-process context every plain ``build()`` call
        uses — what a cold workflow process always paid, now nameable."""
        if cls._PROCESS is None:
            cls._PROCESS = ExecutionContext()
        return cls._PROCESS.activate()

    def install(self) -> "ExecutionContext":
        """Make THIS context the process-wide one (the serve daemon calls
        it once at startup, so in-process builds and the executor's device
        resolution all share the daemon's warm state)."""
        ExecutionContext._PROCESS = self
        return self.activate()


class WorkflowBase(Task):
    """A composite task: ``requires()`` returns the dependency chain, completion
    mirrors the last member task (reference cluster_tasks.py:667-669)."""

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        dependencies: Sequence[Task] = (),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.target = target  # informational; the global config decides

    def run(self) -> None:
        pass  # members do the work

    def output(self) -> Target:
        reqs = list(self.requires())
        if reqs:
            return reqs[-1].output()
        return super().output()

    def complete(self) -> bool:
        reqs = list(self.requires())
        if reqs:
            return all(r.complete() for r in reqs)
        return super().complete()

    @classmethod
    def get_config(cls) -> Dict[str, dict]:
        """Default configs of all member tasks, for users to edit and write to the
        config dir (reference workflows.py:102-107)."""
        return {"global": dict(cfg.DEFAULT_GLOBAL_CONFIG)}

    def fused_chains(self) -> List:
        """Declared fusible chains (ctt-stream): a list of
        ``runtime.stream.FusedChain`` over member tasks.  ``build()``
        attempts each chain as one streaming pass before running its
        members task-at-a-time; any ineligible chain silently falls back.
        Lint rule CTT011 statically validates declarations."""
        return []


def _task_key(task: Task) -> str:
    return f"{type(task).__module__}.{type(task).__qualname__}:{task.output().path}"


def _toposort(roots: Sequence[Task]) -> List[Task]:
    order: List[Task] = []
    seen: Dict[str, Task] = {}
    visiting: set = set()

    def visit(task: Task) -> None:
        key = _task_key(task)
        if key in seen:
            return
        if key in visiting:
            raise RuntimeError(f"dependency cycle at {task!r}")
        visiting.add(key)
        for dep in task.requires():
            visit(dep)
        visiting.discard(key)
        seen[key] = task
        order.append(task)

    for t in roots:
        visit(t)
    return order


def _collect_chains(order: Sequence[Task]):
    """Fused-chain declarations from the workflow nodes of a build, mapped
    by member/covered task key so the build loop can attempt a chain when
    it reaches the first incomplete task the chain would satisfy.  A
    declaration that raises is dropped loudly (declarations must never
    break a build)."""
    by_key: Dict[str, object] = {}
    for task in order:
        if not isinstance(task, WorkflowBase):
            continue
        try:
            chains = list(task.fused_chains())
        except Exception as e:
            print(f"[ctt-stream] ignoring fused_chains() of {task!r}: "
                  f"{type(e).__name__}: {e}")
            continue
        for chain in chains:
            for member in list(chain.members) + list(chain.covers):
                by_key.setdefault(_task_key(member), chain)
    return by_key


def build(
    tasks: Sequence[Task],
    raise_on_failure: bool = True,
    context: Optional[ExecutionContext] = None,
) -> bool:
    """Run a set of root tasks and their dependencies.  Returns success.

    ``context`` carries the warm per-process execution state (compile
    cache, chunk LRU budget, devices, heartbeats).  None — the normal
    cold-process call — activates the process-wide singleton, which is
    exactly the setup every ``build()`` performed inline before; a
    long-lived submitter (the serve daemon) passes its own context so
    that state is armed once and shared across many builds."""
    from ..obs import trace as obs_trace

    ctx = (context or ExecutionContext.process_context()).activate()
    ctx.builds_executed += 1
    order = _toposort(tasks)
    for task in order:
        # resume after a multi-host failure: stale aborted flags from the
        # prior run would otherwise fail peers' barriers immediately
        task.clear_stale_abort()
    chains_by_key = _collect_chains(order)
    attempted: set = set()
    try:
        with obs_trace.span("build", kind="run", n_tasks=len(order)):
            for task in order:
                if task.complete():
                    continue
                # ctt-stream: an incomplete task covered by a declared
                # fused chain triggers ONE attempt at running the whole
                # chain as a streaming pass; on success the members' and
                # covered tasks' status files are complete and the loop
                # skips them.  A declined/failed chain leaves no status
                # behind, so execution proceeds task-at-a-time unchanged.
                chain = chains_by_key.get(_task_key(task))
                if chain is not None and id(chain) not in attempted:
                    attempted.add(id(chain))
                    from . import stream

                    if stream.try_run_chain(chain) and task.complete():
                        continue
                try:
                    task.run()
                except Exception:
                    if raise_on_failure:
                        raise
                    import traceback

                    traceback.print_exc()
                    return False
                if isinstance(task, WorkflowBase):
                    continue
                if not task.complete():
                    msg = f"task {task!r} ran but did not reach completion"
                    if raise_on_failure:
                        raise RuntimeError(msg)
                    print(msg)
                    return False
        return True
    finally:
        # in-process callers (tests, notebooks) see complete shards without
        # waiting for interpreter exit
        obs_trace.flush()
