"""Workflow DAG runner: topological execution with resume-from-checkpoint.

The analog of running luigi with the local scheduler in the reference
(reference workflows.py + cluster_tasks.py:644-675): a workflow's ``requires()``
builds a dependency chain; ``build([task])`` executes incomplete tasks in
topological order, skipping tasks whose completion target already exists —
re-running a workflow resumes from the first incomplete task.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from . import config as cfg
from .task import Target, Task


class WorkflowBase(Task):
    """A composite task: ``requires()`` returns the dependency chain, completion
    mirrors the last member task (reference cluster_tasks.py:667-669)."""

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        target: Optional[str] = None,
        dependencies: Sequence[Task] = (),
    ):
        super().__init__(tmp_folder, config_dir, max_jobs, dependencies)
        self.target = target  # informational; the global config decides

    def run(self) -> None:
        pass  # members do the work

    def output(self) -> Target:
        reqs = list(self.requires())
        if reqs:
            return reqs[-1].output()
        return super().output()

    def complete(self) -> bool:
        reqs = list(self.requires())
        if reqs:
            return all(r.complete() for r in reqs)
        return super().complete()

    @classmethod
    def get_config(cls) -> Dict[str, dict]:
        """Default configs of all member tasks, for users to edit and write to the
        config dir (reference workflows.py:102-107)."""
        return {"global": dict(cfg.DEFAULT_GLOBAL_CONFIG)}

    def fused_chains(self) -> List:
        """Declared fusible chains (ctt-stream): a list of
        ``runtime.stream.FusedChain`` over member tasks.  ``build()``
        attempts each chain as one streaming pass before running its
        members task-at-a-time; any ineligible chain silently falls back.
        Lint rule CTT011 statically validates declarations."""
        return []


def _task_key(task: Task) -> str:
    return f"{type(task).__module__}.{type(task).__qualname__}:{task.output().path}"


def _toposort(roots: Sequence[Task]) -> List[Task]:
    order: List[Task] = []
    seen: Dict[str, Task] = {}
    visiting: set = set()

    def visit(task: Task) -> None:
        key = _task_key(task)
        if key in seen:
            return
        if key in visiting:
            raise RuntimeError(f"dependency cycle at {task!r}")
        visiting.add(key)
        for dep in task.requires():
            visit(dep)
        visiting.discard(key)
        seen[key] = task
        order.append(task)

    for t in roots:
        visit(t)
    return order


def _collect_chains(order: Sequence[Task]):
    """Fused-chain declarations from the workflow nodes of a build, mapped
    by member/covered task key so the build loop can attempt a chain when
    it reaches the first incomplete task the chain would satisfy.  A
    declaration that raises is dropped loudly (declarations must never
    break a build)."""
    by_key: Dict[str, object] = {}
    for task in order:
        if not isinstance(task, WorkflowBase):
            continue
        try:
            chains = list(task.fused_chains())
        except Exception as e:
            print(f"[ctt-stream] ignoring fused_chains() of {task!r}: "
                  f"{type(e).__name__}: {e}")
            continue
        for chain in chains:
            for member in list(chain.members) + list(chain.covers):
                by_key.setdefault(_task_key(member), chain)
    return by_key


def build(tasks: Sequence[Task], raise_on_failure: bool = True) -> bool:
    """Run a set of root tasks and their dependencies.  Returns success."""
    # persistent XLA executable cache: fresh worker processes skip the
    # multi-second jit compiles of the big fused programs (CTT_COMPILE_CACHE
    # relocates/disables — see utils/compile_cache.py)
    from ..obs import trace as obs_trace
    from ..utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    order = _toposort(tasks)
    for task in order:
        # resume after a multi-host failure: stale aborted flags from the
        # prior run would otherwise fail peers' barriers immediately
        task.clear_stale_abort()
    chains_by_key = _collect_chains(order)
    attempted: set = set()
    try:
        with obs_trace.span("build", kind="run", n_tasks=len(order)):
            for task in order:
                if task.complete():
                    continue
                # ctt-stream: an incomplete task covered by a declared
                # fused chain triggers ONE attempt at running the whole
                # chain as a streaming pass; on success the members' and
                # covered tasks' status files are complete and the loop
                # skips them.  A declined/failed chain leaves no status
                # behind, so execution proceeds task-at-a-time unchanged.
                chain = chains_by_key.get(_task_key(task))
                if chain is not None and id(chain) not in attempted:
                    attempted.add(id(chain))
                    from . import stream

                    if stream.try_run_chain(chain) and task.complete():
                        continue
                try:
                    task.run()
                except Exception:
                    if raise_on_failure:
                        raise
                    import traceback

                    traceback.print_exc()
                    return False
                if isinstance(task, WorkflowBase):
                    continue
                if not task.complete():
                    msg = f"task {task!r} ran but did not reach completion"
                    if raise_on_failure:
                        raise RuntimeError(msg)
                    print(msg)
                    return False
        return True
    finally:
        # in-process callers (tests, notebooks) see complete shards without
        # waiting for interpreter exit
        obs_trace.flush()
