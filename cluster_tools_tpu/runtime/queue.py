"""ctt-steal: dynamic work-stealing block scheduler over a filesystem queue.

The batch-scheduler executors previously froze the reference's round-robin
assignment (``block_list[job_id::n_jobs]``, cluster_tasks.py:331) into each
job's config file: one slow volume region or one preempted node pinned a
whole job while its siblings sat idle, and the only recovery from a dead
worker was a full task-level retry round (resubmission of everything the
job's status file never reported done).  ctt-watch can *see* those
stragglers (per-task ETA, in-flight block age, heartbeat staleness) —
this module is the control loop that *acts* on them.

Instead of a frozen split, the driver publishes one **work queue** on the
shared filesystem (``<job_dir>/queue/``) — or, when the global config
sets ``steal_queue_url``, on an ``http(s)://`` object store (ctt-fleet:
every queue file routes through the :class:`StoreBackend` seam, with the
exclusive link becoming a create-only conditional PUT, so workers with no
shared mount steal across hosts) — and workers *pull* block batches
under expiring **leases**:

  * ``manifest.json`` — the immutable item list (block-id batches, formed
    with the same ``parallel.dispatch.form_batches`` chunking the device
    executor uses) plus the lease cadence; written once by the driver
    (fsync'd atomic write, the store convention).
  * ``lease.<k>.g<g>.json`` — generation ``g`` ownership of item ``k``.
    Claims are **atomic and exclusive**: the payload is staged to a tmp
    file and ``os.link``-ed into place — the link either creates the name
    or fails with EEXIST, the same once-latch idiom as the ctt-fault
    ``O_CREAT|O_EXCL`` cross-process latches, but carrying a full record.
    The owner re-stamps its lease every ``lease_s`` (atomic replace); a
    lease whose stamp is older than ``3 x lease_s`` is **expired** — the
    exact heartbeat-staleness rule ctt-watch uses for suspected-dead
    workers (obs/live.py, ``stale_intervals = 3``) — and any worker may
    **requeue** it by claiming generation ``g+1``.  Worker death and
    preemption are therefore self-healing: no task-level retry round, no
    resubmission.
  * ``result.<k>.json`` — terminal per-item record (done/failed blocks,
    errors, owner pid/job, generation, seconds), published with the same
    link idiom: **first writer wins**.  That makes straggler duplication
    safe: an idle worker may re-run the oldest in-flight item
    (``claim age > straggler_k x median item seconds``, the obs.live
    straggler rule) without taking the lease — block outputs ride the
    store's atomic chunk writes and are byte-identical by construction,
    and whichever copy finishes first owns the accounting.

Elasticity falls out of the pull model: a late-joining process (an extra
scheduler job, a burst node, or the driver itself as the worker of last
resort after the scheduler queue drains) just starts pulling.

Clock discipline: lease stamps carry wall time (cross-process ageing, the
same contract as heartbeat ``wall`` fields — readers compare *stored*
stamps against one local ``time.time()`` read) plus the writer's
monotonic clock for diagnostics.  Durations (item seconds) are monotonic.

Chaos sites (ctt-fault): ``sched.claim`` fires between candidate
selection and the lease link (forces two workers into the claim race the
link arbitrates), ``sched.write`` supports ``torn`` lease payloads
(readers fall back to file mtime for ageing — a torn lease still
expires), ``sched.requeue`` fires on the expired-lease takeover path
(stale-requeue storms).

The static split remains available and byte-identical:
``CTT_SCHED=static`` (or global config ``"sched": "static"``) restores
the frozen ``ids[job_id::n_jobs]`` assignment; the default is ``steal``
on multi-job runs of retryable tasks (requeue and duplication re-run
blocks, so ``allow_retry=False`` tasks keep the static split).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .. import faults
from ..obs import heartbeat as obs_heartbeat
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..utils.store_backend import backend_for

__all__ = [
    "WorkQueue", "Claim", "drain", "resolve_sched", "sched_label",
    "steal_batch_size", "publish_once", "MANIFEST_NAME", "ENV_SCHED",
    "STALE_INTERVALS", "STRAGGLER_K",
]

ENV_SCHED = "CTT_SCHED"
MANIFEST_NAME = "manifest.json"

# a lease is expired when its stamp is older than STALE_INTERVALS x the
# renewal cadence — the ctt-watch suspected-dead rule (obs/live.py)
STALE_INTERVALS = 3.0
# duplicate the oldest in-flight item once its claim age exceeds
# STRAGGLER_K x the median completed-item seconds — the ctt-watch
# straggler rule (obs/live.py)
STRAGGLER_K = 4.0

_LEASE_RE = re.compile(r"^lease\.(\d+)\.g(\d+)\.json$")
_RESULT_RE = re.compile(r"^result\.(\d+)\.json$")


def publish_once(path: str, payload: bytes) -> bool:
    """Atomically publish ``payload`` at ``path`` iff nothing is there yet.

    Routed through the owning :class:`StoreBackend` (ctt-fleet): POSIX
    stages to a pid+thread-unique tmp file (fsync'd, the store
    convention) and ``os.link``s it into place — the link either creates
    ``path`` with the full payload visible (no reader can observe a
    partial file) or fails with EEXIST; an ``http(s)://`` path becomes a
    create-only conditional PUT (``If-None-Match: *``, 412 = lost race)
    so leases and results arbitrate identically on an object store.
    Returns True when this caller won the slot.  The cross-process-
    exclusive cousin of ``atomic_write_bytes`` (which last-writer-wins
    replaces)."""
    return backend_for(path).publish_once(path, payload)


def resolve_sched(config: Dict[str, Any], task=None,
                  n_jobs: int = 1) -> str:
    """Scheduling mode for a cluster dispatch: ``CTT_SCHED`` env beats the
    global-config ``sched`` key beats the default (``steal`` on multi-job
    runs of retryable tasks, ``static`` otherwise).  Unknown values are
    loud — a silently disarmed A/B switch would certify nothing (the
    CTT_FAULTS precedent, not the degrade-to-default one)."""
    raw = os.environ.get(ENV_SCHED) or config.get("sched")
    if raw is not None:
        mode = str(raw).strip().lower()
        if mode not in ("static", "steal", "auto", ""):
            raise ValueError(
                f"unknown scheduler mode {raw!r} (CTT_SCHED / config "
                "'sched'): expected 'static', 'steal' or 'auto'"
            )
        if mode in ("static", "steal"):
            if mode == "steal" and task is not None and not task.allow_retry:
                # requeue/duplication re-run blocks; a task that forbids
                # redoing block outputs must keep the frozen split
                return "static"
            return mode
    if n_jobs > 1 and (task is None or task.allow_retry):
        return "steal"
    return "static"


def sched_label(config: Dict[str, Any]) -> str:
    """The *requested* mode for span/status tagging (``auto`` when neither
    the env nor the config pins one) — resolution against the task happens
    in the cluster executor."""
    raw = os.environ.get(ENV_SCHED) or config.get("sched")
    mode = str(raw).strip().lower() if raw is not None else ""
    return mode if mode in ("static", "steal") else "auto"


def steal_batch_size(config: Dict[str, Any], n_blocks: int,
                     n_jobs: int) -> int:
    """Blocks per lease: the ``steal_batch_size`` config knob, else sized
    for ~4 pulls per worker — granular enough that a hot region spreads,
    coarse enough that the claim traffic stays negligible."""
    raw = config.get("steal_batch_size")
    try:
        if raw is not None:
            return max(int(raw), 1)
    except (TypeError, ValueError):
        pass
    per_worker = max(n_blocks // max(n_jobs, 1), 1)
    return max(per_worker // 4, 1)


def _lease_interval_s(config: Dict[str, Any]) -> float:
    """Renewal cadence: the ``steal_lease_s`` config knob, default the
    heartbeat cadence (CTT_HEARTBEAT_S) — the lease staleness signal and
    the ctt-watch liveness signal tick together."""
    raw = config.get("steal_lease_s")
    try:
        val = float(raw) if raw is not None else obs_heartbeat.interval_s()
    except (TypeError, ValueError):
        val = obs_heartbeat.interval_s()
    return val if val > 0 else obs_heartbeat.interval_s()


@dataclass
class Claim:
    """One pulled work item: a block batch plus the lease that owns it
    (``lease_path`` is None for straggler duplicates — the duplicate rides
    first-writer-wins results instead of ownership)."""

    item: int
    block_ids: List[int]
    gen: int
    lease_path: Optional[str]
    duplicate: bool = False
    claim_wall: float = field(default_factory=time.time)


class WorkQueue:
    """Client over one queue directory: the driver creates it, any number
    of workers (scheduler jobs, late joiners, the driver backstop) pull
    from it concurrently through :meth:`claim` / :meth:`complete`."""

    def __init__(self, queue_dir: str):
        # the queue dir may be a POSIX path (one shared filesystem) or an
        # http(s) object-store URL (ctt-fleet: cross-host stealing with
        # no shared mount) — every file operation routes through the
        # owning backend, and claims stay exclusive either way
        self.dir = queue_dir
        self._backend = backend_for(queue_dir)
        self._join = self._backend.join
        m = json.loads(
            self._backend.read_bytes(
                self._join(queue_dir, MANIFEST_NAME)
            ).decode()
        )
        self.task = m.get("task", "unknown")
        self.items: List[List[int]] = [list(map(int, it)) for it in m["items"]]
        self.lease_s = float(m.get("lease_s", 5.0))
        self.duplicate_enabled = bool(m.get("duplicate", True))
        self.stale_after_s = STALE_INTERVALS * self.lease_s
        self._live = None  # lazy obs.live reader (lease-aware stragglers)
        # CTT_SCHED_CLOCK_SKEW_S shifts the READER clock only (stamps stay
        # real): a worker subprocess started with a skew beyond
        # stale_after_s sees every already-dead lease as instantly expired
        # — the injected-clock seam reaching processes a test cannot
        # monkeypatch.  Malformed/unset degrades to 0 (the CTT_* rule).
        try:
            self._clock_skew = float(
                os.getenv("CTT_SCHED_CLOCK_SKEW_S") or 0.0
            )
        except (TypeError, ValueError):
            self._clock_skew = 0.0

    def _now(self) -> float:
        """Reader-side wall clock for lease/claim ageing — a seam so tests
        inject time instead of sleeping real fractions of the cadence
        (expiry decisions become deterministic under arbitrary CI load;
        writer-side stamps stay on the real clock)."""
        return time.time() + self._clock_skew  # ctt: noqa[CTT008] wall by design: lease stamps are cross-process wall times (mtime-ageing contract), not durations

    # -- driver side --------------------------------------------------------

    @staticmethod
    def create(queue_dir: str, task_id: str, block_ids: Sequence[int],
               batch_size: int, lease_s: float,
               duplicate: bool = True) -> "WorkQueue":
        from ..parallel.dispatch import form_batches

        backend = backend_for(queue_dir)
        backend.makedirs(queue_dir)
        items = form_batches(block_ids, batch_size)
        backend.write_bytes(
            backend.join(queue_dir, MANIFEST_NAME),
            json.dumps({
                "task": task_id,
                "items": items,
                "lease_s": float(lease_s),
                "duplicate": bool(duplicate),
                "created_wall": time.time(),
            }).encode(),
        )
        return WorkQueue(queue_dir)

    # -- directory scan ------------------------------------------------------

    def _scan(self):
        """(results, leases) — ``results[k]`` True when item k has a
        terminal record; ``leases[k] = (gen, path)`` for the highest
        generation present."""
        results: Dict[int, bool] = {}
        leases: Dict[int, Tuple[int, str]] = {}
        try:
            names = self._backend.listdir(self.dir)
        except OSError:
            names = []
        for name in names:
            m = _RESULT_RE.match(name)
            if m:
                results[int(m.group(1))] = True
                continue
            m = _LEASE_RE.match(name)
            if m:
                k, g = int(m.group(1)), int(m.group(2))
                cur = leases.get(k)
                if cur is None or g > cur[0]:
                    leases[k] = (g, self._join(self.dir, name))
        return results, leases

    def _read_json(self, path: str) -> Optional[dict]:
        try:
            rec = json.loads(self._backend.read_bytes(path).decode())
            return rec if isinstance(rec, dict) else None
        except (OSError, ValueError):
            return None

    def _lease_age_s(self, path: str, now: float) -> float:
        """Wall age of a lease's last stamp; a torn/unparsable lease ages
        from its storage mtime — it still expires, just without
        attribution."""
        rec = self._read_json(path)
        stamp = None
        if rec is not None:
            try:
                stamp = float(rec["wall"])
            except (KeyError, TypeError, ValueError):
                stamp = None
        if stamp is None:
            stamp = self._backend.mtime(path)
            if stamp is None:
                return 0.0
        return max(0.0, now - stamp)

    # -- worker side ---------------------------------------------------------

    def _lease_payload(self, item: int, gen: int, job_id,
                       claim_wall: float) -> bytes:
        record = {
            "item": item,
            "gen": gen,
            "blocks": self.items[item],
            "owner_pid": os.getpid(),
            "job_id": job_id,
            "host": _hostname(),
            "claim_wall": claim_wall,
            "wall": time.time(),
            "mono": obs_trace.monotonic(),
        }
        payload = json.dumps(record).encode()
        torn = faults.mangle("sched.write", payload, id=item)
        return payload if torn is None else torn

    def _try_claim(self, item: int, gen: int, job_id) -> Optional[Claim]:
        claim_wall = time.time()
        path = self._join(self.dir, f"lease.{item}.g{gen}.json")
        if not publish_once(
            path, self._lease_payload(item, gen, job_id, claim_wall)
        ):
            return None
        obs_metrics.inc("sched.leases_claimed")
        return Claim(
            item=item, block_ids=list(self.items[item]), gen=gen,
            lease_path=path, claim_wall=claim_wall,
        )

    def renew(self, claim: Claim, job_id=None) -> None:
        """Re-stamp an owned lease (atomic replace — claim exclusivity was
        decided at link time, renewal only refreshes the staleness clock)."""
        if claim.lease_path is None:
            return
        self._backend.write_bytes(
            claim.lease_path,
            self._lease_payload(claim.item, claim.gen, job_id,
                                claim.claim_wall),
        )

    def claim(self, job_id=None,
              skip_duplicates: Sequence[int] = ()) -> Optional[Claim]:
        """Pull the next work item: an unclaimed item first, else an
        expired lease (requeue), else — when enabled — a straggler
        duplicate.  Returns None when nothing is claimable *right now*
        (the caller polls; in-flight leases resolve or expire)."""
        results, leases = self._scan()
        open_items = [
            k for k in range(len(self.items)) if k not in results
        ]
        unclaimed = [k for k in open_items if k not in leases]
        obs_metrics.set_gauge("sched.queue_depth", len(unclaimed))
        obs_heartbeat.note_queue_depth(len(unclaimed))

        for k in unclaimed:
            # chaos seam: a stall here widens the window between candidate
            # selection and the lease link — the claim race the link
            # arbitrates (exactly one winner, tested with real processes)
            faults.check("sched.claim", id=k)
            got = self._try_claim(k, 0, job_id)
            if got is not None:
                return got

        now = self._now()
        expired = []
        for k in open_items:
            if k not in leases:
                continue  # raced: claimed above by someone else just now
            gen, path = leases[k]
            age = self._lease_age_s(path, now)
            if age > self.stale_after_s:
                expired.append((age, k, gen))
        # oldest first: the longest-dead owner's work requeues first
        for age, k, gen in sorted(expired, reverse=True):
            faults.check("sched.requeue", id=k)
            got = self._try_claim(k, gen + 1, job_id)
            if got is not None:
                obs_metrics.inc("sched.leases_expired")
                obs_metrics.inc("sched.leases_requeued")
                return got

        if self.duplicate_enabled:
            dup = self._claim_duplicate(
                open_items, leases, results, now, skip_duplicates
            )
            if dup is not None:
                return dup
        return None

    def _live_median_block_s(self) -> Optional[float]:
        """Per-BLOCK median duration for this queue's task from the live
        trace (``obs.live.LiveRun.task_median_s``) — the lease-aware
        straggler baseline.  None when tracing is off or no block of this
        task has finished yet (the caller then falls back to the queue's
        own item-seconds median)."""
        if not obs_trace.enabled():
            return None
        rdir = obs_trace.run_dir()
        if rdir is None:
            return None
        if self._live is None:
            from ..obs.live import LiveRun

            self._live = LiveRun(rdir)
        try:
            med = self._live.task_median_s(self.task)
        except Exception:
            # a torn/alien trace dir must never break the pull loop —
            # worst case the queue keeps its own median
            return None
        return med if med and med > 0 else None

    def _item_median_s(self, results) -> Optional[float]:
        """Median completed-ITEM seconds from this queue's own result
        records — the pre-ctt-serve baseline, now the fallback."""
        seconds = []
        for k in results:
            rec = self._read_json(
                self._join(self.dir, f"result.{k}.json")
            )
            if rec is not None and isinstance(rec.get("seconds"), (int, float)):
                seconds.append(float(rec["seconds"]))
        if not seconds:
            return None
        seconds.sort()
        mid = len(seconds) // 2
        median = (
            seconds[mid] if len(seconds) % 2
            else 0.5 * (seconds[mid - 1] + seconds[mid])
        )
        return median if median > 0 else None

    def _claim_duplicate(self, open_items, leases, results, now,
                         skip_duplicates) -> Optional[Claim]:
        """Straggler re-dispatch: duplicate the oldest in-flight item once
        its claim age exceeds STRAGGLER_K x the median item cost.  No
        lease is taken — the duplicate's result publish is
        first-writer-wins and its chunk writes are byte-identical to the
        owner's by construction.

        The baseline median is lease-aware (ROADMAP item 1 follow-up):
        obs.live's per-task median BLOCK duration — the same number `obs
        watch` flags stragglers with — scaled by the candidate item's
        block count, preferred over the queue's own median of completed-
        item seconds.  The two detectors then agree on what 'slow' means,
        and duplication can fire before the queue's FIRST result record
        lands (a hot first item no longer stalls unflagged)."""
        med_block = self._live_median_block_s()
        med_item = None if med_block is not None else (
            self._item_median_s(results)
        )
        if med_block is None and med_item is None:
            return None
        best = None
        for k in open_items:
            if k in skip_duplicates or k not in leases:
                continue
            rec = self._read_json(leases[k][1])
            try:
                claim_wall = float(rec["claim_wall"])
            except (TypeError, KeyError, ValueError):
                continue
            age = now - claim_wall
            baseline = (
                med_block * max(len(self.items[k]), 1)
                if med_block is not None else med_item
            )
            if age > STRAGGLER_K * baseline and (
                best is None or age > best[0]
            ):
                best = (age, k)
        if best is None:
            return None
        _, k = best
        obs_metrics.inc("sched.leases_stolen")
        return Claim(
            item=k, block_ids=list(self.items[k]),
            gen=leases[k][0], lease_path=None, duplicate=True,
        )

    def complete(self, claim: Claim, done: Sequence[int],
                 failed: Sequence[int], errors: Dict[int, str],
                 seconds: float, job_id=None) -> bool:
        """Publish the item's terminal record (first writer wins — a
        duplicate and its straggling owner race here, and the loser's
        identical block outputs are already on the store)."""
        record = {
            "item": claim.item,
            "gen": claim.gen,
            "done": [int(b) for b in done],
            "failed": [int(b) for b in failed],
            "errors": {str(k): v for k, v in errors.items()},
            "pid": os.getpid(),
            "job_id": job_id,
            "duplicate": bool(claim.duplicate),
            "seconds": float(seconds),
            "wall": time.time(),
        }
        return publish_once(
            self._join(self.dir, f"result.{claim.item}.json"),
            json.dumps(record).encode(),
        )

    # -- completion / aggregation -------------------------------------------

    def all_resolved(self) -> bool:
        results, _ = self._scan()
        return len(results) >= len(self.items)

    def aggregate(self):
        """``(done, failed, errors, owners)`` over the whole queue, with
        failure attribution from the ACTUAL ownership records — a stolen
        or requeued item is blamed on its real last owner, never on the
        job a frozen split would have assigned it to."""
        done: List[int] = []
        failed: List[int] = []
        errors: Dict[int, str] = {}
        owners: Dict[int, dict] = {}
        results, leases = self._scan()
        for k, ids in enumerate(self.items):
            rec = (
                self._read_json(self._join(self.dir, f"result.{k}.json"))
                if k in results else None
            )
            if rec is not None:
                done.extend(int(b) for b in rec.get("done", []))
                failed.extend(int(b) for b in rec.get("failed", []))
                for key, msg in (rec.get("errors") or {}).items():
                    if str(key).lstrip("-").isdigit():
                        errors[int(key)] = msg
                    elif ids:
                        errors.setdefault(ids[0], f"item {k} {key}: {msg}")
                owners[k] = {
                    "pid": rec.get("pid"), "job_id": rec.get("job_id"),
                    "gen": rec.get("gen"),
                    "duplicate": bool(rec.get("duplicate")),
                }
                continue
            failed.extend(ids)
            anchor = ids[0] if ids else -1
            if k in leases:
                gen, path = leases[k]
                lrec = self._read_json(path) or {}
                owners[k] = {
                    "pid": lrec.get("owner_pid"),
                    "job_id": lrec.get("job_id"), "gen": gen,
                    "duplicate": False,
                }
                errors[anchor] = (
                    f"item {k} leased by job {lrec.get('job_id')} "
                    f"(pid {lrec.get('owner_pid')}, gen {gen}) but never "
                    "produced a result — worker died with the lease "
                    "unrecovered"
                )
            else:
                errors[anchor] = f"item {k} was never claimed"
        return done, sorted(set(failed) - set(done)), errors, owners


def _hostname() -> str:
    import socket

    return socket.gethostname()


def drain(queue: WorkQueue,
          run_item: Callable[[Claim], Tuple[List[int], List[int], Dict[int, str]]],
          job_id=None, poll_s: Optional[float] = None) -> Dict[str, Any]:
    """Pull-execute-publish until every queue item has a terminal record.

    ``run_item(claim) -> (done, failed, errors)`` executes one block
    batch (a cluster worker routes it through the local executor).  A
    renewal thread re-stamps the held lease at half the cadence; an
    exception from ``run_item`` publishes an all-failed result (the
    deterministic-failure path stays task-retry-mediated — only worker
    *death* rides the expiry requeue).  When nothing is claimable the
    worker waits: in-flight leases either resolve, expire (requeue), or
    age into straggler duplication."""
    stats: Dict[str, Any] = {
        "done": [], "failed": [], "errors": {}, "items": [],
        "duplicated": [],
    }
    duplicated: set = set()
    if poll_s is None:
        poll_s = min(max(queue.lease_s / 4.0, 0.05), 1.0)
    while True:
        claim = queue.claim(job_id=job_id, skip_duplicates=duplicated)
        if claim is None:
            if queue.all_resolved():
                return stats
            time.sleep(poll_s)  # ctt: noqa[CTT009] queue poll, not an IO retry — in-flight leases resolve, expire, or age into duplication
            continue
        if claim.duplicate:
            duplicated.add(claim.item)
            stats["duplicated"].append(claim.item)
        stop = threading.Event()
        renewer = None
        if claim.lease_path is not None:
            renewer = threading.Thread(
                target=_renew_loop, args=(queue, claim, job_id, stop),
                name="ctt-lease-renew", daemon=True,
            )
            renewer.start()
        t0 = obs_trace.monotonic()
        try:
            with obs_trace.span(
                "work_item", kind="host", task=queue.task,
                item=claim.item, blocks=len(claim.block_ids),
                duplicate=claim.duplicate,
            ):
                done, failed, errors = run_item(claim)
        except Exception:
            done, failed = [], list(claim.block_ids)
            errors = {claim.block_ids[0] if claim.block_ids else -1:
                      traceback.format_exc()}
        finally:
            stop.set()
            if renewer is not None:
                renewer.join(timeout=max(queue.lease_s, 1.0))
        won = queue.complete(
            claim, done, failed, errors, obs_trace.monotonic() - t0,
            job_id=job_id,
        )
        if won:
            stats["done"].extend(int(b) for b in done)
            stats["failed"].extend(int(b) for b in failed)
            stats["errors"].update(errors)
            stats["items"].append(claim.item)


def _renew_loop(queue: WorkQueue, claim: Claim, job_id,
                stop: threading.Event) -> None:
    interval = max(queue.lease_s / 2.0, 0.05)
    while not stop.wait(interval):
        try:
            queue.renew(claim, job_id=job_id)
        except OSError:
            # renewal is best-effort liveness, like heartbeats: a full
            # disk must not take the worker down — worst case the lease
            # expires and the item is duplicated, byte-identically
            pass
