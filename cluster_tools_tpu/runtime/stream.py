"""ctt-stream: cross-task fused streaming execution.

The reference (and PRs 1-6 of this port) runs workflows task-at-a-time:
threshold → CC → watershed each materialize a full intermediate volume to
the store and re-read it, so the same voxels cross the host/store boundary
4-5× per pipeline — the file-target model luigi imposes.  This module
generalizes the split-protocol executor (PR 3's ``read_batch`` /
``compute_batch`` / ``write_batch`` three-stage pipeline) from *intra-task*
pipelining to *cross-task* fusion: a :class:`FusedChain` declared by a
workflow executes as ONE streaming pass over the volume —

  * each z-slab block batch is read from the store once (at the chain's
    maximum halo; downstream members' smaller reads are crops of the same
    host buffer — the "halo reconciliation" between stages);
  * the batch flows through every member's ``compute_batch`` in declared
    order; a member consuming an in-chain product receives the upstream
    member's *device handoff* directly (``fused_read_batch``), so an elided
    intermediate never leaves HBM, let alone reaches the store;
  * only non-elided members' outputs are written back, plus small carried
    merge state (per-slab uniques / max ids, face-edge equivalence tables,
    histograms — the ``fusion_carry_*`` protocol) that replaces the
    downstream re-reads of scratch volumes.

Fallback contract: a chain that is not eligible (member opted out or
partially complete, ``stream_fusion`` disabled, multi-host topology, ROI
restriction, missing contracts) silently degrades to task-at-a-time
execution — declaring a chain never changes *what* is computed, only how
many times the voxels cross the store boundary.  Output is byte-identical
to the unfused pipeline by construction: members run their own unchanged
read/compute/write code against the same bytes.

Shape citations: arXiv:1711.00975 (one incremental pass, bounded memory,
small carried state) and arXiv:2210.06438 (fusing fine-grained stages into
resident device work); the fused ``ShardedWsProblemTask`` proved the
device-resident two-stage pattern this generalizes.

ctt-hbm: member uploads route through the warm device-buffer cache
(``runtime/hbm.py``) inside their own compute helpers — a back-to-back
fused serve job on the same volume skips the head member's store upload
— and each member dispatch is accounted under ``device.dispatches``.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults
from ..obs import heartbeat as obs_heartbeat
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..parallel.dispatch import BlockReadCache, use_read_cache
from ..utils.blocking import Blocking
from . import config as cfg
from .executor import resolve_batch_size


@dataclass
class FusedChain:
    """A declared fusible chain of split-protocol block tasks.

    ``members`` run as one streaming pass in declared order (producers
    before consumers).  ``elide`` names member identifiers whose volume
    output is never materialized (their ``write_batch``/``prepare`` are
    skipped; in-chain consumers take the device handoff instead) — the
    lint rule CTT011 statically verifies no out-of-chain task consumes an
    elided intermediate.  ``covers`` lists downstream tasks whose outputs
    the chain produces from carried state at finalize (e.g. the
    merge-offsets npz and block-face equivalence chunks) — they are
    stamped complete without running.
    """

    name: str
    members: List[Any]
    elide: frozenset = frozenset()
    covers: List[Any] = field(default_factory=list)

    def __post_init__(self) -> None:
        self.elide = frozenset(self.elide)
        ids = [m.identifier for m in self.members]
        if len(set(ids)) != len(ids):
            raise ValueError(
                f"fused chain {self.name!r}: duplicate member identifiers {ids}"
            )
        unknown = self.elide - set(ids)
        if unknown:
            raise ValueError(
                f"fused chain {self.name!r}: elide names non-members {sorted(unknown)}"
            )


class ChainFallback(RuntimeError):
    """Raised during planning when a declared chain cannot run fused; the
    caller degrades to task-at-a-time execution (never an error)."""


def fusion_enabled(gconf: Dict[str, Any]) -> bool:
    """The opt-out switches: ``stream_fusion`` in the global config (default
    on) and the ``CTT_STREAM_FUSION`` environment (``0``/``false``/``off``
    kills fusion process-wide — the chaos/CI escape hatch)."""
    env = os.environ.get("CTT_STREAM_FUSION", "").strip().lower()
    if env in ("0", "false", "off", "no"):
        return False
    return bool(gconf.get("stream_fusion", True))


@dataclass
class _ChainPlan:
    chain: FusedChain
    gconf: Dict[str, Any]
    mconfs: Dict[str, Dict[str, Any]]
    blocking: Blocking
    block_ids: List[int]
    chunks: List[List[int]]
    # external (path, key) -> max halo over members reading it
    prefetch: Dict[Tuple[str, str], Tuple[int, ...]]
    # in-chain (path, key) -> producing member identifier
    produced: Dict[Tuple[str, str], str]
    depth: int
    retries: int


def _member_output_pair(member) -> Optional[Tuple[str, str]]:
    path = getattr(member, "output_path", None)
    key = getattr(member, "output_key", None)
    if path is None or key is None:
        return None
    return (path, key)


def _has_split_protocol(member) -> bool:
    return all(
        callable(getattr(member, name, None))
        for name in ("read_batch", "compute_batch", "write_batch")
    )


def plan_chain(chain: FusedChain) -> _ChainPlan:
    """Validate eligibility and build the execution plan.  Raises
    :class:`ChainFallback` with a human-readable reason otherwise."""
    from .task import BlockTask  # local import to avoid cycle

    members = list(chain.members)
    if not members:
        raise ChainFallback("empty chain")
    head = members[0]
    gconf = head.global_config()
    if not fusion_enabled(gconf):
        raise ChainFallback("stream_fusion disabled")
    _, num = cfg.process_topology(gconf)
    if num > 1:
        raise ChainFallback(
            "multi-host topology (carry state is per-process; the "
            "round-robin block shard would split neighbor faces)"
        )
    if gconf.get("roi_begin") is not None or gconf.get("block_list_path"):
        raise ChainFallback(
            "ROI/block-list restriction (carried face state needs the "
            "full block grid)"
        )

    produced: Dict[Tuple[str, str], str] = {}
    prefetch: Dict[Tuple[str, str], Tuple[int, ...]] = {}
    mconfs: Dict[str, Dict[str, Any]] = {}
    for m in members:
        if not isinstance(m, BlockTask):
            raise ChainFallback(f"{m!r} is not a block task")
        if not getattr(m, "fusable", False) or not _has_split_protocol(m):
            raise ChainFallback(
                f"{m.identifier} is not a fusable split-protocol task"
            )
        if not getattr(m, "pipeline_safe", True):
            raise ChainFallback(
                f"{m.identifier} declares pipeline_safe=False (reads "
                "regions written by concurrent blocks of the same dispatch)"
            )
        mconf = {**gconf, **m.get_task_config()}
        mconfs[m.identifier] = mconf
        inputs = list(m.fusion_inputs(mconf) or [])
        halo = m.fusion_halo(mconf)
        for pair in inputs:
            if pair in produced:
                if type(m).fused_read_batch is BlockTask.fused_read_batch:
                    raise ChainFallback(
                        f"{m.identifier} consumes in-chain product {pair} "
                        "but does not implement fused_read_batch"
                    )
                continue
            have = prefetch.get(pair)
            h = tuple(int(x) for x in (halo or ()))
            if have is None:
                prefetch[pair] = h
            else:
                prefetch[pair] = tuple(
                    max(a, b) for a, b in zip(
                        have + (0,) * (len(h) - len(have)),
                        h + (0,) * (len(have) - len(h)),
                    )
                ) or have
        out_pair = _member_output_pair(m)
        if out_pair is not None:
            produced[out_pair] = m.identifier

    # no member (or covered task) may have prior progress: resumes mix
    # task-at-a-time state with streamed state — fall back and let the
    # per-task retry/resume machinery finish the run
    for t in members + list(chain.covers):
        status = t.output().read()
        if status.get("complete") or status.get("done"):
            raise ChainFallback(
                f"{t.identifier} has prior progress (resumed run)"
            )

    # blocking geometry from the head; every member reading external data
    # must agree (members consuming in-chain products inherit it — their
    # input dataset does not exist yet when the producer is elided)
    shape = tuple(head.get_shape())
    block_shape = head.get_block_shape(gconf)
    blocking = Blocking(shape, block_shape)
    for m in members[1:]:
        ext = [p for p in (m.fusion_inputs(mconfs[m.identifier]) or [])
               if p not in produced or produced[p] == m.identifier]
        consumes_inchain = any(
            p in produced and produced[p] != m.identifier
            for p in (m.fusion_inputs(mconfs[m.identifier]) or [])
        )
        if consumes_inchain and not ext:
            continue
        if not consumes_inchain and tuple(m.get_shape()) != shape:
            raise ChainFallback(
                f"{m.identifier} shape {tuple(m.get_shape())} != head "
                f"shape {shape}"
            )
    block_ids = head.get_block_list(blocking, gconf)
    if list(block_ids) != list(range(blocking.n_blocks)):
        raise ChainFallback("block list is not the full grid")

    # normalize prefetch halos to the blocking rank
    ndim = blocking.ndim
    prefetch = {
        pair: tuple((list(h) + [0] * ndim)[:ndim])
        for pair, h in prefetch.items()
        if pair not in produced
    }

    batch_size = resolve_batch_size(gconf)
    chunks = [
        list(block_ids[i: i + batch_size])
        for i in range(0, len(block_ids), batch_size)
    ]
    depth = max(int(gconf.get("pipeline_depth", 2)), 1)
    retries = max(int(gconf.get("max_num_retries", 0)), 0)
    return _ChainPlan(
        chain=chain, gconf=gconf, mconfs=mconfs, blocking=blocking,
        block_ids=list(block_ids), chunks=chunks, prefetch=prefetch,
        produced=produced, depth=depth, retries=retries,
    )


def try_run_chain(chain: FusedChain) -> bool:
    """Attempt a fused execution of ``chain``.  Returns True when the chain
    ran to completion (members + covered tasks stamped complete); False when
    it declined or failed — the caller then runs task-at-a-time, which is
    always safe: nothing is stamped on failure and all block writes are
    idempotent."""
    try:
        plan = plan_chain(chain)
    except ChainFallback as e:
        obs_metrics.inc("stream.fallbacks")
        print(f"[ctt-stream] chain {chain.name!r}: falling back to "
              f"task-at-a-time ({e})")
        return False
    try:
        _execute(plan)
        return True
    except Exception:
        obs_metrics.inc("stream.fallbacks")
        print(f"[ctt-stream] chain {chain.name!r} failed mid-stream; "
              f"falling back to task-at-a-time (idempotent block writes "
              f"make the partial pass harmless):\n{traceback.format_exc()}")
        return False


# ---------------------------------------------------------------------------
# execution


def _carry_nbytes(member, carry) -> int:
    fn = getattr(member, "fusion_carry_nbytes", None)
    if fn is None or carry is None:
        return 0
    try:
        return int(fn(carry))
    except Exception:
        return 0


class _ChainRunner:
    """One streaming pass: read pool → in-order fused compute → write pool.

    The structural twin of ``TpuExecutor._run_staged`` with the compute
    stage widened to the whole member sequence.  Determinism: the compute
    stage (and the carry updates) run on the calling thread in submission
    order, so device dispatch order and carried state are identical to the
    strictly serial loop; read/write pools only move IO off the critical
    path.  A failed batch is retried whole (read + every member's compute)
    before its carry is applied — carried state never sees a half-computed
    slab, which is what makes mid-slab fault injection recoverable."""

    def __init__(self, plan: _ChainPlan):
        self.plan = plan
        self.members = list(plan.chain.members)
        self.elide = plan.chain.elide
        self.carry: Dict[str, Any] = {}
        self.carry_peak = 0
        self.stage_s = {"read": 0.0, "compute": 0.0, "write": 0.0}
        self._acc_lock = threading.Lock()

    def _acc(self, stage: str, dt: float) -> None:
        with self._acc_lock:
            self.stage_s[stage] += dt

    # -- stages -------------------------------------------------------------

    def _read(self, chunk: List[int]):
        """Read stage for one batch: prefetch every external input's blocks
        at the chain-max halo into a batch-local cache, then run each
        store-reading member's own ``read_batch`` against it — the member's
        unchanged pad/normalize/stack code path runs over crops of the one
        shared read, so byte-identity with the unfused pipeline is
        structural, not re-implemented."""
        plan = self.plan
        obs_heartbeat.note_block_start(chunk[0])
        faults.check("executor.stage_read", id=chunk[0])
        t0 = time.perf_counter()
        cache = BlockReadCache()
        with obs_trace.span(
            "stage_read", kind="host_io", chain=plan.chain.name,
            blocks=len(chunk), block_ids=list(chunk),
        ):
            from ..utils import store as store_mod

            for (path, key), halo in plan.prefetch.items():
                ds = store_mod.file_reader(path, "r")[key]
                cache.prefetch(ds, path, key, plan.blocking, chunk, halo)
            payloads = {}
            with use_read_cache(cache):
                for m in self.members:
                    if self._consumes_inchain(m):
                        continue
                    payloads[m.identifier] = m.read_batch(
                        chunk, plan.blocking, plan.mconfs[m.identifier]
                    )
        self._acc("read", time.perf_counter() - t0)
        return payloads

    def _consumes_inchain(self, member) -> bool:
        pairs = member.fusion_inputs(self.plan.mconfs[member.identifier]) or []
        return any(
            p in self.plan.produced
            and self.plan.produced[p] != member.identifier
            for p in pairs
        )

    def _compute(self, chunk: List[int], payloads) -> Dict[str, Any]:
        """Serialized compute stage: every member's device program for this
        batch, in declared order; handoffs chain members device-side."""
        handoffs: Dict[Tuple[str, str], Any] = {}
        results: Dict[str, Any] = {}
        t0 = time.perf_counter()
        from . import hbm

        with hbm.use_guard():
            self._compute_members(chunk, payloads, handoffs, results)
        self._acc("compute", time.perf_counter() - t0)
        return results

    def _compute_members(self, chunk, payloads, handoffs, results) -> None:
        """Member loop of :meth:`_compute`, inside the hbm eviction guard
        (device handoffs + cached uploads stay alive across members)."""
        plan = self.plan
        for m in self.members:
            mid = m.identifier
            faults.check("executor.stage_compute", id=chunk[0])
            mconf = plan.mconfs[mid]
            if mid in payloads:
                payload = payloads[mid]
            else:
                payload = m.fused_read_batch(
                    handoffs, chunk, plan.blocking, mconf
                )
            t1 = time.perf_counter()
            with obs_trace.span(
                "stage_compute", kind="device", task=mid,
                chain=plan.chain.name, blocks=len(chunk),
                block_ids=list(chunk),
            ):
                result, handoff = m.fused_compute_batch(
                    payload, plan.blocking, mconf, elided=mid in self.elide
                )
            # ctt-hbm accounting: one device dispatch per member per slab
            # (member uploads route through the warm device-buffer cache
            # via their own compute helpers — see tasks/threshold.py)
            obs_metrics.inc("device.dispatches")
            m.record_timing(
                f"batch_{chunk[0]}_{chunk[-1]}", len(chunk),
                time.perf_counter() - t1,
            )
            results[mid] = result
            out_pair = _member_output_pair(m)
            if out_pair is not None:
                handoffs[out_pair] = handoff
            if mid in self.elide:
                obs_metrics.inc(
                    "stream.elided_bytes",
                    int(m.fused_elided_nbytes(handoff, plan.blocking, mconf)),
                )

    def _apply_carry(self, chunk: List[int], results) -> None:
        plan = self.plan
        for m in self.members:
            mid = m.identifier
            self.carry[mid] = m.fusion_carry_update(
                self.carry.get(mid), results[mid], chunk, plan.blocking,
                plan.mconfs[mid],
            )
            self.carry_peak = max(
                self.carry_peak, _carry_nbytes(m, self.carry.get(mid))
            )

    def _write(self, chunk: List[int], results) -> None:
        plan = self.plan
        faults.check("executor.stage_write", id=chunk[0])
        t0 = time.perf_counter()
        with obs_trace.span(
            "stage_write", kind="host_io", chain=plan.chain.name,
            blocks=len(chunk), block_ids=list(chunk),
        ):
            for m in self.members:
                mid = m.identifier
                if mid in self.elide:
                    continue
                m.write_batch(results[mid], plan.blocking, plan.mconfs[mid])
        self._acc("write", time.perf_counter() - t0)

    # -- batch with retry ----------------------------------------------------

    def _run_batch_synchronous(self, chunk, apply_carry: bool) -> None:
        """Serial read→compute(→carry)→write for one batch — the retry and
        write-failure recovery path (recompute is deterministic, block
        writes idempotent; ``apply_carry=False`` prevents double-counting
        state that an earlier attempt already carried)."""
        payloads = self._read(chunk)
        results = self._compute(chunk, payloads)
        if apply_carry:
            self._apply_carry(chunk, results)
        self._write(chunk, results)

    def _attempt(self, fn, chunk, what: str):
        """Run ``fn`` with up to ``retries`` full re-attempts.  The retry
        re-runs read AND compute for the batch (mid-slab faults must not
        leave carried state half-applied)."""
        retries = self.plan.retries
        for attempt in range(retries + 1):
            try:
                return fn()
            except Exception:
                if attempt >= retries:
                    raise
                obs_metrics.inc("task.blocks_retried", len(chunk))
                obs_heartbeat.note_blocks_retried(len(chunk))
                print(f"[ctt-stream] {what} for blocks "
                      f"{chunk[0]}..{chunk[-1]} failed (attempt "
                      f"{attempt + 1}/{retries + 1}); retrying:\n"
                      f"{traceback.format_exc()}")
        return None  # pragma: no cover - loop always returns or raises

    # -- ctt-ingest seam -----------------------------------------------------
    #
    # The incremental driver (ingest/runner.py) runs the SAME pass one
    # chunk at a time, persisting the carry between chunks: prepare() +
    # run_chunk()* + finalize() is run() with the pipelining removed —
    # compute and carry application already happen on the calling thread
    # in chunk order in both, which is what makes the outputs
    # byte-identical.

    def prepare(self) -> None:
        """Output-dataset creation for every non-elided member + carry
        init — the head of :meth:`run`, factored out for incremental
        drivers.  Elided members' outputs intentionally never exist."""
        plan = self.plan
        for m in self.members:
            if m.identifier not in self.elide:
                m.prepare(plan.blocking, plan.mconfs[m.identifier])
            self.carry[m.identifier] = m.fusion_carry_init(
                plan.blocking, plan.mconfs[m.identifier]
            )

    def run_chunk(self, chunk: List[int]) -> None:
        """One batch, serially (read → compute → carry → write) with the
        full retry budget — the per-slab step of an incremental pass."""
        self._attempt(
            lambda: self._run_batch_synchronous(chunk, True),
            chunk, "ingest batch",
        )
        obs_metrics.inc("stream.slabs")
        obs_heartbeat.note_blocks_done(len(chunk))
        obs_heartbeat.note_block_end(chunk[0])

    def export_carry(self) -> Dict[str, Any]:
        """Picklable snapshot of the carried merge state (per-member carry
        + peak accounting) — what ctt-ingest persists after each slab
        commit so a successor process can resume the stream."""
        return {"carry": dict(self.carry), "carry_peak": int(self.carry_peak)}

    def import_carry(self, state: Dict[str, Any]) -> None:
        self.carry = dict(state["carry"])
        self.carry_peak = max(self.carry_peak, int(state.get("carry_peak", 0)))

    def finalize(self, wall: float) -> None:
        """Member finalizers, carry finalizers and completion stamps —
        the tail of :meth:`run`, public for incremental drivers."""
        self._finish(wall)

    # -- main loop -----------------------------------------------------------

    def run(self) -> None:
        plan = self.plan
        chain = plan.chain
        members = self.members
        obs_metrics.inc("stream.chains")
        obs_heartbeat.note_task(
            f"chain:{chain.name}", len(plan.block_ids),
            grid=plan.blocking.grid_shape,
        )
        self.prepare()

        t_wall0 = obs_trace.monotonic()
        reads: deque = deque()   # (chunk, Future[payloads])
        writes: deque = deque()  # (chunk, Future[None])
        depth = plan.depth
        with obs_trace.span(
            "fused_chain", kind="dispatch", task=f"chain:{chain.name}",
            chain=chain.name, members=[m.identifier for m in members],
            blocks=len(plan.block_ids), grid=list(plan.blocking.grid_shape),
        ), ThreadPoolExecutor(
            depth, thread_name_prefix="ctt-stream-read"
        ) as read_pool, ThreadPoolExecutor(
            depth, thread_name_prefix="ctt-stream-write"
        ) as write_pool:

            def _drain_write():
                chunk, fut = writes.popleft()
                try:
                    fut.result()
                except Exception:
                    # the write ran detached from its compute; recover by
                    # re-running the whole batch serially (carry already
                    # applied — deterministic recompute, idempotent writes)
                    self._attempt(
                        lambda: self._run_batch_synchronous(chunk, False),
                        chunk, "write recovery",
                    )
                obs_metrics.inc("stream.slabs")
                obs_heartbeat.note_blocks_done(len(chunk))
                obs_heartbeat.note_block_end(chunk[0])

            def _drain_read():
                chunk, fut = reads.popleft()
                try:
                    payloads = fut.result()
                    results = self._compute(chunk, payloads)
                except Exception:
                    # pipelined attempt failed before carry: retry the
                    # batch whole (read included), serially
                    if self.plan.retries <= 0:
                        raise
                    obs_metrics.inc("task.blocks_retried", len(chunk))
                    obs_heartbeat.note_blocks_retried(len(chunk))
                    print(f"[ctt-stream] batch {chunk[0]}..{chunk[-1]} "
                          f"failed in flight; retrying serially:\n"
                          f"{traceback.format_exc()}")
                    self._attempt(
                        lambda: self._run_batch_synchronous(chunk, True),
                        chunk, "batch retry",
                    )
                    obs_metrics.inc("stream.slabs")
                    obs_heartbeat.note_blocks_done(len(chunk))
                    obs_heartbeat.note_block_end(chunk[0])
                    return
                self._apply_carry(chunk, results)
                writes.append(
                    (chunk, write_pool.submit(self._write, chunk, results))
                )
                while len(writes) > depth:
                    _drain_write()

            for chunk in plan.chunks:
                reads.append((chunk, read_pool.submit(self._read, chunk)))
                while len(reads) >= depth:
                    _drain_read()
            while reads:
                _drain_read()
            while writes:
                _drain_write()

        wall = obs_trace.monotonic() - t_wall0
        self._finish(wall)

    def _finish(self, wall: float) -> None:
        plan = self.plan
        members = self.members
        n_blocks = len(plan.block_ids)

        # finalize hooks (same order as task-at-a-time), then the carry
        # finalizers that write the covered tasks' outputs
        for m in members:
            m.finalize(plan.blocking, plan.mconfs[m.identifier], plan.block_ids)
        for m in members:
            m.fusion_finalize(
                self.carry.get(m.identifier), plan.blocking,
                plan.mconfs[m.identifier],
            )

        obs_metrics.set_gauge("stream.carry_bytes", int(self.carry_peak))
        # pipeline stage aggregates land on the head member's status (the
        # chain shares one read/write pipeline); per-member compute walls
        # were recorded per batch above
        head = members[0]
        head.record_timing("stage_read_total", n_blocks, self.stage_s["read"])
        head.record_timing(
            "stage_compute_total", n_blocks, self.stage_s["compute"]
        )
        head.record_timing(
            "stage_write_total", n_blocks, self.stage_s["write"]
        )
        obs_metrics.inc("executor.stage_batches", len(plan.chunks))
        obs_metrics.inc("executor.stage_read_s", self.stage_s["read"])
        obs_metrics.inc("executor.stage_compute_s", self.stage_s["compute"])
        obs_metrics.inc("executor.stage_write_s", self.stage_s["write"])
        obs_metrics.inc(
            "executor.stage_hidden_io_s",
            max(
                0.0,
                self.stage_s["read"] + self.stage_s["write"]
                - max(0.0, wall - self.stage_s["compute"]),
            ),
        )

        # positive completion records: each member's status says every
        # block is done (resume/retry and downstream completion checks read
        # these exactly as after a task-at-a-time run)
        done = set(plan.block_ids)
        for m in members:
            m._write_status(
                m.output(), plan.block_ids, done, [], [wall], True
            )
            m.log(f"done {m.identifier} (fused chain "
                  f"{plan.chain.name!r}) in {wall:.2f}s")
        for t in plan.chain.covers:
            t.output().write({
                "task": t.identifier,
                "complete": True,
                "fused_chain": plan.chain.name,
                "runtime_s": 0.0,
                "timings": [],
            })


def _execute(plan: _ChainPlan) -> None:
    _ChainRunner(plan).run()
