"""Task protocol: resumable block tasks with positive per-block completion records.

Re-expression of the reference's ``BaseClusterTask`` lifecycle
(reference cluster_tasks.py:27-159: init → prepare_jobs → submit_jobs →
wait_for_jobs → check_jobs) without the scheduler CLIs and log-grepping:

  * success is recorded *positively* in a JSON status file per task
    (``done`` block list + per-attempt runtimes) instead of magic
    ``"processed job N"`` log lines parsed back (reference parse_utils.py:76-135);
  * retry re-runs exactly the failed blocks, with the reference's safety heuristic
    (skip retry when a large fraction of blocks failed — something fundamental broke,
    reference cluster_tasks.py:140-142);
  * the compute inside a task is dispatched by an executor backend (`local` host
    loop or `tpu` batched device dispatch) rather than N scheduler processes.
"""

from __future__ import annotations

import json
import os
import time
import traceback
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import config as cfg
from .. import faults
from ..obs import heartbeat as obs_heartbeat
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..utils.blocking import Blocking, blocks_in_volume
from ..utils.store import atomic_write_bytes


class FailedBlocksError(RuntimeError):
    """Raised when blocks remain failed after exhausting retries
    (the analog of the reference's FailedJobsError, cluster_tasks.py:21)."""


class Target:
    """Completion marker of a task: a JSON status file in the tmp folder.

    Plays the role of the reference's luigi ``LocalTarget`` on the task log file
    (cluster_tasks.py:257-258), but carries machine-readable per-block state.
    """

    def __init__(self, path: str):
        self.path = path

    def exists(self) -> bool:
        if not os.path.exists(self.path):
            return False
        try:
            with open(self.path) as f:
                return bool(json.load(f).get("complete", False))
        except (json.JSONDecodeError, OSError):
            return False

    def read(self) -> Dict[str, Any]:
        if not os.path.exists(self.path):
            return {}
        with open(self.path) as f:
            return json.load(f)

    def write(self, status: Dict[str, Any]) -> None:
        # the store's durable atomic write (tmp + fsync + replace, tmp
        # unlinked on failure): a status file is the ONE record peers and
        # resumes trust — it must never surface empty after a power cut
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        atomic_write_bytes(
            self.path, json.dumps(status, indent=2).encode()
        )


class Task:
    """A node in the workflow DAG."""

    task_name: str = "task"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        dependencies: Sequence["Task"] = (),
    ):
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.dependencies = list(dependencies)
        self._timings: List[Dict[str, Any]] = []

    def record_timing(self, label: str, n_blocks: int, seconds: float) -> None:
        """Per-dispatch timing record (one batch on the tpu executor, one
        block on the local executor, one phase in a single-shot collective
        task) — surfaced in the status file so perf work is data-driven
        (SURVEY.md §5 'strictly additive' tracing).  Also bridged into the
        ctt-obs span stream (kind ``timing``: retroactive, excluded from
        the bucket sums — executor spans measure the same intervals live)
        WITHOUT changing the status-file schema: resume/retry keep reading
        the old keys."""
        self._timings.append(
            {"label": label, "blocks": int(n_blocks), "seconds": float(seconds)}
        )
        obs_trace.event(
            label, "timing", seconds, task=self.identifier,
            blocks=int(n_blocks),
        )

    # -- identity ------------------------------------------------------------

    @property
    def identifier(self) -> str:
        """Distinguishes instances of the same task class (scale/prefix variants
        override this — the analog of the reference's per-scale log names,
        e.g. merge_sub_graphs.py:100-101)."""
        return self.task_name

    # -- DAG protocol --------------------------------------------------------

    def requires(self) -> Sequence["Task"]:
        return self.dependencies

    def output(self) -> Target:
        return Target(
            os.path.join(self.tmp_folder, "status", f"{self.identifier}.status.json")
        )

    def complete(self) -> bool:
        return self.output().exists()

    # -- multi-host topology ---------------------------------------------------

    def topology(self):
        return cfg.process_topology(self.global_config())

    def _peer_wait(
        self, targets, timeout_s: float, what: str, stage: str = "complete"
    ) -> None:
        """Block until every target reports the given status ``stage``
        (the cross-process barrier of the shared-filesystem control plane).
        A peer that recorded an abort fails the waiter immediately instead of
        letting it spin to the timeout."""
        # monotonic deadline: a host clock jump (NTP step, VM migration)
        # must neither fire the timeout early nor stall it forever
        deadline = obs_trace.monotonic() + timeout_s
        with obs_trace.span(
            f"peer_wait:{stage}", kind="barrier", task=self.identifier,
            what=what,
        ):
            while True:
                # chaos seam: `stall` models a slow peer/filesystem (the
                # deadline above must still fire), `fail` a poisoned barrier
                faults.check("task.barrier", what=what)
                missing = []
                for t in targets:
                    status = t.read()
                    if status.get("aborted"):
                        raise FailedBlocksError(
                            f"{self.identifier}: peer process aborted "
                            f"({t.path}): {status.get('error', 'unknown error')}"
                        )
                    if not status.get(stage, False):
                        missing.append(t.path)
                if not missing:
                    return
                if obs_trace.monotonic() > deadline:
                    raise FailedBlocksError(
                        f"{self.identifier}: timed out after {timeout_s:.0f}s "
                        f"waiting for {what}: {missing[:3]}"
                    )
                time.sleep(1.0)

    def _write_abort(self, error: str) -> None:
        """Record this process's failure so peers at a barrier fail fast."""
        status = self.output().read()
        status.update(
            {"task": self.identifier, "aborted": True, "error": error[-2000:]}
        )
        status.setdefault("complete", False)
        self.output().write(status)

    def clear_stale_abort(self) -> None:
        """Drop ``aborted`` flags left by a previous failed run from ALL of
        this task's status files, so a resumed multi-host build doesn't fail
        peers' barriers on stale state.  Called by ``build()`` before any task
        runs.  Every process clears every file (not just its own): hosts start
        with arbitrary skew, and a fast peer must not trip over a slow peer's
        leftover abort before that peer's own build() has begun.  The tiny
        race with a *fresh* abort written concurrently degrades to the barrier
        timeout — recoverable — whereas stale flags would fail every resume."""
        for target in self._all_status_targets():
            status = target.read()
            if status.get("aborted"):
                status.pop("aborted", None)
                status.pop("error", None)
                target.write(status)

    def _all_status_targets(self):
        return [self.output()]

    def run(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    # -- config --------------------------------------------------------------

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        return dict(cfg.DEFAULT_TASK_CONFIG)

    def get_task_config(self) -> Dict[str, Any]:
        return cfg.task_config(self.config_dir, self.task_name, self.default_task_config())

    def global_config(self) -> Dict[str, Any]:
        # cached per task instance: completion polls under multi-host topology
        # would otherwise re-read the config JSON on every status check
        if getattr(self, "_gconf_cache", None) is None:
            conf = cfg.global_config(self.config_dir)
            if self.max_jobs is not None:
                conf["max_jobs"] = self.max_jobs
            self._gconf_cache = conf
        return dict(self._gconf_cache)

    # -- logging -------------------------------------------------------------

    @property
    def log_path(self) -> str:
        return os.path.join(self.tmp_folder, "logs", f"{self.identifier}.log")

    def log(self, msg: str) -> None:
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        with open(self.log_path, "a") as f:
            f.write(f"{stamp}: {msg}\n")

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.identifier})"


class SimpleTask(Task):
    """A single-shot (non-blockwise) task: subclasses implement ``run_impl``.

    Under multi-host topology the merge runs on process 0 only (the
    reference's 1-job merge semantics); peers wait for its status file.

    ``collective = True`` inverts that: EVERY process executes ``run_impl``
    simultaneously — required when the body runs a jax collective over a
    global (multi-process) mesh, where process 0 alone would deadlock
    waiting for shards the peers never contribute.  The jax program itself
    is the synchronization; process 0 owns the status file (and, by
    convention inside such tasks, the store writes — guard them with
    ``jax.process_index() == 0``), and peers wait for it before declaring
    completion.

    Failure semantics: like any NCCL-style collective job, a process dying
    BEFORE or INSIDE the collective leaves its peers blocked in the
    program (no file barrier guards device collectives); the
    ``peer_wait_timeout_s`` protection applies only to the status-file
    waits around it.  A peer that fails and records an abort is never
    masked: process 0 re-checks for abort records before stamping
    completion."""

    collective: bool = False

    def _check_peer_abort(self) -> None:
        status = self.output().read()
        if status and status.get("aborted"):
            raise RuntimeError(
                f"{self.identifier}: peer process recorded an abort: "
                f"{status.get('error', 'unknown error')}"
            )

    def run(self) -> None:
        gconf = self.global_config()
        pid, num = cfg.process_topology(gconf)
        if num > 1 and self.collective:
            # the collective contract needs the jax runtime to SPAN the
            # file-topology processes; otherwise every process believes it
            # is jax process 0 and all of them race the store writes
            import jax

            if jax.process_count() != num:
                raise RuntimeError(
                    f"{self.identifier} is collective over {num} processes "
                    f"but the jax runtime spans {jax.process_count()} — "
                    "call parallel.mesh.init_distributed() at process "
                    "startup (before any jax use) so the mesh is global"
                )
            # store writes are guarded by jax.process_index()==0 while the
            # completion status is stamped by config-pid 0; they must be the
            # SAME process, or pid 0 can stamp 'complete' while the
            # write-owning process is still writing
            if jax.process_index() != pid:
                raise RuntimeError(
                    f"{self.identifier}: config process_id {pid} != "
                    f"jax.process_index() {jax.process_index()} — pass "
                    "process_id to init_distributed() matching the "
                    "config topology so write and status ownership coincide"
                )
        if num > 1 and pid != 0 and not self.collective:
            timeout = float(gconf.get("peer_wait_timeout_s", 3600.0))
            self.log(f"process {pid}: waiting for process 0 to run "
                     f"{self.identifier}")
            self._peer_wait([self.output()], timeout, f"{self.identifier} on p0")
            return
        t0 = obs_trace.monotonic()
        try:
            self.log(f"start {self.identifier}")
            with obs_trace.span(self.identifier, kind="task"):
                self.run_impl()
        except Exception as e:
            if num > 1:
                self._write_abort(f"{type(e).__name__}: {e}")
            raise
        if num > 1 and pid != 0:
            # collective peer: work done inside the jax program; p0 stamps
            # the canonical status once its own (write-owning) body returns
            timeout = float(gconf.get("peer_wait_timeout_s", 3600.0))
            self._peer_wait([self.output()], timeout, f"{self.identifier} on p0")
            self.log(f"done {self.identifier} (collective peer {pid})")
            return
        if num > 1:
            # never stamp completion over a peer's abort record
            self._check_peer_abort()
        status = {
            "task": self.identifier,
            "complete": True,
            "runtime_s": obs_trace.monotonic() - t0,
            "timings": list(self._timings),
        }
        self.output().write(status)
        self.log(f"done {self.identifier} in {status['runtime_s']:.2f}s")

    def run_impl(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class BlockTask(Task):
    """A block-parallel task over a volume decomposition.

    Subclasses implement:
      * ``get_shape()``      — volume shape that defines the blocking;
      * ``process_block(block_id, blocking, config)``  — per-block host path;
      * optionally ``process_block_batch(block_ids, blocking, config)`` — a
        device-batched path the ``tpu`` executor prefers (blocks padded to a static
        shape, vmapped/sharded over the mesh);
      * optionally the SPLIT batch protocol — ``read_batch(block_ids,
        blocking, config) -> payload``, ``compute_batch(payload, blocking,
        config) -> result`` (the device program; the executor serializes
        this stage in batch order), ``write_batch(result, blocking,
        config)`` — which lets the ``tpu`` executor run a true three-stage
        pipeline: batch i+1's chunk reads and batch i−1's chunk writes both
        overlap batch i's device program.  Tasks defining it keep
        ``process_block_batch`` as the read→compute→write composition (used
        at ``pipeline_depth`` 1 and by the per-block fallback);
      * optionally ``prepare(blocking, config)`` / ``finalize(blocking, config,
        block_ids)`` — host-side setup (e.g. output dataset creation) and reduction.

    ``allow_retry=False`` marks tasks whose block outputs cannot safely be redone
    (reference block_components.py:27).

    Split-protocol tasks may additionally opt into **cross-task fusion**
    (``fusable = True`` + the ``fusion_*`` contract below): a workflow can
    then declare a :class:`runtime.stream.FusedChain` over them, and the
    chain executes as one streaming pass — each block batch is read once,
    flows through every member's ``compute_batch``, and elided
    intermediates never reach the store (see ``runtime/stream.py``).
    """

    allow_retry: bool = True

    # -- ctt-stream: cross-task fusion contract ------------------------------
    #
    # Split-protocol tasks that support running as a fused-chain member set
    # ``fusable = True`` and declare what they read; everything defaults to
    # "reads its input dataset block-wise with no halo, carries nothing".

    fusable: bool = False

    def fusion_halo(self, config) -> Optional[Sequence[int]]:
        """Halo this task's per-block reads need (None = zero): the chain
        reads each block once at the max halo over members and serves the
        smaller reads as crops — the halo reconciliation between stages."""
        return None

    def fusion_inputs(self, config) -> List[tuple]:
        """(path, key) datasets read per block — the shared-read prefetch
        set, and how the planner detects in-chain producer→consumer edges."""
        return []

    def fused_read_batch(self, handoffs, block_ids, blocking, config):
        """Build this member's compute payload from upstream device
        handoffs (``handoffs[(path, key)]`` = producing member's handoff).
        MUST be overridden by members consuming an in-chain product — the
        planner refuses the chain otherwise (the product may be elided and
        its store copy may not exist)."""
        raise NotImplementedError(
            f"{self.identifier} consumes an in-chain product but does not "
            "implement fused_read_batch"
        )

    def fused_compute_batch(self, payload, blocking, config, elided=False):
        """Returns ``(result_for_write, handoff)``.  Default: the task's
        own ``compute_batch`` with the result doubling as the handoff.
        Overrides can keep the handoff device-resident (and skip the host
        materialization entirely when ``elided``)."""
        result = self.compute_batch(payload, blocking, config)
        return result, result

    def fused_elided_nbytes(self, handoff, blocking, config) -> int:
        """Store bytes this member's elided output would have written —
        the ``stream.elided_bytes`` accounting hook."""
        return 0

    # Carried merge state (per-slab uniques / max ids, face-edge
    # equivalence tables, histograms): updated on the serialized compute
    # thread in batch order, AFTER the whole batch computed successfully
    # (a retried batch never half-applies), finalized once after the pass.

    def fusion_carry_init(self, blocking, config):
        return None

    def fusion_carry_update(self, carry, result, block_ids, blocking, config):
        return carry

    def fusion_carry_nbytes(self, carry) -> int:
        return 0

    def fusion_finalize(self, carry, blocking, config) -> None:
        """Write deferred small state (e.g. offsets / face-equivalence
        chunks that make ``covers`` tasks' outputs) after the pass."""
        return None

    # -- multi-host: per-process status + all-process completion -------------

    def _status_path(self, pid: int, num: int) -> str:
        name = (
            f"{self.identifier}.status.json"
            if num <= 1
            else f"{self.identifier}.p{pid}.status.json"
        )
        return os.path.join(self.tmp_folder, "status", name)

    def output(self) -> Target:
        pid, num = self.topology()
        return Target(self._status_path(pid, num))

    def peer_outputs(self):
        _, num = self.topology()
        return [Target(self._status_path(i, num)) for i in range(num)]

    # NB: complete() stays per-process (the inherited own-output check).
    # Cross-process consistency is enforced *inside* run() — the blocks_done
    # barrier plus the finalize-on-p0 wait guarantee all peers' data is on
    # disk before this process stamps complete — so the local DAG runner can
    # proceed without waiting for peers' bookkeeping to catch up.

    def _all_status_targets(self):
        return self.peer_outputs()

    def get_shape(self) -> Sequence[int]:  # pragma: no cover - abstract
        raise NotImplementedError

    def process_block(self, block_id: int, blocking: Blocking, config: Dict[str, Any]):
        raise NotImplementedError  # pragma: no cover - abstract

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        pass

    def finalize(
        self, blocking: Blocking, config: Dict[str, Any], block_ids: List[int]
    ) -> None:
        pass

    def get_block_shape(self, gconf: Dict[str, Any]) -> List[int]:
        return list(gconf["block_shape"])

    def get_block_list(self, blocking: Blocking, gconf: Dict[str, Any]) -> List[int]:
        return blocks_in_volume(
            blocking.shape,
            blocking.block_shape,
            gconf.get("roi_begin"),
            gconf.get("roi_end"),
            gconf.get("block_list_path"),
        )

    # -- main lifecycle ------------------------------------------------------

    def run(self) -> None:
        with obs_trace.span(self.identifier, kind="task"):
            self._run_traced()

    def _run_traced(self) -> None:
        t_start = obs_trace.monotonic()
        gconf = self.global_config()
        pid, num = cfg.process_topology(gconf)
        try:
            # everything — setup included — aborts visibly: a peer failing in
            # get_shape/prepare must not leave others spinning to the timeout
            blocking, all_block_ids, block_ids, config, done, runtimes = (
                self._run_blocks_phase(gconf, pid, num)
            )
        except Exception as e:
            if num > 1:
                self._write_abort(f"{type(e).__name__}: {e}")
            raise
        target = self.output()

        if num <= 1:
            self.finalize(blocking, config, block_ids)
            self._write_status(target, block_ids, done, [], runtimes, True)
            self.log(f"done {self.identifier} in "
                     f"{obs_trace.monotonic() - t_start:.2f}s")
            return

        # multi-host completion protocol: blocks_done → all-process barrier →
        # finalize on process 0 over the FULL block list (reducing finalizers
        # must see global state, not a shard) → staged complete markers so
        # downstream tasks start only after the finalize is on disk
        timeout = float(gconf.get("peer_wait_timeout_s", 3600.0))
        self._write_status(
            target, block_ids, done, [], runtimes, False, blocks_done=True
        )
        try:
            self._peer_wait(
                self.peer_outputs(), timeout,
                f"{self.identifier} peers", stage="blocks_done",
            )
            if pid == 0:
                self.finalize(blocking, config, all_block_ids)
            else:
                self._peer_wait(
                    [Target(self._status_path(0, num))], timeout,
                    f"{self.identifier} finalize on p0",
                )
        except Exception as e:
            self._write_abort(f"{type(e).__name__}: {e}")
            raise
        self._write_status(
            target, block_ids, done, [], runtimes, True, blocks_done=True
        )
        self.log(f"done {self.identifier} in "
                 f"{obs_trace.monotonic() - t_start:.2f}s")

    def _run_blocks_phase(self, gconf, pid: int, num: int):
        """Setup + block execution (incl. retries) for this process's shard."""
        from .executor import get_executor  # local import to avoid cycle

        tconf = self.get_task_config()
        config = {**gconf, **tconf}

        shape = tuple(self.get_shape())
        block_shape = self.get_block_shape(gconf)
        blocking = Blocking(shape, block_shape)
        all_block_ids = self.get_block_list(blocking, gconf)
        block_ids = all_block_ids
        if num > 1:
            # round-robin block shard per host process (the multi-host analog
            # of the reference's per-job assignment, cluster_tasks.py:331)
            block_ids = all_block_ids[pid::num]

        target = self.output()
        status = target.read()
        done = set(status.get("done", []))
        todo = [b for b in block_ids if b not in done]
        self.log(
            f"start {self.identifier}: {len(todo)}/{len(block_ids)} blocks to process"
        )

        self.prepare(blocking, config)
        executor = get_executor(config["target"], config)

        # ctt-watch: publish this process's share + the blocking geometry
        # to the heartbeat stream (live progress and the heatmap's grid);
        # a resumed run starts from the already-done count
        obs_heartbeat.note_task(
            self.identifier, len(block_ids), grid=blocking.grid_shape
        )
        if done:
            obs_heartbeat.note_blocks_done(len(done))

        max_retries = int(config.get("max_num_retries", 0))
        failure_fraction = float(config.get("retry_failure_fraction", 0.5))
        runtimes: List[float] = list(status.get("block_runtimes", []))
        self._run_attempts(
            target, blocking, config, executor, block_ids, todo, done,
            runtimes, max_retries, failure_fraction,
        )
        return blocking, all_block_ids, block_ids, config, done, runtimes

    def _run_attempts(
        self, target, blocking, config, executor, block_ids, todo, done,
        runtimes, max_retries, failure_fraction,
    ) -> None:
        # ctt-steal: tag dispatch spans with the requested scheduling mode
        # so obs trace/diff can segment static-vs-steal A/B runs
        from .queue import sched_label

        sched = sched_label(config)
        attempt = 0
        while todo:
            t0 = obs_trace.monotonic()
            with obs_trace.span(
                "dispatch", kind="dispatch", task=self.identifier,
                attempt=attempt, blocks=len(todo),
                grid=list(blocking.grid_shape), sched=sched,
            ):
                newly_done, failed, errors = executor.run_blocks(
                    self, blocking, todo, config
                )
            runtimes.append(obs_trace.monotonic() - t0)
            done.update(newly_done)
            self._write_status(target, block_ids, done, failed, runtimes, False)
            for bid, err in errors.items():
                self.log(f"block {bid} failed: {err}")
            if failed:
                obs_metrics.inc("task.blocks_failed", len(failed))
            if not failed:
                break
            frac = len(failed) / max(len(block_ids), 1)
            if attempt >= max_retries:
                raise FailedBlocksError(
                    f"{self.identifier}: {len(failed)} blocks failed after "
                    f"{attempt + 1} attempts; see {self.log_path}"
                )
            if not self.allow_retry:
                raise FailedBlocksError(
                    f"{self.identifier}: {len(failed)} blocks failed and task "
                    "does not allow retry"
                )
            if frac >= failure_fraction:
                # reference heuristic: too many failures means something fundamental
                # broke — don't burn retries (cluster_tasks.py:140-142)
                raise FailedBlocksError(
                    f"{self.identifier}: {len(failed)}/{len(block_ids)} blocks failed "
                    f"(≥{failure_fraction:.0%}) — refusing retry"
                )
            attempt += 1
            obs_metrics.inc("task.blocks_retried", len(failed))
            obs_heartbeat.note_blocks_retried(len(failed))
            self.log(f"retry {attempt}/{max_retries}: {len(failed)} failed blocks")
            todo = failed

    def _write_status(
        self, target, block_ids, done, failed, runtimes, complete,
        blocks_done: bool = False,
    ):
        target.write(
            {
                "task": self.identifier,
                "n_blocks": len(block_ids),
                "done": sorted(int(b) for b in done),
                "failed": sorted(int(b) for b in failed),
                "block_runtimes": [float(r) for r in runtimes],
                "timings": list(self._timings),
                "blocks_done": bool(blocks_done or complete),
                "complete": bool(complete),
            }
        )
