"""Task protocol: resumable block tasks with positive per-block completion records.

Re-expression of the reference's ``BaseClusterTask`` lifecycle
(reference cluster_tasks.py:27-159: init → prepare_jobs → submit_jobs →
wait_for_jobs → check_jobs) without the scheduler CLIs and log-grepping:

  * success is recorded *positively* in a JSON status file per task
    (``done`` block list + per-attempt runtimes) instead of magic
    ``"processed job N"`` log lines parsed back (reference parse_utils.py:76-135);
  * retry re-runs exactly the failed blocks, with the reference's safety heuristic
    (skip retry when a large fraction of blocks failed — something fundamental broke,
    reference cluster_tasks.py:140-142);
  * the compute inside a task is dispatched by an executor backend (`local` host
    loop or `tpu` batched device dispatch) rather than N scheduler processes.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import config as cfg
from ..utils.blocking import Blocking, blocks_in_volume


class FailedBlocksError(RuntimeError):
    """Raised when blocks remain failed after exhausting retries
    (the analog of the reference's FailedJobsError, cluster_tasks.py:21)."""


class Target:
    """Completion marker of a task: a JSON status file in the tmp folder.

    Plays the role of the reference's luigi ``LocalTarget`` on the task log file
    (cluster_tasks.py:257-258), but carries machine-readable per-block state.
    """

    def __init__(self, path: str):
        self.path = path

    def exists(self) -> bool:
        if not os.path.exists(self.path):
            return False
        try:
            with open(self.path) as f:
                return bool(json.load(f).get("complete", False))
        except (json.JSONDecodeError, OSError):
            return False

    def read(self) -> Dict[str, Any]:
        if not os.path.exists(self.path):
            return {}
        with open(self.path) as f:
            return json.load(f)

    def write(self, status: Dict[str, Any]) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + f".tmp{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(status, f, indent=2)
        os.replace(tmp, self.path)


class Task:
    """A node in the workflow DAG."""

    task_name: str = "task"

    def __init__(
        self,
        tmp_folder: str,
        config_dir: Optional[str] = None,
        max_jobs: Optional[int] = None,
        dependencies: Sequence["Task"] = (),
    ):
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.dependencies = list(dependencies)
        self._timings: List[Dict[str, Any]] = []

    # -- identity ------------------------------------------------------------

    @property
    def identifier(self) -> str:
        """Distinguishes instances of the same task class (scale/prefix variants
        override this — the analog of the reference's per-scale log names,
        e.g. merge_sub_graphs.py:100-101)."""
        return self.task_name

    # -- DAG protocol --------------------------------------------------------

    def requires(self) -> Sequence["Task"]:
        return self.dependencies

    def output(self) -> Target:
        return Target(
            os.path.join(self.tmp_folder, "status", f"{self.identifier}.status.json")
        )

    def complete(self) -> bool:
        return self.output().exists()

    def run(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    # -- config --------------------------------------------------------------

    @classmethod
    def default_task_config(cls) -> Dict[str, Any]:
        return dict(cfg.DEFAULT_TASK_CONFIG)

    def get_task_config(self) -> Dict[str, Any]:
        return cfg.task_config(self.config_dir, self.task_name, self.default_task_config())

    def global_config(self) -> Dict[str, Any]:
        conf = cfg.global_config(self.config_dir)
        if self.max_jobs is not None:
            conf["max_jobs"] = self.max_jobs
        return conf

    # -- logging -------------------------------------------------------------

    @property
    def log_path(self) -> str:
        return os.path.join(self.tmp_folder, "logs", f"{self.identifier}.log")

    def log(self, msg: str) -> None:
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        with open(self.log_path, "a") as f:
            f.write(f"{stamp}: {msg}\n")

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.identifier})"


class SimpleTask(Task):
    """A single-shot (non-blockwise) task: subclasses implement ``run_impl``."""

    def run(self) -> None:
        t0 = time.time()
        self.log(f"start {self.identifier}")
        self.run_impl()
        status = {
            "task": self.identifier,
            "complete": True,
            "runtime_s": time.time() - t0,
        }
        self.output().write(status)
        self.log(f"done {self.identifier} in {status['runtime_s']:.2f}s")

    def run_impl(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class BlockTask(Task):
    """A block-parallel task over a volume decomposition.

    Subclasses implement:
      * ``get_shape()``      — volume shape that defines the blocking;
      * ``process_block(block_id, blocking, config)``  — per-block host path;
      * optionally ``process_block_batch(block_ids, blocking, config)`` — a
        device-batched path the ``tpu`` executor prefers (blocks padded to a static
        shape, vmapped/sharded over the mesh);
      * optionally ``prepare(blocking, config)`` / ``finalize(blocking, config,
        block_ids)`` — host-side setup (e.g. output dataset creation) and reduction.

    ``allow_retry=False`` marks tasks whose block outputs cannot safely be redone
    (reference block_components.py:27).
    """

    allow_retry: bool = True

    def get_shape(self) -> Sequence[int]:  # pragma: no cover - abstract
        raise NotImplementedError

    def process_block(self, block_id: int, blocking: Blocking, config: Dict[str, Any]):
        raise NotImplementedError  # pragma: no cover - abstract

    def prepare(self, blocking: Blocking, config: Dict[str, Any]) -> None:
        pass

    def finalize(
        self, blocking: Blocking, config: Dict[str, Any], block_ids: List[int]
    ) -> None:
        pass

    def get_block_shape(self, gconf: Dict[str, Any]) -> List[int]:
        return list(gconf["block_shape"])

    def get_block_list(self, blocking: Blocking, gconf: Dict[str, Any]) -> List[int]:
        return blocks_in_volume(
            blocking.shape,
            blocking.block_shape,
            gconf.get("roi_begin"),
            gconf.get("roi_end"),
            gconf.get("block_list_path"),
        )

    # -- main lifecycle ------------------------------------------------------

    def run(self) -> None:
        from .executor import get_executor  # local import to avoid cycle

        t_start = time.time()
        gconf = self.global_config()
        tconf = self.get_task_config()
        config = {**gconf, **tconf}

        shape = tuple(self.get_shape())
        block_shape = self.get_block_shape(gconf)
        blocking = Blocking(shape, block_shape)
        block_ids = self.get_block_list(blocking, gconf)

        target = self.output()
        status = target.read()
        done = set(status.get("done", []))
        todo = [b for b in block_ids if b not in done]
        self.log(
            f"start {self.identifier}: {len(todo)}/{len(block_ids)} blocks to process"
        )

        self.prepare(blocking, config)
        executor = get_executor(config["target"], config)

        max_retries = int(config.get("max_num_retries", 0))
        failure_fraction = float(config.get("retry_failure_fraction", 0.5))
        runtimes: List[float] = list(status.get("block_runtimes", []))

        attempt = 0
        while todo:
            t0 = time.time()
            newly_done, failed, errors = executor.run_blocks(
                self, blocking, todo, config
            )
            runtimes.append(time.time() - t0)
            done.update(newly_done)
            self._write_status(target, block_ids, done, failed, runtimes, False)
            for bid, err in errors.items():
                self.log(f"block {bid} failed: {err}")
            if not failed:
                break
            frac = len(failed) / max(len(block_ids), 1)
            if attempt >= max_retries:
                raise FailedBlocksError(
                    f"{self.identifier}: {len(failed)} blocks failed after "
                    f"{attempt + 1} attempts; see {self.log_path}"
                )
            if not self.allow_retry:
                raise FailedBlocksError(
                    f"{self.identifier}: {len(failed)} blocks failed and task "
                    "does not allow retry"
                )
            if frac >= failure_fraction:
                # reference heuristic: too many failures means something fundamental
                # broke — don't burn retries (cluster_tasks.py:140-142)
                raise FailedBlocksError(
                    f"{self.identifier}: {len(failed)}/{len(block_ids)} blocks failed "
                    f"(≥{failure_fraction:.0%}) — refusing retry"
                )
            attempt += 1
            self.log(f"retry {attempt}/{max_retries}: {len(failed)} failed blocks")
            todo = failed

        self.finalize(blocking, config, block_ids)
        self._write_status(target, block_ids, done, [], runtimes, True)
        self.log(f"done {self.identifier} in {time.time() - t_start:.2f}s")

    def record_timing(self, label: str, n_blocks: int, seconds: float) -> None:
        """Per-dispatch timing record (one batch on the tpu executor, one
        block on the local executor) — surfaced in the status file so perf
        work is data-driven (SURVEY.md §5 'strictly additive' tracing)."""
        self._timings.append(
            {"label": label, "blocks": int(n_blocks), "seconds": float(seconds)}
        )

    def _write_status(self, target, block_ids, done, failed, runtimes, complete):
        target.write(
            {
                "task": self.identifier,
                "n_blocks": len(block_ids),
                "done": sorted(int(b) for b in done),
                "failed": sorted(int(b) for b in failed),
                "block_runtimes": [float(r) for r in runtimes],
                "timings": list(self._timings),
                "complete": bool(complete),
            }
        )
