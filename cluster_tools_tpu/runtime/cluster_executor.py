"""Batch-scheduler executors: Slurm and LSF.

The reference's headline deployment mode (cluster_tasks.py:388-624) re-designed
on the executor seam: blocks are round-robined over N scheduler jobs
(``block_list[job_id::n_jobs]``, the reference's assignment at
cluster_tasks.py:331), each job runs ``runtime.cluster_worker`` on its share
and writes a per-job status JSON; the submitting process polls the queue and
aggregates statuses — no shebang rewriting, no script shipping, no
log-grepping.

Scheduler interaction is two overridable commands (``submit_command`` /
``queue_command``), so the submission path is unit-testable with a stub
scheduler (the fake-scheduler seam SURVEY.md §4 calls out as missing from the
reference's test strategy).
"""

from __future__ import annotations

import json
import os
import pickle
import subprocess
import sys
import time
from typing import Any, Dict, List, Sequence, Set

from ..utils.blocking import Blocking
from .cluster_worker import job_paths
from .executor import BaseExecutor, RunResult, register_executor


class ClusterExecutor(BaseExecutor):
    """Shared submit → poll → aggregate logic; subclasses define the
    scheduler CLI."""

    name = "cluster"
    poll_interval_s = 10.0  # reference poll cadence (cluster_tasks.py:489,:601)

    # -- scheduler CLI hooks -------------------------------------------------

    def submit_command(
        self, script: str, job_name: str, log: str, err: str, config
    ) -> List[str]:  # pragma: no cover - abstract
        raise NotImplementedError

    def queue_command(self, job_name: str) -> List[str]:  # pragma: no cover
        raise NotImplementedError

    def parse_queue(self, output: str, job_name: str) -> int:
        """Number of still-queued/running jobs for ``job_name``."""
        return len([ln for ln in output.splitlines() if ln.strip()])

    # -- main protocol -------------------------------------------------------

    def run_blocks(
        self, task, blocking: Blocking, block_ids: Sequence[int], config: Dict[str, Any]
    ) -> RunResult:
        from . import config as cfg

        pid, num = cfg.process_topology(config)
        # namespace per host process: under multi-host topology each driver
        # submits its own jobs and must not clobber peers' task.pkl/configs
        name = task.identifier if num <= 1 else f"{task.identifier}_p{pid}"
        job_dir = os.path.join(task.tmp_folder, "cluster_jobs", name)
        os.makedirs(job_dir, exist_ok=True)
        max_jobs = int(task.max_jobs or config.get("max_jobs", 1) or 1)
        ids = list(block_ids)
        n_jobs = max(min(max_jobs, len(ids)), 1)

        task_path = os.path.join(job_dir, "task.pkl")
        with open(task_path, "wb") as f:
            pickle.dump(task, f)

        job_name = f"ctt_{task.identifier}_{os.getpid()}"
        # the driver may hold cached writable h5 handles (dataset creation in
        # prepare()); under HDF5 file locking they would block the worker
        # processes' own opens — release before spawning
        from ..utils.store import release_h5_handles

        release_h5_handles()
        for job_id in range(n_jobs):
            _, config_path, status_path = job_paths(job_dir, job_id)
            if os.path.exists(status_path):
                os.remove(status_path)
            with open(config_path, "w") as f:
                json.dump(
                    {
                        # reference round-robin assignment cluster_tasks.py:331
                        "block_ids": ids[job_id::n_jobs],
                        "shape": list(blocking.shape),
                        "block_shape": list(blocking.block_shape),
                        "config": _jsonable(config),
                    },
                    f,
                )
            script = self._write_job_script(job_dir, job_id, config)
            log = os.path.join(job_dir, f"job_{job_id}.log")
            err = os.path.join(job_dir, f"job_{job_id}.err")
            cmd = self.submit_command(script, job_name, log, err, config)
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"job submission failed ({' '.join(cmd)}):\n{proc.stderr}"
                )

        self._wait(job_name, n_jobs)
        return self._aggregate(job_dir, n_jobs, ids)

    def _write_job_script(self, job_dir: str, job_id: int, config) -> str:
        script = os.path.join(job_dir, f"job_{job_id}.sh")
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        lines = [
            "#!/bin/bash",
            f"export PYTHONPATH={pkg_root}:$PYTHONPATH",
        ]
        # per-job environment (e.g. JAX_PLATFORMS / accelerator visibility)
        for key, val in (config.get("worker_env") or {}).items():
            lines.append(f"export {key}={val!r}")
        lines.append(
            f"{sys.executable} -m cluster_tools_tpu.runtime.cluster_worker "
            f"{job_dir} {job_id}"
        )
        with open(script, "w") as f:
            f.write("\n".join(lines) + "\n")
        os.chmod(script, 0o755)
        return script

    def _wait(self, job_name: str, n_jobs: int) -> None:
        poll = float(self.config.get("poll_interval_s", self.poll_interval_s))
        while True:
            proc = subprocess.run(
                self.queue_command(job_name), capture_output=True, text=True
            )
            if proc.returncode == 0 and self.parse_queue(proc.stdout, job_name) == 0:
                return
            time.sleep(poll)

    def _aggregate(self, job_dir: str, n_jobs: int, ids: List[int]) -> RunResult:
        done: List[int] = []
        failed_set: Set[int] = set(ids)
        errors: Dict[int, str] = {}
        for job_id in range(n_jobs):
            _, _, status_path = job_paths(job_dir, job_id)
            job_blocks = ids[job_id::n_jobs]
            anchor = job_blocks[0] if job_blocks else -1
            if not os.path.exists(status_path):
                # job died before writing status (crash/kill/preemption) —
                # its blocks stay failed
                errors[anchor] = f"job {job_id} wrote no status file"
                continue
            try:
                with open(status_path) as f:
                    status = json.load(f)
            except (json.JSONDecodeError, OSError) as e:
                # a torn/unreadable status is a failed job, not a crashed
                # submitter — the retry loop resubmits its blocks
                errors[anchor] = f"job {job_id} status unreadable: {e}"
                continue
            done.extend(status["done"])
            failed_set.difference_update(status["done"])
            for k, v in status.get("errors", {}).items():
                if k.isdigit():
                    errors[int(k)] = v
                else:
                    # job-scope errors (setup failure, whole-job crash):
                    # surface the diagnostic on the job's first block
                    errors.setdefault(anchor, f"job {job_id} {k}: {v}")
        failed = sorted(failed_set)
        return done, failed, errors


def _jsonable(config: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in config.items():
        try:
            json.dumps(v)
            out[k] = v
        except TypeError:
            continue
    return out


class SlurmExecutor(ClusterExecutor):
    """sbatch/squeue backend (reference SlurmTask, cluster_tasks.py:388-511)."""

    name = "slurm"

    def submit_command(self, script, job_name, log, err, config):
        cmd = [
            config.get("sbatch_cmd", "sbatch"),
            "-o", log, "-e", err, "-J", job_name,
        ]
        if config.get("partition"):
            cmd += ["-p", str(config["partition"])]
        if config.get("qos"):
            cmd += ["--qos", str(config["qos"])]
        if config.get("time_limit"):
            cmd += ["-t", str(config["time_limit"])]
        if config.get("mem_limit"):
            cmd += ["--mem", str(config["mem_limit"])]
        if config.get("threads_per_job", 1) and int(config.get("threads_per_job", 1)) > 1:
            cmd += ["-c", str(int(config["threads_per_job"]))]
        for extra in config.get("slurm_requirements", []) or []:
            cmd += [str(extra)]
        return cmd + [script]

    def queue_command(self, job_name):
        return [
            self.config.get("squeue_cmd", "squeue"),
            "-h", "-n", job_name, "-o", "%T",
        ]


class LsfExecutor(ClusterExecutor):
    """bsub/bjobs backend (reference LSFTask, cluster_tasks.py:557-624)."""

    name = "lsf"

    def submit_command(self, script, job_name, log, err, config):
        cmd = [
            config.get("bsub_cmd", "bsub"),
            "-o", log, "-e", err, "-J", job_name,
        ]
        if config.get("time_limit"):
            cmd += ["-W", str(config["time_limit"])]
        if config.get("mem_limit"):
            cmd += ["-M", str(config["mem_limit"])]
        if config.get("threads_per_job", 1) and int(config.get("threads_per_job", 1)) > 1:
            cmd += ["-n", str(int(config["threads_per_job"]))]
        return cmd + [script]

    def queue_command(self, job_name):
        return [self.config.get("bjobs_cmd", "bjobs"), "-noheader", "-J", job_name]

    def parse_queue(self, output, job_name):
        lines = [
            ln for ln in output.splitlines()
            if ln.strip() and "not found" not in ln.lower()
        ]
        return len(lines)


register_executor("slurm", SlurmExecutor)
register_executor("lsf", LsfExecutor)
