"""Batch-scheduler executors: Slurm and LSF.

The reference's headline deployment mode (cluster_tasks.py:388-624) re-designed
on the executor seam: each scheduler job runs ``runtime.cluster_worker`` and
writes a per-job status JSON; the submitting process polls the queue and
aggregates — no shebang rewriting, no script shipping, no log-grepping.

Block assignment (ctt-steal): by default on multi-job runs, workers PULL
block batches from a shared filesystem work queue with expiring leases
(``runtime/queue.py`` — worker death self-heals through lease requeue,
late joiners just start pulling, stragglers get duplicated
first-writer-wins).  ``CTT_SCHED=static`` (or config ``"sched"``)
restores the reference's frozen round-robin split
(``block_list[job_id::n_jobs]``, cluster_tasks.py:331) byte-identically —
the A/B baseline and the path for ``allow_retry=False`` tasks.

Scheduler interaction is two overridable commands (``submit_command`` /
``queue_command``), so the submission path is unit-testable with a stub
scheduler (the fake-scheduler seam SURVEY.md §4 calls out as missing from the
reference's test strategy).
"""

from __future__ import annotations

import json
import os
import pickle
import subprocess
import sys
import time
from typing import Any, Dict, List, Sequence, Set

from ..obs import metrics as obs_metrics
from ..utils import store_backend
from ..utils.blocking import Blocking
from . import queue as workq
from .cluster_worker import job_paths
from .executor import BaseExecutor, RunResult, register_executor


class ClusterExecutor(BaseExecutor):
    """Shared submit → poll → aggregate logic; subclasses define the
    scheduler CLI."""

    name = "cluster"
    poll_interval_s = 10.0  # reference poll cadence (cluster_tasks.py:489,:601)

    # -- scheduler CLI hooks -------------------------------------------------

    def submit_command(
        self, script: str, job_name: str, log: str, err: str, config
    ) -> List[str]:  # pragma: no cover - abstract
        raise NotImplementedError

    def queue_command(self, job_name: str) -> List[str]:  # pragma: no cover
        raise NotImplementedError

    def parse_queue(self, output: str, job_name: str) -> int:
        """Number of still-queued/running jobs for ``job_name``."""
        return len([ln for ln in output.splitlines() if ln.strip()])

    # -- main protocol -------------------------------------------------------

    def run_blocks(
        self, task, blocking: Blocking, block_ids: Sequence[int], config: Dict[str, Any]
    ) -> RunResult:
        from . import config as cfg

        pid, num = cfg.process_topology(config)
        # namespace per host process: under multi-host topology each driver
        # submits its own jobs and must not clobber peers' task.pkl/configs
        name = task.identifier if num <= 1 else f"{task.identifier}_p{pid}"
        job_dir = os.path.join(task.tmp_folder, "cluster_jobs", name)
        os.makedirs(job_dir, exist_ok=True)
        max_jobs = int(task.max_jobs or config.get("max_jobs", 1) or 1)
        ids = list(block_ids)
        n_jobs = max(min(max_jobs, len(ids)), 1)

        # workers unpickle this as soon as their job starts — publish it
        # atomically so an early starter never reads a partial pickle
        task_path = os.path.join(job_dir, "task.pkl")
        store_backend.atomic_write_bytes(task_path, pickle.dumps(task))

        job_name = f"ctt_{task.identifier}_{os.getpid()}"
        # the driver may hold cached writable h5 handles (dataset creation in
        # prepare()); under HDF5 file locking they would block the worker
        # processes' own opens — release before spawning
        from ..utils.store import release_h5_handles

        release_h5_handles()
        mode = workq.resolve_sched(config, task, n_jobs)
        queue = None
        if mode == "steal":
            queue = self._create_queue(task, job_dir, ids, config, n_jobs)
        for job_id in range(n_jobs):
            _, config_path, status_path = job_paths(job_dir, job_id)
            if os.path.exists(status_path):
                os.remove(status_path)
            if queue is not None:
                job_conf = {
                    # ctt-steal: no frozen share — the worker pulls leased
                    # block batches from the shared queue
                    "queue_dir": queue.dir,
                    "shape": list(blocking.shape),
                    "block_shape": list(blocking.block_shape),
                    "config": _jsonable(config),
                }
            else:
                job_conf = {
                    # reference round-robin assignment cluster_tasks.py:331
                    "block_ids": ids[job_id::n_jobs],
                    "shape": list(blocking.shape),
                    "block_shape": list(blocking.block_shape),
                    "config": _jsonable(config),
                }
            store_backend.atomic_write_bytes(
                config_path, json.dumps(job_conf).encode()
            )
            script = self._write_job_script(job_dir, job_id, config)
            log = os.path.join(job_dir, f"job_{job_id}.log")
            err = os.path.join(job_dir, f"job_{job_id}.err")
            cmd = self.submit_command(script, job_name, log, err, config)
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"job submission failed ({' '.join(cmd)}):\n{proc.stderr}"
                )

        self._wait(job_name, n_jobs)
        if queue is not None:
            self._drain_leftovers(task, blocking, config, queue)
            return self._aggregate_steal(job_dir, n_jobs, queue)
        return self._aggregate(job_dir, n_jobs, ids)

    # -- ctt-steal: queue setup + driver backstop ---------------------------

    def _create_queue(self, task, job_dir: str, ids: List[int],
                      config, n_jobs: int) -> "workq.WorkQueue":
        base = config.get("steal_queue_url")
        if base:
            # ctt-fleet: queue on an object store — workers on hosts with
            # no shared mount pull/steal through the StoreBackend seam
            # (conditional-PUT claims); keyed by the job-dir leaf so
            # multi-host drivers keep their per-process namespaces
            backend = store_backend.backend_for(str(base))
            queue_dir = backend.join(
                str(base), os.path.basename(job_dir) + "_queue"
            )
        else:
            backend = store_backend.backend_for(job_dir)
            queue_dir = os.path.join(job_dir, "queue")
        try:
            stale = backend.isdir(queue_dir)
        except OSError:
            stale = False
        if stale:
            # one queue per dispatch: a retry round (or a resumed driver)
            # re-publishes exactly its todo list — stale leases/results
            # from a previous round must not satisfy it
            backend.rmtree(queue_dir)
        return workq.WorkQueue.create(
            queue_dir, task.identifier, ids,
            workq.steal_batch_size(config, len(ids), n_jobs),
            workq._lease_interval_s(config),
            duplicate=bool(config.get("steal_duplicate", True)),
        )

    def _drain_leftovers(self, task, blocking, config, queue) -> None:
        """Elastic worker of last resort: every scheduler job has exited,
        yet items remain unresolved (workers died holding leases, or the
        scheduler never really ran them).  The driver pulls the leftovers
        through the local path itself — completion via lease requeue, not
        a task-level resubmission round.  Loud: systematic worker
        breakage must read as 'driver drained N blocks', never as a
        silently single-process run."""
        if queue.all_resolved():
            return
        from .executor import LocalExecutor

        worker_conf = dict(config)
        worker_conf["target"] = "local"
        executor = LocalExecutor(worker_conf)

        def run_item(claim):
            return executor.run_blocks(
                task, blocking, claim.block_ids, worker_conf
            )

        stats = workq.drain(queue, run_item, job_id=None)
        n = len(stats["done"]) + len(stats["failed"])
        if n:
            obs_metrics.inc("sched.driver_drain_blocks", n)
            print(
                f"[{self.name}] scheduler jobs exited with "
                f"{len(stats['items'])} queue item(s) unresolved — driver "
                f"drained {n} block(s) via lease requeue "
                f"(task {task.identifier})"
            )

    def _aggregate_steal(self, job_dir: str, n_jobs: int,
                         queue) -> RunResult:
        """Aggregate from the queue's ownership records (satellite of the
        static `_aggregate` fix): every block's fate comes from the item
        result written by its ACTUAL last owner — a stolen or requeued
        block is never blamed on the job a frozen split would have
        assigned it to.  Job status files contribute job-scope diagnostics
        (setup failures, crashes) only."""
        done, failed, errors, _owners = queue.aggregate()
        for job_id in range(n_jobs):
            _, _, status_path = job_paths(job_dir, job_id)
            status = self._read_status(status_path)
            if status is None:
                if failed:
                    errors.setdefault(
                        -1,
                        f"job {job_id} wrote no status file (its leases "
                        "requeued to surviving workers)",
                    )
                continue
            for k, v in status.get("errors", {}).items():
                if not k.lstrip("-").isdigit():
                    errors.setdefault(
                        failed[0] if failed else -1, f"job {job_id} {k}: {v}"
                    )
        return sorted(set(done)), failed, errors

    @staticmethod
    def _read_status(status_path: str):
        if not os.path.exists(status_path):
            return None
        try:
            with open(status_path) as f:
                return json.load(f)
        except (json.JSONDecodeError, OSError):
            return None

    def _write_job_script(self, job_dir: str, job_id: int, config) -> str:
        script = os.path.join(job_dir, f"job_{job_id}.sh")
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        lines = [
            "#!/bin/bash",
            f"export PYTHONPATH={pkg_root}:$PYTHONPATH",
        ]
        # per-job environment (e.g. JAX_PLATFORMS / accelerator visibility)
        for key, val in (config.get("worker_env") or {}).items():
            lines.append(f"export {key}={val!r}")
        lines.append(
            f"{sys.executable} -m cluster_tools_tpu.runtime.cluster_worker "
            f"{job_dir} {job_id}"
        )
        store_backend.atomic_write_bytes(
            script, ("\n".join(lines) + "\n").encode()
        )
        os.chmod(script, 0o755)
        return script

    def _wait(self, job_name: str, n_jobs: int) -> None:
        poll = float(self.config.get("poll_interval_s", self.poll_interval_s))
        while True:
            proc = subprocess.run(
                self.queue_command(job_name), capture_output=True, text=True
            )
            if proc.returncode == 0 and self.parse_queue(proc.stdout, job_name) == 0:
                return
            time.sleep(poll)

    def _aggregate(self, job_dir: str, n_jobs: int, ids: List[int]) -> RunResult:
        done: List[int] = []
        failed_set: Set[int] = set(ids)
        errors: Dict[int, str] = {}
        for job_id in range(n_jobs):
            _, config_path, status_path = job_paths(job_dir, job_id)
            # attribute by the job's RECORDED assignment (job_N.json), not
            # a re-derived slice: the record is what the worker actually
            # ran, and stays correct if the formation rule ever changes
            job_blocks = ids[job_id::n_jobs]
            job_conf = self._read_status(config_path)
            if job_conf is not None and isinstance(
                job_conf.get("block_ids"), list
            ):
                job_blocks = [int(b) for b in job_conf["block_ids"]]
            anchor = job_blocks[0] if job_blocks else -1
            if not os.path.exists(status_path):
                # job died before writing status (crash/kill/preemption) —
                # its blocks stay failed
                errors[anchor] = f"job {job_id} wrote no status file"
                continue
            try:
                with open(status_path) as f:
                    status = json.load(f)
            except (json.JSONDecodeError, OSError) as e:
                # a torn/unreadable status is a failed job, not a crashed
                # submitter — the retry loop resubmits its blocks
                errors[anchor] = f"job {job_id} status unreadable: {e}"
                continue
            done.extend(status["done"])
            failed_set.difference_update(status["done"])
            for k, v in status.get("errors", {}).items():
                if k.isdigit():
                    errors[int(k)] = v
                else:
                    # job-scope errors (setup failure, whole-job crash):
                    # surface the diagnostic on the job's first block
                    errors.setdefault(anchor, f"job {job_id} {k}: {v}")
        failed = sorted(failed_set)
        return done, failed, errors


def _jsonable(config: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in config.items():
        try:
            json.dumps(v)
            out[k] = v
        except TypeError:
            continue
    return out


class SlurmExecutor(ClusterExecutor):
    """sbatch/squeue backend (reference SlurmTask, cluster_tasks.py:388-511)."""

    name = "slurm"

    def submit_command(self, script, job_name, log, err, config):
        cmd = [
            config.get("sbatch_cmd", "sbatch"),
            "-o", log, "-e", err, "-J", job_name,
        ]
        if config.get("partition"):
            cmd += ["-p", str(config["partition"])]
        if config.get("qos"):
            cmd += ["--qos", str(config["qos"])]
        if config.get("time_limit"):
            cmd += ["-t", str(config["time_limit"])]
        if config.get("mem_limit"):
            cmd += ["--mem", str(config["mem_limit"])]
        if config.get("threads_per_job", 1) and int(config.get("threads_per_job", 1)) > 1:
            cmd += ["-c", str(int(config["threads_per_job"]))]
        for extra in config.get("slurm_requirements", []) or []:
            cmd += [str(extra)]
        return cmd + [script]

    def queue_command(self, job_name):
        return [
            self.config.get("squeue_cmd", "squeue"),
            "-h", "-n", job_name, "-o", "%T",
        ]


class LsfExecutor(ClusterExecutor):
    """bsub/bjobs backend (reference LSFTask, cluster_tasks.py:557-624)."""

    name = "lsf"

    def submit_command(self, script, job_name, log, err, config):
        cmd = [
            config.get("bsub_cmd", "bsub"),
            "-o", log, "-e", err, "-J", job_name,
        ]
        if config.get("time_limit"):
            cmd += ["-W", str(config["time_limit"])]
        if config.get("mem_limit"):
            cmd += ["-M", str(config["mem_limit"])]
        if config.get("threads_per_job", 1) and int(config.get("threads_per_job", 1)) > 1:
            cmd += ["-n", str(int(config["threads_per_job"]))]
        return cmd + [script]

    def queue_command(self, job_name):
        return [self.config.get("bjobs_cmd", "bjobs"), "-noheader", "-J", job_name]

    def parse_queue(self, output, job_name):
        lines = [
            ln for ln in output.splitlines()
            if ln.strip() and "not found" not in ln.lower()
        ]
        return len(lines)


register_executor("slurm", SlurmExecutor)
register_executor("lsf", LsfExecutor)
