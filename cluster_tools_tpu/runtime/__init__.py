from . import config
from .task import Task, BlockTask, FailedBlocksError, Target
from .executor import get_executor
from .workflow import ExecutionContext, WorkflowBase, build

__all__ = [
    "config",
    "Task",
    "BlockTask",
    "FailedBlocksError",
    "Target",
    "get_executor",
    "ExecutionContext",
    "WorkflowBase",
    "build",
]
